/* Chained BLAKE2b-128 block hashing for the global prefix-KV-cache index.
 *
 * Native twin of xllm_service_tpu/common/hashing.py: digests are
 * byte-identical to Python's hashlib.blake2b(digest_size=16, key=...) —
 * RFC 7693 keyed sequential mode — so engines running the pure-Python
 * path and orchestration components running this extension compute the
 * same 16-byte keys for the same token prefixes (the whole point of the
 * index). tests/test_common.py asserts the equivalence over many sizes.
 *
 * The exported entry point loops the chain in C: one call hashes every
 * complete block of a token buffer, keying block i with the digest of
 * block i-1 (the seed for block 0), amortizing the per-block Python/FFI
 * overhead that dominates the hashlib loop.
 *
 * Build: make -C csrc libblockhash.so   (loaded via ctypes, optional —
 * hashing.py falls back to pure Python when the .so is absent).
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

static const uint64_t B2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
    0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
    0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
};

static const uint8_t B2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
};

typedef struct {
    uint64_t h[8];
    uint64_t t0, t1;
    uint8_t buf[128];
    size_t buflen;
    size_t outlen;
} b2b_state;

static inline uint64_t rotr64(uint64_t x, unsigned n) {
    return (x >> n) | (x << (64 - n));
}

static inline uint64_t load64le(const uint8_t *p) {
    return (uint64_t)p[0] | ((uint64_t)p[1] << 8) | ((uint64_t)p[2] << 16) |
           ((uint64_t)p[3] << 24) | ((uint64_t)p[4] << 32) |
           ((uint64_t)p[5] << 40) | ((uint64_t)p[6] << 48) |
           ((uint64_t)p[7] << 56);
}

#define B2B_G(a, b, c, d, x, y)                                               \
    do {                                                                      \
        v[a] = v[a] + v[b] + (x);                                             \
        v[d] = rotr64(v[d] ^ v[a], 32);                                       \
        v[c] = v[c] + v[d];                                                   \
        v[b] = rotr64(v[b] ^ v[c], 24);                                       \
        v[a] = v[a] + v[b] + (y);                                             \
        v[d] = rotr64(v[d] ^ v[a], 16);                                       \
        v[c] = v[c] + v[d];                                                   \
        v[b] = rotr64(v[b] ^ v[c], 63);                                       \
    } while (0)

static void b2b_compress(b2b_state *S, const uint8_t block[128], int last) {
    uint64_t v[16], m[16];
    int i;
    for (i = 0; i < 8; i++) {
        v[i] = S->h[i];
        v[i + 8] = B2B_IV[i];
    }
    v[12] ^= S->t0;
    v[13] ^= S->t1;
    if (last)
        v[14] = ~v[14];
    for (i = 0; i < 16; i++)
        m[i] = load64le(block + 8 * i);
    for (i = 0; i < 12; i++) {
        const uint8_t *s = B2B_SIGMA[i];
        B2B_G(0, 4, 8, 12, m[s[0]], m[s[1]]);
        B2B_G(1, 5, 9, 13, m[s[2]], m[s[3]]);
        B2B_G(2, 6, 10, 14, m[s[4]], m[s[5]]);
        B2B_G(3, 7, 11, 15, m[s[6]], m[s[7]]);
        B2B_G(0, 5, 10, 15, m[s[8]], m[s[9]]);
        B2B_G(1, 6, 11, 12, m[s[10]], m[s[11]]);
        B2B_G(2, 7, 8, 13, m[s[12]], m[s[13]]);
        B2B_G(3, 4, 9, 14, m[s[14]], m[s[15]]);
    }
    for (i = 0; i < 8; i++)
        S->h[i] ^= v[i] ^ v[i + 8];
}

static void b2b_update(b2b_state *S, const uint8_t *in, size_t inlen) {
    while (inlen > 0) {
        if (S->buflen == 128) {
            /* Buffer full AND more input follows: compress as non-final. */
            S->t0 += 128;
            if (S->t0 < 128)
                S->t1++;
            b2b_compress(S, S->buf, 0);
            S->buflen = 0;
        }
        size_t n = 128 - S->buflen;
        if (n > inlen)
            n = inlen;
        memcpy(S->buf + S->buflen, in, n);
        S->buflen += n;
        in += n;
        inlen -= n;
    }
}

static void b2b_init_keyed(b2b_state *S, size_t outlen, const uint8_t *key,
                           size_t keylen) {
    int i;
    memset(S, 0, sizeof(*S));
    for (i = 0; i < 8; i++)
        S->h[i] = B2B_IV[i];
    S->h[0] ^= 0x01010000ULL ^ ((uint64_t)keylen << 8) ^ (uint64_t)outlen;
    S->outlen = outlen;
    if (keylen > 0) {
        uint8_t block[128];
        memset(block, 0, sizeof(block));
        memcpy(block, key, keylen);
        b2b_update(S, block, 128);
    }
}

static void b2b_final(b2b_state *S, uint8_t *out) {
    size_t i;
    S->t0 += S->buflen;
    if (S->t0 < S->buflen)
        S->t1++;
    memset(S->buf + S->buflen, 0, 128 - S->buflen);
    b2b_compress(S, S->buf, 1);
    for (i = 0; i < S->outlen; i++)
        out[i] = (uint8_t)(S->h[i >> 3] >> (8 * (i & 7)));
}

/* Chained driver: data is the raw little-endian int32 token buffer of
 * n_blocks complete blocks, block_bytes bytes each. Block 0 is keyed with
 * seed; block i with block i-1's 16-byte digest. Writes 16 bytes per block
 * into out. */
void chained_block_hashes(const uint8_t *data, size_t n_blocks,
                          size_t block_bytes, const uint8_t *seed,
                          size_t seed_len, uint8_t *out) {
    const uint8_t *key = seed;
    size_t keylen = seed_len;
    size_t i;
    b2b_state S;
    for (i = 0; i < n_blocks; i++) {
        b2b_init_keyed(&S, 16, key, keylen);
        b2b_update(&S, data + i * block_bytes, block_bytes);
        b2b_final(&S, out + i * 16);
        key = out + i * 16;
        keylen = 16;
    }
}

/* Single keyed hash, exposed for the equivalence tests. */
void blake2b_128_keyed(const uint8_t *data, size_t datalen,
                       const uint8_t *key, size_t keylen, uint8_t *out) {
    b2b_state S;
    b2b_init_keyed(&S, 16, key, keylen);
    b2b_update(&S, data, datalen);
    b2b_final(&S, out);
}

#ifdef BLOCKHASH_PYLIST
/* List-ingest entry point, called via ctypes.PyDLL (GIL held): converts
 * the Python token sequence to little-endian int32 in C — profiling shows
 * np.asarray(list) costs ~25x the hash chain itself for a 4k-token
 * prompt — then runs the chain. Returns bytes(n_blocks*16); NULL with an
 * exception set on a non-integer element. Compiled in only when Python.h
 * is available (see csrc/Makefile); hashing.py probes for the symbol. */
#include <Python.h>

PyObject *chained_block_hashes_list(PyObject *tokens, Py_ssize_t block_size,
                                    PyObject *seed) {
    PyObject *fast = PySequence_Fast(tokens, "token_ids must be a sequence");
    if (fast == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    Py_ssize_t n_blocks = block_size > 0 ? n / block_size : 0;
    if (n_blocks <= 0) {
        Py_DECREF(fast);
        return PyBytes_FromStringAndSize(NULL, 0);
    }
    char *seed_buf;
    Py_ssize_t seed_len;
    if (PyBytes_AsStringAndSize(seed, &seed_buf, &seed_len) < 0) {
        Py_DECREF(fast);
        return NULL;
    }
    Py_ssize_t n_used = n_blocks * block_size;
    int32_t *data = (int32_t *)PyMem_Malloc((size_t)n_used * 4);
    if (data == NULL) {
        Py_DECREF(fast);
        return PyErr_NoMemory();
    }
    PyObject **items = PySequence_Fast_ITEMS(fast);
    for (Py_ssize_t i = 0; i < n_used; i++) {
        long v = PyLong_AsLong(items[i]);
        if (v == -1 && PyErr_Occurred()) {
            PyMem_Free(data);
            Py_DECREF(fast);
            return NULL;
        }
        /* Same narrowing as the np.int32 conversion on the Python path
         * (token ids are < 2^31 in practice). Stored little-endian;
         * byte-swap would be needed on a big-endian host, but every
         * deployment target (x86/ARM TPU-VM hosts) is little-endian. */
        data[i] = (int32_t)v;
    }
    Py_DECREF(fast);
    PyObject *out = PyBytes_FromStringAndSize(NULL, n_blocks * 16);
    if (out == NULL) {
        PyMem_Free(data);
        return NULL;
    }
    chained_block_hashes((const uint8_t *)data, (size_t)n_blocks,
                         (size_t)block_size * 4, (const uint8_t *)seed_buf,
                         (size_t)seed_len, (uint8_t *)PyBytes_AS_STRING(out));
    PyMem_Free(data);
    return out;
}
#endif /* BLOCKHASH_PYLIST */
