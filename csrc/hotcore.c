/* Native hot-path core: the top offenders named by the continuous
 * profiler (BENCH_profile_r19.json composition block), moved to C.
 *
 * Twin of xllm_service_tpu/common/native.py — every entry point here has
 * a mandatory pure-Python fallback at its call site, and the differential
 * property tests (tests/test_native_hotcore.py) assert byte-for-byte
 * parity between the two:
 *
 *   - hc_json_bytes / hc_sse_data_frame / hc_sse_event_frame:
 *     compact JSON serialization + SSE `data: ...\n\n` framing, parity
 *     with json.dumps(obj, ensure_ascii=False, separators=(",", ":"))
 *     (http_service/service.py _respond emit loop, the profiler's
 *     hottest output-lane frames).
 *   - hc_packb / hc_unpackb / hc_pack_b64 / hc_unpack_b64:
 *     msgpack encode/decode, parity with msgpack.packb(use_bin_type=True)
 *     / msgpack.unpackb(raw=False), plus the fused base64(msgpack) form
 *     the LOADFRAME wire uses (rpc/wire.py encode/decode_load_frame).
 *   - hc_rendezvous: the blake2b-8 highest-random-weight walk of
 *     multimaster/ownership.py (one native call over the member set).
 *   - hc_tok_encode: SimpleTokenizer.encode's utf8-byte+offset id map —
 *     the single hottest route frame (~70 us/KiB in pure Python).
 *
 * Error contract: every PyObject* entry point returns NULL with an
 * exception set for ANY input it does not support bit-exactly (int
 * subclasses, ext types, lone surrogates, non-canonical base64, depth
 * over the guard). The loader's wrappers catch, discard, and rerun the
 * pure-Python path, which either handles the input or raises the
 * canonical library error. Native is therefore an all-or-nothing fast
 * path: it never produces bytes the Python path would not.
 *
 * All entry points are called via ctypes.PyDLL — the GIL is held, so
 * CPython C-API use is safe and no locking is needed.
 *
 * Build: make -C csrc libhotcore.so (requires Python.h; the loader falls
 * back to pure Python when the .so is absent or XLLM_NATIVE=0).
 */

#include <Python.h>

#include <stddef.h>
#include <stdint.h>
#include <string.h>

/* ------------------------------------------------------------------ */
/* BLAKE2b core (RFC 7693), identical to csrc/blockhash.c — duplicated
 * rather than cross-linked so each .so stays a single-file build.      */
/* ------------------------------------------------------------------ */

static const uint64_t B2B_IV[8] = {
    0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL,
    0x3c6ef372fe94f82bULL, 0xa54ff53a5f1d36f1ULL,
    0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
    0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL,
};

static const uint8_t B2B_SIGMA[12][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
};

typedef struct {
    uint64_t h[8];
    uint64_t t0, t1;
    uint8_t buf[128];
    size_t buflen;
    size_t outlen;
} b2b_state;

static inline uint64_t rotr64(uint64_t x, unsigned n) {
    return (x >> n) | (x << (64 - n));
}

static inline uint64_t load64le(const uint8_t *p) {
    return (uint64_t)p[0] | ((uint64_t)p[1] << 8) | ((uint64_t)p[2] << 16) |
           ((uint64_t)p[3] << 24) | ((uint64_t)p[4] << 32) |
           ((uint64_t)p[5] << 40) | ((uint64_t)p[6] << 48) |
           ((uint64_t)p[7] << 56);
}

#define B2B_G(a, b, c, d, x, y)                                               \
    do {                                                                      \
        v[a] = v[a] + v[b] + (x);                                             \
        v[d] = rotr64(v[d] ^ v[a], 32);                                       \
        v[c] = v[c] + v[d];                                                   \
        v[b] = rotr64(v[b] ^ v[c], 24);                                       \
        v[a] = v[a] + v[b] + (y);                                             \
        v[d] = rotr64(v[d] ^ v[a], 16);                                       \
        v[c] = v[c] + v[d];                                                   \
        v[b] = rotr64(v[b] ^ v[c], 63);                                       \
    } while (0)

static void b2b_compress(b2b_state *S, const uint8_t block[128], int last) {
    uint64_t v[16], m[16];
    int i;
    for (i = 0; i < 8; i++) {
        v[i] = S->h[i];
        v[i + 8] = B2B_IV[i];
    }
    v[12] ^= S->t0;
    v[13] ^= S->t1;
    if (last)
        v[14] = ~v[14];
    for (i = 0; i < 16; i++)
        m[i] = load64le(block + 8 * i);
    for (i = 0; i < 12; i++) {
        const uint8_t *s = B2B_SIGMA[i];
        B2B_G(0, 4, 8, 12, m[s[0]], m[s[1]]);
        B2B_G(1, 5, 9, 13, m[s[2]], m[s[3]]);
        B2B_G(2, 6, 10, 14, m[s[4]], m[s[5]]);
        B2B_G(3, 7, 11, 15, m[s[6]], m[s[7]]);
        B2B_G(0, 5, 10, 15, m[s[8]], m[s[9]]);
        B2B_G(1, 6, 11, 12, m[s[10]], m[s[11]]);
        B2B_G(2, 7, 8, 13, m[s[12]], m[s[13]]);
        B2B_G(3, 4, 9, 14, m[s[14]], m[s[15]]);
    }
    for (i = 0; i < 8; i++)
        S->h[i] ^= v[i] ^ v[i + 8];
}

static void b2b_update(b2b_state *S, const uint8_t *in, size_t inlen) {
    while (inlen > 0) {
        if (S->buflen == 128) {
            S->t0 += 128;
            if (S->t0 < 128)
                S->t1++;
            b2b_compress(S, S->buf, 0);
            S->buflen = 0;
        }
        size_t n = 128 - S->buflen;
        if (n > inlen)
            n = inlen;
        memcpy(S->buf + S->buflen, in, n);
        S->buflen += n;
        in += n;
        inlen -= n;
    }
}

static void b2b_init(b2b_state *S, size_t outlen) {
    int i;
    memset(S, 0, sizeof(*S));
    for (i = 0; i < 8; i++)
        S->h[i] = B2B_IV[i];
    S->h[0] ^= 0x01010000ULL ^ (uint64_t)outlen;
    S->outlen = outlen;
}

static void b2b_final(b2b_state *S, uint8_t *out) {
    size_t i;
    S->t0 += S->buflen;
    if (S->t0 < S->buflen)
        S->t1++;
    memset(S->buf + S->buflen, 0, 128 - S->buflen);
    b2b_compress(S, S->buf, 1);
    for (i = 0; i < S->outlen; i++)
        out[i] = (uint8_t)(S->h[i >> 3] >> (8 * (i & 7)));
}

/* ------------------------------------------------------------------ */
/* Growable output buffer.                                             */
/* ------------------------------------------------------------------ */

typedef struct {
    char *p;
    size_t len, cap;
    int err; /* sticky: 1 = OOM */
} hc_buf;

static int buf_init(hc_buf *b, size_t cap) {
    b->p = (char *)PyMem_Malloc(cap);
    b->len = 0;
    b->cap = cap;
    b->err = b->p == NULL;
    return b->err ? -1 : 0;
}

static void buf_free(hc_buf *b) {
    if (b->p)
        PyMem_Free(b->p);
    b->p = NULL;
}

static int buf_grow(hc_buf *b, size_t need) {
    size_t cap = b->cap;
    while (cap - b->len < need)
        cap = cap < 4096 ? cap * 2 : cap + cap / 2;
    char *np = (char *)PyMem_Realloc(b->p, cap);
    if (np == NULL) {
        b->err = 1;
        return -1;
    }
    b->p = np;
    b->cap = cap;
    return 0;
}

static inline int buf_reserve(hc_buf *b, size_t need) {
    if (b->err)
        return -1;
    if (b->cap - b->len < need)
        return buf_grow(b, need);
    return 0;
}

static inline void buf_put(hc_buf *b, const char *src, size_t n) {
    if (buf_reserve(b, n) < 0)
        return;
    memcpy(b->p + b->len, src, n);
    b->len += n;
}

static inline void buf_putc(hc_buf *b, char c) {
    if (buf_reserve(b, 1) < 0)
        return;
    b->p[b->len++] = c;
}

/* "This input is valid but outside the native subset — rerun on the
 * pure-Python path." The loader treats any exception as this signal. */
static void *unsupported(const char *what) {
    PyErr_Format(PyExc_TypeError, "hotcore: unsupported input (%s)", what);
    return NULL;
}

#define HC_MAX_DEPTH 64

/* ------------------------------------------------------------------ */
/* JSON serializer: parity with                                        */
/*   json.dumps(obj, ensure_ascii=False, separators=(",", ":"))        */
/* ------------------------------------------------------------------ */

static const char HEXDIG[] = "0123456789abcdef";

static int json_write_str(hc_buf *b, PyObject *s) {
    Py_ssize_t n;
    const char *u = PyUnicode_AsUTF8AndSize(s, &n);
    if (u == NULL)
        return -1; /* lone surrogate: UnicodeEncodeError -> fallback */
    buf_putc(b, '"');
    Py_ssize_t run = 0, i = 0;
    for (i = 0; i < n; i++) {
        unsigned char c = (unsigned char)u[i];
        /* ensure_ascii=False: only '"', '\\' and controls < 0x20 are
         * escaped; everything else (incl. UTF-8 multibyte) passes raw. */
        if (c >= 0x20 && c != '"' && c != '\\') {
            run++;
            continue;
        }
        if (run)
            buf_put(b, u + i - run, (size_t)run);
        run = 0;
        switch (c) {
        case '"':
            buf_put(b, "\\\"", 2);
            break;
        case '\\':
            buf_put(b, "\\\\", 2);
            break;
        case '\b':
            buf_put(b, "\\b", 2);
            break;
        case '\t':
            buf_put(b, "\\t", 2);
            break;
        case '\n':
            buf_put(b, "\\n", 2);
            break;
        case '\f':
            buf_put(b, "\\f", 2);
            break;
        case '\r':
            buf_put(b, "\\r", 2);
            break;
        default: {
            char esc[6] = {'\\', 'u', '0', '0', HEXDIG[c >> 4],
                           HEXDIG[c & 15]};
            buf_put(b, esc, 6);
        }
        }
    }
    if (run)
        buf_put(b, u + n - run, (size_t)run);
    buf_putc(b, '"');
    return 0;
}

static int json_write_float(hc_buf *b, double v) {
    if (Py_IS_NAN(v)) {
        buf_put(b, "NaN", 3);
        return 0;
    }
    if (Py_IS_INFINITY(v)) {
        if (v < 0)
            buf_put(b, "-Infinity", 9);
        else
            buf_put(b, "Infinity", 8);
        return 0;
    }
    /* Exactly float.__repr__, which is exactly what json.dumps emits. */
    char *s = PyOS_double_to_string(v, 'r', 0, Py_DTSF_ADD_DOT_0, NULL);
    if (s == NULL)
        return -1;
    buf_put(b, s, strlen(s));
    PyMem_Free(s);
    return 0;
}

static int json_write_long(hc_buf *b, PyObject *obj) {
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(obj, &overflow);
    if (v == -1 && !overflow && PyErr_Occurred())
        return -1;
    if (!overflow) {
        char tmp[24];
        int n = snprintf(tmp, sizeof(tmp), "%lld", v);
        buf_put(b, tmp, (size_t)n);
        return 0;
    }
    /* Arbitrary-size int: same digits as int.__repr__. */
    PyObject *r = PyLong_Type.tp_repr(obj);
    if (r == NULL)
        return -1;
    Py_ssize_t n;
    const char *u = PyUnicode_AsUTF8AndSize(r, &n);
    if (u == NULL) {
        Py_DECREF(r);
        return -1;
    }
    buf_put(b, u, (size_t)n);
    Py_DECREF(r);
    return 0;
}

static int json_write(hc_buf *b, PyObject *obj, int depth) {
    if (depth > HC_MAX_DEPTH) {
        unsupported("nesting depth");
        return -1;
    }
    if (obj == Py_None) {
        buf_put(b, "null", 4);
        return 0;
    }
    if (obj == Py_True) {
        buf_put(b, "true", 4);
        return 0;
    }
    if (obj == Py_False) {
        buf_put(b, "false", 5);
        return 0;
    }
    if (PyUnicode_CheckExact(obj))
        return json_write_str(b, obj);
    if (PyLong_CheckExact(obj))
        return json_write_long(b, obj);
    if (PyFloat_CheckExact(obj))
        return json_write_float(b, PyFloat_AS_DOUBLE(obj));
    if (PyDict_CheckExact(obj)) {
        buf_putc(b, '{');
        PyObject *k, *v;
        Py_ssize_t pos = 0;
        int first = 1;
        while (PyDict_Next(obj, &pos, &k, &v)) {
            if (!PyUnicode_CheckExact(k)) {
                unsupported("non-str dict key");
                return -1;
            }
            if (!first)
                buf_putc(b, ',');
            first = 0;
            if (json_write_str(b, k) < 0)
                return -1;
            buf_putc(b, ':');
            if (json_write(b, v, depth + 1) < 0)
                return -1;
        }
        buf_putc(b, '}');
        return 0;
    }
    if (PyList_CheckExact(obj) || PyTuple_CheckExact(obj)) {
        buf_putc(b, '[');
        Py_ssize_t n = PySequence_Fast_GET_SIZE(obj);
        PyObject **items = PySequence_Fast_ITEMS(obj);
        for (Py_ssize_t i = 0; i < n; i++) {
            if (i)
                buf_putc(b, ',');
            if (json_write(b, items[i], depth + 1) < 0)
                return -1;
        }
        buf_putc(b, ']');
        return 0;
    }
    /* Subclasses, enums, dataclasses, ... -> Python encoder. */
    unsupported(Py_TYPE(obj)->tp_name);
    return -1;
}

static PyObject *buf_to_bytes(hc_buf *b) {
    if (b->err) {
        buf_free(b);
        if (!PyErr_Occurred())
            PyErr_NoMemory();
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize(b->p, (Py_ssize_t)b->len);
    buf_free(b);
    return out;
}

PyObject *hc_json_bytes(PyObject *obj) {
    hc_buf b;
    if (buf_init(&b, 256) < 0)
        return PyErr_NoMemory();
    if (json_write(&b, obj, 0) < 0) {
        buf_free(&b);
        return NULL;
    }
    return buf_to_bytes(&b);
}

/* SSE data frame: b"data: " + json + b"\n\n" (service.py _respond). */
PyObject *hc_sse_data_frame(PyObject *obj) {
    hc_buf b;
    if (buf_init(&b, 256) < 0)
        return PyErr_NoMemory();
    buf_put(&b, "data: ", 6);
    if (json_write(&b, obj, 0) < 0) {
        buf_free(&b);
        return NULL;
    }
    buf_put(&b, "\n\n", 2);
    return buf_to_bytes(&b);
}

/* SSE named-event frame: b"event: <name>\ndata: <json>\n\n". */
PyObject *hc_sse_event_frame(PyObject *name, PyObject *obj) {
    if (!PyUnicode_CheckExact(name))
        return unsupported("event name");
    Py_ssize_t nlen;
    const char *n = PyUnicode_AsUTF8AndSize(name, &nlen);
    if (n == NULL)
        return NULL;
    hc_buf b;
    if (buf_init(&b, 256 + (size_t)nlen) < 0)
        return PyErr_NoMemory();
    buf_put(&b, "event: ", 7);
    buf_put(&b, n, (size_t)nlen);
    buf_put(&b, "\ndata: ", 7);
    if (json_write(&b, obj, 0) < 0) {
        buf_free(&b);
        return NULL;
    }
    buf_put(&b, "\n\n", 2);
    return buf_to_bytes(&b);
}

/* ------------------------------------------------------------------ */
/* msgpack packer: parity with msgpack.packb(obj, use_bin_type=True).  */
/* ------------------------------------------------------------------ */

static inline void put_be16(hc_buf *b, uint16_t v) {
    char t[2] = {(char)(v >> 8), (char)v};
    buf_put(b, t, 2);
}

static inline void put_be32(hc_buf *b, uint32_t v) {
    char t[4] = {(char)(v >> 24), (char)(v >> 16), (char)(v >> 8), (char)v};
    buf_put(b, t, 4);
}

static inline void put_be64(hc_buf *b, uint64_t v) {
    char t[8] = {(char)(v >> 56), (char)(v >> 48), (char)(v >> 40),
                 (char)(v >> 32), (char)(v >> 24), (char)(v >> 16),
                 (char)(v >> 8),  (char)v};
    buf_put(b, t, 8);
}

static int mp_write_long(hc_buf *b, PyObject *obj) {
    int overflow = 0;
    long long d = PyLong_AsLongLongAndOverflow(obj, &overflow);
    if (d == -1 && !overflow && PyErr_Occurred())
        return -1;
    if (overflow > 0) {
        /* LLONG_MAX < v: msgpack packs uint64 when it fits, else
         * OverflowError (via the fallback). */
        unsigned long long u = PyLong_AsUnsignedLongLong(obj);
        if (u == (unsigned long long)-1 && PyErr_Occurred())
            return -1;
        buf_putc(b, (char)0xcf);
        put_be64(b, (uint64_t)u);
        return 0;
    }
    if (overflow < 0) {
        unsupported("int below int64");
        return -1;
    }
    /* msgpack-c pack_template.h: smallest encoding that fits. */
    if (d < -(1LL << 5)) {
        if (d < -(1LL << 15)) {
            if (d < -(1LL << 31)) {
                buf_putc(b, (char)0xd3);
                put_be64(b, (uint64_t)d);
            } else {
                buf_putc(b, (char)0xd2);
                put_be32(b, (uint32_t)(int32_t)d);
            }
        } else if (d < -(1LL << 7)) {
            buf_putc(b, (char)0xd1);
            put_be16(b, (uint16_t)(int16_t)d);
        } else {
            buf_putc(b, (char)0xd0);
            buf_putc(b, (char)(int8_t)d);
        }
    } else if (d < (1LL << 7)) {
        buf_putc(b, (char)(int8_t)d); /* pos/neg fixint */
    } else if (d < (1LL << 8)) {
        buf_putc(b, (char)0xcc);
        buf_putc(b, (char)(uint8_t)d);
    } else if (d < (1LL << 16)) {
        buf_putc(b, (char)0xcd);
        put_be16(b, (uint16_t)d);
    } else if (d < (1LL << 32)) {
        buf_putc(b, (char)0xce);
        put_be32(b, (uint32_t)d);
    } else {
        buf_putc(b, (char)0xcf);
        put_be64(b, (uint64_t)d);
    }
    return 0;
}

static int mp_write(hc_buf *b, PyObject *obj, int depth) {
    if (depth > HC_MAX_DEPTH) {
        unsupported("nesting depth");
        return -1;
    }
    if (obj == Py_None) {
        buf_putc(b, (char)0xc0);
        return 0;
    }
    if (obj == Py_True) {
        buf_putc(b, (char)0xc3);
        return 0;
    }
    if (obj == Py_False) {
        buf_putc(b, (char)0xc2);
        return 0;
    }
    if (PyLong_CheckExact(obj))
        return mp_write_long(b, obj);
    if (PyFloat_CheckExact(obj)) {
        double v = PyFloat_AS_DOUBLE(obj);
        uint64_t bits;
        memcpy(&bits, &v, 8);
        buf_putc(b, (char)0xcb);
        put_be64(b, bits);
        return 0;
    }
    if (PyUnicode_CheckExact(obj)) {
        Py_ssize_t n;
        const char *u = PyUnicode_AsUTF8AndSize(obj, &n);
        if (u == NULL)
            return -1;
        if (n < 32) {
            buf_putc(b, (char)(0xa0 | (unsigned)n));
        } else if (n < 256) {
            buf_putc(b, (char)0xd9);
            buf_putc(b, (char)(uint8_t)n);
        } else if (n < 65536) {
            buf_putc(b, (char)0xda);
            put_be16(b, (uint16_t)n);
        } else {
            buf_putc(b, (char)0xdb);
            put_be32(b, (uint32_t)n);
        }
        buf_put(b, u, (size_t)n);
        return 0;
    }
    if (PyBytes_CheckExact(obj)) {
        Py_ssize_t n = PyBytes_GET_SIZE(obj);
        if (n < 256) {
            buf_putc(b, (char)0xc4);
            buf_putc(b, (char)(uint8_t)n);
        } else if (n < 65536) {
            buf_putc(b, (char)0xc5);
            put_be16(b, (uint16_t)n);
        } else {
            buf_putc(b, (char)0xc6);
            put_be32(b, (uint32_t)n);
        }
        buf_put(b, PyBytes_AS_STRING(obj), (size_t)n);
        return 0;
    }
    if (PyList_CheckExact(obj) || PyTuple_CheckExact(obj)) {
        Py_ssize_t n = PySequence_Fast_GET_SIZE(obj);
        if (n < 16) {
            buf_putc(b, (char)(0x90 | (unsigned)n));
        } else if (n < 65536) {
            buf_putc(b, (char)0xdc);
            put_be16(b, (uint16_t)n);
        } else {
            buf_putc(b, (char)0xdd);
            put_be32(b, (uint32_t)n);
        }
        PyObject **items = PySequence_Fast_ITEMS(obj);
        for (Py_ssize_t i = 0; i < n; i++)
            if (mp_write(b, items[i], depth + 1) < 0)
                return -1;
        return 0;
    }
    if (PyDict_CheckExact(obj)) {
        Py_ssize_t n = PyDict_GET_SIZE(obj);
        if (n < 16) {
            buf_putc(b, (char)(0x80 | (unsigned)n));
        } else if (n < 65536) {
            buf_putc(b, (char)0xde);
            put_be16(b, (uint16_t)n);
        } else {
            buf_putc(b, (char)0xdf);
            put_be32(b, (uint32_t)n);
        }
        PyObject *k, *v;
        Py_ssize_t pos = 0;
        while (PyDict_Next(obj, &pos, &k, &v)) {
            if (mp_write(b, k, depth + 1) < 0)
                return -1;
            if (mp_write(b, v, depth + 1) < 0)
                return -1;
        }
        return 0;
    }
    unsupported(Py_TYPE(obj)->tp_name);
    return -1;
}

PyObject *hc_packb(PyObject *obj) {
    hc_buf b;
    if (buf_init(&b, 256) < 0)
        return PyErr_NoMemory();
    if (mp_write(&b, obj, 0) < 0) {
        buf_free(&b);
        return NULL;
    }
    return buf_to_bytes(&b);
}

/* ------------------------------------------------------------------ */
/* msgpack unpacker: parity with msgpack.unpackb(data, raw=False).     */
/* Any shortfall (ext types, invalid utf-8, truncation, trailing       */
/* bytes) -> NULL, and the loader reruns msgpack for the canonical     */
/* result or error.                                                    */
/* ------------------------------------------------------------------ */

typedef struct {
    const uint8_t *p;
    size_t len, off;
} mp_reader;

static inline int rd_need(mp_reader *r, size_t n) {
    if (r->len - r->off < n) {
        unsupported("truncated msgpack");
        return -1;
    }
    return 0;
}

static inline uint16_t rd_be16(mp_reader *r) {
    const uint8_t *p = r->p + r->off;
    r->off += 2;
    return (uint16_t)((p[0] << 8) | p[1]);
}

static inline uint32_t rd_be32(mp_reader *r) {
    const uint8_t *p = r->p + r->off;
    r->off += 4;
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

static inline uint64_t rd_be64(mp_reader *r) {
    uint64_t hi = rd_be32(r);
    return (hi << 32) | rd_be32(r);
}

static PyObject *mp_read(mp_reader *r, int depth);

static PyObject *mp_read_str(mp_reader *r, size_t n) {
    if (rd_need(r, n) < 0)
        return NULL;
    PyObject *s = PyUnicode_DecodeUTF8((const char *)r->p + r->off,
                                       (Py_ssize_t)n, NULL);
    r->off += n;
    return s; /* invalid utf-8 -> NULL -> fallback raises canonically */
}

static PyObject *mp_read_bin(mp_reader *r, size_t n) {
    if (rd_need(r, n) < 0)
        return NULL;
    PyObject *s =
        PyBytes_FromStringAndSize((const char *)r->p + r->off, (Py_ssize_t)n);
    r->off += n;
    return s;
}

static PyObject *mp_read_array(mp_reader *r, size_t n, int depth) {
    if (n > r->len - r->off) { /* >=1 byte per element */
        unsupported("truncated msgpack array");
        return NULL;
    }
    PyObject *list = PyList_New((Py_ssize_t)n);
    if (list == NULL)
        return NULL;
    for (size_t i = 0; i < n; i++) {
        PyObject *v = mp_read(r, depth + 1);
        if (v == NULL) {
            Py_DECREF(list);
            return NULL;
        }
        PyList_SET_ITEM(list, (Py_ssize_t)i, v);
    }
    return list;
}

static PyObject *mp_read_map(mp_reader *r, size_t n, int depth) {
    if (n > (r->len - r->off) / 2) {
        unsupported("truncated msgpack map");
        return NULL;
    }
    PyObject *d = PyDict_New();
    if (d == NULL)
        return NULL;
    for (size_t i = 0; i < n; i++) {
        PyObject *k = mp_read(r, depth + 1);
        if (k == NULL) {
            Py_DECREF(d);
            return NULL;
        }
        PyObject *v = mp_read(r, depth + 1);
        if (v == NULL) {
            Py_DECREF(k);
            Py_DECREF(d);
            return NULL;
        }
        int rc = PyDict_SetItem(d, k, v);
        Py_DECREF(k);
        Py_DECREF(v);
        if (rc < 0) {
            Py_DECREF(d);
            return NULL;
        }
    }
    return d;
}

static PyObject *mp_read(mp_reader *r, int depth) {
    if (depth > HC_MAX_DEPTH)
        return unsupported("nesting depth");
    if (rd_need(r, 1) < 0)
        return NULL;
    uint8_t c = r->p[r->off++];
    if (c < 0x80)
        return PyLong_FromLong(c); /* positive fixint */
    if (c >= 0xe0)
        return PyLong_FromLong((long)(int8_t)c); /* negative fixint */
    if ((c & 0xf0) == 0x80)
        return mp_read_map(r, c & 0x0f, depth);
    if ((c & 0xf0) == 0x90)
        return mp_read_array(r, c & 0x0f, depth);
    if ((c & 0xe0) == 0xa0)
        return mp_read_str(r, c & 0x1f);
    switch (c) {
    case 0xc0:
        Py_RETURN_NONE;
    case 0xc2:
        Py_RETURN_FALSE;
    case 0xc3:
        Py_RETURN_TRUE;
    case 0xc4:
        if (rd_need(r, 1) < 0)
            return NULL;
        return mp_read_bin(r, r->p[r->off++]);
    case 0xc5:
        if (rd_need(r, 2) < 0)
            return NULL;
        return mp_read_bin(r, rd_be16(r));
    case 0xc6:
        if (rd_need(r, 4) < 0)
            return NULL;
        return mp_read_bin(r, rd_be32(r));
    case 0xca: { /* float32: widened to double, like msgpack-python */
        if (rd_need(r, 4) < 0)
            return NULL;
        uint32_t bits = rd_be32(r);
        float f;
        memcpy(&f, &bits, 4);
        return PyFloat_FromDouble((double)f);
    }
    case 0xcb: {
        if (rd_need(r, 8) < 0)
            return NULL;
        uint64_t bits = rd_be64(r);
        double d;
        memcpy(&d, &bits, 8);
        return PyFloat_FromDouble(d);
    }
    case 0xcc:
        if (rd_need(r, 1) < 0)
            return NULL;
        return PyLong_FromLong(r->p[r->off++]);
    case 0xcd:
        if (rd_need(r, 2) < 0)
            return NULL;
        return PyLong_FromLong(rd_be16(r));
    case 0xce:
        if (rd_need(r, 4) < 0)
            return NULL;
        return PyLong_FromUnsignedLong(rd_be32(r));
    case 0xcf:
        if (rd_need(r, 8) < 0)
            return NULL;
        return PyLong_FromUnsignedLongLong(rd_be64(r));
    case 0xd0:
        if (rd_need(r, 1) < 0)
            return NULL;
        return PyLong_FromLong((long)(int8_t)r->p[r->off++]);
    case 0xd1:
        if (rd_need(r, 2) < 0)
            return NULL;
        return PyLong_FromLong((long)(int16_t)rd_be16(r));
    case 0xd2:
        if (rd_need(r, 4) < 0)
            return NULL;
        return PyLong_FromLong((long)(int32_t)rd_be32(r));
    case 0xd3:
        if (rd_need(r, 8) < 0)
            return NULL;
        return PyLong_FromLongLong((long long)(int64_t)rd_be64(r));
    case 0xd9:
        if (rd_need(r, 1) < 0)
            return NULL;
        return mp_read_str(r, r->p[r->off++]);
    case 0xda:
        if (rd_need(r, 2) < 0)
            return NULL;
        return mp_read_str(r, rd_be16(r));
    case 0xdb:
        if (rd_need(r, 4) < 0)
            return NULL;
        return mp_read_str(r, rd_be32(r));
    case 0xdc:
        if (rd_need(r, 2) < 0)
            return NULL;
        return mp_read_array(r, rd_be16(r), depth);
    case 0xdd:
        if (rd_need(r, 4) < 0)
            return NULL;
        return mp_read_array(r, rd_be32(r), depth);
    case 0xde:
        if (rd_need(r, 2) < 0)
            return NULL;
        return mp_read_map(r, rd_be16(r), depth);
    case 0xdf:
        if (rd_need(r, 4) < 0)
            return NULL;
        return mp_read_map(r, rd_be32(r), depth);
    default:
        /* ext family (0xc1, 0xc7-0xc9, 0xd4-0xd8): never on this wire;
         * the fallback decides whether it is valid. */
        return unsupported("msgpack type");
    }
}

static PyObject *mp_unpack_buf(const uint8_t *p, size_t len) {
    mp_reader r = {p, len, 0};
    PyObject *obj = mp_read(&r, 0);
    if (obj == NULL)
        return NULL;
    if (r.off != r.len) {
        Py_DECREF(obj);
        return unsupported("trailing msgpack bytes");
    }
    return obj;
}

PyObject *hc_unpackb(PyObject *data) {
    if (!PyBytes_CheckExact(data))
        return unsupported("unpack input");
    return mp_unpack_buf((const uint8_t *)PyBytes_AS_STRING(data),
                         (size_t)PyBytes_GET_SIZE(data));
}

/* ------------------------------------------------------------------ */
/* base64 (standard alphabet, canonical form only) fused with msgpack  */
/* for the LOADFRAME wire: str = b64(msgpack(frame)).                  */
/* ------------------------------------------------------------------ */

static const char B64E[] =
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";

static int8_t B64D[256]; /* built lazily: -1 invalid */
static int b64d_ready = 0;

static void b64d_build(void) {
    memset(B64D, -1, sizeof(B64D));
    for (int i = 0; i < 64; i++)
        B64D[(uint8_t)B64E[i]] = (int8_t)i;
    b64d_ready = 1;
}

PyObject *hc_pack_b64(PyObject *obj) {
    hc_buf b;
    if (buf_init(&b, 256) < 0)
        return PyErr_NoMemory();
    if (mp_write(&b, obj, 0) < 0) {
        buf_free(&b);
        return NULL;
    }
    if (b.err) {
        buf_free(&b);
        return PyErr_NoMemory();
    }
    size_t n = b.len;
    size_t outn = ((n + 2) / 3) * 4;
    PyObject *s = PyUnicode_New((Py_ssize_t)outn, 127);
    if (s == NULL) {
        buf_free(&b);
        return NULL;
    }
    uint8_t *o = (uint8_t *)PyUnicode_1BYTE_DATA(s);
    const uint8_t *in = (const uint8_t *)b.p;
    size_t i = 0;
    while (i + 3 <= n) {
        uint32_t v = ((uint32_t)in[i] << 16) | ((uint32_t)in[i + 1] << 8) |
                     in[i + 2];
        *o++ = (uint8_t)B64E[(v >> 18) & 63];
        *o++ = (uint8_t)B64E[(v >> 12) & 63];
        *o++ = (uint8_t)B64E[(v >> 6) & 63];
        *o++ = (uint8_t)B64E[v & 63];
        i += 3;
    }
    if (i + 1 == n) {
        uint32_t v = (uint32_t)in[i] << 16;
        *o++ = (uint8_t)B64E[(v >> 18) & 63];
        *o++ = (uint8_t)B64E[(v >> 12) & 63];
        *o++ = '=';
        *o++ = '=';
    } else if (i + 2 == n) {
        uint32_t v = ((uint32_t)in[i] << 16) | ((uint32_t)in[i + 1] << 8);
        *o++ = (uint8_t)B64E[(v >> 18) & 63];
        *o++ = (uint8_t)B64E[(v >> 12) & 63];
        *o++ = (uint8_t)B64E[(v >> 6) & 63];
        *o++ = '=';
    }
    buf_free(&b);
    return s;
}

PyObject *hc_unpack_b64(PyObject *s) {
    const uint8_t *in;
    size_t n;
    Py_ssize_t sn;
    if (PyUnicode_CheckExact(s)) {
        const char *u = PyUnicode_AsUTF8AndSize(s, &sn);
        if (u == NULL)
            return NULL;
        in = (const uint8_t *)u;
        n = (size_t)sn;
    } else if (PyBytes_CheckExact(s)) {
        in = (const uint8_t *)PyBytes_AS_STRING(s);
        n = (size_t)PyBytes_GET_SIZE(s);
    } else {
        return unsupported("b64 input");
    }
    /* Canonical base64 only (what our encoders emit); anything looser
     * (whitespace, missing padding) goes to base64.b64decode via the
     * fallback. */
    if (n == 0 || n % 4 != 0)
        return unsupported("non-canonical base64");
    if (!b64d_ready)
        b64d_build();
    size_t pad = 0;
    if (in[n - 1] == '=')
        pad++;
    if (in[n - 2] == '=')
        pad++;
    size_t outn = n / 4 * 3 - pad;
    uint8_t *buf = (uint8_t *)PyMem_Malloc(outn ? outn : 1);
    if (buf == NULL)
        return PyErr_NoMemory();
    uint8_t *o = buf;
    for (size_t i = 0; i < n; i += 4) {
        int8_t a = B64D[in[i]], b = B64D[in[i + 1]];
        int8_t c, d;
        int npad = 0;
        if (in[i + 2] == '=') {
            c = 0;
            npad = 2;
            if (in[i + 3] != '=' || i + 4 != n)
                goto bad;
            d = 0;
        } else {
            c = B64D[in[i + 2]];
            if (in[i + 3] == '=') {
                npad = 1;
                if (i + 4 != n)
                    goto bad;
                d = 0;
            } else {
                d = B64D[in[i + 3]];
            }
        }
        if (a < 0 || b < 0 || c < 0 || d < 0)
            goto bad;
        uint32_t v = ((uint32_t)a << 18) | ((uint32_t)b << 12) |
                     ((uint32_t)c << 6) | (uint32_t)d;
        *o++ = (uint8_t)(v >> 16);
        if (npad < 2)
            *o++ = (uint8_t)(v >> 8);
        if (npad < 1)
            *o++ = (uint8_t)v;
    }
    {
        PyObject *obj = mp_unpack_buf(buf, outn);
        PyMem_Free(buf);
        return obj;
    }
bad:
    PyMem_Free(buf);
    return unsupported("non-canonical base64");
}

/* ------------------------------------------------------------------ */
/* Rendezvous (HRW) walk: parity with ownership._rendezvous_score —    */
/* score(m) = BE-uint64 of blake2b(f"{m}|{key}", digest_size=8);       */
/* first strictly-greatest member wins. One native call per walk.      */
/* ------------------------------------------------------------------ */

PyObject *hc_rendezvous(PyObject *members, PyObject *key) {
    if (!(PyTuple_CheckExact(members) || PyList_CheckExact(members)))
        return unsupported("members sequence");
    if (!PyUnicode_CheckExact(key))
        return unsupported("rendezvous key");
    Py_ssize_t klen;
    const char *k = PyUnicode_AsUTF8AndSize(key, &klen);
    if (k == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(members);
    PyObject **items = PySequence_Fast_ITEMS(members);
    PyObject *best = NULL;
    uint64_t best_score = 0;
    uint8_t stackbuf[512];
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *m = items[i];
        if (!PyUnicode_CheckExact(m))
            return unsupported("member");
        Py_ssize_t mlen;
        const char *mu = PyUnicode_AsUTF8AndSize(m, &mlen);
        if (mu == NULL)
            return NULL;
        size_t total = (size_t)mlen + 1 + (size_t)klen;
        uint8_t *msg = stackbuf;
        if (total > sizeof(stackbuf)) {
            msg = (uint8_t *)PyMem_Malloc(total);
            if (msg == NULL)
                return PyErr_NoMemory();
        }
        memcpy(msg, mu, (size_t)mlen);
        msg[mlen] = '|';
        memcpy(msg + mlen + 1, k, (size_t)klen);
        b2b_state S;
        uint8_t dig[8];
        b2b_init(&S, 8);
        b2b_update(&S, msg, total);
        b2b_final(&S, dig);
        if (msg != stackbuf)
            PyMem_Free(msg);
        uint64_t score = ((uint64_t)dig[0] << 56) | ((uint64_t)dig[1] << 48) |
                         ((uint64_t)dig[2] << 40) | ((uint64_t)dig[3] << 32) |
                         ((uint64_t)dig[4] << 24) | ((uint64_t)dig[5] << 16) |
                         ((uint64_t)dig[6] << 8) | (uint64_t)dig[7];
        if (best == NULL || score > best_score) {
            best = m;
            best_score = score;
        }
    }
    if (best == NULL)
        return PyUnicode_FromStringAndSize("", 0);
    Py_INCREF(best);
    return best;
}

/* ------------------------------------------------------------------ */
/* Byte tokenizer: parity with SimpleTokenizer.encode —                */
/* [b + 256 for b in text.encode("utf-8")].                            */
/* ------------------------------------------------------------------ */

/* The id space is exactly byte+256 = [256, 511], so every id a prompt can
 * produce comes from a 256-entry table of interned PyLongs built on first
 * use.  Encoding is then one INCREF + pointer store per byte instead of a
 * PyLong allocation, which is what keeps the native slope flat under
 * allocator pressure at fleet load (boxing 24K ints per batch prompt
 * otherwise dominates the C path).  GIL held (PyDLL), so the lazy init
 * needs no locking. */
static PyObject *tok_id_table[256];

static int tok_table_init(void) {
    if (tok_id_table[0] != NULL)
        return 0;
    for (int i = 0; i < 256; i++) {
        PyObject *v = PyLong_FromLong((long)i + 256);
        if (v == NULL) {
            for (int j = 0; j < i; j++) {
                Py_CLEAR(tok_id_table[j]);
            }
            return -1;
        }
        tok_id_table[i] = v;
    }
    return 0;
}

PyObject *hc_tok_encode(PyObject *text) {
    if (!PyUnicode_CheckExact(text))
        return unsupported("tokenizer input");
    if (tok_table_init() != 0)
        return NULL;
    Py_ssize_t n;
    const char *u = PyUnicode_AsUTF8AndSize(text, &n);
    if (u == NULL)
        return NULL;
    PyObject *list = PyList_New(n);
    if (list == NULL)
        return NULL;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *v = tok_id_table[(uint8_t)u[i]];
        Py_INCREF(v);
        PyList_SET_ITEM(list, i, v);
    }
    return list;
}

/* Loader handshake (ctypes CDLL-callable). */
int hc_abi_version(void) { return 1; }
