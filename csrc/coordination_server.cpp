// xllm-service-tpu coordination server (native).
//
// Standalone C++17 binary replacing the external etcd cluster the reference
// depends on (reference wraps etcd-cpp-apiv3 in scheduler/etcd_client/;
// SURVEY.md §2.7). Speaks the framework's newline-delimited JSON protocol
// (see xllm_service_tpu/coordination/server.py — the Python client and this
// server are wire-compatible; both are covered by the same test suite).
//
// Capabilities (etcd-parity as used by the orchestration plane):
//   - put (plain / TTL-leased / create-if-absent), refresh (lease keepalive)
//   - get, get_prefix, rm, guarded rm_prefix, bulk_set, bulk_rm
//   - recursive prefix watches with PUT/DELETE push events
//   - lease expiry sweep -> DELETE events (the liveness primitive instance
//     failure detection builds on)
//   - optional username/password auth
//
// Single-threaded poll() event loop; no external dependencies.
//
// Build: g++ -O2 -std=c++17 -o coordination_server coordination_server.cpp
// Run:   ./coordination_server --port 2379 [--username u --password p]

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <optional>
#include <string>
#include <unordered_map>
#include <variant>
#include <vector>

// ---------------------------------------------------------------- JSON ----
// Minimal JSON value + parser + writer (objects, arrays, strings, numbers,
// bools, null) — sufficient for the coordination protocol.
struct Json;
using JsonObject = std::map<std::string, Json>;
using JsonArray = std::vector<Json>;

struct Json {
  std::variant<std::nullptr_t, bool, double, std::string, JsonArray,
               JsonObject>
      v = nullptr;

  bool is_null() const { return std::holds_alternative<std::nullptr_t>(v); }
  bool is_str() const { return std::holds_alternative<std::string>(v); }
  bool is_obj() const { return std::holds_alternative<JsonObject>(v); }
  bool is_arr() const { return std::holds_alternative<JsonArray>(v); }
  const std::string& str() const { return std::get<std::string>(v); }
  double num() const { return std::get<double>(v); }
  bool boolean() const { return std::get<bool>(v); }
  const JsonObject& obj() const { return std::get<JsonObject>(v); }
  const JsonArray& arr() const { return std::get<JsonArray>(v); }

  const Json* find(const std::string& key) const {
    if (!is_obj()) return nullptr;
    auto it = obj().find(key);
    return it == obj().end() ? nullptr : &it->second;
  }
  std::string get_str(const std::string& key,
                      const std::string& dflt = "") const {
    const Json* j = find(key);
    return j && j->is_str() ? j->str() : dflt;
  }
  std::optional<double> get_num(const std::string& key) const {
    const Json* j = find(key);
    if (j && std::holds_alternative<double>(j->v)) return j->num();
    return std::nullopt;
  }
  bool get_bool(const std::string& key, bool dflt = false) const {
    const Json* j = find(key);
    return j && std::holds_alternative<bool>(j->v) ? j->boolean() : dflt;
  }
};

class JsonParser {
 public:
  explicit JsonParser(const std::string& s) : s_(s) {}

  bool parse(Json* out) {
    skip_ws();
    if (!value(out)) return false;
    skip_ws();
    return pos_ == s_.size();
  }

 private:
  const std::string& s_;
  size_t pos_ = 0;

  void skip_ws() {
    while (pos_ < s_.size() && (s_[pos_] == ' ' || s_[pos_] == '\t' ||
                                s_[pos_] == '\n' || s_[pos_] == '\r'))
      pos_++;
  }
  bool literal(const char* lit) {
    size_t n = strlen(lit);
    if (s_.compare(pos_, n, lit) != 0) return false;
    pos_ += n;
    return true;
  }
  bool value(Json* out) {
    skip_ws();
    if (pos_ >= s_.size()) return false;
    char c = s_[pos_];
    if (c == '{') return object(out);
    if (c == '[') return array(out);
    if (c == '"') {
      std::string str;
      if (!string_(&str)) return false;
      out->v = std::move(str);
      return true;
    }
    if (c == 't') { out->v = true; return literal("true"); }
    if (c == 'f') { out->v = false; return literal("false"); }
    if (c == 'n') { out->v = nullptr; return literal("null"); }
    return number(out);
  }
  bool object(Json* out) {
    pos_++;  // '{'
    JsonObject obj;
    skip_ws();
    if (pos_ < s_.size() && s_[pos_] == '}') { pos_++; out->v = std::move(obj); return true; }
    while (true) {
      skip_ws();
      std::string key;
      if (!string_(&key)) return false;
      skip_ws();
      if (pos_ >= s_.size() || s_[pos_] != ':') return false;
      pos_++;
      Json val;
      if (!value(&val)) return false;
      obj.emplace(std::move(key), std::move(val));
      skip_ws();
      if (pos_ >= s_.size()) return false;
      if (s_[pos_] == ',') { pos_++; continue; }
      if (s_[pos_] == '}') { pos_++; break; }
      return false;
    }
    out->v = std::move(obj);
    return true;
  }
  bool array(Json* out) {
    pos_++;  // '['
    JsonArray arr;
    skip_ws();
    if (pos_ < s_.size() && s_[pos_] == ']') { pos_++; out->v = std::move(arr); return true; }
    while (true) {
      Json val;
      if (!value(&val)) return false;
      arr.push_back(std::move(val));
      skip_ws();
      if (pos_ >= s_.size()) return false;
      if (s_[pos_] == ',') { pos_++; continue; }
      if (s_[pos_] == ']') { pos_++; break; }
      return false;
    }
    out->v = std::move(arr);
    return true;
  }
  bool string_(std::string* out) {
    if (pos_ >= s_.size() || s_[pos_] != '"') return false;
    pos_++;
    out->clear();
    while (pos_ < s_.size()) {
      char c = s_[pos_++];
      if (c == '"') return true;
      if (c == '\\') {
        if (pos_ >= s_.size()) return false;
        char e = s_[pos_++];
        switch (e) {
          case '"': out->push_back('"'); break;
          case '\\': out->push_back('\\'); break;
          case '/': out->push_back('/'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'n': out->push_back('\n'); break;
          case 'r': out->push_back('\r'); break;
          case 't': out->push_back('\t'); break;
          case 'u': {
            if (pos_ + 4 > s_.size()) return false;
            unsigned cp = std::stoul(s_.substr(pos_, 4), nullptr, 16);
            pos_ += 4;
            // UTF-8 encode (surrogate pairs for completeness).
            if (cp >= 0xD800 && cp <= 0xDBFF && pos_ + 6 <= s_.size() &&
                s_[pos_] == '\\' && s_[pos_ + 1] == 'u') {
              unsigned lo = std::stoul(s_.substr(pos_ + 2, 4), nullptr, 16);
              pos_ += 6;
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            if (cp < 0x80) {
              out->push_back(static_cast<char>(cp));
            } else if (cp < 0x800) {
              out->push_back(static_cast<char>(0xC0 | (cp >> 6)));
              out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
            } else if (cp < 0x10000) {
              out->push_back(static_cast<char>(0xE0 | (cp >> 12)));
              out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
            } else {
              out->push_back(static_cast<char>(0xF0 | (cp >> 18)));
              out->push_back(static_cast<char>(0x80 | ((cp >> 12) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | ((cp >> 6) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | (cp & 0x3F)));
            }
            break;
          }
          default: return false;
        }
      } else {
        out->push_back(c);
      }
    }
    return false;
  }
  bool number(Json* out) {
    size_t start = pos_;
    if (pos_ < s_.size() && (s_[pos_] == '-' || s_[pos_] == '+')) pos_++;
    while (pos_ < s_.size() &&
           (isdigit(static_cast<unsigned char>(s_[pos_])) || s_[pos_] == '.' ||
            s_[pos_] == 'e' || s_[pos_] == 'E' || s_[pos_] == '-' ||
            s_[pos_] == '+'))
      pos_++;
    if (pos_ == start) return false;
    try {
      out->v = std::stod(s_.substr(start, pos_ - start));
    } catch (...) {
      return false;
    }
    return true;
  }
};

void json_escape(const std::string& s, std::string* out) {
  out->push_back('"');
  for (unsigned char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\b': *out += "\\b"; break;
      case '\f': *out += "\\f"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(static_cast<char>(c));
        }
    }
  }
  out->push_back('"');
}

// ---------------------------------------------------------------- store ----
using Clock = std::chrono::steady_clock;

struct Entry {
  std::string value;
  std::optional<Clock::time_point> expire_at;
};

struct Watch {
  int fd;
  double client_watch_id;
  std::string prefix;
};

struct Conn {
  int fd;
  std::string rbuf;
  std::string wbuf;
  bool authed = true;
  bool closing = false;
};

class Server {
 public:
  Server(int port, std::string username, std::string password)
      : username_(std::move(username)), password_(std::move(password)) {
    listen_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
        0) {
      perror("bind");
      exit(1);
    }
    listen(listen_fd_, 128);
    socklen_t len = sizeof(addr);
    getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    fprintf(stderr, "coordination server (native) listening on :%d\n",
            ntohs(addr.sin_port));
    fflush(stderr);
  }

  [[noreturn]] void run() {
    while (true) {
      std::vector<pollfd> pfds;
      pfds.push_back({listen_fd_, POLLIN, 0});
      for (auto& [fd, c] : conns_) {
        short ev = POLLIN;
        if (!c->wbuf.empty()) ev |= POLLOUT;
        pfds.push_back({fd, ev, 0});
      }
      poll(pfds.data(), pfds.size(), 50);
      if (pfds[0].revents & POLLIN) accept_conn();
      std::vector<int> dead;
      for (size_t i = 1; i < pfds.size(); i++) {
        auto it = conns_.find(pfds[i].fd);
        if (it == conns_.end()) continue;
        Conn* c = it->second.get();
        if (pfds[i].revents & (POLLERR | POLLHUP)) {
          dead.push_back(c->fd);
          continue;
        }
        if (pfds[i].revents & POLLIN) {
          if (!read_conn(c)) dead.push_back(c->fd);
        }
        if (pfds[i].revents & POLLOUT) {
          if (!flush_conn(c)) dead.push_back(c->fd);
        }
      }
      for (int fd : dead) close_conn(fd);
      sweep_expired();
    }
  }

 private:
  int listen_fd_;
  std::string username_, password_;
  std::unordered_map<int, std::unique_ptr<Conn>> conns_;
  std::map<std::string, Entry> data_;  // ordered: efficient prefix scans
  std::vector<Watch> watches_;

  void accept_conn() {
    int fd = accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) return;
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    auto conn = std::make_unique<Conn>();
    conn->fd = fd;
    conn->authed = username_.empty();
    conns_[fd] = std::move(conn);
  }

  void close_conn(int fd) {
    watches_.erase(
        std::remove_if(watches_.begin(), watches_.end(),
                       [fd](const Watch& w) { return w.fd == fd; }),
        watches_.end());
    conns_.erase(fd);
    close(fd);
  }

  bool read_conn(Conn* c) {
    char buf[65536];
    while (true) {
      ssize_t n = recv(c->fd, buf, sizeof(buf), MSG_DONTWAIT);
      if (n > 0) {
        c->rbuf.append(buf, static_cast<size_t>(n));
        if (c->rbuf.size() > (64u << 20)) return false;  // runaway line
        continue;
      }
      if (n == 0) return false;
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      return false;
    }
    size_t start = 0;
    while (true) {
      size_t nl = c->rbuf.find('\n', start);
      if (nl == std::string::npos) break;
      std::string line = c->rbuf.substr(start, nl - start);
      start = nl + 1;
      if (!line.empty() && line.back() == '\r') line.pop_back();
      if (!line.empty()) handle_line(c, line);
    }
    c->rbuf.erase(0, start);
    return flush_conn(c);
  }

  bool flush_conn(Conn* c) {
    while (!c->wbuf.empty()) {
      ssize_t n =
          send(c->fd, c->wbuf.data(), c->wbuf.size(), MSG_DONTWAIT | MSG_NOSIGNAL);
      if (n > 0) {
        c->wbuf.erase(0, static_cast<size_t>(n));
        continue;
      }
      if (errno == EAGAIN || errno == EWOULDBLOCK) return true;
      return false;
    }
    return true;
  }

  void send_json(Conn* c, const std::string& body) {
    c->wbuf += body;
    c->wbuf.push_back('\n');
  }

  static std::string ok_response(const Json* id, bool ok,
                                 const std::string& extra = "") {
    std::string out = "{";
    if (id && !id->is_null()) {
      out += "\"id\": " + std::to_string(static_cast<long long>(id->num())) +
             ", ";
    }
    out += std::string("\"ok\": ") + (ok ? "true" : "false");
    if (!extra.empty()) out += ", " + extra;
    out += "}";
    return out;
  }

  void emit_event(const std::string& type, const std::string& key,
                  const std::string& value) {
    for (const Watch& w : watches_) {
      if (key.compare(0, w.prefix.size(), w.prefix) != 0) continue;
      auto it = conns_.find(w.fd);
      if (it == conns_.end()) continue;
      std::string msg = "{\"event\": \"watch\", \"watch_id\": " +
                        std::to_string(static_cast<long long>(w.client_watch_id)) +
                        ", \"prefix\": ";
      json_escape(w.prefix, &msg);
      msg += ", \"events\": [{\"type\": \"" + type + "\", \"key\": ";
      json_escape(key, &msg);
      msg += ", \"value\": ";
      json_escape(value, &msg);
      msg += "}]}";
      send_json(it->second.get(), msg);
    }
  }

  void sweep_expired() {
    auto now = Clock::now();
    std::vector<std::string> expired;
    for (const auto& [k, e] : data_) {
      if (e.expire_at && *e.expire_at <= now) expired.push_back(k);
    }
    for (const std::string& k : expired) {
      data_.erase(k);
      emit_event("DELETE", k, "");
    }
    // Push any queued watch events.
    for (auto& [fd, c] : conns_) flush_conn(c.get());
  }

  void handle_line(Conn* c, const std::string& line) {
    Json req;
    JsonParser parser(line);
    if (!parser.parse(&req) || !req.is_obj()) {
      send_json(c, "{\"ok\": false, \"error\": \"bad json\"}");
      return;
    }
    const Json* id = req.find("id");
    std::string op = req.get_str("op");

    if (op == "auth") {
      c->authed = username_.empty() ||
                  (req.get_str("username") == username_ &&
                   req.get_str("password") == password_);
      send_json(c, ok_response(id, c->authed));
      return;
    }
    if (!c->authed) {
      send_json(c, ok_response(id, false, "\"error\": \"unauthenticated\""));
      return;
    }

    if (op == "ping") {
      send_json(c, ok_response(id, true));
    } else if (op == "put") {
      std::string key = req.get_str("key");
      std::string value = req.get_str("value");
      bool create_only = req.get_bool("create_only");
      auto ttl = req.get_num("ttl");
      auto it = data_.find(key);
      if (create_only && it != data_.end()) {
        bool expired = it->second.expire_at &&
                       *it->second.expire_at <= Clock::now();
        if (!expired) {
          send_json(c, ok_response(id, false));
          return;
        }
      }
      Entry e;
      e.value = value;
      if (ttl && *ttl > 0)
        e.expire_at = Clock::now() + std::chrono::microseconds(
                                         static_cast<int64_t>(*ttl * 1e6));
      data_[key] = std::move(e);
      emit_event("PUT", key, value);
      send_json(c, ok_response(id, true));
    } else if (op == "refresh") {
      std::string key = req.get_str("key");
      auto ttl = req.get_num("ttl");
      auto it = data_.find(key);
      bool ok = false;
      if (it != data_.end() && it->second.expire_at && ttl) {
        it->second.expire_at =
            Clock::now() +
            std::chrono::microseconds(static_cast<int64_t>(*ttl * 1e6));
        ok = true;
      }
      send_json(c, ok_response(id, ok));
    } else if (op == "get") {
      auto it = data_.find(req.get_str("key"));
      std::string extra = "\"value\": ";
      if (it == data_.end()) {
        extra += "null";
      } else {
        json_escape(it->second.value, &extra);
      }
      send_json(c, ok_response(id, true, extra));
    } else if (op == "get_prefix") {
      std::string prefix = req.get_str("prefix");
      std::string extra = "\"kvs\": {";
      bool first = true;
      for (auto it = data_.lower_bound(prefix);
           it != data_.end() &&
           it->first.compare(0, prefix.size(), prefix) == 0;
           ++it) {
        if (!first) extra += ", ";
        first = false;
        json_escape(it->first, &extra);
        extra += ": ";
        json_escape(it->second.value, &extra);
      }
      extra += "}";
      send_json(c, ok_response(id, true, extra));
    } else if (op == "rm") {
      std::string key = req.get_str("key");
      bool ok = data_.erase(key) > 0;
      if (ok) emit_event("DELETE", key, "");
      send_json(c, ok_response(id, ok));
    } else if (op == "rm_prefix") {
      std::string prefix = req.get_str("prefix");
      const Json* guard = req.find("guard_key");
      int count = 0;
      bool guard_ok = !guard || guard->is_null() ||
                      data_.count(guard->str()) > 0;
      if (guard_ok) {
        std::vector<std::string> keys;
        for (auto it = data_.lower_bound(prefix);
             it != data_.end() &&
             it->first.compare(0, prefix.size(), prefix) == 0;
             ++it)
          keys.push_back(it->first);
        for (const std::string& k : keys) {
          data_.erase(k);
          emit_event("DELETE", k, "");
          count++;
        }
      }
      send_json(c, ok_response(id, true,
                               "\"count\": " + std::to_string(count)));
    } else if (op == "bulk_set") {
      const Json* kvs = req.find("kvs");
      if (kvs && kvs->is_obj()) {
        for (const auto& [k, v] : kvs->obj()) {
          data_[k] = Entry{v.is_str() ? v.str() : "", std::nullopt};
          emit_event("PUT", k, v.is_str() ? v.str() : "");
        }
      }
      send_json(c, ok_response(id, true));
    } else if (op == "bulk_rm") {
      const Json* keys = req.find("keys");
      int count = 0;
      if (keys && keys->is_arr()) {
        for (const Json& k : keys->arr()) {
          if (k.is_str() && data_.erase(k.str()) > 0) {
            emit_event("DELETE", k.str(), "");
            count++;
          }
        }
      }
      send_json(c, ok_response(id, true,
                               "\"count\": " + std::to_string(count)));
    } else if (op == "watch") {
      auto wid = req.get_num("watch_id");
      watches_.push_back(
          {c->fd, wid ? *wid : 0.0, req.get_str("prefix")});
      send_json(c, ok_response(id, true));
    } else if (op == "unwatch") {
      auto wid = req.get_num("watch_id");
      int fd = c->fd;
      watches_.erase(std::remove_if(watches_.begin(), watches_.end(),
                                    [&](const Watch& w) {
                                      return w.fd == fd && wid &&
                                             w.client_watch_id == *wid;
                                    }),
                     watches_.end());
      send_json(c, ok_response(id, true));
    } else {
      send_json(c, ok_response(id, false, "\"error\": \"unknown op\""));
    }
  }
};

int main(int argc, char** argv) {
  int port = 2379;
  std::string username, password;
  for (int i = 1; i < argc - 1; i++) {
    std::string arg = argv[i];
    if (arg == "--port") port = atoi(argv[++i]);
    else if (arg == "--username") username = argv[++i];
    else if (arg == "--password") password = argv[++i];
  }
  Server server(port, username, password);
  server.run();
  return 0;
}
