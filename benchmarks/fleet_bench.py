"""Fleet-scale service-plane bench: masters-N scaling + native A/B.

ISSUE 19's acceptance harness. One run spawns a deployment-shaped fleet
— coordination server, N masters, M fake engines, and an OPEN-LOOP
driver, each its own OS process — and measures two things:

1. **masters-{1,2,4} scaling curve**: aggregate served rps as active
   frontends are added, with per-master CPU attribution
   (``/admin/hotpath`` route/ingest/stream buckets) and continuous-
   profiler composition (``/admin/profile``) alongside, so the curve is
   explainable, not just a number.
2. **native hot-path A/B** (masters=1): the same drive with
   ``XLLM_NATIVE`` on vs off — the per-request route+stream CPU cut
   libhotcore.so (csrc/hotcore.c) buys on the LOADFRAME/SSE/rendezvous/
   tokenizer frames.

CPU isolation: the planner assigns DISJOINT CPU sets — one exclusive
core per master, one set for the engines+coordination, the remainder to
the driver — and pins each process with ``sched_setaffinity`` so the
driver can never steal master cycles mid-window. When the box is too
small (fewer than masters+2 cores) the bench DEGRADES GRACEFULLY to
``phased-projection`` mode with a prominent warning: every process
still runs, but each master is driven alone in its own exclusive
measurement window and the aggregate is the SUM of per-master rates —
an upper-bound projection of the pinned-concurrent number, labeled as
such in the artifact (``"mode"``).

Workload: the PR-13 diurnal/burst open-loop generator
(master_hotpath_bench._due_offsets) over a simulated
millions-of-users population — a ``--streams`` pool (default 200k) of
DISTINCT prompt streams across three tenant classes (interactive /
agent / batch: different prompt lengths and token budgets). Every
request samples a stream id, so prompts are unique (zero prefix
overlap) and heterogeneous, and the artifact records both the
population size and how many distinct streams the drive actually hit.

    python benchmarks/fleet_bench.py --out BENCH_fleet_r20.json

The artifact's top-level ``headline`` block is auto-tracked by
scripts/bench_trend.py (family ``fleet``): aggregate rps regresses
downward, the native speedup regresses downward, and the native-on
route+stream ``_us``-per-request cost regresses upward.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import statistics
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import requests

from benchmarks.master_hotpath_bench import (  # noqa: E402
    _admin_cpu,
    _due_offsets,
    _proc_cpu_s,
    free_port,
    percentile,
)

# Heterogeneous tenant mix: share of the stream population, prompt bytes
# (== token_ids length through the byte-level tokenizer) and the token
# budget. Long-context serving shapes: interactive chat dominates
# volume, agent tenants carry tool-call transcripts, batch tenants carry
# RAG/document contexts — the frame sizes the route/stream hot path
# actually moves at fleet scale. ``--prompt-scale`` shrinks the mix
# proportionally for smoke runs.
TENANTS = (
    {"name": "interactive", "share": 0.60, "prompt_chars": 2048,
     "max_tokens": 8},
    {"name": "agent", "share": 0.25, "prompt_chars": 8192,
     "max_tokens": 16},
    {"name": "batch", "share": 0.15, "prompt_chars": 24576,
     "max_tokens": 12},
)


def _warn(msg: str) -> None:
    print(f"[fleet_bench] WARNING: {msg}", file=sys.stderr, flush=True)


def _info(msg: str) -> None:
    print(f"[fleet_bench] {msg}", file=sys.stderr, flush=True)


# ------------------------------------------------------------ stream population
class StreamPopulation:
    """Deterministic sampler over ``n_streams`` distinct prompt streams.

    Stream k's identity prefix changes block 0 of the prompt, so every
    stream is a distinct prefix chain (CAR's worst case, and exactly the
    millions-of-users shape: no two users share a cache line). Tenant
    class is a deterministic function of the stream id, so reruns and
    the native A/B legs see the SAME offered mix."""

    def __init__(self, n_streams: int, seed: int = 0x20,
                 prompt_scale: float = 1.0):
        self.n_streams = max(1, n_streams)
        self.seed = seed
        self.prompt_scale = max(0.01, prompt_scale)
        self._hit: set = set()
        # Cumulative tenant shares for the id->class map.
        acc, self._cut = 0.0, []
        for t in TENANTS:
            acc += t["share"]
            self._cut.append((acc, t))

    def _stream_id(self, k: int) -> int:
        # SplitMix64-style scramble: uniform over the population without
        # materializing it.
        z = (k + self.seed) * 0x9E3779B97F4A7C15 % (1 << 64)
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) % (1 << 64)
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) % (1 << 64)
        return (z ^ (z >> 31)) % self.n_streams

    def request_for(self, k: int) -> dict:
        sid = self._stream_id(k)
        self._hit.add(sid)
        frac = (sid + 0.5) / self.n_streams
        tenant = next(t for cut, t in self._cut if frac <= cut)
        chars = max(64, int(tenant["prompt_chars"] * self.prompt_scale))
        head = f"{tenant['name']}:{sid:08d}|"
        body = "fleet load " * (chars // 11 + 1)
        return {
            "tenant": tenant["name"],
            "prompt": (head + body)[:chars],
            "max_tokens": tenant["max_tokens"],
        }

    def stats(self) -> dict:
        return {"population": self.n_streams,
                "distinct_streams_hit": len(self._hit),
                "tenants": [{"name": t["name"], "share": t["share"],
                             "prompt_chars": t["prompt_chars"],
                             "max_tokens": t["max_tokens"]}
                            for t in TENANTS]}


# ---------------------------------------------------------------- CPU planning
def plan_cpu_sets(n_masters: int) -> "tuple[dict | None, str]":
    """Disjoint CPU sets: one exclusive core per master, one for the
    engine+coordination side, the rest for the driver. Returns (plan,
    reason); plan is None when the box cannot isolate (the caller then
    falls back to phased-projection mode)."""
    try:
        avail = sorted(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        return None, "sched_getaffinity unavailable on this platform"
    need = n_masters + 2
    if len(avail) < need:
        return None, (f"{len(avail)} usable core(s) < {need} needed for "
                      f"{n_masters} exclusive master core(s) + engines + "
                      f"driver")
    plan = {f"master{i}": {avail[i]} for i in range(n_masters)}
    rest = avail[n_masters:]
    # Engines + coord share one set; the driver gets the remainder (at
    # least one core each by the `need` check above).
    split = max(1, len(rest) // 2)
    plan["engines"] = set(rest[:split])
    plan["driver"] = set(rest[split:]) or set(rest[:split])
    return plan, f"{len(avail)} cores, exclusive per-master sets"


def pin(pid: int, cpuset: "set[int]", what: str) -> bool:
    try:
        os.sched_setaffinity(pid, cpuset)
        return True
    except (AttributeError, OSError) as e:
        _warn(f"could not pin {what} to {sorted(cpuset)}: {e}")
        return False


# ------------------------------------------------------------------ the driver
#
# The driver is a SEPARATE PROCESS (this file re-executed with --drive):
# process isolation keeps client-side JSON/HTTP work off the masters'
# cores even when pinning is unavailable, and gives the planner one pid
# to pin. The parent passes the window spec on the command line and
# reads one JSON report from stdout.

def drive_window(spec: dict) -> dict:
    """Open-loop drive of one measurement window (runs in the driver
    process). Latency is measured from each request's DUE slot
    (coordinated omission counted, not hidden)."""
    bases = spec["bases"]
    n = spec["requests"]
    pop = StreamPopulation(spec["streams"], seed=spec.get("seed", 0x20),
                           prompt_scale=spec.get("prompt_scale", 1.0))
    sched_args = argparse.Namespace(
        rps=spec["rps"], traffic=spec["traffic"],
        diurnal_amp=spec.get("diurnal_amp", 0.6),
        diurnal_period=spec.get("diurnal_period", 12.0),
        burst_every=spec.get("burst_every", 10.0),
        burst_len=spec.get("burst_len", 2.0),
        burst_mult=spec.get("burst_mult", 4.0))
    offsets = _due_offsets(n, sched_args)
    reqs = [pop.request_for(k) for k in range(n)]

    if spec.get("warmup", True):
        # Driver-side warmup (connection pools + lazy paths). The parent
        # normally pre-warms the masters itself BEFORE snapshotting the
        # CPU attribution, so cold-path costs stay out of the A/B; this
        # is the standalone-driver fallback.
        for b in bases:
            for w in range(3):
                try:
                    requests.post(b + "/v1/completions", json={
                        "model": "fake-model", "prompt": reqs[w]["prompt"],
                        "max_tokens": 2, "stream": True},
                        timeout=30).close()
                except requests.RequestException:
                    pass

    ttfts: list = []
    e2es: list = []
    per_tenant: dict = {t["name"]: [] for t in TENANTS}
    errors = [0]
    lock = threading.Lock()
    # FIFO dispatch in due order: popping from the tail would have
    # every worker sleep to the LAST due slot first and then serve the
    # early slots arbitrarily late — due-slot latency would measure the
    # dispatch order, not the service.
    next_k = [0]
    pace_start = time.perf_counter() + 0.05

    def worker(wbase: str) -> None:
        session = requests.Session()
        while True:
            with lock:
                if next_k[0] >= n:
                    return
                k = next_k[0]
                next_k[0] += 1
            due = pace_start + offsets[k]
            now = time.perf_counter()
            if due > now:
                time.sleep(due - now)
            req = reqs[k]
            try:
                r = session.post(wbase + "/v1/completions", json={
                    "model": "fake-model", "prompt": req["prompt"],
                    "max_tokens": req["max_tokens"], "stream": True},
                    stream=True, timeout=60)
                ttft = None
                for line in r.iter_lines():
                    if not line.startswith(b"data: "):
                        continue
                    if ttft is None:
                        ttft = time.perf_counter() - due
                    if line == b"data: [DONE]":
                        break
                e2e = time.perf_counter() - due
                if ttft is None:
                    raise RuntimeError("stream produced no deltas")
                with lock:
                    ttfts.append(ttft * 1000)
                    e2es.append(e2e * 1000)
                    per_tenant[req["tenant"]].append(ttft * 1000)
            except Exception:  # noqa: BLE001 — counted, not fatal
                with lock:
                    errors[0] += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker,
                                args=(bases[i % len(bases)],))
               for i in range(spec["concurrency"])]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    served = len(e2es)
    return {
        "requests": n,
        "served": served,
        "errors": errors[0],
        "wall_s": round(wall, 2),
        "req_per_s": round(served / wall, 2) if wall else 0.0,
        "ttft_ms": {"p50": round(percentile(ttfts, 50), 2),
                    "p90": round(percentile(ttfts, 90), 2),
                    "p99": round(percentile(ttfts, 99), 2),
                    "mean": round(statistics.mean(ttfts), 2)
                    if ttfts else 0.0},
        "e2e_ms": {"p50": round(percentile(e2es, 50), 2),
                   "p99": round(percentile(e2es, 99), 2)},
        "ttft_p50_ms_by_tenant": {
            t: round(percentile(v, 50), 2) for t, v in per_tenant.items()},
        "streams": pop.stats(),
    }


def _spawn_driver(spec: dict, cpuset: "set[int] | None") -> dict:
    """Run one drive window in a separate driver process."""
    p = subprocess.Popen(
        [sys.executable, str(Path(__file__).resolve()), "--drive",
         json.dumps(spec)],
        stdout=subprocess.PIPE, stderr=sys.stderr, cwd=str(REPO),
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if cpuset:
        pin(p.pid, cpuset, "driver")
    out, _ = p.communicate(timeout=600)
    if p.returncode != 0:
        raise RuntimeError(f"driver window failed rc={p.returncode}")
    return json.loads(out)


# ------------------------------------------------------------------- the fleet
class Fleet:
    """coordination + N masters + M engines, each a separate process."""

    def __init__(self, n_masters: int, n_engines: int,
                 native_on: bool, reply_chars: int = 32,
                 chunk_size: int = 32,
                 master_extra: "list[str] | None" = None,
                 engine_specs: "list[list[str]] | None" = None):
        self.n_masters = n_masters
        self.n_engines = n_engines
        self.native_on = native_on
        self.reply_chars = reply_chars
        self.chunk_size = chunk_size
        # Topology A/B leg hooks: extra master flags (e.g.
        # --topology-tradeoff) and per-engine extra flags (role + slice
        # coordinates). engine_specs engines get explicit ports so their
        # /admin/topology endpoints are scrapeable (engine_bases).
        self.master_extra = list(master_extra or ())
        self.engine_specs = engine_specs
        self.engine_bases: "list[str]" = []
        self.procs: "list[subprocess.Popen]" = []
        self.names: "list[str]" = []
        self.bases: "list[str]" = []
        self.pinned = False
        # Per-process affinity verdicts (machine-readable isolation
        # evidence for the artifact): name -> {cpuset, pinned}.
        self.pin_verdicts: "dict[str, dict]" = {}

    def _spawn(self, name: str, cmd: "list[str]", env: dict) -> None:
        logdir = Path(os.environ.get("XLLM_BENCH_LOGDIR", "/tmp"))
        log = open(logdir / f"fleet_bench_{name}.log", "w")
        p = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                             cwd=str(REPO), env=env)
        self.procs.append(p)
        self.names.append(name)

    def start(self, plan: "dict | None") -> "Fleet":
        coord_port = free_port()
        http_ports = [free_port() for _ in range(self.n_masters)]
        rpc_ports = [free_port() for _ in range(self.n_masters)]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["XLLM_NATIVE"] = "1" if self.native_on else "0"
        self._spawn("coord", [sys.executable, "-m",
                              "xllm_service_tpu.coordination.server",
                              "--port", str(coord_port)], env)
        time.sleep(0.3)
        for i in range(self.n_masters):
            self._spawn(f"master{i}",
                        [sys.executable, "-m", "xllm_service_tpu.master",
                         "--coordination-addr", f"127.0.0.1:{coord_port}",
                         "--host", "127.0.0.1",
                         "--http-port", str(http_ports[i]),
                         "--rpc-port", str(rpc_ports[i]),
                         "--load-balance-policy", "RR",
                         "--telemetry-ingest-mode", "shard"]
                        + self.master_extra, env)
            if i == 0 and self.n_masters > 1:
                time.sleep(0.5)   # deterministic election winner
        n_engines = len(self.engine_specs) \
            if self.engine_specs is not None else self.n_engines
        for i in range(n_engines):
            cmd = [sys.executable,
                   str(REPO / "examples" / "run_fake_engine.py"),
                   "--coordination-addr", f"127.0.0.1:{coord_port}",
                   "--reply", "x" * self.reply_chars,
                   "--chunk-size", str(self.chunk_size),
                   "--delay", "0",
                   "--telemetry-mode", "mux"]
            if self.engine_specs is not None:
                eport = free_port()
                cmd += ["--host", "127.0.0.1", "--port", str(eport)]
                cmd += self.engine_specs[i]
                self.engine_bases.append(f"http://127.0.0.1:{eport}")
            self._spawn(f"engine{i}", cmd, env)
        if plan:
            ok = True
            for name, p in zip(self.names, self.procs):
                cpuset = plan.get(name) or plan["engines"]
                pinned = pin(p.pid, cpuset, name)
                self.pin_verdicts[name] = {"cpuset": sorted(cpuset),
                                           "pinned": pinned}
                ok = pinned and ok
            self.pinned = ok
        else:
            self.pin_verdicts = {n: {"cpuset": [], "pinned": False}
                                 for n in self.names}
        self.bases = [f"http://127.0.0.1:{p}" for p in http_ports]
        return self

    def wait_ready(self, timeout: float = 90.0) -> None:
        deadline = time.monotonic() + timeout
        ready: set = set()
        while time.monotonic() < deadline:
            for name, p in zip(self.names, self.procs):
                if p.poll() is not None:
                    logdir = os.environ.get("XLLM_BENCH_LOGDIR", "/tmp")
                    raise RuntimeError(
                        f"{name} died rc={p.returncode} — see "
                        f"{logdir}/fleet_bench_{name}.log")
            for base in self.bases:
                if base in ready:
                    continue
                try:
                    r = requests.post(base + "/v1/completions", json={
                        "model": "fake-model", "prompt": "ready?",
                        "max_tokens": 2}, timeout=10)
                    if r.status_code == 200:
                        ready.add(base)
                except requests.RequestException:
                    pass
            if len(ready) == len(self.bases):
                return
            time.sleep(0.25)
        raise RuntimeError(f"fleet never became ready "
                           f"({len(ready)}/{len(self.bases)} frontends)")

    def master_pids(self) -> "dict[str, int]":
        return {n: p.pid for n, p in zip(self.names, self.procs)
                if n.startswith("master")}

    def native_status(self) -> "list[dict]":
        """Per-master ``native_path_active{component}`` gauges (scraped
        from /metrics — the degraded-process signal the fleet dashboards
        key on)."""
        out = []
        for base in self.bases:
            row: dict = {}
            try:
                r = requests.get(base + "/metrics", timeout=5)
                for line in r.text.splitlines():
                    if not line.startswith("native_path_active{"):
                        continue
                    label, _, val = line.rpartition(" ")
                    comp = label.split('component="', 1)[-1].split('"')[0]
                    try:
                        row[comp] = float(val)
                    except ValueError:
                        pass
            except requests.RequestException:
                pass
            out.append(row)
        return out

    def profile_composition(self, top: int = 12) -> "list[dict]":
        """Per-master continuous-profiler top-N (the 'why' behind the
        CPU numbers)."""
        out = []
        for base in self.bases:
            try:
                r = requests.get(base + "/admin/profile",
                                 params={"top": top}, timeout=5)
                payload = r.json() if r.status_code == 200 else {}
            except (requests.RequestException, ValueError):
                payload = {}
            # The artifact keeps the composition (hottest frames), not
            # the full stack table — flamegraph-sized payloads belong in
            # the live endpoint, not a checked-in JSON.
            out.append({"samples": payload.get("samples", 0),
                        "top_frames": payload.get("top_frames", [])[:top]})
        return out

    def stop(self) -> None:
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def _cpu_isolation(mode: str, reason: str, fleet: "Fleet") -> dict:
    """Machine-readable isolation record: how many cores the box gave
    us, which measurement mode that forced, and the per-process affinity
    verdict — so a trend diff can tell a code regression from a
    projection artifact produced by a smaller box."""
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = 0
    return {
        "cores_available": cores,
        "mode": mode,
        "mode_reason": reason,
        "all_pinned": fleet.pinned,
        "per_process": fleet.pin_verdicts,
    }


# ------------------------------------------------------------------- one leg
def run_leg(n_masters: int, args, native_on: bool = True,
            rps: float = None, purpose: str = "scale") -> dict:
    """One point on the scaling curve: spawn the fleet, drive it, read
    the per-master attribution, tear it down.

    `rps` overrides the open-loop rate for this leg: the scaling legs
    drive above capacity (the window measures capacity), while the
    native A/B legs drive at the stable `--ab-rps` point — per-request
    CPU measured under queueing collapse is dominated by cache-cold
    preemption noise on both legs, which buries the code-path delta the
    A/B exists to isolate."""
    plan, plan_reason = plan_cpu_sets(n_masters)
    mode = "pinned-concurrent" if plan else "phased-projection"
    if plan is None:
        _warn(f"CPU isolation unavailable ({plan_reason}); falling back "
              f"to PHASED-PROJECTION mode — each master is driven alone "
              f"in an exclusive window and aggregate rps is the sum of "
              f"per-master rates (an upper-bound projection, labeled in "
              f"the artifact)")
    else:
        _info(f"CPU plan: {plan_reason}: "
              f"{ {k: sorted(v) for k, v in plan.items()} }")
    fleet = Fleet(n_masters, args.engines, native_on,
                  reply_chars=args.reply_chars,
                  chunk_size=args.chunk_size).start(plan)
    try:
        fleet.wait_ready()
        # Pre-warm every frontend across the tenant shapes BEFORE the
        # attribution snapshot: first-request costs (lazy imports, .so
        # load, session setup) must not pollute the native A/B.
        warm_pop = StreamPopulation(args.streams, seed=0x7777,
                                    prompt_scale=args.prompt_scale)
        for base in fleet.bases:
            for w in range(8):
                req = warm_pop.request_for(w)
                try:
                    requests.post(base + "/v1/completions", json={
                        "model": "fake-model", "prompt": req["prompt"],
                        "max_tokens": req["max_tokens"], "stream": True},
                        timeout=30).close()
                except requests.RequestException:
                    pass
        pids = fleet.master_pids()
        cpu0 = {n: _proc_cpu_s(p) for n, p in pids.items()}
        attr0 = {f"master{i}": _admin_cpu(b)
                 for i, b in enumerate(fleet.bases)}
        leg_rps = rps if rps is not None else args.rps
        spec_base = {
            "requests": args.requests, "concurrency": args.concurrency,
            "rps": leg_rps, "traffic": args.traffic,
            "streams": args.streams,
            "prompt_scale": args.prompt_scale,
            "diurnal_amp": args.diurnal_amp,
            "diurnal_period": args.diurnal_period,
            "burst_every": args.burst_every, "burst_len": args.burst_len,
            "burst_mult": args.burst_mult,
            "warmup": False,   # the parent pre-warmed before snapshotting
        }
        driver_set = plan["driver"] if plan else None
        if mode == "pinned-concurrent":
            # True concurrent drive: workers spread across frontends,
            # masters on exclusive cores.
            spec = dict(spec_base, bases=fleet.bases, seed=0x20)
            window = _spawn_driver(spec, driver_set)
            windows = [window]
            agg_rps = window["req_per_s"]
        else:
            # Phased projection: each master alone in its own window
            # (the 1-core degraded mode). Different seed per window so
            # the population sampling doesn't repeat streams.
            windows = []
            for i, base in enumerate(fleet.bases):
                _info(f"phased window {i + 1}/{n_masters} -> {base}")
                spec = dict(spec_base, bases=[base], seed=0x20 + i,
                            requests=max(1,
                                         args.requests // n_masters))
                windows.append(_spawn_driver(spec, None))
            agg_rps = round(sum(w["req_per_s"] for w in windows), 2)
        cpu = {n: round(_proc_cpu_s(p) - cpu0[n], 2)
               for n, p in pids.items()}
        served = max(1, sum(w["served"] for w in windows))
        attr: dict = {}
        for i, base in enumerate(fleet.bases):
            name = f"master{i}"
            after = _admin_cpu(base)
            buckets = {}
            for cat, row in (after.get("cpu") or {}).items():
                before = ((attr0.get(name) or {}).get("cpu") or {}) \
                    .get(cat, {})
                buckets[cat] = {
                    "cpu_s": round(row.get("cpu_s", 0.0)
                                   - before.get("cpu_s", 0.0), 4),
                    "n": row.get("n", 0) - before.get("n", 0),
                }
            attr[name] = buckets
        route_s = sum(b.get("route", {}).get("cpu_s", 0.0)
                      for b in attr.values())
        stream_s = sum(b.get("stream", {}).get("cpu_s", 0.0)
                       for b in attr.values())
        leg = {
            "masters": n_masters,
            "engines": args.engines,
            "native_on": native_on,
            "purpose": purpose,
            "offered_rps": leg_rps,
            "mode": mode,
            "mode_reason": plan_reason,
            "pinned": fleet.pinned,
            "cpu_isolation": _cpu_isolation(mode, plan_reason, fleet),
            "agg_req_per_s": agg_rps,
            "served": served,
            "errors": sum(w["errors"] for w in windows),
            "windows": windows,
            "master_cpu_s_during_drive": cpu,
            "master_cpu_attr": attr,
            "route_cpu_us_per_req": round(route_s * 1e6 / served, 2),
            "stream_cpu_us_per_req": round(stream_s * 1e6 / served, 2),
            "route_stream_cpu_us_per_req": round(
                (route_s + stream_s) * 1e6 / served, 2),
            "native_status_per_master": fleet.native_status(),
            "profile_per_master": fleet.profile_composition(),
        }
        return leg
    finally:
        fleet.stop()


# --------------------------------------------------------- topology A/B legs
#
# ISSUE 20's proof: the same 2-slice fleet (1 PREFILL + 1 DECODE on
# slice-a, 2 DECODE on slice-b) driven twice — topology-aware routing
# (--topology-tradeoff > 0) vs flat (0) — with the DCN link throttled so
# a cross-slice KV handoff costs real wall time. The fake engines model
# the handoff (kv-handoff-bytes-per-token x prompt tokens over the
# link's bytes/s) as a sleep before the first delta, so client TTFT
# feels it exactly like a real pull-mode transfer. Evidence per leg:
# client TTFT p50/p95, the master's pair-link census
# (/admin/hotpath -> telemetry.topology.pair_links), and per-engine
# modeled handoff p50/p95 by link class (/admin/topology).

TOPO_ENGINE_SPECS = (
    ["--type", "PREFILL", "--slice-id", "slice-a", "--topo-host", "host-a0"],
    ["--type", "DECODE", "--slice-id", "slice-a", "--topo-host", "host-a1"],
    ["--type", "DECODE", "--slice-id", "slice-b", "--topo-host", "host-b0"],
    ["--type", "DECODE", "--slice-id", "slice-b", "--topo-host", "host-b1"],
)


def run_topo_leg(args, tradeoff: float, label: str) -> dict:
    throttle = ["--kv-handoff-bytes-per-token", str(args.topo_kv_bytes),
                "--ici-bytes-per-s", str(args.topo_ici_bytes_per_s),
                "--dcn-bytes-per-s", str(args.topo_dcn_bytes_per_s)]
    specs = [spec + throttle for spec in TOPO_ENGINE_SPECS]
    plan, plan_reason = plan_cpu_sets(1)
    mode = "pinned-concurrent" if plan else "phased-projection"
    fleet = Fleet(1, len(specs), native_on=True,
                  reply_chars=args.reply_chars,
                  chunk_size=args.chunk_size,
                  master_extra=["--topology-tradeoff", str(tradeoff)],
                  engine_specs=specs).start(plan)
    try:
        fleet.wait_ready()
        spec = {
            "bases": fleet.bases,
            "requests": args.topo_requests,
            "concurrency": args.topo_concurrency,
            "rps": args.topo_rps, "traffic": "steady",
            "streams": args.streams,
            "prompt_scale": args.topo_prompt_scale,
            "seed": 0x21,
            "warmup": True,
        }
        window = _spawn_driver(spec, plan["driver"] if plan else None)
        # Pair-link census from the master (authoritative: every
        # SCHEDULE's prefill->decode link class).
        pair_links: dict = {}
        try:
            hot = requests.get(fleet.bases[0] + "/admin/hotpath",
                               timeout=5).json()
            pair_links = ((hot.get("telemetry") or {})
                          .get("topology") or {}).get("pair_links") or {}
        except (requests.RequestException, ValueError):
            _warn("could not scrape /admin/hotpath pair_links")
        # Modeled-handoff latencies from the engines, by link class.
        by_link: "dict[str, list[float]]" = {}
        for base in fleet.engine_bases:
            try:
                t = requests.get(base + "/admin/topology", timeout=5).json()
            except (requests.RequestException, ValueError):
                continue
            for row in t.get("handoffs", ()):
                by_link.setdefault(row["link"], []).append(row["ms"])
        split = {link: n for link, n in pair_links.items()
                 if link in ("local", "ici", "dcn")}
        total_split = sum(split.values())
        same = split.get("local", 0) + split.get("ici", 0)
        handoffs = [ms for rows in by_link.values() for ms in rows]
        return {
            "label": label,
            "topology_tradeoff": tradeoff,
            "engines": [" ".join(s) for s in TOPO_ENGINE_SPECS],
            "mode": mode,
            "cpu_isolation": _cpu_isolation(mode, plan_reason, fleet),
            "window": window,
            "pair_links": pair_links,
            "same_slice_pair_share": round(same / total_split, 4)
            if total_split else 0.0,
            "handoff_ms_by_link": {
                link: {"n": len(v),
                       "p50": round(percentile(v, 50), 2),
                       "p95": round(percentile(v, 95), 2)}
                for link, v in sorted(by_link.items())},
            "handoff_ms": {"n": len(handoffs),
                           "p50": round(percentile(handoffs, 50), 2),
                           "p95": round(percentile(handoffs, 95), 2)},
        }
    finally:
        fleet.stop()


def run_topo(args) -> dict:
    _info(f"topo leg: flat routing (tradeoff=0, DCN throttled to "
          f"{args.topo_dcn_bytes_per_s:g} B/s)")
    flat = run_topo_leg(args, 0.0, "flat")
    _info("topo leg: topology-aware routing "
          f"(tradeoff={args.topo_tradeoff:g})")
    topo_leg = run_topo_leg(args, args.topo_tradeoff, "topo")
    flat_p50 = flat["window"]["ttft_ms"]["p50"]
    topo_p50 = topo_leg["window"]["ttft_ms"]["p50"]
    headline = {
        # Higher-is-better keys carry no unit suffix on purpose:
        # bench_trend auto-tracks every headline leaf and infers the
        # regression direction from the suffix.
        "topo_ttft_p50_speedup": round(flat_p50 / max(0.01, topo_p50), 2),
        "same_slice_pair_share": topo_leg["same_slice_pair_share"],
        "topo_ttft_p50_ms": topo_p50,
        "topo_handoff_p95_ms": topo_leg["handoff_ms"]["p95"],
    }
    return {
        "bench": "topo",
        "kv_handoff_bytes_per_token": args.topo_kv_bytes,
        "ici_bytes_per_s": args.topo_ici_bytes_per_s,
        "dcn_bytes_per_s": args.topo_dcn_bytes_per_s,
        "requests_per_leg": args.topo_requests,
        "offered_rps": args.topo_rps,
        "legs": [flat, topo_leg],
        "headline": headline,
    }


# ---------------------------------------------------------------------- main
def run(args) -> dict:
    legs: "list[dict]" = []
    report: dict = {
        "bench": "fleet",
        "traffic": args.traffic,
        "offered_rps_per_window": args.rps,
        "ab_rps": args.ab_rps,
        "stream_population": args.streams,
        "prompt_scale": args.prompt_scale,
        "reply_chars": args.reply_chars,
        "chunk_size": args.chunk_size,
        "legs": legs,
    }
    # Native A/B at masters=1 first (the per-request CPU cut, driven at
    # the stable --ab-rps point), then the scaling curve at the
    # saturating rate with the native core on.
    _info(f"leg: masters=1 native=off (A/B baseline, "
          f"{args.ab_rps} rps stable)")
    off = run_leg(1, args, native_on=False, rps=args.ab_rps,
                  purpose="native-ab")
    legs.append(off)
    _info(f"leg: masters=1 native=on (A/B, {args.ab_rps} rps stable)")
    on = run_leg(1, args, native_on=True, rps=args.ab_rps,
                 purpose="native-ab")
    legs.append(on)
    for n in (1, 2, 4):
        if n > args.max_masters:
            continue
        _info(f"leg: masters={n} native=on (scaling, {args.rps} rps)")
        legs.append(run_leg(n, args, native_on=True))

    by_masters = {leg["masters"]: leg for leg in legs
                  if leg["native_on"] and leg["purpose"] == "scale"}
    rs_on = on["route_stream_cpu_us_per_req"]
    rs_off = off["route_stream_cpu_us_per_req"]
    headline = {
        "agg_rps_masters_1": by_masters.get(1, {}).get("agg_req_per_s"),
        "agg_rps_masters_2": by_masters.get(2, {}).get("agg_req_per_s"),
        "agg_rps_masters_4": by_masters.get(4, {}).get("agg_req_per_s"),
        "route_stream_cpu_us_per_req": rs_on,
        "native_route_stream_speedup": round(rs_off / rs_on, 2)
        if rs_on else 0.0,
        "native_route_speedup": round(
            off["route_cpu_us_per_req"]
            / max(0.01, on["route_cpu_us_per_req"]), 2),
        "native_stream_speedup": round(
            off["stream_cpu_us_per_req"]
            / max(0.01, on["stream_cpu_us_per_req"]), 2),
    }
    if headline["agg_rps_masters_4"] and headline["agg_rps_masters_1"]:
        headline["masters_4_over_1_scaling"] = round(
            headline["agg_rps_masters_4"]
            / headline["agg_rps_masters_1"], 2)
    report["headline"] = {k: v for k, v in headline.items()
                          if v is not None}
    report["native_ab"] = {
        "route_cpu_us_per_req": {"off": off["route_cpu_us_per_req"],
                                 "on": on["route_cpu_us_per_req"]},
        "stream_cpu_us_per_req": {"off": off["stream_cpu_us_per_req"],
                                  "on": on["stream_cpu_us_per_req"]},
        "route_stream_cpu_us_per_req": {"off": rs_off, "on": rs_on},
    }
    return report


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--drive", default=None, help=argparse.SUPPRESS)
    ap.add_argument("--requests", type=int, default=240,
                    help="requests per measurement window")
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--rps", type=float, default=120.0,
                    help="open-loop base rate per window (offered above "
                         "capacity -> the window measures capacity under "
                         "the diurnal shape; due-slot latency counts the "
                         "queueing)")
    ap.add_argument("--ab-rps", type=float, default=40.0,
                    help="open-loop rate for the native A/B legs — a "
                         "stable sub-capacity point so per-request CPU "
                         "reflects the code path, not overload thrash")
    ap.add_argument("--engines", type=int, default=2)
    ap.add_argument("--prompt-scale", type=float, default=1.0,
                    help="scale the tenant-mix prompt lengths (smoke "
                         "runs: 0.1)")
    ap.add_argument("--reply-chars", type=int, default=32)
    ap.add_argument("--chunk-size", type=int, default=32,
                    help="engine delta chunk size (reply-chars/chunk = "
                         "generation deltas per request)")
    ap.add_argument("--streams", type=int, default=200_000,
                    help="distinct prompt-stream population (simulated "
                         "user base; each request samples one stream)")
    ap.add_argument("--max-masters", type=int, default=4)
    ap.add_argument("--traffic", default="diurnal",
                    choices=["steady", "diurnal", "burst"])
    ap.add_argument("--diurnal-amp", type=float, default=0.6)
    ap.add_argument("--diurnal-period", type=float, default=12.0)
    ap.add_argument("--burst-every", type=float, default=10.0)
    ap.add_argument("--burst-len", type=float, default=2.0)
    ap.add_argument("--burst-mult", type=float, default=4.0)
    ap.add_argument("--topo", action="store_true",
                    help="run the ICI-topology A/B instead of the fleet "
                         "scaling suite: topology-aware vs flat routing "
                         "over a 2-slice fleet with the DCN link "
                         "throttled (artifact family BENCH_topo_*)")
    ap.add_argument("--topo-tradeoff", type=float, default=0.25,
                    help="--topology-tradeoff for the topo-aware leg")
    ap.add_argument("--topo-requests", type=int, default=90,
                    help="requests per topo A/B leg")
    ap.add_argument("--topo-rps", type=float, default=3.0,
                    help="steady open-loop rate for the topo legs (sub-"
                         "capacity even on a 1-core box: the A/B "
                         "isolates link cost, not queueing)")
    ap.add_argument("--topo-concurrency", type=int, default=6,
                    help="driver workers for the topo legs (enough to "
                         "cover rps x worst DCN sleep without going "
                         "closed-loop)")
    ap.add_argument("--topo-prompt-scale", type=float, default=0.1,
                    help="prompt scale for the topo legs: keeps the "
                         "throttled-DCN handoff in the ~100ms-1s band "
                         "(the batch tenant's full 24k-token payload "
                         "would sleep >10s per cross-slice request)")
    ap.add_argument("--topo-kv-bytes", type=int, default=1024,
                    help="modeled KV payload per prompt token for the "
                         "topo legs")
    ap.add_argument("--topo-ici-bytes-per-s", type=float, default=2e8,
                    help="modeled ICI bandwidth for the topo legs")
    ap.add_argument("--topo-dcn-bytes-per-s", type=float, default=2e6,
                    help="modeled (throttled) DCN bandwidth for the "
                         "topo legs")
    ap.add_argument("--out", default=None,
                    help="write the artifact here (stdout otherwise)")
    args = ap.parse_args()
    if args.drive:
        # Driver-process mode: one measurement window, JSON on stdout.
        print(json.dumps(drive_window(json.loads(args.drive))))
        return
    report = run_topo(args) if args.topo else run(args)
    text = json.dumps(report, indent=2)
    if args.out:
        Path(args.out).write_text(text + "\n")
        _info(f"wrote {args.out}")
    else:
        print(text)


if __name__ == "__main__":
    main()
