"""Micro-bench: decode-step KV writeback strategies.

Compares the current per-layer `jnp.stack + dynamic_update_index_in_dim`
pool writeback against a direct full-pool scatter
(`kv.at[l, :, page_idx, :, slot, :]`). Run on CPU for structure (alias
analysis) and on TPU for truth.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from xllm_service_tpu.utils import pin_cpu_platform_if_requested

pin_cpu_platform_if_requested()

import time

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial


def run(L=4, pages=1024, n_kv=4, ps=16, hd=64, B=8, steps=30):
    rng = np.random.default_rng(0)
    kv = jnp.zeros((L, 2, pages, n_kv, ps, hd), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, n_kv, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, n_kv, hd)), jnp.float32)
    page_idx = jnp.asarray(rng.integers(0, pages, B), jnp.int32)
    slot = jnp.asarray(rng.integers(0, ps, B), jnp.int32)

    @partial(jax.jit, donate_argnums=(0,))
    def step_stack(kv, k, v):
        for l in range(L):
            k_pages, v_pages = kv[l, 0], kv[l, 1]
            k_pages = k_pages.at[page_idx, :, slot, :].set(k, mode="drop")
            v_pages = v_pages.at[page_idx, :, slot, :].set(v, mode="drop")
            s = jnp.sum(k_pages[page_idx, :, slot, :] * v_pages[page_idx, :, slot, :])
            k = k + s * 1e-9   # data dependence so layers serialize
            kv = jax.lax.dynamic_update_index_in_dim(
                kv, jnp.stack([k_pages, v_pages]), l, 0)
        return kv, k

    @partial(jax.jit, donate_argnums=(0,))
    def step_scatter(kv, k, v):
        for l in range(L):
            kv = kv.at[l, 0, page_idx, :, slot, :].set(k, mode="drop")
            kv = kv.at[l, 1, page_idx, :, slot, :].set(v, mode="drop")
            s = jnp.sum(kv[l, 0, page_idx, :, slot, :] * kv[l, 1, page_idx, :, slot, :])
            k = k + s * 1e-9
        return kv, k

    for name, fn in [("stack+dynupd", step_stack), ("direct-scatter", step_scatter)]:
        pool = jnp.zeros((L, 2, pages, n_kv, ps, hd), jnp.float32)
        pool, kk = fn(pool, k, v)   # compile
        jax.block_until_ready(pool)
        t0 = time.perf_counter()
        for _ in range(steps):
            pool, kk = fn(pool, k, kk)
        jax.block_until_ready(pool)
        dt = (time.perf_counter() - t0) / steps
        import json
        print(json.dumps({"variant": name, "ms_per_step": round(dt * 1e3, 3),
                          "pool_mb": round(pool.nbytes / 1e6),
                          "backend": jax.default_backend()}))


if __name__ == "__main__":
    run()
