"""Generations-ingest micro-bench: deltas/sec the service plane sustains
on /rpc/generations (HTTP parse + scheduler dispatch + SSE fan-out), for
msgpack vs JSON framing. This is the hop that bounds aggregate decode
throughput across the fleet (reference ships batched protobuf here,
`rpc_service/service.cpp:149-215`).

Prints one JSON line per framing and the ratio.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from xllm_service_tpu.utils import pin_cpu_platform_if_requested

pin_cpu_platform_if_requested()

import json
import time
import uuid

import msgpack
import requests


def main() -> None:
    from xllm_service_tpu.common.call_data import CollectingConnection
    from xllm_service_tpu.common.config import ServiceOptions
    from xllm_service_tpu.common.request import Request
    from xllm_service_tpu.common.types import InstanceType
    from xllm_service_tpu.coordination.memory import (
        InMemoryCoordination,
        MemoryStore,
    )
    from xllm_service_tpu.master import Master
    from xllm_service_tpu.testing.fake_engine import (
        FakeEngine,
        FakeEngineConfig,
    )

    store = MemoryStore(expiry_tick_s=0.05)
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=2.0, sync_interval_s=1.0)
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()
    engine = FakeEngine(
        InMemoryCoordination(store),
        FakeEngineConfig(instance_type=InstanceType.MIX)).start()
    deadline = time.time() + 10
    while not master.scheduler.has_available_instances():
        if time.time() > deadline:
            raise RuntimeError("fake engine never became available")
        time.sleep(0.05)

    # In-flight streaming requests for the deltas to land on.
    N_REQ = 64
    sids = []
    for i in range(N_REQ):
        sid = f"bench-{uuid.uuid4().hex[:8]}"
        req = Request(service_request_id=sid, request_id=sid, model="fake",
                      stream=True, prompt="x", token_ids=[1, 2, 3])
        assert master.scheduler.schedule(req).ok()
        master.scheduler.record_new_request(
            req, CollectingConnection(stream=True), "completion")
        sids.append(sid)

    url = f"http://127.0.0.1:{master.rpc_port}/rpc/generations"
    BATCH = 32        # deltas per POST (the agent's flush batching)
    ROUNDS = 60
    results = {}
    for mode in ("json", "msgpack"):
        seq = {sid: 0 for sid in sids}
        t0 = time.perf_counter()
        n = 0
        for r in range(ROUNDS):
            gens = []
            for k in range(BATCH):
                sid = sids[(r * BATCH + k) % N_REQ]
                seq[sid] += 1
                gens.append({
                    "request_id": sid, "service_request_id": sid,
                    "status": {"code": 0, "message": ""},
                    "outputs": [{"index": 0, "text": "tok ",
                                 "token_ids": [7], "finish_reason": "",
                                 "logprobs": []}],
                    "finished": False, "finished_on_prefill": False,
                    "delta_seq": seq[sid],
                })
            if mode == "msgpack":
                resp = requests.post(
                    url, data=msgpack.packb({"gens": gens},
                                            use_bin_type=True),
                    headers={"Content-Type": "application/msgpack"},
                    timeout=10)
            else:
                resp = requests.post(url, json={"gens": gens}, timeout=10)
            assert resp.status_code == 200, resp.text
            n += BATCH
        dt = time.perf_counter() - t0
        results[mode] = n / dt
        print(json.dumps({"mode": mode,
                          "deltas_per_s": round(n / dt, 1),
                          "batch": BATCH}))

    print(json.dumps({
        "metric": "generations_ingest_msgpack_vs_json",
        "value": round(results["msgpack"] / results["json"], 3),
        "unit": "x",
        "deltas_per_s": round(results["msgpack"], 1),
    }))
    master.stop()
    store.close()


if __name__ == "__main__":
    main()
