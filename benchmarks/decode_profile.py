"""Decode-step component profile: names where the decode token-step time
goes on the attached accelerator.

Round-2 context: bench.py measured 1091 tok/s at bench-1b/B=16 — ~20% of
the HBM roofline — and int8 (halving the weight stream) changed nothing,
so the step is NOT weight-bandwidth-bound. This bench times the step's
components in isolation at the same shapes so the sweep can attribute
the other 80%:

  - full_step: fam.decode_forward + sample (what bench.py times)
  - forward_only: fam.decode_forward alone
  - attention_only: the paged-attention op over the same pool (isolated,
    scaled by n_layers)
  - sampling_only: sample_tokens on random logits
  - matmul_and_rest_ms (derived): forward_only - attention_only — the
    layer matmuls PLUS norms/rope/KV-writeback/dispatch gaps
  - sample_overhead_ms (derived): full_step - forward_only
  - dispatch_fetch_rtt_ms / upload_32kb_ms: the per-program-call floor
    on this attachment (relay RTT on tunnel-attached chips)

Prints ONE JSON line. CPU runs validate mechanism only.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from xllm_service_tpu.utils import pin_cpu_platform_if_requested

pin_cpu_platform_if_requested()


def bench_fn(fn, *args, iters=30):
    out = fn(*args)
    jax_block(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax_block(out)
    return (time.perf_counter() - t0) / iters * 1e3


def jax_block(x):
    import jax
    jax.block_until_ready(x)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from xllm_service_tpu.engine.sampling import SamplingState, sample_tokens
    from xllm_service_tpu.models import get_model_family
    from xllm_service_tpu.models.base import bench_1b_config, tiny_config
    from xllm_service_tpu.ops.attention import paged_attention

    backend = jax.default_backend()
    on_accel = backend != "cpu"
    mcfg = bench_1b_config() if on_accel else tiny_config(
        dtype=jnp.float32)
    fam = get_model_family(mcfg.name)

    B = 16 if on_accel else 4
    ctx = 512 if on_accel else 64
    ps = 16
    pages_per_seq = -(-1024 // ps) if on_accel else -(-128 // ps)
    num_pages = B * pages_per_seq + 64

    rng = np.random.default_rng(0)
    key = jax.random.PRNGKey(0)
    params = fam.init_params(mcfg, key)

    kv = jnp.zeros((mcfg.num_layers, 2, num_pages, mcfg.num_kv_heads, ps,
                    mcfg.head_dim), mcfg.dtype)
    pt = np.full((B, pages_per_seq), num_pages - 1, np.int32)
    for b in range(B):
        pt[b] = rng.permutation(np.arange(num_pages - 64))[:pages_per_seq]
    page_table = jnp.asarray(pt)
    clens = jnp.full((B,), ctx, jnp.int32)
    tokens = jnp.asarray(rng.integers(10, mcfg.vocab_size - 10, B),
                         jnp.int32)
    positions = clens - 1

    result = {"backend": backend, "B": B, "ctx": ctx,
              "model": "1b" if on_accel else "tiny",
              "metric": "decode_step_component_ms", "unit": "ms"}

    # 1. forward_only (returns logits + new kv; donation off for timing).
    fwd = jax.jit(lambda p, t, pos, k, tab, cl: fam.decode_forward(
        p, mcfg, t, pos, k, tab, cl)[0])
    result["forward_only_ms"] = round(bench_fn(
        fwd, params, tokens, positions, kv, page_table, clens), 3)

    def greedy_state():
        import dataclasses

        # Greedy = temperature 0 (the common serving case bench.py runs).
        return dataclasses.replace(
            SamplingState.init(B, mcfg.vocab_size),
            temperature=jnp.zeros((B,), jnp.float32))

    # 2. full step: forward + greedy sample.
    def full(p, t, pos, k, tab, cl, keys):
        logits, _ = fam.decode_forward(p, mcfg, t, pos, k, tab, cl)
        toks, _ = sample_tokens(logits.astype(jnp.float32),
                                greedy_state(), keys, cl)
        return toks

    keys = jax.random.split(key, B)
    result["full_step_ms"] = round(bench_fn(
        jax.jit(full), params, tokens, positions, kv, page_table, clens,
        keys), 3)

    # 3. attention_only over one layer's pool, scaled by n_layers.
    q = jax.random.normal(key, (B, mcfg.num_heads, mcfg.head_dim),
                          mcfg.dtype)
    attn = jax.jit(lambda qq, kk, vv, tab, cl: paged_attention(
        qq, kk, vv, tab, cl))
    per_layer = bench_fn(attn, q, kv[0, 0], kv[0, 1], page_table, clens)
    result["attention_only_ms"] = round(per_layer * mcfg.num_layers, 3)
    result["attention_per_layer_ms"] = round(per_layer, 4)

    # 4. sampling_only on random logits.
    logits = jax.random.normal(key, (B, mcfg.vocab_size), jnp.float32)

    def samp(lg, keys, cl):
        return sample_tokens(lg, greedy_state(), keys, cl)[0]

    result["sampling_only_ms"] = round(bench_fn(
        jax.jit(samp), logits, keys, clens), 3)

    # 5. Per-call overhead floor on this attachment (tunnel-attached
    # chips pay a relay RTT per dispatch+fetch; serving pays it per
    # horizon call and ~3x per admission).
    tiny = jnp.zeros((8,), jnp.float32)
    bump = jax.jit(lambda x: x + 1)
    result["dispatch_fetch_rtt_ms"] = round(bench_fn(bump, tiny), 3)
    up = np.zeros((8192,), np.int32)   # ~an admission's packed upload

    def upload(_):
        return jax.device_put(up)

    result["upload_32kb_ms"] = round(bench_fn(upload, None), 3)

    # Derived attribution.
    result["matmul_and_rest_ms"] = round(
        result["forward_only_ms"] - result["attention_only_ms"], 3)
    result["sample_overhead_ms"] = round(
        result["full_step_ms"] - result["forward_only_ms"], 3)
    result["value"] = result["full_step_ms"]
    # Roofline context: ideal weight-stream time at this config.
    wbytes = mcfg.decode_weight_stream_bytes()
    result["weight_stream_mb"] = round(wbytes / 1e6, 1)
    if on_accel:
        result["ideal_weight_stream_ms"] = round(wbytes / 819e9 * 1e3, 3)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
