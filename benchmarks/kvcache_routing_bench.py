"""Cache-aware routing data-plane benchmark.

Prices the PR's three hot-path claims, before/after on the same box,
measurement rounds interleaved so CPU drift can't masquerade as speedup:

1. **Prefix-index match**: the pre-PR index (coarse lock around a flat
   hex-string dict, per-match chained hashing in a Python per-slice loop,
   per-block ``getattr`` tier scoring — reproduced verbatim below as
   :class:`LegacyKVCacheIndex`) vs the shipped lock-free radix index
   (``GlobalKVCacheMgr``: RCU-published immutable entries, memoized
   request hashes, precomputed per-entry score tuples). Reported single-
   threaded and at N threads (the schedule executor is 8-way — the lock
   is exactly what it serializes on).
2. **Chained block hashing**: the old per-slice hashlib loop vs
   ``common/hashing.py`` (one-shot conversion + optional C extension).
3. **Routed TTFT** (``--routed``): the PR-4 ``master_hotpath_bench``
   multiproc harness driven under RR and CAR, so the end-to-end cost of
   putting CAR on the schedule path is visible in client TTFT.

    python benchmarks/kvcache_routing_bench.py                   # 1 + 2
    python benchmarks/kvcache_routing_bench.py --routed          # + 3
    python benchmarks/kvcache_routing_bench.py --instances 8 \
        --blocks 100000                                          # full scale

The tier-1 budget test (tests/test_kvcache_routing_budget.py) runs
:func:`run_index_bench` with a small workload and generous ceilings.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import statistics
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import numpy as np

from xllm_service_tpu.common.hashing import (
    native_available,
    prefix_block_hashes,
)
from xllm_service_tpu.common.types import KvCacheEvent
from xllm_service_tpu.coordination.memory import InMemoryCoordination, MemoryStore
from xllm_service_tpu.devtools.locks import make_lock
from xllm_service_tpu.scheduler.global_kvcache_mgr import GlobalKVCacheMgr

BLOCK = 128


def percentile(xs, p):
    if not xs:
        return 0.0
    xs = sorted(xs)
    k = min(len(xs) - 1, int(round((p / 100) * (len(xs) - 1))))
    return xs[k]


# --------------------------------------------------------------------------
# Pre-PR implementation, kept verbatim in shape: flat hex dict under one
# lock, per-match per-slice hashing, getattr tier walk. This is the
# "before" side of every index comparison.
# --------------------------------------------------------------------------

LEGACY_TIER_WEIGHTS = {"hbm": 1.0, "dram": 0.6, "ssd": 0.3}
_SEED = b"xllm-service-tpu"


def _legacy_hash_block(prev: bytes, token_ids) -> bytes:
    key = prev if prev else _SEED
    h = hashlib.blake2b(digest_size=16, key=key)
    h.update(np.asarray(token_ids, dtype=np.int32).tobytes())
    return h.digest()


def legacy_prefix_block_hash_hexes(token_ids, block_size=BLOCK) -> list[str]:
    arr = np.asarray(token_ids, dtype=np.int32)
    n_blocks = len(arr) // block_size
    out, prev = [], b""
    for i in range(n_blocks):
        prev = _legacy_hash_block(prev, arr[i * block_size:(i + 1) * block_size])
        out.append(prev)
    return [h.hex() for h in out]


class _LegacyLocations:
    __slots__ = ("hbm", "dram", "ssd")

    def __init__(self):
        self.hbm: set[str] = set()
        self.dram: set[str] = set()
        self.ssd: set[str] = set()

    def empty(self):
        return not (self.hbm or self.dram or self.ssd)

    def remove_instance(self, name):
        self.hbm.discard(name)
        self.dram.discard(name)
        self.ssd.discard(name)


class LegacyKVCacheIndex:
    """The pre-PR GlobalKVCacheMgr core (coordination sync stripped)."""

    def __init__(self, block_size=BLOCK):
        self._block_size = block_size
        self._lock = make_lock("bench.legacy_kvcache", order=890)  # lock-order: 890
        self._cache: dict[str, _LegacyLocations] = {}
        self._dirty: set[str] = set()
        self._removed: set[str] = set()

    def match(self, token_ids):
        hashes = legacy_prefix_block_hash_hexes(token_ids, self._block_size)
        scores: dict[str, float] = {}
        with self._lock:
            for h in hashes:
                loc = self._cache.get(h)
                if loc is None or loc.empty():
                    break
                for tier, weight in LEGACY_TIER_WEIGHTS.items():
                    for inst in getattr(loc, tier):
                        scores[inst] = scores.get(inst, 0.0) + weight
        return scores

    def record_updated_kvcaches(self, instance, stored_hexes):
        with self._lock:
            for h in stored_hexes:
                loc = self._cache.setdefault(h, _LegacyLocations())
                loc.hbm.add(instance)
                loc.dram.discard(instance)
                loc.ssd.discard(instance)
                self._dirty.add(h)

    def remove_instance(self, instance):
        with self._lock:
            dead = []
            for h, loc in self._cache.items():
                before = (len(loc.hbm), len(loc.dram), len(loc.ssd))
                loc.remove_instance(instance)
                if (len(loc.hbm), len(loc.dram), len(loc.ssd)) != before:
                    if loc.empty():
                        dead.append(h)
                    else:
                        self._dirty.add(h)
            for h in dead:
                del self._cache[h]
                self._removed.add(h)
                self._dirty.discard(h)


# --------------------------------------------------------------------------
# Workload
# --------------------------------------------------------------------------

def make_workload(n_instances, blocks_per_instance, n_prompts, chain_len,
                  seed=0):
    """Synthetic fleet state + match traffic.

    - ``n_prompts`` prompts of ``chain_len`` full blocks; 75% of their
      chains are stored (each on 1-3 instances), 25% miss at block 0.
    - Filler keys pad every instance to ``blocks_per_instance`` owned
      blocks (the realistic case: the index is much bigger than any one
      prompt's chain).
    """
    rng = np.random.default_rng(seed)
    instances = [f"inst-{i}:8000" for i in range(n_instances)]
    prompts, prompt_hashes, stored_flags = [], [], []
    per_instance_keys: dict[str, list[bytes]] = {n: [] for n in instances}
    for p in range(n_prompts):
        toks = ((np.arange(chain_len * BLOCK, dtype=np.int64) * 131 + p * 7919)
                % 50000).astype(np.int32).tolist()
        chain = prefix_block_hashes(toks, BLOCK)
        prompts.append(toks)
        prompt_hashes.append(chain)
        hit = (p % 4) != 3
        stored_flags.append(hit)
        if hit:
            for k in range(1 + p % 3):
                per_instance_keys[instances[(p + k) % n_instances]].extend(chain)
    for name in instances:
        deficit = blocks_per_instance - len(per_instance_keys[name])
        if deficit > 0:
            blob = rng.bytes(16 * deficit)
            per_instance_keys[name].extend(
                blob[i * 16:(i + 1) * 16] for i in range(deficit))
    return instances, per_instance_keys, prompts, prompt_hashes, stored_flags


def _timed_matches(fn, work, rounds, threads):
    """Run `fn(item)` over `work` `rounds` times on `threads` threads;
    returns (throughput per s, latencies ms)."""
    lat_all: list[float] = []
    lock = threading.Lock()
    total = [0]

    def worker(items):
        lats = []
        pc = time.perf_counter
        for it in items:
            t0 = pc()
            fn(it)
            lats.append((pc() - t0) * 1000)
        with lock:
            lat_all.extend(lats)
            total[0] += len(items)

    items = work * rounds
    shards = [items[i::threads] for i in range(threads)]
    t0 = time.perf_counter()
    ts = [threading.Thread(target=worker, args=(s,)) for s in shards]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    return total[0] / wall if wall else 0.0, lat_all


def run_index_bench(n_instances=8, blocks_per_instance=100_000,
                    n_prompts=256, chain_len=32, threads=4, rounds=4,
                    seed=0):
    (instances, per_keys, prompts, prompt_hashes, _flags) = make_workload(
        n_instances, blocks_per_instance, n_prompts, chain_len, seed)

    store = MemoryStore()
    coord = InMemoryCoordination(store)
    new_mgr = GlobalKVCacheMgr(coord, block_size=BLOCK, is_master=True)
    legacy = LegacyKVCacheIndex(BLOCK)

    # Ingest (batched heartbeat-sized events), interleaved new/legacy.
    ingest_new_s = ingest_legacy_s = 0.0
    n_keys = 0
    for name in instances:
        keys = per_keys[name]
        n_keys += len(keys)
        for i in range(0, len(keys), 10_000):
            batch = keys[i:i + 10_000]
            t0 = time.perf_counter()
            new_mgr.record_updated_kvcaches(name, KvCacheEvent(stored=batch))
            ingest_new_s += time.perf_counter() - t0
            hexes = [b.hex() for b in batch]
            t0 = time.perf_counter()
            legacy.record_updated_kvcaches(name, hexes)
            ingest_legacy_s += time.perf_counter() - t0

    # Match throughput, interleaved rounds: legacy hashes per call (that
    # IS its hot path); new walks the memoized chain (hashed once per
    # request at tokenize — Request.prefix_hashes).
    def legacy_match(i):
        legacy.match(prompts[i])

    def new_match(i):
        new_mgr.match(block_hashes=prompt_hashes[i])

    def new_match_rehash(i):
        new_mgr.match(prompts[i])

    idx = list(range(len(prompts)))
    report = {"config": {
        "instances": n_instances, "blocks_per_instance": blocks_per_instance,
        "total_keys_ingested": n_keys, "index_blocks": new_mgr.num_blocks(),
        "prompts": len(prompts), "chain_len_blocks": chain_len,
        "threads": threads, "rounds": rounds,
        "native_hash": native_available(),
    }}
    for label, fn in (("legacy", legacy_match), ("new", new_match),
                      ("new_rehash", new_match_rehash)):
        tput1, lat1 = _timed_matches(fn, idx, rounds, 1)
        tputN, latN = _timed_matches(fn, idx, rounds, threads)
        report[f"match_{label}"] = {
            "throughput_1t_per_s": round(tput1, 1),
            f"throughput_{threads}t_per_s": round(tputN, 1),
            "p50_ms": round(percentile(lat1, 50), 4),
            "p99_ms": round(percentile(lat1, 99), 4),
            f"p99_{threads}t_ms": round(percentile(latN, 99), 4),
        }
    t_key = f"throughput_{threads}t_per_s"
    report["match_speedup_1t"] = round(
        report["match_new"]["throughput_1t_per_s"]
        / max(report["match_legacy"]["throughput_1t_per_s"], 1e-9), 2)
    report[f"match_speedup_{threads}t"] = round(
        report["match_new"][t_key]
        / max(report["match_legacy"][t_key], 1e-9), 2)
    report["ingest_new_keys_per_s"] = round(n_keys / max(ingest_new_s, 1e-9))
    report["ingest_legacy_keys_per_s"] = round(
        n_keys / max(ingest_legacy_s, 1e-9))

    # Eviction: legacy walks the whole index; new touches only the dead
    # instance's reverse-index entry.
    victim = instances[0]
    t0 = time.perf_counter()
    new_mgr.remove_instance(victim)
    report["remove_instance_new_ms"] = round(
        (time.perf_counter() - t0) * 1000, 3)
    t0 = time.perf_counter()
    legacy.remove_instance(victim)
    report["remove_instance_legacy_ms"] = round(
        (time.perf_counter() - t0) * 1000, 3)

    coord.close()
    store.close()
    return report


def run_hashing_bench(prompt_tokens=4096, iters=400, rounds=5):
    """Old per-slice loop vs shipped hashing, interleaved."""
    toks = list(range(prompt_tokens))
    t_old = t_new = 0.0
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(iters):
            legacy_prefix_block_hash_hexes(toks, BLOCK)
        t_old += time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(iters):
            prefix_block_hashes(toks, BLOCK)
        t_new += time.perf_counter() - t0
    n = iters * rounds
    return {
        "prompt_tokens": prompt_tokens,
        "native_hash": native_available(),
        "old_us_per_prompt": round(t_old / n * 1e6, 1),
        "new_us_per_prompt": round(t_new / n * 1e6, 1),
        "speedup": round(t_old / max(t_new, 1e-12), 2),
    }


def run_routed_bench(requests_n=192, concurrency=8):
    """CAR vs RR client TTFT through the PR-4 multiproc harness."""
    from benchmarks.master_hotpath_bench import run_bench
    out = {}
    for policy in ("RR", "CAR"):
        r = run_bench(requests_n=requests_n, concurrency=concurrency,
                      prompt_chars=1024, max_tokens=8, reply_chars=32,
                      policy=policy, n_engines=2)
        out[policy] = {
            "ttft_ms": r["master_wire_ttft_ms"],
            "req_per_s": r["req_per_s"],
            "errors": r["errors"],
            "schedule_p50_ms": (r.get("master_stages_ms", {})
                                .get("schedule", {}).get("p50")),
        }
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--instances", type=int, default=8)
    ap.add_argument("--blocks", type=int, default=100_000,
                    help="blocks per instance")
    ap.add_argument("--prompts", type=int, default=256)
    ap.add_argument("--chain-len", type=int, default=32,
                    help="full blocks per prompt (32 = 4096 tokens)")
    ap.add_argument("--threads", type=int, default=4)
    ap.add_argument("--rounds", type=int, default=4)
    ap.add_argument("--routed", action="store_true",
                    help="also run the CAR-vs-RR multiproc TTFT bench")
    args = ap.parse_args()
    report = {
        "index": run_index_bench(args.instances, args.blocks, args.prompts,
                                 args.chain_len, args.threads, args.rounds),
        "hashing": run_hashing_bench(),
    }
    if args.routed:
        report["routed_ttft"] = run_routed_bench()
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
