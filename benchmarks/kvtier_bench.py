"""Tiered KV-cache data-plane benchmark (ISSUE 7 acceptance numbers).

Four legs, all on the real engine (tiny model, ``JAX_PLATFORMS=cpu``):

1. **Tier-aware TTFT** — one ≥1k-token shared prefix, measured hot
   (prefix in HBM), warm (prefix offloaded to the DRAM arena), cold-SSD
   (prefix demoted to the spill file) and cold-recompute (tiering off:
   the full prefill runs again). The warm/cold gap is the Mooncake-style
   claim: an onload is a host memcpy + device scatter, a recompute is
   the whole prefill.
2. **Capacity multiplier** — distinct prefixes pushed through a fixed
   HBM budget until far past eviction; addressable prefix blocks
   (HBM + fence-complete tier blocks) vs the HBM-only baseline.
3. **Decode-step latency under background offload** — identical
   decode+churn workload on a tiered and an untiered engine,
   interleaved rounds; the tier pump must not move p50 step time.
4. **Streaming transfer framing** — chunked offer/pull throughput at
   two chunk sizes, plus a DCN-budgeted run showing the token-bucket
   pacing converge on the configured bytes/s.

    python benchmarks/kvtier_bench.py                 # all legs
    python benchmarks/kvtier_bench.py --quick         # CI-scale
    python benchmarks/kvtier_bench.py --out BENCH_kvtier_r09.json
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import jax.numpy as jnp
import numpy as np

from xllm_service_tpu.common.hashing import prefix_block_hashes
from xllm_service_tpu.common.request import RequestOutput, SamplingParams
from xllm_service_tpu.engine.config import EngineConfig
from xllm_service_tpu.engine.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.engine.kv_transfer import (
    BandwidthAccountant,
    StreamOfferTable,
    pull_stream,
)
from xllm_service_tpu.models.base import tiny_config


def percentile(xs, p):
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(p / 100 * (len(xs) - 1))))
    return xs[i]


# --------------------------------------------------------------- harness
class _FirstToken:
    """Records the wall time of the first emitted token."""

    def __init__(self):
        self.t_first = None
        self.done = False

    def __call__(self, out: RequestOutput) -> None:
        if self.t_first is None and any(s.token_ids for s in out.outputs):
            self.t_first = time.perf_counter()
        if out.finished:
            self.done = True


def _mk_engine(num_pages: int, tier_dram: int = 0, tier_ssd: int = 0,
               hash_block: int = 64, max_ctx: int = 2048,
               buckets=(64, 128, 1088, 2048)) -> InferenceEngine:
    return InferenceEngine(EngineConfig(
        model=tiny_config(dtype=jnp.float32, max_context_len=max_ctx),
        num_pages=num_pages, page_size=16, hash_block_size=hash_block,
        max_batch_size=4, max_seq_len=max_ctx, prefill_buckets=buckets,
        kv_tier_dram_bytes=tier_dram, kv_tier_ssd_bytes=tier_ssd))


def _run(engine: InferenceEngine, rid: str, prompt, max_tokens=8) -> float:
    """Submit one request, drive the loop to completion; returns TTFT s."""
    col = _FirstToken()
    t0 = time.perf_counter()
    engine.submit(EngineRequest(
        rid, rid, token_ids=list(prompt),
        sampling=SamplingParams(max_tokens=max_tokens, temperature=0.0,
                                ignore_eos=True),
        on_output=col))
    while not col.done:
        if not engine.step():
            time.sleep(0.0005)
    return col.t_first - t0


def _wait(pred, timeout=20.0):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            raise TimeoutError("tier state never converged")
        time.sleep(0.01)


# --------------------------------------------------------- leg 1: TTFT
def bench_tier_ttft(prefix_tokens: int, rounds: int) -> dict:
    hbs = 64
    n_blocks = prefix_tokens // hbs
    prefix = list(np.random.default_rng(7).integers(
        2, 500, size=prefix_tokens))
    tail = list(range(16))           # distinct suffix past the prefix

    def prompt():
        return prefix + tail

    def churn(engine, start, count, tokens=384):
        """Distinct throwaway prompts that force LRU eviction of the
        shared prefix (and, tiered, its offload)."""
        for i in range(count):
            base = 10_000 + (start + i) * 1000
            p = list(np.random.default_rng(base).integers(
                2, 500, size=tokens))
            _run(engine, f"churn-{base}", p, max_tokens=2)

    out = {"prefix_tokens": prefix_tokens, "prefix_blocks": n_blocks}

    hx = [h.hex()
          for h in prefix_block_hashes(prompt(), hbs)][:n_blocks]

    # Tiered engine: DRAM generously sized; prefix offloads whole.
    eng = _mk_engine(num_pages=96, tier_dram=256 << 20)
    blk = eng.tier_store.block_nbytes
    hot, warm = [], []
    _run(eng, "seed", prompt())      # donate the prefix blocks
    # Warm-up cycle: compile the suffix-prefill bucket and the tier
    # scatter program OUTSIDE the measured rounds.
    _run(eng, "wu-hot", prompt())
    churn(eng, 900, 5)
    _wait(lambda: all(eng.tier_store.ready(h) for h in hx))
    _run(eng, "wu-warm", prompt())
    for r in range(rounds):
        hot.append(_run(eng, f"hot-{r}", prompt()))
        churn(eng, r * 10, 5)
        _wait(lambda: all(eng.tier_store.ready(h) for h in hx))
        warm.append(_run(eng, f"warm-{r}", prompt()))
    tier_stats = eng.tier_store.stats()

    # SSD leg: DRAM squeezed to 2 blocks so the prefix demotes to disk.
    eng_ssd = _mk_engine(num_pages=96, tier_dram=2 * blk,
                         tier_ssd=256 << 20)
    ssd = []
    _run(eng_ssd, "seed", prompt())
    for r in range(-1, rounds):      # round -1 = compile warm-up
        churn(eng_ssd, 100 + r * 10, 5)
        _wait(lambda: all(eng_ssd.tier_store.ready(h) for h in hx)
              and eng_ssd.tier_store.ssd_blocks() >= n_blocks - 2)
        t = _run(eng_ssd, f"ssd-{r}", prompt())
        if r >= 0:
            ssd.append(t)

    # Cold recompute: tiering OFF — eviction destroys the prefix, every
    # re-admission pays the full prefill.
    eng_cold = _mk_engine(num_pages=96)
    cold = []
    _run(eng_cold, "seed", prompt())
    for r in range(rounds):
        churn(eng_cold, 200 + r * 10, 5)
        cold.append(_run(eng_cold, f"cold-{r}", prompt()))

    out.update({
        "block_nbytes": blk,
        "hot_hbm_ttft_ms": round(statistics.median(hot) * 1e3, 2),
        "warm_dram_ttft_ms": round(statistics.median(warm) * 1e3, 2),
        "warm_ssd_ttft_ms": round(statistics.median(ssd) * 1e3, 2),
        "cold_recompute_ttft_ms": round(statistics.median(cold) * 1e3, 2),
        "warm_vs_cold_speedup": round(
            statistics.median(cold) / statistics.median(warm), 2),
        "ssd_vs_cold_speedup": round(
            statistics.median(cold) / statistics.median(ssd), 2),
        "tier_stats": tier_stats,
    })
    return out


# ------------------------------------------------- leg 2: capacity
def bench_capacity(num_prefixes: int) -> dict:
    """Fixed HBM budget; distinct 256-token prefixes far past HBM
    capacity. Addressable = still-matchable prefix blocks."""
    def feed(engine):
        for i in range(num_prefixes):
            p = list(np.random.default_rng(5_000 + i).integers(
                2, 500, size=256))
            _run(engine, f"cap-{i}", p, max_tokens=2)

    base = _mk_engine(num_pages=64, max_ctx=512, buckets=(64, 128, 512))
    feed(base)
    hbm_only = base.page_mgr.cached_block_count()

    tiered = _mk_engine(num_pages=64, tier_dram=16 << 20,
                        tier_ssd=64 << 20, max_ctx=512,
                        buckets=(64, 128, 512))
    feed(tiered)
    _wait(lambda: not tiered.page_mgr._evicted_pending)
    time.sleep(0.3)          # let in-flight offload writes fence
    st = tiered.tier_store.stats()
    hbm = tiered.page_mgr.cached_block_count()
    addressable = hbm + st["dram_blocks"] + st["ssd_blocks"]
    return {
        "distinct_prefixes": num_prefixes,
        "prefix_blocks_fed": num_prefixes * 4,
        "hbm_budget_pages": 64,
        "hbm_only_addressable_blocks": hbm_only,
        "tiered_addressable_blocks": addressable,
        "tiered_split": {"hbm": hbm, "dram": st["dram_blocks"],
                         "ssd": st["ssd_blocks"]},
        "offload_dropped": st["offload_dropped"],
        "capacity_multiplier": round(addressable / max(1, hbm_only), 2),
    }


# -------------------------------------- leg 3: step latency under offload
def _step_workload(engine: InferenceEngine, churn_every: int,
                   n_churn: int) -> list[float]:
    """One long decode + periodic churn admissions; returns step() wall
    times for steps taken while the long decode is live."""
    col = _FirstToken()
    engine.submit(EngineRequest(
        "longdec", "longdec", token_ids=list(range(40, 72)),
        sampling=SamplingParams(max_tokens=160, temperature=0.0,
                                ignore_eos=True),
        on_output=col))
    durs = []
    steps = 0
    injected = 0
    sink = []
    while not col.done:
        if injected < n_churn and steps and steps % churn_every == 0:
            c = _FirstToken()
            sink.append(c)
            p = list(np.random.default_rng(9_000 + injected).integers(
                2, 500, size=192))
            engine.submit(EngineRequest(
                f"churn-{injected}", f"churn-{injected}", token_ids=p,
                sampling=SamplingParams(max_tokens=2, temperature=0.0,
                                        ignore_eos=True),
                on_output=c))
            injected += 1
        t0 = time.perf_counter()
        busy = engine.step()
        durs.append(time.perf_counter() - t0)
        steps += 1
        if not busy:
            time.sleep(0.0005)
    while not all(c.done for c in sink):
        engine.step()
    return durs


def bench_step_latency(rounds: int) -> dict:
    base = _mk_engine(num_pages=64, max_ctx=512, buckets=(64, 256, 512))
    tier = _mk_engine(num_pages=64, tier_dram=256 << 20, max_ctx=512,
                      buckets=(64, 256, 512))
    base_durs, tier_durs = [], []
    for _ in range(rounds):          # interleaved rounds: drift-proof
        base_durs += _step_workload(base, churn_every=12, n_churn=8)
        tier_durs += _step_workload(tier, churn_every=12, n_churn=8)
    st = tier.tier_store.stats()
    b50 = statistics.median(base_durs)
    t50 = statistics.median(tier_durs)
    return {
        "rounds": rounds,
        "baseline_step_p50_ms": round(b50 * 1e3, 3),
        "tiered_step_p50_ms": round(t50 * 1e3, 3),
        "baseline_step_p90_ms": round(percentile(base_durs, 90) * 1e3, 3),
        "tiered_step_p90_ms": round(percentile(tier_durs, 90) * 1e3, 3),
        "delta_p50_perc": round((t50 - b50) / b50 * 100, 2),
        "offloads_during_tiered_run": st["offload_total"],
        "offload_dropped": st["offload_dropped"],
    }


# ----------------------------------------------- leg 4: stream framing
def bench_stream(payload_mb: int) -> dict:
    data = np.random.default_rng(3).standard_normal(
        payload_mb * (1 << 20) // 4).astype(np.float32)
    out = {"payload_mb": payload_mb, "chunks": {}}
    for chunk in (1 << 18, 1 << 20):
        table = StreamOfferTable(default_chunk_bytes=chunk)
        desc = table.offer("bench", data.tobytes(),
                           shape=[data.size], dtype="float32")

        def post(url, payload):
            return table.read_chunk(payload["uuid"], payload["offset"],
                                    payload["max_bytes"])

        bw = BandwidthAccountant()
        t0 = time.perf_counter()
        got = pull_stream("peer:0", desc, accountant=bw, post=post)
        el = time.perf_counter() - t0
        assert got.nbytes == data.nbytes
        table.release(desc["stream_uuid"])
        out["chunks"][f"{chunk >> 10}KiB"] = {
            "mb_per_s": round(data.nbytes / el / 1e6, 1),
            "round_trips": -(-data.nbytes // chunk),
        }
    # Budgeted run: the token bucket allows ONE budget-second of burst,
    # so a payload of ~3 budget-seconds must take ~2s of pacing sleep.
    budget = data.nbytes // 3
    table = StreamOfferTable(default_chunk_bytes=1 << 20)
    desc = table.offer("bench-paced", data.tobytes(),
                       shape=[data.size], dtype="float32")

    def post(url, payload):
        return table.read_chunk(payload["uuid"], payload["offset"],
                                payload["max_bytes"])

    bw = BandwidthAccountant(dcn_bytes_per_s=budget)
    t0 = time.perf_counter()
    pull_stream("peer:0", desc, accountant=bw, link="dcn", post=post)
    el = time.perf_counter() - t0
    out["paced_dcn"] = {
        "budget_mb_per_s": round(budget / 1e6, 1),
        "achieved_mb_per_s": round(data.nbytes / el / 1e6, 1),
    }
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CI scale: small prefix, 1 round")
    ap.add_argument("--prefix-tokens", type=int, default=1024)
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--prefixes", type=int, default=14)
    ap.add_argument("--payload-mb", type=int, default=8)
    ap.add_argument("--out", default="")
    args = ap.parse_args()
    if args.quick:
        args.prefix_tokens, args.rounds, args.prefixes = 256, 1, 12
        args.payload_mb = 2

    report = {
        "round": 9,
        "box": "CI container, JAX_PLATFORMS=cpu",
        "bench": "benchmarks/kvtier_bench.py",
        "tier_ttft": bench_tier_ttft(args.prefix_tokens, args.rounds),
        "capacity": bench_capacity(args.prefixes),
        "step_latency": bench_step_latency(max(1, args.rounds - 1)),
        "stream": bench_stream(args.payload_mb),
    }
    print(json.dumps(report, indent=2))
    if args.out:
        Path(args.out).write_text(json.dumps(report, indent=2) + "\n")


if __name__ == "__main__":
    main()
