"""Mosaic compile gate: AOT-lower and compile every Pallas kernel arm
the sweep A/Bs, BEFORE any timing step runs (VERDICT r4 next #6).

A Mosaic rejection becomes a named per-arm verdict in one JSON line
instead of a mid-sweep crash:

    {"metric": "mosaic_compile_gate", "backend": "tpu",
     "arms": {"paged_default": {"ok": true, "compile_s": 8.1}, ...},
     "failed_arms": ["..."], "error": "..."?}

Arms cover the full A/B matrix (tpu_sweep.sh): the paged decode kernel
at every chunk/rowpipe setting, the gemma-2 softcap route and the
sliding-window walk start, the fused append+attend kernel, the MQ
verify/prefill kernel, and the CP partial-stats kernel.

Shapes are the bench-1b serving shapes (bench.py), so the gate compiles
the exact programs the timing steps will run. Lowering uses
jax.ShapeDtypeStruct — no HBM is touched, so the gate is safe to run
even when a later OOM would kill a timing arm.

On CPU (relay down / tests) the kernels run in interpret mode, which
skips Mosaic entirely — the artifact then reports backend "cpu" and the
sweep's backend check keeps it from masquerading as a real verdict.
"""

from __future__ import annotations

import json
import time


def _arm_specs(interpret: bool):
    """Yield (name, thunk) pairs; each thunk AOT-lowers + compiles one
    kernel variant and returns None (raises on rejection)."""
    import jax
    import jax.numpy as jnp

    from xllm_service_tpu.models.base import bench_1b_config

    mcfg = bench_1b_config()
    B, ps, max_seq = 16, 16, 1024
    n_q, n_kv, hd = mcfg.num_heads, mcfg.num_kv_heads, mcfg.head_dim
    max_pages = max_seq // ps
    pool_pages = B * max_pages + 64
    f = jax.ShapeDtypeStruct
    bf16, i32 = jnp.bfloat16, jnp.int32

    q = f((B, n_q, hd), bf16)
    kv_pages = f((pool_pages, n_kv, ps, hd), bf16)
    pt = f((B, max_pages), i32)
    lens = f((B,), i32)

    def compile_jitted(fn, *args, **static_kwargs):
        fn.lower(*args, **static_kwargs).compile()

    def paged(chunk, pipeline_rows, softcap=0.0, window=0,
              b=B, mp=max_pages, pool=pool_pages):
        from xllm_service_tpu.ops.pallas_paged_attention import (
            _paged_attention_impl)

        def thunk():
            compile_jitted(_paged_attention_impl,
                           f((b, n_q, hd), bf16),
                           f((pool, n_kv, ps, hd), bf16),
                           f((pool, n_kv, ps, hd), bf16),
                           f((b, mp), i32), f((b,), i32), chunk=chunk,
                           pipeline_rows=pipeline_rows,
                           scale=1.0 / (hd ** 0.5), softcap=softcap,
                           window=window, interpret=interpret)
        return thunk

    from xllm_service_tpu.ops.pallas_page_dma import page_chunk_size
    default_chunk = page_chunk_size(max_pages)

    yield "paged_default", paged(default_chunk, False)
    yield "paged_chunk16", paged(16, False)
    yield "paged_chunk32", paged(32, False)
    yield "paged_rowpipe", paged(default_chunk, True)
    yield "paged_rowpipe16", paged(16, True)
    # The long-context arms are DIFFERENT grids (bench.py's shape
    # ladder: batch shrinks as the walk deepens), not re-tiles of
    # chunk16 — gate each one the timing steps will actually run.
    yield "paged_chunk16_ctx2k", paged(
        16, False, b=4, mp=160, pool=4 * 160 + 64)
    yield "paged_chunk16_ctx8k", paged(
        16, False, b=2, mp=544, pool=2 * 544 + 64)
    yield "paged_chunk16_ctx16k", paged(
        16, False, b=2, mp=1056, pool=2 * 1056 + 64)
    yield "paged_chunk16_ctx32k", paged(
        16, False, b=1, mp=2080, pool=2080 + 64)
    # gemma-2 route: softcap + explicit scale, static kernel params.
    yield "gemma2_softcap", paged(default_chunk, False, softcap=30.0)
    # sliding-window walk start (gemma-2 local layers).
    yield "window_start", paged(default_chunk, False, window=512)

    def fused():
        from xllm_service_tpu.ops.pallas_fused_decode_attention import (
            _fused_impl)
        k_new = f((B, n_kv, hd), bf16)
        compile_jitted(_fused_impl, q, k_new, k_new, kv_pages, kv_pages,
                       pt, lens, chunk=default_chunk,
                       pipeline_rows=False, interpret=interpret)
    yield "fused_writeback", fused

    def fused_rp16():
        from xllm_service_tpu.ops.pallas_fused_decode_attention import (
            _fused_impl)
        k_new = f((B, n_kv, hd), bf16)
        compile_jitted(_fused_impl, q, k_new, k_new, kv_pages, kv_pages,
                       pt, lens, chunk=16, pipeline_rows=True,
                       interpret=interpret)
    yield "fused_rowpipe16", fused_rp16

    def mq(s_q):
        # The MQ kernel has two users with DIFFERENT grids: the
        # speculative-verify program runs [B, Kd+1] = [B, 5] blocks
        # (spec_bench speculate_k=4), the Pallas prefill route runs the
        # S=128 chunk bucket. Gate both programs.
        from xllm_service_tpu.ops.pallas_mq_paged_attention import _mq_impl

        def thunk():
            q_blk = f((B, s_q, n_q, hd), bf16)
            compile_jitted(_mq_impl, q_blk, kv_pages, kv_pages, pt, lens,
                           lens, chunk=default_chunk, pipeline_rows=False,
                           interpret=interpret)
        return thunk
    yield "mq_verify_k4", mq(5)
    yield "prefill_pallas_s128", mq(128)

    def cp_partial():
        from xllm_service_tpu.ops.cp_paged_attention import (
            _paged_partial_impl)
        # Exactly cp_bench's on-accel program: B=16, ctx=2048 → 132-wide
        # tables (128 pages + 4 slack), 2112-page pool, 1-device mesh.
        # local_pt/starts are per-table-entry [B, mp]; n_local and
        # context_lens are [B] (see _local_partial_kernelized).
        cp_b, cp_mp, cp_pool = 16, 132, 16 * 128 + 64
        compile_jitted(_paged_partial_impl,
                       f((cp_b, n_q, hd), bf16),
                       f((cp_pool, n_kv, ps, hd), bf16),
                       f((cp_pool, n_kv, ps, hd), bf16),
                       f((cp_b, cp_mp), i32), f((cp_b, cp_mp), i32),
                       f((cp_b,), i32), f((cp_b,), i32),
                       scale=1.0 / (hd ** 0.5),
                       chunk=page_chunk_size(cp_mp),
                       pipeline_rows=False, interpret=interpret)
    yield "cp_partial_stats", cp_partial


def run_gate() -> dict:
    import jax

    backend = jax.default_backend()
    interpret = backend == "cpu"
    arms: dict[str, dict] = {}
    failed = []
    try:
        # Materialize the matrix first: a kernel-module ImportError is
        # exactly the breakage the gate exists to name, and it fires at
        # generator level — it must become a verdict, not a traceback
        # that breaks the one-JSON-line contract.
        specs = list(_arm_specs(interpret))
    except Exception as e:  # noqa: BLE001 — import/spec failure
        return {"metric": "mosaic_compile_gate", "backend": backend,
                "interpret": interpret, "arms": {},
                "error": f"arm setup failed: "
                         f"{type(e).__name__}: {e}"[:400]}
    for name, thunk in specs:
        t0 = time.perf_counter()
        try:
            thunk()
            arms[name] = {"ok": True,
                          "compile_s": round(time.perf_counter() - t0, 1)}
        except Exception as e:  # noqa: BLE001 — the verdict IS the point
            arms[name] = {"ok": False,
                          "error": f"{type(e).__name__}: {e}"[:300]}
            failed.append(name)
    out = {"metric": "mosaic_compile_gate", "backend": backend,
           "interpret": interpret, "arms": arms}
    if failed:
        out["failed_arms"] = failed
        out["error"] = f"{len(failed)} arm(s) failed Mosaic compile"
    return out


# No standalone __main__: run via `python bench.py --compile-only`, which
# wraps this module in the dead-relay probe + CPU pinning a bare
# jax.default_backend() call here would bypass (an in-process first init
# against a dead relay hangs past any driver timeout).
