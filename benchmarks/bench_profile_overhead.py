"""Continuous-profiler overhead micro-bench (ISSUE 18 acceptance: the
always-on sampler costs <=1% of the serve path at the default rate).

Measures the fake-engine request path end-to-end (HTTP frontend ->
scheduler -> fake engine -> generations ingest -> response) with the
sampling profiler OFF vs ON at the default ~19 Hz, against ONE shared
cluster with the modes interleaved round-robin (cluster-to-cluster and
drift noise would otherwise swamp the sub-percent effect being
measured). The profiler toggles through its public refcounted
start/stop, so every round also exercises the spawn/join lifecycle.

Also times one raw sampler tick in isolation (``sample_tick_us`` — the
per-tick cost amortized over ``1/hz`` seconds is the first-principles
overhead bound), and records the loaded run's *composition*: the
profiler's own per-role sample split next to ``CPU_ATTR``'s per-loop CPU
split, the evidence that the flamegraph names the same hot loops the
coarse attribution does (the ISSUE 18 alignment acceptance).

Prints one JSON line per mode, the overhead ratio, and a
BENCH_profile-shaped document at the end (headline tracked by
scripts/bench_trend.py). Exits non-zero when the measured p50 overhead
exceeds the gate (``PROFILE_GATE_PCT``, default 1.0 points).
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from xllm_service_tpu.utils import pin_cpu_platform_if_requested

pin_cpu_platform_if_requested()

import json
import os
import statistics
import threading
import time

import requests

MODES = ("off", "on")
PROFILE_HZ = 19.0


def sample_tick_us(iters: int = 2000) -> float:
    """Cost of one raw sampler tick (all threads walked, stacks folded,
    merged under the leaf lock) against the current thread population."""
    from xllm_service_tpu.profiling import SamplingProfiler

    p = SamplingProfiler()
    p.configure(hz=0)   # never spawns; we drive ticks by hand
    ident = threading.get_ident()
    p._sample_once(ident)   # warm the label cache
    t0 = time.perf_counter()
    for _ in range(iters):
        p._sample_once(ident)
    return (time.perf_counter() - t0) / iters * 1e6


def main() -> None:
    from xllm_service_tpu.common.config import ServiceOptions
    from xllm_service_tpu.common.hotpath import CPU_ATTR
    from xllm_service_tpu.coordination.memory import (
        InMemoryCoordination,
        MemoryStore,
    )
    from xllm_service_tpu.master import Master
    from xllm_service_tpu.profiling import PROFILER
    from xllm_service_tpu.testing.fake_engine import (
        FakeEngine,
        FakeEngineConfig,
    )

    store = MemoryStore(expiry_tick_s=0.05)
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=2.0, sync_interval_s=1.0,
                          profile_hz=PROFILE_HZ)
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()
    engine = FakeEngine(
        InMemoryCoordination(store),
        FakeEngineConfig(reply_text="x" * 8, chunk_size=8,
                         delay_s=0.0)).start()
    deadline = time.time() + 10
    while not master.scheduler.has_available_instances():
        if time.time() > deadline:
            raise RuntimeError("fake engine never became available")
        time.sleep(0.05)

    tick_us = sample_tick_us()
    # First-principles bound: one tick every 1/hz seconds.
    amortized_pct = tick_us * 1e-6 * PROFILE_HZ * 100.0
    print(json.dumps({"sample_tick_us": round(tick_us, 1),
                      "amortized_cpu_pct": round(amortized_pct, 4)}))

    def set_mode(mode: str) -> None:
        # The master owns one profiler ref; the bench borrows/returns a
        # second through the public refcounted lifecycle. "off" drops
        # BOTH (master's comes back at the end of the round), so the
        # sampler thread is truly gone during off rounds.
        if mode == "off":
            PROFILER.stop()
        else:
            PROFILER.start()

    url = f"http://127.0.0.1:{master.http_port}/v1/completions"
    body = {"model": "fake-model", "prompt": "bench", "max_tokens": 8}
    session = requests.Session()

    def one() -> float:
        t0 = time.perf_counter()
        r = session.post(url, json=body, timeout=30)
        assert r.status_code == 200, r.text
        return (time.perf_counter() - t0) * 1000.0

    for _ in range(50):   # warmup (threads, sockets, code paths)
        one()
    CPU_ATTR.clear()
    PROFILER.clear()

    ROUNDS, PER_ROUND = 16, 40
    lat: dict[str, list[float]] = {m: [] for m in MODES}
    round_p50: dict[str, list[float]] = {m: [] for m in MODES}
    for r in range(ROUNDS):
        # Alternate leg order: a monotonic machine-load drift would
        # otherwise systematically penalize whichever mode runs second.
        for mode in (MODES if r % 2 == 0 else MODES[::-1]):
            set_mode(mode)
            xs = [one() for _ in range(PER_ROUND)]
            lat[mode].extend(xs)
            round_p50[mode].append(sorted(xs)[len(xs) // 2])
    # End every cycle "on": the master's ref is outstanding and its
    # cleanup pairs the final stop.

    results = {}
    for mode in MODES:
        xs = sorted(lat[mode])
        results[mode] = {
            "mode": mode,
            "n": len(xs),
            "mean_ms": round(statistics.fmean(xs), 3),
            "p50_ms": round(xs[len(xs) // 2], 3),
            "p95_ms": round(xs[int(len(xs) * 0.95)], 3),
        }
        print(json.dumps(results[mode]))
    base = results["off"]["p50_ms"]
    overhead_pct = round(
        (results["on"]["p50_ms"] - base) / base * 100.0, 2)
    # Noise-robust secondary estimate: median of the per-round paired
    # p50 deltas (drift cancels within each interleaved round).
    deltas = sorted((b - a) / a * 100.0
                    for a, b in zip(round_p50["off"], round_p50["on"]))
    paired_median_pct = round(deltas[len(deltas) // 2], 2)
    print(json.dumps({"profile_overhead_p50_pct": overhead_pct,
                      "paired_round_median_pct": paired_median_pct}))

    # Composition: the profiler's own view of the loaded run next to the
    # coarse CPU attribution — the flamegraph must name the same hot
    # loops CPU_ATTR charges (ingest/route/stream).
    snap = PROFILER.snapshot(top_n=8)
    composition = {
        "profile_role_samples": {role: r["samples"]
                                 for role, r in snap["roles"].items()},
        "profile_top_frames": snap["top_frames"][:8],
        "cpu_attr": CPU_ATTR.summary(),
    }
    print(json.dumps({"composition": composition["profile_role_samples"]}))

    doc = {
        "bench": "benchmarks/bench_profile_overhead.py",
        "profile_hz": PROFILE_HZ,
        "sample_tick_us": round(tick_us, 1),
        "amortized_cpu_pct": round(amortized_pct, 4),
        "modes": results,
        "overall_p50_delta_pct": overhead_pct,
        "composition": composition,
        # Signed: negative = measured faster than off (noise); the
        # bench-trend tripwire judges *_pct headlines in absolute
        # points, so a clamped 0 would hide a later real regression.
        # The headline is the paired-round median — the overall p50
        # delta is the more drift-contaminated estimator and stays in
        # the body as context.
        "headline": {
            "profile_overhead_pct": paired_median_pct,
        },
    }
    print("BENCH_DOC " + json.dumps(doc))

    engine.stop()
    master.stop()

    gate = float(os.environ.get("PROFILE_GATE_PCT", "1.0"))
    if min(overhead_pct, paired_median_pct) > gate:
        print(f"FAIL: profiler overhead {overhead_pct}% (paired "
              f"{paired_median_pct}%) exceeds the {gate}% gate")
        sys.exit(1)
    print(f"OK: profiler overhead {overhead_pct}% (paired "
          f"{paired_median_pct}%) within the {gate}% gate")


if __name__ == "__main__":
    main()
