"""Master hot-path micro-benchmark: isolates the master+wire span.

BASELINE round 5 measured the master+wire TTFT span at 69-80 ms flat
across load — 35-40% of the north-star p50 TTFT < 200 ms budget — but
serve_bench can only derive it by subtraction (client TTFT minus the
agent's accept→first-delta span), with real engine compute adding noise.
This bench removes the engine entirely: a deployment-shaped multiproc
stack (coordination server, master, fake engine — each its own OS
process) where the fake engine replies instantly, so client TTFT ~=
frontend parse + schedule (template/tokenize/route/bind) + dispatch wire
+ token-return wire + SSE emit. That IS the master+wire span, measured
directly per stage.

Per-stage attribution comes from the master's ``GET /admin/hotpath``
(schedule / enrich / forward / first_delta p50s, recorded by the service
with two perf_counter reads per stage — always on, no tracing needed).
On trees without the endpoint (pre-PR-4) the bench still reports client
percentiles, so before/after comparisons run the same driver.

    python benchmarks/master_hotpath_bench.py --requests 256 --concurrency 8

``--masters N`` spawns an active-active multi-master plane (every process
an active frontend, the first holds the write lease; multimaster/) and
spreads the driver's workers across the frontends — the multi-master
rps-scaling run. The report then carries per-frontend ownership/mining
stats and per-process CPU attribution over the drive window (on a small
box aggregate rps saturates on total CPU, so the scaling evidence is
each of N masters doing ~1/N of the frontend work at a constant
master-CPU-ms-per-request).

ISSUE 15 additions:

- ``--heartbeat-storm M`` registers M simulated (non-schedulable)
  instances and heartbeats them from driver threads at ``--storm-hz``
  each for the whole drive window — the telemetry-ingest load the
  sharded plane exists to spread. Per-master ingest/route/stream CPU
  attribution (the service's thread_time buckets, /admin/hotpath "cpu")
  is sampled around the drive, so the report shows each master's ingest
  CPU share directly.
- ``--telemetry-mode shard|master`` flips the service plane between
  sharded rendezvous-owned ingest (engines in "mux": ONE multiplexed
  keepalive session each) and the legacy elected-master funnel — the
  baseline the ≥2× ingest-share cut is measured against.
- ``--traffic diurnal|burst`` drives a time-varying open-loop schedule
  (sinusoidal day-curve / square-wave bursts on top of ``--rps``) for
  the CAR-vs-SLO-vs-RR heterogeneous-mix comparison.

The tier-1 budget test (tests/test_master_hotpath_budget.py) runs
``run_bench`` with a small workload and a generous ceiling to catch
order-of-magnitude regressions without flaking on CI noise.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import statistics
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import requests


def percentile(xs, p):
    if not xs:
        return 0.0
    xs = sorted(xs)
    k = min(len(xs) - 1, int(round((p / 100) * (len(xs) - 1))))
    return xs[k]


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _proc_cpu_s(pid: int) -> float:
    """utime+stime of one process in seconds (0.0 if unreadable)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            parts = f.read().split()
        return (int(parts[13]) + int(parts[14])) / os.sysconf("SC_CLK_TCK")
    except (OSError, ValueError, IndexError):
        return 0.0


def _due_offsets(n: int, args) -> "list[float]":
    """Open-loop due times (seconds from pace start) for request k=0..n-1
    under the selected traffic shape. steady = constant --rps;
    diurnal = sinusoidal rate swing (amplitude --diurnal-amp around the
    base, period --diurnal-period); burst = --burst-mult x the base rate
    for --burst-len out of every --burst-every seconds. Time-varying
    schedules integrate 1/rate(t) stepwise so the OFFERED rate follows
    the profile exactly."""
    base = getattr(args, "rps", 0.0) or 0.0
    mode = getattr(args, "traffic", "steady")
    if base <= 0 or mode == "steady":
        return [k / base if base > 0 else 0.0 for k in range(n)]
    import math

    offsets: list[float] = []
    t = 0.0
    for _ in range(n):
        offsets.append(t)
        if mode == "diurnal":
            amp = min(0.95, max(0.0, getattr(args, "diurnal_amp", 0.6)))
            period = max(1.0, getattr(args, "diurnal_period", 20.0))
            rate = base * (1.0 + amp * math.sin(2 * math.pi * t / period))
        else:   # burst
            every = max(1.0, getattr(args, "burst_every", 10.0))
            blen = min(every, max(0.1, getattr(args, "burst_len", 2.0)))
            mult = max(1.0, getattr(args, "burst_mult", 4.0))
            # Off-window rate compensates so the MEAN offered rate stays
            # at the base (bursts test absorption, not extra volume).
            off_rate = base * max(0.1, (every - blen * mult)
                                  / max(0.1, every - blen))
            rate = base * mult if (t % every) < blen else off_rate
        t += 1.0 / max(0.1, rate)
    return offsets


class HeartbeatStorm:
    """Driver-side heartbeat storm: M simulated instances (DEFAULT role,
    draining=True so they never enter routing) registered in
    coordination with kept-alive leases, heartbeating at ``hz`` each.
    Destination: the rendezvous telemetry owner (shard mode — resolved
    from the mirrored SERVICE membership, like a real engine) or the
    elected master (the legacy-funnel baseline)."""

    def __init__(self, coord, n: int, hz: float, mode: str,
                 workers: int = 8):
        self.coord = coord
        self.n = n
        self.hz = max(0.1, hz)
        self.mode = mode
        self.names = [f"127.1.{i // 250}.{1 + i % 250}:9"
                      for i in range(n)]
        self.sent = 0
        self.errors = 0
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._workers = max(1, min(workers, n))
        self._members: list[str] = []
        self._master = ""

    def start(self) -> "HeartbeatStorm":
        import uuid

        from xllm_service_tpu.common.types import (InstanceMetaInfo,
                                                   InstanceType)
        from xllm_service_tpu.rpc import instance_key

        for name in self.names:
            meta = InstanceMetaInfo(
                name=name, rpc_address=name, type=InstanceType.DEFAULT,
                draining=True, incarnation_id=uuid.uuid4().hex[:12],
                models=["fake-model"])
            self.coord.set(instance_key("DEFAULT", name), meta.to_json(),
                           ttl_s=10.0)
        t = threading.Thread(target=self._membership_loop, daemon=True)
        t.start()
        self._threads.append(t)
        self._refresh_membership()
        for w in range(self._workers):
            t = threading.Thread(target=self._worker, args=(w,),
                                 daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def _refresh_membership(self) -> None:
        from xllm_service_tpu.rpc import MASTER_KEY, SERVICE_KEY_PREFIX

        try:
            self._members = [
                k[len(SERVICE_KEY_PREFIX):]
                for k in self.coord.get_prefix(SERVICE_KEY_PREFIX)
                if k != MASTER_KEY]
            self._master = self.coord.get(MASTER_KEY) or ""
        except Exception:  # noqa: BLE001 — next refresh retries
            pass

    def _membership_loop(self) -> None:
        while not self._stop.wait(1.0):
            self._refresh_membership()

    def _worker(self, w: int) -> None:
        import requests as _rq

        from xllm_service_tpu.multimaster import telemetry_owner
        from xllm_service_tpu.rpc import wire as _wire

        session = _rq.Session()
        session.mount("http://", _rq.adapters.HTTPAdapter(
            pool_connections=8, pool_maxsize=8))
        mine = self.names[w::self._workers]
        interval = 1.0 / self.hz
        while not self._stop.is_set():
            t0 = time.monotonic()
            for i, name in enumerate(mine):
                if self._stop.is_set():
                    return
                if self.mode == "shard":
                    target = telemetry_owner(self._members, name) \
                        or self._master
                else:
                    target = self._master
                if not target:
                    continue
                payload = {
                    "name": name, "incarnation_id": "",
                    "load_metrics": {
                        "waiting_requests_num": i % 5,
                        "running_requests_num": i % 3,
                        "hbm_cache_usage_perc": 0.2,
                    },
                    "latency_metrics": {"recent_max_ttft": 20.0,
                                        "recent_max_tbt": 5.0},
                }
                body, ctype = _wire.encode_dispatch(payload,
                                                    _wire.WIRE_MSGPACK)
                try:
                    session.post(f"http://{target}/rpc/heartbeat",
                                 data=body,
                                 headers={"Content-Type": ctype},
                                 timeout=3)
                    self.sent += 1
                except _rq.RequestException:
                    self.errors += 1
            # Pace the sweep so each instance beats at ~hz.
            elapsed = time.monotonic() - t0
            if elapsed < interval:
                time.sleep(interval - elapsed)

    def stop(self) -> dict:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        return {"instances": self.n, "hz": self.hz, "mode": self.mode,
                "beats_sent": self.sent, "errors": self.errors}


def _admin_cpu(base: str) -> dict:
    """One master's /admin/hotpath cpu + telemetry sections ({} when
    unreachable)."""
    try:
        r = requests.get(base + "/admin/hotpath", timeout=5)
        if r.status_code != 200:
            return {}
        payload = r.json()
        return {"cpu": payload.get("cpu", {}),
                "telemetry": {k: v for k, v in
                              (payload.get("telemetry") or {}).items()
                              if k != "load_info_ages_s"}}
    except requests.RequestException:
        return {}


def _engine_telemetry(coord) -> "list[dict]":
    """Scrape every registered engine's /metrics for the telemetry
    connection counters (the O(engines) fan-out evidence)."""
    from xllm_service_tpu.rpc import INSTANCE_KEY_PREFIX, parse_instance_key

    out = []
    for key in coord.get_prefix(INSTANCE_KEY_PREFIX):
        _t, name = parse_instance_key(key)
        if name.endswith(":9"):
            continue   # storm instances have no HTTP surface
        try:
            r = requests.get(f"http://{name}/metrics", timeout=3)
        except requests.RequestException:
            continue
        row = {"engine": name}
        for line in r.text.splitlines():
            if line.startswith("engine_telemetry_"):
                k, _, v = line.rpartition(" ")
                try:
                    row[k.replace("engine_telemetry_", "")] = float(v)
                except ValueError:
                    pass
        out.append(row)
    return out


# ~1 KiB prompt -> 1024 token ids through the byte-level SimpleTokenizer:
# the enriched dispatch payload carries a multi-thousand-byte token_ids
# list, which is exactly the wire cost this bench exists to attribute.
_PROMPT_WORD = "hotpath "


def _make_prompt(n_chars: int) -> str:
    return (_PROMPT_WORD * (n_chars // len(_PROMPT_WORD) + 1))[:n_chars]


def drive(base, args) -> dict:
    """Fire the streaming workload at the master(s) and collect
    client-side TTFT/E2E percentiles plus the per-stage span table.
    `base` may be one URL or a list (multi-master: workers spread
    round-robin across the active frontends)."""
    bases = [base] if isinstance(base, str) else list(base)
    prompt = _make_prompt(args.prompt_chars)
    # Heterogeneous mix (the CAR-default soak): every request gets a
    # UNIQUE prompt (index prefix changes block 0, so the whole hash
    # chain differs -> zero prefix overlap, CAR's worst case) at one of
    # three lengths. Identical-prompt mode (default) is the cache-hot
    # best case.
    distinct = bool(getattr(args, "distinct_prompts", False))

    def prompt_for(k: int) -> str:
        if not distinct:
            return prompt
        n = (args.prompt_chars // 2, args.prompt_chars,
             args.prompt_chars * 2)[k % 3]
        return f"{k:08d}" + _make_prompt(n - 8)

    # Warmup: prime connection pools, lazy imports, the schedule executor.
    for b in bases:
        for _ in range(4):
            requests.post(b + "/v1/completions", json={
                "model": "fake-model", "prompt": prompt, "max_tokens": 4,
                "stream": True}, timeout=30).close()

    ttfts, e2es, errors = [], [], [0]
    lock = threading.Lock()
    work = list(range(args.requests))
    rps = getattr(args, "rps", 0.0) or 0.0
    # Precomputed open-loop schedule (steady constant-rate, or the
    # diurnal/burst profile): slot j = offsets[j] seconds after start.
    offsets = _due_offsets(args.requests, args) if rps > 0 else None
    pace_start = time.perf_counter() + 0.05

    def worker(wbase):
        session = requests.Session()
        base = wbase
        while True:
            with lock:
                if not work:
                    return
                k = work.pop()
            if rps > 0:
                # Paced (open-loop) mode: request k is DUE at a fixed wall
                # slot, and latency is measured from the slot, not from
                # the actual send — a tree that can't keep up accrues the
                # queueing delay instead of hiding it (coordinated
                # omission). k counts down; slots are order-insensitive.
                due = pace_start + offsets[args.requests - 1 - k]
                now = time.perf_counter()
                if due > now:
                    time.sleep(due - now)
                t0 = due
            else:
                t0 = time.perf_counter()
            try:
                r = session.post(base + "/v1/completions", json={
                    "model": "fake-model", "prompt": prompt_for(k),
                    "max_tokens": args.max_tokens, "stream": True},
                    stream=True, timeout=60)
                ttft = None
                for line in r.iter_lines():
                    if not line.startswith(b"data: "):
                        continue
                    if ttft is None:
                        ttft = time.perf_counter() - t0
                    if line == b"data: [DONE]":
                        break
                e2e = time.perf_counter() - t0
                if ttft is None:
                    raise RuntimeError("stream produced no deltas")
                with lock:
                    ttfts.append(ttft * 1000)
                    e2es.append(e2e * 1000)
            except Exception:  # noqa: BLE001 — counted, not fatal
                with lock:
                    errors[0] += 1

    t_start = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(bases[i % len(bases)],))
               for i in range(args.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start

    report = {
        "requests": args.requests,
        "masters": len(bases),
        "concurrency": args.concurrency,
        "prompt_chars": args.prompt_chars,
        "max_tokens": args.max_tokens,
        "offered_rps": rps or None,
        "errors": errors[0],
        "req_per_s": round(len(e2es) / wall, 1) if wall else 0.0,
        "master_wire_ttft_ms": {
            "p50": round(percentile(ttfts, 50), 2),
            "p90": round(percentile(ttfts, 90), 2),
            "p99": round(percentile(ttfts, 99), 2),
            "mean": round(statistics.mean(ttfts), 2) if ttfts else 0.0,
        },
        "e2e_ms": {"p50": round(percentile(e2es, 50), 2),
                   "p99": round(percentile(e2es, 99), 2)},
    }
    # Per-stage master span table (absent on pre-PR-4 trees: the client
    # percentiles above still make the before/after comparison). Multi-
    # master: the first frontend's table is representative (workers are
    # spread evenly); ownership stats show mining hit rate per master.
    try:
        r = requests.get(bases[0] + "/admin/hotpath", timeout=5)
        if r.status_code == 200:
            payload = r.json()
            report["master_stages_ms"] = payload.get("stages", {})
            if payload.get("ownership"):
                report["ownership"] = payload["ownership"]
    except requests.RequestException:
        pass
    if len(bases) > 1:
        # Per-frontend ownership/mining stats: the acceptance story needs
        # the handoff rate (mined-to-self accepts pay no forward hop).
        per_master = []
        for b in bases:
            try:
                r = requests.get(b + "/admin/hotpath", timeout=5)
                per_master.append(r.json().get("ownership", {})
                                  if r.status_code == 200 else {})
            except requests.RequestException:
                per_master.append({})
        report["ownership_per_master"] = per_master
    return report


def run_bench(requests_n: int = 256, concurrency: int = 8,
              prompt_chars: int = 1024, max_tokens: int = 16,
              reply_chars: int = 64, rps: float = 0.0,
              policy: str = "RR", n_engines: int = 1,
              n_masters: int = 1,
              master_args: tuple = (),
              distinct_prompts: bool = False,
              telemetry_mode: str = "shard",
              heartbeat_storm: int = 0, storm_hz: float = 2.0,
              traffic: str = "steady", diurnal_period: float = 20.0,
              diurnal_amp: float = 0.6, burst_every: float = 10.0,
              burst_len: float = 2.0, burst_mult: float = 4.0) -> dict:
    """Spawn the multiproc stack, drive it, tear it down. Importable for
    the tier-1 budget test. ``policy`` selects the master's load-balance
    policy (RR | CAR | SLO_AWARE) — the kvcache routing bench drives the
    same harness under RR and CAR to price cache-aware routing on the
    schedule path; ``n_engines`` > 1 gives the policy a real choice.
    ``n_masters`` > 1 spawns an active-active multi-master service plane
    (every process an active frontend; the first wins the election and
    carries the write lease) and the driver spreads its workers evenly
    across the frontends — the multi-master rps-scaling acceptance run."""
    n_masters = max(1, n_masters)
    args = argparse.Namespace(
        requests=requests_n, concurrency=concurrency,
        prompt_chars=prompt_chars, max_tokens=max_tokens, rps=rps,
        distinct_prompts=distinct_prompts, traffic=traffic,
        diurnal_period=diurnal_period, diurnal_amp=diurnal_amp,
        burst_every=burst_every, burst_len=burst_len,
        burst_mult=burst_mult)
    coord_port = free_port()
    http_ports = [free_port() for _ in range(n_masters)]
    rpc_ports = [free_port() for _ in range(n_masters)]
    procs: list[subprocess.Popen] = []
    names: list[str] = []
    logdir = Path(os.environ.get("XLLM_BENCH_LOGDIR", "/tmp"))
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def spawn(name, cmd):
        log = open(logdir / f"hotpath_bench_{name}.log", "w")
        p = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                             cwd=str(REPO), env=env)
        procs.append(p)
        names.append(name)
        return p

    try:
        spawn("coord", [sys.executable, "-m",
                        "xllm_service_tpu.coordination.server",
                        "--port", str(coord_port)])
        time.sleep(0.3)
        for i in range(n_masters):
            spawn(f"master{i}",
                  [sys.executable, "-m", "xllm_service_tpu.master",
                   "--coordination-addr", f"127.0.0.1:{coord_port}",
                   "--host", "127.0.0.1",
                   "--http-port", str(http_ports[i]),
                   "--rpc-port", str(rpc_ports[i]),
                   "--load-balance-policy", policy,
                   "--telemetry-ingest-mode", telemetry_mode,
                   *master_args])
            if i == 0 and n_masters > 1:
                # Let master0 win the election deterministically so the
                # write lease (frames, LOADMETRICS, planner) sits on a
                # known process for the whole run.
                time.sleep(0.5)
        # Engines mirror the service-plane mode: multiplexed owner-routed
        # telemetry under sharding, the legacy elected-master funnel for
        # the baseline.
        engine_telemetry = "mux" if telemetry_mode == "shard" else "master"
        for i in range(max(1, n_engines)):
            spawn(f"engine{i}", [sys.executable,
                                 str(REPO / "examples" / "run_fake_engine.py"),
                                 "--coordination-addr",
                                 f"127.0.0.1:{coord_port}",
                                 "--reply", "x" * reply_chars,
                                 "--chunk-size", "4", "--delay", "0",
                                 "--telemetry-mode", engine_telemetry])

        bases = [f"http://127.0.0.1:{p}" for p in http_ports]
        deadline = time.monotonic() + 60
        ready: set[str] = set()
        while time.monotonic() < deadline:
            for name, p in zip(names, procs):
                if p.poll() is not None:
                    raise RuntimeError(
                        f"{name} process died rc={p.returncode} — see "
                        f"{logdir}/hotpath_bench_{name}.log")
            for base in bases:
                if base in ready:
                    continue
                try:
                    r = requests.post(base + "/v1/completions", json={
                        "model": "fake-model", "prompt": "ready?",
                        "max_tokens": 2}, timeout=10)
                    if r.status_code == 200:
                        ready.add(base)
                except requests.RequestException:
                    pass
            if len(ready) == len(bases):
                break
            time.sleep(0.25)
        else:
            raise RuntimeError(
                f"cluster never became ready ({len(ready)}/{len(bases)} "
                f"frontends serving)")
        storm = None
        coord = None
        if heartbeat_storm > 0:
            from xllm_service_tpu.coordination import connect
            coord = connect(f"127.0.0.1:{coord_port}")
            storm = HeartbeatStorm(coord, heartbeat_storm, storm_hz,
                                   telemetry_mode).start()
            # Let the fleet register the storm instances before driving.
            time.sleep(2.0)
        cpu0 = {n: _proc_cpu_s(p.pid) for n, p in zip(names, procs)}
        attr0 = {f"master{i}": _admin_cpu(b) for i, b in enumerate(bases)}
        report = drive(bases if n_masters > 1 else bases[0], args)
        # Per-process CPU attribution over the drive window: on a small
        # box the aggregate rps saturates on TOTAL cpu, so the scaling
        # evidence is each of N masters doing ~1/N of the frontend work
        # (master CPU-ms per request ~constant while per-master share
        # drops near-linearly).
        cpu = {n: round(_proc_cpu_s(p.pid) - cpu0[n], 2)
               for n, p in zip(names, procs)}
        report["cpu_s_during_drive"] = cpu
        # Per-master ingest/route/stream CPU buckets over the drive
        # (thread_time measured inside the handlers) and each bucket's
        # share of the process's total CPU — the ISSUE-15 acceptance
        # number is the ELECTED master's ingest share, sharded vs not.
        attr: dict = {}
        for i, b in enumerate(bases):
            name = f"master{i}"
            after = _admin_cpu(b)
            buckets = {}
            for cat, row in (after.get("cpu") or {}).items():
                before = ((attr0.get(name) or {}).get("cpu") or {}) \
                    .get(cat, {})
                cpu_s = round(row.get("cpu_s", 0.0)
                              - before.get("cpu_s", 0.0), 3)
                total = max(1e-9, cpu.get(name, 0.0))
                buckets[cat] = {
                    "cpu_s": cpu_s,
                    "share_of_proc": round(cpu_s / total, 4),
                    "n": row.get("n", 0) - before.get("n", 0),
                }
            attr[name] = {"buckets": buckets,
                          "telemetry": after.get("telemetry", {})}
        report["master_cpu_attribution"] = attr
        if storm is not None:
            report["heartbeat_storm"] = storm.stop()
        if coord is not None:
            report["engine_telemetry"] = _engine_telemetry(coord)
            coord.close()
        served = max(1, args.requests - report.get("errors", 0))
        master_cpu = sum(v for n, v in cpu.items() if n.startswith("master"))
        report["master_cpu_ms_per_request"] = round(
            master_cpu * 1000.0 / served, 2)
        report["policy"] = policy
        report["n_engines"] = max(1, n_engines)
        report["telemetry_mode"] = telemetry_mode
        report["traffic"] = traffic
        return report
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=256)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--prompt-chars", type=int, default=1024,
                    help="prompt length in bytes (byte-level tokenizer: "
                         "== token_ids length on the dispatch wire)")
    ap.add_argument("--max-tokens", type=int, default=16)
    ap.add_argument("--reply-chars", type=int, default=64)
    ap.add_argument("--rps", type=float, default=0.0,
                    help="paced open-loop request rate (0 = closed-loop); "
                         "paced TTFT is measured from the request's due "
                         "slot, so queueing delay is counted, not hidden")
    ap.add_argument("--policy", default="RR",
                    help="master load-balance policy (RR | CAR | SLO_AWARE)")
    ap.add_argument("--engines", type=int, default=1,
                    help="fake engine instances (give CAR a real choice)")
    ap.add_argument("--masters", type=int, default=1,
                    help="active frontends (multi-master service plane); "
                         "workers are spread evenly across them")
    ap.add_argument("--distinct-prompts", action="store_true",
                    help="unique prompt per request at 3 lengths (zero "
                         "prefix overlap — the heterogeneous-mix soak for "
                         "the CAR default)")
    ap.add_argument("--telemetry-mode", default="shard",
                    choices=["shard", "master"],
                    help="shard = rendezvous-owned heartbeat ingest + "
                         "multiplexed engine sessions (default); master "
                         "= legacy elected-master funnel (the ingest-"
                         "share baseline)")
    ap.add_argument("--heartbeat-storm", type=int, default=0,
                    help="register this many simulated instances and "
                         "heartbeat them from the driver for the whole "
                         "drive window (the telemetry-ingest load)")
    ap.add_argument("--storm-hz", type=float, default=2.0,
                    help="heartbeats per second per storm instance")
    ap.add_argument("--traffic", default="steady",
                    choices=["steady", "diurnal", "burst"],
                    help="open-loop schedule shape on top of --rps")
    ap.add_argument("--diurnal-period", type=float, default=20.0)
    ap.add_argument("--diurnal-amp", type=float, default=0.6)
    ap.add_argument("--burst-every", type=float, default=10.0)
    ap.add_argument("--burst-len", type=float, default=2.0)
    ap.add_argument("--burst-mult", type=float, default=4.0)
    args = ap.parse_args()
    report = run_bench(args.requests, args.concurrency, args.prompt_chars,
                       args.max_tokens, args.reply_chars, args.rps,
                       policy=args.policy, n_engines=args.engines,
                       n_masters=args.masters,
                       distinct_prompts=args.distinct_prompts,
                       telemetry_mode=args.telemetry_mode,
                       heartbeat_storm=args.heartbeat_storm,
                       storm_hz=args.storm_hz,
                       traffic=args.traffic,
                       diurnal_period=args.diurnal_period,
                       diurnal_amp=args.diurnal_amp,
                       burst_every=args.burst_every,
                       burst_len=args.burst_len,
                       burst_mult=args.burst_mult)
    report["distinct_prompts"] = args.distinct_prompts
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
