"""Master hot-path micro-benchmark: isolates the master+wire span.

BASELINE round 5 measured the master+wire TTFT span at 69-80 ms flat
across load — 35-40% of the north-star p50 TTFT < 200 ms budget — but
serve_bench can only derive it by subtraction (client TTFT minus the
agent's accept→first-delta span), with real engine compute adding noise.
This bench removes the engine entirely: a deployment-shaped multiproc
stack (coordination server, master, fake engine — each its own OS
process) where the fake engine replies instantly, so client TTFT ~=
frontend parse + schedule (template/tokenize/route/bind) + dispatch wire
+ token-return wire + SSE emit. That IS the master+wire span, measured
directly per stage.

Per-stage attribution comes from the master's ``GET /admin/hotpath``
(schedule / enrich / forward / first_delta p50s, recorded by the service
with two perf_counter reads per stage — always on, no tracing needed).
On trees without the endpoint (pre-PR-4) the bench still reports client
percentiles, so before/after comparisons run the same driver.

    python benchmarks/master_hotpath_bench.py --requests 256 --concurrency 8

The tier-1 budget test (tests/test_master_hotpath_budget.py) runs
``run_bench`` with a small workload and a generous ceiling to catch
order-of-magnitude regressions without flaking on CI noise.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import statistics
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import requests


def percentile(xs, p):
    if not xs:
        return 0.0
    xs = sorted(xs)
    k = min(len(xs) - 1, int(round((p / 100) * (len(xs) - 1))))
    return xs[k]


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ~1 KiB prompt -> 1024 token ids through the byte-level SimpleTokenizer:
# the enriched dispatch payload carries a multi-thousand-byte token_ids
# list, which is exactly the wire cost this bench exists to attribute.
_PROMPT_WORD = "hotpath "


def _make_prompt(n_chars: int) -> str:
    return (_PROMPT_WORD * (n_chars // len(_PROMPT_WORD) + 1))[:n_chars]


def drive(base: str, args) -> dict:
    """Fire the streaming workload at the master and collect client-side
    TTFT/E2E percentiles plus the master's per-stage span table."""
    prompt = _make_prompt(args.prompt_chars)

    # Warmup: prime connection pools, lazy imports, the schedule executor.
    for _ in range(4):
        requests.post(base + "/v1/completions", json={
            "model": "fake-model", "prompt": prompt, "max_tokens": 4,
            "stream": True}, timeout=30).close()

    ttfts, e2es, errors = [], [], [0]
    lock = threading.Lock()
    work = list(range(args.requests))
    rps = getattr(args, "rps", 0.0) or 0.0
    pace_start = time.perf_counter() + 0.05

    def worker():
        session = requests.Session()
        while True:
            with lock:
                if not work:
                    return
                k = work.pop()
            if rps > 0:
                # Paced (open-loop) mode: request k is DUE at a fixed wall
                # slot, and latency is measured from the slot, not from
                # the actual send — a tree that can't keep up accrues the
                # queueing delay instead of hiding it (coordinated
                # omission). k counts down; slots are order-insensitive.
                due = pace_start + (args.requests - 1 - k) / rps
                now = time.perf_counter()
                if due > now:
                    time.sleep(due - now)
                t0 = due
            else:
                t0 = time.perf_counter()
            try:
                r = session.post(base + "/v1/completions", json={
                    "model": "fake-model", "prompt": prompt,
                    "max_tokens": args.max_tokens, "stream": True},
                    stream=True, timeout=60)
                ttft = None
                for line in r.iter_lines():
                    if not line.startswith(b"data: "):
                        continue
                    if ttft is None:
                        ttft = time.perf_counter() - t0
                    if line == b"data: [DONE]":
                        break
                e2e = time.perf_counter() - t0
                if ttft is None:
                    raise RuntimeError("stream produced no deltas")
                with lock:
                    ttfts.append(ttft * 1000)
                    e2es.append(e2e * 1000)
            except Exception:  # noqa: BLE001 — counted, not fatal
                with lock:
                    errors[0] += 1

    t_start = time.perf_counter()
    threads = [threading.Thread(target=worker)
               for _ in range(args.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start

    report = {
        "requests": args.requests,
        "concurrency": args.concurrency,
        "prompt_chars": args.prompt_chars,
        "max_tokens": args.max_tokens,
        "offered_rps": rps or None,
        "errors": errors[0],
        "req_per_s": round(len(e2es) / wall, 1) if wall else 0.0,
        "master_wire_ttft_ms": {
            "p50": round(percentile(ttfts, 50), 2),
            "p90": round(percentile(ttfts, 90), 2),
            "p99": round(percentile(ttfts, 99), 2),
            "mean": round(statistics.mean(ttfts), 2) if ttfts else 0.0,
        },
        "e2e_ms": {"p50": round(percentile(e2es, 50), 2),
                   "p99": round(percentile(e2es, 99), 2)},
    }
    # Per-stage master span table (absent on pre-PR-4 trees: the client
    # percentiles above still make the before/after comparison).
    try:
        r = requests.get(base + "/admin/hotpath", timeout=5)
        if r.status_code == 200:
            report["master_stages_ms"] = r.json().get("stages", {})
    except requests.RequestException:
        pass
    return report


def run_bench(requests_n: int = 256, concurrency: int = 8,
              prompt_chars: int = 1024, max_tokens: int = 16,
              reply_chars: int = 64, rps: float = 0.0,
              policy: str = "RR", n_engines: int = 1) -> dict:
    """Spawn the multiproc stack, drive it, tear it down. Importable for
    the tier-1 budget test. ``policy`` selects the master's load-balance
    policy (RR | CAR | SLO_AWARE) — the kvcache routing bench drives the
    same harness under RR and CAR to price cache-aware routing on the
    schedule path; ``n_engines`` > 1 gives the policy a real choice."""
    args = argparse.Namespace(
        requests=requests_n, concurrency=concurrency,
        prompt_chars=prompt_chars, max_tokens=max_tokens, rps=rps)
    coord_port, http_port, rpc_port = free_port(), free_port(), free_port()
    procs: list[subprocess.Popen] = []
    names: list[str] = []
    logdir = Path(os.environ.get("XLLM_BENCH_LOGDIR", "/tmp"))
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def spawn(name, cmd):
        log = open(logdir / f"hotpath_bench_{name}.log", "w")
        p = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                             cwd=str(REPO), env=env)
        procs.append(p)
        names.append(name)
        return p

    try:
        spawn("coord", [sys.executable, "-m",
                        "xllm_service_tpu.coordination.server",
                        "--port", str(coord_port)])
        time.sleep(0.3)
        spawn("master", [sys.executable, "-m", "xllm_service_tpu.master",
                         "--coordination-addr", f"127.0.0.1:{coord_port}",
                         "--host", "127.0.0.1",
                         "--http-port", str(http_port),
                         "--rpc-port", str(rpc_port),
                         "--load-balance-policy", policy])
        for i in range(max(1, n_engines)):
            spawn(f"engine{i}", [sys.executable,
                                 str(REPO / "examples" / "run_fake_engine.py"),
                                 "--coordination-addr",
                                 f"127.0.0.1:{coord_port}",
                                 "--reply", "x" * reply_chars,
                                 "--chunk-size", "4", "--delay", "0"])

        base = f"http://127.0.0.1:{http_port}"
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            for name, p in zip(names, procs):
                if p.poll() is not None:
                    raise RuntimeError(
                        f"{name} process died rc={p.returncode} — see "
                        f"{logdir}/hotpath_bench_{name}.log")
            try:
                r = requests.post(base + "/v1/completions", json={
                    "model": "fake-model", "prompt": "ready?",
                    "max_tokens": 2}, timeout=10)
                if r.status_code == 200:
                    break
            except requests.RequestException:
                pass
            time.sleep(0.25)
        else:
            raise RuntimeError("fake-engine cluster never became ready")
        report = drive(base, args)
        report["policy"] = policy
        report["n_engines"] = max(1, n_engines)
        return report
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=256)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--prompt-chars", type=int, default=1024,
                    help="prompt length in bytes (byte-level tokenizer: "
                         "== token_ids length on the dispatch wire)")
    ap.add_argument("--max-tokens", type=int, default=16)
    ap.add_argument("--reply-chars", type=int, default=64)
    ap.add_argument("--rps", type=float, default=0.0,
                    help="paced open-loop request rate (0 = closed-loop); "
                         "paced TTFT is measured from the request's due "
                         "slot, so queueing delay is counted, not hidden")
    ap.add_argument("--policy", default="RR",
                    help="master load-balance policy (RR | CAR | SLO_AWARE)")
    ap.add_argument("--engines", type=int, default=1,
                    help="fake engine instances (give CAR a real choice)")
    args = ap.parse_args()
    report = run_bench(args.requests, args.concurrency, args.prompt_chars,
                       args.max_tokens, args.reply_chars, args.rps,
                       policy=args.policy, n_engines=args.engines)
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
