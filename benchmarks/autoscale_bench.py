"""Closed-loop autoscaling bench: burst -> breach -> scale-out -> recover.

The acceptance run for ISSUE 13's control plane (docs/autoscaling.md):
a deployment-shaped multiproc stack (coordination server, master with
the autoscaler enabled and the LOCAL process actuator, one initial
capacity-capped fake engine) is driven through a bursty workload:

  baseline (light)  ->  burst (overload)  ->  cooldown (light)

Each fake engine serializes accepts behind a blocking per-accept delay
(``--accept-delay``), capping it at ~1/delay requests per second — so
fleet capacity genuinely scales with instance count. Under the burst the
one-engine fleet queues, server-side TTFT blows through ``slo_ttft_ms``,
the burn-rate monitor (fast AND slow windows) crosses ``slo_burn_alert``,
and the controller scales out through the LocalProcessActuator — real
OS processes launched via examples/run_fake_engine.py. The bench then
asserts the loop CLOSED: burn rates return below the alert while the
burst is still running, and after the burst the controller drains the
extra engines back down with steady-state TTFT within a few percent of
the pre-burst baseline.

An interleaved STATIC control run (same stack, autoscaler off) proves
the counterfactual: without the controller the burst stays breached for
its whole duration.

The idle-overhead leg A/Bs a light closed-loop workload with the
controller on vs off — the decision loop runs on the sync thread, never
a request path, so the request-path cost must be ~0 (the ISSUE gate is
<= 1%, i.e. inside noise on this box).

    python benchmarks/autoscale_bench.py                # full run
    python benchmarks/autoscale_bench.py --quick        # CI-sized

Output: JSON report (see BENCH_autoscale_r12.json); headline keys are
bench_trend-tracked.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import requests


def percentile(xs, p):
    if not xs:
        return 0.0
    xs = sorted(xs)
    k = min(len(xs) - 1, int(round((p / 100) * (len(xs) - 1))))
    return xs[k]


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


SERVICE_RATE_RPS = 25.0         # per-engine capacity (deterministic model)
# Deep accept queue: overload manifests as queueing delay (the TTFT
# collapse the static control demonstrates), not fast 503s — the same
# shape the old blocking-accept hack produced.
ACCEPT_QUEUE = 512
REPLY_CHARS = 32


class Stack:
    """Coordination server + master + initial engine, each an OS
    process (the same shape as master_hotpath_bench)."""

    def __init__(self, autoscale: bool, args):
        self.args = args
        self.autoscale = autoscale
        self.procs: list[tuple[str, subprocess.Popen]] = []
        self.coord_port = free_port()
        self.http_port = free_port()
        self.rpc_port = free_port()
        self.logdir = Path(os.environ.get("XLLM_BENCH_LOGDIR", "/tmp"))
        self.env = dict(os.environ, JAX_PLATFORMS="cpu")

    def spawn(self, name, cmd):
        log = open(self.logdir / f"autoscale_bench_{name}.log", "w")
        p = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                             cwd=str(REPO), env=self.env)
        self.procs.append((name, p))
        return p

    def engine_cmd_template(self) -> str:
        # The fake engine's DETERMINISTIC capacity model (bounded accept
        # queue + service rate; ISSUE 14) replaced the old 40ms
        # blocking-accept hack — same ~25 req/s per engine, same
        # headline, reproducible queueing under overload.
        return (f"{sys.executable} {REPO}/examples/run_fake_engine.py "
                f"--coordination-addr {{coordination_addr}} "
                f"--port {{port}} --service-rate {SERVICE_RATE_RPS} "
                f"--accept-queue {ACCEPT_QUEUE} "
                f"--reply {'x' * REPLY_CHARS} --chunk-size 8 --delay 0")

    def start(self):
        a = self.args
        self.spawn("coord", [sys.executable, "-m",
                             "xllm_service_tpu.coordination.server",
                             "--port", str(self.coord_port)])
        time.sleep(0.3)
        master_cmd = [
            sys.executable, "-m", "xllm_service_tpu.master",
            "--coordination-addr", f"127.0.0.1:{self.coord_port}",
            "--host", "127.0.0.1",
            "--http-port", str(self.http_port),
            "--rpc-port", str(self.rpc_port),
            "--load-balance-policy", "RR",
            "--sync-interval-s", "0.5",
            "--slo-ttft-ms", str(a.slo_ttft_ms),
            "--slo-tpot-ms", "60000",
            "--slo-fast-window-s", str(a.fast_window_s),
            "--slo-slow-window-s", str(a.slow_window_s),
            "--slo-burn-alert", "14.4",
        ]
        if self.autoscale:
            master_cmd += [
                "--autoscaler-enabled",
                "--autoscaler-actuator", "local",
                "--autoscaler-min-instances", "1",
                "--autoscaler-max-instances", str(a.max_instances),
                "--autoscaler-breach-ticks", "2",
                "--autoscaler-idle-ticks", "4",
                "--autoscaler-scale-out-cooldown-s", "3",
                "--autoscaler-scale-in-cooldown-s", "5",
                "--autoscaler-stale-hold-s", "30",
                "--autoscaler-drain-grace-s", "0.5",
                "--autoscaler-spawn-cmd", self.engine_cmd_template(),
            ]
        self.spawn("master", master_cmd)
        # The initial engine: same capacity model as autoscaled ones.
        tmpl = self.engine_cmd_template()
        self.spawn("engine0", tmpl.format(
            coordination_addr=f"127.0.0.1:{self.coord_port}",
            port=free_port()).split())

        base = self.base()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            for name, p in self.procs:
                if p.poll() is not None:
                    raise RuntimeError(
                        f"{name} died rc={p.returncode} — see "
                        f"{self.logdir}/autoscale_bench_{name}.log")
            try:
                r = requests.post(base + "/v1/completions", json={
                    "model": "fake-model", "prompt": "ready?",
                    "max_tokens": 2}, timeout=5)
                if r.status_code == 200:
                    return
            except requests.RequestException:
                pass
            time.sleep(0.25)
        raise RuntimeError("stack never became ready")

    def base(self) -> str:
        return f"http://127.0.0.1:{self.http_port}"

    def stop(self):
        for _, p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for _, p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


class Sampler(threading.Thread):
    """1 Hz poll of /admin/slo + /admin/autoscaler -> timeline rows."""

    def __init__(self, base: str):
        super().__init__(daemon=True, name="bench-sampler")
        self.base = base
        self.rows: list[dict] = []
        # NB: not `_stop` — threading.Thread uses that name internally.
        self._halt = threading.Event()

    def run(self):
        t0 = time.monotonic()
        while not self._halt.wait(1.0):
            row = {"t_s": round(time.monotonic() - t0, 1)}
            try:
                slo = requests.get(self.base + "/admin/slo",
                                   timeout=3).json()
                # Worst objective per window — the controller's own view
                # (overload shows as TTFT collapse when requests queue,
                # as error_rate when a bounded engine queue 503s the
                # excess; either is a breach).
                objs = slo["objectives"].values()
                row["burn_fast"] = max(
                    o["fast"]["burn_rate"] for o in objs)
                row["burn_slow"] = max(
                    o["slow"]["burn_rate"] for o in objs)
                row["breaching"] = slo["breaching"]
            except (requests.RequestException, KeyError, ValueError):
                pass
            try:
                rep = requests.get(self.base + "/admin/autoscaler",
                                   timeout=3).json()
                row["desired"] = rep.get("state", {}).get("desired")
                if rep.get("decisions"):
                    row["live"] = rep["decisions"][0]["inputs"]["live"]
            except (requests.RequestException, ValueError):
                pass
            self.rows.append(row)

    def stop(self):
        self._halt.set()
        self.join(timeout=3)


def drive_phase(base: str, concurrency: int, duration_s: float,
                ttfts: list, lock: threading.Lock,
                rps: float = 0.0) -> None:
    """One traffic phase; client TTFTs (ms) appended to `ttfts`.

    Closed-loop (rps=0): `concurrency` workers stream requests
    back-to-back — arrival self-limits to fleet capacity (stable under
    overload, the recorded-artifact mode).

    Open-loop (rps>0): requests are DUE at fixed wall slots and TTFT is
    measured from the slot, not the actual send — a fleet that can't
    keep up accrues the queueing delay instead of hiding it
    (coordinated-omission-corrected, same scheme as
    master_hotpath_bench --rps). `concurrency` bounds the worker pool;
    when all workers are stuck behind an overloaded fleet the pacer
    falls behind its slots and the accrued lateness is charged to the
    requests that suffered it."""
    stop_at = time.monotonic() + duration_s
    slot = [0]

    def worker():
        session = requests.Session()
        while True:
            if rps > 0:
                with lock:
                    k = slot[0]
                    slot[0] += 1
                due = stop_at - duration_s + k / rps
                if due >= stop_at:
                    return
                now = time.monotonic()
                if due > now:
                    time.sleep(due - now)
                t0 = due
            else:
                if time.monotonic() >= stop_at:
                    return
                t0 = time.monotonic()
            try:
                r = session.post(base + "/v1/completions", json={
                    "model": "fake-model", "prompt": "autoscale bench",
                    "max_tokens": 8, "stream": True},
                    stream=True, timeout=120)
                ttft = None
                for line in r.iter_lines():
                    if ttft is None and line.startswith(b"data: "):
                        ttft = time.monotonic() - t0
                    if line == b"data: [DONE]":
                        break
                r.close()
                if ttft is not None:
                    with lock:
                        ttfts.append(ttft * 1000)
            except requests.RequestException:
                time.sleep(0.2)

    threads = [threading.Thread(target=worker) for _ in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def run_scenario(autoscale: bool, args) -> dict:
    stack = Stack(autoscale, args)
    stack.start()
    base = stack.base()
    sampler = Sampler(base)
    sampler.start()
    lock = threading.Lock()
    baseline: list = []
    burst: list = []
    cooldown: list = []
    try:
        drive_phase(base, args.light_concurrency, args.baseline_s,
                    baseline, lock, rps=args.light_rps)
        burst_start = len(sampler.rows)
        drive_phase(base, args.burst_concurrency, args.burst_s,
                    burst, lock, rps=args.burst_rps)
        burst_end = len(sampler.rows)
        drive_phase(base, args.light_concurrency, args.cooldown_s,
                    cooldown, lock, rps=args.light_rps)
        # Steady state = the tail of the cooldown phase.
        tail_n = max(1, len(cooldown) // 3)
        steady = cooldown[-tail_n:]
        burst_rows = sampler.rows[burst_start:burst_end] or [{}]
        end_row = burst_rows[-1]
        peak_live = max((r.get("live") or 1 for r in sampler.rows),
                        default=1)
        final_live = next((r.get("live") for r in reversed(sampler.rows)
                           if r.get("live") is not None), 1)
        return {
            "autoscale": autoscale,
            "baseline_ttft_p50_ms": round(percentile(baseline, 50), 1),
            "burst_ttft_p50_ms": round(percentile(burst, 50), 1),
            "burst_ttft_p99_ms": round(percentile(burst, 99), 1),
            "steady_ttft_p50_ms": round(percentile(steady, 50), 1),
            "requests": {"baseline": len(baseline), "burst": len(burst),
                         "cooldown": len(cooldown)},
            "burn_at_burst_end": {
                "fast": end_row.get("burn_fast"),
                "slow": end_row.get("burn_slow"),
                "breaching": end_row.get("breaching"),
            },
            "peak_live_instances": peak_live,
            "final_live_instances": final_live,
            "timeline": sampler.rows,
        }
    finally:
        sampler.stop()
        stack.stop()


def run_idle_overhead(args) -> dict:
    """A/B a light closed-loop workload with the controller on vs off.
    The decision loop never touches the request path; this prices the
    claim (expected: inside noise)."""
    p50s = {}
    for autoscale in (False, True):
        stack = Stack(autoscale, args)
        stack.start()
        try:
            lock = threading.Lock()
            ttfts: list = []
            drive_phase(stack.base(), 2, args.overhead_s, ttfts, lock)
            p50s["on" if autoscale else "off"] = percentile(ttfts, 50)
        finally:
            stack.stop()
    off, on = p50s["off"], p50s["on"]
    return {
        "ttft_p50_off_ms": round(off, 2),
        "ttft_p50_on_ms": round(on, 2),
        "delta_pct": round((on - off) / off * 100, 2) if off else 0.0,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized phases (functional, not publication)")
    ap.add_argument("--baseline-s", type=float, default=20.0)
    ap.add_argument("--burst-s", type=float, default=50.0)
    ap.add_argument("--cooldown-s", type=float, default=60.0)
    ap.add_argument("--overhead-s", type=float, default=20.0)
    ap.add_argument("--light-concurrency", type=int, default=2)
    ap.add_argument("--burst-concurrency", type=int, default=24)
    ap.add_argument("--light-rps", type=float, default=0.0,
                    help="paced open-loop rate for baseline/cooldown "
                         "phases (0 = closed-loop workers)")
    ap.add_argument("--burst-rps", type=float, default=0.0,
                    help="paced open-loop burst rate; TTFT measured from "
                         "the due slot (coordinated-omission-corrected). "
                         "0 = closed-loop burst (the recorded mode)")
    ap.add_argument("--max-instances", type=int, default=4)
    ap.add_argument("--slo-ttft-ms", type=float, default=300.0)
    ap.add_argument("--fast-window-s", type=float, default=8.0)
    ap.add_argument("--slow-window-s", type=float, default=16.0)
    ap.add_argument("--skip-static", action="store_true")
    ap.add_argument("--skip-overhead", action="store_true")
    args = ap.parse_args()
    if args.quick:
        args.baseline_s, args.burst_s, args.cooldown_s = 8.0, 25.0, 25.0
        args.overhead_s = 8.0

    print("== autoscaled run ==", file=sys.stderr)
    auto = run_scenario(True, args)
    static = None
    if not args.skip_static:
        print("== static control run ==", file=sys.stderr)
        static = run_scenario(False, args)
    overhead = None
    if not args.skip_overhead:
        print("== idle-overhead A/B ==", file=sys.stderr)
        overhead = run_idle_overhead(args)

    alert = 14.4
    auto_end = auto["burn_at_burst_end"]
    static_end = (static or {}).get("burn_at_burst_end", {})
    recovered = (auto_end["fast"] is not None
                 and auto_end["fast"] < alert
                 and auto_end["slow"] is not None
                 and auto_end["slow"] < alert)
    steady_delta_pct = (
        (auto["steady_ttft_p50_ms"] - auto["baseline_ttft_p50_ms"])
        / auto["baseline_ttft_p50_ms"] * 100
        if auto["baseline_ttft_p50_ms"] else 0.0)
    speedup = (round(static["burst_ttft_p50_ms"]
                     / auto["burst_ttft_p50_ms"], 2)
               if static and auto["burst_ttft_p50_ms"] else None)
    report = {
        "config": {
            "service_rate_rps": SERVICE_RATE_RPS,
            "accept_queue": ACCEPT_QUEUE,
            "slo_ttft_ms": args.slo_ttft_ms,
            "fast_window_s": args.fast_window_s,
            "slow_window_s": args.slow_window_s,
            "burst_concurrency": args.burst_concurrency,
            "light_concurrency": args.light_concurrency,
            "burst_rps": args.burst_rps or None,
            "light_rps": args.light_rps or None,
            "phases_s": [args.baseline_s, args.burst_s, args.cooldown_s],
            "max_instances": args.max_instances,
            "quick": args.quick,
        },
        "autoscaled": auto,
        "static": static,
        "idle_overhead": overhead,
        # The ISSUE acceptance evidence (not trend-tracked: burn rates at
        # a phase boundary are timing-noisy; the gate is the boolean).
        "acceptance": {
            "alert_burn_rate": alert,
            "autoscaled_burst_end_burn": auto_end,
            "static_burst_end_burn": static_end or None,
            "autoscaled_recovered_below_alert": bool(recovered),
            "static_stays_breached":
                (static_end.get("fast") is not None
                 and static_end["fast"] >= alert
                 and static_end["slow"] >= alert) if static else None,
            "peak_live_instances": auto["peak_live_instances"],
            "final_live_instances": auto["final_live_instances"],
        },
        # bench_trend-tracked (direction by suffix: _pct regress upward
        # in absolute points, bare ratios regress downward).
        "headline": {
            "burst_ttft_recovery_speedup": speedup,
            "steady_vs_baseline_ttft_delta_pct":
                round(steady_delta_pct, 2),
            "idle_overhead_ttft_delta_pct":
                (overhead or {}).get("delta_pct"),
        },
    }
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
