"""PD KV-handoff latency: device transfer path vs host msgpack path
(VERDICT r3 weak #4 / next-round #5).

The reference justifies its engine-side RDMA link negotiation with "KV
must never bounce through a host" (instance_mgr.cpp:1087-1113). Our
device path is the JAX transfer server (engine/kv_transfer.py); the
fallback is msgpack-over-HTTP with the blob inline (engine/agent.py
pack_handoff). This times BOTH at bench-1b KV shapes for 2k and 8k
contexts — per handoff, including the loopback HTTP hop the real
fallback pays — and prints one JSON line. The device path must win or
be demoted.
"""

from __future__ import annotations

import json
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from xllm_service_tpu.utils import pin_cpu_platform_if_requested

pin_cpu_platform_if_requested()


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from xllm_service_tpu.common.request import SamplingParams
    from xllm_service_tpu.engine.agent import pack_handoff, unpack_handoff
    from xllm_service_tpu.engine.engine import PrefillHandoff
    from xllm_service_tpu.engine.kv_transfer import KvTransferManager

    backend = jax.default_backend()
    on_accel = backend != "cpu"
    dev = jax.devices()[0]

    # bench-1b KV shapes: [L, 2, n_pages, n_kv, ps, hd].
    L, n_kv, ps, hd = (16, 8, 16, 128) if on_accel else (2, 2, 16, 32)
    dtype = jnp.bfloat16 if on_accel else jnp.float32
    ctxs = (2048, 8192) if on_accel else (256,)

    # Host-path receiver: the loopback HTTP hop the real fallback pays.
    received: dict = {}

    class _H(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers["Content-Length"])
            body = self.rfile.read(n)
            obj = unpack_handoff(body)
            # Decode side uploads the blob to its device (the cost the
            # device path exists to avoid).
            received["kv"] = jax.device_put(
                jnp.asarray(obj["kv_blob"]), dev)
            received["kv"].block_until_ready()
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"{}")

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    host_url = f"http://127.0.0.1:{srv.server_port}/rpc/kv_transfer"

    import requests

    mgr_p = KvTransferManager.create(dev)
    mgr_d = KvTransferManager.create(dev)

    result = {"backend": backend,
              "metric": "pd_handoff_ms_per_transfer", "unit": "ms",
              "device_transfer_available": mgr_p is not None}

    sampling = SamplingParams(max_tokens=16)
    for ctx in ctxs:
        n_pages = ctx // ps
        blob = jax.device_put(
            jax.random.normal(jax.random.PRNGKey(0),
                              (L, 2, n_pages, n_kv, ps, hd), dtype), dev)
        blob.block_until_ready()
        mb = blob.size * blob.dtype.itemsize / 1e6
        row: dict = {"ctx": ctx, "blob_mb": round(mb, 1)}

        # --- device path: offer + pull (device-to-device) ------------
        if mgr_p is not None and mgr_d is not None:
            try:
                times = []
                for i in range(5):
                    t0 = time.perf_counter()
                    desc = mgr_p.offer(f"bench-{ctx}-{i}", blob)
                    out = mgr_d.pull(desc)
                    out.block_until_ready()
                    mgr_p.release(desc["uuid"])
                    times.append(time.perf_counter() - t0)
                    del out
                row["device_ms"] = round(min(times) * 1e3, 2)
                row["device_gbps"] = round(mb / 1e3 / min(times), 2)
            except Exception as e:  # noqa: BLE001 — record, keep going
                row["device_error"] = f"{type(e).__name__}: {e}"[:300]

        # --- host path: pack (device_get+msgpack) → HTTP → unpack+put -
        h = PrefillHandoff(
            service_request_id=f"bench-{ctx}", request_id="r0",
            token_ids=list(range(ctx)), first_token=1,
            first_logprob=None, sampling=sampling, kv_blob=blob)
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            data = pack_handoff(h, "127.0.0.1:0")
            r = requests.post(host_url, data=data,
                              headers={"Content-Type":
                                       "application/msgpack"})
            assert r.status_code == 200
            times.append(time.perf_counter() - t0)
        row["host_ms"] = round(min(times) * 1e3, 2)
        row["host_gbps"] = round(mb / 1e3 / min(times), 2)
        if "device_ms" in row and row["device_ms"] > 0:
            row["device_speedup"] = round(row["host_ms"] / row["device_ms"],
                                          2)
        result[f"ctx_{ctx}"] = row
        del blob
        received.clear()

    srv.shutdown()
    # Headline value: device-path ms at the largest context measured.
    last = result.get(f"ctx_{ctxs[-1]}", {})
    result["value"] = last.get("device_ms", last.get("host_ms", 0.0))
    print(json.dumps(result))


if __name__ == "__main__":
    main()
