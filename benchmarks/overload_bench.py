"""Closed-loop overload drill: burst -> shed -> hold TTFT (ISSUE 14).

The acceptance run for the overload-hardening plane
(docs/robustness.md). A deployment-shaped multiproc stack (coordination
server, master, capacity-capped fake engines with the deterministic
service-rate model) is driven through a steady phase and then a burst
at ~4x fleet capacity, in three configurations:

- **shed**: admission control ON — the gate 429s the excess fast while
  ADMITTED requests keep a TTFT p50 within 1.5x of steady state, and
  shed responses complete in well under 50 ms p99,
- **noshed**: admission OFF (the PR-11 static-control shape) — the same
  burst queues unboundedly until BOTH SLO burn windows breach,
- **shed+autoscale**: admission ON + the closed-loop autoscaler with
  the local process actuator — the shed-rate signal (wired into the
  autoscaler kernel this PR) drives scale-out, and the shed rate decays
  to ~0 as the capacity arrives.

An idle-overhead A/B (light load, overload plane configured vs
default-off) prices the per-request cost of the deadline parse +
admission gate — the gate is <= 1%.

    python benchmarks/overload_bench.py            # full run
    python benchmarks/overload_bench.py --quick    # CI-sized

Output: JSON report (BENCH_overload_r15.json); headline keys are
bench_trend-tracked.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import requests


def percentile(xs, p):
    if not xs:
        return 0.0
    xs = sorted(xs)
    k = min(len(xs) - 1, int(round((p / 100) * (len(xs) - 1))))
    return xs[k]


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


SERVICE_RATE_RPS = 6.0        # per-engine capacity (deterministic model)
FIRST_DELTA_DELAY_S = 0.2     # simulated prefill: the TTFT floor
N_ENGINES = 2                 # steady fleet (shed/noshed legs)
REPLY_CHARS = 8


class Stack:
    """Coordination server + master + engines, each an OS process."""

    def __init__(self, args, admission_limit: int = 0,
                 autoscale: bool = False, n_engines: int = N_ENGINES,
                 deadline_ms: float = 0.0):
        self.args = args
        self.admission_limit = admission_limit
        self.autoscale = autoscale
        self.n_engines = n_engines
        self.deadline_ms = deadline_ms
        self.procs: list[tuple[str, subprocess.Popen]] = []
        self.coord_port = free_port()
        self.http_port = free_port()
        self.rpc_port = free_port()
        self.logdir = Path(os.environ.get("XLLM_BENCH_LOGDIR", "/tmp"))
        self.env = dict(os.environ, JAX_PLATFORMS="cpu")

    def spawn(self, name, cmd):
        log = open(self.logdir / f"overload_bench_{name}.log", "w")
        p = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                             cwd=str(REPO), env=self.env)
        self.procs.append((name, p))
        return p

    def engine_cmd_template(self) -> str:
        return (f"{sys.executable} {REPO}/examples/run_fake_engine.py "
                f"--coordination-addr {{coordination_addr}} "
                f"--port {{port}} --service-rate {SERVICE_RATE_RPS} "
                f"--accept-queue 512 "
                f"--first-delta-delay {FIRST_DELTA_DELAY_S} "
                f"--reply {'x' * REPLY_CHARS} --chunk-size 8 --delay 0")

    def start(self):
        a = self.args
        self.spawn("coord", [sys.executable, "-m",
                             "xllm_service_tpu.coordination.server",
                             "--port", str(self.coord_port)])
        time.sleep(0.3)
        master_cmd = [
            sys.executable, "-m", "xllm_service_tpu.master",
            "--coordination-addr", f"127.0.0.1:{self.coord_port}",
            "--host", "127.0.0.1",
            "--http-port", str(self.http_port),
            "--rpc-port", str(self.rpc_port),
            "--load-balance-policy", "RR",
            "--sync-interval-s", "0.5",
            "--slo-ttft-ms", str(a.slo_ttft_ms),
            "--slo-tpot-ms", "60000",
            "--slo-fast-window-s", str(a.fast_window_s),
            "--slo-slow-window-s", str(a.slow_window_s),
            "--slo-burn-alert", "14.4",
        ]
        if self.admission_limit:
            master_cmd += ["--admission-max-inflight-per-instance",
                           str(self.admission_limit)]
        if self.deadline_ms:
            master_cmd += ["--default-request-deadline-ms",
                           str(self.deadline_ms)]
        if self.autoscale:
            # Scale-OUT settings compressed for the burst; scale-IN
            # hysteresis deliberately SLOW relative to the burst. A
            # fleet the admission gate holds exactly at capacity looks
            # idle to the burn monitor (shed 0, burn 0, queues empty) —
            # an aggressive idle streak scales in mid-burst and
            # shedding resumes (a damped oscillation: the shed-rate
            # breach immediately restarts growth). Production defaults
            # (idle_ticks 5 x 3s sync + 45s cooldown) have the same
            # slow-in shape; autoscale_bench covers scale-in proper.
            master_cmd += [
                "--autoscaler-enabled",
                "--autoscaler-actuator", "local",
                "--autoscaler-min-instances", "1",
                "--autoscaler-max-instances", str(a.max_instances),
                "--autoscaler-breach-ticks", "2",
                "--autoscaler-idle-ticks", "60",
                "--autoscaler-scale-out-cooldown-s", "3",
                "--autoscaler-scale-in-cooldown-s", "45",
                "--autoscaler-stale-hold-s", "30",
                "--autoscaler-drain-grace-s", "0.5",
                "--autoscaler-spawn-cmd", self.engine_cmd_template(),
            ]
        self.spawn("master", master_cmd)
        tmpl = self.engine_cmd_template()
        for i in range(self.n_engines):
            self.spawn(f"engine{i}", tmpl.format(
                coordination_addr=f"127.0.0.1:{self.coord_port}",
                port=free_port()).split())

        base = self.base()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            for name, p in self.procs:
                if p.poll() is not None:
                    raise RuntimeError(
                        f"{name} died rc={p.returncode} — see "
                        f"{self.logdir}/overload_bench_{name}.log")
            try:
                r = requests.post(base + "/v1/completions", json={
                    "model": "fake-model", "prompt": "ready?",
                    "max_tokens": 2}, timeout=5)
                if r.status_code == 200:
                    return
            except requests.RequestException:
                pass
            time.sleep(0.25)
        raise RuntimeError("stack never became ready")

    def base(self) -> str:
        return f"http://127.0.0.1:{self.http_port}"

    def stop(self):
        for _, p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for _, p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


class Sampler(threading.Thread):
    """1 Hz poll of /admin/slo + /admin/overload + /admin/autoscaler."""

    def __init__(self, base: str):
        super().__init__(daemon=True, name="bench-sampler")
        self.base = base
        self.rows: list[dict] = []
        self._halt = threading.Event()

    def run(self):
        t0 = time.monotonic()
        while not self._halt.wait(1.0):
            row = {"t_s": round(time.monotonic() - t0, 1)}
            try:
                slo = requests.get(self.base + "/admin/slo",
                                   timeout=3).json()
                ttft = slo["objectives"]["ttft"]
                row["burn_fast"] = ttft["fast"]["burn_rate"]
                row["burn_slow"] = ttft["slow"]["burn_rate"]
                row["breaching"] = slo["breaching"]
            except (requests.RequestException, KeyError, ValueError):
                pass
            try:
                ov = requests.get(self.base + "/admin/overload",
                                  timeout=3).json()
                row["shed_rate"] = ov["admission"]["shed_rate_per_s"]
                row["pending"] = ov["admission"]["pending"]
                row["brownout"] = ov["brownout"]["active"]
            except (requests.RequestException, KeyError, ValueError):
                pass
            try:
                rep = requests.get(self.base + "/admin/autoscaler",
                                   timeout=3).json()
                if rep.get("decisions"):
                    row["live"] = rep["decisions"][0]["inputs"]["live"]
            except (requests.RequestException, ValueError):
                pass
            self.rows.append(row)

    def stop(self):
        self._halt.set()
        self.join(timeout=3)


def drive_phase(base: str, rps: float, duration_s: float, workers: int,
                out: dict) -> None:
    """Open-loop paced phase: requests are DUE at fixed wall slots;
    TTFT is measured from the slot (coordinated-omission-corrected).
    200s record into out["ttfts"]; 429s into out["shed_ms"] (request
    turnaround — the 'shed fast' claim); other codes into
    out["errors"]."""
    lock = threading.Lock()
    out.setdefault("ttfts", [])
    out.setdefault("shed_ms", [])
    out.setdefault("errors", 0)
    t_start = time.monotonic()
    stop_at = t_start + duration_s
    slot = [0]

    def worker():
        session = requests.Session()
        while True:
            with lock:
                k = slot[0]
                slot[0] += 1
            due = t_start + k / rps
            if due >= stop_at:
                return
            now = time.monotonic()
            if due > now:
                time.sleep(due - now)
            try:
                t_send = time.monotonic()
                r = session.post(base + "/v1/completions", json={
                    "model": "fake-model", "prompt": "overload bench",
                    "max_tokens": 8, "stream": True},
                    stream=True, timeout=120)
                if r.status_code == 429:
                    r.close()
                    with lock:
                        out["shed_ms"].append(
                            (time.monotonic() - t_send) * 1000)
                    continue
                if r.status_code != 200:
                    r.close()
                    with lock:
                        out["errors"] += 1
                    continue
                ttft = None
                for line in r.iter_lines():
                    if ttft is None and line.startswith(b"data: "):
                        ttft = time.monotonic() - due   # from the SLOT
                    if line == b"data: [DONE]":
                        break
                r.close()
                if ttft is not None:
                    with lock:
                        out["ttfts"].append(ttft * 1000)
            except requests.RequestException:
                with lock:
                    out["errors"] += 1
                time.sleep(0.1)

    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def run_leg(args, admission_limit: int, autoscale: bool,
            n_engines: int, burst_rps: float) -> dict:
    stack = Stack(args, admission_limit=admission_limit,
                  autoscale=autoscale, n_engines=n_engines)
    stack.start()
    sampler = Sampler(stack.base())
    sampler.start()
    steady: dict = {}
    burst: dict = {}
    try:
        drive_phase(stack.base(), args.steady_rps, args.steady_s,
                    args.workers, steady)
        burst_start = len(sampler.rows)
        drive_phase(stack.base(), burst_rps, args.burst_s,
                    args.workers, burst)
        burst_rows = sampler.rows[burst_start:] or [{}]
        end_row = burst_rows[-1]
        # Shed-rate decay (autoscale leg): mean over the last quarter of
        # the burst vs the first quarter.
        q = max(1, len(burst_rows) // 4)
        shed_head = [r.get("shed_rate") for r in burst_rows[:q]
                     if r.get("shed_rate") is not None]
        shed_tail = [r.get("shed_rate") for r in burst_rows[-q:]
                     if r.get("shed_rate") is not None]
        peak_live = max((r.get("live") or n_engines
                         for r in sampler.rows), default=n_engines)
        return {
            "admission_limit": admission_limit,
            "autoscale": autoscale,
            "steady_ttft_p50_ms": round(percentile(steady["ttfts"], 50), 1),
            "burst_admitted_ttft_p50_ms":
                round(percentile(burst["ttfts"], 50), 1),
            "burst_admitted_ttft_p99_ms":
                round(percentile(burst["ttfts"], 99), 1),
            "burst_shed_count": len(burst["shed_ms"]),
            "burst_admitted_count": len(burst["ttfts"]),
            "burst_shed_p50_ms": round(percentile(burst["shed_ms"], 50), 2),
            "burst_shed_p99_ms": round(percentile(burst["shed_ms"], 99), 2),
            "errors": steady["errors"] + burst["errors"],
            "burn_at_burst_end": {
                "fast": end_row.get("burn_fast"),
                "slow": end_row.get("burn_slow"),
                "breaching": end_row.get("breaching"),
            },
            "shed_rate_first_quarter": round(
                sum(shed_head) / len(shed_head), 2) if shed_head else None,
            "shed_rate_last_quarter": round(
                sum(shed_tail) / len(shed_tail), 2) if shed_tail else None,
            "peak_live_instances": peak_live,
            "timeline": sampler.rows,
        }
    finally:
        sampler.stop()
        stack.stop()


def run_idle_overhead(args) -> dict:
    """A/B light load with the overload plane configured (admission
    gate + default deadline: the per-request parse/check cost) vs the
    default-off config."""
    p50s = {}
    for on in (False, True):
        stack = Stack(args, admission_limit=64 if on else 0,
                      deadline_ms=30000.0 if on else 0.0)
        stack.start()
        try:
            out: dict = {}
            drive_phase(stack.base(), args.steady_rps, args.overhead_s,
                        args.workers, out)
            p50s["on" if on else "off"] = percentile(out["ttfts"], 50)
        finally:
            stack.stop()
    off, on = p50s["off"], p50s["on"]
    return {
        "ttft_p50_off_ms": round(off, 2),
        "ttft_p50_on_ms": round(on, 2),
        "delta_pct": round((on - off) / off * 100, 2) if off else 0.0,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized phases (functional, not publication)")
    ap.add_argument("--steady-s", type=float, default=20.0)
    ap.add_argument("--burst-s", type=float, default=45.0)
    ap.add_argument("--overhead-s", type=float, default=20.0)
    ap.add_argument("--steady-rps", type=float, default=6.0)
    ap.add_argument("--burst-multiple", type=float, default=4.0)
    ap.add_argument("--workers", type=int, default=24)
    ap.add_argument("--admission-limit", type=int, default=1,
                    help="per-instance admitted-in-flight watermark for "
                         "the shed leg")
    ap.add_argument("--autoscale-admission-limit", type=int, default=2)
    ap.add_argument("--max-instances", type=int, default=4)
    ap.add_argument("--slo-ttft-ms", type=float, default=600.0)
    ap.add_argument("--fast-window-s", type=float, default=8.0)
    ap.add_argument("--slow-window-s", type=float, default=16.0)
    ap.add_argument("--skip-noshed", action="store_true")
    ap.add_argument("--skip-autoscale", action="store_true")
    ap.add_argument("--skip-overhead", action="store_true")
    args = ap.parse_args()
    if args.quick:
        args.steady_s, args.burst_s = 10.0, 30.0
        args.overhead_s = 10.0

    capacity = SERVICE_RATE_RPS * N_ENGINES
    burst_rps = args.burst_multiple * capacity

    print("== shed leg (admission ON) ==", file=sys.stderr)
    shed = run_leg(args, args.admission_limit, autoscale=False,
                   n_engines=N_ENGINES, burst_rps=burst_rps)
    noshed = None
    if not args.skip_noshed:
        print("== noshed control (admission OFF) ==", file=sys.stderr)
        noshed = run_leg(args, 0, autoscale=False,
                         n_engines=N_ENGINES, burst_rps=burst_rps)
    autoscale = None
    if not args.skip_autoscale:
        print("== shed+autoscale leg ==", file=sys.stderr)
        # Burst sized to the MAX fleet: shedding bridges the gap while
        # capacity arrives, then decays to ~0.
        autoscale = run_leg(
            args, args.autoscale_admission_limit, autoscale=True,
            n_engines=1,
            burst_rps=3.0 * SERVICE_RATE_RPS)
    overhead = None
    if not args.skip_overhead:
        print("== idle-overhead A/B ==", file=sys.stderr)
        overhead = run_idle_overhead(args)

    alert = 14.4
    ttft_ratio = (shed["burst_admitted_ttft_p50_ms"]
                  / shed["steady_ttft_p50_ms"]
                  if shed["steady_ttft_p50_ms"] else None)
    noshed_end = (noshed or {}).get("burn_at_burst_end", {})
    shed_end = shed["burn_at_burst_end"]
    decay_ok = None
    if autoscale is not None and \
            autoscale["shed_rate_first_quarter"] is not None:
        decay_ok = (autoscale["shed_rate_last_quarter"] is not None
                    and autoscale["shed_rate_last_quarter"] <= max(
                        0.5, 0.1 * autoscale["shed_rate_first_quarter"]))
    report = {
        "config": {
            "service_rate_rps": SERVICE_RATE_RPS,
            "first_delta_delay_s": FIRST_DELTA_DELAY_S,
            "n_engines": N_ENGINES,
            "fleet_capacity_rps": capacity,
            "burst_rps": burst_rps,
            "steady_rps": args.steady_rps,
            "admission_limit": args.admission_limit,
            "phases_s": [args.steady_s, args.burst_s],
            "slo_ttft_ms": args.slo_ttft_ms,
            "windows_s": [args.fast_window_s, args.slow_window_s],
            "quick": args.quick,
        },
        "shed": shed,
        "noshed": noshed,
        "autoscale": autoscale,
        "idle_overhead": overhead,
        # The ISSUE acceptance evidence.
        "acceptance": {
            "admitted_ttft_ratio_vs_steady":
                round(ttft_ratio, 2) if ttft_ratio else None,
            "admitted_ttft_within_1p5x":
                bool(ttft_ratio and ttft_ratio <= 1.5),
            "shed_p99_ms": shed["burst_shed_p99_ms"],
            "shed_under_50ms_p99": shed["burst_shed_p99_ms"] < 50.0,
            "shed_leg_burn_at_end": shed_end,
            "noshed_breaches_both_windows":
                (noshed_end.get("fast") is not None
                 and noshed_end["fast"] >= alert
                 and noshed_end["slow"] >= alert) if noshed else None,
            "autoscale_shed_rate_decays_to_zero": decay_ok,
            "autoscale_peak_live":
                (autoscale or {}).get("peak_live_instances"),
        },
        # bench_trend-tracked (direction by suffix: _pct in absolute
        # points upward = regression, bare ratios downward).
        "headline": {
            "admitted_ttft_ratio_vs_steady":
                round(ttft_ratio, 3) if ttft_ratio else None,
            "shed_p99_ms": shed["burst_shed_p99_ms"],
            "idle_overhead_ttft_delta_pct":
                (overhead or {}).get("delta_pct"),
        },
    }
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
