"""Speculative-decoding benchmark: tok/s with prompt-lookup speculation
on vs off, greedy, repetitive workload (where lookahead drafts accept).
Run on TPU for real numbers; CPU runs validate the mechanism only.

Prints one JSON line per mode plus the speedup.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from xllm_service_tpu.utils import pin_cpu_platform_if_requested

pin_cpu_platform_if_requested()


def main() -> None:
    import jax
    import jax.numpy as jnp

    from xllm_service_tpu.common.request import SamplingParams
    from xllm_service_tpu.engine.config import EngineConfig
    from xllm_service_tpu.engine.engine import EngineRequest, InferenceEngine
    from xllm_service_tpu.models.base import bench_1b_config, tiny_config

    on_accel = jax.default_backend() != "cpu"
    mcfg = bench_1b_config() if on_accel else tiny_config(dtype=jnp.float32)
    B = 8
    # Budgets ample enough that the timed window is pure steady state (no
    # budget-bounded horizon shrink -> no tail compiles in the window).
    ctx, new = (256, 640) if on_accel else (64, 160)
    max_seq = 1024 if on_accel else 256

    # Repetitive prompts (the prompt-lookup draft's home turf — code/JSON
    # style repetition).
    base_unit = list(range(11, 11 + 8))
    prompt = (base_unit * (ctx // len(base_unit)))[:ctx]

    results = {}
    for spec_k in (0, 4):
        cfg = EngineConfig(
            model_id="spec-bench", model=mcfg,
            num_pages=(B * max_seq) // 16 + 64, page_size=16,
            max_batch_size=B, max_seq_len=max_seq,
            prefill_buckets=(64, 256, max_seq),
            hash_block_size=128 if on_accel else 32,
            decode_horizon=8 if spec_k == 0 else 1,
            speculate_k=spec_k)
        engine = InferenceEngine(cfg)
        counts = {"tokens": 0}

        def on_output(out):
            counts["tokens"] += sum(len(s.token_ids) for s in out.outputs)

        for i in range(B):
            engine.submit(EngineRequest(
                f"s{i}", token_ids=list(prompt) + [i],
                sampling=SamplingParams(max_tokens=new, temperature=0.0,
                                        ignore_eos=True),
                on_output=on_output))
        # Warm up admission + compile the decode/verify programs (a few
        # steps) so XLA compiles stay out of the timed window.
        while engine._waiting:
            engine.step()
        for _ in range(3):
            engine.step()
        # Steady-state window: fixed step count at full batch.
        n_steps = 10
        t0 = time.perf_counter()
        start_toks = counts["tokens"]
        for _ in range(n_steps):
            engine.step()
        dt = time.perf_counter() - t0
        toks = counts["tokens"] - start_toks
        results[spec_k] = toks / dt
        print(json.dumps({"mode": f"speculate_k={spec_k}",
                          "tok_per_s": round(toks / dt, 2),
                          "tokens": toks}))
        engine.stop()

    print(json.dumps({"metric": "speculative_speedup",
                      "value": round(results[4] / results[0], 3),
                      "unit": "x",
                      "backend": jax.default_backend()}))


if __name__ == "__main__":
    main()
