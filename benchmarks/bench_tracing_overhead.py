"""Tracing-overhead micro-bench (ISSUE 3 acceptance: tracing-off <2%;
re-run in ISSUE 9 with federation + sampling in the tree).

Measures the fake-engine request path end-to-end (HTTP frontend ->
scheduler -> fake engine -> generations ingest -> response) under four
tracer configurations, against ONE shared cluster with the modes
interleaved round-robin (cluster-to-cluster and drift noise would
otherwise swamp the sub-ms effect being measured):

- ``off``     — tracing disabled: every span call is one attribute check +
                shared no-op singleton.
- ``ring``    — spans recorded into the in-memory SpanStore ring (default).
- ``sampled`` — ring at ``sample_rate=0.1`` with tail-based keep: ~90% of
                traces park in the pending buffer and are dropped at
                clean exit (the high-QPS always-on configuration).
- ``jsonl``   — ring + every finished span mirrored into a RequestTracer
                JSONL (the enable_request_trace pairing).

Also times the disabled `start_span` call in isolation (ns/call), and —
fleet observability plane — the cost of one `/admin/trace?scope=fleet`
assembly and one `/metrics/fleet` scrape against the live cluster
(query-side cost; the request path is untouched by federation).

Prints one JSON line per mode plus p50 overhead ratios vs ``off``, and a
BENCH_tracing-shaped document at the end (headline tracked by
scripts/bench_trend.py). Results are quoted in docs/observability.md and
docs/performance.md.
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from xllm_service_tpu.utils import pin_cpu_platform_if_requested

pin_cpu_platform_if_requested()

import json
import statistics
import tempfile
import time

import requests

MODES = ("off", "ring", "sampled", "jsonl")


def disabled_span_call_ns(iters: int = 200_000) -> float:
    from xllm_service_tpu.common.tracing import Tracer

    tr = Tracer()
    tr.configure(enabled=False)
    t0 = time.perf_counter()
    for _ in range(iters):
        sp = tr.start_span("frontend.request")
        sp.end()
    return (time.perf_counter() - t0) / iters * 1e9


def main() -> None:
    from xllm_service_tpu.common.config import ServiceOptions
    from xllm_service_tpu.common.tracing import TRACER
    from xllm_service_tpu.coordination.memory import (
        InMemoryCoordination,
        MemoryStore,
    )
    from xllm_service_tpu.http_service.request_tracer import RequestTracer
    from xllm_service_tpu.master import Master
    from xllm_service_tpu.testing.fake_engine import (
        FakeEngine,
        FakeEngineConfig,
    )

    print(json.dumps({"disabled_span_call_ns":
                      round(disabled_span_call_ns(), 1)}))

    store = MemoryStore(expiry_tick_s=0.05)
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=2.0, sync_interval_s=1.0)
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()
    # Single-delta replies: the per-request fixed path (accept -> schedule
    # -> forward -> generate -> ingest -> respond) is what tracing
    # instruments; multi-delta streaming only adds thread-scheduling noise.
    engine = FakeEngine(
        InMemoryCoordination(store),
        FakeEngineConfig(reply_text="x" * 8, chunk_size=8,
                         delay_s=0.0)).start()
    deadline = time.time() + 10
    while not master.scheduler.has_available_instances():
        if time.time() > deadline:
            raise RuntimeError("fake engine never became available")
        time.sleep(0.05)

    jsonl_tracer = RequestTracer(tempfile.mkdtemp(prefix="bench-trace-"),
                                 enabled=True)

    def mirror(span: dict) -> None:
        jsonl_tracer.log(span.get("request_id", ""),
                         {"type": "span", "span": span})

    def set_mode(mode: str) -> None:
        TRACER.configure(enabled=mode != "off",
                         mirror=mirror if mode == "jsonl" else None,
                         sample_rate=0.1 if mode == "sampled" else 1.0)

    url = f"http://127.0.0.1:{master.http_port}/v1/completions"
    body = {"model": "fake-model", "prompt": "bench", "max_tokens": 8}
    session = requests.Session()

    def one() -> float:
        t0 = time.perf_counter()
        r = session.post(url, json=body, timeout=30)
        assert r.status_code == 200, r.text
        return (time.perf_counter() - t0) * 1000.0

    for _ in range(50):   # warmup (threads, sockets, code paths)
        one()

    ROUNDS, PER_ROUND = 12, 40
    lat: dict[str, list[float]] = {m: [] for m in MODES}
    for _ in range(ROUNDS):
        for mode in MODES:
            set_mode(mode)
            lat[mode].extend(one() for _ in range(PER_ROUND))
    set_mode("ring")

    results = {}
    for mode in MODES:
        xs = sorted(lat[mode])
        results[mode] = {
            "mode": mode,
            "n": len(xs),
            "mean_ms": round(statistics.fmean(xs), 3),
            "p50_ms": round(xs[len(xs) // 2], 3),
            "p95_ms": round(xs[int(len(xs) * 0.95)], 3),
        }
        print(json.dumps(results[mode]))
    base = results["off"]["p50_ms"]
    overheads = {}
    for mode in ("ring", "sampled", "jsonl"):
        ratio = (results[mode]["p50_ms"] - base) / base * 100.0
        overheads[mode] = round(ratio, 2)
        print(json.dumps({"overhead_vs_off": mode, "p50_pct": ratio}))

    # Fleet-endpoint query cost (not on the request path; informational).
    recent = session.get(
        f"http://127.0.0.1:{master.http_port}/admin/trace/recent",
        timeout=10).json()
    sid = recent["traces"][0]["request_id"] if recent["traces"] else ""
    fleet = {}
    for name, path, params in (
            ("fleet_trace_ms", "/admin/trace",
             {"scope": "fleet", "request_id": sid}),
            ("fleet_metrics_ms", "/metrics/fleet", {})):
        t0 = time.perf_counter()
        session.get(f"http://127.0.0.1:{master.http_port}{path}",
                    params=params, timeout=10)
        fleet[name] = round((time.perf_counter() - t0) * 1000.0, 3)
    print(json.dumps(fleet))

    doc = {
        "bench": "benchmarks/bench_tracing_overhead.py",
        "modes": results,
        "fleet_endpoint_cost": fleet,
        # Signed: negative = measured faster than off (noise); the
        # bench-trend tripwire judges *_pct headlines in absolute
        # points, so a clamped 0 would hide a later real regression.
        "headline": {
            "ring_overhead_p50_pct": overheads["ring"],
            "sampled_overhead_p50_pct": overheads["sampled"],
        },
    }
    print("BENCH_DOC " + json.dumps(doc))

    jsonl_tracer.close()
    engine.stop()
    master.stop()


if __name__ == "__main__":
    main()
