"""Coordination-outage static-stability bench (ISSUE 16).

The acceptance run for degraded-mode serving (docs/robustness.md
"Degraded mode"). A deployment-shaped multiproc stack (coordination
server, master, fake engines — each an OS process) is driven with paced
open-loop load through three phases: steady, a ~30 s TOTAL coordination
outage (the server process is SIGKILLed mid-load), and recovery (a
fresh, EMPTY server restarted on the same port), in two configurations:

- **degraded** (static stability ON): rps and TTFT p50 during the
  outage hold within 10% of steady state, zero instances are evicted,
  no evictions are even *held* (every engine keeps beating), and
  recovery is storm-free — the restarted server's accept log shows the
  fleet's re-registration spread over the jitter window, after which
  the monitor returns to CONNECTED with the fleet intact.
- **control** (`--coordination-degraded-mode off`, engines
  `--degraded-mode off`): the legacy behavior loses the fleet — silent
  engines are swept and evicted against the dead plane's evidence, and
  outage-phase throughput collapses.

    python benchmarks/outage_bench.py            # full run
    python benchmarks/outage_bench.py --quick    # CI-sized

Output: JSON report (BENCH_outage_r17.json); headline keys are
bench_trend-tracked.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import requests


def percentile(xs, p):
    if not xs:
        return 0.0
    xs = sorted(xs)
    k = min(len(xs) - 1, int(round((p / 100) * (len(xs) - 1))))
    return xs[k]


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


SERVICE_RATE_RPS = 6.0        # per-engine capacity (deterministic model)
FIRST_DELTA_DELAY_S = 0.2     # simulated prefill: the TTFT floor
N_ENGINES = 4
RECONNECT_JITTER_S = 2.0      # recovery spread window (master + engines)


class Stack:
    """Coordination server + master + engines, each an OS process.

    The coordination server is killable (SIGKILL) and restartable on
    the same port with a fresh accept log — process-death semantics,
    exactly what the degraded-mode plane is built for."""

    def __init__(self, args, degraded: bool):
        self.args = args
        self.degraded = degraded
        self.procs: list[tuple[str, subprocess.Popen]] = []
        self.coord_proc: subprocess.Popen | None = None
        self.coord_port = free_port()
        self.http_port = free_port()
        self.rpc_port = free_port()
        self.logdir = Path(os.environ.get("XLLM_BENCH_LOGDIR", "/tmp"))
        self.accept_log = Path(tempfile.mkstemp(
            prefix="outage_bench_accepts_")[1])
        self.env = dict(os.environ, JAX_PLATFORMS="cpu")

    def spawn(self, name, cmd) -> subprocess.Popen:
        log = open(self.logdir / f"outage_bench_{name}.log", "a")
        p = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                             cwd=str(REPO), env=self.env)
        self.procs.append((name, p))
        return p

    def start_coord(self, name="coord") -> None:
        self.coord_proc = self.spawn(name, [
            sys.executable, "-m", "xllm_service_tpu.coordination.server",
            "--host", "127.0.0.1", "--port", str(self.coord_port),
            "--accept-log", str(self.accept_log)])

    def kill_coord(self) -> None:
        """SIGKILL — no graceful teardown; clients see dead sockets."""
        assert self.coord_proc is not None
        self.coord_proc.send_signal(signal.SIGKILL)
        self.coord_proc.wait(timeout=10)

    def start(self):
        mode = "on" if self.degraded else "off"
        self.start_coord()
        time.sleep(0.3)
        self.spawn("master", [
            sys.executable, "-m", "xllm_service_tpu.master",
            "--coordination-addr", f"127.0.0.1:{self.coord_port}",
            "--host", "127.0.0.1",
            "--http-port", str(self.http_port),
            "--rpc-port", str(self.rpc_port),
            "--load-balance-policy", "RR",
            "--sync-interval-s", "0.5",
            "--lease-ttl-s", "1.5",
            "--heartbeat-silence-to-suspect-s", "2.0",
            "--detect-disconnected-instance-interval-s", "2.0",
            "--coordination-degraded-mode", mode,
            "--coordination-degraded-after-ticks", "2",
            "--degraded-heartbeat-silence-s", "10.0",
            "--coordination-reconnect-jitter-s", str(RECONNECT_JITTER_S),
        ])
        for i in range(N_ENGINES):
            self.spawn(f"engine{i}", [
                sys.executable, str(REPO / "examples/run_fake_engine.py"),
                "--coordination-addr", f"127.0.0.1:{self.coord_port}",
                "--port", str(free_port()),
                "--service-rate", str(SERVICE_RATE_RPS),
                "--accept-queue", "512",
                "--first-delta-delay", str(FIRST_DELTA_DELAY_S),
                "--reply", "x" * 8, "--chunk-size", "8", "--delay", "0",
                "--heartbeat-interval", "0.25",
                "--lease-ttl", "1.5",
                "--degraded-mode", mode])

        base = self.base()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            for name, p in self.procs:
                if p.poll() is not None:
                    raise RuntimeError(
                        f"{name} died rc={p.returncode} — see "
                        f"{self.logdir}/outage_bench_{name}.log")
            try:
                r = requests.post(base + "/v1/completions", json={
                    "model": "fake-model", "prompt": "ready?",
                    "max_tokens": 2}, timeout=5)
                if r.status_code == 200 and self.fleet_size() >= N_ENGINES:
                    return
            except requests.RequestException:
                pass
            time.sleep(0.25)
        raise RuntimeError("stack never became ready")

    def base(self) -> str:
        return f"http://127.0.0.1:{self.http_port}"

    def metrics(self) -> str:
        try:
            return requests.get(self.base() + "/metrics", timeout=5).text
        except requests.RequestException:
            return ""

    def fleet_size(self) -> int:
        """Distinct registered instances, from the per-instance queue
        gauge (one series per registered engine; deregistration removes
        it)."""
        return sum(1 for ln in self.metrics().splitlines()
                   if ln.startswith("instance_queue_depth{"))

    def evictions_total(self) -> float:
        total = 0.0
        for ln in self.metrics().splitlines():
            if ln.startswith("instance_evictions_total{"):
                total += float(ln.rsplit(" ", 1)[1])
        return total

    def coordination_report(self) -> dict:
        try:
            return requests.get(self.base() + "/admin/coordination",
                                timeout=5).json()
        except (requests.RequestException, ValueError):
            return {}

    def accept_times(self) -> list[float]:
        try:
            return [float(ln) for ln in
                    self.accept_log.read_text().splitlines() if ln]
        except OSError:
            return []

    def stop(self):
        for _, p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for _, p in self.procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        try:
            self.accept_log.unlink()
        except OSError:
            pass


class Sampler(threading.Thread):
    """1 Hz poll of /admin/coordination: monitor state + held-log shape
    over the run (the 'what was held back' timeline)."""

    def __init__(self, stack: Stack):
        super().__init__(daemon=True, name="bench-sampler")
        self.stack = stack
        self.rows: list[dict] = []
        self._halt = threading.Event()

    def run(self):
        t0 = time.monotonic()
        while not self._halt.wait(1.0):
            rep = self.stack.coordination_report()
            held = rep.get("held", {})
            actions = held.get("actions", [])
            self.rows.append({
                "t_s": round(time.monotonic() - t0, 1),
                "state": rep.get("state"),
                "held_depth": held.get("depth"),
                "held_evicts": sum(1 for a in actions
                                   if a.get("kind") == "evict"),
                "fleet": self.stack.fleet_size(),
            })

    def stop(self):
        self._halt.set()
        self.join(timeout=3)


def drive_phase(base: str, rps: float, duration_s: float, workers: int,
                out: dict) -> None:
    """Open-loop paced phase: requests are DUE at fixed wall slots; TTFT
    is measured from the slot (coordinated-omission-corrected)."""
    lock = threading.Lock()
    out.setdefault("ttfts", [])
    out.setdefault("errors", 0)
    t_start = time.monotonic()
    stop_at = t_start + duration_s
    slot = [0]

    def worker():
        session = requests.Session()
        while True:
            with lock:
                k = slot[0]
                slot[0] += 1
            due = t_start + k / rps
            if due >= stop_at:
                return
            now = time.monotonic()
            if due > now:
                time.sleep(due - now)
            try:
                t_send = time.monotonic()
                r = session.post(base + "/v1/completions", json={
                    "model": "fake-model", "prompt": "outage bench",
                    "max_tokens": 8, "stream": True},
                    stream=True, timeout=60)
                if r.status_code != 200:
                    r.close()
                    with lock:
                        out["errors"] += 1
                    continue
                ttft = None
                done = False
                for line in r.iter_lines():
                    if ttft is None and line.startswith(b"data: "):
                        ttft = time.monotonic() - due   # from the SLOT
                    if line == b"data: [DONE]":
                        done = True
                        break
                r.close()
                if done and ttft is not None:
                    with lock:
                        out["ttfts"].append(ttft * 1000)
                else:
                    with lock:
                        out["errors"] += 1
            except requests.RequestException:
                with lock:
                    out["errors"] += 1
                time.sleep(0.05)
            del t_send

    threads = [threading.Thread(target=worker) for _ in range(workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


def phase_stats(out: dict, duration_s: float) -> dict:
    return {
        "completed": len(out["ttfts"]),
        "errors": out["errors"],
        "rps": round(len(out["ttfts"]) / duration_s, 2),
        "ttft_p50_ms": round(percentile(out["ttfts"], 50), 1),
        "ttft_p99_ms": round(percentile(out["ttfts"], 99), 1),
    }


def run_leg(args, degraded: bool) -> dict:
    stack = Stack(args, degraded=degraded)
    stack.start()
    sampler = Sampler(stack)
    sampler.start()
    try:
        steady: dict = {}
        drive_phase(stack.base(), args.rps, args.steady_s, args.workers,
                    steady)
        evictions_pre = stack.evictions_total()

        # Kill the coordination server ~1 s INTO the outage-phase load:
        # the paced driver is mid-flight when the plane dies.
        outage: dict = {}
        driver = threading.Thread(
            target=drive_phase,
            args=(stack.base(), args.rps, args.outage_s, args.workers,
                  outage))
        driver.start()
        time.sleep(1.0)
        stack.kill_coord()
        t_killed = time.time()
        driver.join()
        fleet_at_outage_end = stack.fleet_size()
        rep = stack.coordination_report()
        state_at_outage_end = rep.get("state")
        held_at_outage_end = rep.get("held", {}).get("depth")
        max_held_evicts = max((r["held_evicts"] or 0
                               for r in sampler.rows
                               if r.get("held_evicts") is not None),
                              default=0)

        # Restart EMPTY on the same port; the recovery phase drives load
        # while the fleet reconnects with jittered backoff + spread
        # re-registration.
        stack.start_coord(name="coord2")
        t_restarted = time.time()
        recovery: dict = {}
        drive_phase(stack.base(), args.rps, args.recovery_s, args.workers,
                    recovery)
        deadline = time.monotonic() + 30
        final_state = None
        while time.monotonic() < deadline:
            final_state = stack.coordination_report().get("state")
            if final_state == "CONNECTED" or not degraded:
                break
            time.sleep(0.5)
        accepts = [t - t_restarted for t in stack.accept_times()
                   if t >= t_restarted]
        return {
            "degraded_mode": degraded,
            "steady": phase_stats(steady, args.steady_s),
            "outage": phase_stats(outage, args.outage_s),
            "recovery": phase_stats(recovery, args.recovery_s),
            "evictions_total": stack.evictions_total() - evictions_pre,
            "fleet_at_outage_end": fleet_at_outage_end,
            "fleet_final": stack.fleet_size(),
            "state_at_outage_end": state_at_outage_end,
            "held_depth_at_outage_end": held_at_outage_end,
            "max_held_evictions_observed": max_held_evicts,
            "final_monitor_state": final_state,
            "outage_started_unix": t_killed,
            "reconnect_accepts": len(accepts),
            "reconnect_spread_s": round(max(accepts) - min(accepts), 3)
                if len(accepts) >= 2 else 0.0,
            "reconnect_first_s": round(min(accepts), 3) if accepts
                else None,
            "timeline": sampler.rows,
        }
    finally:
        sampler.stop()
        stack.stop()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized phases (functional, not publication)")
    ap.add_argument("--steady-s", type=float, default=15.0)
    ap.add_argument("--outage-s", type=float, default=30.0)
    ap.add_argument("--recovery-s", type=float, default=15.0)
    ap.add_argument("--rps", type=float, default=8.0)
    ap.add_argument("--workers", type=int, default=16)
    ap.add_argument("--skip-control", action="store_true")
    args = ap.parse_args()
    if args.quick:
        args.steady_s, args.outage_s, args.recovery_s = 8.0, 15.0, 10.0

    print("== degraded leg (static stability ON) ==", file=sys.stderr)
    deg = run_leg(args, degraded=True)
    control = None
    if not args.skip_control:
        print("== control leg (degraded mode OFF) ==", file=sys.stderr)
        control = run_leg(args, degraded=False)

    steady, outage = deg["steady"], deg["outage"]
    rps_ratio = (outage["rps"] / steady["rps"]) if steady["rps"] else None
    rec_ratio = (deg["recovery"]["rps"] / steady["rps"]) \
        if steady["rps"] else None
    ttft_ratio = (outage["ttft_p50_ms"] / steady["ttft_p50_ms"]) \
        if steady["ttft_p50_ms"] else None
    ctl = control or {}
    ctl_rps_ratio = None
    if ctl and ctl["steady"]["rps"]:
        ctl_rps_ratio = round(ctl["outage"]["rps"] / ctl["steady"]["rps"],
                              3)
    control_loses_fleet = None
    if ctl:
        control_loses_fleet = bool(
            ctl["evictions_total"] > 0
            or ctl["fleet_at_outage_end"] < N_ENGINES
            or ctl["outage"]["errors"] > ctl["outage"]["completed"])
    spread_frac = round(deg["reconnect_spread_s"] / RECONNECT_JITTER_S, 3)
    report = {
        "config": {
            "service_rate_rps": SERVICE_RATE_RPS,
            "first_delta_delay_s": FIRST_DELTA_DELAY_S,
            "n_engines": N_ENGINES,
            "drive_rps": args.rps,
            "phases_s": [args.steady_s, args.outage_s, args.recovery_s],
            "reconnect_jitter_s": RECONNECT_JITTER_S,
            "quick": args.quick,
        },
        "degraded": deg,
        "control": control,
        # The ISSUE acceptance evidence.
        "acceptance": {
            "outage_rps_within_10pct":
                bool(rps_ratio and rps_ratio >= 0.9),
            "outage_ttft_p50_within_10pct":
                bool(ttft_ratio and ttft_ratio <= 1.1),
            "zero_evictions": deg["evictions_total"] == 0,
            "zero_spurious_held_evictions":
                deg["max_held_evictions_observed"] == 0,
            "fleet_intact_after_recovery":
                deg["fleet_final"] == N_ENGINES,
            "monitor_reconnected":
                deg["final_monitor_state"] == "CONNECTED",
            "recovery_spread_over_jitter_window": spread_frac >= 0.1,
            "control_outage_rps_ratio": ctl_rps_ratio,
            "control_loses_fleet": control_loses_fleet,
        },
        # bench_trend-tracked (ratios: higher is better; _ms: lower).
        "headline": {
            "outage_rps_ratio_vs_steady":
                round(rps_ratio, 3) if rps_ratio else None,
            "recovery_rps_ratio_vs_steady":
                round(rec_ratio, 3) if rec_ratio else None,
            "outage_ttft_p50_ms": outage["ttft_p50_ms"],
            "fleet_survival_ratio":
                round(deg["fleet_final"] / N_ENGINES, 3),
            "reconnect_spread_frac_of_window": spread_frac,
        },
    }
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
