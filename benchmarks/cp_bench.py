"""Context-parallel paged-decode kernel benchmark (VERDICT r3 weak #2).

On the one real chip this Mosaic-validates the CP partial-stats Pallas
kernel (ops/cp_paged_attention.py) and A/Bs three bodies at bench-1b
attention shapes:

  1. single-device decode kernel (ops/pallas_paged_attention) — the
     non-CP reference number,
  2. cp_paged_attention with the Pallas partial kernel (1-device mesh:
     same math, full shard_map + psum-merge machinery),
  3. cp_paged_attention with the dense-gather XLA fallback body.

Prints one JSON line with per-body step times. A Mosaic compile failure
in (2) surfaces as an "error" field — exactly what the sweep exists to
catch (the kernel has only ever compiled under interpret=True on CPU).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from xllm_service_tpu.utils import pin_cpu_platform_if_requested

pin_cpu_platform_if_requested()


def _time(fn, *args, iters=50):
    out = fn(*args)
    jax_block(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax_block(out)
    return (time.perf_counter() - t0) / iters * 1e3   # ms/step


def jax_block(x):
    import jax
    jax.block_until_ready(x)


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P

    from xllm_service_tpu.ops.cp_paged_attention import cp_paged_attention
    from xllm_service_tpu.ops.pallas_paged_attention import (
        paged_attention_pallas,
    )

    backend = jax.default_backend()
    on_accel = backend != "cpu"

    # bench-1b attention shapes (models/base.py bench_1b_config).
    B, n_q, n_kv, hd, ps = (16, 16, 8, 128, 16) if on_accel \
        else (4, 4, 2, 32, 16)
    ctx = int(os.environ.get("XLLM_CP_CTX", "0")) or \
        (2048 if on_accel else 128)
    if ctx > 8192:
        B = max(2, B // 4)   # keep the pool inside one chip's HBM
    pages_per_seq = ctx // ps
    num_pages = B * pages_per_seq + 64
    dtype = jnp.bfloat16 if on_accel else jnp.float32

    rng = np.random.default_rng(0)
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, n_q, hd), dtype)
    k_pages = jax.random.normal(key, (num_pages, n_kv, ps, hd), dtype)
    v_pages = jax.random.normal(key, (num_pages, n_kv, ps, hd), dtype)
    pt = np.zeros((B, pages_per_seq + 4), np.int32)
    for b in range(B):
        pt[b, :pages_per_seq] = rng.permutation(
            np.arange(num_pages - 64))[:pages_per_seq]
    page_table = jnp.asarray(pt)
    clens = jnp.full((B,), ctx, jnp.int32)

    mesh = Mesh(np.array(jax.devices()[:1]), ("seq",))

    result = {"backend": backend, "B": B, "ctx": ctx,
              "metric": "cp_decode_attention_ms_per_step", "unit": "ms"}

    # 1. single-device decode kernel (reference point).
    if on_accel:
        single = jax.jit(paged_attention_pallas)
        try:
            result["single_device_kernel_ms"] = round(
                _time(single, q, k_pages, v_pages, page_table, clens), 4)
        except Exception as e:  # noqa: BLE001 — record, keep going
            result["single_device_kernel_error"] = str(e)[:300]

    # 2. CP Pallas partial kernel (Mosaic on accel; the validation target).
    def cp(qq, kk, vv, tt, cc):
        return cp_paged_attention(qq, kk, vv, tt, cc, mesh=mesh)

    os.environ.pop("XLLM_DISABLE_PALLAS_ATTENTION", None)
    try:
        cp_pallas = jax.jit(cp)
        result["cp_pallas_ms"] = round(
            _time(cp_pallas, q, k_pages, v_pages, page_table, clens), 4)
    except Exception as e:  # noqa: BLE001 — Mosaic failure is the finding
        result["error"] = f"cp pallas kernel: {type(e).__name__}: {e}"[:400]

    # 3. dense XLA fallback body.
    os.environ["XLLM_DISABLE_PALLAS_ATTENTION"] = "1"
    try:
        cp_xla = jax.jit(lambda *a: cp(*a))
        result["cp_xla_fallback_ms"] = round(
            _time(cp_xla, q, k_pages, v_pages, page_table, clens), 4)
    finally:
        os.environ.pop("XLLM_DISABLE_PALLAS_ATTENTION", None)

    if "cp_pallas_ms" in result and "cp_xla_fallback_ms" in result:
        result["pallas_vs_xla"] = round(
            result["cp_xla_fallback_ms"] / result["cp_pallas_ms"], 3)
        result["value"] = result["cp_pallas_ms"]

    # Parity check between the two CP bodies (and vs single-device).
    try:
        a = np.asarray(jax.jit(cp)(q, k_pages, v_pages, page_table, clens),
                       np.float32)
        os.environ["XLLM_DISABLE_PALLAS_ATTENTION"] = "1"
        b = np.asarray(
            jax.jit(lambda *x: cp(*x))(q, k_pages, v_pages, page_table,
                                       clens), np.float32)
        os.environ.pop("XLLM_DISABLE_PALLAS_ATTENTION", None)
        result["parity_max_abs_diff"] = float(np.max(np.abs(a - b)))
    except Exception as e:  # noqa: BLE001
        result.setdefault("error", f"parity: {type(e).__name__}: {e}"[:300])

    print(json.dumps(result))


if __name__ == "__main__":
    main()
