"""Summarize tpu_results/*.json sweep artifacts into a BASELINE.md-ready
markdown table + a one-line verdict per A/B arm.

    python benchmarks/summarize_sweep.py [tpu_results/]

Reads every known artifact name the round-4 sweep writes (tpu_sweep.sh),
tolerates missing/failed steps, and prints:
  - the headline bench rows (tok/s, vs_baseline, pct_roofline) per arm,
  - kernel A/B verdicts (chunk16/32, rowpipe, fused/scatter, int8, 8B),
  - serve + span table, spec speedup, PD handoff, decode profile.
"""

from __future__ import annotations

import json
import sys
from pathlib import Path


def load(d: Path, name: str):
    p = d / f"{name}.json"
    if not p.exists():
        return None
    try:
        text = p.read_text().strip()
        return json.loads(text) if text else None
    except ValueError:
        # spec_bench prints multiple lines; take the last parseable one.
        recs = []
        for ln in text.splitlines():
            try:
                recs.append(json.loads(ln))
            except ValueError:
                continue
        return recs[-1] if recs else None


BENCH_ARMS = [
    ("bench", "1b bf16 (default)"),
    ("bench_8b", "8B int8 (north-star scale)"),
    ("bench_moe", "MLA+MoE int8 (config-4 datum)"),
    ("bench_int8", "1b int8"),
    ("bench_chunk16", "1b chunk=16"),
    ("bench_chunk32", "1b chunk=32"),
    ("bench_rowpipe", "1b rowpipe"),
    ("bench_rowpipe16", "1b rowpipe+chunk16"),
    ("bench_ctx2k", "1b ctx=2048 chunk=16"),
    ("bench_ctx8k", "1b ctx=8192 chunk=16"),
    ("bench_ctx16k", "1b ctx=16384 chunk=16"),
    ("bench_ctx32k", "1b ctx=32768 chunk=16"),
    ("bench_fused", "1b fused writeback"),
    ("bench_fused_rp16", "1b fused+rowpipe+chunk16"),
    ("bench_scatter", "1b scatter writeback"),
    ("bench_prefill_pallas", "1b pallas prefill route"),
]


def main() -> None:
    d = Path(sys.argv[1] if len(sys.argv) > 1 else "tpu_results")
    rows = []
    baseline = None
    baseline_backend = None
    for name, label in BENCH_ARMS:
        r = load(d, name)
        if not r:
            continue
        if r.get("error"):
            rows.append((label, None, r["error"][:60], r.get("backend")))
            continue
        v = r.get("value")
        if name == "bench":
            baseline, baseline_backend = v, r.get("backend")
        rows.append((label, v, r, r.get("backend")))

    print("## Sweep summary\n")
    gate = load(d, "compile_gate")
    if gate and isinstance(gate.get("arms"), dict):
        bad = [n for n, a in gate["arms"].items() if not a.get("ok")]
        if bad:
            errs = ", ".join(
                f"`{n}` ({gate['arms'][n].get('error', '')[:80]})"
                for n in bad)
            print(f"**Mosaic compile gate: {len(bad)} arm(s) FAILED:** "
                  f"{errs}\n")
        else:
            n = len(gate["arms"])
            print(f"Mosaic compile gate: all {n} kernel arms compiled "
                  f"({gate.get('backend')}"
                  + (", interpret" if gate.get("interpret") else "")
                  + ")\n")

    print("| Arm | tok/s | vs default | pct_roofline | backend |")
    print("|---|---|---|---|---|")
    for label, v, r, backend in rows:
        if v is None:
            note = r if isinstance(r, str) else "no value recorded"
            print(f"| {label} | ERROR | {note} | | {backend} |")
            continue
        # A ratio across backends is meaningless (a CPU-fallback arm vs a
        # TPU default, or vice versa) — refuse rather than mis-compare.
        # Artifacts without a backend tag are unknown provenance: also
        # refuse (None == None must not earn a confident ratio).
        if baseline and label != "1b bf16 (default)":
            if backend is None or baseline_backend is None:
                rel = "n/a (backend unknown)"
            elif backend == baseline_backend:
                rel = f"{v / baseline:.3f}x"
            else:
                rel = "n/a (backend mismatch)"
        else:
            rel = "—"
        roof = r.get("pct_roofline", "")
        suffix = ""
        if r.get("structural_only"):
            # Surface the carried on-chip figure right where maintainers
            # read the table — the CPU number must never stand in for it.
            best = r.get("best_tpu") or {}
            chip = (f"; best on-chip {best['value']}"
                    + (f" @ {best['ts']}" if best.get("ts") else "")
                    if best.get("value") else "")
            suffix = f" (structural only{chip})"
        print(f"| {label} | {v}{suffix} | {rel} | {roof} | {backend} |")

    prof = load(d, "decode_profile")
    if prof and not prof.get("error"):
        print("\n### Decode step components (ms)\n")
        for k in ("full_step_ms", "forward_only_ms", "attention_only_ms",
                  "matmul_and_rest_ms", "sampling_only_ms",
                  "sample_overhead_ms", "ideal_weight_stream_ms"):
            if k in prof:
                print(f"- {k}: {prof[k]}")

    spec = load(d, "spec")
    spec_mq = load(d, "spec_mq")
    for tag, r in (("spec", spec), ("spec+mq-kernel", spec_mq)):
        if r and isinstance(r, dict):
            print(f"\n### {tag}: {json.dumps(r)[:300]}")

    cp = load(d, "cp_kernel")
    if cp:
        print("\n### CP kernel:",
              {k: cp.get(k) for k in ("cp_pallas_ms", "cp_xla_fallback_ms",
                                      "pallas_vs_xla",
                                      "single_device_kernel_ms", "error")})

    pd = load(d, "pd_handoff")
    if pd:
        print("\n### PD handoff:",
              {k: pd.get(k) for k in pd if k.startswith("ctx_")
               or k == "error"})

    for tag in ("serve", "serve_warm", "serve_long", "serve_sarathi"):
        sv = load(d, tag)
        if sv:
            print(f"\n### {tag}:",
                  {k: sv.get(k) for k in ("req_per_s", "decode_tok_per_s",
                                          "ttft_ms", "tbt_ms",
                                          "ttft_spans_p50_ms",
                                          "prefill_chunk", "sarathi",
                                          "sarathi_rides", "errors")})

    kv = load(d, "kvwb")
    if kv:
        print("\n### kv writeback micro:", json.dumps(kv)[:300])

    rc = load(d, "real_ckpt")
    if rc:
        print("\n### real checkpoint parity:", json.dumps(rc)[:300])


if __name__ == "__main__":
    main()
