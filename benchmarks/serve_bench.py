"""Full-stack serving benchmark: req/s + TTFT/E2E percentiles through the
real HTTP path (client → master → engine agent → TPU → SSE back).

This measures the BASELINE.json north-star metrics ("req/s + p50/p99 TTFT")
on whatever accelerator is attached; `bench.py` (repo root) remains the
driver's single-line engine-throughput metric.

Default is --stack multiproc: coordination server, master and engine
agent each run as their OWN process, exactly like a real deployment.
(The old in-process mode kept master+agent+engine+client threads inside
one interpreter, so the GIL charged engine host work to the wire — the
round-2 'master+wire' span was mostly that artifact.)

    python benchmarks/serve_bench.py --requests 32 --concurrency 8
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import statistics
import subprocess
import sys
import threading
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from xllm_service_tpu.utils import pin_cpu_platform_if_requested

pin_cpu_platform_if_requested()

import numpy as np
import requests


def percentile(xs, p):
    if not xs:
        return 0.0
    xs = sorted(xs)
    k = min(len(xs) - 1, int(round((p / 100) * (len(xs) - 1))))
    return xs[k]


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def drive(base: str, stats_url: str, args, vocab: int) -> dict:
    """Fire the workload at `base` and collect client + span metrics."""
    rng = np.random.default_rng(0)

    # Warmup: compile prefill bucket + decode program.
    requests.post(base + "/v1/completions", json={
        "model": "bench",
        "prompt": [int(t) for t in rng.integers(10, vocab - 10,
                                                args.prompt_tokens)],
        "max_tokens": 4, "temperature": 0, "ignore_eos": True}, timeout=600)

    ttfts, e2es, tbts, errors = [], [], [], [0]
    lock = threading.Lock()
    work = list(range(args.requests))
    # np.random.Generator is not thread-safe: give each worker its own
    # spawned child stream instead of racing one shared state.
    child_rngs = rng.spawn(args.concurrency)

    def worker(wrng):
        while True:
            with lock:
                if not work:
                    return
                work.pop()
            prompt = [int(t) for t in wrng.integers(10, vocab - 10,
                                                    args.prompt_tokens)]
            t0 = time.perf_counter()
            try:
                r = requests.post(base + "/v1/completions", json={
                    "model": "bench", "prompt": prompt,
                    "max_tokens": args.max_tokens, "temperature": 0,
                    "ignore_eos": True, "stream": True}, stream=True,
                    timeout=600)
                ttft = None
                gaps = []
                last = None
                for line in r.iter_lines():
                    if not line.startswith(b"data: "):
                        continue
                    now = time.perf_counter()
                    if ttft is None:
                        ttft = now - t0
                    elif line != b"data: [DONE]":
                        # Inter-delta gap after the first content delta:
                        # the user-perceived stall metric (a decode pause
                        # behind a prefill install shows up HERE, not in
                        # averaged throughput).
                        gaps.append((now - last) * 1000)
                    last = now
                e2e = time.perf_counter() - t0
                with lock:
                    ttfts.append(ttft * 1000)
                    e2es.append(e2e * 1000)
                    tbts.extend(gaps)
            except Exception:  # noqa: BLE001
                with lock:
                    errors[0] += 1

    t_start = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(child_rngs[i],))
               for i in range(args.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start

    n_ok = len(e2es)
    total_tokens = n_ok * args.max_tokens
    report = {
        "requests": args.requests,
        "concurrency": args.concurrency,
        "prompt_tokens": args.prompt_tokens,
        "max_tokens": args.max_tokens,
        "errors": errors[0],
        "req_per_s": round(n_ok / wall, 3),
        "decode_tok_per_s": round(total_tokens / wall, 1),
        "ttft_ms": {"p50": round(percentile(ttfts, 50), 1),
                    "p90": round(percentile(ttfts, 90), 1),
                    "p99": round(percentile(ttfts, 99), 1),
                    "mean": round(statistics.mean(ttfts), 1) if ttfts else 0},
        "e2e_ms": {"p50": round(percentile(e2es, 50), 1),
                   "p99": round(percentile(e2es, 99), 1)},
        # Coalesced SSE events (several deltas in one TCP read) record
        # near-0 gaps that would deflate the p50 — percentiles run over
        # gaps >= 0.5 ms; max is valid either way.
        "tbt_ms": {"p50": round(percentile(
                       [g for g in tbts if g >= 0.5], 50), 1),
                   "p99": round(percentile(
                       [g for g in tbts if g >= 0.5], 99), 1),
                   "max": round(max(tbts), 1) if tbts else 0},
    }
    if getattr(args, "prefill_chunk", 0) > 0:
        report["prefill_chunk"] = args.prefill_chunk
        report["sarathi"] = os.environ.get("XLLM_SARATHI", "1") != "0"

    # TTFT span breakdown (VERDICT r3 weak #1: name where the time goes).
    # client TTFT = master+wire + agent span; agent span = engine queue +
    # prefill + streamer flush. Spans come from the agent's /stats so
    # this works across process boundaries.
    try:
        stats = requests.get(stats_url, timeout=10).json()
        spans = stats.get("ttft_spans", {})
        if getattr(args, "prefill_chunk", 0) > 0:
            # Proof the Sarathi arm exercised the ride path (0 means the
            # A/B silently measured the whole-install configuration).
            report["sarathi_rides"] = stats.get("sarathi_rides", 0)
    except Exception:  # noqa: BLE001
        spans = {}
    if spans.get("n") and ttfts:
        client_p50 = percentile(ttfts, 50)
        agent_p50 = spans["agent_accept_to_first_delta_ms"]
        report["ttft_spans_p50_ms"] = {
            "client": round(client_p50, 1),
            "agent_accept_to_first_delta": agent_p50,
            "master_and_wire": round(client_p50 - agent_p50, 1),
            "engine_queue": spans["engine_queue_ms"],
            "engine_prefill": spans["engine_prefill_ms"],
        }
    # Per-stage master span table (GET /admin/hotpath, always-on recorder):
    # attributes the master+wire leg to schedule / enrich / forward /
    # first_delta so future rounds can localize a regression without
    # re-instrumenting.
    try:
        r = requests.get(base + "/admin/hotpath", timeout=10)
        if r.status_code == 200:
            stages = r.json().get("stages", {})
            report["master_stages_ms"] = {
                stage: row for stage, row in stages.items() if row.get("n")}
    except requests.RequestException:
        pass
    return report


def run_multiproc(args, model_config: str, on_accel: bool) -> dict:
    """Deployment-shaped stack: 3 separate OS processes."""
    coord_port, http_port, rpc_port = free_port(), free_port(), free_port()
    agent_port = free_port()
    procs: list[subprocess.Popen] = []
    logdir = Path(os.environ.get("XLLM_BENCH_LOGDIR", "/tmp"))

    def spawn(name, cmd):
        log = open(logdir / f"serve_bench_{name}.log", "w")
        p = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                             cwd=str(REPO))
        procs.append(p)
        return p

    try:
        spawn("coord", [sys.executable, "-m",
                        "xllm_service_tpu.coordination.server",
                        "--port", str(coord_port)])
        time.sleep(0.5)
        spawn("master", [sys.executable, "-m", "xllm_service_tpu.master",
                         "--coordination-addr", f"127.0.0.1:{coord_port}",
                         "--host", "127.0.0.1",
                         "--http-port", str(http_port),
                         "--rpc-port", str(rpc_port)])
        if model_config == "tiny":
            # tiny_f32 = the same float32 tiny shape the inproc stack
            # builds, so the two stacks benchmark the SAME model on CPU.
            agent_model = "tiny_f32"
            eng_args = ["--max-seq-len", "512", "--num-pages", "256",
                        "--decode-horizon", "4"]
        else:
            agent_model = model_config
            # Full horizon 32 is safe for TTFT now: decode calls shrink
            # to admission_horizon while requests are waiting.
            eng_args = ["--max-seq-len", "1024", "--num-pages", "1024",
                        "--decode-horizon", "32"]
        if args.prefill_chunk > 0:
            eng_args += ["--prefill-chunk", str(args.prefill_chunk)]
        spawn("agent", [sys.executable, "-m",
                        "xllm_service_tpu.engine.agent",
                        "--coordination-addr", f"127.0.0.1:{coord_port}",
                        "--host", "127.0.0.1", "--port", str(agent_port),
                        "--model-id", "bench",
                        "--model-config", agent_model,
                        "--generation-flush-ms", "2.0",
                        "--max-batch-size", "16", *eng_args])

        base = f"http://127.0.0.1:{http_port}"
        names = ("coord", "master", "agent")
        deadline = time.monotonic() + 600   # agent boot includes warmup
        while time.monotonic() < deadline:
            for name, p in zip(names, procs):
                if p.poll() is not None:
                    raise RuntimeError(
                        f"{name} process died rc={p.returncode} — see "
                        f"{logdir}/serve_bench_{name}.log")
            try:
                r = requests.post(base + "/v1/completions", json={
                    "model": "bench", "prompt": [11, 12, 13],
                    "max_tokens": 2, "temperature": 0,
                    "ignore_eos": True}, timeout=120)
                if r.status_code == 200:
                    break
            except requests.RequestException:
                pass
            time.sleep(1.0)
        else:
            raise RuntimeError("cluster never became ready")

        from xllm_service_tpu.models import base as model_base
        vocab = getattr(model_base, model_config + "_config")().vocab_size
        stats_url = f"http://127.0.0.1:{agent_port}/stats"
        return drive(base, stats_url, args, vocab)
    finally:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def run_inproc(args, model_config: str, on_accel: bool) -> dict:
    import jax.numpy as jnp

    from xllm_service_tpu.common.config import ServiceOptions
    from xllm_service_tpu.coordination.memory import (
        InMemoryCoordination,
        MemoryStore,
    )
    from xllm_service_tpu.engine.agent import AgentConfig, EngineAgent
    from xllm_service_tpu.engine.config import EngineConfig
    from xllm_service_tpu.master import Master
    from xllm_service_tpu.models import base as model_base

    if model_config == "tiny":
        mcfg = model_base.tiny_config(
            dtype=jnp.float32, max_context_len=1024)
        max_seq, pages, horizon = 512, 256, 4
        buckets = (128, 256, 512)
    else:
        mcfg = getattr(model_base, model_config + "_config")()
        max_seq, pages, horizon = 1024, 16 * 1024 // 16, 32
        buckets = (128, 256, 512, 1024)

    store = MemoryStore()
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=3.0, sync_interval_s=1.0)
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()
    ecfg = EngineConfig(
        model_id="bench", model=mcfg, num_pages=pages, page_size=16,
        max_batch_size=16, max_seq_len=max_seq, prefill_buckets=buckets,
        decode_horizon=horizon,
        prefill_chunk_tokens=max(0, args.prefill_chunk),
        # Pre-compile every horizon + prefill bucket at boot: on TPU a
        # cold bucket otherwise lands a ~20s XLA compile on a live
        # request's TTFT, which is boot cost, not serving latency.
        warmup_programs=on_accel)
    agent = EngineAgent(
        ecfg, AgentConfig(host="127.0.0.1", model_id="bench",
                          generation_flush_ms=2.0),
        coord=InMemoryCoordination(store)).start()
    deadline = time.time() + 30
    while time.time() < deadline and \
            master.scheduler.instance_mgr.get_instance_meta(agent.name) is None:
        time.sleep(0.1)

    try:
        return drive(f"http://127.0.0.1:{master.http_port}",
                     f"http://{agent.name}/stats", args, mcfg.vocab_size)
    finally:
        agent.stop()
        master.stop()
        store.close()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--prompt-tokens", type=int, default=256)
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--model-config", default="auto",
                    help="auto = bench_1b on accelerator, tiny on CPU")
    ap.add_argument("--stack", default="multiproc",
                    choices=("multiproc", "inproc"),
                    help="multiproc (deployment-shaped; default) or the "
                         "old single-interpreter stack")
    ap.add_argument("--prefill-chunk", type=int, default=0,
                    help="engine chunked-prefill tokens (0 = whole-suffix "
                         "installs); chunks ride decode steps unless "
                         "XLLM_SARATHI=0")
    args = ap.parse_args()

    if args.stack == "multiproc":
        # Probe the accelerator in a SUBPROCESS: the agent process owns
        # the chip; initializing it here too would contend for the
        # (exclusive) relay attachment, and a dead relay would hang an
        # in-process init past any driver timeout.
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            on_accel = False
        else:
            try:
                r = subprocess.run(
                    [sys.executable, "-c",
                     "import jax; assert jax.default_backend() != 'cpu'"],
                    timeout=150, capture_output=True)
                on_accel = r.returncode == 0
            except Exception:  # noqa: BLE001 — timeout or spawn failure
                on_accel = False
        backend = "tpu" if on_accel else "cpu"
        if not on_accel:
            os.environ["JAX_PLATFORMS"] = "cpu"   # inherited by children
    else:
        import jax

        on_accel = jax.default_backend() != "cpu"
        backend = jax.default_backend()

    model_config = args.model_config
    if model_config == "auto":
        model_config = "bench_1b" if on_accel else "tiny"

    runner = run_multiproc if args.stack == "multiproc" else run_inproc
    report = runner(args, model_config, on_accel)
    report = {"backend": backend,
              "model_config": model_config,
              "stack": args.stack, **report}
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    main()
