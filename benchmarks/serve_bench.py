"""Full-stack serving benchmark: req/s + TTFT/E2E percentiles through the
real HTTP path (client → master → engine agent → TPU → SSE back).

This measures the BASELINE.json north-star metrics ("req/s + p50/p99 TTFT")
on whatever accelerator is attached; `bench.py` (repo root) remains the
driver's single-line engine-throughput metric.

    python benchmarks/serve_bench.py --requests 32 --concurrency 8
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import threading
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from xllm_service_tpu.utils import pin_cpu_platform_if_requested

pin_cpu_platform_if_requested()

import numpy as np
import requests


def percentile(xs, p):
    if not xs:
        return 0.0
    xs = sorted(xs)
    k = min(len(xs) - 1, int(round((p / 100) * (len(xs) - 1))))
    return xs[k]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=32)
    ap.add_argument("--concurrency", type=int, default=8)
    ap.add_argument("--prompt-tokens", type=int, default=256)
    ap.add_argument("--max-tokens", type=int, default=64)
    ap.add_argument("--model-config", default="auto",
                    help="auto = bench_1b on accelerator, tiny on CPU")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from xllm_service_tpu.common.config import ServiceOptions
    from xllm_service_tpu.coordination.memory import (
        InMemoryCoordination,
        MemoryStore,
    )
    from xllm_service_tpu.engine.agent import AgentConfig, EngineAgent
    from xllm_service_tpu.engine.config import EngineConfig
    from xllm_service_tpu.master import Master
    from xllm_service_tpu.models import base as model_base

    on_accel = jax.default_backend() != "cpu"
    if args.model_config == "auto":
        args.model_config = "bench_1b" if on_accel else "tiny"
    if args.model_config == "tiny":
        mcfg = model_base.tiny_config(
            dtype=jnp.float32, max_context_len=1024)
        max_seq, pages, horizon = 512, 256, 4
        buckets = (128, 512)
    else:
        mcfg = getattr(model_base, args.model_config + "_config")()
        max_seq, pages, horizon = 1024, 16 * 1024 // 16, 8
        buckets = (128, 512, 1024)

    store = MemoryStore()
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=3.0, sync_interval_s=1.0)
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()
    ecfg = EngineConfig(
        model_id="bench", model=mcfg, num_pages=pages, page_size=16,
        max_batch_size=16, max_seq_len=max_seq, prefill_buckets=buckets,
        decode_horizon=horizon,
        # Pre-compile every horizon + prefill bucket at boot: on TPU a
        # cold bucket otherwise lands a ~20s XLA compile on a live
        # request's TTFT, which is boot cost, not serving latency.
        warmup_programs=on_accel)
    agent = EngineAgent(
        ecfg, AgentConfig(host="127.0.0.1", model_id="bench",
                          generation_flush_ms=2.0),
        coord=InMemoryCoordination(store)).start()
    deadline = time.time() + 30
    while time.time() < deadline and \
            master.scheduler.instance_mgr.get_instance_meta(agent.name) is None:
        time.sleep(0.1)

    base = f"http://127.0.0.1:{master.http_port}"
    rng = np.random.default_rng(0)
    vocab = mcfg.vocab_size

    # Warmup: compile prefill bucket + decode program.
    requests.post(base + "/v1/completions", json={
        "model": "bench",
        "prompt": [int(t) for t in rng.integers(10, vocab - 10,
                                                args.prompt_tokens)],
        "max_tokens": 4, "temperature": 0, "ignore_eos": True}, timeout=600)

    ttfts, e2es, errors = [], [], [0]
    lock = threading.Lock()
    work = list(range(args.requests))

    def worker():
        while True:
            with lock:
                if not work:
                    return
                work.pop()
            prompt = [int(t) for t in rng.integers(10, vocab - 10,
                                                   args.prompt_tokens)]
            t0 = time.perf_counter()
            try:
                r = requests.post(base + "/v1/completions", json={
                    "model": "bench", "prompt": prompt,
                    "max_tokens": args.max_tokens, "temperature": 0,
                    "ignore_eos": True, "stream": True}, stream=True,
                    timeout=600)
                ttft = None
                for line in r.iter_lines():
                    if line.startswith(b"data: ") and ttft is None:
                        ttft = time.perf_counter() - t0
                e2e = time.perf_counter() - t0
                with lock:
                    ttfts.append(ttft * 1000)
                    e2es.append(e2e * 1000)
            except Exception:  # noqa: BLE001
                with lock:
                    errors[0] += 1

    t_start = time.perf_counter()
    threads = [threading.Thread(target=worker)
               for _ in range(args.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start

    n_ok = len(e2es)
    total_tokens = n_ok * args.max_tokens
    report = {
        "backend": jax.default_backend(),
        "model_config": args.model_config,
        "requests": args.requests,
        "concurrency": args.concurrency,
        "prompt_tokens": args.prompt_tokens,
        "max_tokens": args.max_tokens,
        "errors": errors[0],
        "req_per_s": round(n_ok / wall, 3),
        "decode_tok_per_s": round(total_tokens / wall, 1),
        "ttft_ms": {"p50": round(percentile(ttfts, 50), 1),
                    "p90": round(percentile(ttfts, 90), 1),
                    "p99": round(percentile(ttfts, 99), 1),
                    "mean": round(statistics.mean(ttfts), 1) if ttfts else 0},
        "e2e_ms": {"p50": round(percentile(e2es, 50), 1),
                   "p99": round(percentile(e2es, 99), 1)},
    }
    print(json.dumps(report, indent=2))
    agent.stop()
    master.stop()
    store.close()


if __name__ == "__main__":
    main()
