#!/bin/bash
# TPU measurement sweep (round 4): retries until the flaky axon relay
# answers, then runs the round-4 conversion queue (VERDICT.md r3 "Next
# round" #1): 1b regression, first 8B-scale number, Pallas kernel
# Mosaic-validation + A/Bs, speculative decoding, PD KV-handoff timing,
# full-stack serve. Results land in tpu_results/. Each step re-checks the
# relay so a mid-sweep flake restarts the loop instead of silently
# recording CPU-fallback numbers.
set -u
cd /root/repo
mkdir -p tpu_results
DEADLINE=$(( $(date +%s) + ${SWEEP_BUDGET_S:-40000} ))   # default: ~11h

probe() {
  timeout -k 10 150 python - <<'EOF' >/dev/null 2>&1
import jax
assert jax.default_backend() != "cpu"
EOF
}

# run <name> <timeout_s> <cmd...>: run one step, then verify it really ran
# on the TPU (every bench emits a "backend" field) and the relay is still
# up. Returns nonzero (flake) — caller restarts the loop.
FAILED_STEPS=""
run_step() {
  local name="$1" to="$2"; shift 2
  timeout -k 15 "$to" "$@" > "tpu_results/$name.json" 2> "tpu_results/$name.err"
  local rc=$?
  echo "$name rc=$rc $(head -c 200 "tpu_results/$name.json")"
  if [ "$rc" -ne 0 ]; then
    # Probe FIRST: if the relay died, the step crashed because of the
    # flake — retry the loop instead of recording a phantom failure.
    if ! probe; then
      echo "relay died during failed step $name — restarting sweep loop"
      return 1
    fi
    # Relay is healthy: the step genuinely failed (OOM, crash, timeout);
    # record it and keep going — a retry would fail the same way. The
    # final exit code reflects it so 'sweep complete' can't mask it.
    FAILED_STEPS="$FAILED_STEPS $name(rc=$rc)"
    return 0
  fi
  # A step that started while the relay was down silently initializes the
  # CPU backend even if the relay recovers mid-run: reject any artifact
  # that doesn't claim the tpu backend (every bench emits "backend").
  # BUT an rc=0 artifact carrying an "error" field ran fine and failed
  # INSIDE the bench (e.g. a Mosaic compile error) — if the relay is
  # still alive that's a genuine failure, not a flake: restarting would
  # loop forever re-hitting the same error. Record it and move on.
  if ! grep -q '"backend": "tpu"' "tpu_results/$name.json"; then
    if grep -q '"error"' "tpu_results/$name.json" \
        && ! grep -q '"backend": "cpu"' "tpu_results/$name.json" \
        && grep -q '"backend"' "tpu_results/$name.json" && probe; then
      echo "step $name failed inside the bench (relay alive) — recorded"
      FAILED_STEPS="$FAILED_STEPS $name(bench-error)"
      return 0
    fi
    echo "step $name did not run on TPU — restarting sweep loop"
    return 1
  fi
  # rc=0 AND backend=tpu, but the artifact still carries an "error" field:
  # the bench caught an in-run failure (e.g. cp_bench records a Mosaic
  # compile error and exits 0). Count it so 'sweep complete' can't mask it.
  if grep -q '"error"' "tpu_results/$name.json"; then
    echo "step $name recorded an in-bench error on TPU"
    FAILED_STEPS="$FAILED_STEPS $name(bench-error)"
  fi
  if ! probe; then
    echo "relay died after step $name — restarting sweep loop"
    return 1
  fi
  return 0
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe; then
    echo "=== relay alive at $(date) ==="
    FAILED_STEPS=""
    # 0. Mosaic compile gate: AOT-compile EVERY kernel arm first so a
    # Mosaic rejection is a named per-arm verdict, not a mid-sweep crash.
    run_step compile_gate 1800 python bench.py --compile-only \
      || { sleep 60; continue; }
    # 1. bench.py 1b (the driver contract number; regression check vs 1091)
    run_step bench 900 python bench.py || { sleep 60; continue; }
    # 2. FIRST north-star-scale number: Llama-3-8B shapes, weight-only int8
    run_step bench_8b 1800 env XLLM_BENCH_MODEL=8b python bench.py \
      || { sleep 60; continue; }
    # 2b. FIRST MoE on-chip number: MLA+MoE bench shape, int8 experts
    # (BASELINE config 4's single-chip datum)
    run_step bench_moe 1800 env XLLM_BENCH_MODEL=moe python bench.py \
      || { sleep 60; continue; }
    # 3. 1b int8 A/B
    run_step bench_int8 900 env XLLM_QUANT=int8 python bench.py \
      || { sleep 60; continue; }
    # 3b/3c. page-walk DMA chunk size A/B (decode is DMA-latency-bound at
    # serving shapes; bigger chunks = fewer, larger DMAs)
    run_step bench_chunk16 900 env XLLM_PAGE_CHUNK=16 python bench.py \
      || { sleep 60; continue; }
    run_step bench_chunk32 900 env XLLM_PAGE_CHUNK=32 python bench.py \
      || { sleep 60; continue; }
    # 3d. long-context decode (the page walk dominates; chunk16 together)
    run_step bench_ctx2k 900 \
      env XLLM_BENCH_CTX=2048 XLLM_PAGE_CHUNK=16 python bench.py \
      || { sleep 60; continue; }
    # 3d2-3d4. the 8k-32k curve (VERDICT r4 next #7): walk depth scales,
    # batch shrinks (8k:B2 via ladder, 16k:B2, 32k:B1) — together with
    # 3d this gives tok/s vs context length at four points.
    run_step bench_ctx8k 1200 \
      env XLLM_BENCH_CTX=8192 XLLM_PAGE_CHUNK=16 python bench.py \
      || { sleep 60; continue; }
    run_step bench_ctx16k 1200 \
      env XLLM_BENCH_CTX=16384 XLLM_PAGE_CHUNK=16 python bench.py \
      || { sleep 60; continue; }
    run_step bench_ctx32k 1800 \
      env XLLM_BENCH_CTX=32768 XLLM_PAGE_CHUNK=16 python bench.py \
      || { sleep 60; continue; }
    # 3e. cross-row DMA pipelining in the decode kernel
    run_step bench_rowpipe 900 env XLLM_PAGE_PIPELINE=row python bench.py \
      || { sleep 60; continue; }
    # 3f. rowpipe + chunk16 combined
    run_step bench_rowpipe16 900 \
      env XLLM_PAGE_PIPELINE=row XLLM_PAGE_CHUNK=16 python bench.py \
      || { sleep 60; continue; }
    # 4. fused append+attend decode kernel (Mosaic validation + A/B vs 1.)
    run_step bench_fused 900 env XLLM_KV_WRITEBACK=fused python bench.py \
      || { sleep 60; continue; }
    # 4b. fused + cross-row pipelining + chunk16
    run_step bench_fused_rp16 900 env XLLM_KV_WRITEBACK=fused \
      XLLM_PAGE_PIPELINE=row XLLM_PAGE_CHUNK=16 python bench.py \
      || { sleep 60; continue; }
    # 5. scatter-writeback A/B
    run_step bench_scatter 900 env XLLM_KV_WRITEBACK=scatter python bench.py \
      || { sleep 60; continue; }
    # 6. Pallas prefill route under real Mosaic (admission exercises it)
    run_step bench_prefill_pallas 900 \
      env XLLM_PREFILL_PALLAS=1 python bench.py || { sleep 60; continue; }
    # 7. speculative decoding (target >=1.3x on repetitive workload)
    run_step spec 1200 python benchmarks/spec_bench.py || { sleep 60; continue; }
    # 8. MQ pallas verify kernel under Mosaic (validates + measures)
    run_step spec_mq 1200 env XLLM_MQ_PALLAS=1 python benchmarks/spec_bench.py \
      || { sleep 60; continue; }
    # 9. KV writeback micro (times both XLA variants internally)
    run_step kvwb 900 python benchmarks/kv_writeback_micro.py \
      || { sleep 60; continue; }
    # 9b. decode-step component profile (names the 80%-off-roofline cost)
    run_step decode_profile 900 python benchmarks/decode_profile.py \
      || { sleep 60; continue; }
    # 10. CP paged-decode kernel vs XLA gather path under real Mosaic
    run_step cp_kernel 1200 python benchmarks/cp_bench.py \
      || { sleep 60; continue; }
    # 10b. CP kernel at 16k context (ring/CP design claims at real
    # lengths, VERDICT r4 next #7)
    run_step cp_kernel_16k 1800 \
      env XLLM_CP_CTX=16384 python benchmarks/cp_bench.py \
      || { sleep 60; continue; }
    # 11. PD KV handoff: device path vs host msgpack path at 2k/8k ctx
    run_step pd_handoff 1200 python benchmarks/pd_handoff_bench.py \
      || { sleep 60; continue; }
    # 12. serve bench (full stack TTFT; measures the 24x-gap fixes)
    run_step serve 1800 python benchmarks/serve_bench.py \
      || { sleep 60; continue; }
    # 13. serve bench, second boot (persistent-compile-cache warmup check)
    run_step serve_warm 1800 python benchmarks/serve_bench.py \
      || { sleep 60; continue; }
    # 14. real published checkpoint end-to-end (downloads when the
    # sandbox has egress; records the attempt as "skipped" when not)
    run_step real_ckpt 3600 python scripts/real_ckpt_drill.py \
      || { sleep 60; continue; }
    # 15. Sarathi serve A/B at long prompts: chunked installs ride
    # decode programs (shared GEMMs = decode rows skip their own weight
    # stream — a TPU-side win CPU can't show; CPU A/B at 384-token
    # prompts measured riding ~parity with standalone chunking and both
    # BELOW unchunked, see NOTES_ROUND5). Only flip serve defaults if
    # serve_sarathi beats serve_long here.
    run_step serve_long 1800 python benchmarks/serve_bench.py \
      --prompt-tokens 768 --max-tokens 64 || { sleep 60; continue; }
    # chunk 128 (not 256): the adaptive queue-pressure bypass whole-
    # installs suffixes <= 4*chunk when arrivals are waiting, and the
    # closed-loop bench always has arrivals waiting — 768 > 4*128 keeps
    # chunking (and riding) engaged. The report's sarathi_rides counter
    # proves the path actually ran.
    run_step serve_sarathi 1800 python benchmarks/serve_bench.py \
      --prompt-tokens 768 --max-tokens 64 --prefill-chunk 128 \
      || { sleep 60; continue; }
    # Digest everything for BASELINE.md / the next round.
    python benchmarks/summarize_sweep.py tpu_results \
      > tpu_results/summary.md 2>/dev/null || true
    if [ -n "$FAILED_STEPS" ]; then
      echo "=== sweep finished at $(date) with FAILED steps:$FAILED_STEPS ==="
      exit 2
    fi
    echo "=== sweep complete at $(date) ==="
    exit 0
  fi
  echo "relay down at $(date); sleeping 90s"
  sleep 90
done
echo "deadline reached; relay never stayed up"
exit 1
