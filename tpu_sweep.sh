#!/bin/bash
# TPU measurement sweep: retries until the flaky axon relay answers, then
# runs the whole round-2 TPU queue (NOTES_ROUND2.md "TPU to-do").
# Results land in tpu_results/. Each step re-checks the relay so a
# mid-sweep flake restarts the loop instead of silently recording
# CPU-fallback numbers.
set -u
cd /root/repo
mkdir -p tpu_results
DEADLINE=$(( $(date +%s) + ${SWEEP_BUDGET_S:-14400} ))   # default: give up after 4h

probe() {
  timeout 150 python - <<'EOF' >/dev/null 2>&1
import jax
assert jax.default_backend() != "cpu"
EOF
}

# run <name> <timeout_s> <cmd...>: run one step, then verify it really ran
# on the TPU (every bench emits a "backend" field) and the relay is still
# up. Returns nonzero (flake) — caller restarts the loop.
FAILED_STEPS=""
run_step() {
  local name="$1" to="$2"; shift 2
  timeout "$to" "$@" > "tpu_results/$name.json" 2> "tpu_results/$name.err"
  local rc=$?
  echo "$name rc=$rc $(head -c 200 "tpu_results/$name.json")"
  if [ "$rc" -ne 0 ]; then
    # Probe FIRST: if the relay died, the step crashed because of the
    # flake — retry the loop instead of recording a phantom failure.
    if ! probe; then
      echo "relay died during failed step $name — restarting sweep loop"
      return 1
    fi
    # Relay is healthy: the step genuinely failed (OOM, crash, timeout);
    # record it and keep going — a retry would fail the same way. The
    # final exit code reflects it so 'sweep complete' can't mask it.
    FAILED_STEPS="$FAILED_STEPS $name(rc=$rc)"
    return 0
  fi
  # A step that started while the relay was down silently initializes the
  # CPU backend even if the relay recovers mid-run: reject any artifact
  # that doesn't claim the tpu backend (every bench emits "backend").
  # BUT an rc=0 artifact carrying an "error" field ran fine and failed
  # INSIDE the bench (e.g. a Mosaic compile error) — if the relay is
  # still alive that's a genuine failure, not a flake: restarting would
  # loop forever re-hitting the same error. Record it and move on.
  if ! grep -q '"backend": "tpu"' "tpu_results/$name.json"; then
    # Error artifacts carry "backend" too (bench.py _fail): an error that
    # happened ON the tpu backend is a genuine in-bench failure worth
    # recording, but one claiming cpu (or claiming no backend at all)
    # means the step silently initialized the CPU backend while the relay
    # was down and failed BECAUSE of it — restart the sweep loop so it
    # reruns on TPU instead of recording a phantom failure.
    if grep -q '"error"' "tpu_results/$name.json" \
        && ! grep -q '"backend": "cpu"' "tpu_results/$name.json" \
        && grep -q '"backend"' "tpu_results/$name.json" && probe; then
      echo "step $name failed inside the bench (relay alive) — recorded"
      FAILED_STEPS="$FAILED_STEPS $name(bench-error)"
      return 0
    fi
    echo "step $name did not run on TPU — restarting sweep loop"
    return 1
  fi
  if ! probe; then
    echo "relay died after step $name — restarting sweep loop"
    return 1
  fi
  return 0
}

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if probe; then
    echo "=== relay alive at $(date) ==="
    FAILED_STEPS=""
    # 1. bench.py (the driver contract number)
    run_step bench 900 python bench.py || { sleep 60; continue; }
    # 2. fused append+attend decode kernel (Mosaic validation + A/B vs 1.)
    run_step bench_fused 900 env XLLM_KV_WRITEBACK=fused python bench.py \
      || { sleep 60; continue; }
    # 3. scatter-writeback A/B
    run_step bench_scatter 900 env XLLM_KV_WRITEBACK=scatter python bench.py \
      || { sleep 60; continue; }
    # 3b. weight-only int8 (the HBM-bound decode lever)
    run_step bench_int8 900 env XLLM_QUANT=int8 python bench.py \
      || { sleep 60; continue; }
    # 4. speculative decoding
    run_step spec 1200 python benchmarks/spec_bench.py || { sleep 60; continue; }
    # 5. KV writeback micro (times both XLA variants internally)
    run_step kvwb 900 python benchmarks/kv_writeback_micro.py \
      || { sleep 60; continue; }
    # 6. MQ pallas verify kernel under Mosaic (validates + measures)
    run_step spec_mq 1200 env XLLM_MQ_PALLAS=1 python benchmarks/spec_bench.py \
      || { sleep 60; continue; }
    # 7. serve bench (full stack TTFT)
    run_step serve 1200 python benchmarks/serve_bench.py \
      || { sleep 60; continue; }
    if [ -n "$FAILED_STEPS" ]; then
      echo "=== sweep finished at $(date) with FAILED steps:$FAILED_STEPS ==="
      exit 2
    fi
    echo "=== sweep complete at $(date) ==="
    exit 0
  fi
  echo "relay down at $(date); sleeping 90s"
  sleep 90
done
echo "deadline reached; relay never stayed up"
exit 1
