"""XLLM_LEAK_DEBUG runtime leak-verifier tests: per-pair balance
counters on the instrumented acquire/release sites, double-release and
strict-leak verdicts, the labeled-series tombstone half (the resurrected
PR-12 gauge-resurrection bug, caught at runtime, with the fixed
membership-gated heartbeat path as control), the escape hatch, and
passthrough-when-disabled. The static half of this round's regression
pair lives in tests/test_xlint.py / pair_regress.py."""

import threading

import pytest

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.flightrecorder import FlightRecorder
from xllm_service_tpu.common.metrics import (
    INSTANCE_QUEUE_DEPTH,
    evict_series,
)
from xllm_service_tpu.common.types import LoadMetrics
from xllm_service_tpu.devtools import lifecycle
from xllm_service_tpu.overload.admission import AdmissionController
from xllm_service_tpu.scheduler.instance_mgr import InstanceMgr

from fakes import FakeChannel, make_meta

BLOCK = 16


@pytest.fixture()
def leak_debug():
    """Arm the verifier for the test body; restore the PRIOR state on
    teardown (hardcoding False would disarm a suite-wide
    XLLM_LEAK_DEBUG=1 run for every test collected after this file)."""
    was = lifecycle.debug_enabled()
    lifecycle.set_debug(True)
    lifecycle.reset_violations()
    lifecycle.reset_balances()
    yield
    lifecycle.reset_violations()
    lifecycle.reset_balances()
    lifecycle.set_debug(was)


@pytest.fixture(autouse=True)
def _reset_channels():
    FakeChannel.reset()
    yield
    FakeChannel.reset()


# ----------------------------------------------------------- escape hatch
class TestEscape:
    def test_escape_requires_reason(self):
        with pytest.raises(ValueError):
            lifecycle.escape("")
        with pytest.raises(ValueError):
            lifecycle.escape(None)

    def test_escape_suppresses_bookkeeping(self, leak_debug):
        with lifecycle.escape("test: harness owns this slot"):
            lifecycle.note_acquire("admission-slot")
            lifecycle.note_release("flight-context", key="ghost")
        assert not lifecycle.balances()
        assert not lifecycle.violations()


# ------------------------------------------------------------ passthrough
class TestPassthrough:
    def test_noop_when_disabled(self):
        if lifecycle.debug_enabled():
            pytest.skip("XLLM_LEAK_DEBUG armed for this whole run")
        lifecycle.note_acquire("admission-slot")
        lifecycle.note_release("flight-context", key="ghost")
        lifecycle.note_series_evicted("m", ("x",))
        lifecycle.note_series_created("m", ("x",))
        assert not lifecycle.balances()
        assert not lifecycle.violations()


# -------------------------------------------------------- balance verdicts
class TestBalances:
    def test_strict_imbalance_is_a_leak(self, leak_debug):
        lifecycle.note_acquire("admission-slot")
        vs = lifecycle.strict_imbalances()
        assert len(vs) == 1 and vs[0].kind == "leak"
        assert "unreleased acquisition" in vs[0].message
        lifecycle.note_release("admission-slot")
        assert not lifecycle.strict_imbalances()

    def test_non_strict_imbalance_not_reported(self, leak_debug):
        # retry-budget is a token bucket, not a strict pair.
        lifecycle.note_acquire("retry-budget")
        assert not lifecycle.strict_imbalances()

    def test_double_release_caught(self, leak_debug):
        lifecycle.note_release("admission-slot")
        vs = lifecycle.violations()
        assert len(vs) == 1 and vs[0].kind == "double-release"

    def test_idempotent_pair_zero_balance_release_quiet(self, leak_debug):
        # span-pending is pop-style: promote/drop of an unknown trace is
        # a no-op, not a double-release.
        lifecycle.note_release("span-pending", key="t1")
        assert not lifecycle.violations()

    def test_note_reset_drops_balances(self, leak_debug):
        lifecycle.note_acquire("admission-slot")
        lifecycle.note_acquire("admission-slot")
        lifecycle.note_reset("admission-slot")
        assert not lifecycle.strict_imbalances()


# ------------------------------------------- instrumented real pair sites
class TestAdmissionSlot:
    def test_leaked_slot_caught_at_teardown(self, leak_debug):
        ctl = AdmissionController()
        ctl.configure(per_instance_limit=4)
        ok, _, _ = ctl.try_admit("interactive", live=0, burn_hot=False)
        assert ok
        vs = lifecycle.strict_imbalances()
        assert vs and vs[0].pair == "admission-slot"

    def test_balanced_slot_quiet(self, leak_debug):
        ctl = AdmissionController()
        ctl.configure(per_instance_limit=4)
        ok, _, _ = ctl.try_admit("interactive", live=0, burn_hot=False)
        assert ok
        ctl.release()
        assert not lifecycle.strict_imbalances()
        assert not lifecycle.violations()

    def test_release_without_admit_is_double_release(self, leak_debug):
        ctl = AdmissionController()
        ctl.release()
        vs = lifecycle.violations()
        assert vs and vs[0].kind == "double-release" \
            and vs[0].pair == "admission-slot"


class TestFlightContext:
    def test_leaked_provider_caught(self, leak_debug):
        rec = FlightRecorder(capacity=8)
        rec.add_context_provider("ctx", lambda: {})
        vs = lifecycle.strict_imbalances()
        assert vs and vs[0].pair == "flight-context"
        rec.remove_context_provider("ctx")
        assert not lifecycle.strict_imbalances()

    def test_replacement_keeps_balance_at_one(self, leak_debug):
        # Re-registering under the same name replaces the provider — the
        # balance must stay 1 (release-then-acquire), not grow.
        rec = FlightRecorder(capacity=8)
        rec.add_context_provider("ctx", lambda: {})
        rec.add_context_provider("ctx", lambda: {"v": 2})
        assert lifecycle.balances()[("flight-context", "ctx")] == 1
        rec.remove_context_provider("ctx")
        assert not lifecycle.strict_imbalances()
        assert not lifecycle.violations()


# --------------------------------------------- PR-12 gauge resurrection
class TestSeriesResurrection:
    def test_stale_write_after_evict_caught(self, leak_debug):
        """The resurrected PR-12 bug, runtime half: a racing writer
        re-creates a labeled child after the owner's eviction."""
        INSTANCE_QUEUE_DEPTH.labels(instance="zombie").set(3)
        evict_series(INSTANCE_QUEUE_DEPTH, instance="zombie")
        INSTANCE_QUEUE_DEPTH.labels(instance="zombie").set(1)   # stale
        vs = lifecycle.violations()
        assert vs and vs[0].kind == "resurrected-series", vs
        assert "zombie" in vs[0].message
        evict_series(INSTANCE_QUEUE_DEPTH, instance="zombie")

    def test_revived_registration_quiet(self, leak_debug):
        """Legitimate re-registration clears the tombstone first."""
        INSTANCE_QUEUE_DEPTH.labels(instance="phoenix").set(3)
        evict_series(INSTANCE_QUEUE_DEPTH, instance="phoenix")
        lifecycle.note_series_revived("phoenix")
        INSTANCE_QUEUE_DEPTH.labels(instance="phoenix").set(1)
        assert not lifecycle.violations()
        evict_series(INSTANCE_QUEUE_DEPTH, instance="phoenix")
        lifecycle.reset_balances()

    def test_fixed_heartbeat_path_control(self, leak_debug, store):
        """The fixed path stays quiet end-to-end: a heartbeat landing
        after deregistration is dropped by the membership gate instead
        of resurrecting the evicted gauge series."""
        from xllm_service_tpu.coordination.memory import InMemoryCoordination

        coord = InMemoryCoordination(store)
        mgr = InstanceMgr(coord, ServiceOptions(block_size=BLOCK),
                          channel_factory=FakeChannel.factory,
                          start_threads=False)
        try:
            meta = make_meta("i1")
            assert mgr.register_instance(meta)
            assert mgr.record_instance_heartbeat(
                "i1", meta.incarnation_id,
                load=LoadMetrics(waiting_requests_num=2))
            mgr.deregister_instance("i1", reason="drill")
            # The late beat: incarnation check fails first (instance
            # gone), so no metric write, no resurrection.
            assert not mgr.record_instance_heartbeat(
                "i1", meta.incarnation_id,
                load=LoadMetrics(waiting_requests_num=9))
            assert not [v for v in lifecycle.violations()
                        if v.kind == "resurrected-series"]
        finally:
            mgr.stop()
            coord.close()
            lifecycle.reset_violations()
            lifecycle.reset_balances()


# ------------------------------------------------------------ chaos drill
@pytest.mark.chaos
class TestLeakDrill:
    def test_concurrent_admission_churn_is_balanced(self, leak_debug):
        """N threads hammer admit/release; the verifier must end with
        zero strict balance and no violations (the soak-leg shape
        scripts/chaos_soak.sh runs with XLLM_LEAK_DEBUG=1)."""
        ctl = AdmissionController()
        ctl.configure(per_instance_limit=64)
        errs: list = []

        def churn():
            try:
                for _ in range(200):
                    ok, _, _ = ctl.try_admit("interactive", live=0,
                                             burn_hot=False)
                    if ok:
                        ctl.release()
            except Exception as e:   # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=churn, name=f"churn-{i}")
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert not lifecycle.strict_imbalances()
        assert not lifecycle.violations()
