"""Qwen2 + DeepSeek-MoE family tests and ring-attention correctness."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xllm_service_tpu.models.base import get_model_family, tiny_config


def alloc_pages(cfg, num_pages, page_size=16):
    return jnp.zeros((cfg.num_layers, 2, num_pages, cfg.num_kv_heads,
                      page_size, cfg.head_dim), cfg.dtype)


class TestQwen2:
    def test_decode_matches_prefill_with_bias(self):
        cfg = tiny_config(dtype=jnp.float32, qkv_bias=True)
        fam = get_model_family("qwen2")
        params = fam.init_params(cfg, jax.random.PRNGKey(0))
        # Biases must exist and be non-degenerate in the pytree.
        assert "bias" in params["layers"]["q_proj"]
        T = 20
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, T), 0,
                                  cfg.vocab_size)
        pt = jnp.arange(4, dtype=jnp.int32)[None, :]
        pos = jnp.arange(T)[None, :]
        kv = alloc_pages(cfg, 8)
        full, _ = fam.prefill_forward(params, cfg, toks, pos, kv, pt,
                                      jnp.zeros((1,), jnp.int32),
                                      jnp.array([T], jnp.int32))
        kv2 = alloc_pages(cfg, 8)
        _, kv2 = fam.prefill_forward(params, cfg, toks[:, :T - 1],
                                     pos[:, :T - 1], kv2, pt,
                                     jnp.zeros((1,), jnp.int32),
                                     jnp.array([T - 1], jnp.int32))
        dec, _ = fam.decode_forward(params, cfg, toks[:, T - 1],
                                    jnp.array([T - 1], jnp.int32), kv2, pt,
                                    jnp.array([T], jnp.int32))
        np.testing.assert_allclose(np.asarray(full), np.asarray(dec),
                                   rtol=2e-4, atol=2e-4)


class TestDeepSeekMoE:
    def _setup(self):
        from xllm_service_tpu.models.deepseek_moe import tiny_moe_config

        cfg = tiny_moe_config(dtype=jnp.float32)
        fam = get_model_family("deepseek_moe")
        params = fam.init_params(cfg, jax.random.PRNGKey(0))
        return cfg, fam, params

    def test_decode_matches_prefill(self):
        cfg, fam, params = self._setup()
        T = 18
        toks = jax.random.randint(jax.random.PRNGKey(2), (1, T), 0,
                                  cfg.vocab_size)
        pt = jnp.arange(4, dtype=jnp.int32)[None, :]
        pos = jnp.arange(T)[None, :]
        kv = alloc_pages(cfg, 8)
        full, _ = fam.prefill_forward(params, cfg, toks, pos, kv, pt,
                                      jnp.zeros((1,), jnp.int32),
                                      jnp.array([T], jnp.int32))
        kv2 = alloc_pages(cfg, 8)
        _, kv2 = fam.prefill_forward(params, cfg, toks[:, :T - 1],
                                     pos[:, :T - 1], kv2, pt,
                                     jnp.zeros((1,), jnp.int32),
                                     jnp.array([T - 1], jnp.int32))
        dec, _ = fam.decode_forward(params, cfg, toks[:, T - 1],
                                    jnp.array([T - 1], jnp.int32), kv2, pt,
                                    jnp.array([T], jnp.int32))
        np.testing.assert_allclose(np.asarray(full), np.asarray(dec),
                                   rtol=5e-4, atol=5e-4)

    def test_router_sparsity(self):
        """Only top-k experts receive nonzero gates per token."""
        from xllm_service_tpu.models.deepseek_moe import _moe_mlp

        cfg, fam, params = self._setup()
        lp = jax.tree.map(lambda a: a[0], params["moe"])
        x = jax.random.normal(jax.random.PRNGKey(3), (5, cfg.hidden_size),
                              jnp.float32)
        logits = x @ lp["router"]["kernel"]
        topv, _ = jax.lax.top_k(logits, cfg.num_experts_per_token)
        assert topv.shape == (5, 2)
        out = _moe_mlp(lp, x, cfg)
        assert out.shape == x.shape
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_expert_parallel_matches_single_device(self):
        cfg, fam, params = self._setup()
        from xllm_service_tpu.models.deepseek_moe import MOE_STACKED_RULES
        from xllm_service_tpu.parallel.mesh import MeshConfig, build_mesh
        from xllm_service_tpu.parallel.sharding import shard_params

        mesh = build_mesh(MeshConfig(expert=4, model=2),
                          devices=jax.devices()[:8])
        sharded = shard_params(params, mesh, MOE_STACKED_RULES)
        T = 16
        toks = jax.random.randint(jax.random.PRNGKey(4), (1, T), 0,
                                  cfg.vocab_size)
        pt = jnp.arange(4, dtype=jnp.int32)[None, :]
        pos = jnp.arange(T)[None, :]
        args = (toks, pos, alloc_pages(cfg, 8), pt,
                jnp.zeros((1,), jnp.int32), jnp.array([T], jnp.int32))
        ref, _ = fam.prefill_forward(params, cfg, *args)
        with mesh:
            got, _ = jax.jit(
                lambda p, *a: fam.prefill_forward(p, cfg, *a))(sharded, *args)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                                   rtol=2e-3, atol=2e-3)


class TestRingAttention:
    def test_matches_dense_causal(self):
        from xllm_service_tpu.ops.attention import prefill_attention
        from xllm_service_tpu.ops.ring_attention import ring_attention
        from xllm_service_tpu.parallel.mesh import MeshConfig, build_mesh

        mesh = build_mesh(MeshConfig(seq=4), devices=jax.devices()[:4])
        B, S, H, hd = 2, 64, 4, 32
        k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(k1, (B, S, H, hd), jnp.float32)
        k = jax.random.normal(k2, (B, S, H, hd), jnp.float32)
        v = jax.random.normal(k3, (B, S, H, hd), jnp.float32)

        ref = prefill_attention(q, k, v, None, None,
                                jnp.zeros((B, 1), jnp.int32),
                                jnp.zeros((B,), jnp.int32),
                                jnp.full((B,), S, jnp.int32))
        with mesh:
            got = ring_attention(q, k, v, mesh, seq_axis="seq")
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_ring_degree_2(self):
        from xllm_service_tpu.ops.attention import prefill_attention
        from xllm_service_tpu.ops.ring_attention import ring_attention
        from xllm_service_tpu.parallel.mesh import MeshConfig, build_mesh

        mesh = build_mesh(MeshConfig(seq=2), devices=jax.devices()[:2])
        B, S, H, hd = 1, 32, 2, 32
        q, k, v = (jax.random.normal(jax.random.PRNGKey(i), (B, S, H, hd),
                                     jnp.float32) for i in range(3))
        ref = prefill_attention(q, k, v, None, None,
                                jnp.zeros((B, 1), jnp.int32),
                                jnp.zeros((B,), jnp.int32),
                                jnp.full((B,), S, jnp.int32))
        with mesh:
            got = ring_attention(q, k, v, mesh, seq_axis="seq")
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


class TestMLA:
    def _setup(self):
        from xllm_service_tpu.models.deepseek_moe import tiny_mla_config

        cfg = tiny_mla_config(dtype=jnp.float32)
        fam = get_model_family("deepseek_moe")
        params = fam.init_params(cfg, jax.random.PRNGKey(0))
        return cfg, fam, params

    def test_cache_entry_is_compressed(self):
        cfg, fam, params = self._setup()
        # The pool stores one latent per token: n_kv=1, hd = dc + dr.
        assert cfg.num_kv_heads == 1
        assert cfg.head_dim == cfg.kv_lora_rank + cfg.qk_rope_head_dim
        assert "k_up" in params["layers"] and "kv_down" in params["layers"]

    def test_decode_matches_prefill(self):
        cfg, fam, params = self._setup()
        T = 21
        toks = jax.random.randint(jax.random.PRNGKey(5), (1, T), 0,
                                  cfg.vocab_size)
        pt = jnp.arange(4, dtype=jnp.int32)[None, :]
        pos = jnp.arange(T)[None, :]
        kv = alloc_pages(cfg, 8)
        full, _ = fam.prefill_forward(params, cfg, toks, pos, kv, pt,
                                      jnp.zeros((1,), jnp.int32),
                                      jnp.array([T], jnp.int32))
        kv2 = alloc_pages(cfg, 8)
        _, kv2 = fam.prefill_forward(params, cfg, toks[:, :T - 1],
                                     pos[:, :T - 1], kv2, pt,
                                     jnp.zeros((1,), jnp.int32),
                                     jnp.array([T - 1], jnp.int32))
        dec, _ = fam.decode_forward(params, cfg, toks[:, T - 1],
                                    jnp.array([T - 1], jnp.int32), kv2, pt,
                                    jnp.array([T], jnp.int32))
        np.testing.assert_allclose(np.asarray(full), np.asarray(dec),
                                   rtol=5e-4, atol=5e-4)

    def test_mla_engine_end_to_end(self):
        """MLA model through the continuous-batching engine."""
        from xllm_service_tpu.engine.config import EngineConfig
        from xllm_service_tpu.engine.engine import EngineRequest, InferenceEngine
        from xllm_service_tpu.common.request import SamplingParams
        from xllm_service_tpu.models.deepseek_moe import tiny_mla_config
        from test_engine import Collector, run_requests

        cfg = EngineConfig(
            model_family="deepseek_moe",
            model=tiny_mla_config(dtype=jnp.float32, max_context_len=256),
            num_pages=32, page_size=16, hash_block_size=32,
            max_batch_size=2, max_seq_len=128, prefill_buckets=(32, 128))
        engine = InferenceEngine(cfg)
        col = Collector()
        run_requests(engine, [EngineRequest(
            "mla", token_ids=list(range(10, 40)),
            sampling=SamplingParams(max_tokens=4, temperature=0.0,
                                    ignore_eos=True), on_output=col)])
        assert len(col.tokens) == 4
        assert col.finish_reason == "length"

    def test_mla_sharded_matches_single_device(self):
        cfg, fam, params = self._setup()
        from xllm_service_tpu.models.deepseek_moe import MOE_STACKED_RULES
        from xllm_service_tpu.parallel.mesh import MeshConfig, build_mesh
        from xllm_service_tpu.parallel.sharding import shard_params

        mesh = build_mesh(MeshConfig(expert=2, model=2),
                          devices=jax.devices()[:4])
        sharded = shard_params(params, mesh, MOE_STACKED_RULES)
        T = 16
        toks = jax.random.randint(jax.random.PRNGKey(6), (1, T), 0,
                                  cfg.vocab_size)
        pt = jnp.arange(4, dtype=jnp.int32)[None, :]
        pos = jnp.arange(T)[None, :]
        args = (toks, pos, alloc_pages(cfg, 8), pt,
                jnp.zeros((1,), jnp.int32), jnp.array([T], jnp.int32))
        ref, _ = fam.prefill_forward(params, cfg, *args)
        with mesh:
            got, _ = jax.jit(
                lambda p, *a: fam.prefill_forward(p, cfg, *a))(sharded, *args)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                                   rtol=2e-3, atol=2e-3)


class TestMoeSpecAndEmbed:
    def test_moe_speculative_greedy_identical(self):
        """Speculative decoding over the MoE (MLA) family must equal its
        normal greedy output."""
        import threading

        from xllm_service_tpu.common.request import SamplingParams
        from xllm_service_tpu.engine.config import EngineConfig
        from xllm_service_tpu.engine.engine import (
            EngineRequest,
            InferenceEngine,
        )
        from xllm_service_tpu.models.deepseek_moe import tiny_mla_config

        def mk(spec):
            return InferenceEngine(EngineConfig(
                model_id="tiny-moe", model_family="deepseek_moe",
                model=tiny_mla_config(dtype=jnp.float32,
                                      max_context_len=256),
                num_pages=64, page_size=16, hash_block_size=32,
                max_batch_size=2, max_seq_len=256,
                prefill_buckets=(32, 64, 256), speculate_k=spec))

        def run(engine, prompt, n=16):
            done = threading.Event()
            toks = []

            def cb(out):
                toks.extend(t for s in out.outputs for t in s.token_ids)
                if out.finished:
                    done.set()

            engine.submit(EngineRequest(
                "m", token_ids=prompt,
                sampling=SamplingParams(max_tokens=n, temperature=0.0,
                                        ignore_eos=True), on_output=cb))
            for _ in range(400):
                if done.is_set():
                    break
                engine.step()
            assert done.is_set()
            return toks

        prompt = [5, 6, 7, 8] * 8
        assert run(mk(4), prompt) == run(mk(0), prompt)

    def test_moe_embed_forward(self):
        from xllm_service_tpu.models.base import get_model_family
        from xllm_service_tpu.models.deepseek_moe import tiny_moe_config

        cfg = tiny_moe_config(dtype=jnp.float32)
        fam = get_model_family("deepseek_moe")
        params = fam.init_params(cfg, jax.random.PRNGKey(0))
        toks = jnp.asarray([[5, 6, 7, 0], [9, 10, 0, 0]], jnp.int32)
        lens = jnp.asarray([3, 2], jnp.int32)
        v = fam.embed_forward(params, cfg, toks, lens)
        assert v.shape == (2, cfg.hidden_size)
        # Padding must not affect the pooled vector.
        toks2 = jnp.asarray([[5, 6, 7, 99], [9, 10, 42, 77]], jnp.int32)
        v2 = fam.embed_forward(params, cfg, toks2, lens)
        np.testing.assert_allclose(np.asarray(v), np.asarray(v2),
                                   rtol=1e-5, atol=1e-6)
