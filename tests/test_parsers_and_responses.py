"""Output-parser + ResponseHandler + chat-template + tokenizer tests."""

import base64
import json

import pytest

from xllm_service_tpu.chat_template import JinjaChatTemplate
from xllm_service_tpu.common.call_data import CollectingConnection
from xllm_service_tpu.common.request import (
    Request,
    RequestOutput,
    SamplingParams,
    SequenceOutput,
    Usage,
)
from xllm_service_tpu.scheduler.output_parsers import (
    FamilyTags,
    StreamChatParser,
    parse_chat_output,
    resolve_family_tags,
)
from xllm_service_tpu.scheduler.response_handler import ResponseHandler
from xllm_service_tpu.tokenizer import SimpleTokenizer, TokenizerFactory
from xllm_service_tpu.tokenizer.tiktoken import TiktokenTokenizer


class TestFullParse:
    TAGS = FamilyTags()

    def test_plain_text(self):
        p = parse_chat_output("hello world", "stop", self.TAGS)
        assert p.content == "hello world"
        assert p.reasoning_content == ""
        assert p.tool_calls == []
        assert p.finish_reason == "stop"

    def test_reasoning_split(self):
        p = parse_chat_output("<think>step by step</think>the answer is 4",
                              "stop", self.TAGS)
        assert p.reasoning_content == "step by step"
        assert p.content == "the answer is 4"

    def test_tool_call_and_finish_rewrite(self):
        text = ('I will check the weather.\n<tool_call>\n'
                '{"name": "get_weather", "arguments": {"city": "Paris"}}\n'
                '</tool_call>')
        p = parse_chat_output(text, "stop", self.TAGS)
        assert len(p.tool_calls) == 1
        assert p.tool_calls[0].name == "get_weather"
        assert json.loads(p.tool_calls[0].arguments) == {"city": "Paris"}
        assert p.finish_reason == "tool_calls"   # stop -> tool_calls rewrite
        assert "tool_call" not in p.content

    def test_implicit_reasoning_family(self):
        tags = resolve_family_tags("deepseek-r1-distill")
        p = parse_chat_output("chain of thought</think>final", "stop", tags)
        assert p.reasoning_content == "chain of thought"
        assert p.content == "final"

    def test_family_resolution(self):
        assert resolve_family_tags("Qwen3-32B") == FamilyTags()
        assert resolve_family_tags("deepseek-v3").tool_open == "<|tool▁call▁begin|>"
        assert resolve_family_tags("unknown-model") == FamilyTags()
        # Explicit parser name overrides model id.
        assert resolve_family_tags("foo", tool_call_parser="kimi").tool_open \
            == "<|tool_call_begin|>"


class TestStreamParse:
    def _collect(self, chunks, tags=FamilyTags()):
        parser = StreamChatParser(tags)
        events = []
        for c in chunks:
            events.extend(parser.feed(c))
        events.extend(parser.finalize())
        return events, parser

    def test_content_only(self):
        events, _ = self._collect(["hel", "lo"])
        assert "".join(e.text for e in events if e.kind == "content") == "hello"

    def test_reasoning_tag_split_across_chunks(self):
        events, _ = self._collect(["<th", "ink>rea", "soning</th", "ink>ans"])
        reasoning = "".join(e.text for e in events if e.kind == "reasoning")
        content = "".join(e.text for e in events if e.kind == "content")
        assert reasoning == "reasoning"
        assert content == "ans"

    def test_tool_call_streamed_incrementally(self):
        payload = '{"name": "f", "arguments": {"x": 1}}'
        events, parser = self._collect(
            ["before <tool_call>", payload[:10], payload[10:], "</tool_call> after"])
        tool_events = [e for e in events if e.kind == "tool_call"]
        # First event names the call; subsequent ones stream arguments.
        assert tool_events[0].tool_name == "f"
        assert tool_events[0].tool_id
        args = "".join(e.tool_args_delta for e in tool_events)
        assert json.loads(args) == {"x": 1}
        assert all(e.tool_index == 0 for e in tool_events)
        assert parser.saw_tool_call
        content = "".join(e.text for e in events if e.kind == "content")
        assert "before" in content and "after" in content

    def test_tool_args_stream_char_by_char(self):
        """Arguments arrive as true deltas even one char at a time, with
        nested braces and braces inside strings."""
        payload = ('{"name": "g", "arguments": '
                   '{"s": "a}b{", "nested": {"k": [1, 2]}}}')
        chunks = ["<tool_call>"] + list(payload) + ["</tool_call>"]
        events, parser = self._collect(chunks)
        tool_events = [e for e in events if e.kind == "tool_call"]
        assert tool_events[0].tool_name == "g"
        args = "".join(e.tool_args_delta for e in tool_events)
        assert json.loads(args) == {"s": "a}b{", "nested": {"k": [1, 2]}}
        # Incremental: arguments arrived across many events.
        assert len(tool_events) > 3

    def test_two_tool_calls_streamed(self):
        text = ('<tool_call>{"name": "a", "arguments": {}}</tool_call>'
                '<tool_call>{"name": "b", "arguments": {"y": 2}}</tool_call>')
        events, parser = self._collect([text[:25], text[25:60], text[60:]])
        tool_events = [e for e in events if e.kind == "tool_call"]
        names = [e.tool_name for e in tool_events if e.tool_name]
        assert names == ["a", "b"]
        assert {e.tool_index for e in tool_events} == {0, 1}
        args1 = "".join(e.tool_args_delta for e in tool_events
                        if e.tool_index == 1)
        assert json.loads(args1) == {"y": 2}

    def test_unterminated_tool_block_flushes_as_content(self):
        events, parser = self._collect(["<tool_call>oops no json"])
        assert not parser.saw_tool_call
        content = "".join(e.text for e in events if e.kind == "content")
        assert "oops no json" in content


def _chat_request(stream=True, **kw):
    return Request(service_request_id="s1", request_id="chatcmpl-1",
                   model="m", stream=stream, **kw)


class TestResponseHandler:
    def test_streaming_chat_chunks(self):
        rh = ResponseHandler("qwen3")
        req = _chat_request(include_usage=True)
        state = rh.create_chat_stream_state(req)
        conn = CollectingConnection(stream=True)
        out1 = RequestOutput(service_request_id="s1", outputs=[
            SequenceOutput(index=0, text="<think>hm</think>he", token_ids=[1])])
        assert rh.send_chat_delta(conn, state, req, out1)
        out2 = RequestOutput(service_request_id="s1", outputs=[
            SequenceOutput(index=0, text="llo", token_ids=[2],
                           finish_reason="stop")],
            usage=Usage(5, 2), finished=True)
        assert rh.send_chat_delta(conn, state, req, out2)
        assert conn.finished
        deltas = [c["choices"][0]["delta"] for c in conn.payloads if c["choices"]]
        assert deltas[0] == {"role": "assistant", "content": ""}
        reasoning = "".join(d.get("reasoning_content", "") for d in deltas)
        content = "".join(d.get("content", "") or "" for d in deltas)
        assert reasoning == "hm"
        assert content == "hello"
        finish = [c["choices"][0]["finish_reason"]
                  for c in conn.payloads if c["choices"]]
        assert "stop" in finish
        usage_chunks = [c for c in conn.payloads if c.get("usage")]
        assert usage_chunks and usage_chunks[-1]["usage"]["total_tokens"] == 7

    def test_streaming_tool_call_finish_rewrite(self):
        rh = ResponseHandler("qwen3")
        req = _chat_request()
        state = rh.create_chat_stream_state(req)
        conn = CollectingConnection(stream=True)
        out = RequestOutput(service_request_id="s1", outputs=[
            SequenceOutput(index=0,
                           text='<tool_call>{"name":"f","arguments":{}}</tool_call>',
                           finish_reason="stop")], finished=True)
        rh.send_chat_delta(conn, state, req, out)
        finish = [c["choices"][0]["finish_reason"]
                  for c in conn.payloads if c["choices"]]
        assert "tool_calls" in finish
        tool_deltas = [c["choices"][0]["delta"].get("tool_calls")
                       for c in conn.payloads
                       if c["choices"] and c["choices"][0]["delta"].get("tool_calls")]
        assert tool_deltas[0][0]["function"]["name"] == "f"

    def test_non_stream_chat_result(self):
        rh = ResponseHandler("qwen3")
        req = _chat_request(stream=False)
        conn = CollectingConnection()
        out = RequestOutput(service_request_id="s1", outputs=[
            SequenceOutput(index=0, text="<think>x</think>hi",
                           finish_reason="stop")],
            usage=Usage(3, 1), finished=True)
        assert rh.send_chat_result(conn, req, out)
        body = conn.payloads[0]
        msg = body["choices"][0]["message"]
        assert msg["content"] == "hi"
        assert msg["reasoning_content"] == "x"
        assert body["usage"]["prompt_tokens"] == 3

    def test_completion_stream_and_result(self):
        rh = ResponseHandler("")
        req = Request(service_request_id="s1", request_id="cmpl-1", model="m",
                      stream=True, include_usage=True)
        conn = CollectingConnection(stream=True)
        rh.send_completion_delta(conn, req, RequestOutput(
            outputs=[SequenceOutput(index=0, text="abc")]))
        rh.send_completion_delta(conn, req, RequestOutput(
            outputs=[SequenceOutput(index=0, text="def", finish_reason="length")],
            usage=Usage(2, 4), finished=True))
        assert conn.finished
        texts = "".join(c["choices"][0]["text"]
                        for c in conn.payloads if c["choices"])
        assert texts == "abcdef"
        conn2 = CollectingConnection()
        rh.send_completion_result(conn2, Request(stream=False, model="m",
                                                 request_id="cmpl-2"),
                                  RequestOutput(outputs=[
                                      SequenceOutput(index=0, text="xyz",
                                                     finish_reason="stop")],
                                      usage=Usage(1, 1), finished=True))
        assert conn2.payloads[0]["choices"][0]["text"] == "xyz"

    def test_logprobs_rendering(self):
        from xllm_service_tpu.common.request import LogProb, LogProbData

        rh = ResponseHandler("")
        req = _chat_request(stream=False,
                            sampling=SamplingParams(logprobs=True))
        conn = CollectingConnection()
        out = RequestOutput(outputs=[SequenceOutput(
            index=0, text="hi", finish_reason="stop",
            logprobs=[LogProb(token="hi", token_id=5, logprob=-0.1,
                              top_logprobs=[LogProbData("hi", 5, -0.1)])])],
            finished=True)
        rh.send_chat_result(conn, req, out)
        lp = conn.payloads[0]["choices"][0]["logprobs"]
        assert lp["content"][0]["token"] == "hi"
        assert lp["content"][0]["top_logprobs"][0]["logprob"] == -0.1


class TestChatTemplate:
    def test_default_template(self):
        t = JinjaChatTemplate()
        out = t.apply([{"role": "user", "content": "hi"}])
        assert "<|im_start|>user\nhi<|im_end|>" in out
        assert out.endswith("<|im_start|>assistant\n")

    def test_tools_and_kwargs(self):
        tmpl = ("{% if tools %}TOOLS:{{ tools | length }}\n{% endif %}"
                "{% if enable_thinking %}THINK\n{% endif %}"
                "{% for m in messages %}{{ m.content }}{% endfor %}")
        t = JinjaChatTemplate(tmpl)
        out = t.apply([{"role": "user", "content": "q"}],
                      tools=[{"type": "function", "function": {"name": "f"}}],
                      chat_template_kwargs={"enable_thinking": True})
        assert out == "TOOLS:1\nTHINK\nq"

    def test_multimodal_placeholder(self):
        t = JinjaChatTemplate("{{ messages[0].content }}")
        out = t.apply([{"role": "user", "content": [
            {"type": "text", "text": "look: "},
            {"type": "image_url", "image_url": {"url": "http://x/im.png"}}]}])
        assert out == "look: <|multimodal_placeholder|>"


class TestTokenizers:
    def test_simple_roundtrip(self):
        tok = SimpleTokenizer()
        ids = tok.encode("héllo!")
        assert tok.decode(ids) == "héllo!"

    def test_factory_fallback(self):
        assert isinstance(TokenizerFactory.create_tokenizer(""), SimpleTokenizer)

    def test_tiktoken_bpe(self, tmp_path):
        # Tiny vocab: bytes a,b,c + merges "ab", "abc".
        vocab = {b"a": 0, b"b": 1, b"c": 2, b"ab": 3, b"abc": 4}
        lines = "\n".join(
            f"{base64.b64encode(k).decode()} {v}" for k, v in vocab.items())
        f = tmp_path / "vocab.tiktoken"
        f.write_text(lines)
        tok = TiktokenTokenizer(f, special_tokens={"<|eot|>": 100})
        assert tok.encode("abc") == [4]
        assert tok.encode("abab") == [3, 3]
        assert tok.encode("cab") == [2, 3]
        assert tok.encode("ab<|eot|>c") == [3, 100, 2]
        assert tok.decode([4, 100], skip_special_tokens=False) == "abc<|eot|>"
        assert tok.decode([4, 100]) == "abc"

    def test_factory_detects_tiktoken_dir(self, tmp_path):
        (tmp_path / "m.tiktoken").write_text(
            base64.b64encode(b"a").decode() + " 0")
        tok = TokenizerFactory.create_tokenizer(str(tmp_path))
        assert isinstance(tok, TiktokenTokenizer)

    def test_hf_tokenizer(self, tmp_path):
        # Build a minimal HF tokenizer.json (WordLevel) hermetically.
        from tokenizers import Tokenizer as HFTok
        from tokenizers.models import WordLevel
        from tokenizers.pre_tokenizers import Whitespace

        t = HFTok(WordLevel({"hello": 0, "world": 1, "[UNK]": 2}, unk_token="[UNK]"))
        t.pre_tokenizer = Whitespace()
        t.save(str(tmp_path / "tokenizer.json"))
        tok = TokenizerFactory.create_tokenizer(str(tmp_path))
        assert tok.encode("hello world") == [0, 1]
        assert tok.vocab_size() == 3


class TestTokenizerArgs:
    """Full TokenizerArgs surface (reference tokenizer_args.{h,cpp})."""

    def _write_cfg(self, tmp_path, **extra):
        cfg = {
            "add_bos_token": True,
            "bos_token": {"content": "<s>"},
            "eos_token": "</s>",
            "pad_token": "<pad>",
            "tokenizer_class": "TikTokenTokenizer",
            "chat_template": "CFG-TEMPLATE",
            **extra,
        }
        (tmp_path / "tokenizer_config.json").write_text(json.dumps(cfg))

    def test_args_loaded_from_config(self, tmp_path):
        self._write_cfg(tmp_path, added_tokens_decoder={
            "100": {"content": "<|eot|>"}, "101": {"content": "<|pad|>"}})
        args = TokenizerFactory.load_args(str(tmp_path))
        assert args.add_bos_token is True
        assert args.bos_token == "<s>"        # dict .content form
        assert args.eos_token == "</s>"       # plain string form
        assert args.pad_token == "<pad>"
        assert args.tokenizer_class == "TikTokenTokenizer"
        assert ("<|eot|>", 100) in args.special_tokens
        assert args.chat_template == "CFG-TEMPLATE"

    def test_chat_template_json_takes_priority(self, tmp_path):
        self._write_cfg(tmp_path)
        (tmp_path / "chat_template.json").write_text(
            json.dumps({"chat_template": "FILE-TEMPLATE"}))
        args = TokenizerFactory.load_args(str(tmp_path))
        assert args.chat_template == "FILE-TEMPLATE"
        assert TokenizerFactory.load_chat_template(str(tmp_path)) == \
            "FILE-TEMPLATE"

    def test_tiktoken_with_pattern_specials_and_prefix(self, tmp_path):
        vocab = {b"a": 0, b"b": 1, b"c": 2, b" ": 5, b"ab": 3, b"abc": 4,
                 b"ab ": 6}
        lines = "\n".join(
            f"{base64.b64encode(k).decode()} {v}" for k, v in vocab.items())
        (tmp_path / "m.tiktoken").write_text(lines)
        self._write_cfg(
            tmp_path,
            tokenizer_type="tiktoken",
            # \p{L} word-property split: needs the `regex` module (re2 in
            # the reference); trailing-space run NOT merged across words.
            pattern=r"\p{L}+|\s+",
            prefix_tokens=["<|bos|>"],
            added_tokens_decoder={"100": {"content": "<|eot|>"},
                                  "101": {"content": "<|bos|>"}})
        tok = TokenizerFactory.create_tokenizer(str(tmp_path))
        assert isinstance(tok, TiktokenTokenizer)
        # Prefix token id prepended; pattern splits words so "ab " cannot
        # merge across the word boundary (id 6 unused).
        assert tok.encode("ab ab") == [101, 3, 5, 3]
        assert tok.encode("ab<|eot|>c") == [101, 3, 100, 2]
        # Without the pattern the space WOULD merge into "ab ".
        plain = TiktokenTokenizer(tmp_path / "m.tiktoken")
        assert plain.encode("ab ab") == [6, 3]

    def test_special_token_without_id_gets_appended(self, tmp_path):
        (tmp_path / "v.tiktoken").write_text(
            base64.b64encode(b"a").decode() + " 0")
        tok = TiktokenTokenizer(tmp_path / "v.tiktoken",
                                special_tokens={"<|x|>": -1})
        assert tok.token_to_id("<|x|>") == 1   # max rank + 1
        assert tok.vocab_size() == 2
