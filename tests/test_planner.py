"""Planner: fleet-level scale hints + telemetry-driven PD-ratio
correction (reference names the component, docs/en/overview.md:56-60,
with no code — the decision surface here is ours)."""

import pytest

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.types import (
    InstanceType,
    LatencyMetrics,
    LoadMetrics,
)
from xllm_service_tpu.coordination.memory import InMemoryCoordination, MemoryStore
from xllm_service_tpu.scheduler.instance_mgr import InstanceMgr
from xllm_service_tpu.scheduler.planner import Planner

from fakes import FakeChannel, make_meta


@pytest.fixture()
def coord():
    st = MemoryStore(expiry_tick_s=0.02)
    yield InMemoryCoordination(st)
    st.close()


@pytest.fixture(autouse=True)
def _reset_channels():
    FakeChannel.reset()
    yield
    FakeChannel.reset()


def make_mgr(coord) -> InstanceMgr:
    return InstanceMgr(coord, ServiceOptions(), start_threads=False,
                       channel_factory=FakeChannel.factory)


def set_load(mgr, name, waiting=0, running=0, kv=0.0, tbt=0.0):
    mgr.record_instance_heartbeat(
        name, mgr.get_instance_meta(name).incarnation_id,
        LoadMetrics(waiting_requests_num=waiting,
                    running_requests_num=running,
                    hbm_cache_usage_perc=kv),
        LatencyMetrics(recent_max_tbt=tbt))


class TestPlanner:
    def test_empty_fleet_wants_instances(self, coord):
        mgr = make_mgr(coord)
        d = Planner(mgr, ServiceOptions()).plan_once()
        assert d.scale_hint >= 1
        mgr.stop()

    def test_scale_out_under_pressure(self, coord):
        mgr = make_mgr(coord)
        mgr.register_instance(make_meta("m1"), link_peers=False)
        set_load(mgr, "m1", waiting=30, running=4, kv=0.95)
        d = Planner(mgr, ServiceOptions()).plan_once()
        assert d.scale_hint >= 1
        assert d.reasons
        mgr.stop()

    def test_scale_in_when_idle(self, coord):
        mgr = make_mgr(coord)
        mgr.register_instance(make_meta("m1"), link_peers=False)
        mgr.register_instance(make_meta("m2"), link_peers=False)
        set_load(mgr, "m1")
        set_load(mgr, "m2")
        d = Planner(mgr, ServiceOptions()).plan_once()
        assert d.scale_hint == -1
        mgr.stop()

    def test_tpot_breach_requests_flip(self, coord):
        """Slow decodes + an idle prefill -> the planner queues a P->D
        flip (enacted by the reconcile thread)."""
        mgr = make_mgr(coord)
        mgr.register_instance(make_meta("p1", InstanceType.PREFILL),
                              link_peers=False)
        mgr.register_instance(make_meta("p2", InstanceType.PREFILL),
                              link_peers=False)
        mgr.register_instance(make_meta("d1", InstanceType.DECODE),
                              link_peers=False)
        set_load(mgr, "p1", waiting=4, running=2)
        set_load(mgr, "p2")                       # idle
        set_load(mgr, "d1", running=8, tbt=500.0)  # way over 50ms target
        planner = Planner(mgr, ServiceOptions())
        d = planner.plan_once()
        assert d.flips_requested == [["p2", "DECODE"]]
        mgr.reconcile_once()
        assert mgr.get_instance_meta("p2").type == InstanceType.DECODE
        mgr.stop()

    def test_master_publishes_decision(self, coord):
        """The master sync loop publishes the planner decision to the
        coordination key external autoscalers watch."""
        from xllm_service_tpu.scheduler.planner import PLANNER_KEY
        from xllm_service_tpu.scheduler.scheduler import Scheduler

        sched = Scheduler(ServiceOptions(sync_interval_s=0.1),
                          coord=coord, start_threads=False)
        sched.instance_mgr._channel_factory = FakeChannel.factory
        sched.sync_once()
        assert coord.get(PLANNER_KEY) is not None
        import json as _json

        d = _json.loads(coord.get(PLANNER_KEY))
        assert "scale_hint" in d
        sched.stop()
