"""Service-replica HA drill (SURVEY.md §3.5): two masters, master death,
watch-driven takeover, continued serving through the survivor."""

import requests

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.coordination.memory import InMemoryCoordination, MemoryStore
from xllm_service_tpu.master import Master
from xllm_service_tpu.rpc import MASTER_KEY
from xllm_service_tpu.testing.fake_engine import FakeEngine, FakeEngineConfig

from fakes import wait_until


def _opts():
    return ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=0.5, sync_interval_s=0.2,
                          reconcile_interval_s=0.1,
                          heartbeat_silence_to_suspect_s=1.0,
                          detect_disconnected_instance_interval_s=2.0)


class TestHAFailover:
    def test_replica_takeover_and_serving(self, store):
        m1 = Master(_opts(), coord=InMemoryCoordination(store))
        m1.start()
        m2 = Master(_opts(), coord=InMemoryCoordination(store))
        m2.start()
        assert m1.scheduler.is_master and not m2.scheduler.is_master

        engine = FakeEngine(InMemoryCoordination(store),
                            FakeEngineConfig(heartbeat_interval_s=0.2,
                                             lease_ttl_s=0.5)).start()
        try:
            # Both replicas see the instance (watch-driven registration).
            for m in (m1, m2):
                assert wait_until(
                    lambda m=m: m.scheduler.instance_mgr.get_instance_meta(
                        engine.name) is not None, timeout=5)

            # Serving works through BOTH replicas (any replica routes).
            for m in (m1, m2):
                r = requests.post(
                    f"http://127.0.0.1:{m.http_port}/v1/completions",
                    json={"model": "fake-model", "prompt": "hi",
                          "max_tokens": 32}, timeout=10)
                assert r.status_code == 200, r.text

            # Master dies -> replica must win the election and keep serving.
            m1.stop()
            assert wait_until(lambda: m2.scheduler.is_master, timeout=5)
            coord = InMemoryCoordination(store)
            assert coord.get(MASTER_KEY) == m2.scheduler.self_addr
            coord.close()

            # The new master performs master duties: engines heartbeat to it
            # (they resolve MASTER_KEY) and serving continues.
            before = m2.scheduler.instance_mgr.get_load_infos()[
                engine.name].load.running_requests_num
            r = requests.post(
                f"http://127.0.0.1:{m2.http_port}/v1/completions",
                json={"model": "fake-model", "prompt": "after failover",
                      "max_tokens": 32}, timeout=10)
            assert r.status_code == 200, r.text
            assert r.json()["choices"][0]["text"] == \
                "Hello from the fake engine!"
        finally:
            engine.stop()
            m2.stop()
