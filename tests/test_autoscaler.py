"""Closed-loop fleet autoscaler (ISSUE 13): decision-kernel tables,
guard edges (hysteresis / cooldowns / clamps / stale-telemetry hold),
actuator contracts (hint publish, local process lifecycle, spawn-failure
retry), the graceful DRAINING lifecycle, multimaster write-lease
discipline, and the full-loop chaos drills (`scripts/chaos_soak.sh
--autoscale`): a killed instance is replaced, a killed DRAINING instance
falls back to the normal failover path, a flaky actuator never wedges
the loop."""

import shlex
import sys
import time

import pytest
import requests

from xllm_service_tpu.autoscaler import (
    Action,
    AutoscalerConfig,
    AutoscalerController,
    HintActuator,
    KernelInputs,
    KernelState,
    LocalProcessActuator,
    decide,
)
from xllm_service_tpu.autoscaler.actuator import (
    AUTOSCALER_ACTION_KEY_PREFIX,
    AUTOSCALER_DECISION_KEY,
    FleetActuator,
)
from xllm_service_tpu.autoscaler.controller import (
    ACTION_FLIP,
    ACTION_HOLD,
    ACTION_SCALE_IN,
    ACTION_SCALE_OUT,
)
from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.metrics import INSTANCE_EVICTIONS_TOTAL
from xllm_service_tpu.common.request import Request
from xllm_service_tpu.common.slo import SloMonitor
from xllm_service_tpu.common.types import (
    InstanceRuntimeState,
    InstanceType,
    LatencyMetrics,
    LoadMetrics,
)
from xllm_service_tpu.coordination.memory import InMemoryCoordination
from xllm_service_tpu.devtools import ownership as _ownership
from xllm_service_tpu.master import Master
from xllm_service_tpu.scheduler.instance_mgr import InstanceMgr
from xllm_service_tpu.scheduler.policies import create_policy
from xllm_service_tpu.testing.fake_engine import FakeEngine, FakeEngineConfig

from fakes import FakeChannel, make_meta, wait_until

CFG = AutoscalerConfig(min_instances=1, max_instances=4, breach_ticks=2,
                       idle_ticks=3, scale_out_step=0.5,
                       scale_out_cooldown_s=10.0, scale_in_cooldown_s=10.0,
                       flip_cooldown_s=5.0, stale_hold_s=15.0)


def inputs(**kw) -> KernelInputs:
    base = dict(now_s=1000.0, live=2, max_load_age_s=1.0)
    base.update(kw)
    return KernelInputs(**base)


@pytest.fixture(autouse=True)
def _reset_channels():
    FakeChannel.reset()
    yield
    FakeChannel.reset()


@pytest.fixture()
def coordination(store):
    return InMemoryCoordination(store)


# --------------------------------------------------------------------------
# The pure decision kernel: input -> expected-action tables.
# --------------------------------------------------------------------------
class TestDecisionKernel:
    def test_quiet_fleet_no_action(self):
        st = KernelState(desired=2)
        actions, nxt, _ = decide(
            inputs(worst_fast_burn=0.5, pressure=0.5), st, CFG)
        assert actions == []
        assert nxt.desired == 2
        assert nxt.breach_streak == 0

    def test_breach_below_hysteresis_waits(self):
        st = KernelState(desired=2)
        actions, nxt, _ = decide(
            inputs(breaching=("ttft",), worst_fast_burn=30.0), st, CFG)
        assert actions == []
        assert nxt.breach_streak == 1

    def test_breach_at_hysteresis_scales_out(self):
        st = KernelState(desired=2, breach_streak=1)
        actions, nxt, _ = decide(
            inputs(breaching=("ttft",), worst_fast_burn=30.0), st, CFG)
        assert [a.kind for a in actions] == [ACTION_SCALE_OUT]
        assert actions[0].count == 1          # ceil(2 * 0.5)
        assert nxt.desired == 3
        assert nxt.last_scale_out_s == 1000.0

    def test_pressure_alone_triggers_breach(self):
        st = KernelState(desired=2, breach_streak=1)
        actions, _, _ = decide(inputs(pressure=2.0), st, CFG)
        assert [a.kind for a in actions] == [ACTION_SCALE_OUT]

    def test_kv_pressure_alone_triggers_breach(self):
        st = KernelState(desired=2, breach_streak=1)
        actions, _, _ = decide(inputs(kv_pressure=0.95), st, CFG)
        assert [a.kind for a in actions] == [ACTION_SCALE_OUT]

    def test_max_instances_clamp(self):
        st = KernelState(desired=4, breach_streak=5)
        actions, nxt, reasons = decide(
            inputs(live=4, breaching=("ttft",)), st, CFG)
        assert actions == []
        assert nxt.desired == 4
        assert any("max_instances" in r for r in reasons)

    def test_scale_out_step_clamped_to_max(self):
        cfg = AutoscalerConfig(max_instances=4, breach_ticks=1,
                               scale_out_step=5.0)
        st = KernelState(desired=3)
        actions, nxt, _ = decide(
            inputs(live=3, breaching=("ttft",)), st, cfg)
        assert actions[0].count == 1          # 3 -> 4, never past max
        assert nxt.desired == 4

    def test_scale_out_cooldown(self):
        st = KernelState(desired=2, breach_streak=5, last_scale_out_s=995.0)
        actions, _, reasons = decide(
            inputs(breaching=("ttft",)), st, CFG)
        assert actions == []
        assert any("cooldown" in r for r in reasons)
        # Cooldown elapsed -> fires.
        st2 = KernelState(desired=2, breach_streak=5,
                          last_scale_out_s=985.0)
        actions, _, _ = decide(inputs(breaching=("ttft",)), st2, CFG)
        assert [a.kind for a in actions] == [ACTION_SCALE_OUT]

    def test_idle_hysteresis_and_scale_in(self):
        st = KernelState(desired=3)
        for tick in range(CFG.idle_ticks - 1):
            actions, st, _ = decide(
                inputs(now_s=1000.0 + tick, live=3,
                       scale_in_candidate="e3"), st, CFG)
            assert actions == []
        actions, nxt, _ = decide(
            inputs(now_s=1010.0, live=3, scale_in_candidate="e3"), st, CFG)
        assert [(a.kind, a.instance) for a in actions] == \
            [(ACTION_SCALE_IN, "e3")]
        assert nxt.desired == 2
        assert nxt.idle_streak == 0           # streak resets after acting

    def test_min_instances_clamp(self):
        st = KernelState(desired=1, idle_streak=99)
        actions, nxt, reasons = decide(
            inputs(live=1, scale_in_candidate="e1"), st, CFG)
        assert actions == []
        assert nxt.desired == 1
        assert any("min_instances" in r for r in reasons)

    def test_scale_in_needs_candidate(self):
        st = KernelState(desired=3, idle_streak=99)
        actions, _, reasons = decide(
            inputs(live=3, scale_in_candidate=""), st, CFG)
        assert actions == []
        assert any("role availability" in r for r in reasons)

    def test_scale_in_waits_for_inflight_drain(self):
        st = KernelState(desired=3, idle_streak=99)
        actions, _, reasons = decide(
            inputs(live=2, draining=1, scale_in_candidate="e2"), st, CFG)
        assert actions == []
        assert any("drain is already in progress" in r for r in reasons)

    def test_stale_telemetry_holds_and_freezes_streaks(self):
        st = KernelState(desired=2, breach_streak=1)
        for age in (-1.0, CFG.stale_hold_s + 1.0):
            actions, nxt, reasons = decide(
                inputs(breaching=("ttft",), max_load_age_s=age), st, CFG)
            assert [a.kind for a in actions] == [ACTION_HOLD]
            assert nxt.breach_streak == 1     # frozen, not advanced
            assert nxt.desired == 2
            assert any("HOLD" in r for r in reasons)

    def test_replacement_bypasses_cooldown_and_hysteresis(self):
        # A scale-out just happened (cooldown hot) and there is no
        # breach — but capacity below desired is replaced immediately.
        st = KernelState(desired=3, last_scale_out_s=999.0)
        actions, _, _ = decide(inputs(live=1), st, CFG)
        assert [(a.kind, a.count) for a in actions] == [(ACTION_SCALE_OUT, 2)]
        assert "replacing lost capacity" in actions[0].reason

    def test_replacement_honors_spawn_retry_backoff(self):
        st = KernelState(desired=3, retry_at_s=1005.0, retry_count=2)
        actions, _, reasons = decide(inputs(live=1), st, CFG)
        assert actions == []
        assert any("backed off" in r for r in reasons)
        # Backoff elapsed -> replacement resumes.
        actions, _, _ = decide(inputs(now_s=1006.0, live=1), st, CFG)
        assert [a.kind for a in actions] == [ACTION_SCALE_OUT]

    def test_external_join_raises_desired(self):
        st = KernelState(desired=2)
        _, nxt, reasons = decide(inputs(live=4), st, CFG)
        assert nxt.desired == 4
        assert any("observed fleet" in r for r in reasons)

    def test_flip_proposal_enacted_with_cooldown(self):
        st = KernelState(desired=2)
        actions, nxt, _ = decide(
            inputs(flip_proposals=(("p2", "DECODE"),)), st, CFG)
        assert [(a.kind, a.instance, a.target_type) for a in actions] == \
            [(ACTION_FLIP, "p2", "DECODE")]
        # Second proposal inside the flip cooldown is deferred.
        actions, _, reasons = decide(
            inputs(now_s=1002.0, flip_proposals=(("p1", "DECODE"),)),
            nxt, CFG)
        assert actions == []
        assert any("deferred" in r for r in reasons)

    def test_replacement_never_exceeds_max_instances(self):
        """Review regression: the replacement path must honor the fleet
        bounds too — an over-joined fleet is tolerated while alive but
        never re-grown past max by the controller."""
        st = KernelState(desired=2)
        # 10 engines joined externally with max_instances=4: desired
        # clamps to max, no replacement storm when some later die.
        _, nxt, _ = decide(inputs(live=10), st, CFG)
        assert nxt.desired == CFG.max_instances
        actions, _, _ = decide(inputs(live=5), nxt, CFG)
        assert actions == []          # 5 live >= desired 4: nothing to do
        actions, _, _ = decide(inputs(live=3), nxt, CFG)
        assert [(a.kind, a.count) for a in actions] == [(ACTION_SCALE_OUT, 1)]

    def test_min_above_max_misconfig_normalized(self):
        opts = _opts(autoscaler_min_instances=9, autoscaler_max_instances=4)
        cfg = AutoscalerConfig.from_options(opts)
        assert cfg.max_instances >= cfg.min_instances

    def test_suspect_instance_is_not_lost_capacity(self):
        """Review regression: a network-blip SUSPECT either recovers or
        is evicted within the detection window — replacing it on the
        next tick (hysteresis-free) would permanently inflate the fleet
        when it recovers."""
        st = KernelState(desired=3)
        actions, nxt, _ = decide(
            inputs(live=2, suspect=1), st, CFG)
        assert actions == []
        assert nxt.desired == 3
        # Evicted (suspect gone, still dead) -> NOW it is lost capacity.
        actions, _, _ = decide(inputs(live=2, suspect=0), nxt, CFG)
        assert [(a.kind, a.count) for a in actions] == [(ACTION_SCALE_OUT, 1)]

    def test_one_scale_action_per_tick(self):
        # Breaching AND missing capacity: replacement wins, growth waits.
        st = KernelState(desired=3, breach_streak=9)
        actions, _, _ = decide(
            inputs(live=2, breaching=("ttft",)), st, CFG)
        scale_actions = [a for a in actions
                         if a.kind in (ACTION_SCALE_OUT, ACTION_SCALE_IN)]
        assert len(scale_actions) == 1
        assert "replacing lost capacity" in scale_actions[0].reason


# --------------------------------------------------------------------------
# Controller over a live InstanceMgr (fake channels) + recording actuator.
# --------------------------------------------------------------------------
class RecordingActuator(FleetActuator):
    name = "recording"

    def __init__(self, scale_out_result=None):
        self.scale_outs: list[tuple[int, str]] = []
        self.scale_ins: list[str] = []
        self.reaps: list[str] = []
        self._result = scale_out_result   # None = echo count

    def scale_out(self, count, reason, slice_id=""):
        self.scale_outs.append((count, reason))
        self.scale_out_slices = getattr(self, "scale_out_slices", [])
        self.scale_out_slices.append(slice_id)
        return count if self._result is None else self._result

    def scale_in(self, instance, reason):
        self.scale_ins.append(instance)
        return True

    def reap(self, instance):
        self.reaps.append(instance)


def _opts(**kw) -> ServiceOptions:
    base = dict(autoscaler_enabled=True, autoscaler_breach_ticks=2,
                autoscaler_idle_ticks=2, autoscaler_min_instances=1,
                autoscaler_max_instances=4,
                autoscaler_scale_out_cooldown_s=0.2,
                autoscaler_scale_in_cooldown_s=0.2,
                autoscaler_flip_cooldown_s=0.1,
                autoscaler_stale_hold_s=30.0,
                autoscaler_drain_grace_s=0.05,
                autoscaler_spawn_retry_base_s=0.05,
                autoscaler_spawn_retry_max_s=0.2)
    base.update(kw)
    return ServiceOptions(**base)


def make_mgr(coordination, n_mix=2, opts=None) -> InstanceMgr:
    mgr = InstanceMgr(coordination, opts or _opts(), start_threads=False,
                      channel_factory=FakeChannel.factory)
    for i in range(n_mix):
        mgr.register_instance(make_meta(f"e{i + 1}"), link_peers=False)
    return mgr


def heartbeat_all(mgr):
    for meta in mgr.list_instances():
        mgr.record_instance_heartbeat(
            meta.name, meta.incarnation_id, LoadMetrics(), LatencyMetrics())


def breach_monitor(bad_samples=30) -> SloMonitor:
    mon = SloMonitor()
    mon.configure(ttft_ms=100.0, tpot_ms=50.0, budget=0.01,
                  fast_s=60.0, slow_s=120.0, alert=14.4)
    for _ in range(bad_samples):
        mon.record_ttft(500.0)   # every sample over target -> burn 100x
    return mon


def make_controller(mgr, opts=None, actuator=None, monitor=None,
                    is_master=None):
    opts = opts or _opts()
    return AutoscalerController(
        opts, mgr, actuator if actuator is not None else RecordingActuator(),
        is_master_fn=is_master or (lambda: True),
        slo_monitor=monitor or SloMonitor())


class TestController:
    def test_disabled_controller_never_ticks(self, coordination):
        mgr = make_mgr(coordination)
        ctl = make_controller(mgr, opts=_opts(autoscaler_enabled=False))
        assert ctl.tick() is None
        assert ctl.report()["ticks"] == 0
        mgr.stop()

    def test_burn_breach_drives_scale_out(self, coordination):
        mgr = make_mgr(coordination, n_mix=2)
        heartbeat_all(mgr)
        act = RecordingActuator()
        ctl = make_controller(mgr, actuator=act, monitor=breach_monitor())
        rec1 = ctl.tick()
        assert rec1["actions"] == []          # hysteresis tick 1
        rec2 = ctl.tick()
        kinds = [a["kind"] for a in rec2["actions"]]
        assert kinds == [ACTION_SCALE_OUT]
        assert act.scale_outs and act.scale_outs[0][0] == 1
        assert rec2["inputs"]["breaching"] == ["ttft"]
        mgr.stop()

    def test_stale_telemetry_holds(self, coordination):
        mgr = make_mgr(coordination, n_mix=2)   # no heartbeats -> age -1
        act = RecordingActuator()
        ctl = make_controller(mgr, actuator=act, monitor=breach_monitor())
        rec = ctl.tick()
        assert [a["kind"] for a in rec["actions"]] == [ACTION_HOLD]
        assert act.scale_outs == []
        mgr.stop()

    def test_idle_fleet_scale_in_drains_least_loaded(self, coordination):
        mgr = make_mgr(coordination, n_mix=3)
        heartbeat_all(mgr)
        # e1 is visibly busy; e2/e3 idle -> victim must not be e1.
        mgr.record_instance_heartbeat(
            "e1", mgr.get_instance_meta("e1").incarnation_id,
            LoadMetrics(waiting_requests_num=5, running_requests_num=3))
        act = RecordingActuator()
        ctl = make_controller(mgr, actuator=act)
        recs = [ctl.tick(), ctl.tick()]
        acted = [a for rec in recs for a in rec["actions"]]
        assert [a["kind"] for a in acted] == [ACTION_SCALE_IN]
        victim = acted[0]["instance"]
        assert victim in ("e2", "e3")
        # The drain is enqueued; the reconcile pass marks DRAINING and
        # the routing snapshot stops offering the victim.
        mgr.reconcile_once()
        assert mgr.get_instance_state(victim) == InstanceRuntimeState.DRAINING
        assert victim not in mgr.routing_snapshot().schedulable
        assert FakeChannel.registry[victim].drains == 1
        mgr.stop()

    def test_scale_in_never_breaks_role_availability(self, coordination):
        mgr = InstanceMgr(coordination, _opts(), start_threads=False,
                          channel_factory=FakeChannel.factory)
        mgr.register_instance(make_meta("p1", InstanceType.PREFILL),
                              link_peers=False)
        mgr.register_instance(make_meta("d1", InstanceType.DECODE),
                              link_peers=False)
        heartbeat_all(mgr)
        act = RecordingActuator()
        ctl = make_controller(mgr, actuator=act)
        for _ in range(4):
            rec = ctl.tick()
        assert act.scale_ins == []
        assert any("role availability" in r for r in rec["reasons"])
        mgr.stop()

    def test_spawn_failure_backs_off_and_recovers(self, coordination):
        mgr = make_mgr(coordination, n_mix=1)
        heartbeat_all(mgr)
        act = RecordingActuator(scale_out_result=0)   # every launch fails
        ctl = make_controller(mgr, actuator=act, monitor=breach_monitor())
        ctl.tick()
        rec = ctl.tick()                   # acts: scale_out -> fails
        assert rec["enacted"][0]["launched"] == 0
        assert ctl.report()["state"]["retry_count"] == 1
        n_calls = len(act.scale_outs)
        rec = ctl.tick()                   # inside backoff: no new launch
        assert len(act.scale_outs) == n_calls
        assert any("backed off" in r or "backoff" in r
                   for r in rec["reasons"])
        # Loop never wedges: ticks keep completing and, once the actuator
        # heals and the backoff elapses, the replacement lands.
        act._result = None
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            rec = ctl.tick()
            if len(act.scale_outs) > n_calls:
                break
            time.sleep(0.05)
        assert len(act.scale_outs) > n_calls
        assert ctl.report()["state"]["retry_count"] == 0
        mgr.stop()

    def test_killed_capacity_gets_replaced(self, coordination):
        mgr = make_mgr(coordination, n_mix=3)
        heartbeat_all(mgr)
        act = RecordingActuator()
        ctl = make_controller(mgr, actuator=act)
        ctl.tick()
        assert ctl.report()["state"]["desired"] == 3
        mgr.deregister_instance("e2", reason="test kill")
        heartbeat_all(mgr)
        rec = ctl.tick()
        assert [(a["kind"], a["count"]) for a in rec["actions"]] == \
            [(ACTION_SCALE_OUT, 1)]
        assert "replacing lost capacity" in rec["actions"][0]["reason"]
        mgr.stop()

    def test_flip_proposals_route_through_controller(self, coordination):
        mgr = make_mgr(coordination, n_mix=0)
        for n, t in (("p1", InstanceType.PREFILL),
                     ("p2", InstanceType.PREFILL),
                     ("d1", InstanceType.DECODE)):
            mgr.register_instance(make_meta(n, t), link_peers=False)
        heartbeat_all(mgr)
        ctl = make_controller(mgr)
        ctl.propose_flip("p2", InstanceType.DECODE)
        rec = ctl.tick()
        assert [a["kind"] for a in rec["actions"]] == [ACTION_FLIP]
        mgr.reconcile_once()   # the reconcile thread executes the flip
        assert mgr.get_instance_meta("p2").type == InstanceType.DECODE
        mgr.stop()

    def test_deferred_flip_proposal_survives_cooldown(self, coordination):
        """Review regression: a proposal that hits the flip cooldown is
        logged as 'deferred' — it must actually survive to a later tick
        instead of being silently dropped."""
        mgr = make_mgr(coordination, n_mix=0)
        for n, t in (("p1", InstanceType.PREFILL),
                     ("p2", InstanceType.PREFILL),
                     ("p3", InstanceType.PREFILL),
                     ("d1", InstanceType.DECODE)):
            mgr.register_instance(make_meta(n, t), link_peers=False)
        heartbeat_all(mgr)
        # idle_ticks pinned high: this test watches the flip queue, not
        # the idle scale-in path.
        ctl = make_controller(mgr, opts=_opts(autoscaler_flip_cooldown_s=0.3,
                                              autoscaler_idle_ticks=99))
        ctl.propose_flip("p2", InstanceType.DECODE)
        rec = ctl.tick()
        assert [a["kind"] for a in rec["actions"]] == [ACTION_FLIP]
        ctl.propose_flip("p3", InstanceType.DECODE)
        rec = ctl.tick()                  # inside the flip cooldown
        assert rec["actions"] == []
        assert any("deferred" in r for r in rec["reasons"])
        time.sleep(0.35)
        rec = ctl.tick()                  # cooldown over: p3 still queued
        assert [(a["kind"], a["instance"]) for a in rec["actions"]] == \
            [(ACTION_FLIP, "p3")]
        mgr.stop()

    def test_drains_dropped_after_demotion(self, coordination):
        """Review regression (write-lease): a drain enqueued by the
        elected master's controller must not be enacted by a frontend
        that was demoted before its reconcile pass ran."""
        mgr = make_mgr(coordination, n_mix=2)
        mgr.request_drain("e2")
        mgr._is_master = False            # demotion lands before reconcile
        mgr.reconcile_once()
        assert mgr.get_instance_state("e2") == InstanceRuntimeState.ACTIVE
        assert FakeChannel.registry["e2"].drains == 0
        # Re-elected: a fresh drain request is enacted normally.
        mgr._is_master = True
        mgr.request_drain("e2")
        mgr.reconcile_once()
        assert mgr.get_instance_state("e2") == InstanceRuntimeState.DRAINING
        mgr.stop()

    def test_decision_log_is_bounded_and_reasoned(self, coordination):
        mgr = make_mgr(coordination, n_mix=1)
        heartbeat_all(mgr)
        ctl = make_controller(
            mgr, opts=_opts(autoscaler_decision_log_capacity=8))
        for _ in range(20):
            ctl.tick()
        rep = ctl.report()
        assert len(rep["decisions"]) <= 8
        assert rep["ticks"] == 20
        assert rep["last_decision_age_s"] >= 0.0
        mgr.stop()


# --------------------------------------------------------------------------
# Write-lease discipline: only the elected master's controller acts.
# --------------------------------------------------------------------------
class TestWriteLease:
    def test_non_master_controller_acts_on_nothing(self, coordination):
        mgr = make_mgr(coordination, n_mix=2)
        heartbeat_all(mgr)
        act = RecordingActuator()
        ctl = make_controller(mgr, actuator=act,
                              monitor=breach_monitor(),
                              is_master=lambda: False)
        for _ in range(3):
            assert ctl.tick() is None
        assert act.scale_outs == [] and act.scale_ins == []
        assert ctl.report()["ticks"] == 0
        assert ctl.report()["decisions"] == []
        mgr.stop()

    def test_demoted_master_straggler_tick_acts_on_nothing(self, coordination):
        """The multimaster drill: a controller that was acting loses the
        election between ticks — its straggler tick must gather nothing,
        enact nothing, log nothing."""
        mgr = make_mgr(coordination, n_mix=2)
        heartbeat_all(mgr)
        mastership = {"is_master": True}
        act = RecordingActuator()
        ctl = make_controller(mgr, actuator=act, monitor=breach_monitor(),
                              is_master=lambda: mastership["is_master"])
        ctl.tick()
        ctl.tick()
        assert act.scale_outs          # acted while elected
        calls = len(act.scale_outs)
        ticks = ctl.report()["ticks"]
        mastership["is_master"] = False   # demotion lands
        for _ in range(3):
            assert ctl.tick() is None     # straggler ticks
        assert len(act.scale_outs) == calls
        assert ctl.report()["ticks"] == ticks
        mgr.stop()

    def test_scheduler_demotion_gates_controller(self, store):
        """Multimaster end-to-end: two schedulers over one coordination
        plane, both with the autoscaler enabled. Only the elected
        master's controller ticks; after the election moves, the old
        master's next sync pass demotes it and its controller goes
        silent while the new master's starts acting."""
        from xllm_service_tpu.rpc import MASTER_KEY
        from xllm_service_tpu.scheduler.scheduler import Scheduler

        opts = _opts(lease_ttl_s=1.0)
        s1 = Scheduler(opts, coord=InMemoryCoordination(store),
                       start_threads=False)
        s2 = Scheduler(opts.with_overrides(rpc_port=8890),
                       coord=InMemoryCoordination(store),
                       start_threads=False)
        try:
            assert s1.is_master and not s2.is_master
            s1.sync_once()
            s2.sync_once()
            assert s1.autoscaler.report()["ticks"] == 1
            assert s2.autoscaler.report()["ticks"] == 0   # replica: silent
            # Election moves (s1's lease lapsed during an outage and s2
            # won): s1's next sync pass must demote and its straggler
            # autoscaler tick acts on nothing.
            s1._coord.set(MASTER_KEY, s2.self_addr)
            s2.is_master = True
            s1.sync_once()
            assert not s1.is_master
            assert s1.autoscaler.report()["ticks"] == 1   # no new tick
            s2.sync_once()
            assert s2.autoscaler.report()["ticks"] == 1   # new master acts
        finally:
            s1.stop()
            s2.stop()


# --------------------------------------------------------------------------
# Actuators.
# --------------------------------------------------------------------------
class TestHintActuator:
    def test_publishes_action_records(self, coordination):
        act = HintActuator(coordination)
        assert act.scale_out(2, "burn over alert") == 2
        act.scale_in("e2", "idle")
        act.reap("e2")
        latest = coordination.get(AUTOSCALER_DECISION_KEY)
        assert latest is not None
        import json
        d = json.loads(latest)
        assert d["action"] == "scale_in" and d["phase"] == "drained"
        stream = coordination.get_prefix(AUTOSCALER_ACTION_KEY_PREFIX)
        assert len(stream) == 3

    def test_identical_unsatisfied_hint_not_respammed(self, coordination):
        act = HintActuator(coordination)
        act.scale_out(2, "replacing lost capacity")
        act.scale_out(2, "replacing lost capacity")   # same hint, same tick
        stream = coordination.get_prefix(AUTOSCALER_ACTION_KEY_PREFIX)
        assert len(stream) == 1


class TestLocalProcessActuator:
    def _actuator(self, cmd, **opt_kw):
        return LocalProcessActuator(
            _opts(autoscaler_actuator="local", **opt_kw),
            spawn_cmd=cmd)

    def test_spawn_and_reap(self):
        cmd = f"{shlex.quote(sys.executable)} -c " \
              f"{shlex.quote('import time; time.sleep(30)')}"
        act = self._actuator(cmd)
        try:
            assert act.scale_out(1, "test") == 1
            kids = act.live_children()
            assert len(kids) == 1 and kids[0].startswith("127.0.0.1:")
            act.reap(kids[0])
            assert act.live_children() == []
        finally:
            act.stop()

    def test_spawn_failure_reports_zero(self):
        act = self._actuator("/nonexistent-binary-xyz --port {port}")
        try:
            assert act.scale_out(2, "test") == 0
            assert act.spawn_failures_total == 2
        finally:
            act.stop()

    def test_immediate_child_death_detected(self):
        cmd = f"{shlex.quote(sys.executable)} -c " \
              f"{shlex.quote('import sys; sys.exit(3)')}"
        act = self._actuator(cmd)
        try:
            assert act.scale_out(1, "test") == 0
            assert act.spawn_failures_total == 1
        finally:
            act.stop()

    def test_runaway_cap(self):
        cmd = f"{shlex.quote(sys.executable)} -c " \
              f"{shlex.quote('import time; time.sleep(30)')}"
        act = self._actuator(cmd, autoscaler_max_instances=1)
        try:
            assert act.scale_out(5, "test") == act._max_procs
        finally:
            act.stop()


# --------------------------------------------------------------------------
# Rebuilt SLO policy: lock-free + staleness-aware (the sensing side).
# --------------------------------------------------------------------------
class _PoisonLock:
    def __enter__(self):
        raise AssertionError("manager lock taken on the SLO hot path")

    def __exit__(self, *exc):
        return False

    def acquire(self, *a, **k):
        raise AssertionError("manager lock taken on the SLO hot path")

    def release(self):
        pass


class TestRebuiltSloPolicy:
    def _fleet(self, coordination):
        mgr = InstanceMgr(coordination, _opts(), start_threads=False,
                          channel_factory=FakeChannel.factory)
        ttft = [[128, 20.0], [512, 60.0], [2048, 200.0]]
        tpot = [[1, 100, 5.0], [4, 1000, 10.0], [16, 8000, 30.0]]
        mgr.register_instance(make_meta(
            "p1", InstanceType.PREFILL, ttft_profiling_data=ttft),
            link_peers=False)
        mgr.register_instance(make_meta(
            "d1", InstanceType.DECODE, tpot_profiling_data=tpot),
            link_peers=False)
        return mgr

    def test_selection_is_lock_free(self, coordination):
        """Regression (ISSUE 13 satellite): the SLO selection must not
        touch `_metrics_lock` — poison it and select anyway."""
        mgr = self._fleet(coordination)
        policy = create_policy("SLO_AWARE", mgr, None, _opts())
        with _ownership.escape("test poisons the lock to prove the hot "
                               "path never takes it"):
            mgr._metrics_lock = _PoisonLock()
        r = policy.select_instances_pair(
            Request(service_request_id="s1", token_ids=list(range(256))))
        assert r.prefill_name == "p1" and r.decode_name == "d1"

    def test_request_load_view_tracks_accounting(self, coordination):
        from xllm_service_tpu.common.types import RequestAction

        mgr = self._fleet(coordination)
        req = Request(service_request_id="s1", token_ids=list(range(64)))
        req.routing.prefill_name = "p1"
        req.routing.decode_name = "d1"
        mgr.update_request_metrics(req, RequestAction.SCHEDULE)
        assert mgr.get_request_loads()["p1"] == (1, 64, 0, 0)
        mgr.update_request_metrics(req, RequestAction.FINISH_PREFILL,
                                   n_new=2)
        view = mgr.get_request_loads()
        assert view["p1"] == (0, 0, 0, 0)
        assert view["d1"] == (0, 0, 1, 66)
        mgr.stop()

    def test_no_flip_of_stale_idle_prefill(self, coordination):
        """A stale idle-LOOKING prefill may be carrying load its
        telemetry stopped reporting — never a flip target."""
        opts = _opts(loadinfo_stale_after_s=0.15, target_tpot_ms=1.0)
        mgr = InstanceMgr(coordination, opts, start_threads=False,
                          channel_factory=FakeChannel.factory)
        tpot_awful = [[1, 100, 500.0], [4, 1000, 900.0],
                      [16, 8000, 2000.0]]
        mgr.register_instance(make_meta("p1", InstanceType.PREFILL),
                              link_peers=False)
        mgr.register_instance(make_meta("p2", InstanceType.PREFILL),
                              link_peers=False)
        mgr.register_instance(make_meta(
            "d1", InstanceType.DECODE, tpot_profiling_data=tpot_awful),
            link_peers=False)
        for n in ("p1", "p2", "d1"):
            mgr.record_instance_heartbeat(
                n, mgr.get_instance_meta(n).incarnation_id, LoadMetrics())
        time.sleep(0.25)
        for n in ("p1", "d1"):            # p2's telemetry goes stale
            mgr.record_instance_heartbeat(
                n, mgr.get_instance_meta(n).incarnation_id, LoadMetrics())
        assert mgr.stale_load_names() == {"p2"}
        flips: list = []
        from xllm_service_tpu.scheduler.policies.slo_aware import \
            select_pair_on_slo

        select_pair_on_slo(
            mgr, opts, Request(service_request_id="s1",
                               token_ids=list(range(128))),
            flip_sink=lambda n, t: flips.append((n, t)))
        assert flips == []                # p2 stale -> not flipped
        mgr.stop()


# --------------------------------------------------------------------------
# Planner: flips through the controller sink + staleness regression.
# --------------------------------------------------------------------------
class TestPlannerThroughController:
    def test_planner_flip_rides_sink(self, coordination):
        from xllm_service_tpu.scheduler.planner import Planner

        mgr = InstanceMgr(coordination, _opts(), start_threads=False,
                          channel_factory=FakeChannel.factory)
        for n, t in (("p1", InstanceType.PREFILL),
                     ("p2", InstanceType.PREFILL),
                     ("d1", InstanceType.DECODE)):
            mgr.register_instance(make_meta(n, t), link_peers=False)
        mgr.record_instance_heartbeat(
            "p1", mgr.get_instance_meta("p1").incarnation_id,
            LoadMetrics(waiting_requests_num=4, running_requests_num=2))
        mgr.record_instance_heartbeat(
            "p2", mgr.get_instance_meta("p2").incarnation_id, LoadMetrics())
        mgr.record_instance_heartbeat(
            "d1", mgr.get_instance_meta("d1").incarnation_id,
            LoadMetrics(running_requests_num=8),
            LatencyMetrics(recent_max_tbt=500.0))
        planner = Planner(mgr, _opts())
        proposals: list = []
        planner.flip_sink = lambda n, t: proposals.append((n, t))
        d = planner.plan_once()
        assert d.flips_requested == [["p2", "DECODE"]]
        assert proposals == [("p2", InstanceType.DECODE)]
        # Nothing hit the instance manager's pending-flip queue directly.
        with mgr._flip_lock:
            assert mgr._pending_flips == {}
        mgr.stop()

    def test_planner_skips_stale_flip_target(self, coordination):
        from xllm_service_tpu.scheduler.planner import Planner

        opts = _opts(loadinfo_stale_after_s=0.15)
        mgr = InstanceMgr(coordination, opts, start_threads=False,
                          channel_factory=FakeChannel.factory)
        for n, t in (("p1", InstanceType.PREFILL),
                     ("p2", InstanceType.PREFILL),
                     ("d1", InstanceType.DECODE)):
            mgr.register_instance(make_meta(n, t), link_peers=False)
        # p2 (the only idle prefill) heartbeats once, then goes silent.
        mgr.record_instance_heartbeat(
            "p2", mgr.get_instance_meta("p2").incarnation_id, LoadMetrics())
        time.sleep(0.25)
        mgr.record_instance_heartbeat(
            "p1", mgr.get_instance_meta("p1").incarnation_id,
            LoadMetrics(waiting_requests_num=4, running_requests_num=2))
        mgr.record_instance_heartbeat(
            "d1", mgr.get_instance_meta("d1").incarnation_id,
            LoadMetrics(running_requests_num=8),
            LatencyMetrics(recent_max_tbt=500.0))
        planner = Planner(mgr, opts)
        d = planner.plan_once()
        assert d.flips_requested == []
        assert "p2" in d.stale_load_entries
        mgr.stop()


# --------------------------------------------------------------------------
# Full-stack drills: Master + fake engines + in-process actuator.
# --------------------------------------------------------------------------
class FakeEngineActuator(FleetActuator):
    """In-process actuator for hermetic closed-loop drills: 'launching an
    instance' starts a FakeEngine against the shared coordination
    store."""

    name = "fake-engine"

    def __init__(self, store, **cfg_kw):
        self._store = store
        self._cfg_kw = cfg_kw
        self.engines: dict[str, FakeEngine] = {}
        self.scale_out_slices: list[str] = []

    def scale_out(self, count, reason, slice_id=""):
        self.scale_out_slices.append(slice_id)
        for _ in range(count):
            kw = dict(self._cfg_kw)
            if slice_id:
                kw["slice_id"] = slice_id
            e = FakeEngine(InMemoryCoordination(self._store),
                           FakeEngineConfig(**kw)).start()
            self.engines[e.name] = e
        return count

    def pending(self, live):
        return sum(1 for n in self.engines if n not in live)

    def reap(self, instance):
        e = self.engines.pop(instance, None)
        if e is not None:
            e.stop()

    def stop(self):
        for e in list(self.engines.values()):
            e.stop()
        self.engines.clear()


REPLY = "Scaling is the art of adding exactly what the burst demands."

ENGINE_CFG = dict(reply_text=REPLY, chunk_size=4, delay_s=0.05,
                  heartbeat_interval_s=0.1, lease_ttl_s=0.5)


def _master_opts(**kw) -> ServiceOptions:
    base = dict(
        host="127.0.0.1", http_port=0, rpc_port=0,
        lease_ttl_s=0.5, reconcile_interval_s=0.05,
        heartbeat_silence_to_suspect_s=0.3,
        detect_disconnected_instance_interval_s=0.3,
        health_probe_attempts=1, health_probe_timeout_s=0.2,
        sync_interval_s=0.1,
        failover_backoff_base_s=0.05, failover_backoff_max_s=0.3,
        rpc_backoff_base_s=0.02, rpc_backoff_max_s=0.1,
        autoscaler_enabled=True,
        # Floor at the drill fleet size: these drills exercise
        # replacement and drains, not idle scale-in — without the floor
        # the controller (correctly) trims the idle 2-engine fleet to 1
        # mid-drill.
        autoscaler_min_instances=2,
        autoscaler_breach_ticks=2, autoscaler_idle_ticks=3,
        autoscaler_scale_out_cooldown_s=0.3,
        autoscaler_scale_in_cooldown_s=0.3,
        autoscaler_stale_hold_s=30.0,
        autoscaler_drain_grace_s=0.05,
        autoscaler_drain_deadline_s=10.0,
        autoscaler_spawn_retry_base_s=0.05,
        autoscaler_spawn_retry_max_s=0.3,
        # The drills isolate replacement/drain mechanics: the fake
        # engine's deliberate 50ms inter-delta delay must not read as a
        # TPOT breach, or burn-driven growth runs the fleet to max
        # mid-drill (that loop is covered by the kernel tests and the
        # closed-loop bench).
        slo_ttft_ms=60000.0, slo_tpot_ms=60000.0)
    base.update(kw)
    return ServiceOptions(**base)


@pytest.fixture()
def scaled_cluster(store):
    """Master (autoscaler on, in-process actuator) + 2 fake engines."""
    master = Master(_master_opts(), coord=InMemoryCoordination(store))
    master.start()
    engines = [FakeEngine(InMemoryCoordination(store),
                          FakeEngineConfig(**ENGINE_CFG)).start()
               for _ in range(2)]
    mgr = master.scheduler.instance_mgr
    assert wait_until(
        lambda: len(mgr.routing_snapshot().schedulable) == 2, timeout=5)
    # Swap in the hermetic actuator only once the external fleet is
    # registered: the cold-start ticks (live=0, desired=min) go to the
    # default hint actuator, so they publish intents instead of
    # spawning extra engines under the drill.
    act = FakeEngineActuator(store, **ENGINE_CFG)
    with _ownership.escape("test injects the hermetic in-process "
                           "actuator between ticks"):
        master.scheduler.autoscaler._actuator = act
    yield master, engines, act
    act.stop()
    for e in engines:
        e.stop()
    master.stop()


def _base(master) -> str:
    return f"http://127.0.0.1:{master.http_port}"


def _stream(master, timeout=30) -> str:
    r = requests.post(_base(master) + "/v1/completions", json={
        "model": "fake-model", "prompt": "autoscale", "stream": True,
        "max_tokens": 64}, stream=True, timeout=timeout)
    assert r.status_code == 200, r.text
    text = []
    for line in r.iter_lines():
        if not line.startswith(b"data: ") or line == b"data: [DONE]":
            continue
        import json as _json

        payload = _json.loads(line[len(b"data: "):])
        text.append(payload["choices"][0]["text"])
    return "".join(text)


@pytest.mark.chaos
class TestClosedLoopDrills:
    def test_admin_autoscaler_surface(self, scaled_cluster):
        master, engines, act = scaled_cluster
        assert wait_until(lambda: requests.get(
            _base(master) + "/admin/autoscaler",
            timeout=5).json()["ticks"] > 0, timeout=10)
        assert wait_until(lambda: requests.get(
            _base(master) + "/admin/autoscaler",
            timeout=5).json()["state"]["desired"] == 2, timeout=10)
        rep = requests.get(_base(master) + "/admin/autoscaler",
                           timeout=5).json()
        assert rep["enabled"] and rep["master"]
        assert rep["actuator"] == "fake-engine"
        assert rep["decisions"]
        metrics = requests.get(_base(master) + "/metrics", timeout=5).text
        assert "autoscaler_last_decision_age_seconds" in metrics
        assert 'fleet_size{role="prefill"}' in metrics

    def test_instance_killed_mid_burst_is_replaced(self, scaled_cluster):
        """Chaos drill (ISSUE 13): an instance killed while serving gets
        its in-flight request failed over AND the lost capacity
        replaced through the actuator."""
        master, engines, act = scaled_cluster
        mgr = master.scheduler.instance_mgr
        assert wait_until(
            lambda: master.scheduler.autoscaler.report()["state"]
            ["desired"] == 2, timeout=10)
        # Kill the engine serving a live stream, mid-stream.
        import threading

        texts: list[str] = []
        t = threading.Thread(target=lambda: texts.append(_stream(master)))
        t.start()
        assert wait_until(
            lambda: any(e.accepted_requests for e in engines), timeout=5)
        victim = next(e for e in engines if e.accepted_requests)
        time.sleep(0.1)       # a few deltas in flight
        victim.kill()
        t.join(timeout=30)
        assert texts and texts[0] == REPLY    # failover completed it
        # Replacement: the controller observes live < desired and spawns
        # a fresh engine through the actuator.
        assert wait_until(lambda: len(act.engines) >= 1, timeout=10)
        assert wait_until(
            lambda: len(mgr.routing_snapshot().schedulable) == 2,
            timeout=10)

    def test_graceful_drain_retires_idle_instance(self, scaled_cluster):
        master, engines, act = scaled_cluster
        mgr = master.scheduler.instance_mgr
        victim = engines[1].name
        mgr.request_drain(victim)
        # Reconcile marks DRAINING; the engine self-stops once idle; the
        # lease-lapse handler deregisters it as cleanly drained.
        assert wait_until(
            lambda: mgr.get_instance_meta(victim) is None, timeout=10)
        # Planned retirement, not an eviction.
        assert INSTANCE_EVICTIONS_TOTAL.labels(
            instance=victim).value() == 0
        # Traffic still flows on the survivor.
        assert _stream(master) == REPLY

    def test_draining_instance_killed_mid_drain_fails_over(
            self, scaled_cluster):
        """Chaos drill (ISSUE 13): a DRAINING instance that dies before
        its in-flight streams finish falls back to the NORMAL failover
        path — the client still gets the full reply."""
        master, engines, act = scaled_cluster
        mgr = master.scheduler.instance_mgr
        import threading

        texts: list[str] = []
        t = threading.Thread(target=lambda: texts.append(_stream(master)))
        t.start()
        assert wait_until(
            lambda: any(e.accepted_requests for e in engines), timeout=5)
        victim = next(e for e in engines if e.accepted_requests)
        mgr.request_drain(victim.name)
        assert wait_until(
            lambda: mgr.get_instance_state(victim.name)
            == InstanceRuntimeState.DRAINING, timeout=5)
        victim.kill()         # dies mid-drain with the stream in flight
        t.join(timeout=30)
        assert texts and texts[0] == REPLY
        assert wait_until(
            lambda: mgr.get_instance_meta(victim.name) is None, timeout=10)
