"""Tiered KV-cache data plane (ISSUE 7): DRAM/SSD offload store,
streaming multi-block transfer, and tier truth in the routing plane.

Covers the satellite matrix: eviction→offload→onload round-trip
byte-identical KV, SSD checksum corruption failing only its own block,
tier-transition KV events applied in order by a watching replica, CAR
preferring a DRAM/SSD holder over a fully cold instance (and failover
re-selects doing the same), plus the chunked streaming transfer with
bandwidth accounting and its inline-fallback chaos drill.
"""

import threading
import time

import numpy as np
import pytest

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.faults import FAULTS
from xllm_service_tpu.common.hashing import prefix_block_hash_hexes
from xllm_service_tpu.common.request import Request
from xllm_service_tpu.common.types import InstanceType, KvCacheEvent
from xllm_service_tpu.coordination.memory import InMemoryCoordination, MemoryStore
from xllm_service_tpu.engine.kv_tier import TieredKVStore
from xllm_service_tpu.engine.kv_transfer import (
    BandwidthAccountant,
    StreamOfferTable,
    pull_stream,
)
from xllm_service_tpu.scheduler.global_kvcache_mgr import GlobalKVCacheMgr
from xllm_service_tpu.scheduler.instance_mgr import InstanceMgr
from xllm_service_tpu.scheduler.policies import create_policy

from fakes import FakeChannel, make_meta, wait_until

BLOCK = 16          # routing-plane block size (tokens)
BLOCK_SHAPE = (2, 2, 2, 1, 4, 8)        # [L, 2, ppb, n_kv, ps, hd]
BLOCK_NBYTES = int(np.prod(BLOCK_SHAPE)) * 4


def _blk(seed: int) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.standard_normal(BLOCK_SHAPE).astype(np.float32)


def _store(dram_blocks=4, ssd_blocks=0, **kw) -> TieredKVStore:
    return TieredKVStore(BLOCK_SHAPE, np.float32,
                         dram_bytes=dram_blocks * BLOCK_NBYTES,
                         ssd_bytes=ssd_blocks * BLOCK_NBYTES, **kw)


@pytest.fixture(autouse=True)
def _reset_channels():
    FakeChannel.reset()
    FAULTS.clear()
    yield
    FakeChannel.reset()
    FAULTS.clear()


@pytest.fixture()
def coord(store):
    c = InMemoryCoordination(store)
    yield c
    c.close()


class TestTieredKVStore:
    def test_dram_round_trip_byte_identical(self):
        st = _store()
        try:
            a = _blk(1)
            assert st.offload("aa" * 16, a)
            assert wait_until(lambda: st.ready("aa" * 16))
            assert st.tier_of("aa" * 16) == "dram"
            off, rem = st.drain_events()
            assert off == ["aa" * 16] and rem == []
            got = st.fetch("aa" * 16)
            assert got.tobytes() == a.tobytes()
            # Move semantics: the fetch consumed the cold copy.
            assert st.tier_of("aa" * 16) is None
        finally:
            st.close()

    def test_dram_overflow_demotes_lru_to_ssd_round_trip(self):
        st = _store(dram_blocks=2, ssd_blocks=4)
        try:
            blocks = {f"{i:02x}" * 16: _blk(i) for i in range(3)}
            for h, arr in blocks.items():
                assert st.offload(h, arr)
            hashes = list(blocks)
            # First-offloaded block is the LRU victim → demoted to SSD.
            assert wait_until(lambda: st.tier_of(hashes[0]) == "ssd")
            assert st.tier_of(hashes[1]) == "dram"
            assert st.tier_of(hashes[2]) == "dram"
            assert st.demote_total == 1
            # Both the offloads AND the demotion ride the event stream
            # (demotion repeats the hash: DRAM→SSD is one more move).
            off, rem = st.drain_events()
            assert off.count(hashes[0]) == 2 and rem == []
            got = st.fetch(hashes[0])
            assert got.tobytes() == blocks[hashes[0]].tobytes()
        finally:
            st.close()

    def test_ssd_checksum_corruption_fails_only_that_block(self):
        st = _store(dram_blocks=1, ssd_blocks=4)
        try:
            h1, h2, h3 = ("11" * 16, "22" * 16, "33" * 16)
            b1, b2 = _blk(11), _blk(12)
            assert st.offload(h1, b1)
            assert wait_until(lambda: st.tier_of(h1) == "dram")
            assert st.offload(h2, b2)      # demotes h1 → SSD
            assert wait_until(lambda: st.tier_of(h1) == "ssd")
            assert st.offload(h3, _blk(13))  # demotes h2 → SSD
            assert wait_until(lambda: st.tier_of(h2) == "ssd")
            # Flip one byte of h1's spill slot behind the store's back.
            slot = st._ssd[h1]
            off = slot * st.block_nbytes
            st._ssd_map[off] = st._ssd_map[off] ^ 0xFF
            assert st.fetch(h1) is None          # corrupt: dropped
            assert st.corrupt_total == 1
            _, rem = st.drain_events()
            assert h1 in rem
            # ...but ONLY h1: its neighbor reads back intact.
            got = st.fetch(h2)
            assert got is not None and got.tobytes() == b2.tobytes()
        finally:
            st.close()

    def test_same_window_onload_cancels_unshipped_offload_event(self):
        """Heartbeat event lists carry no intra-window ordering and the
        global index applies `stored` before `offloaded` — so an
        offload→onload inside ONE window must ship NO `offloaded` (the
        `stored` from the HBM re-install is the whole story), or the
        index would end on the stale cold tier."""
        st = _store()
        try:
            assert st.offload("aa" * 16, _blk(1))
            assert wait_until(lambda: st.ready("aa" * 16))
            # No drain in between: the offload delta is still un-shipped
            # when the onload consumes the block.
            assert st.fetch("aa" * 16) is not None
            off, rem = st.drain_events()
            assert off == [] and rem == []
            # Across windows the pair is fine: offloaded ships first,
            # the later `stored` promotes DRAM→HBM in order.
        finally:
            st.close()

    def test_saturated_pump_drops_instead_of_queueing(self):
        st = _store(dram_blocks=8, threads=1, max_inflight=1)
        gate = threading.Event()

        def slow_fetch(blob):
            gate.wait(5)
            return np.asarray(blob)

        try:
            assert st.offload("aa" * 16, _blk(1), fetch=slow_fetch)
            # Fence: in flight → not ready, no tier.
            assert not st.ready("aa" * 16)
            # Pump saturated: the next eviction is dropped, not queued.
            assert not st.offload("bb" * 16, _blk(2))
            assert st.offload_dropped == 1
            _, rem = st.drain_events()
            assert rem == ["bb" * 16]
            gate.set()
            assert wait_until(lambda: st.ready("aa" * 16))
        finally:
            gate.set()
            st.close()

    def test_discard_supersedes_inflight_offload(self):
        """A block re-donated to HBM (fresh prefill) while its offload is
        still in flight: discard() must abort the pending install — a
        late-landing cold copy would queue an `offloaded` event that
        demotes an HBM-resident block in the global index."""
        st = _store(threads=1)
        gate = threading.Event()

        def gated_fetch(blob):
            gate.wait(5)
            return np.asarray(blob)

        try:
            assert st.offload("aa" * 16, _blk(1), fetch=gated_fetch)
            st.discard("aa" * 16)          # re-prefill superseded it
            gate.set()
            assert wait_until(lambda: not st._pending)
            assert st.tier_of("aa" * 16) is None
            assert st.dram_blocks() == 0
            off, rem = st.drain_events()
            assert off == [] and rem == []
            # ...but a RE-eviction while still pending legitimizes the
            # pending install (same hash, same bytes).
            gate.clear()
            assert st.offload("bb" * 16, _blk(2), fetch=gated_fetch)
            st.discard("bb" * 16)
            assert st.offload("bb" * 16, _blk(2), fetch=gated_fetch)
            gate.set()
            assert wait_until(lambda: st.ready("bb" * 16))
            off, _ = st.drain_events()
            assert off == ["bb" * 16]
        finally:
            gate.set()
            st.close()

    def test_disabled_store_rejects_offloads(self):
        st = _store(dram_blocks=0)
        try:
            assert not st.enabled
            assert not st.offload("aa" * 16, _blk(1))
        finally:
            st.close()


class TestBandwidthAccountant:
    def test_unthrottled_counts_without_pacing(self):
        bw = BandwidthAccountant()
        assert bw.debit("dcn", 1 << 20) == 0.0
        assert bw.stats()["dcn"]["bytes_total"] == 1 << 20

    def test_budget_produces_pacing_debt(self):
        bw = BandwidthAccountant(dcn_bytes_per_s=1000.0)
        assert bw.debit("dcn", 500) == 0.0       # inside one budget-second
        sleep = bw.debit("dcn", 1500)            # bucket now ~2000 > 1000
        assert sleep == pytest.approx(1.0, abs=0.1)
        st = bw.stats()["dcn"]
        assert st["bytes_total"] == 2000
        assert st["budget_bytes_per_s"] == 1000.0

    def test_links_account_independently(self):
        bw = BandwidthAccountant(ici_bytes_per_s=0.0, dcn_bytes_per_s=100.0)
        bw.debit("ici", 10_000)
        assert bw.debit("ici", 10_000) == 0.0    # ICI unthrottled
        assert bw.debit("dcn", 1000) > 0.0       # DCN over budget
        bw.record_busy("ici", 2.0)
        assert bw.stats()["ici"]["throughput_bytes_per_s"] == \
            pytest.approx(10_000.0)


@pytest.mark.chaos
class TestStreamingTransfer:
    def _pull(self, table, desc, **kw):
        calls = []

        def post(url, payload):
            calls.append(payload)
            return table.read_chunk(payload["uuid"], payload["offset"],
                                    payload["max_bytes"])

        out = pull_stream("peer:1", desc, post=post, **kw)
        return out, calls

    def test_chunked_round_trip_byte_identical(self):
        table = StreamOfferTable(default_chunk_bytes=256)
        arr = np.arange(300, dtype=np.float32)          # 1200 bytes
        desc = table.offer("req-1", arr.tobytes(), shape=[300],
                           dtype="float32")
        bw = BandwidthAccountant()
        got, calls = self._pull(table, desc, accountant=bw, link="dcn")
        assert np.array_equal(got, arr)
        # ceil(1200 / 256) round-trips, each one frame.
        assert len(calls) == 5
        assert bw.stats()["dcn"]["bytes_total"] == 1200

    def test_checksum_mismatch_raises(self):
        table = StreamOfferTable(default_chunk_bytes=1024)
        arr = np.arange(64, dtype=np.float32)
        desc = table.offer("req-2", arr.tobytes(), shape=[64],
                           dtype="float32")
        desc["checksum"] = "00" * 8
        with pytest.raises(ValueError, match="checksum"):
            self._pull(table, desc)

    def test_released_offer_surfaces_expiry(self):
        table = StreamOfferTable()
        arr = np.zeros(4, dtype=np.float32)
        desc = table.offer("req-3", arr.tobytes(), shape=[4],
                           dtype="float32")
        table.release(desc["stream_uuid"])
        with pytest.raises(ValueError, match="expired or unknown"):
            self._pull(table, desc)

    def test_pull_fault_point_aborts_transfer(self):
        table = StreamOfferTable(default_chunk_bytes=64)
        arr = np.arange(64, dtype=np.float32)
        desc = table.offer("req-4", arr.tobytes(), shape=[64],
                           dtype="float32")
        FAULTS.add("kv_transfer.pull", action="error", max_fires=1)
        with pytest.raises(Exception):
            self._pull(table, desc)
        # The offer survives the aborted pull: the retry (inline
        # fallback in the agent) decides its fate, not the fault.
        assert table.count() == 1


class TestReplicaEventMerge:
    def test_merge_stored_beats_cross_replica_offloaded(self):
        """dp>1: replica A holds h hot (stored), replica B offloaded its
        copy in the same window — the merged instance delta must ship
        stored-only (the index applies stored before offloaded; shipping
        both would demote the instance below its best tier)."""
        h = ["aa" * 16]
        a = KvCacheEvent(stored=list(h))
        a.merge(KvCacheEvent(offloaded=list(h)))
        assert a.stored == h and a.offloaded == []
        # Symmetric direction.
        b = KvCacheEvent(offloaded=list(h))
        b.merge(KvCacheEvent(stored=list(h)))
        assert b.stored == h and b.offloaded == []

    def test_merge_keeps_within_delta_donate_then_evict(self):
        """Within ONE replica's delta stored+offloaded is the ordered
        donate-then-evict sequence: the cold move must survive the merge
        (only a DIFFERENT replica's hot copy outranks it)."""
        h = ["aa" * 16]
        a = KvCacheEvent(stored=list(h), offloaded=list(h))
        a.merge(KvCacheEvent())
        assert a.stored == h and a.offloaded == h
        # ...but a peer replica holding it hot still wins.
        a.merge(KvCacheEvent(stored=list(h)))
        assert a.stored == h and a.offloaded == []


class TestTierRoutingPlane:
    """Tier truth reaching CAR: engine tier transitions ride the existing
    KV-event wire, the global index demotes/promotes, and routing prefers
    warm holders."""

    def _opts(self, **kw):
        return ServiceOptions(block_size=BLOCK, reconcile_interval_s=0.05,
                              **kw)

    def _fleet(self, coord, names=("p1", "p2")):
        mgr = InstanceMgr(coord, self._opts(),
                          channel_factory=FakeChannel.factory,
                          start_threads=False)
        for n in names:
            mgr.register_instance(make_meta(n, InstanceType.MIX),
                                  link_peers=False)
        return mgr

    def test_replica_applies_tier_transitions_in_order(self, coord, store):
        master = GlobalKVCacheMgr(coord, block_size=BLOCK, is_master=True)
        rc = InMemoryCoordination(store)
        replica = GlobalKVCacheMgr(rc, block_size=BLOCK, is_master=False)
        toks = list(range(BLOCK))
        h = prefix_block_hash_hexes(toks, BLOCK)
        try:
            score = lambda m: m.match(toks).scores.get("i1")  # noqa: E731
            master.record_updated_kvcaches("i1", KvCacheEvent(stored=h))
            master.upload_kvcache()
            assert wait_until(lambda: score(replica) == pytest.approx(1.0))
            # HBM→DRAM, DRAM→SSD, then evicted — each step observed in
            # order by the watching replica.
            master.record_updated_kvcaches("i1", KvCacheEvent(offloaded=h))
            master.upload_kvcache()
            assert wait_until(lambda: score(replica) == pytest.approx(0.6))
            master.record_updated_kvcaches("i1", KvCacheEvent(offloaded=h))
            master.upload_kvcache()
            assert wait_until(lambda: score(replica) == pytest.approx(0.3))
            master.record_updated_kvcaches("i1", KvCacheEvent(removed=h))
            master.upload_kvcache()
            assert wait_until(lambda: replica.match(toks).scores == {})
        finally:
            master.stop()
            replica.stop()
            rc.close()

    def test_onload_promotion_clears_cold_tier(self, coord):
        """The engine reports an onload as `stored`: the index must move
        the instance DRAM→HBM, not double-count it."""
        mgr = GlobalKVCacheMgr(coord, block_size=BLOCK)
        toks = list(range(BLOCK))
        h = prefix_block_hash_hexes(toks, BLOCK)
        mgr.record_updated_kvcaches("i1", KvCacheEvent(stored=h))
        mgr.record_updated_kvcaches("i1", KvCacheEvent(offloaded=h))
        assert mgr.match(toks).scores["i1"] == pytest.approx(0.6)
        mgr.record_updated_kvcaches("i1", KvCacheEvent(stored=h))
        assert mgr.match(toks).scores["i1"] == pytest.approx(1.0)

    def test_car_prefers_dram_holder_over_cold(self, coord):
        """Acceptance: a request whose prefix lives only in p2's DRAM
        routes to p2, not to an equally-idle cold instance."""
        mgr = self._fleet(coord)
        kv = GlobalKVCacheMgr(coord, block_size=BLOCK)
        policy = create_policy("CAR", mgr, kv, self._opts())
        toks = list(range(BLOCK * 3))
        h = prefix_block_hash_hexes(toks, BLOCK)
        # `offloaded` with no prior `stored` lands the blocks in DRAM
        # (exactly what a tier-store offload heartbeat reports).
        kv.record_updated_kvcaches("p2", KvCacheEvent(offloaded=h))
        for _ in range(4):   # beat RR jitter: must be deterministic
            assert policy.select_instances_pair(
                Request(token_ids=toks)).prefill_name == "p2"
        mgr.stop()

    def test_car_prefers_ssd_holder_over_cold(self, coord):
        mgr = self._fleet(coord)
        kv = GlobalKVCacheMgr(coord, block_size=BLOCK)
        policy = create_policy("CAR", mgr, kv, self._opts())
        toks = list(range(BLOCK * 3))
        h = prefix_block_hash_hexes(toks, BLOCK)
        kv.record_updated_kvcaches("p2", KvCacheEvent(offloaded=h))
        kv.record_updated_kvcaches("p2", KvCacheEvent(offloaded=h))  # →SSD
        for _ in range(4):
            assert policy.select_instances_pair(
                Request(token_ids=toks)).prefill_name == "p2"
        mgr.stop()

    def test_failover_reselect_lands_on_dram_holder(self, coord):
        """Failover re-dispatch runs the same CAR selection: with the
        dead HBM holder dropped from the index, the re-select must land
        on the surviving DRAM-tier holder, not a cold instance."""
        mgr = self._fleet(coord, names=("p1", "p2", "p3"))
        kv = GlobalKVCacheMgr(coord, block_size=BLOCK)
        policy = create_policy("CAR", mgr, kv, self._opts())
        toks = list(range(BLOCK * 3))
        h = prefix_block_hash_hexes(toks, BLOCK)
        kv.record_updated_kvcaches("p1", KvCacheEvent(stored=h))     # HBM
        kv.record_updated_kvcaches("p2", KvCacheEvent(offloaded=h))  # DRAM
        req = Request(token_ids=toks)
        assert policy.select_instances_pair(req).prefill_name == "p1"
        # p1 dies: instance-death handling drops it from the index, and
        # the failover loop re-runs select_instances_pair.
        kv.remove_instance("p1")
        mgr.deregister_instance("p1", reason="died")
        for _ in range(4):
            assert policy.select_instances_pair(req).prefill_name == "p2"
        mgr.stop()


@pytest.mark.chaos
class TestEngineTierRoundTrip:
    """The full engine-side loop: LRU eviction → async offload →
    prefix-matching admission onload, with the device movers in the
    middle — proven by identical greedy output across the round trip."""

    def test_evict_offload_onload_identical_tokens(self):
        from test_engine import Collector, make_engine
        from xllm_service_tpu.common.request import SamplingParams
        from xllm_service_tpu.engine.engine import EngineRequest

        engine = make_engine(num_pages=10, kv_tier_dram_bytes=64 << 20)
        store = engine.tier_store
        assert store is not None and store.enabled

        def run(rid, prompt):
            col = Collector()
            req = EngineRequest(rid, rid, token_ids=list(prompt),
                                sampling=SamplingParams(max_tokens=8,
                                                        temperature=0.0,
                                                        ignore_eos=True),
                                on_output=col)
            engine.submit(req)
            while not col.done.is_set():
                if not engine.step():
                    time.sleep(0.001)
            return col.tokens

        prompt_a = list(range(100, 196))        # 96 tokens = 3 hash blocks
        first = run("a1", prompt_a)
        ev = engine.drain_kv_events()
        assert len(ev.stored) == 3              # all full blocks donated

        # An unrelated larger prompt forces LRU eviction of a's blocks;
        # with tiering on they offload to the DRAM arena instead of
        # being dropped.
        run("b1", list(range(300, 428)))        # 128 tokens → page pressure
        assert wait_until(lambda: store.offload_total >= 3, timeout=10)
        ev = engine.drain_kv_events()
        assert len(ev.offloaded) >= 3           # tier transitions on the wire
        assert store.dram_blocks() >= 3

        # Re-admission of a: zero HBM match, but the cold tier extends
        # the prefix — restored pages land via the device scatter ahead
        # of a suffix-only prefill. Greedy output must be identical.
        second = run("a2", prompt_a)
        assert second == first
        assert store.onload_total >= 2          # blocks 0 and 1 (2 keeps
        ev = engine.drain_kv_events()           # the ≥1-suffix-token rule)
        assert len(ev.stored) >= 2              # onloads promoted to HBM

    def test_decode_not_blocked_by_saturated_pump(self):
        """With the transfer pump hard-capped at one in-flight offload,
        eviction bursts DROP overflow instead of queueing — admission
        and decode proceed, and drops surface as plain removals."""
        from test_engine import Collector, make_engine
        from xllm_service_tpu.common.request import SamplingParams
        from xllm_service_tpu.engine.engine import EngineRequest

        engine = make_engine(num_pages=10, kv_tier_dram_bytes=64 << 20,
                             kv_tier_threads=1, kv_tier_max_inflight=1)

        def run(rid, prompt):
            col = Collector()
            req = EngineRequest(rid, rid, token_ids=list(prompt),
                                sampling=SamplingParams(max_tokens=4,
                                                        temperature=0.0,
                                                        ignore_eos=True),
                                on_output=col)
            engine.submit(req)
            while not col.done.is_set():
                if not engine.step():
                    time.sleep(0.001)
            return col.tokens

        for i in range(6):      # churn: every admission evicts
            out = run(f"r{i}", list(range(i * 97, i * 97 + 96)))
            assert len(out) == 4
        st = engine.tier_store.stats()
        # The pump made progress AND the loop never stalled on it; any
        # overflow was dropped and reported, not queued.
        assert st["offload_total"] + st["offload_dropped"] > 0


@pytest.fixture(scope="class")
def stream_pd_cluster():
    """PD pair with the device transfer path disabled and a zero stream
    threshold: every handoff rides the chunked streaming host path."""
    import jax.numpy as jnp

    from xllm_service_tpu.engine.agent import AgentConfig, EngineAgent
    from xllm_service_tpu.engine.config import EngineConfig
    from xllm_service_tpu.master import Master
    from xllm_service_tpu.models.base import tiny_config

    def engine_cfg():
        return EngineConfig(
            model_id="tiny-llama",
            model=tiny_config(dtype=jnp.float32, max_context_len=256),
            num_pages=64, page_size=16, hash_block_size=32,
            max_batch_size=4, max_seq_len=256,
            prefill_buckets=(32, 64, 256))

    mem = MemoryStore(expiry_tick_s=0.05)
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=1.0, sync_interval_s=0.3,
                          reconcile_interval_s=0.1)
    master = Master(opts, coord=InMemoryCoordination(mem))
    master.start()
    agents = []
    for itype in (InstanceType.PREFILL, InstanceType.DECODE):
        agents.append(EngineAgent(
            engine_cfg(),
            AgentConfig(host="127.0.0.1", model_id="tiny-llama",
                        instance_type=itype,
                        heartbeat_interval_s=0.3, lease_ttl_s=1.0,
                        enable_device_kv_transfer=False,
                        kv_stream_threshold_bytes=0,
                        kv_stream_chunk_bytes=4096),
            coord=InMemoryCoordination(mem)).start())
    prefill, decode = agents
    assert wait_until(
        lambda: master.scheduler.instance_mgr.get_instance_meta(prefill.name)
        is not None
        and master.scheduler.instance_mgr.get_instance_meta(decode.name)
        is not None, timeout=10)
    yield master, prefill, decode
    prefill.stop()
    decode.stop()
    master.stop()
    mem.close()


@pytest.mark.chaos
class TestStreamedPDHandoff:
    """PD handoff over the chunked streaming host path, end to end."""

    BODY = {"model": "tiny-llama", "prompt": "stream these blocks " * 6,
            "max_tokens": 6, "temperature": 0, "ignore_eos": True}

    def _post(self, master):
        import requests as rq

        return rq.post(f"http://127.0.0.1:{master.http_port}"
                       "/v1/completions", json=self.BODY, timeout=120)

    def test_streamed_handoff_completes_and_accounts(self, stream_pd_cluster):
        master, prefill, decode = stream_pd_cluster
        r = self._post(master)
        assert r.status_code == 200, r.text
        assert r.json()["usage"]["completion_tokens"] == 6
        assert prefill.kv_stream_sent == 1
        assert decode.kv_stream_received == 1
        # Same slice (default slice-0 on both) → ICI-shaped link, pulled
        # in multiple chunked round-trips, bytes accounted.
        bw = decode.bandwidth.stats()
        assert "ici" in bw and bw["ici"]["bytes_total"] > 4096

    def test_stream_pull_fault_falls_back_inline(self, stream_pd_cluster):
        """Chaos: a fault at kv_transfer.pull aborts the chunked pull;
        the prefill side must retry via the inline host path and the
        request must still complete."""
        master, prefill, decode = stream_pd_cluster
        sent0, recv0 = prefill.kv_stream_sent, decode.kv_stream_received
        host0 = decode.kv_host_received
        FAULTS.add("kv_transfer.pull", action="error", max_fires=1)
        r = self._post(master)
        assert r.status_code == 200, r.text
        assert r.json()["usage"]["completion_tokens"] == 6
        # Stream attempt failed → no stream receive; inline fallback
        # carried the KV instead.
        assert decode.kv_stream_received == recv0
        assert decode.kv_host_received == host0 + 1
        assert prefill.kv_stream_sent == sent0

    def test_stream_offer_fault_falls_back_inline(self, stream_pd_cluster):
        """Chaos: a fault at kv_transfer.offer kills the stream offer
        before the control message ever leaves — the sender must fall
        straight back to the inline host path."""
        master, prefill, decode = stream_pd_cluster
        sent0, host0 = prefill.kv_stream_sent, decode.kv_host_received
        FAULTS.add("kv_transfer.offer", action="error", max_fires=1)
        r = self._post(master)
        assert r.status_code == 200, r.text
        assert r.json()["usage"]["completion_tokens"] == 6
        assert prefill.kv_stream_sent == sent0
        assert decode.kv_host_received == host0 + 1
        # The aborted offer must not leak in the table (gc'd by release).
        assert prefill.kv_stream.count() == 0
