"""Native (C++) coordination server tested through the SAME Python client
as the in-process backend — the two servers are wire-compatible."""

import re
import subprocess
import threading
import time
from pathlib import Path

import pytest

from xllm_service_tpu.coordination.base import WatchEventType
from xllm_service_tpu.coordination.client import TcpCoordinationClient

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(scope="module")
def native_server():
    binary = REPO / "csrc" / "coordination_server"
    build = subprocess.run(["make", "-C", str(REPO / "csrc")],
                           capture_output=True, text=True)
    if build.returncode != 0 or not binary.exists():
        pytest.skip(f"native build failed: {build.stderr[-500:]}")
    proc = subprocess.Popen([str(binary), "--port", "0"],
                            stderr=subprocess.PIPE, text=True)
    # Parse the bound port from stderr.
    line = proc.stderr.readline()
    m = re.search(r"listening on :(\d+)", line)
    assert m, f"unexpected server banner: {line!r}"
    port = int(m.group(1))
    time.sleep(0.1)
    yield port
    proc.terminate()
    proc.wait(timeout=5)


class _Sink:
    def __init__(self):
        self.events = []
        self.cv = threading.Condition()

    def __call__(self, events, prefix):
        with self.cv:
            self.events.extend(events)
            self.cv.notify_all()

    def wait_for(self, pred, timeout=5.0):
        with self.cv:
            return self.cv.wait_for(lambda: pred(self.events), timeout)


class TestNativeServer:
    def test_kv_roundtrip(self, native_server):
        c = TcpCoordinationClient(f"127.0.0.1:{native_server}")
        assert c.set("a/b", 'va"l\nue')      # exercises JSON escaping
        assert c.get("a/b") == 'va"l\nue'
        c.bulk_set({"a/c": "2", "z": "3"})
        assert c.get_prefix("a/") == {"a/b": 'va"l\nue', "a/c": "2"}
        assert c.rm("a/b")
        assert c.get("a/b") is None
        assert c.bulk_rm(["a/c", "missing"]) == 1
        c.close()

    def test_unicode_values(self, native_server):
        c = TcpCoordinationClient(f"127.0.0.1:{native_server}")
        meta = '{"name": "host:1", "模型": "型号", "emoji": "🚀"}'
        assert c.set("uni", meta)
        assert c.get("uni") == meta
        c.close()

    def test_lease_expiry_and_watch(self, native_server):
        owner = TcpCoordinationClient(f"127.0.0.1:{native_server}")
        observer = TcpCoordinationClient(f"127.0.0.1:{native_server}")
        sink = _Sink()
        observer.add_watch("svc/", sink)
        owner.set("svc/me", "alive", ttl_s=0.3)
        assert sink.wait_for(lambda ev: any(
            e.type == WatchEventType.PUT and e.key == "svc/me" for e in ev))
        time.sleep(0.9)
        assert observer.get("svc/me") == "alive"   # keepalive holds it
        owner.close()                              # process death
        assert sink.wait_for(lambda ev: any(
            e.type == WatchEventType.DELETE and e.key == "svc/me"
            for e in ev), timeout=8.0)
        observer.close()

    def test_create_if_absent_election(self, native_server):
        a = TcpCoordinationClient(f"127.0.0.1:{native_server}")
        b = TcpCoordinationClient(f"127.0.0.1:{native_server}")
        assert a.create_if_absent("EL/MASTER", "a", ttl_s=0.3)
        assert not b.create_if_absent("EL/MASTER", "b", ttl_s=0.3)
        a.close()
        deadline = time.time() + 5
        won = False
        while time.time() < deadline:
            if b.create_if_absent("EL/MASTER", "b", ttl_s=0.3):
                won = True
                break
            time.sleep(0.05)
        assert won
        b.close()

    def test_guarded_rm_prefix(self, native_server):
        c = TcpCoordinationClient(f"127.0.0.1:{native_server}")
        c.set("G/CACHE/a", "1")
        c.set("G/CACHE/b", "2")
        assert c.rm_prefix("G/CACHE/", guard_key="G/MASTER") == 0
        c.set("G/MASTER", "me")
        assert c.rm_prefix("G/CACHE/", guard_key="G/MASTER") == 2
        c.close()

    def test_auth(self):
        binary = REPO / "csrc" / "coordination_server"
        if not binary.exists():
            pytest.skip("native binary missing")
        proc = subprocess.Popen(
            [str(binary), "--port", "0", "--username", "u",
             "--password", "p"], stderr=subprocess.PIPE, text=True)
        try:
            m = re.search(r":(\d+)", proc.stderr.readline())
            port = int(m.group(1))
            ok = TcpCoordinationClient(f"127.0.0.1:{port}",
                                       username="u", password="p")
            assert ok.set("k", "v")
            ok.close()
            from xllm_service_tpu.coordination.client import CoordinationError
            with pytest.raises(CoordinationError):
                TcpCoordinationClient(f"127.0.0.1:{port}",
                                      username="u", password="wrong")
        finally:
            proc.terminate()
            proc.wait(timeout=5)

    def test_full_service_stack_on_native_coordination(self, native_server):
        """Master + fake engine coordinated by the NATIVE server."""
        import requests

        from xllm_service_tpu.common.config import ServiceOptions
        from xllm_service_tpu.master import Master
        from xllm_service_tpu.testing.fake_engine import (
            FakeEngine,
            FakeEngineConfig,
        )
        from fakes import wait_until

        addr = f"127.0.0.1:{native_server}"
        opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                              coordination_addr=addr,
                              coordination_namespace="native-e2e",
                              lease_ttl_s=1.0, sync_interval_s=0.3)
        master = Master(opts)
        master.start()
        engine = FakeEngine(
            TcpCoordinationClient(addr, namespace="native-e2e"),
            FakeEngineConfig()).start()
        try:
            assert wait_until(
                lambda: master.scheduler.instance_mgr.get_instance_meta(
                    engine.name) is not None, timeout=10)
            r = requests.post(
                f"http://127.0.0.1:{master.http_port}/v1/completions",
                json={"model": "fake-model", "prompt": "hi",
                      "max_tokens": 32}, timeout=10)
            assert r.status_code == 200, r.text
            assert r.json()["choices"][0]["text"] == \
                "Hello from the fake engine!"
        finally:
            engine.stop()
            master.stop()


class TestServerRestartResilience:
    def test_client_survives_server_restart(self):
        """Kill + restart the native server on the same port: the client
        reconnects, re-creates its leased keys, and watches fire again."""
        binary = REPO / "csrc" / "coordination_server"
        if not binary.exists():
            pytest.skip("native binary missing")
        import socket as _socket

        with _socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]

        def start():
            p = subprocess.Popen([str(binary), "--port", str(port)],
                                 stderr=subprocess.PIPE, text=True)
            p.stderr.readline()
            return p

        proc = start()
        try:
            owner = TcpCoordinationClient(f"127.0.0.1:{port}")
            observer = TcpCoordinationClient(f"127.0.0.1:{port}")
            sink = _Sink()
            observer.add_watch("svc/", sink)
            assert owner.set("svc/me", "alive", ttl_s=0.5)
            assert sink.wait_for(lambda ev: any(e.key == "svc/me"
                                                for e in ev))

            proc.terminate()
            proc.wait(timeout=5)
            time.sleep(0.3)
            proc = start()

            # The owner's keepalive must re-create the key on the fresh
            # (empty) server, and the observer's re-subscribed watch must
            # see it as a new PUT.
            deadline = time.time() + 10
            recreated = False
            while time.time() < deadline:
                if observer.get("svc/me") == "alive":
                    recreated = True
                    break
                time.sleep(0.1)
            assert recreated
            # The observer's re-subscribed watch works for NEW events
            # (reconnect order between the two clients is nondeterministic,
            # so the re-creation PUT itself may or may not be observed).
            owner.set("svc/fresh", "post-restart", ttl_s=1.0)
            assert sink.wait_for(lambda ev: any(
                e.key == "svc/fresh" for e in ev), timeout=5.0)
            owner.close()
            observer.close()
        finally:
            proc.terminate()
            proc.wait(timeout=5)
