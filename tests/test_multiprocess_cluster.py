"""Full-stack drill with REAL OS processes (the SURVEY §4 "multi-node
without a cluster" recipe, automated): native C++ coordination server +
master process + engine process, driven over HTTP — then a
failure/recovery cycle. This is the CI form of the manual verify recipe
(.claude/skills/verify)."""

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest
import requests

REPO = Path(__file__).resolve().parent.parent

ENV = {**os.environ,
       "JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": "",
       "PYTHONPATH": str(REPO)}


def _wait_http(url: str, timeout: float = 30.0):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            r = requests.get(url, timeout=2)
            return r
        except requests.RequestException as e:
            last = e
            time.sleep(0.2)
    raise TimeoutError(f"{url} never came up: {last}")


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    procs: list[subprocess.Popen] = []
    logdir = tmp_path_factory.mktemp("logs")

    def spawn(name, cmd):
        log = open(logdir / f"{name}.log", "w")
        p = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                             env=ENV, cwd=str(REPO))
        procs.append(p)
        return p

    # Native coordination server on a fixed free-ish port.
    build = subprocess.run(["make", "-C", str(REPO / "csrc")],
                           capture_output=True, text=True)
    if build.returncode != 0:
        pytest.skip(f"native build failed: {build.stderr[-300:]}")
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    coord_port = s.getsockname()[1]
    s.close()
    s2 = socket.socket()
    s2.bind(("127.0.0.1", 0))
    http_port = s2.getsockname()[1]
    s2.close()
    s3 = socket.socket()
    s3.bind(("127.0.0.1", 0))
    rpc_port = s3.getsockname()[1]
    s3.close()

    spawn("coord", [str(REPO / "csrc" / "coordination_server"),
                    "--port", str(coord_port)])
    time.sleep(0.5)
    spawn("master", [sys.executable, "-m", "xllm_service_tpu.master",
                     "--coordination-addr", f"127.0.0.1:{coord_port}",
                     "--host", "127.0.0.1",
                     "--http-port", str(http_port),
                     "--rpc-port", str(rpc_port)])
    engine = spawn("engine", [sys.executable,
                              str(REPO / "examples" / "run_fake_engine.py"),
                              "--coordination-addr",
                              f"127.0.0.1:{coord_port}"])
    base = f"http://127.0.0.1:{http_port}"
    _wait_http(base + "/hello")
    # Readiness flips once the engine registers.
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        r = requests.post(base + "/v1/completions", json={
            "model": "fake-model", "prompt": "hi", "max_tokens": 8},
            timeout=10)
        if r.status_code == 200:
            break
        time.sleep(0.3)
    else:
        pytest.fail("cluster never became ready")
    yield base, engine, spawn, coord_port
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


class TestMultiprocessCluster:
    def test_completion_and_stream(self, cluster):
        base, _, _, _ = cluster
        r = requests.post(base + "/v1/completions", json={
            "model": "fake-model", "prompt": "hi", "max_tokens": 16},
            timeout=30)
        assert r.status_code == 200
        assert r.json()["choices"][0]["text"]

        r = requests.post(base + "/v1/chat/completions", json={
            "model": "fake-model", "stream": True,
            "messages": [{"role": "user", "content": "hi"}]},
            stream=True, timeout=30)
        events = [ln for ln in r.iter_lines() if ln.startswith(b"data: ")]
        assert events[-1] == b"data: [DONE]"
        texts = [json.loads(e[6:]) for e in events[:-1]]
        assert any(
            t["choices"][0]["delta"].get("content") for t in texts)

    def test_engine_failure_and_recovery(self, cluster):
        base, engine, spawn, coord_port = cluster
        engine.send_signal(signal.SIGKILL)
        # Lease lapses + probe fails -> SUSPECT -> 503 within ~10s.
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            r = requests.post(base + "/v1/completions", json={
                "model": "fake-model", "prompt": "hi", "max_tokens": 4},
                timeout=10)
            if r.status_code == 503:
                break
            time.sleep(0.3)
        else:
            pytest.fail("dead engine never surfaced as 503")

        # A replacement engine restores service.
        spawn("engine2", [sys.executable,
                          str(REPO / "examples" / "run_fake_engine.py"),
                          "--coordination-addr",
                          f"127.0.0.1:{coord_port}"])
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            r = requests.post(base + "/v1/completions", json={
                "model": "fake-model", "prompt": "hi", "max_tokens": 4},
                timeout=10)
            if r.status_code == 200:
                return
            time.sleep(0.3)
        pytest.fail("replacement engine never restored service")
