"""dp_size > 1: N model replicas behind one agent registration (reference
dp_size metadata, `xllm_rpc_service.proto:40-43`). Verifies dispatch,
aggregate accounting, correctness parity with dp=1, and that concurrent
capacity actually doubles (both replicas hold running sequences at once)."""

import threading

import jax.numpy as jnp
import pytest
import requests

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.types import InstanceType
from xllm_service_tpu.coordination.memory import InMemoryCoordination, MemoryStore
from xllm_service_tpu.engine.agent import AgentConfig, EngineAgent
from xllm_service_tpu.engine.config import EngineConfig
from xllm_service_tpu.master import Master
from xllm_service_tpu.models.base import tiny_config

from fakes import wait_until


def _engine_cfg(max_batch=2) -> EngineConfig:
    return EngineConfig(
        model_id="tiny-llama",
        model=tiny_config(dtype=jnp.float32, max_context_len=256),
        num_pages=64, page_size=16, hash_block_size=32,
        max_batch_size=max_batch, max_seq_len=256,
        prefill_buckets=(32, 64, 256), decode_horizon=2)


@pytest.fixture(scope="module")
def dp_cluster():
    store = MemoryStore(expiry_tick_s=0.05)
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=1.0, sync_interval_s=0.3,
                          reconcile_interval_s=0.1)
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()
    agent = EngineAgent(
        _engine_cfg(),
        AgentConfig(host="127.0.0.1", model_id="tiny-llama",
                    instance_type=InstanceType.MIX,
                    heartbeat_interval_s=0.3, lease_ttl_s=1.0, dp_size=2),
        coord=InMemoryCoordination(store)).start()
    assert wait_until(
        lambda: master.scheduler.instance_mgr.get_instance_meta(agent.name)
        is not None, timeout=10)
    yield master, agent
    agent.stop()
    master.stop()
    store.close()


def _base(master):
    return f"http://127.0.0.1:{master.http_port}"


class TestDpReplicas:
    def test_two_replicas_advertised(self, dp_cluster):
        master, agent = dp_cluster
        assert len(agent.engines) == 2
        meta = master.scheduler.instance_mgr.get_instance_meta(agent.name)
        assert meta.dp_size == 2

    def test_output_matches_dp1(self, dp_cluster):
        master, agent = dp_cluster
        body = {"model": "tiny-llama", "prompt": "replicate this output",
                "max_tokens": 6, "temperature": 0, "ignore_eos": True}
        r = requests.post(_base(master) + "/v1/completions", json=body,
                          timeout=120)
        assert r.status_code == 200, r.text
        dp_text = r.json()["choices"][0]["text"]

        store2 = MemoryStore(expiry_tick_s=0.05)
        m2 = Master(ServiceOptions(host="127.0.0.1", http_port=0,
                                   rpc_port=0, lease_ttl_s=1.0,
                                   sync_interval_s=0.3),
                    coord=InMemoryCoordination(store2))
        m2.start()
        a2 = EngineAgent(
            _engine_cfg(),
            AgentConfig(host="127.0.0.1", model_id="tiny-llama",
                        instance_type=InstanceType.MIX,
                        heartbeat_interval_s=0.3, lease_ttl_s=1.0,
                        dp_size=1),
            coord=InMemoryCoordination(store2)).start()
        try:
            assert wait_until(
                lambda: m2.scheduler.instance_mgr.get_instance_meta(a2.name)
                is not None, timeout=10)
            r2 = requests.post(f"http://127.0.0.1:{m2.http_port}"
                               "/v1/completions", json=body, timeout=120)
            assert r2.status_code == 200
            assert r2.json()["choices"][0]["text"] == dp_text
        finally:
            a2.stop()
            m2.stop()
            store2.close()

    def test_concurrent_capacity_doubles(self, dp_cluster):
        """4 distinct-prefix requests against max_batch_size=2 per replica:
        with dp=2 all four run concurrently — both replicas end up with
        running sequences, and every request completes."""
        master, agent = dp_cluster
        results: list[int] = []
        per_replica_peak = [0, 0]
        stop = threading.Event()

        def watch():
            while not stop.is_set():
                for i, e in enumerate(agent.engines):
                    per_replica_peak[i] = max(per_replica_peak[i],
                                              e.stats()["running"])
                stop.wait(0.01)

        w = threading.Thread(target=watch, daemon=True)
        w.start()

        def fire(i: int) -> None:
            body = {"model": "tiny-llama",
                    "prompt": f"distinct prefix number {i} " * 4,
                    "max_tokens": 24, "temperature": 0, "ignore_eos": True}
            r = requests.post(_base(master) + "/v1/completions", json=body,
                              timeout=120)
            results.append(r.status_code)

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        stop.set()
        w.join(timeout=5)
        assert results == [200, 200, 200, 200]
        # Both replicas actually carried load.
        assert per_replica_peak[0] > 0 and per_replica_peak[1] > 0

    def test_prefix_affinity(self, dp_cluster):
        """The same prompt routes to the same replica both times (its
        prefix cache can hit); dispatch is deterministic in token prefix."""
        master, agent = dp_cluster
        toks = list(range(50, 90))
        first = agent._pick_engine(toks)
        for _ in range(3):
            assert agent._pick_engine(toks) is first
