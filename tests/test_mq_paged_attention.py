"""Multi-query paged attention kernel (spec-verify path) vs the XLA
prefill_attention reference, interpret mode on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xllm_service_tpu.ops.attention import (
    prefill_attention,
    write_prefill_kv,
)
from xllm_service_tpu.ops.pallas_mq_paged_attention import (
    mq_paged_attention_pallas,
)


def _setup(B=3, s_q=5, n_q=8, n_kv=4, hd=128, pages=32, ps=16,
           max_pages=6, seed=0):
    """Build pools where each row's prefix AND block KV are written (the
    verify path's invariant), plus the matching dense reference inputs."""
    rng = np.random.default_rng(seed)
    k_pages = jnp.zeros((pages, n_kv, ps, hd), jnp.float32)
    v_pages = jnp.zeros((pages, n_kv, ps, hd), jnp.float32)
    pt = (jnp.arange(B * max_pages, dtype=jnp.int32)
          .reshape(B, max_pages) + 1)
    prefix = jnp.asarray(rng.integers(1, 3 * ps, B).astype(np.int32))
    block = jnp.asarray(rng.integers(1, s_q + 1, B).astype(np.int32))

    # Prefix KV written page-wise.
    pk = jnp.asarray(rng.normal(size=(B, 3 * ps, n_kv, hd)), jnp.float32)
    pv = jnp.asarray(rng.normal(size=(B, 3 * ps, n_kv, hd)), jnp.float32)
    k_pages, v_pages = write_prefill_kv(
        k_pages, v_pages, pk, pv, pt, jnp.zeros((B,), jnp.int32), prefix)
    # Block KV written at positions prefix..prefix+block.
    bk = jnp.asarray(rng.normal(size=(B, s_q, n_kv, hd)), jnp.float32)
    bv = jnp.asarray(rng.normal(size=(B, s_q, n_kv, hd)), jnp.float32)
    k_pages, v_pages = write_prefill_kv(k_pages, v_pages, bk, bv, pt,
                                        prefix, block)
    q = jnp.asarray(rng.normal(size=(B, s_q, n_q, hd)), jnp.float32)
    return q, bk, bv, k_pages, v_pages, pt, prefix, block


class TestMqPagedAttention:
    @pytest.mark.parametrize("seed", [0, 3])
    def test_matches_prefill_attention(self, seed):
        q, bk, bv, kp, vp, pt, prefix, block = _setup(seed=seed)
        ref = prefill_attention(q, bk, bv, kp, vp, pt, prefix, block)
        got = mq_paged_attention_pallas(q, kp, vp, pt, prefix, block,
                                        interpret=True)
        # Compare only valid (row, s) queries — padding rows are undefined
        # in both paths.
        for b in range(q.shape[0]):
            for s in range(int(block[b])):
                np.testing.assert_allclose(
                    np.asarray(got[b, s]), np.asarray(ref[b, s]),
                    rtol=2e-5, atol=2e-5)

    def test_single_query_degenerates_to_decode_semantics(self):
        """s_q=1, block=1: behaves like decode attention over
        context = prefix + 1."""
        from xllm_service_tpu.ops.attention import paged_attention_xla

        q, bk, bv, kp, vp, pt, prefix, block = _setup(s_q=1, seed=7)
        B = q.shape[0]
        one = jnp.ones((B,), jnp.int32)
        got = mq_paged_attention_pallas(q, kp, vp, pt, prefix, one,
                                        interpret=True)
        ref = paged_attention_xla(q[:, 0], kp, vp, pt, prefix + 1)
        np.testing.assert_allclose(np.asarray(got[:, 0]), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
