"""The sweep writes its digest unattended (tpu_sweep.sh final step) — a
crash there silently loses the round's summary, so pin the summarizer
against every artifact shape the sweep can produce."""

import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_summarizer_handles_all_artifact_shapes(tmp_path):
    m = "decode_tokens_per_sec_per_chip"
    (tmp_path / "bench.json").write_text(json.dumps(
        {"metric": m, "value": 1200.0, "backend": "tpu",
         "pct_roofline": 33.1}))
    (tmp_path / "bench_chunk16.json").write_text(json.dumps(
        {"metric": m, "value": 1500.0, "backend": "tpu",
         "variant": "chunk=16"}))
    # Mosaic failure recorded as an error artifact (rc=0).
    (tmp_path / "bench_rowpipe.json").write_text(json.dumps(
        {"metric": m, "value": 0.0, "backend": "tpu",
         "error": "cp pallas kernel: Mosaic: oops"}))
    # Crashed step: empty file.
    (tmp_path / "bench_8b.json").write_text("")
    # Partial JSON without a value.
    (tmp_path / "bench_int8.json").write_text(json.dumps(
        {"backend": "tpu", "metric": m}))
    # Multi-line spec output (one JSON line per mode).
    (tmp_path / "spec.json").write_text("\n".join([
        json.dumps({"mode": "speculate_k=0", "tok_per_s": 900.0}),
        json.dumps({"metric": "speculative_speedup", "value": 1.4,
                    "backend": "tpu"})]))
    (tmp_path / "serve.json").write_text(json.dumps(
        {"backend": "tpu", "req_per_s": 3.0, "decode_tok_per_s": 700.0,
         "ttft_ms": {"p50": 120.0},
         "ttft_spans_p50_ms": {"client": 120.0}, "errors": 0}))
    (tmp_path / "decode_profile.json").write_text(json.dumps(
        {"backend": "tpu", "full_step_ms": 10.0, "forward_only_ms": 8.0,
         "attention_only_ms": 5.0, "sampling_only_ms": 0.5}))
    (tmp_path / "pd_handoff.json").write_text(json.dumps(
        {"backend": "tpu",
         "ctx_2048": {"device_ms": 5.0, "host_ms": 50.0}}))
    (tmp_path / "compile_gate.json").write_text(json.dumps(
        {"metric": "mosaic_compile_gate", "backend": "tpu",
         "arms": {"paged_default": {"ok": True, "compile_s": 8.0},
                  "fused_writeback": {"ok": False,
                                      "error": "Mosaic: bad layout"}},
         "failed_arms": ["fused_writeback"]}))

    r = subprocess.run(
        [sys.executable, str(REPO / "benchmarks" / "summarize_sweep.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr[-500:]
    out = r.stdout
    assert "| 1b bf16 (default) | 1200.0 |" in out
    assert "Mosaic compile gate: 1 arm(s) FAILED" in out
    assert "`fused_writeback` (Mosaic: bad layout)" in out
    assert "1.250x" in out                      # chunk16 vs default
    assert "Mosaic" in out                      # error arm surfaced
    assert "no value recorded" in out           # partial artifact
    assert "full_step_ms: 10.0" in out
    assert "ctx_2048" in out
    assert "speculative_speedup" in out


def test_summarizer_refuses_cross_backend_ratio(tmp_path):
    """A CPU-fallback arm must never be ratioed against a TPU default
    (VERDICT r4 weak #1): the comparison column says so explicitly."""
    m = "decode_tokens_per_sec_per_chip"
    (tmp_path / "bench.json").write_text(json.dumps(
        {"metric": m, "value": 1091.4, "backend": "tpu"}))
    (tmp_path / "bench_int8.json").write_text(json.dumps(
        {"metric": m, "value": 4400.0, "backend": "cpu",
         "structural_only": True,
         "best_tpu": {"value": 1077.83, "model": "1b", "quant": "int8",
                      "ts": "2026-07-29T14:26:00Z"},
         "note": "accelerator unreachable; measured on CPU fallback"}))
    r = subprocess.run(
        [sys.executable, str(REPO / "benchmarks" / "summarize_sweep.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr[-500:]
    assert "n/a (backend mismatch)" in r.stdout
    assert "4.032x" not in r.stdout              # no cross-backend ratio
    assert "structural only; best on-chip 1077.83 @ 2026-07-29" in r.stdout
