"""Pallas paged-attention kernel vs XLA reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xllm_service_tpu.ops.attention import paged_attention_xla, write_decode_kv
from xllm_service_tpu.ops.pallas_paged_attention import paged_attention_pallas


def _setup(B=4, n_q=8, n_kv=4, hd=128, pages=32, ps=16, max_pages=6, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    k_pages = jax.random.normal(k1, (pages, n_kv, ps, hd), jnp.float32)
    v_pages = jax.random.normal(k2, (pages, n_kv, ps, hd), jnp.float32)
    q = jax.random.normal(k3, (B, n_q, hd), jnp.float32)
    # Distinct pages per row, nonzero ids (page 0 = garbage).
    pt = (jnp.arange(B * max_pages, dtype=jnp.int32).reshape(B, max_pages) + 1)
    return q, k_pages, v_pages, pt


class TestPallasPagedAttention:
    @pytest.mark.parametrize("context_lens", [
        [96, 96, 96, 96],          # full pages
        [1, 17, 33, 90],           # ragged, partial pages
        [5, 96, 0, 50],            # includes an inactive row (ctx 0)
    ])
    def test_matches_xla(self, context_lens):
        q, k_pages, v_pages, pt = _setup()
        cl = jnp.asarray(context_lens, jnp.int32)
        ref = paged_attention_xla(q, k_pages, v_pages, pt, cl)
        got = paged_attention_pallas(q, k_pages, v_pages, pt, cl,
                                     interpret=True)
        # Rows with ctx 0 are undefined in both paths; compare active rows.
        for b, c in enumerate(context_lens):
            if c > 0:
                np.testing.assert_allclose(np.asarray(got[b]),
                                           np.asarray(ref[b]),
                                           rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("context_lens", [
        [96, 96, 96, 96],          # full pages, even chunk counts
        [1, 17, 33, 90],           # ragged: odd chunk counts -> pad chunk
        [5, 96, 0, 50],            # empty row mid-batch: pipeline forward
        [0, 0, 0, 7],              # leading empty rows
        [64, 0, 0, 0],             # trailing empty rows
    ])
    def test_cross_row_pipeline_matches_xla(self, context_lens,
                                            monkeypatch):
        """XLLM_PAGE_PIPELINE=row: rows prefetch each other's first chunk
        (see _kernel) — numerics must be identical across empty rows, odd
        chunk counts, and row boundaries."""
        monkeypatch.setenv("XLLM_PAGE_PIPELINE", "row")
        monkeypatch.setenv("XLLM_PAGE_CHUNK", "1")   # maximize row turns
        q, k_pages, v_pages, pt = _setup()
        cl = jnp.asarray(context_lens, jnp.int32)
        ref = paged_attention_xla(q, k_pages, v_pages, pt, cl)
        got = paged_attention_pallas(q, k_pages, v_pages, pt, cl,
                                     interpret=True)
        for b, c in enumerate(context_lens):
            if c > 0:
                np.testing.assert_allclose(np.asarray(got[b]),
                                           np.asarray(ref[b]),
                                           rtol=2e-5, atol=2e-5)

    def test_span_bucketed_xla_gather_parity(self, monkeypatch):
        """XLLM_XLA_SPAN_BUCKETS=1 forces the pow2 span ladder the
        accelerator backend uses (the CPU suite default keeps the single
        full-span branch for compile time): every ladder rung must match
        the full-span gather, including at occupancies that select the
        shortest span."""
        q, k_pages, v_pages, pt = _setup()
        for cls in ([8, 12, 4, 16],              # shortest span
                    [40, 41, 33, 50],            # middle rung
                    [96, 96, 96, 96]):           # full span
            cl = jnp.asarray(cls, jnp.int32)
            monkeypatch.setenv("XLLM_XLA_SPAN_BUCKETS", "0")
            ref = paged_attention_xla(q, k_pages, v_pages, pt, cl)
            monkeypatch.setenv("XLLM_XLA_SPAN_BUCKETS", "1")
            got = paged_attention_xla(q, k_pages, v_pages, pt, cl)
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-6, atol=2e-6)

    @pytest.mark.parametrize("opts", [
        {"softcap": 30.0},                       # gemma-2 logit cap
        {"window": 40},                          # sliding-window layer
        {"scale": 0.0883883},                    # query_pre_attn_scalar
        {"softcap": 50.0, "window": 33, "scale": 0.0625},
    ])
    def test_gemma2_options_match_xla(self, opts):
        """softcap / sliding window / explicit query scale are static
        kernel params now — gemma-2 decode must route through the kernel
        with XLA-exact numerics."""
        q, k_pages, v_pages, pt = _setup()
        cl = jnp.asarray([96, 41, 8, 64], jnp.int32)
        ref = paged_attention_xla(q, k_pages, v_pages, pt, cl, **opts)
        got = paged_attention_pallas(q, k_pages, v_pages, pt, cl,
                                     interpret=True, **opts)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_gqa_grouping(self):
        q, k_pages, v_pages, pt = _setup(n_q=16, n_kv=2)
        cl = jnp.asarray([40, 96, 8, 64], jnp.int32)
        ref = paged_attention_xla(q, k_pages, v_pages, pt, cl)
        got = paged_attention_pallas(q, k_pages, v_pages, pt, cl,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_fused_decode_step_parity(self):
        """Fused append+attend kernel == scatter-then-attend: attention
        output AND resulting pool contents must both match."""
        from xllm_service_tpu.ops.pallas_fused_decode_attention import (
            fused_decode_attention_pallas,
        )

        q, k_pages, v_pages, pt = _setup()
        B, n_kv, hd = 4, 4, 128
        for prev in ([10, 20, 30, 40],   # mid-page appends
                     [0, 16, 31, 95],    # page starts/edges + pool-full row
                     [0, 0, 0, 0]):      # empty contexts: first token ever
            cl_prev = jnp.asarray(prev, jnp.int32)
            k_new = jax.random.normal(jax.random.PRNGKey(9), (B, n_kv, hd))
            v_new = jax.random.normal(jax.random.PRNGKey(10), (B, n_kv, hd))
            kp_ref, vp_ref = write_decode_kv(k_pages, v_pages, k_new, v_new,
                                             pt, cl_prev)
            cl = cl_prev + 1
            ref = paged_attention_xla(q, kp_ref, vp_ref, pt, cl)
            got, kp_got, vp_got = fused_decode_attention_pallas(
                q, k_new, v_new, k_pages, v_pages, pt, cl, interpret=True)
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)
            np.testing.assert_array_equal(np.asarray(kp_got),
                                          np.asarray(kp_ref))
            np.testing.assert_array_equal(np.asarray(vp_got),
                                          np.asarray(vp_ref))

    def test_fused_decode_step_parity_rowpipe(self, monkeypatch):
        """Fused kernel with cross-row pipelining: same parity contract
        as the default walk across empty contexts, page edges, and odd
        chunk counts."""
        from xllm_service_tpu.ops.pallas_fused_decode_attention import (
            fused_decode_attention_pallas,
        )

        monkeypatch.setenv("XLLM_PAGE_PIPELINE", "row")
        monkeypatch.setenv("XLLM_PAGE_CHUNK", "1")   # maximize row turns
        q, k_pages, v_pages, pt = _setup()
        B, n_kv, hd = 4, 4, 128
        for prev in ([10, 20, 30, 40],
                     [0, 16, 31, 95],
                     [0, 0, 0, 0],
                     [50, 0, 0, 12]):    # empty rows between active ones
            cl_prev = jnp.asarray(prev, jnp.int32)
            k_new = jax.random.normal(jax.random.PRNGKey(9), (B, n_kv, hd))
            v_new = jax.random.normal(jax.random.PRNGKey(10), (B, n_kv, hd))
            kp_ref, vp_ref = write_decode_kv(k_pages, v_pages, k_new, v_new,
                                             pt, cl_prev)
            cl = cl_prev + 1
            ref = paged_attention_xla(q, kp_ref, vp_ref, pt, cl)
            got, kp_got, vp_got = fused_decode_attention_pallas(
                q, k_new, v_new, k_pages, v_pages, pt, cl, interpret=True)
            np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)
            np.testing.assert_array_equal(np.asarray(kp_got),
                                          np.asarray(kp_ref))
            np.testing.assert_array_equal(np.asarray(vp_got),
                                          np.asarray(vp_ref))

    def test_fused_decode_step_gqa(self):
        from xllm_service_tpu.ops.pallas_fused_decode_attention import (
            fused_decode_attention_pallas,
        )

        q, k_pages, v_pages, pt = _setup(n_q=16, n_kv=2)
        B, n_kv, hd = 4, 2, 128
        cl_prev = jnp.asarray([3, 40, 64, 95], jnp.int32)
        k_new = jax.random.normal(jax.random.PRNGKey(4), (B, n_kv, hd))
        v_new = jax.random.normal(jax.random.PRNGKey(5), (B, n_kv, hd))
        kp_ref, vp_ref = write_decode_kv(k_pages, v_pages, k_new, v_new,
                                         pt, cl_prev)
        cl = cl_prev + 1
        ref = paged_attention_xla(q, kp_ref, vp_ref, pt, cl)
        got, kp_got, vp_got = fused_decode_attention_pallas(
            q, k_new, v_new, k_pages, v_pages, pt, cl, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_array_equal(np.asarray(kp_got), np.asarray(kp_ref))
        np.testing.assert_array_equal(np.asarray(vp_got), np.asarray(vp_ref))

    def test_after_decode_write(self):
        """End-to-end shape: write one token then attend, both paths."""
        q, k_pages, v_pages, pt = _setup()
        B, n_kv, hd = 4, 4, 128
        cl_prev = jnp.asarray([10, 20, 30, 40], jnp.int32)
        k_new = jax.random.normal(jax.random.PRNGKey(9), (B, n_kv, hd))
        v_new = jax.random.normal(jax.random.PRNGKey(10), (B, n_kv, hd))
        k_pages, v_pages = write_decode_kv(k_pages, v_pages, k_new, v_new,
                                           pt, cl_prev)
        cl = cl_prev + 1
        ref = paged_attention_xla(q, k_pages, v_pages, pt, cl)
        got = paged_attention_pallas(q, k_pages, v_pages, pt, cl,
                                     interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
