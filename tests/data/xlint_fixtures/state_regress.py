"""RESURRECTED pre-PR-5 bug, static half (never imported).

Before the RCU refactors, `InstanceMgr` kept its per-instance load-info
view as a plain lock-guarded dict and REBUILT it — O(fleet) allocations
— on every heartbeat ingest, under `_cluster_lock` instead of the
`_metrics_lock` the load tables actually belong to: a heartbeat storm
stalled routing behind the rebuild, and the metrics writers raced the
rebuild because the lock didn't cover them. The state-write ownership
rule catches the class statically: `MiniInstanceMgr._load_infos` is
declared `lock:_metrics_lock` in this directory's ownership.py registry
stand-in, so the wrong-lock rebuild flags while the fixed shape stays
quiet."""

import threading


class MiniInstanceMgr:
    def __init__(self):
        self._cluster_lock = threading.Lock()   # lock-order: 85
        self._metrics_lock = threading.Lock()   # lock-order: 86
        self._instances = {}
        self._load_infos = {}

    def record_heartbeat_buggy(self, name, load):
        # VIOLATION (the resurrected shape): the O(fleet) per-heartbeat
        # rebuild ran under the CLUSTER lock — the declared discipline
        # is lock:_metrics_lock.
        with self._cluster_lock:
            self._load_infos = {n: (n, load) for n in self._instances}

    def record_heartbeat_fixed(self, name, load):
        # Control: the fixed path rebuilds under the declared lock.
        with self._metrics_lock:
            self._load_infos = {n: (n, load) for n in self._instances}
