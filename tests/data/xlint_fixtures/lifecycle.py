"""Fixture EFFECT_PAIRS registry stand-in for the pair rules.

Never imported — xlint parses the registry out of the AST (detected by
filename, like the other fixture registries). Endpoints live in
``pair_sites.py`` / ``pair_regress.py`` / ``metrics.py``.
"""

EFFECT_PAIRS = {
    # Clean entries: endpoints all defined in the fixture tree.
    "slot": "SlotGate.claim -> SlotGate.unclaim @ finally;"
            " transfer=Pipeline.hand_off; sink=Pipeline.drop_request;"
            " strict",
    "probe": "ProbeGate.admit -> ProbeGate.resolve @ owner",
    "series": "FGauge.labels -> FGauge.remove @ evict;"
              " helper=evict_series; idempotent",
    # VIOLATION pair-release (x2): both endpoints stale (GhostGate
    # is not defined anywhere in the tree).
    "ghost": "GhostGate.grab -> GhostGate.ungrab @ gc",
    # Hatched stale entry: the hatch silences the registry check.
    "ghost2": "GhostGate.grab -> GhostGate.ungrab @ gc",  # xlint: allow-pair-release(migration window: endpoints land next PR)
    # VIOLATION pair-release: malformed spec (missing '@ scope').
    "broken": "SlotGate.claim -> SlotGate.unclaim",
    # VIOLATION pair-release: endpoints defined but no acquire site.
    "dead": "DeadGate.claim -> DeadGate.unclaim @ finally",
    # VIOLATION pair-evict: evict-scope pair without a helper=.
    "bare-series": "FGauge.labels -> FGauge.remove @ evict",
}
