"""state-decl / state-write / state-read fixtures (never imported).

StateHolder's disciplines live in this directory's ownership.py registry
stand-in; ``hot_read`` / ``hot_read_locked`` are registered in the
wire.py HOT_PATH_FUNCTIONS stand-in for the state-read rule."""

import threading

from somewhere import ownership  # noqa — parsed, not imported


class StateHolder:
    def __init__(self):
        self._lock = threading.Lock()        # lock-order: 80
        self._other_lock = threading.Lock()  # lock-order: 81
        self._table = {}
        self._mode = "idle"
        self._config = {"a": 1}
        self._weights = {"hbm": 1.0}
        self._snap = FrozSnap()
        self._unpub = {}

    # ------------------------------------------------------------ lock: ok
    def write_ok(self):
        with self._lock:
            self._table["k"] = 1          # clean: lexical lock

    def write_via_helper(self):
        with self._lock:
            self._rebuild_locked()

    def _rebuild_locked(self):
        # Clean: every resolvable call site holds the lock (transitive
        # call-summary, the *_locked convention).
        self._table = {"fresh": True}

    def write_escaped(self):
        with ownership.escape("single-writer bootstrap, pre-thread"):
            self._table = {}              # clean: escape hatch

    def write_hatched(self):
        self._table["k"] = 2  # xlint: allow-state-write(benign test knob)

    # ---------------------------------------------------- lock: violations
    def write_unlocked(self):
        self._table["k"] = 1              # VIOLATION: no lock

    def write_wrong_lock(self):
        with self._other_lock:
            self._table.pop("k", None)    # VIOLATION: wrong lock

    def rebind_unlocked(self):
        self._table = {}                  # VIOLATION: rebind, no lock

    def escape_empty(self):
        with ownership.escape(""):        # VIOLATION: reason required
            self._table = {}

    def _cycle_a(self):
        self._table = {"cyc": 1}      # VIOLATION: mutual recursion only —
        self._cycle_b()               # no locked external entry exists

    def _cycle_b(self):
        self._cycle_a()

    # ------------------------------------------------------------ confined
    def tick(self):
        self._mode = "running"            # clean: role entry function

    def _advance(self):
        self._mode = "advancing"          # clean: only called from tick

    def _helper_chain(self):
        self._advance()

    def rogue_rebind(self):
        self._mode = "hijacked"           # VIOLATION: not a role entry

    def stop(self):
        self._mode = "stopped"            # clean: lifecycle teardown

    # ------------------------------------------------- init-only/immutable
    def reconfigure(self):
        self._config = {"a": 2}           # VIOLATION: init-only rebind

    def reconfigure_hatched(self):
        self._config = {"a": 3}  # xlint: allow-state-write(test-only reset knob)

    def tweak_weights(self):
        self._weights = {}                # VIOLATION: immutable rebind

    def poke_weights(self):
        self._weights["ssd"] = 0.1        # VIOLATION: immutable item write

    # -------------------------------------------------- rcu (cross-check)
    def publish_snap(self):
        with self._lock:
            self._snap = FrozSnap()       # clean here: rcu-publish owns it

    def touch_unpub(self):
        with self._lock:
            self._unpub = {}              # decl says rcu but not published

    # ------------------------------------------------------ undeclared attr
    def late_init(self):
        self._surprise = 1                # VIOLATION: state-decl (undeclared)

    def late_init_hatched(self):
        self._scratch = 2  # xlint: allow-state-decl(ephemeral debug probe)

    def close(self):
        self._teardown_flag = True        # clean: lifecycle scope

    # ----------------------------------------------------------- state-read
    def hot_read(self):
        return self._table.get("k")       # VIOLATION: unlocked hot read

    def hot_read_locked(self):
        with self._lock:
            return self._table.get("k")   # clean: lock taken

    def cold_read(self):
        return self._table.get("k")       # clean: not a hot function

    def _run_loop(self):
        # Role entry: ONLY the confined-clean chain is reachable from
        # here (calling the violating methods would launder them through
        # the transitive caller summary).
        while True:
            self.tick()
            self._helper_chain()
