"""Fixture: rcu-frozen / rcu-publish / rcu-read rules — frozen-type
mutation (in-class, via local, via publication field), publication swaps
(fresh under lock = clean; aliased / unlocked / field-by-field =
violations), the thaw escape hatch, and single-load discipline for
hot-registered readers. Never imported; only parsed by xlint."""

import threading


class FrozSnap:
    """Registered frozen type: immutable once constructed."""

    __slots__ = ("items", "n")

    def __init__(self, items):
        self.items = dict(items)   # fine: construction scope
        self.n = len(items)

    def grow(self, k, v):
        self.items[k] = v          # VIOLATION rcu-frozen: in-class mutation
        self.n += 1                # VIOLATION rcu-frozen: attribute write


class Publisher:
    def __init__(self):
        self._lock = threading.Lock()        # lock-order: 30
        self._other_lock = threading.Lock()  # lock-order: 31
        self._snap = FrozSnap({})
        self._infos = {}
        self._unlocked = {}
        self._badspec = {}
        self._weird = {}
        self._stash = FrozSnap({})

    # ------------------------------------------------------ clean publishes
    def publish_ok(self):
        with self._lock:
            self._snap = rcu.publish(FrozSnap({"a": 1}))

    def publish_fresh_local_ok(self):
        nxt = dict(self._infos)
        nxt["k"] = 1
        with self._lock:
            self._infos = nxt

    def publish_via_helper(self):
        with self._lock:
            self._publish_locked()

    def _publish_locked(self):
        # Clean: not lexically under the lock, but every resolvable call
        # site holds it (the one-level call-site summary).
        self._infos = {}

    def get_infos(self):
        return self._infos

    # -------------------------------------------------- publish violations
    def publish_unlocked(self):
        self._snap = FrozSnap({})      # VIOLATION rcu-publish: no lock held

    def publish_wrong_lock(self):
        with self._other_lock:
            self._infos = {}           # VIOLATION rcu-publish: wrong lock

    def publish_alias(self):
        with self._lock:
            self._snap = self._stash   # VIOLATION rcu-publish: not fresh

    def publish_augassign(self):
        with self._lock:
            self._infos += {}          # VIOLATION rcu-publish: augmented

    def publish_annassign_alias(self):
        with self._lock:
            # Annotated swaps are checked too (the PR-4 AnnAssign lesson).
            self._snap: FrozSnap = self._stash   # VIOLATION rcu-publish

    def publish_hatched(self):
        self._infos = {}  # xlint: allow-rcu-publish(fixture demonstrates the hatch)

    # --------------------------------------------------- frozen violations
    def field_by_field(self):
        with self._lock:
            self._infos["k"] = 1        # VIOLATION rcu-frozen: item write
            self._snap.items.update({})  # VIOLATION rcu-frozen: mutator call

    def mutate_via_local(self):
        snap = self._snap
        snap.items["k"] = 1            # VIOLATION rcu-frozen: via local

    def mutate_via_annotated_local(self):
        snap: FrozSnap = self._snap
        snap.items["q"] = 1            # VIOLATION rcu-frozen: AnnAssign alias

    def mutate_ctor_local(self):
        fresh = FrozSnap({})
        fresh.n = 7                    # VIOLATION rcu-frozen: post-ctor write

    def mutate_hatched(self):
        snap = self._snap
        snap.items["k"] = 1  # xlint: allow-rcu-frozen(fixture demonstrates the hatch)

    # -------------------------------------------------------- thaw hatches
    def thaw_ok(self):
        with self._lock:
            store = rcu.thaw(self._snap.items, "declared entry-level writer")
            store["k"] = 1             # clean: thaw-bound local not tracked

    def thaw_no_reason(self):
        with self._lock:
            store = rcu.thaw(self._snap.items)   # VIOLATION rcu-frozen: no reason
            store["k"] = 1

    # --------------------------------------------------- hot-path readers
    def hot_double_read(self):
        if self._snap.n:               # load 1
            return self._snap.items    # load 2 -> VIOLATION rcu-read
        return None

    def hot_single_read(self):
        snap = self._snap              # one load into a local: clean
        return snap.items if snap.n else None

    def hot_hatched_double(self):
        a = self._snap.n  # xlint: allow-rcu-read(fixture demonstrates the hatch)
        return a + self._snap.n


class Reader:
    def __init__(self, pub):
        self._pub = pub

    def hot_accessor_double(self):
        a = self._pub.get_infos()
        b = self._pub.get_infos()      # VIOLATION rcu-read: accessor x2
        return a, b
