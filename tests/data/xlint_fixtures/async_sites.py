"""Fixture: async-blocking rule (blocking primitives inside ``async
def``) and async-aware lock discipline/ordering (``asyncio.Lock``
declarations + ``async with`` acquisition edges). Never imported."""

import asyncio
import threading
import time

import requests


class AsyncOrderly:
    def __init__(self):
        self.alock_outer = asyncio.Lock()   # lock-order: 50
        self.alock_inner = asyncio.Lock()   # lock-order: 51

    async def respects(self):
        async with self.alock_outer:
            async with self.alock_inner:
                pass

    async def inverts(self):
        async with self.alock_inner:
            async with self.alock_outer:    # VIOLATION lock-order (async with)
                pass


class AsyncSloppy:
    def __init__(self):
        self.alock_raw = asyncio.Lock()     # VIOLATION: no order annotation


class AsyncBlocky:
    async def sleeps(self):
        time.sleep(0.1)                     # VIOLATION async-blocking

    async def fetches(self):
        return requests.get("http://x")     # VIOLATION async-blocking

    async def raw_channel(self, ch):
        return ch._post("/rpc/x", {})       # VIOLATION async-blocking

    async def awaited_ok(self):
        await asyncio.sleep(0)              # clean: awaited async API

    async def async_cm_ok(self, session):
        async with session.post("http://x") as r:   # clean: async CM
            return r

    async def nested_sync_ok(self):
        def work():
            time.sleep(0.1)                 # clean: fresh execution context
        return work

    async def hatched(self):
        time.sleep(0.1)  # xlint: allow-async-blocking(fixture demonstrates the hatch)


def sync_blocking_is_not_flagged_here():
    time.sleep(0.0)   # clean: the rule only applies inside async def
