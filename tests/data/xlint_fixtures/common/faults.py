"""Fixture registry for the fault-point rule. Never imported."""

FAULT_POINTS = {
    "demo.used": "referenced from fault_sites.py",
    "demo.dead": "VIOLATION: registered but never referenced",
}
