"""Fixture: the PR-12 admission-slot leak, resurrected. Never imported.

``LeakyFrontend`` is the exact pre-fix shape: a helper acquires the
slot, the caller hands the request off on the happy path, and nothing
releases on the reject/exception paths. ``FixedFrontend`` is the
post-fix control: ownership flag + try/finally, cleared on transfer.
"""

from .pair_sites import GATE, PIPE, do_work  # noqa: F401


class LeakyFrontend:
    """Pre-PR-12: rejected/raising requests leak their slot."""

    def _check(self):
        return GATE.claim()   # VIOLATION pair-release: no caller finally

    def serve(self, req):
        if not self._check():
            return False
        do_work()
        PIPE.hand_off(req)
        return True


class FixedFrontend:
    """Post-PR-12 control: flag-guarded finally, cleared on transfer."""

    def serve(self, req):
        if not GATE.claim():
            return False
        held = True
        try:
            do_work()
            PIPE.hand_off(req)
            held = False     # ownership transferred to the sink
            return True
        finally:
            if held:
                GATE.unclaim()
