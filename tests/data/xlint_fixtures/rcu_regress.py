"""Fixture: RESURRECTED PR-5 BUG (frame prune-after-install), as the
pre-fix replica apply wrote it — the static regression that proves the
rcu-frozen rule catches the class.

Historically: compaction pruned the legacy per-block keys and installed
the full-state frame in SEPARATE coordination revisions; a watching
replica applied the prune DELETEs in place on the LIVE published index
(and, delivered after the frame install, permanently dropped fresh
blocks). The in-place delete is the static signature: a lock-free
``match()`` racing this loop sees the half-pruned intermediate. The
fixed code (scheduler/global_kvcache_mgr.py) batches prune+install into
one ``bulk_apply`` revision and applies it copy-on-write.

Never imported; only parsed by xlint (tests/test_xlint.py asserts the
rule fires on the marked line)."""

import threading


class PrefixIndex:
    __slots__ = ("blocks",)

    def __init__(self, blocks=None):
        self.blocks = blocks if blocks is not None else {}


class GlobalKVCacheMgr:
    def __init__(self):
        self._lock = threading.Lock()   # lock-order: 40
        self._snapshot = PrefixIndex()

    def _on_cache_event(self, events):
        with self._lock:
            for ev in events:
                if ev.type == "DELETE":
                    # PR-5 pre-fix: prune applied IN PLACE on the live
                    # published index, ordered independently of the
                    # full-frame install below.
                    self._snapshot.blocks.pop(ev.key, None)   # VIOLATION rcu-frozen
                else:
                    self._snapshot = PrefixIndex(dict(ev.blocks))
