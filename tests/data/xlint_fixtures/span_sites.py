"""Fixture: span-point rule call sites. Never imported."""

from .tracing import TRACER


def touch(dynamic_point):
    TRACER.span("demo.span_used")
    TRACER.start_span("demo.span_unregistered")   # VIOLATION: unregistered
    TRACER.span(dynamic_point)                    # VIOLATION: non-literal
    TRACER.start_span(dynamic_point)  # xlint: allow-span-point(helper forwards literal points)
    not_a_tracer = object()
    not_a_tracer.span("whatever")                 # not checked: not TRACER
