"""Fixture: metrics-registry rule call sites. Never imported."""

from .metrics import IMPORT_ONLY_TOTAL, NOT_DECLARED, REGISTRY, USED_TOTAL  # noqa: F401
# NOT_DECLARED import above is a VIOLATION (not declared in metrics.py).

ROGUE_TOTAL = REGISTRY.counter("rogue_total")   # VIOLATION: ad-hoc creation


def touch():
    USED_TOTAL.inc()
