"""Fixture: metrics-registry rule call sites. Never imported."""

from . import metrics as m  # noqa: F401
from .metrics import (  # noqa: F401
    IMPORT_ONLY_TOTAL,
    LABELED_TOTAL,
    NOT_DECLARED,
    REGISTRY,
    USED_TOTAL,
)
# NOT_DECLARED import above is a VIOLATION (not declared in metrics.py).

ROGUE_TOTAL = REGISTRY.counter("rogue_total")   # VIOLATION: ad-hoc creation


def touch():
    USED_TOTAL.inc()
    LABELED_TOTAL.labels(instance="a", phase="prefill").inc()   # clean
    LABELED_TOTAL.labels(shard="x").inc()     # VIOLATION: wrong label names
    LABELED_TOTAL.inc()                       # VIOLATION: write without .labels()
    USED_TOTAL.labels(instance="a")           # VIOLATION: no labelnames declared
    m.LABELED_TOTAL.inc()                     # VIOLATION: module-qualified write without .labels()
