"""Fixture: effect-pair rule call sites. Never imported."""

from .metrics import LABELED_TOTAL, evict_series  # noqa: F401


class SlotGate:
    """Acquire/release endpoints of the fixture 'slot' pair."""

    def claim(self):
        return True

    def unclaim(self):
        pass


class ProbeGate:
    """Owner-scope 'probe' pair: the owner class itself balances it."""

    def admit(self):
        self._inflight = True
        return True

    def resolve(self, ok):
        self._inflight = False


class DeadGate:
    """Endpoints for the 'dead' pair — deliberately never acquired."""

    def claim(self):
        return True

    def unclaim(self):
        pass


class Pipeline:
    """Transfer/sink endpoints of the 'slot' pair."""

    def hand_off(self, req):
        req["held"] = True

    def drop_request(self, req):
        # Sink-owned release of a transferred slot (pair machinery:
        # exempt from the call-site rules).
        if req.pop("held", False):
            GATE.unclaim()


GATE = SlotGate()
PROBE = ProbeGate()
PIPE = Pipeline()


def do_work():
    pass


# ---- pair-release shapes ---------------------------------------------------
def clean_finally():
    """Blessed shape: acquire discharged by this function's finally."""
    held = GATE.claim()
    try:
        do_work()
    finally:
        if held:
            GATE.unclaim()


class Frontend:
    """Acquire-in-a-helper shape: the helper's caller owns the finally."""

    def _begin(self):
        return GATE.claim()

    def handle(self):
        held = self._begin()
        try:
            do_work()
        finally:
            if held:
                GATE.unclaim()


def leaky_claim():
    if GATE.claim():    # VIOLATION pair-release: no finally discharge
        do_work()


def hatched_claim():
    GATE.claim()  # xlint: allow-pair-release(drill hook: the harness releases the slot)
    do_work()


def probe_round():
    """Owner-scope pairs impose no call-site discipline."""
    if PROBE.admit():
        PROBE.resolve(True)


# ---- pair-once shapes ------------------------------------------------------
def finish_twice(req):
    GATE.unclaim()
    do_work()
    GATE.unclaim()      # VIOLATION pair-once: released twice on one path


def finish_after_transfer(req):
    PIPE.hand_off(req)
    GATE.unclaim()      # VIOLATION pair-once: release after transfer


def finish_guarded(req):
    GATE.unclaim()
    if req.get("held"):
        GATE.unclaim()  # clean: second release behind the ownership flag


def finish_hatched(req):
    PIPE.hand_off(req)
    GATE.unclaim()  # xlint: allow-pair-once(abort path: the sink never ran)


# ---- pair-evict shapes -----------------------------------------------------
def evict_direct(name):
    # VIOLATION pair-evict: hand-rolled eviction path.
    LABELED_TOTAL.remove(instance=name, phase="prefill")


def evict_blessed(name):
    evict_series(LABELED_TOTAL, instance=name, phase="prefill")   # clean


def evict_then_write(name):
    evict_series(LABELED_TOTAL, instance=name, phase="prefill")
    # VIOLATION pair-evict: write after evict (gauge resurrection).
    LABELED_TOTAL.labels(instance=name, phase="prefill").inc()


def evict_hatched(name):
    LABELED_TOTAL.remove(instance=name, phase="prefill")  # xlint: allow-pair-evict(test-only shim owns this family)
