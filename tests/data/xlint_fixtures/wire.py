"""hot-json fixture registry (stands in for rpc/wire.py's
HOT_PATH_FUNCTIONS — the rule keys on the file name)."""

HOT_PATH_FUNCTIONS = {
    "HotDispatcher.forward_hot": "dispatch wire with hand-rolled JSON",
    "HotDispatcher.forward_hatched": "dispatch wire with a hatched encode",
    "push_hot": "module-level hot function with a dumps alias",
    "Ghost.never_defined": "stale registry entry (no such function)",
}
