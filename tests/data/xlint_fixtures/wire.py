"""hot-json fixture registry (stands in for rpc/wire.py's
HOT_PATH_FUNCTIONS — the rule keys on the file name)."""

HOT_PATH_FUNCTIONS = {
    "HotDispatcher.forward_hot": "dispatch wire with hand-rolled JSON",
    "HotDispatcher.forward_hatched": "dispatch wire with a hatched encode",
    "push_hot": "module-level hot function with a dumps alias",
    "Ghost.never_defined": "stale registry entry (no such function)",
    # rcu-read fixtures (rcu_sites.py): single-load discipline applies
    # to registered hot readers.
    "Publisher.hot_double_read": "double publication load (violation)",
    "Publisher.hot_single_read": "single publication load (clean)",
    "Publisher.hot_hatched_double": "double load with a hatch (clean)",
    "Reader.hot_accessor_double": "double accessor load (violation)",
    # state-read fixtures (state_sites.py): lock-guarded attrs are not
    # read on hot paths without the lock.
    "StateHolder.hot_read": "unlocked lock-guarded read (violation)",
    "StateHolder.hot_read_locked": "lock taken before the read (clean)",
}
