"""State-ownership fixture registry (stands in for
devtools/ownership.py — the state rules key on the file name). Carries
deliberate registry-staleness violations alongside the live entries
used by state_sites.py / state_regress.py."""

STATE_DISCIPLINES = {
    # Live entries exercised by state_sites.py.
    "StateHolder._table": "lock:_lock",
    "StateHolder._mode": "confined:demo-loop",
    "StateHolder._config": "init-only",
    "StateHolder._weights": "immutable",
    "StateHolder._snap": "rcu",
    # Live entries exercised by state_regress.py (the resurrected
    # pre-PR-5 shape: load infos were a lock-guarded dict back then).
    "MiniInstanceMgr._load_infos": "lock:_metrics_lock",
    # Deliberate registry-staleness violations.
    "Ghost._attr": "lock:_lock",              # VIOLATION: no such class
    "StateHolder._never": "lock:_lock",       # VIOLATION: never assigned
    "StateHolder._nolock": "lock:_missing_lock",   # VIOLATION: no such lock
    "StateHolder._badrole": "confined:ghost-role",  # VIOLATION: no such role
    "StateHolder._badspec": "franchised",     # VIOLATION: malformed spec
    "StateHolder._unpub": "rcu",              # VIOLATION: not in RCU_PUBLICATIONS
}

THREAD_ROLES = {
    "demo-loop": {
        "threads": ("demo-loop",),
        "entries": ("StateHolder._run_loop", "StateHolder.tick"),
    },
    # VIOLATION: no confined declaration references this role.
    "dead-role": {
        "threads": ("never",),
        "entries": ("StateHolder.tick",),
    },
}

STATE_CLASSES = (
    "StateHolder",
    "MiniInstanceMgr",
    "GhostStrict",   # VIOLATION: no class definition in the tree
)
