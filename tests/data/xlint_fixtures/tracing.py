"""Fixture span-point registry for the span-point rule. Never imported."""

SPAN_POINTS = {
    "demo.span_used": "referenced from span_sites.py",
    "demo.span_dead": "VIOLATION: no call site",
}


class _Tracer:
    def span(self, point, **kw):
        return object()

    start_span = span


TRACER = _Tracer()
