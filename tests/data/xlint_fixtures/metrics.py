"""Fixture instrument registry for the metrics rule. Never imported."""


class _Reg:
    def counter(self, name, help_="", labelnames=()):
        return object()


REGISTRY = _Reg()

USED_TOTAL = REGISTRY.counter("used_total")
DEAD_TOTAL = REGISTRY.counter("dead_total")      # VIOLATION: never used
IMPORT_ONLY_TOTAL = REGISTRY.counter("import_only_total")   # VIOLATION: imported, never referenced
DUP_A = REGISTRY.counter("duplicated_name")
DUP_B = REGISTRY.counter("duplicated_name")      # VIOLATION: duplicate name
LABELED_TOTAL = REGISTRY.counter("labeled_total",
                                 labelnames=("instance", "phase"))


class FGauge:
    """Endpoint stand-in for the fixture 'series' evict pair."""

    def labels(self, **kw):
        return self

    def remove(self, **kw):
        pass


def evict_series(metric, **labels):
    """Blessed release site for the fixture 'series' evict pair."""
    metric.remove(**labels)
