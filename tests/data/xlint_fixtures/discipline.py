"""Fixture: lock-discipline rule — missing annotation, declaration outside
__init__, bare acquire/release. Never imported; only parsed by xlint."""

import threading


class Sloppy:
    def __init__(self):
        self.ok_lock = threading.Lock()            # lock-order: 10
        self.unannotated_lock = threading.Lock()   # VIOLATION: no order

    def lazy_init(self):
        self.late_lock = threading.Lock()   # lock-order: 11  (VIOLATION: outside __init__)

    def manual_acquire(self):
        self.ok_lock.acquire()    # VIOLATION: with-only
        try:
            pass
        finally:
            self.ok_lock.release()   # VIOLATION: with-only

    def excused_acquire(self):
        got = self.ok_lock.acquire(False)  # xlint: allow-bare-acquire(fixture demonstrates the escape hatch)
        if got:
            self.ok_lock.release()  # xlint: allow-bare-acquire(fixture demonstrates the escape hatch)


def makes_local_lock():
    tmp_lock = threading.Lock()   # VIOLATION: function-local lock
    with tmp_lock:
        return 1


def excused_local_lock():
    scratch = threading.Lock()  # xlint: allow-local-lock(fixture demonstrates the escape hatch)
    with scratch:
        return 2


class Conflicted:
    def __init__(self, flag):
        if flag:
            self.mode_lock = threading.Lock()   # lock-order: 20
        else:
            self.mode_lock = threading.Lock()   # lock-order: 21  (VIOLATION: conflicting re-declaration)
