"""Fixture: fault-point rule call sites. Never imported."""


class _Plane:
    def check(self, point, **ctx):
        pass

    def fire(self, point, **ctx):
        pass


FAULTS = _Plane()


def exercise(dynamic_point):
    FAULTS.check("demo.used")           # ok: registered
    FAULTS.check("demo.unregistered")   # VIOLATION: unknown point
    FAULTS.fire(dynamic_point)          # VIOLATION: non-literal point
