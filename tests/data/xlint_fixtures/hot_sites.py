"""hot-json rule fixtures: hand-rolled JSON inside registered hot-path
dispatch functions (registry in the sibling wire.py)."""

import json

import requests


class HotDispatcher:
    def forward_hot(self, url, payload):
        body = json.dumps(payload)                    # violation: dumps ref
        requests.post(url, json=payload, timeout=1)   # violation: json= kwarg
        return body

    def forward_hatched(self, url, payload):
        dumps = json.dumps  # xlint: allow-hot-json(protocol JSON frames, not the dispatch wire)
        return dumps(payload)

    def unregistered_sibling(self, payload):
        # Not in the registry: hand-rolled JSON is fine here.
        return json.dumps(payload)


def push_hot(url, payload):
    dumps = json.dumps            # violation: alias laundering the encode
    return dumps(payload)


def bystander(payload):
    # Module-level function not in the registry: quiet.
    return json.dumps(payload)
