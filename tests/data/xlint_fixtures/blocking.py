"""Fixture: no-blocking-under-lock rule — deliberate violations + clean
and hatched variants. Never imported; only parsed by xlint."""

import threading
import time

import requests


class Chatty:
    def __init__(self):
        self._lock = threading.Lock()   # lock-order: 1

    def sleeps_under_lock(self):
        with self._lock:
            time.sleep(0.1)             # VIOLATION

    def http_under_lock(self):
        with self._lock:
            requests.post("http://example", json={})   # VIOLATION

    def coord_under_lock(self):
        with self._lock:
            self._coord.set("k", "v")   # VIOLATION (coordination call)

    def channel_rpc_under_lock(self, ch):
        with self._lock:
            ch.forward("/v1/completions", {})   # VIOLATION (channel RPC)

    def fine_outside(self):
        with self._lock:
            x = 1
        time.sleep(0)                   # ok: after the lock is released
        return x

    def closure_defined_under_lock(self):
        # ok: the nested def RUNS later, not under the lock.
        with self._lock:
            def later():
                time.sleep(1)
            return later

    def excused(self):
        with self._lock:
            # xlint: allow-blocking-under-lock(fixture demonstrates the escape hatch)
            time.sleep(0)
