"""Fixture: broad-except rule in a scoped (scheduler) path. Never
imported; only parsed by xlint."""

import logging

logger = logging.getLogger(__name__)


def silent_swallow():
    try:
        pass
    except Exception:     # VIOLATION: neither logs nor re-raises
        pass


def bare_handler():
    try:
        pass
    except:               # noqa: E722  VIOLATION: bare except
        pass


def logs_it():
    try:
        pass
    except Exception:     # ok: logs
        logger.exception("boom")


def reraises():
    try:
        pass
    except Exception:     # ok: re-raises
        raise


def excused():
    try:
        pass
    except Exception:  # xlint: allow-broad-except(fixture demonstrates the escape hatch)
        pass
