"""RCU fixture registry (stands in for devtools/rcu.py — the rcu rules
key on the file name). Carries deliberate registry-staleness violations
alongside the live entries used by rcu_sites.py / rcu_regress.py."""

RCU_FROZEN_TYPES = {
    "FrozSnap": "published fixture snapshot (rcu_sites.py)",
    "PrefixIndex": "published fixture index (rcu_regress.py)",
    "GhostType": "VIOLATION: stale registry entry (no such class)",
}

RCU_PUBLICATIONS = {
    "Publisher._snap": "FrozSnap @ _lock",
    "StateHolder._snap": "FrozSnap @ _lock",   # state-decl rcu cross-check
    "Publisher._infos": "dict @ _lock",
    "GlobalKVCacheMgr._snapshot": "PrefixIndex @ _lock",
    "Phantom._x": "dict @ _lock",            # VIOLATION: no such class
    "Publisher._never": "dict @ _lock",      # VIOLATION: never assigned
    "Publisher._unlocked": "dict @ _nolock",  # VIOLATION: undeclared lock
    "Publisher._badspec": "dict-no-at-sign",  # VIOLATION: malformed spec
    "Publisher._weird": "Widget @ _lock",    # VIOLATION: unknown type
}
