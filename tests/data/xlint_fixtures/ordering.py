"""Fixture: lock-order rule — inversion via nested with, inversion via a
project-resolvable call, and a two-lock cycle. Never imported."""

import threading


class Orderly:
    def __init__(self):
        self.lock_a = threading.Lock()   # lock-order: 1
        self.lock_b = threading.Lock()   # lock-order: 2

    def respects_order(self):
        with self.lock_a:
            with self.lock_b:
                pass

    def inverts_order(self):
        with self.lock_b:
            with self.lock_a:            # VIOLATION: 2 -> 1 (also a cycle
                pass                     # together with respects_order)


class Interproc:
    def __init__(self):
        self.outer_lock = threading.Lock()   # lock-order: 5
        self.inner_lock = threading.Lock()   # lock-order: 4

    def grab_inner_interproc(self):
        with self.inner_lock:
            pass

    def outer_then_call(self):
        with self.outer_lock:
            self.grab_inner_interproc()      # VIOLATION: 5 -> 4 via call
