"""Shared test doubles: fake engine channel + registration helpers.

The reference has no fake engine (SURVEY.md §4 names this the key testing
gap); this module is the hermetic stand-in for channel-level behavior. The
full in-process fake engine (heartbeats, Generations streams) lives in
`xllm_service_tpu.testing.fake_engine`.
"""

from __future__ import annotations

import json
import threading
import uuid

from xllm_service_tpu.common.types import InstanceMetaInfo, InstanceType, TpuTopology
from xllm_service_tpu.rpc import instance_key


class FakeChannel:
    """Records control-plane calls; health and link results are scriptable."""

    registry: dict[str, "FakeChannel"] = {}

    def __init__(self, name: str, rpc_addr: str = ""):
        self.name = name
        self.healthy = True
        self.link_ok = True
        self.links: list[str] = []
        self.unlinks: list[str] = []
        self.cancels: list[str] = []
        self.flips: list[str] = []
        self.flip_ok = True
        self.drains = 0
        self.drain_ok = True
        self.closed = False
        FakeChannel.registry[name] = self

    @classmethod
    def factory(cls, name: str, rpc_addr: str) -> "FakeChannel":
        return cls(name, rpc_addr)

    @classmethod
    def reset(cls) -> None:
        cls.registry.clear()

    def health(self, timeout_s: float = 1.0) -> bool:
        return self.healthy

    def link(self, peer: InstanceMetaInfo) -> bool:
        if self.link_ok:
            self.links.append(peer.name)
        return self.link_ok

    def unlink(self, peer_name: str) -> bool:
        self.unlinks.append(peer_name)
        return True

    def cancel(self, service_request_id: str) -> bool:
        self.cancels.append(service_request_id)
        return True

    def flip_role(self, new_type: str) -> bool:
        if self.flip_ok:
            self.flips.append(new_type)
        return self.flip_ok

    def drain(self) -> bool:
        self.drains += 1
        return self.drain_ok

    def models(self):
        return []

    def forward(self, path, payload):
        return True, {}

    def close(self) -> None:
        self.closed = True


def make_meta(name: str, itype: InstanceType = InstanceType.MIX,
              **kw) -> InstanceMetaInfo:
    return InstanceMetaInfo(
        name=name, rpc_address=name, type=itype,
        incarnation_id=kw.pop("incarnation_id", uuid.uuid4().hex[:8]),
        topology=TpuTopology(slice_id=kw.pop("slice_id", "s0"),
                             host=kw.pop("topo_host", ""),
                             chip=kw.pop("topo_chip", -1),
                             mesh_shape=[1], axis_names=["data"]),
        **kw)


def register_in_coord(coord, meta: InstanceMetaInfo, ttl_s: float = 3.0,
                      keepalive: bool = True) -> None:
    """Simulate an engine registering itself (reference: engines write their
    meta to etcd under a TTL lease, SURVEY.md §3.4)."""
    coord.set(instance_key(meta.type.value, meta.name), meta.to_json(),
              ttl_s=ttl_s, keepalive=keepalive)


def wait_until(pred, timeout: float = 3.0, interval: float = 0.02) -> bool:
    import time

    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False
