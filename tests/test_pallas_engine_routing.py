"""Engine-level Pallas routing, hermetic on CPU.

XLLM_PALLAS_INTERPRET=1 makes the dispatch gates treat the CPU backend as
kernel-capable and run every Pallas kernel in interpret mode, so these
tests drive the REAL trace-time routing (fused decode writeback, Pallas
chunked-prefill attention) end-to-end through the engine and compare
greedy outputs against the default XLA paths. Tiny 1-layer config with
head_dim=128 (the Mosaic lane-width requirement the gates check).
"""

import jax.numpy as jnp

from xllm_service_tpu.common.request import SamplingParams
from xllm_service_tpu.engine.config import EngineConfig
from xllm_service_tpu.engine.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.models.base import tiny_config

from test_engine import Collector, run_requests


def _pallas_capable_engine(**kw) -> InferenceEngine:
    cfg = EngineConfig(
        model=tiny_config(dtype=jnp.float32, hidden_size=128,
                          num_heads=2, num_kv_heads=1, head_dim=128,
                          num_layers=1, ffn_size=128,
                          max_context_len=128),
        num_pages=40, page_size=16, hash_block_size=32,
        max_batch_size=2, max_seq_len=128, prefill_buckets=(16, 32, 128),
        decode_horizon=4, **kw)
    return InferenceEngine(cfg)


def _greedy(engine, prompt, n=6):
    col = Collector()
    req = EngineRequest(service_request_id="r0", token_ids=list(prompt),
                        sampling=SamplingParams(max_tokens=n,
                                                temperature=0.0),
                        on_output=col)
    run_requests(engine, [req])
    return col.tokens


PROMPT = [7, 11, 13, 17, 19, 23, 29, 31, 37, 41]


class TestPallasEngineRouting:
    def test_fused_decode_writeback_matches_default(self, monkeypatch):
        baseline = _greedy(_pallas_capable_engine(), PROMPT)
        assert len(baseline) == 6
        monkeypatch.setenv("XLLM_PALLAS_INTERPRET", "1")
        monkeypatch.setenv("XLLM_KV_WRITEBACK", "fused")
        fused = _greedy(_pallas_capable_engine(), PROMPT)
        assert fused == baseline

    def test_pallas_prefill_matches_default(self, monkeypatch):
        baseline = _greedy(_pallas_capable_engine(), PROMPT)
        monkeypatch.setenv("XLLM_PALLAS_INTERPRET", "1")
        monkeypatch.setenv("XLLM_PREFILL_PALLAS", "1")
        routed = _greedy(_pallas_capable_engine(), PROMPT)
        assert routed == baseline

    def test_all_pallas_paths_together(self, monkeypatch):
        baseline = _greedy(_pallas_capable_engine(), PROMPT)
        monkeypatch.setenv("XLLM_PALLAS_INTERPRET", "1")
        monkeypatch.setenv("XLLM_KV_WRITEBACK", "fused")
        monkeypatch.setenv("XLLM_PREFILL_PALLAS", "1")
        routed = _greedy(_pallas_capable_engine(), PROMPT)
        assert routed == baseline
