"""Tier-1 tests for the continuous-profiling plane (ISSUE 18):

- sampling profiler units: role attribution off THREAD_ROLES, bounded
  per-role stack tables (overflow bucket), window rotation, folded-stack
  round-trip, depth bounding,
- lifecycle: refcounted start/stop is leak-free under the runtime pair
  verifier (strict `profiler-thread` pair), hz=0 spawns nothing,
- anomaly path: flight-recorder bundles carry a non-empty profile window
  while the sampler runs,
- critical-path decomposition units (stage waits sum exactly to the
  TTFT window; relay + failover cases) and the /admin/hotpath aggregate,
- SLO trace exemplars: worst trace_id per window bucket,
- CPU_ATTR -> hotpath_cpu_seconds_total counter export,
- fleet drill: `/admin/profile?scope=fleet` merges per-role stacks,
  survives a dead agent with a partial marker, and the critical path of
  a relayed + failed-over request sums to the measured TTFT.
"""

import json
import os
import threading
import time

import pytest
import requests

from xllm_service_tpu.common.faults import FAULTS
from xllm_service_tpu.common.flightrecorder import RECORDER, FlightRecorder
from xllm_service_tpu.common.hotpath import CpuAttribution
from xllm_service_tpu.common.metrics import HOTPATH_CPU_SECONDS
from xllm_service_tpu.common.slo import SloMonitor
from xllm_service_tpu.common.tracing import TRACER
from xllm_service_tpu.coordination.memory import InMemoryCoordination
from xllm_service_tpu.devtools import lifecycle as _lifecycle
from xllm_service_tpu.master import Master
from xllm_service_tpu.profiling import (
    CRITICAL_STAGES,
    PROFILER,
    SamplingProfiler,
    aggregate_critical_paths,
    critical_path,
    parse_folded,
    summarize_stacks,
)
from xllm_service_tpu.testing.fake_engine import FakeEngine, FakeEngineConfig

from fakes import wait_until

SEED = int(os.environ.get("XLLM_CHAOS_SEED", "0"))
REPLY = "Every sample lands in exactly one stage bucket."


@pytest.fixture(autouse=True)
def _clean_plane():
    FAULTS.configure((), seed=SEED)
    TRACER.configure(enabled=True, mirror=None, sample_rate=1.0)
    TRACER.store.clear()
    RECORDER.clear()
    RECORDER.configure(capacity=64, directory="")
    yield
    FAULTS.clear()
    TRACER.configure(enabled=True, mirror=None, sample_rate=1.0)
    RECORDER.configure(capacity=64, directory="")


def _opts(**kw):
    from xllm_service_tpu.common.config import ServiceOptions

    base = dict(
        host="127.0.0.1", http_port=0, rpc_port=0,
        lease_ttl_s=0.5, sync_interval_s=0.2,
        reconcile_interval_s=0.05,
        heartbeat_silence_to_suspect_s=0.3,
        detect_disconnected_instance_interval_s=0.3,
        health_probe_attempts=1, health_probe_timeout_s=0.2,
        failover_backoff_base_s=0.05, failover_backoff_max_s=0.3,
        rpc_backoff_base_s=0.02, rpc_backoff_max_s=0.1,
        handoff_stall_timeout_s=1.5,
        metrics_fleet_cache_ttl_s=0.0,
        fleet_peer_timeout_s=2.0,
        profile_hz=97.0, profile_window_s=60.0)
    base.update(kw)
    return ServiceOptions(**base)


def _master(store, **kw) -> Master:
    m = Master(_opts(**kw), coord=InMemoryCoordination(store))
    m.start()
    return m


def _engine(store, **cfg_kw) -> FakeEngine:
    cfg_kw.setdefault("delay_s", 0.02)
    cfg = FakeEngineConfig(reply_text=REPLY, chunk_size=4,
                           heartbeat_interval_s=0.1, lease_ttl_s=0.5,
                           **cfg_kw)
    return FakeEngine(InMemoryCoordination(store), cfg).start()


def _base(m: Master) -> str:
    return f"http://127.0.0.1:{m.http_port}"


def _await_fleet(masters, engines) -> None:
    addrs = {m.scheduler.self_addr for m in masters}
    assert wait_until(
        lambda: all(
            all(m.scheduler.instance_mgr.get_instance_meta(e.name)
                is not None for e in engines)
            and set(m.scheduler.ownership.members()) == addrs
            for m in masters), timeout=20)


# ------------------------------------------------------------ sampler units
class TestSampler:
    def test_role_attribution_and_frame_labels(self):
        """A thread named with a THREAD_ROLES prefix aggregates under
        that role; the main thread under 'main'; an unregistered worker
        under its name stem — with real file:qualname frame labels."""
        p = SamplingProfiler()
        p.configure(hz=500, window_s=60)
        stop = threading.Event()

        def _spin_marker():
            while not stop.wait(0.001):
                pass

        threads = [threading.Thread(target=_spin_marker, daemon=True,
                                    name=name)
                   for name in ("engine-loop-0", "fleetworker-7_3")]
        for t in threads:
            t.start()
        p.start()
        try:
            assert wait_until(lambda: p.snapshot()["samples"] > 20,
                              timeout=10)
            snap = p.snapshot()
        finally:
            p.stop()
            stop.set()
            for t in threads:
                t.join(5)
        roles = snap["roles"]
        # engine-loop-* is the registered engine-pump role; the
        # unregistered worker groups under its numbering-stripped stem.
        assert "engine-pump" in roles
        assert "fleetworker" in roles
        assert "main" in roles
        # Stacks carry file:qualname labels from the real frames (the
        # leaf is the Event.wait; the marker function sits above it).
        stacks = " ".join(s["stack"]
                          for s in roles["engine-pump"]["top_stacks"])
        assert "test_profiling.py" in stacks
        assert "_spin_marker" in stacks
        # The sampler never samples itself.
        assert "profiler" not in roles

    def test_bounded_stacks_overflow_bucket(self):
        """Per-role distinct-stack tables cap at max_stacks; the excess
        is charged to a visible overflow bucket, not dropped and not
        unbounded."""
        p = SamplingProfiler()
        p.configure(hz=0, window_s=60, max_stacks=16)
        for i in range(100):
            p._merge([("role", (f"frame-{i}",))], now=time.monotonic())
        snap = p.snapshot(top_n=200)
        role = snap["roles"]["role"]
        assert role["samples"] == 100
        stacks = {s["stack"] for s in role["top_stacks"]}
        assert len(stacks) == 17   # 16 distinct + the overflow bucket
        overflow = next(s for s in role["top_stacks"]
                        if s["stack"] == "(overflow)")
        assert overflow["count"] == 84

    def test_bounded_role_cardinality(self):
        """Adversarial thread naming (one distinct role per sample) must
        not grow the role table past MAX_ROLES + the spill bucket."""
        from xllm_service_tpu.profiling.sampler import MAX_ROLES, _name_stem

        p = SamplingProfiler()
        p.configure(hz=0)
        for i in range(500):
            p._merge([(f"role-{i}", ("f",))], now=time.monotonic())
        with p._lock:
            assert len(p._agg) <= MAX_ROLES + 1
            assert p._agg["(otherrole)"]
        assert p.snapshot(top_n=1000)["samples"] == 500
        # CPython default worker names collapse to the target function.
        assert _name_stem("Thread-1078 (_generate)") == "_generate"
        assert _name_stem("ThreadPoolExecutor-0_3") == "ThreadPoolExecutor"

    def test_depth_bound_keeps_leaf_side(self):
        p = SamplingProfiler()
        p.configure(hz=500, window_s=60, max_depth=4)
        stop = threading.Event()

        def _recurse(n):
            if n:
                return _recurse(n - 1)
            while not stop.wait(0.001):
                pass

        t = threading.Thread(target=lambda: _recurse(40), daemon=True,
                             name="deepworker")
        t.start()
        p.start()
        try:
            assert wait_until(
                lambda: "deepworker" in p.snapshot()["roles"], timeout=10)
            snap = p.snapshot()
        finally:
            p.stop()
            stop.set()
            t.join(5)
        for s in snap["roles"]["deepworker"]["top_stacks"]:
            frames = s["stack"].split(";")
            assert len(frames) <= 4
            # Leaf side kept: the innermost frame is the wait, not the
            # thread bootstrap.
            assert "bootstrap" not in frames[-1]

    def test_window_rotation_keeps_last_complete_window(self):
        p = SamplingProfiler()
        p.configure(hz=0, window_s=5, max_stacks=64)
        t0 = time.monotonic()
        with p._lock:
            p._window_started = t0
        p._merge([("r", ("a",))], now=t0 + 1)
        p._merge([("r", ("b",))], now=t0 + 6)      # rotates
        p._merge([("r", ("c",))], now=t0 + 7)
        ctx_roles = p.snapshot(top_n=10)["roles"]["r"]
        # Snapshot merges prev + live: all three stacks visible.
        assert ctx_roles["samples"] == 3
        with p._lock:
            assert ("a",) in p._prev["r"] and ("b",) in p._prev["r"]
            assert ("c",) in p._agg["r"]
            assert p._prev_ticks == 2

    def test_folded_roundtrip_and_summary(self):
        counts = {("main", "a.py:f", "a.py:g"): 7,
                  ("engine-pump", "b.py:h"): 3}
        p = SamplingProfiler()
        p.configure(hz=0)
        for stack, n in counts.items():
            p._merge([(stack[0], stack[1:])] * n, now=time.monotonic())
        folded = p.folded()
        assert "main;a.py:f;a.py:g 7" in folded
        assert parse_folded(folded) == counts
        summary = summarize_stacks(counts, top_n=5)
        assert summary["samples"] == 10
        assert summary["roles"]["main"]["samples"] == 7
        assert summary["top_frames"][0]["frame"] == "a.py:g"
        assert summary["top_frames"][0]["pct"] == 70.0

    def test_refcounted_stop_is_leak_free(self):
        """Strict `profiler-thread` pair under the runtime verifier:
        start/start/stop/stop leaves zero balance, no violations, and no
        sampler thread alive."""
        was = _lifecycle.debug_enabled()
        _lifecycle.set_debug(True)
        _lifecycle.reset_violations()
        _lifecycle.reset_balances()
        try:
            p = SamplingProfiler()
            p.configure(hz=500)
            p.start()
            p.start()          # second owner: refcount, no second thread
            assert p.running()
            assert sum(1 for t in threading.enumerate()
                       if t.name == "profiler-sampler") == 1
            p.stop()
            assert p.running()   # one owner left
            p.stop()
            assert not p.running()
            assert wait_until(
                lambda: not any(t.name == "profiler-sampler"
                                for t in threading.enumerate()), timeout=5)
            p.stop()             # idempotent: no outstanding start
            vs = _lifecycle.violations() + _lifecycle.strict_imbalances()
            assert not vs, "\n".join(str(v) for v in vs)
        finally:
            _lifecycle.set_debug(was)
            _lifecycle.reset_balances()

    def test_hz_zero_spawns_nothing(self):
        p = SamplingProfiler()
        p.configure(hz=0)
        p.start()
        assert not p.running()
        assert p.snapshot()["enabled"] is False
        assert p.anomaly_context() == {"enabled": False}
        p.stop()

    def test_anomaly_bundle_carries_profile_window(self):
        """While the sampler runs, every flight-recorder bundle's context
        includes a non-empty profile of the last window."""
        rec = FlightRecorder(capacity=8)
        p = SamplingProfiler()
        p.configure(hz=500, window_s=60)
        p.start()
        try:
            # The profiler registers its provider on the GLOBAL recorder;
            # mirror it onto this test-local one.
            rec.add_context_provider("profile", p.anomaly_context)
            assert wait_until(lambda: p.snapshot()["samples"] > 0,
                              timeout=10)
            rec.record("slo_breach", request_id="r-1", trace_id="t-1",
                       detail={"ttft_ms": 999})
            bundle = rec.recent(1)[0]
            prof = bundle["profile"]
            assert prof["enabled"] is True
            assert prof["ticks"] > 0
            assert prof["role_samples"]
            assert prof["top_frames"]
            rec.remove_context_provider("profile", p.anomaly_context)
        finally:
            p.stop()


# ------------------------------------------------------- critical-path units
def _span(point, start, end, span_id, parent="", trace="t1", rid="r1",
          **attrs):
    return {"point": point, "trace_id": trace, "span_id": span_id,
            "parent_span_id": parent, "request_id": rid,
            "instance": "i1", "start_ms": float(start),
            "end_ms": None if end is None else float(end),
            "status": "OK", "attrs": attrs}


class TestCriticalPath:
    def test_stages_sum_exactly_to_ttft_window(self):
        spans = [
            _span("frontend.request", 0, 250, "root", ttft_ms=100.0),
            _span("scheduler.schedule", 5, 20, "sched", parent="root"),
            _span("engine.prefill", 30, 80, "pre", parent="sched"),
        ]
        cp = critical_path(spans)
        assert cp is not None
        s = cp["stages_ms"]
        assert s["admission_wait"] == 5.0
        assert s["schedule"] == 15.0
        assert s["dispatch_wait"] == 10.0
        assert s["prefill"] == 50.0
        assert s["first_delta"] == 20.0
        assert s["handoff"] == 0.0 and s["failover"] == 0.0
        assert abs(sum(s.values()) - cp["ttft_ms"]) < 1e-9
        assert cp["ttft_ms"] == 100.0
        assert cp["relayed"] is False
        assert abs(sum(cp["stage_share"].values()) - 1.0) < 0.01
        assert set(s) == set(CRITICAL_STAGES)

    def test_relayed_failover_decomposition(self):
        spans = [
            # Accepting frontend's relay root; owner hop starts at 10.
            _span("frontend.request", 0, 260, "relay", relay=True),
            _span("frontend.request", 10, 250, "owner", parent="relay",
                  ttft_ms=200.0, failover_attempts=1),
            _span("scheduler.schedule", 15, 25, "sched", parent="owner"),
            _span("engine.prefill", 30, 60, "pre1", parent="sched"),
            _span("scheduler.failover", 70, 80, "fo", parent="owner"),
            _span("engine.prefill", 85, 150, "pre2", parent="fo"),
        ]
        cp = critical_path(spans)
        assert cp is not None
        assert cp["relayed"] is True
        assert cp["failover_attempts"] == 1
        # Window: relay accept (0) -> owner start (10) + ttft (200).
        assert cp["ttft_ms"] == 210.0
        s = cp["stages_ms"]
        assert s["handoff"] == 10.0
        assert s["admission_wait"] == 5.0   # 10 -> 15
        assert s["schedule"] == 10.0
        assert s["prefill"] == 30.0 + 65.0
        assert s["failover"] == 10.0
        assert abs(sum(s.values()) - cp["ttft_ms"]) < 1e-9

    def test_open_span_and_no_ttft(self):
        # Still-open prefill covers to the window end.
        spans = [
            _span("frontend.request", 0, None, "root", ttft_ms=50.0),
            _span("engine.prefill", 10, None, "pre", parent="root"),
        ]
        cp = critical_path(spans)
        assert cp["stages_ms"]["prefill"] == 40.0
        # No TTFT observation anywhere -> no decomposition.
        assert critical_path(
            [_span("frontend.request", 0, 100, "root")]) is None
        assert critical_path([]) is None

    def test_aggregate(self):
        spans = [
            _span("frontend.request", 0, 250, "root", ttft_ms=100.0),
            _span("scheduler.schedule", 5, 20, "sched", parent="root"),
        ]
        agg = aggregate_critical_paths(
            [critical_path(spans), None, critical_path(spans)])
        assert agg["requests"] == 2
        assert agg["ttft_ms"]["mean"] == 100.0
        assert agg["stages"]["schedule"]["mean_ms"] == 15.0
        assert 0 < agg["stages"]["schedule"]["mean_share"] < 1


# ----------------------------------------------------------- SLO exemplars
class TestSloExemplars:
    def test_worst_trace_per_window(self):
        mon = SloMonitor()
        mon.configure(ttft_ms=100, tpot_ms=50, budget=0.01,
                      fast_s=60, slow_s=600)
        now = time.time()
        mon.record_ttft(80, now=now, trace_id="t-ok")
        mon.record_ttft(500, now=now, trace_id="t-bad")
        mon.record_ttft(300, now=now, trace_id="t-mid")
        rep = mon.report(now=now)
        ex = rep["objectives"]["ttft"]["fast"]["exemplar"]
        assert ex["trace_id"] == "t-bad"
        assert ex["value"] == 500
        # Error-rate exemplar: only failures carry a trace.
        mon.record_request(True, now=now, trace_id="t-fine")
        mon.record_request(False, now=now, trace_id="t-err")
        rep = mon.report(now=now)
        assert rep["objectives"]["error_rate"]["fast"]["exemplar"][
            "trace_id"] == "t-err"

    def test_exemplar_ages_out_of_fast_window(self):
        mon = SloMonitor()
        mon.configure(ttft_ms=100, tpot_ms=50, budget=0.01,
                      fast_s=10, slow_s=600)
        now = time.time()
        mon.record_ttft(900, now=now - 60, trace_id="t-old")
        mon.record_ttft(200, now=now, trace_id="t-new")
        rep = mon.report(now=now)
        assert rep["objectives"]["ttft"]["fast"]["exemplar"][
            "trace_id"] == "t-new"
        # ...but the slow window still remembers the worst one.
        assert rep["objectives"]["ttft"]["slow"]["exemplar"][
            "trace_id"] == "t-old"
        # Absent observations -> null exemplar, not an error.
        mon2 = SloMonitor()
        assert mon2.report()["objectives"]["ttft"]["fast"][
            "exemplar"] is None


# ------------------------------------------------------ CPU counter export
class TestCpuCounterExport:
    def test_export_counters_publishes_delta(self):
        attr = CpuAttribution()
        before = HOTPATH_CPU_SECONDS.labels(loop="ingest").value()
        attr.add("ingest", 0.25)
        attr.export_counters()
        attr.export_counters()   # idempotent: no double-count
        mid = HOTPATH_CPU_SECONDS.labels(loop="ingest").value()
        assert abs(mid - before - 0.25) < 1e-9
        attr.add("ingest", 0.5)
        attr.export_counters()
        after = HOTPATH_CPU_SECONDS.labels(loop="ingest").value()
        assert abs(after - before - 0.75) < 1e-9


# -------------------------------------------------------------- fleet drills
class TestFleetProfile:
    pytestmark = pytest.mark.chaos
    def test_fleet_scope_merges_and_survives_dead_agent(self, store):
        """`/admin/profile?scope=fleet`: per-role merged view with peer
        markers; a killed agent degrades to a non-ok marker, never a
        non-200."""
        master = _master(store,
                         heartbeat_silence_to_suspect_s=3.0,
                         detect_disconnected_instance_interval_s=30.0,
                         fleet_peer_timeout_s=1.0)
        engines = [_engine(store), _engine(store)]
        try:
            _await_fleet([master], engines)
            local = requests.get(_base(master) + "/admin/profile",
                                 timeout=5).json()
            assert local["enabled"] is True
            assert wait_until(
                lambda: requests.get(_base(master) + "/admin/profile",
                                     timeout=5).json()["samples"] > 0,
                timeout=15)
            folded = requests.get(_base(master) + "/admin/profile",
                                  params={"format": "folded"}, timeout=5)
            assert folded.headers["Content-Type"].startswith("text/plain")
            assert parse_folded(folded.text)

            engines[0].kill()
            time.sleep(0.2)
            got = requests.get(_base(master) + "/admin/profile",
                               params={"scope": "fleet"}, timeout=10)
            assert got.status_code == 200, got.text
            doc = got.json()
            assert doc["scope"] == "fleet"
            assert doc["samples"] > 0
            assert "main" in doc["roles"]
            statuses = {a: p["status"] for a, p in doc["peers"].items()}
            assert statuses[engines[0].name] not in ("ok",), statuses
            assert "ok" in statuses.values()   # a live peer answered
            # Bad query param -> 400, not a crash.
            bad = requests.get(_base(master) + "/admin/profile",
                               params={"scope": "fleet", "top": "x"},
                               timeout=5)
            assert bad.status_code == 400
        finally:
            for e in engines:
                e.stop()
            master.stop()

    def test_relayed_failover_critical_path_sums_to_ttft(self, store):
        """Acceptance drill: a relayed request that fails over mid-stream
        gets a fleet critical-path decomposition whose stage waits sum to
        within 10% of the measured end-to-end TTFT."""
        m1 = _master(store)
        m2 = _master(store)
        engines = [_engine(store), _engine(store)]
        try:
            _await_fleet([m1, m2], engines)
            okey = next(
                f"prof-affinity-{i}" for i in range(10000)
                if m1.scheduler.ownership.owner_of(f"prof-affinity-{i}")
                == m2.scheduler.self_addr)
            FAULTS.configure([dict(point="engine.token", action="crash",
                                   after=4, max_fires=1)], seed=SEED)
            body = {"model": "fake-model", "prompt": "fleet",
                    "stream": True, "max_tokens": 1000,
                    "ownership_key": okey}
            r = requests.post(_base(m1) + "/v1/completions", json=body,
                              stream=True, timeout=90)
            assert r.status_code == 200, r.text
            text = ""
            for line in r.iter_lines():
                if not line.startswith(b"data: "):
                    continue
                data = line[len(b"data: "):]
                if data == b"[DONE]":
                    break
                for c in json.loads(data).get("choices", ()):
                    text += c.get("text", "")
            assert text == REPLY

            def fleet_cp():
                rec = requests.get(_base(m1) + "/admin/trace/recent",
                                   timeout=5).json()
                sid = next((t["request_id"] for t in rec["traces"]
                            if t["request_id"].startswith("completion-")),
                           None)
                if sid is None:
                    return None
                doc = requests.get(
                    _base(m1) + "/admin/trace",
                    params={"scope": "fleet", "request_id": sid},
                    timeout=15).json()
                pts = {s["point"] for s in doc.get("spans", ())}
                if "scheduler.failover" not in pts:
                    return None
                return doc.get("critical_path") and doc

            assert wait_until(lambda: fleet_cp() is not None, timeout=15), \
                "no fleet critical path for the drill request"
            doc = fleet_cp()
            cp = doc["critical_path"]
            assert cp["relayed"] is True
            assert cp["failover_attempts"] >= 1
            assert cp["stages_ms"]["handoff"] > 0
            assert cp["stages_ms"]["prefill"] > 0
            # Measured end-to-end TTFT, recomputed from the raw merged
            # spans: accepting-frontend start -> owner's first token.
            spans = doc["spans"]
            ids = {s["span_id"] for s in spans}
            fronts = [s for s in spans
                      if s["point"] == "frontend.request"]
            root = min((s for s in fronts
                        if s.get("parent_span_id") not in ids),
                       key=lambda s: s["start_ms"])
            owner = next(s for s in fronts
                         if (s.get("attrs") or {}).get("ttft_ms")
                         is not None)
            measured = (owner["start_ms"] + owner["attrs"]["ttft_ms"]
                        - root["start_ms"])
            total = sum(cp["stages_ms"].values())
            assert abs(total - measured) <= 0.1 * measured, \
                (total, measured, cp["stages_ms"])
            # The per-trace view carries the same decomposition, and the
            # hotpath aggregate has absorbed it.
            hot = requests.get(_base(m2) + "/admin/hotpath",
                               timeout=5).json()
            assert hot["critical_path"]["requests"] >= 1
            assert set(hot["critical_path"]["stages"]) == \
                set(CRITICAL_STAGES)
        finally:
            for e in engines:
                e.stop()
            m1.stop()
            m2.stop()

    def test_breach_bundle_includes_profile_window(self, store):
        """SLO-breach flight-recorder bundles captured on a live master
        carry a non-empty profile window (the anomaly-path acceptance
        criterion)."""
        master = _master(store, slo_ttft_ms=0.001)
        engine = _engine(store)
        try:
            _await_fleet([master], [engine])
            assert wait_until(
                lambda: PROFILER.snapshot()["samples"] > 0, timeout=15)
            r = requests.post(_base(master) + "/v1/completions", json={
                "model": "fake-model", "prompt": "fleet",
                "max_tokens": 8}, timeout=30)
            assert r.status_code == 200, r.text

            def breach_bundle():
                got = requests.get(
                    _base(master) + "/admin/flightrecorder/recent",
                    params={"kind": "slo_breach"}, timeout=5).json()
                return next(iter(got.get("records", ())), None)

            assert wait_until(lambda: breach_bundle() is not None,
                              timeout=15), "no slo_breach bundle captured"
            bundle = breach_bundle()
            prof = bundle["profile"]
            assert prof["enabled"] is True
            assert prof["ticks"] > 0
            assert prof["role_samples"]
        finally:
            engine.stop()
            master.stop()
