"""MoE expert-parallel PD pair e2e (SURVEY §7.3 hard part #5: the
interaction between expert-sharded decode meshes and the PD link
topology): a prefill+decode pair of expert-sharded DeepSeek-MoE engines
must disaggregate correctly — device-path KV handoff between identical
EP meshes — with output equal to a MIX instance."""

import jax.numpy as jnp
import pytest
import requests

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.types import InstanceType
from xllm_service_tpu.coordination.memory import InMemoryCoordination, MemoryStore
from xllm_service_tpu.engine.agent import AgentConfig, EngineAgent
from xllm_service_tpu.engine.config import EngineConfig
from xllm_service_tpu.master import Master
from xllm_service_tpu.models.deepseek_moe import tiny_moe_config
from xllm_service_tpu.parallel.mesh import MeshConfig

from fakes import wait_until

BODY = {"model": "tiny-moe", "prompt": "route me through the experts",
        "max_tokens": 6, "temperature": 0, "ignore_eos": True}


def _cfg() -> EngineConfig:
    return EngineConfig(
        model_id="tiny-moe", model_family="deepseek_moe",
        model=tiny_moe_config(dtype=jnp.float32, max_context_len=256),
        mesh=MeshConfig(expert=2, model=2),
        num_pages=64, page_size=16, hash_block_size=32,
        max_batch_size=4, max_seq_len=256, prefill_buckets=(32, 64, 256))


def _cluster(itypes):
    store = MemoryStore(expiry_tick_s=0.05)
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=1.0, sync_interval_s=0.3,
                          reconcile_interval_s=0.1)
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()
    agents = [EngineAgent(
        _cfg(),
        AgentConfig(host="127.0.0.1", model_id="tiny-moe",
                    instance_type=t, heartbeat_interval_s=0.3,
                    lease_ttl_s=1.0),
        coord=InMemoryCoordination(store)).start() for t in itypes]
    assert wait_until(
        lambda: all(master.scheduler.instance_mgr.get_instance_meta(a.name)
                    is not None for a in agents), timeout=10)
    return master, agents, store


def _run(master):
    r = requests.post(f"http://127.0.0.1:{master.http_port}/v1/completions",
                      json=BODY, timeout=180)
    assert r.status_code == 200, r.text
    return r.json()["choices"][0]["text"]


class TestMoeExpertParallelPD:
    def test_ep_pd_matches_mix(self):
        m1, a1, s1 = _cluster([InstanceType.MIX])
        try:
            assert a1[0].engine.mesh.shape["expert"] == 2
            want = _run(m1)
        finally:
            for a in a1:
                a.stop()
            m1.stop()
            s1.close()

        m2, a2, s2 = _cluster([InstanceType.PREFILL, InstanceType.DECODE])
        try:
            prefill, decode = a2
            got = _run(m2)
            assert prefill.kv_device_sent + prefill.kv_host_sent == 1
        finally:
            for a in a2:
                a.stop()
            m2.stop()
            s2.close()
        assert got == want
