"""Sarathi mixed decode+chunk engine path (VERDICT r4 next #3): while a
long prompt chunk-prefills, running decodes ride the SAME device program
(shared GEMMs). Output must be token-exact vs the plain interleaved
path, the ride must actually engage, and XLLM_SARATHI=0 must disable."""

import jax.numpy as jnp

from xllm_service_tpu.common.request import SamplingParams
from xllm_service_tpu.engine.config import EngineConfig
from xllm_service_tpu.engine.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.models.base import tiny_config

from test_engine import Collector, naive_greedy


def make_engine(chunk=32, **kw):
    return InferenceEngine(EngineConfig(
        model=tiny_config(dtype=jnp.float32, max_context_len=512),
        num_pages=96, page_size=16, hash_block_size=32,
        max_batch_size=4, max_seq_len=512,
        prefill_buckets=(32, 64, 512), prefill_chunk_tokens=chunk, **kw))


def _drive(engine):
    """Short decode running, then a long prompt chunk-prefills: the
    chunks should ride decode steps. Returns (short, long, rode)."""
    short, long_ = Collector(), Collector()
    engine.submit(EngineRequest(
        "short", token_ids=list(range(11, 21)),
        sampling=SamplingParams(max_tokens=40, temperature=0.0,
                                ignore_eos=True), on_output=short))
    engine.step()                      # short admitted + decoding
    engine.submit(EngineRequest(
        "long", token_ids=list(range(5, 245)),   # 240 tokens
        sampling=SamplingParams(max_tokens=4, temperature=0.0,
                                ignore_eos=True), on_output=long_))
    rode = 0
    for _ in range(300):
        engine.step()
        rode += bool(engine._rode_chunk)
        if short.done.is_set() and long_.done.is_set():
            break
    engine.stop()
    assert short.done.is_set() and long_.done.is_set()
    return short, long_, rode


def test_ride_engages_and_tokens_exact():
    plain = make_engine(chunk=0)
    want_short = naive_greedy(plain, list(range(11, 21)), 40)
    want_long = naive_greedy(plain, list(range(5, 245)), 4)

    engine = make_engine(chunk=32)
    short, long_, rode = _drive(engine)
    assert rode >= 2, "mixed decode+chunk path never engaged"
    assert short.tokens == want_short
    assert long_.tokens == want_long


def test_kill_switch_disables_ride(monkeypatch):
    monkeypatch.setenv("XLLM_SARATHI", "0")
    engine = make_engine(chunk=32)
    short, long_, rode = _drive(engine)
    assert rode == 0
    assert len(short.tokens) == 40 and len(long_.tokens) == 4


def test_ride_respects_final_chunk_boundary():
    """The final <= chunk tokens must go through the normal install
    program (it samples the first token): _ride_chunk_args consumes at
    most remaining - C, and returns None once only the final chunk is
    left. Exercised directly so a regression (e.g. dropping the - C
    from rideable) fails here, not just via downstream parity."""
    engine = make_engine(chunk=32)
    col = Collector()
    engine.submit(EngineRequest(
        "warm", token_ids=list(range(3, 13)),
        sampling=SamplingParams(max_tokens=60, temperature=0.0,
                                ignore_eos=True), on_output=col))
    engine.step()
    long_ = Collector()
    engine.submit(EngineRequest(
        "long", token_ids=list(range(7, 107)),   # 100 tokens
        sampling=SamplingParams(max_tokens=2, temperature=0.0,
                                ignore_eos=True), on_output=long_))
    engine._admit()
    assert engine._prefillings
    st = engine._prefillings[0]
    C = engine.cfg.prefill_chunk_tokens
    seen_rides = 0
    while True:
        before = st["written"]
        ride = engine._ride_chunk_args(engine.cfg.decode_horizon)
        if ride is None:
            break
        seen_rides += 1
        # Each ride consumes at most one chunk and NEVER crosses into
        # the final chunk's territory.
        assert st["written"] - before <= C
        assert len(st["prompt"]) - st["written"] >= C
    assert seen_rides >= 1
    # Exactly the final chunk remains un-ridden.
    assert 0 < len(st["prompt"]) - st["written"] <= C
    # (Host bookkeeping only — the ride arrays were never dispatched, so
    # no generation assertions here; token parity with riding live is
    # test_ride_engages_and_tokens_exact's job.)
    engine.stop()


def test_pressure_rides_consume_four_chunk_spans():
    """With arrivals waiting, a truly-long prefill rides 4C per decode
    call (one fused step) instead of pacing one chunk at a time —
    token-exact either way."""
    plain = make_engine(chunk=0)
    want = [naive_greedy(plain, list(range(7 + i, 207 + i)), 3)
            for i in range(3)]

    engine = make_engine(chunk=16)   # 200-token prompts >> 4*16
    warm = Collector()
    engine.submit(EngineRequest(
        "warm", token_ids=list(range(2, 12)),
        sampling=SamplingParams(max_tokens=60, temperature=0.0,
                                ignore_eos=True), on_output=warm))
    engine.step()
    cols = [Collector() for _ in range(3)]
    for i, c in enumerate(cols):
        engine.submit(EngineRequest(
            f"L{i}", token_ids=list(range(7 + i, 207 + i)),
            sampling=SamplingParams(max_tokens=3, temperature=0.0,
                                    ignore_eos=True), on_output=c))
    big_rides = 0
    for _ in range(400):
        if engine._prefillings:
            st = engine._prefillings[0]
            before = st["written"]
            engine.step()
            if engine._rode_chunk and st["written"] - before > \
                    engine.cfg.prefill_chunk_tokens:
                big_rides += 1
        else:
            engine.step()
        if all(c.done.is_set() for c in cols):
            break
    engine.stop()
    assert big_rides >= 1, "pressure span never engaged"
    for i, c in enumerate(cols):
        assert c.tokens == want[i], i


def test_gemma2_rides_with_softcap():
    """The mixed program composes with the gemma-2 attention extras
    (score softcap, sliding window, query scale as static params) —
    token-exact vs the unchunked engine."""
    from xllm_service_tpu.models.gemma import gemma2_tiny_config

    def eng(chunk):
        return InferenceEngine(EngineConfig(
            model=gemma2_tiny_config(dtype=jnp.float32,
                                     max_context_len=512),
            model_family="gemma",
            num_pages=96, page_size=16, hash_block_size=32,
            max_batch_size=4, max_seq_len=512,
            prefill_buckets=(32, 64, 512), prefill_chunk_tokens=chunk))

    plain = eng(0)
    want_short = naive_greedy(plain, list(range(11, 21)), 30)
    want_long = naive_greedy(plain, list(range(5, 205)), 4)

    engine = eng(32)
    short, long_ = Collector(), Collector()
    engine.submit(EngineRequest(
        "short", token_ids=list(range(11, 21)),
        sampling=SamplingParams(max_tokens=30, temperature=0.0,
                                ignore_eos=True), on_output=short))
    engine.step()
    engine.submit(EngineRequest(
        "long", token_ids=list(range(5, 205)),
        sampling=SamplingParams(max_tokens=4, temperature=0.0,
                                ignore_eos=True), on_output=long_))
    rode = 0
    for _ in range(300):
        engine.step()
        rode += bool(engine._rode_chunk)
        if short.done.is_set() and long_.done.is_set():
            break
    engine.stop()
    assert rode >= 1, "gemma-2 never took the mixed path"
    assert short.tokens == want_short
    assert long_.tokens == want_long


def test_n_fanout_and_cancel_under_ride():
    """Cancellation of a riding prefill returns its pages/slot."""
    engine = make_engine(chunk=32)
    col = Collector()
    engine.submit(EngineRequest(
        "k", token_ids=list(range(4, 14)),
        sampling=SamplingParams(max_tokens=50, temperature=0.0,
                                ignore_eos=True), on_output=col))
    engine.step()
    lcol = Collector()
    engine.submit(EngineRequest(
        "lx", token_ids=list(range(9, 250)),
        sampling=SamplingParams(max_tokens=3, temperature=0.0,
                                ignore_eos=True), on_output=lcol))
    for _ in range(4):
        engine.step()
    assert engine._prefillings
    engine.cancel("lx")
    for _ in range(200):
        engine.step()
        if col.done.is_set():
            break
    engine.stop()
    assert not engine._prefillings
    assert lcol.done.is_set() and not lcol.outputs[-1].status.ok()
    assert len(col.tokens) == 50
    assert engine.page_mgr.num_free == engine.cfg.num_pages - 1
    assert len(engine._free_slots) == engine.cfg.max_batch_size
