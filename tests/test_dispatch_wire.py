"""Dispatch-wire unit tests (rpc/wire.py + channel negotiation).

The master → engine hot wire is msgpack when the target advertises it
(`InstanceMetaInfo.wire_formats`), JSON otherwise, with a 415-triggered
demotion for engines behind a stale registration. Determinism of the
binary encoding is load-bearing: the failover layer replays a retained
payload, and the chaos drill asserts byte-equivalence with the first
dispatch.
"""

import json

import pytest

from xllm_service_tpu.devtools import ownership
from xllm_service_tpu.rpc import wire
from xllm_service_tpu.rpc.channel import EngineChannel


PAYLOAD = {
    "model": "m",
    "service_request_id": "sid-1",
    "token_ids": list(range(2048)),
    "sampling": {"max_tokens": 16, "temperature": 0.0},
    "routing": {"prefill_name": "a:1", "decode_name": "b:2",
                "encode_name": ""},
}


class TestWireCodec:
    def test_msgpack_roundtrip(self):
        data, ctype = wire.encode_dispatch(PAYLOAD, wire.WIRE_MSGPACK)
        assert ctype == wire.MSGPACK_CONTENT_TYPE
        assert wire.decode_body(ctype, data) == PAYLOAD

    def test_json_roundtrip_compact(self):
        data, ctype = wire.encode_dispatch(PAYLOAD, wire.WIRE_JSON)
        assert ctype == wire.JSON_CONTENT_TYPE
        assert b": " not in data.split(b'"token_ids"')[0]  # compact seps
        assert wire.decode_body(ctype, data) == PAYLOAD
        # Default format is JSON (legacy engines).
        assert wire.encode_dispatch(PAYLOAD)[1] == wire.JSON_CONTENT_TYPE

    def test_msgpack_encoding_deterministic(self):
        a = wire.pack_dispatch(PAYLOAD)
        b = wire.pack_dispatch(json.loads(json.dumps(PAYLOAD)))
        c = wire.pack_dispatch(wire.unpack_dispatch(a))
        assert a == b == c

    def test_malformed_bodies_raise_valueerror(self):
        with pytest.raises(ValueError):
            wire.decode_body(wire.MSGPACK_CONTENT_TYPE, b"\xc1broken")
        with pytest.raises(ValueError):
            wire.decode_body(wire.JSON_CONTENT_TYPE, b"{nope")

    def test_negotiate(self):
        assert wire.negotiate(["msgpack", "json"]) == wire.WIRE_MSGPACK
        assert wire.negotiate(["json"]) == wire.WIRE_JSON
        assert wire.negotiate([]) == wire.WIRE_JSON
        assert wire.negotiate(None) == wire.WIRE_JSON
        assert wire.negotiate(123) == wire.WIRE_JSON   # garbage metadata


class _Resp:
    def __init__(self, status_code, body=b"{}"):
        self.status_code = status_code
        self.text = body.decode()

    def json(self):
        return json.loads(self.text)


class _StubSession:
    """Records (content-type, body) per POST; scripted status codes."""

    def __init__(self, statuses):
        self.statuses = list(statuses)
        self.posts = []

    def post(self, url, data=None, headers=None, timeout=None):
        self.posts.append(((headers or {}).get("Content-Type"), data))
        return _Resp(self.statuses.pop(0),
                     b'{"ok": true}' if self.statuses or True else b"")

    def close(self):
        pass


class TestChannelNegotiation:
    def test_forward_demotes_on_415_and_resends_json(self):
        ch = EngineChannel("e:1", retries=1)
        ch._session = _StubSession([415, 200])
        with ownership.escape("test knob: simulate a negotiated "
                              "msgpack channel"):
            ch.wire_format = wire.WIRE_MSGPACK
        ok, resp = ch.forward("/v1/completions", PAYLOAD)
        assert ok
        assert ch.wire_format == wire.WIRE_JSON
        ctypes = [c for c, _ in ch._session.posts]
        assert ctypes == [wire.MSGPACK_CONTENT_TYPE,
                          wire.JSON_CONTENT_TYPE]
        # Demotion sticks: the next forward goes straight to JSON.
        ch._session.statuses = [200]
        ch.forward("/v1/completions", PAYLOAD)
        assert ch._session.posts[-1][0] == wire.JSON_CONTENT_TYPE

    def test_forward_msgpack_when_negotiated(self):
        ch = EngineChannel("e:1", retries=1)
        ch._session = _StubSession([200])
        with ownership.escape("test knob: simulate a negotiated "
                              "msgpack channel"):
            ch.wire_format = wire.WIRE_MSGPACK
        ok, _ = ch.forward("/v1/completions", PAYLOAD)
        assert ok
        ctype, data = ch._session.posts[0]
        assert ctype == wire.MSGPACK_CONTENT_TYPE
        assert wire.unpack_dispatch(data) == PAYLOAD

    def test_non_415_failure_does_not_demote(self):
        ch = EngineChannel("e:1", retries=1)
        ch._session = _StubSession([503])
        with ownership.escape("test knob: simulate a negotiated "
                              "msgpack channel"):
            ch.wire_format = wire.WIRE_MSGPACK
        ok, _ = ch.forward("/v1/completions", PAYLOAD)
        assert not ok
        assert ch.wire_format == wire.WIRE_MSGPACK
        assert len(ch._session.posts) == 1   # single-shot, no blind retry
