"""v5e-64 north-star topology proof (VERDICT r4 next #4): the worker
runs in a fresh process with 64 virtual CPU devices (this suite's own
platform is pinned to 8, so a subprocess is the only way to get there)
and must print every section's OK line. Any mesh-math assumption that
breaks past 8 devices — head/expert/page divisibility at axis size 8,
ring step counts, disjoint-group PD placement — fails this test."""

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

SECTIONS = [
    "OK northstar_dryrun",
    "OK page_shard_divisibility_guard",
    "OK cp8_engine_decode",
    "OK pd_disjoint_device_groups",
]


def test_northstar_topology_worker():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)            # worker pins its own 64
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, str(REPO / "tests" / "northstar_worker.py")],
        capture_output=True, text=True, timeout=900, env=env,
        cwd=str(REPO))
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-3000:])
    for line in SECTIONS:
        assert line in r.stdout, (line, r.stdout[-2000:])
