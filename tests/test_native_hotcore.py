"""Differential property tests for the native hot-path core (ISSUE 19).

libhotcore.so reimplements four frame families the profiler blamed for
most of the master's route/stream CPU: msgpack LOADFRAME/telemetry
encode+decode (rpc/wire.py), SSE delta-frame assembly
(http_service/service.py), the blake2b-8 rendezvous walk
(multimaster/ownership.py), and the byte tokenizer. The contract is
byte-for-byte parity: native output must be indistinguishable from the
pure-Python libraries it shadows, and anything it cannot serve
bit-exactly must MISS so the call site's pure path runs.

Two layers of drills:

- RAW core parity (``CORE = native.load_core(force=True)``): randomized
  inputs, native vs msgpack/json/hashlib reference, byte equality.
  Skipped when the .so is absent (no C toolchain in the container).
- CALL-SITE equivalence via the ``XLLM_NATIVE`` kill switch +
  ``native.reload()``: the public wire/ownership/tokenizer functions
  produce identical outputs with the switch on and off. These run
  everywhere — with no .so both legs are the pure path and the drill
  degrades to a determinism check, which is exactly the no-toolchain
  acceptance mode.

Randomness is seeded per test: a failure reproduces.
"""

import base64
import json
import math
import os
import random
import string

import msgpack
import pytest

from xllm_service_tpu.common import native
from xllm_service_tpu.coordination.memory import InMemoryCoordination
from xllm_service_tpu.multimaster import ownership as own
from xllm_service_tpu.multimaster.ownership import OwnershipRouter
from xllm_service_tpu.rpc import wire
from xllm_service_tpu.tokenizer.simple import SimpleTokenizer

CORE = native.load_core(force=True)

needs_so = pytest.mark.skipif(
    CORE is None, reason="libhotcore.so not built (no C toolchain)")

_COMPACT = (",", ":")


# ------------------------------------------------------------- generators
#
# Weighted toward the wire's real shapes (str-keyed maps, int/str/float
# leaves) but salted with every edge the C code special-cases: int64/u64
# bounds, subnormal/huge floats, NaN/±Inf, control chars, non-ASCII,
# surrogate-ADJACENT code points (U+D7FF / U+E000), astral planes,
# empty containers, bytes (wire only — JSON rejects them either way).

_EDGE_INTS = (0, -1, 1, 127, 128, -32, -33, 255, 256, 65535, 65536,
              2**31 - 1, 2**31, 2**32 - 1, 2**32, 2**63 - 1, -2**63,
              2**64 - 1)
_EDGE_FLOATS = (0.0, -0.0, 1.5, -1.5, 1e308, 1e-310, 5e-324,
                math.pi, 1 / 3, 123456789.123456789)
_EDGE_STRS = ("", "é", "héllo wörld", "日本語テキスト", "🦖🚀",
              "é́", "퟿",   # surrogate-adjacent
              "line\nbreak\ttab\rret", "\x00\x01\x1f\x7f",
              'quote" back\\slash', "a" * 300)


def rand_str(rng: random.Random) -> str:
    if rng.random() < 0.5:
        return rng.choice(_EDGE_STRS)
    n = rng.randrange(0, 40)
    pool = (string.ascii_letters + string.digits + "éüß日本🎉\n\t\"\\"
            + "퟿\x1f")
    return "".join(rng.choice(pool) for _ in range(n))


def rand_scalar(rng: random.Random, for_json: bool):
    r = rng.random()
    if r < 0.25:
        return rng.choice(_EDGE_INTS) if rng.random() < 0.5 else \
            rng.randrange(-10**12, 10**12)
    if r < 0.45:
        return rng.choice(_EDGE_FLOATS) if rng.random() < 0.5 else \
            rng.random() * rng.choice((1.0, 1e6, 1e-6, 1e300))
    if r < 0.75:
        return rand_str(rng)
    if r < 0.85:
        return rng.choice((True, False, None))
    if not for_json and rng.random() < 0.5:
        return bytes(rng.randrange(256) for _ in range(rng.randrange(20)))
    return rng.randrange(100)


def rand_obj(rng: random.Random, depth: int = 0, for_json: bool = False):
    if depth >= 4 or rng.random() < 0.4 + depth * 0.2:
        return rand_scalar(rng, for_json)
    if rng.random() < 0.5:
        return [rand_obj(rng, depth + 1, for_json)
                for _ in range(rng.randrange(6))]
    return {rand_str(rng): rand_obj(rng, depth + 1, for_json)
            for _ in range(rng.randrange(6))}


def rand_load_frame(rng: random.Random) -> dict:
    """Realistic LOADFRAME body (encode_load_frame's shape)."""
    instances = {}
    for _ in range(rng.randrange(8)):
        name = f"eng-{rng.randrange(1000)}:{rng.randrange(65536)}"
        instances[name] = {
            "l": {"waiting": rng.randrange(64), "running": rng.randrange(8),
                  "kv_usage": rng.random()},
            "y": {"ttft_ms": rng.random() * 500, "tpot_ms": rng.random() * 40},
            "hb": rng.randrange(2**41), "up": rng.randrange(2**41),
            "st": rng.choice(("READY", "DRAINING", "DEAD")),
        }
    gone = {f"eng-{rng.randrange(1000)}": rng.choice(("lease", "drain"))
            for _ in range(rng.randrange(3))}
    return {"i": instances, "g": gone, "s": rng.randrange(2**31),
            "ms": rng.randrange(2**41)}


def rand_telemetry_batch(rng: random.Random) -> list:
    frames = []
    for _ in range(1 + rng.randrange(5)):
        if rng.random() < 0.5:
            frames.append({"t": "hb", "d": rand_load_frame(rng)})
        else:
            frames.append({"t": "gens",
                           "dest": f"10.0.0.{rng.randrange(256)}:9000",
                           "d": {"gens": [rand_obj(rng, 2)
                                          for _ in range(rng.randrange(4))]}})
    return frames


def rand_sse_delta(rng: random.Random) -> dict:
    """OpenAI-style streaming delta, non-ASCII-heavy text."""
    return {
        "id": f"completion-{rng.randrange(10**9)}",
        "object": "text_completion",
        "created": rng.randrange(2**31),
        "model": "fake-model",
        "choices": [{"index": 0, "text": rand_str(rng),
                     "finish_reason": rng.choice((None, "stop", "length"))}],
        "usage": None if rng.random() < 0.5 else
        {"prompt_tokens": rng.randrange(4096),
         "completion_tokens": rng.randrange(4096)},
    }


def pure_sse_data(obj) -> bytes:
    return (b"data: " + json.dumps(obj, ensure_ascii=False,
                                   separators=_COMPACT).encode() + b"\n\n")


def pure_sse_event(name: str, obj) -> bytes:
    return (f"event: {name}\n".encode() + pure_sse_data(obj))


# ---------------------------------------------------------- raw core parity
@needs_so
class TestCoreMsgpackParity:
    def test_randomized_pack_unpack(self):
        rng = random.Random(0x19A)
        for _ in range(300):
            obj = rand_obj(rng)
            ref = msgpack.packb(obj, use_bin_type=True)
            assert CORE.packb(obj) == ref
            back = CORE.unpackb(ref)
            # NaN != NaN: compare re-encodings, not objects.
            assert msgpack.packb(back, use_bin_type=True) == ref
            assert msgpack.packb(msgpack.unpackb(ref, raw=False),
                                 use_bin_type=True) == ref

    def test_randomized_load_frames(self):
        rng = random.Random(0x19B)
        for _ in range(100):
            frame = rand_load_frame(rng)
            ref = msgpack.packb(frame, use_bin_type=True)
            assert CORE.packb(frame) == ref
            assert CORE.unpackb(ref) == frame
            b64 = base64.b64encode(ref).decode("ascii")
            assert CORE.pack_b64(frame) == b64
            assert CORE.unpack_b64(b64) == frame
            assert CORE.unpack_b64(b64.encode("ascii")) == frame

    def test_randomized_telemetry_batches(self):
        rng = random.Random(0x19C)
        for _ in range(60):
            batch = {"frames": rand_telemetry_batch(rng)}
            ref = msgpack.packb(batch, use_bin_type=True)
            assert CORE.packb(batch) == ref
            assert msgpack.packb(CORE.unpackb(ref),
                                 use_bin_type=True) == ref

    def test_int_boundaries_exact_format(self):
        for v in _EDGE_INTS:
            for sign in (v, -v if v <= 2**63 else v):
                if -2**63 <= sign <= 2**64 - 1:
                    assert CORE.packb(sign) == msgpack.packb(sign)

    def test_unsupported_inputs_raise(self):
        class Odd:
            pass
        for bad in (Odd(), {1: "non-str-key-is-fine-for-msgpack"},
                    2**64, -2**63 - 1):
            if isinstance(bad, dict):
                # msgpack allows int keys; native must agree, not refuse.
                assert CORE.packb(bad) == msgpack.packb(
                    bad, use_bin_type=True)
                continue
            with pytest.raises(Exception):
                CORE.packb(bad)

    def test_decode_rejects_what_msgpack_rejects(self):
        for raw in (b"", b"\xc1", b"\x81\xa1a",       # truncated / reserved
                    msgpack.packb(1) + b"tail"):       # trailing bytes
            with pytest.raises(Exception):
                CORE.unpackb(raw)

    def test_ext_types_refused_not_corrupted(self):
        raw = msgpack.packb(msgpack.ExtType(4, b"x"))
        with pytest.raises(Exception):
            CORE.unpackb(raw)

    def test_non_canonical_base64_refused(self):
        frame = {"i": {}, "g": {}, "s": 1, "ms": 2}
        good = CORE.pack_b64(frame)
        # Whitespace / padding games decode fine in Python's lax
        # b64decode; native refuses -> call sites fall back, results agree.
        with pytest.raises(Exception):
            CORE.unpack_b64(good + "\n")


@needs_so
class TestCoreSseParity:
    def test_randomized_deltas(self):
        rng = random.Random(0x19D)
        for _ in range(300):
            delta = rand_sse_delta(rng)
            assert CORE.sse_data_frame(delta) == pure_sse_data(delta)

    def test_randomized_json_objects(self):
        rng = random.Random(0x19E)
        for _ in range(300):
            obj = rand_obj(rng, for_json=True)
            assert CORE.sse_data_frame(obj) == pure_sse_data(obj)

    def test_event_frames(self):
        rng = random.Random(0x19F)
        for _ in range(100):
            obj = rand_obj(rng, 2, for_json=True)
            name = rng.choice(("telemetry", "usage", "x-keepalive"))
            assert CORE.sse_event_frame(name, obj) == pure_sse_event(
                name, obj)

    def test_float_repr_parity(self):
        for v in _EDGE_FLOATS + (math.nan, math.inf, -math.inf):
            got = CORE.sse_data_frame({"v": v})
            want = pure_sse_data({"v": v})
            assert got == want, repr(v)

    def test_surrogate_adjacent_ok_lone_surrogate_refused(self):
        ok = {"text": "퟿ and  bracket the surrogate block"}
        assert CORE.sse_data_frame(ok) == pure_sse_data(ok)
        with pytest.raises(Exception):
            CORE.sse_data_frame({"text": "lone \ud800 surrogate"})
        # The wrapper turns that refusal into MISS; the pure path then
        # raises the canonical UnicodeEncodeError — native never emits
        # bytes Python wouldn't.
        if native.available("sse"):
            assert native.sse_data_frame(
                {"text": "\udc00"}) is native.MISS


@needs_so
class TestCoreRendezvousParity:
    @staticmethod
    def _pure(members, key):
        best, best_score = "", -1
        for m in members:
            s = own._rendezvous_score(m, key)
            if s > best_score:
                best, best_score = m, s
        return best

    def test_randomized_draws(self):
        rng = random.Random(0x1A0)
        for _ in range(200):
            members = tuple(sorted(
                {f"10.{rng.randrange(256)}.{rng.randrange(256)}."
                 f"{rng.randrange(256)}:{rng.randrange(65536)}"
                 for _ in range(rng.randrange(1, 12))}))
            key = rand_str(rng) + str(rng.randrange(10**9))
            assert CORE.rendezvous(members, key) == self._pure(members, key)
            assert CORE.rendezvous(list(members), key) == \
                self._pure(members, key)

    def test_empty_and_single(self):
        assert CORE.rendezvous((), "k") == ""
        assert CORE.rendezvous(("only:1",), "k") == "only:1"

    def test_tie_breaks_first_strict_max(self):
        # Duplicate members score identically; first wins in both paths.
        members = ("a:1", "a:1", "b:2")
        assert CORE.rendezvous(members, "k") == self._pure(members, "k")


@needs_so
class TestCoreTokenizerParity:
    def test_randomized_text(self):
        rng = random.Random(0x1A1)
        for _ in range(300):
            text = rand_str(rng)
            assert CORE.tok_encode(text) == \
                [b + 256 for b in text.encode("utf-8")]

    def test_lone_surrogate_refused(self):
        with pytest.raises(Exception):
            CORE.tok_encode("bad \ud800")


# ------------------------------------------- call-site switch equivalence
@pytest.fixture
def native_off():
    """Force XLLM_NATIVE=0 + reload for one test; restore after."""
    old = os.environ.get("XLLM_NATIVE")
    os.environ["XLLM_NATIVE"] = "0"
    native.reload()
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("XLLM_NATIVE", None)
        else:
            os.environ["XLLM_NATIVE"] = old
        native.reload()


def _both_paths(fn):
    """Run ``fn()`` with the native loader in its default state and with
    the kill switch forced off; return (default_leg, off_leg)."""
    default_leg = fn()
    old = os.environ.get("XLLM_NATIVE")
    os.environ["XLLM_NATIVE"] = "0"
    native.reload()
    try:
        off_leg = fn()
    finally:
        if old is None:
            os.environ.pop("XLLM_NATIVE", None)
        else:
            os.environ["XLLM_NATIVE"] = old
        native.reload()
    return default_leg, off_leg


class TestKillSwitch:
    def test_switch_off_disables_everything(self, native_off):
        st = native.status()
        assert st["enabled"] is False
        assert st["loaded"] is False
        assert not any(st["components"].values())
        assert native.packb({"a": 1}) is native.MISS
        assert native.sse_data_frame({}) is native.MISS
        assert native.rendezvous(("a",), "k") is native.MISS
        assert native.tok_encode("x") is native.MISS

    def test_status_shape(self):
        st = native.status()
        assert set(st) == {"enabled", "loaded", "so", "components"}
        assert set(st["components"]) == set(native.COMPONENTS)


class TestCallSiteEquivalence:
    """Public wire/ownership/tokenizer outputs are identical with the
    switch on and off (pure-vs-pure determinism when no .so exists)."""

    def test_load_frame_wire(self):
        rng = random.Random(0x1A2)
        frames = [rand_load_frame(rng) for _ in range(20)]
        on, off = _both_paths(lambda: [
            wire.encode_load_frame(f["i"], f["g"], f["s"], f["ms"])
            for f in frames])
        assert on == off
        decoded_on, decoded_off = _both_paths(
            lambda: [wire.decode_load_frame(v) for v in on])
        assert decoded_on == decoded_off == frames

    def test_kv_frame_wire(self):
        rng = random.Random(0x1A3)
        upserts = {bytes(rng.randrange(256) for _ in range(16)):
                   [[f"i{rng.randrange(9)}"], [], []]
                   for _ in range(10)}
        removals = list(upserts)[:3]
        on, off = _both_paths(
            lambda: wire.encode_kv_frame(upserts, removals, full=True))
        assert on == off
        d_on, d_off = _both_paths(lambda: wire.decode_kv_frame(on))
        assert d_on == d_off == (upserts, removals, True)

    def test_telemetry_wire(self):
        rng = random.Random(0x1A4)
        batch = rand_telemetry_batch(rng)
        on, off = _both_paths(lambda: wire.encode_telemetry(batch))
        assert on == off
        assert wire.decode_body(on[1], on[0]) == {"frames": batch}

    def test_dispatch_wire(self):
        rng = random.Random(0x1A5)
        payloads = [rand_load_frame(rng) for _ in range(10)]
        on, off = _both_paths(
            lambda: [wire.pack_dispatch(p) for p in payloads])
        assert on == off
        assert [wire.unpack_dispatch(b) for b in on] == payloads

    def test_malformed_frames_raise_valueerror_both_paths(self):
        for bad in ("%%%not-base64%%%",
                    base64.b64encode(b"\xc1").decode(),
                    base64.b64encode(msgpack.packb([1, 2])).decode()):
            for leg in _both_paths(lambda b=bad: self._decode_err(b)):
                assert leg == "ValueError"

    @staticmethod
    def _decode_err(value):
        try:
            wire.decode_load_frame(value)
        except ValueError:
            return "ValueError"
        return "no-error"

    def test_rendezvous_owner(self):
        rng = random.Random(0x1A6)
        draws = [(tuple(sorted({f"m{rng.randrange(30)}:1"
                                for _ in range(rng.randrange(1, 8))})),
                  f"completion-{rng.randrange(10**9)}")
                 for _ in range(50)]
        on, off = _both_paths(lambda: [
            own.rendezvous_owner(m, k) for m, k in draws])
        assert on == off

    def test_tokenizer_encode(self):
        tok = SimpleTokenizer()
        texts = ["", "hello", "héllo wörld", "日本語 🦖", "\x00\x1f",
                 "a" * 1000]
        on, off = _both_paths(lambda: [tok.encode(t) for t in texts])
        assert on == off
        for t, ids in zip(texts, on):
            assert tok.decode(ids) == t


# ------------------------------------------------ instance_owner verdict memo
class TestInstanceOwnerMemo:
    def _router(self):
        return OwnershipRouter(InMemoryCoordination({}), "10.0.0.1:1",
                               start_watch=False)

    def test_memo_hits_and_matches_uncached(self):
        r = self._router()
        with r._lock:
            r._addrs |= {"10.0.0.2:1", "10.0.0.3:1"}
            r._publish_locked()
        names = [f"eng-{i}" for i in range(40)]
        first = [r.instance_owner(n) for n in names]
        # Uncached reference: the module-level walk over the same tuple.
        want = [own.telemetry_owner(r.members(), n) for n in names]
        assert first == want
        # Second pass is pure memo hits — same verdicts, cache populated.
        assert [r.instance_owner(n) for n in names] == first
        assert len(r._own_cache[1]) == len(names)

    def test_membership_change_invalidates(self):
        r = self._router()
        with r._lock:
            r._addrs |= {"10.0.0.2:1", "10.0.0.3:1", "10.0.0.4:1"}
            r._publish_locked()
        names = [f"eng-{i}" for i in range(60)]
        before = {n: r.instance_owner(n) for n in names}
        epoch = r._own_cache[0]
        with r._lock:
            r._addrs.discard("10.0.0.3:1")
            r._publish_locked()
        # The published tuple is a fresh object: the identity check must
        # rebuild the memo and re-walk against the survivors.
        after = {n: r.instance_owner(n) for n in names}
        assert r._own_cache[0] is not epoch
        assert r._own_cache[0] is r.members()
        for n in names:
            assert after[n] == (own.telemetry_owner(r.members(), n)
                                or r.self_addr)
        assert any(before[n] != after[n] for n in names) or \
            all(before[n] != "10.0.0.3:1" for n in names)
        assert "10.0.0.3:1" not in after.values()

    def test_exclude_bypasses_memo(self):
        r = self._router()
        with r._lock:
            r._addrs |= {"10.0.0.2:1", "10.0.0.3:1"}
            r._publish_locked()
        n = "eng-x"
        owner = r.instance_owner(n)
        successor = r.instance_owner(n, exclude=(owner,))
        assert successor != owner
        # The bypass never polluted the memo with the successor.
        assert r._own_cache[1].get(n) in (None, owner)
        assert r.instance_owner(n) == owner

    def test_runaway_namespace_resets_not_grows(self):
        r = self._router()
        r.OWN_CACHE_MAX = 32   # shrink the bound for the drill
        for i in range(100):
            r.instance_owner(f"chaos-{i}")
        assert len(r._own_cache[1]) <= 33

    def test_disabled_router_short_circuits(self):
        r = OwnershipRouter(InMemoryCoordination({}), "10.0.0.1:1",
                            enabled=False, start_watch=False)
        assert r.instance_owner("eng-a") == "10.0.0.1:1"
        assert r.owns_instance("eng-a")
