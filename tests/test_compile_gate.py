"""The Mosaic compile gate runs unattended as the sweep's step 0 — its
job is to turn kernel-compile rejections into named verdicts. Pin the
arm matrix, the verdict wiring, and one real end-to-end arm compile
(full 11-arm runs belong to the sweep, not the suite's wall clock)."""

import importlib.util
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

spec = importlib.util.spec_from_file_location(
    "compile_gate", REPO / "benchmarks" / "compile_gate.py")
compile_gate = importlib.util.module_from_spec(spec)
spec.loader.exec_module(compile_gate)


def test_arm_matrix_covers_every_sweep_ab():
    names = [n for n, _ in compile_gate._arm_specs(interpret=True)]
    # Every kernel knob the sweep A/Bs (tpu_sweep.sh) has a gate arm.
    assert names == [
        "paged_default", "paged_chunk16", "paged_chunk32",
        "paged_rowpipe", "paged_rowpipe16", "paged_chunk16_ctx2k",
        "paged_chunk16_ctx8k", "paged_chunk16_ctx16k",
        "paged_chunk16_ctx32k", "gemma2_softcap", "window_start",
        "fused_writeback", "fused_rowpipe16", "mq_verify_k4",
        "prefill_pallas_s128", "cp_partial_stats"]


def test_one_real_arm_compiles():
    specs = dict(compile_gate._arm_specs(interpret=True))
    specs["paged_default"]()          # raises on lowering failure


def test_run_gate_records_failures_without_crashing(monkeypatch):
    def fake_specs(interpret):
        yield "good", lambda: None
        yield "bad", lambda: (_ for _ in ()).throw(ValueError("Mosaic: no"))
    monkeypatch.setattr(compile_gate, "_arm_specs", fake_specs)
    out = compile_gate.run_gate()
    assert out["metric"] == "mosaic_compile_gate"
    assert out["arms"]["good"]["ok"] is True
    assert out["arms"]["bad"]["ok"] is False
    assert "Mosaic: no" in out["arms"]["bad"]["error"]
    assert out["failed_arms"] == ["bad"]
    assert "error" in out
