"""XLLM_STATE_DEBUG attribute-race verifier tests: discipline checks on
the instrumented ``__setattr__``, guarded container views, the escape
hatch, passthrough-when-disabled, clean-operation integration for the
registered managers, and the resurrected PR-9 context-provider shape
(caught at runtime by the verifier — the static half of this round's
regression pair lives in tests/test_xlint.py / state_regress.py)."""

import threading

import numpy as np
import pytest

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.flightrecorder import FlightRecorder
from xllm_service_tpu.common.hashing import prefix_block_hash_hexes
from xllm_service_tpu.common.types import KvCacheEvent
from xllm_service_tpu.coordination.memory import InMemoryCoordination
from xllm_service_tpu.devtools import locks, ownership, rcu
from xllm_service_tpu.engine.kv_tier import TieredKVStore
from xllm_service_tpu.scheduler.global_kvcache_mgr import (
    GlobalKVCacheMgr,
    PrefixIndex,
)
from xllm_service_tpu.scheduler.instance_mgr import InstanceMgr

from fakes import FakeChannel, make_meta, wait_until

BLOCK = 16


@pytest.fixture()
def coord(store):
    c = InMemoryCoordination(store)
    yield c
    c.close()


@pytest.fixture()
def state_debug():
    """Arm the verifier for the test body; restore the PRIOR state on
    teardown (hardcoding False would disarm a suite-wide
    XLLM_STATE_DEBUG=1 run for every test collected after this file).
    Arming also arms the instrumented locks — restore those too."""
    was = ownership.debug_enabled()
    was_locks = locks.debug_enabled()
    ownership.set_debug(True)
    ownership.reset_violations()
    locks.reset_violations()
    yield
    ownership.reset_violations()
    locks.reset_violations()
    ownership.set_debug(was)
    locks.set_debug(was_locks)


@pytest.fixture(autouse=True)
def _reset_channels():
    FakeChannel.reset()
    yield
    FakeChannel.reset()


def _run_in_thread(fn, name):
    t = threading.Thread(target=fn, name=name)
    t.start()
    t.join()


# ----------------------------------------------------------- escape hatch
class TestEscape:
    def test_escape_requires_reason(self):
        with pytest.raises(ValueError):
            ownership.escape("")
        with pytest.raises(ValueError):
            ownership.escape(None)

    def test_escape_suppresses_checks(self, coord, state_debug):
        mgr = GlobalKVCacheMgr(coord, block_size=BLOCK)
        ownership.reset_violations()
        with ownership.escape("test: deliberate unguarded write"):
            mgr._frame_seq = 99
        assert not ownership.violations()


# ------------------------------------------------------------ passthrough
class TestPassthrough:
    def test_identity_when_disabled(self, coord):
        if ownership.debug_enabled():
            pytest.skip("XLLM_STATE_DEBUG armed for this whole run")
        mgr = GlobalKVCacheMgr(coord, block_size=BLOCK)
        mgr._frame_seq = 5            # unguarded: nothing records
        mgr._dirty.add(b"x" * 16)
        assert not ownership.violations()
        assert type(mgr._dirty) is set   # no guarded view installed


# ------------------------------------------------- discipline enforcement
class TestDisciplines:
    def test_lock_guarded_rebind_without_lock_caught(self, coord,
                                                     state_debug):
        mgr = GlobalKVCacheMgr(coord, block_size=BLOCK)
        ownership.reset_violations()
        mgr._frame_seq = 123          # declared lock:_lock, none held
        vs = ownership.violations()
        assert any(v.kind == "state-lock"
                   and "GlobalKVCacheMgr._frame_seq" in v.message
                   for v in vs), vs

    def test_lock_guarded_container_mutation_caught(self, coord,
                                                    state_debug):
        mgr = InstanceMgr(coord, ServiceOptions(block_size=BLOCK),
                          channel_factory=FakeChannel.factory,
                          start_threads=False)
        try:
            assert mgr.register_instance(make_meta("i1"))
            ownership.reset_violations()
            # The deliberate unguarded cross-thread write drill: a rogue
            # thread mutates the metrics table without _metrics_lock.
            _run_in_thread(
                lambda: mgr._load_metrics.__setitem__("ghost", None),
                "rogue-writer")
            vs = ownership.violations()
            assert any(v.kind == "state-lock"
                       and "_load_metrics" in v.message
                       and "rogue-writer" in v.thread for v in vs), vs
        finally:
            ownership.reset_violations()
            mgr.stop()

    def test_rcu_swap_without_writer_lock_caught(self, coord, state_debug):
        mgr = GlobalKVCacheMgr(coord, block_size=BLOCK)
        ownership.reset_violations()
        mgr._snapshot = PrefixIndex()   # declared rcu @ _lock, none held
        vs = ownership.violations()
        assert any(v.kind == "state-lock" and "rcu" in v.message
                   for v in vs), vs

    def test_confined_write_from_wrong_thread_caught(self, coord,
                                                     state_debug):
        mgr = InstanceMgr(coord, ServiceOptions(block_size=BLOCK),
                          channel_factory=FakeChannel.factory,
                          start_threads=False)
        try:
            ownership.reset_violations()
            _run_in_thread(lambda: setattr(mgr, "_is_master", True),
                           "rogue-writer")
            vs = ownership.violations()
            assert any(v.kind == "state-confined"
                       and "mastership" in v.message for v in vs), vs
        finally:
            ownership.reset_violations()
            mgr.stop()

    def test_confined_write_from_main_thread_exempt(self, coord,
                                                    state_debug):
        # Single-threaded test drivers stand in for every role.
        mgr = InstanceMgr(coord, ServiceOptions(block_size=BLOCK),
                          channel_factory=FakeChannel.factory,
                          start_threads=False)
        try:
            ownership.reset_violations()
            mgr.set_as_master()
            mgr.set_as_replica()
            assert not ownership.violations()
        finally:
            mgr.stop()

    def test_confined_write_from_role_thread_clean(self, coord,
                                                   state_debug):
        mgr = InstanceMgr(coord, ServiceOptions(block_size=BLOCK),
                          channel_factory=FakeChannel.factory,
                          start_threads=False)
        try:
            ownership.reset_violations()
            # scheduler-sync is a declared mastership-role thread.
            _run_in_thread(mgr.set_as_master, "scheduler-sync")
            assert not ownership.violations()
        finally:
            mgr.stop()

    def test_init_only_reassign_caught(self, coord, state_debug):
        mgr = InstanceMgr(coord, ServiceOptions(block_size=BLOCK),
                          channel_factory=FakeChannel.factory,
                          start_threads=False)
        try:
            ownership.reset_violations()
            mgr._opts = ServiceOptions(block_size=BLOCK)
            vs = ownership.violations()
            assert any(v.kind == "state-reassign" for v in vs), vs
        finally:
            ownership.reset_violations()
            mgr.stop()


# --------------------------------------------------- manager integration
class TestManagerIntegration:
    def test_kvcache_ingest_and_match_run_clean(self, coord, state_debug):
        """The real write paths hold their declared locks: a full
        ingest/offload/remove cycle records nothing."""
        mgr = GlobalKVCacheMgr(coord, block_size=BLOCK)
        toks = list(range(BLOCK * 2))
        hashes = prefix_block_hash_hexes(toks, BLOCK)
        mgr.record_updated_kvcaches("i1", KvCacheEvent(stored=hashes))
        assert mgr.match(toks).scores["i1"] == pytest.approx(2.0)
        mgr.record_updated_kvcaches("i1", KvCacheEvent(offloaded=hashes[:1]))
        mgr.remove_instance("i1")
        mgr.upload_kvcache()
        assert not ownership.violations(), ownership.violations()[:3]

    def test_instance_mgr_lifecycle_runs_clean(self, coord, state_debug):
        mgr = InstanceMgr(coord, ServiceOptions(block_size=BLOCK),
                          channel_factory=FakeChannel.factory,
                          start_threads=False)
        try:
            assert mgr.register_instance(make_meta("i1"))
            mgr.record_instance_heartbeat("i1", "")
            mgr.reconcile_once()
            mgr.upload_load_metrics()
            mgr.deregister_instance("i1", reason="test")
            assert not ownership.violations(), ownership.violations()[:3]
        finally:
            mgr.stop()

    def test_tier_store_runs_clean_and_freeze_compat(self, coord,
                                                     state_debug):
        """Tier offload/drain under the verifier records nothing — and
        with the RCU freezer ALSO armed (the combined soak leg), the
        drained guarded lists still deep-freeze, so the PR-7 late-append
        bug class still raises."""
        was_rcu = rcu.debug_enabled()
        rcu.set_debug(True)
        store = TieredKVStore(block_shape=(2, 2), dtype="float32",
                              dram_bytes=64, threads=1, max_inflight=2)
        try:
            assert store.offload("ab" * 16, np.ones((2, 2), np.float32))
            wait_until(lambda: store.ready("ab" * 16))
            off, rem = store.drain_events()
            assert off == ["ab" * 16]
            assert not ownership.violations(), ownership.violations()[:3]
            rcu.reset_violations()
            with pytest.raises(rcu.RcuMutationError):
                off.append("late-delta")   # the PR-7 bug class
            rcu.reset_violations()
        finally:
            store.close()
            rcu.reset_violations()
            rcu.set_debug(was_rcu)


# ------------------------------------- resurrected PR-9 provider shape
class TestResurrectedContextProviderRace:
    """PR-9 regression pair, runtime half: context providers were
    registered/deregistered with a bare dict write while record()
    iterated the same table from request-exit threads — and a stopped
    owner's provider could linger process-long. The fixed paths hold
    the ring lock; the pre-fix shape (a bare cross-thread table write)
    is exactly what the verifier catches."""

    def test_pre_fix_shape_is_caught(self, state_debug):
        fr = FlightRecorder(capacity=4)
        ownership.reset_violations()
        _run_in_thread(
            lambda: fr._context.__setitem__("svc", lambda: {}),
            "service-startup")
        vs = ownership.violations()
        assert any(v.kind == "state-lock" and "_context" in v.message
                   for v in vs), vs

    def test_fixed_path_is_clean(self, state_debug):
        fr = FlightRecorder(capacity=4)
        ownership.reset_violations()

        def register():
            fr.add_context_provider("svc", lambda: {"ok": True})

        _run_in_thread(register, "service-startup")
        bundle = fr.record("error", request_id="r1")
        assert bundle["svc"] == {"ok": True}
        fr.remove_context_provider("svc")
        assert not ownership.violations(), ownership.violations()[:3]


# ----------------------------------------------------------- chaos drills
@pytest.mark.chaos
class TestStateChaosDrills:
    """Drill leg for ``chaos_soak.sh --state``: the detector proves it is
    live (deliberate unguarded cross-thread write caught) and the real
    concurrent write paths prove they are disciplined (a heartbeat storm
    against a churning fleet records nothing)."""

    def test_deliberate_unguarded_write_is_caught(self, coord, state_debug):
        mgr = InstanceMgr(coord, ServiceOptions(block_size=BLOCK),
                          channel_factory=FakeChannel.factory,
                          start_threads=False)
        try:
            assert mgr.register_instance(make_meta("i1"))
            ownership.reset_violations()
            _run_in_thread(
                lambda: mgr._request_loads.pop("i1", None),
                "rogue-accountant")
            assert any("_request_loads" in v.message
                       for v in ownership.violations())
        finally:
            ownership.reset_violations()
            mgr.stop()

    def test_concurrent_heartbeat_storm_runs_clean(self, coord,
                                                   state_debug):
        mgr = InstanceMgr(coord, ServiceOptions(block_size=BLOCK),
                          channel_factory=FakeChannel.factory,
                          start_threads=False)
        kv = GlobalKVCacheMgr(coord, block_size=BLOCK)
        try:
            for i in range(4):
                assert mgr.register_instance(make_meta(f"i{i}"))
            stop = threading.Event()

            def beat(name):
                toks = list(range(BLOCK))
                hashes = prefix_block_hash_hexes(toks, BLOCK)
                while not stop.is_set():
                    mgr.record_instance_heartbeat(name, "")
                    kv.record_updated_kvcaches(
                        name, KvCacheEvent(stored=hashes))
                    kv.match(toks)

            threads = [threading.Thread(target=beat, args=(f"i{i}",),
                                        name=f"agent-heartbeat-{i}")
                       for i in range(4)]
            for t in threads:
                t.start()
            for _ in range(10):
                mgr.reconcile_once()
                mgr.upload_load_metrics()
                kv.upload_kvcache()
            stop.set()
            for t in threads:
                t.join()
            assert not ownership.violations(), ownership.violations()[:3]
        finally:
            mgr.stop()
            kv.stop()
