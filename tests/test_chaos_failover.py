"""Chaos drills: deterministic fault injection driving transparent failover.

The acceptance bar (ISSUE 1): with a fault killing the serving (decode
stage) instance mid-stream, in-flight requests complete with byte-identical
output to a no-fault run; with retry budget 0 the same drill returns a
prompt 503 (no hang); stale-incarnation replays are dropped; per-instance
load accounting returns to zero after every drill.

All drills run against the seeded fault plane (`XLLM_CHAOS_SEED` selects
the schedule; `scripts/chaos_soak.sh` sweeps seeds) and are fast enough
for tier-1 (none is marked slow).
"""

import json
import os
import threading
import time

import pytest
import requests

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.faults import FAULTS, FaultInjected, FaultPlane
from xllm_service_tpu.common.metrics import (
    FAILOVER_SUCCESS_TOTAL,
    REQUESTS_CANCELLED_TOTAL,
)
from xllm_service_tpu.common.request import Request, RequestOutput, SequenceOutput
from xllm_service_tpu.common.call_data import CollectingConnection
from xllm_service_tpu.common.types import InstanceRuntimeState, InstanceType
from xllm_service_tpu.coordination.memory import InMemoryCoordination
from xllm_service_tpu.master import Master
from xllm_service_tpu.scheduler.scheduler import Scheduler
from xllm_service_tpu.testing.fake_engine import FakeEngine, FakeEngineConfig

from fakes import FakeChannel, make_meta, wait_until

pytestmark = pytest.mark.chaos

SEED = int(os.environ.get("XLLM_CHAOS_SEED", "0"))

REPLY = "Resilience is the art of continuing exactly where you left off."


@pytest.fixture(autouse=True)
def _armed_fault_plane():
    FAULTS.configure((), seed=SEED)
    yield
    FAULTS.clear()


def _opts(**kw) -> ServiceOptions:
    base = dict(
        host="127.0.0.1", http_port=0, rpc_port=0,
        lease_ttl_s=0.5, reconcile_interval_s=0.05,
        heartbeat_silence_to_suspect_s=0.3,
        detect_disconnected_instance_interval_s=0.3,
        health_probe_attempts=1, health_probe_timeout_s=0.2,
        sync_interval_s=0.2,
        failover_backoff_base_s=0.05, failover_backoff_max_s=0.3,
        rpc_backoff_base_s=0.02, rpc_backoff_max_s=0.1)
    base.update(kw)
    return ServiceOptions(**base)


def _engine(store, **cfg_kw) -> FakeEngine:
    cfg = FakeEngineConfig(reply_text=REPLY, chunk_size=4, delay_s=0.05,
                           heartbeat_interval_s=0.1, lease_ttl_s=0.5,
                           **cfg_kw)
    return FakeEngine(InMemoryCoordination(store), cfg).start()


def _base(master) -> str:
    return f"http://127.0.0.1:{master.http_port}"


def _loads_zero(master) -> bool:
    mgr = master.scheduler.instance_mgr
    with mgr._metrics_lock:
        return all(
            rl.num_prefill_requests == 0 and rl.num_prefill_tokens == 0
            and rl.num_decode_requests == 0 and rl.num_decode_tokens == 0
            for rl in mgr._request_loads.values())


def _stream_completion(master, timeout=60) -> tuple[str, list[str]]:
    """Returns (concatenated text, raw finish_reasons) of one streamed
    completion; raises on an error payload."""
    r = requests.post(_base(master) + "/v1/completions", json={
        "model": "fake-model", "prompt": "chaos", "stream": True,
        "max_tokens": 1000}, stream=True, timeout=timeout)
    assert r.status_code == 200, r.text
    text, finishes = "", []
    for line in r.iter_lines():
        if not line.startswith(b"data: "):
            continue
        data = line[len(b"data: "):]
        if data == b"[DONE]":
            break
        obj = json.loads(data)
        if "error" in obj:
            raise RuntimeError(f"stream error: {obj['error']}")
        for c in obj.get("choices", ()):
            text += c.get("text", "")
            if c.get("finish_reason"):
                finishes.append(c["finish_reason"])
    return text, finishes


@pytest.fixture()
def duo_cluster(store):
    """Master + two MIX fake engines. RR collapses each request onto a
    single MIX instance (it serves both stages), so killing it mid-stream
    IS killing the request's decode-stage instance."""
    master = Master(_opts(), coord=InMemoryCoordination(store))
    master.start()
    engines = [_engine(store), _engine(store)]
    assert wait_until(
        lambda: all(master.scheduler.instance_mgr.get_instance_meta(e.name)
                    is not None for e in engines), timeout=5)
    yield master, engines
    for e in engines:
        e.stop()
    master.stop()


class TestMidstreamCrashFailover:
    def test_stream_survives_decode_crash_byte_identical(self, duo_cluster):
        master, engines = duo_cluster
        # No-fault reference run.
        expected, _ = _stream_completion(master)
        assert expected == REPLY

        # Crash the serving instance right before its 5th delta (the
        # request is decode-stage by then: tokens are already streaming).
        FAULTS.configure([dict(point="engine.token", action="crash",
                               after=4, max_fires=1)], seed=SEED)
        success_before = FAILOVER_SUCCESS_TOTAL.value()
        text, finishes = _stream_completion(master)
        assert text == expected          # byte-identical, no gap, no dup
        assert finishes == ["stop"]
        assert FAILOVER_SUCCESS_TOTAL.value() == success_before + 1
        # Exactly one engine died; the survivor finished the stream.
        assert sum(1 for e in engines if not e._alive) == 1
        # Load accounting drains back to zero on the survivor.
        assert wait_until(lambda: _loads_zero(master), timeout=5)

    def test_concurrent_inflight_requests_all_complete(self, duo_cluster):
        """Acceptance: 100% of in-flight requests complete across an
        instance death (those on the dead instance fail over; the rest are
        untouched)."""
        master, engines = duo_cluster
        FAULTS.configure([dict(point="engine.token", action="crash",
                               after=10, max_fires=1)], seed=SEED)
        results: dict[int, str] = {}
        errors: list[BaseException] = []

        def run(i: int) -> None:
            try:
                results[i], _ = _stream_completion(master)
            except BaseException as e:  # noqa: BLE001 — collected
                errors.append(e)

        threads = [threading.Thread(target=run, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
            time.sleep(0.02)   # spread arrivals across the RR ring
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert len(results) == 4
        assert all(text == REPLY for text in results.values()), results
        assert sum(1 for e in engines if not e._alive) == 1
        assert wait_until(lambda: _loads_zero(master), timeout=5)


class TestDispatchFailureFailover:
    def test_engine_5xx_on_accept_fails_over(self, duo_cluster):
        """The initial forward bounces off a 503ing engine: the request is
        re-dispatched (MIX routing with empty decode_name must not be
        mistaken for the dead instance) and completes."""
        master, engines = duo_cluster
        FAULTS.configure([dict(point="engine.accept", action="error",
                               max_fires=1)], seed=SEED)
        success_before = FAILOVER_SUCCESS_TOTAL.value()
        text, finishes = _stream_completion(master)
        assert text == REPLY
        assert finishes == ["stop"]
        assert FAILOVER_SUCCESS_TOTAL.value() == success_before + 1
        assert all(e._alive for e in engines)   # nobody died; pure re-route
        assert wait_until(lambda: _loads_zero(master), timeout=5)


class TestCoordinationOutageFailover:
    """Coordination death composed with data-plane chaos: a mid-burst
    total outage must be invisible to in-flight streams, and an engine
    crash DURING the outage must still fail over byte-identically —
    the failover path reads only RCU routing snapshots, never the
    (dead) plane."""

    def test_burst_survives_outage_and_midstream_crash(self, store):
        master = Master(_opts(coordination_degraded_after_ticks=2,
                              coordination_reconnect_jitter_s=0.2,
                              degraded_heartbeat_silence_s=0.5),
                        coord=InMemoryCoordination(store))
        master.start()
        engines = [_engine(store), _engine(store)]
        try:
            assert wait_until(
                lambda: all(
                    master.scheduler.instance_mgr.get_instance_meta(e.name)
                    is not None for e in engines), timeout=5)
            expected, _ = _stream_completion(master)
            assert expected == REPLY
            mon = master.scheduler.coordination_health

            # Kill the plane mid-burst: every in-flight stream finishes
            # byte-identical while the monitor walks to DEGRADED.
            results: dict[int, str] = {}
            errors: list[BaseException] = []

            def run(i: int) -> None:
                try:
                    results[i], _ = _stream_completion(master)
                except BaseException as e:  # noqa: BLE001 — collected
                    errors.append(e)

            threads = [threading.Thread(target=run, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
                time.sleep(0.02)
            FAULTS.add("coord.outage", action="error")
            for t in threads:
                t.join(timeout=60)
            assert not errors, errors
            assert len(results) == 4
            assert all(text == REPLY for text in results.values()), results
            assert wait_until(lambda: mon.state() == "DEGRADED", timeout=5)
            assert master.scheduler.is_master   # sticky

            # An engine crashes mid-stream DURING the outage: the stream
            # fails over to the survivor with zero byte loss.
            FAULTS.configure([dict(point="coord.outage", action="error"),
                              dict(point="engine.token", action="crash",
                                   after=4, max_fires=1)], seed=SEED)
            success_before = FAILOVER_SUCCESS_TOTAL.value()
            text, finishes = _stream_completion(master)
            assert text == expected
            assert finishes == ["stop"]
            assert FAILOVER_SUCCESS_TOTAL.value() == success_before + 1
            dead = [e for e in engines if not e._alive]
            live = [e for e in engines if e._alive]
            assert len(dead) == 1

            # The frozen census never evicts on the lapsed lease; the
            # crash is detected via degraded-mode heartbeat silence and
            # the eviction HELD for post-recovery replay.
            mgr = master.scheduler.instance_mgr
            assert wait_until(
                lambda: mgr.get_instance_state(dead[0].name)
                == InstanceRuntimeState.SUSPECT, timeout=5)
            assert wait_until(
                lambda: any(a["kind"] == "evict" and a["key"] == dead[0].name
                            for a in mon.held.report()["actions"]),
                timeout=5)
            # The chatty survivor rode the whole outage verdict-free, and
            # streams keep completing on it.
            assert (mgr.get_instance_state(live[0].name)
                    == InstanceRuntimeState.ACTIVE)
            assert _stream_completion(master)[0] == expected

            # Plane returns: the held eviction replays, the survivor is
            # untouched, traffic still flows.
            FAULTS.configure((), seed=SEED)
            assert wait_until(lambda: mon.state() == "CONNECTED",
                              timeout=10)
            assert wait_until(
                lambda: mgr.get_instance_meta(dead[0].name) is None,
                timeout=5)
            assert (mgr.get_instance_state(live[0].name)
                    == InstanceRuntimeState.ACTIVE)
            assert _stream_completion(master)[0] == expected
        finally:
            for e in engines:
                if e._alive:
                    e.stop()
            master.stop()


class TestRetryBudget:
    def test_budget_zero_prompt_503_no_hang(self, store):
        """failover_max_retries=0 restores reference cancel-and-surface:
        the stream errors promptly (no hang until request timeout) and no
        load accounting leaks."""
        master = Master(_opts(failover_max_retries=0, request_timeout_s=60),
                        coord=InMemoryCoordination(store))
        master.start()
        engine = _engine(store)
        try:
            assert wait_until(
                lambda: master.scheduler.instance_mgr.get_instance_meta(
                    engine.name) is not None, timeout=5)
            FAULTS.configure([dict(point="engine.token", action="crash",
                                   after=4, max_fires=1)], seed=SEED)
            cancelled_before = REQUESTS_CANCELLED_TOTAL.value()
            start = time.time()
            with pytest.raises(RuntimeError, match="stream error"):
                _stream_completion(master, timeout=30)
            assert time.time() - start < 20   # prompt, not a timeout hang
            assert REQUESTS_CANCELLED_TOTAL.value() == \
                cancelled_before + 1
            assert wait_until(lambda: _loads_zero(master), timeout=5)
        finally:
            engine.stop()
            master.stop()

    def test_budget_exhausted_with_no_survivors_503(self, store):
        """Budget > 0 but nowhere to go: retries burn out against an empty
        fleet and the client still gets a prompt 503."""
        master = Master(_opts(failover_max_retries=2, request_timeout_s=60),
                        coord=InMemoryCoordination(store))
        master.start()
        engine = _engine(store)
        try:
            assert wait_until(
                lambda: master.scheduler.instance_mgr.get_instance_meta(
                    engine.name) is not None, timeout=5)
            FAULTS.configure([dict(point="engine.token", action="crash",
                                   after=4, max_fires=1)], seed=SEED)
            start = time.time()
            with pytest.raises(RuntimeError, match="stream error"):
                _stream_completion(master, timeout=30)
            assert time.time() - start < 20
            assert wait_until(lambda: _loads_zero(master), timeout=5)
        finally:
            engine.stop()
            master.stop()


class TestIdempotentReplay:
    def test_stale_incarnation_delta_dropped(self, store):
        """A delta stamped with an incarnation the request is no longer
        bound to is dropped (and its sender told to stop)."""
        FakeChannel.reset()
        coord = InMemoryCoordination(store)
        sched = Scheduler(ServiceOptions(reconcile_interval_s=0.05,
                                         sync_interval_s=0.1,
                                         lease_ttl_s=0.2),
                          coord=coord, start_threads=False)
        sched.instance_mgr._channel_factory = FakeChannel.factory
        try:
            sched.instance_mgr.register_instance(
                make_meta("m1", InstanceType.MIX, incarnation_id="INC-NEW"),
                link_peers=False)
            req = Request(service_request_id="sid-1", request_id="r",
                          model="m", stream=True, prompt="hello")
            assert sched.schedule(req).ok()
            conn = CollectingConnection(stream=True)
            sched.record_new_request(req, conn, "completion")
            assert req.prefill_incarnation == "INC-NEW"

            # Replay from the dead incarnation: dropped, sender stopped.
            stale = RequestOutput(
                service_request_id="sid-1", instance="m1",
                incarnation="INC-OLD",
                outputs=[SequenceOutput(index=0, text="ZOMBIE",
                                        token_ids=[9])])
            assert not sched.handle_generation(stale)
            # Current incarnation flows through.
            fresh = RequestOutput(
                service_request_id="sid-1", instance="m1",
                incarnation="INC-NEW",
                outputs=[SequenceOutput(index=0, text="ok", token_ids=[0])])
            assert sched.handle_generation(fresh)
            sched._output_executor.drain()
            texts = [c["choices"][0]["text"] for c in conn.payloads
                     if c.get("choices")]
            assert texts == ["ok"]
            assert sched.has_request("sid-1")
        finally:
            sched.stop()

    def test_replay_token_prefix_is_tracked(self, store):
        """The failover resume prefix is exactly the index-0 token ids the
        client has been sent."""
        FakeChannel.reset()
        sched = Scheduler(ServiceOptions(reconcile_interval_s=0.05,
                                         sync_interval_s=0.1,
                                         lease_ttl_s=0.2),
                          coord=InMemoryCoordination(store),
                          start_threads=False)
        sched.instance_mgr._channel_factory = FakeChannel.factory
        try:
            sched.instance_mgr.register_instance(
                make_meta("m1", InstanceType.MIX), link_peers=False)
            req = Request(service_request_id="sid-2", request_id="r",
                          model="m", stream=True, prompt="hello")
            assert sched.schedule(req).ok()
            sched.record_new_request(req, CollectingConnection(stream=True),
                                     "completion")
            for seq, toks in enumerate(([1, 2], [3], [4, 5]), start=1):
                sched.handle_generation(RequestOutput(
                    service_request_id="sid-2", delta_seq=seq,
                    outputs=[SequenceOutput(index=0, text="x",
                                            token_ids=list(toks))]))
            # Duplicate delivery must not extend the replay prefix.
            sched.handle_generation(RequestOutput(
                service_request_id="sid-2", delta_seq=3,
                outputs=[SequenceOutput(index=0, text="x",
                                        token_ids=[4, 5])]))
            st = sched._requests["sid-2"]
            assert st.replay_token_ids == [1, 2, 3, 4, 5]
        finally:
            sched.stop()


class TestMsgpackDispatchWire:
    def test_failover_replay_byte_equivalent_on_binary_wire(self,
                                                            duo_cluster):
        """The dispatch wire is msgpack (both engines advertise it), and a
        failover replay of the retained payload is byte-equivalent to the
        first dispatch: decoding both wires and re-packing them minus the
        failover-volatile keys (routing / trace_context / attempt /
        resume prefix) yields identical bytes — deterministic encoding of
        an identical retained payload."""
        from xllm_service_tpu.rpc import wire

        master, engines = duo_cluster
        # Reject the first accept AFTER the body is read: the initial
        # dispatch bounces off engine A and the failover layer replays
        # the retained payload onto the survivor.
        FAULTS.configure([dict(point="engine.accept", action="error",
                               max_fires=1)], seed=SEED)
        text, finishes = _stream_completion(master)
        assert text == REPLY and finishes == ["stop"]

        wires = [w for e in engines for w in e.accepted_wire]
        assert len(wires) == 2
        assert all(ctype == wire.MSGPACK_CONTENT_TYPE
                   for ctype, _ in wires)
        first, replay = (wire.unpack_dispatch(raw) for _, raw in wires)
        assert replay["failover_attempt"] == 1
        assert replay["resume_generated_token_ids"] == []
        assert replay["token_ids"] == first["token_ids"]
        volatile = ("routing", "trace_context", "failover_attempt",
                    "resume_generated_token_ids")
        core_first = {k: v for k, v in first.items() if k not in volatile}
        core_replay = {k: v for k, v in replay.items() if k not in volatile}
        assert wire.pack_dispatch(core_first) == \
            wire.pack_dispatch(core_replay)
        assert wait_until(lambda: _loads_zero(master), timeout=5)


class TestFaultPlaneDeterminism:
    def test_same_seed_same_schedule(self):
        def draw(seed):
            plane = FaultPlane(seed=seed)
            plane.configure([dict(point="rpc.post", action="error",
                                  probability=0.5)])
            return [plane.fire("rpc.post") is not None for _ in range(64)]

        assert draw(1234) == draw(1234)
        assert draw(1234) != draw(4321)   # astronomically unlikely to tie

    def test_after_and_max_fires_counting(self):
        plane = FaultPlane(seed=0)
        rule = plane.add("engine.token", action="crash", after=2, max_fires=1)
        fires = [plane.fire("engine.token") is not None for _ in range(5)]
        assert fires == [False, False, True, False, False]
        assert rule.hits == 5 and rule.fires == 1

    def test_match_and_glob(self):
        plane = FaultPlane(seed=0)
        plane.add("rpc.*", action="error", match={"instance": "a:1"})
        assert plane.fire("rpc.post", instance="b:2") is None
        assert plane.fire("rpc.get", instance="a:1") is not None

    def test_check_raises_and_delays(self):
        plane = FaultPlane(seed=0)
        plane.add("kv_transfer.offer", action="error", max_fires=1)
        with pytest.raises(FaultInjected):
            plane.check("kv_transfer.offer")
        plane.check("kv_transfer.offer")   # max_fires spent: no-op


class TestAdminFaultsEndpoint:
    def test_configure_inspect_clear(self, store):
        master = Master(_opts(), coord=InMemoryCoordination(store))
        master.start()
        try:
            base = _base(master)
            r = requests.post(base + "/admin/faults", json={
                "seed": 77,
                "rules": [{"point": "rpc.post", "action": "delay",
                           "delay_s": 0.01}]}, timeout=5)
            assert r.status_code == 200 and r.json()["seed"] == 77
            got = requests.get(base + "/admin/faults", timeout=5).json()
            assert got["rules"][0]["point"] == "rpc.post"
            assert requests.post(base + "/admin/faults",
                                 json={"rules": [{"point": "x",
                                                  "action": "nope"}]},
                                 timeout=5).status_code == 400
            r = requests.post(base + "/admin/faults", json={"clear": True},
                              timeout=5)
            assert r.status_code == 200 and r.json()["rules"] == []
        finally:
            master.stop()

    def test_failure_metrics_exported(self, store):
        master = Master(_opts(), coord=InMemoryCoordination(store))
        master.start()
        try:
            text = requests.get(_base(master) + "/metrics", timeout=5).text
            for name in ("failover_attempts_total", "failover_success_total",
                         "rpc_retries_total", "instance_evictions_total",
                         "requests_cancelled_total"):
                assert name in text
        finally:
            master.stop()
