"""North-star-topology worker (VERDICT r4 next #4): runs in its OWN
process on a 64-virtual-device CPU platform (the suite's conftest pins
8) and proves the v5e-64 serving topology's mesh math end to end:

  1. dryrun_multichip(64, northstar=True): train {data 8 x model 8},
     TP-8 decode, EP-8 MoE (16 experts, 2/shard), ring attention seq=8,
     CP paged decode seq=8, pipeline pipe=8.
  2. page-shard divisibility guard: a CP engine whose num_pages doesn't
     divide the seq axis must refuse at construction, not corrupt pages.
  3. a REAL InferenceEngine decoding context-parallel at seq=8.
  4. PD across host groups: master + prefill agent on devices [0:8] +
     decode agent on devices [32:40] (disjoint groups via
     mesh_device_offset), one greedy completion through the full HTTP
     path with device KV handoff between the groups.

Prints one "OK <section>" line per proof; tests/test_northstar_topology
asserts all of them. (BASELINE.json "v5e-64"; SURVEY §2.12/§2.13.)
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import __graft_entry__ as graft  # noqa: E402

N = 64


def main() -> None:
    graft._pin_cpu_platform(N)

    # ---- 1. full dryrun battery at north-star axis sizes ----
    graft.dryrun_multichip(N, northstar=True)
    print("OK northstar_dryrun")

    import jax
    import jax.numpy as jnp
    import requests

    from xllm_service_tpu.common.config import ServiceOptions
    from xllm_service_tpu.common.request import SamplingParams
    from xllm_service_tpu.common.types import InstanceType
    from xllm_service_tpu.coordination.memory import (InMemoryCoordination,
                                                      MemoryStore)
    from xllm_service_tpu.engine.agent import AgentConfig, EngineAgent
    from xllm_service_tpu.engine.config import EngineConfig
    from xllm_service_tpu.engine.engine import EngineRequest, InferenceEngine
    from xllm_service_tpu.master import Master
    from xllm_service_tpu.models.base import tiny_config
    from xllm_service_tpu.parallel.mesh import MeshConfig

    assert len(jax.devices()) >= N

    def cp_cfg(num_pages: int) -> EngineConfig:
        return EngineConfig(
            model_id="ns-cp",
            model=tiny_config(dtype=jnp.float32, num_heads=8,
                              num_kv_heads=8, max_context_len=256),
            mesh=MeshConfig(seq=8),
            num_pages=num_pages, page_size=16, hash_block_size=32,
            max_batch_size=2, max_seq_len=256,
            prefill_buckets=(32, 256), seq_parallel_min_tokens=64)

    # ---- 2. page-shard divisibility must be refused at seq=8 ----
    try:
        InferenceEngine(cp_cfg(num_pages=100))   # 100 % 8 != 0
        raise SystemExit("divisibility guard MISSING: engine accepted a "
                         "page pool that does not shard over seq=8")
    except ValueError as e:
        assert "num_pages" in str(e), e
    print("OK page_shard_divisibility_guard")

    # ---- 3. real CP engine decoding at seq=8 ----
    eng = InferenceEngine(cp_cfg(num_pages=96))
    got: list[int] = []
    eng.submit(EngineRequest(
        "ns-cp-req", token_ids=list(range(2, 82)),
        sampling=SamplingParams(max_tokens=8, temperature=0.0,
                                ignore_eos=True),
        on_output=lambda out: got.extend(
            t for s in out.outputs for t in s.token_ids)))
    for _ in range(40):
        eng.step()
        if len(got) >= 8:
            break
    assert len(got) >= 8, f"CP engine produced {len(got)} tokens"
    eng.stop()
    print("OK cp8_engine_decode")

    # ---- 4. PD pair on DISJOINT device groups + device KV handoff ----
    def pd_cfg() -> EngineConfig:
        return EngineConfig(
            model_id="ns-pd",
            model=tiny_config(dtype=jnp.float32, num_heads=8,
                              num_kv_heads=8, max_context_len=256),
            num_pages=64, page_size=16, hash_block_size=32,
            max_batch_size=2, max_seq_len=256, prefill_buckets=(32, 256))

    store = MemoryStore(expiry_tick_s=0.05)
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=2.0, sync_interval_s=0.3,
                          reconcile_interval_s=0.1)
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()

    def agent(itype: InstanceType, offset: int) -> EngineAgent:
        cfg = pd_cfg()
        cfg.mesh = MeshConfig(model=8)
        cfg.mesh_device_offset = offset
        return EngineAgent(
            cfg,
            AgentConfig(host="127.0.0.1", model_id="ns-pd",
                        instance_type=itype,
                        heartbeat_interval_s=0.3, lease_ttl_s=2.0,
                        enable_device_kv_transfer=True),
            coord=InMemoryCoordination(store)).start()

    prefill = agent(InstanceType.PREFILL, 0)      # host group 0
    decode = agent(InstanceType.DECODE, 32)       # host group 4
    try:
        import time
        deadline = time.time() + 60
        mgr = master.scheduler.instance_mgr
        while time.time() < deadline:
            if (mgr.get_instance_meta(prefill.name) is not None
                    and mgr.get_instance_meta(decode.name) is not None):
                break
            time.sleep(0.1)
        else:
            raise SystemExit("PD agents never registered")

        pre_devs = {d.id for d in prefill.engine.mesh.devices.flat}
        dec_devs = {d.id for d in decode.engine.mesh.devices.flat}
        assert pre_devs == set(range(8)), pre_devs
        assert dec_devs == set(range(32, 40)), dec_devs
        assert not (pre_devs & dec_devs), "device groups overlap"

        r = requests.post(
            f"http://127.0.0.1:{master.http_port}/v1/completions",
            json={"model": "ns-pd", "prompt": "cross slice handoff",
                  "max_tokens": 8, "temperature": 0, "ignore_eos": True},
            timeout=300)
        assert r.status_code == 200, r.text[:300]
        assert r.json()["choices"][0]["finish_reason"] == "length"
    finally:
        prefill.stop()
        decode.stop()
        master.stop()
        store.close()
    print("OK pd_disjoint_device_groups")


if __name__ == "__main__":
    main()
