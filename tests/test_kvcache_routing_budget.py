"""Tier-1 cache-aware-routing data-plane budget gate.

Runs the kvcache routing bench (in-process, no subprocesses) with a small
workload and DELIBERATELY generous ceilings — like the master hot-path
budget test, the point is to catch an order-of-magnitude regression (a
lock sneaking back onto the match path, per-match re-hashing, an O(index)
eviction), not to assert the full-scale numbers. Those live in
BENCH_kvcache_r07.json (8 instances x 100k blocks: 17.7x/27.6x match
speedup, 3.85x hashing).
"""

import pytest

from benchmarks.kvcache_routing_bench import run_hashing_bench, run_index_bench
from xllm_service_tpu.common.hashing import native_available

# Generous CI ceilings: order-of-magnitude guards, not perf targets.
MATCH_P50_CEILING_MS = 2.0          # measured ~0.01-0.02 ms
MIN_MATCH_SPEEDUP = 2.0             # measured 10-28x
MIN_INGEST_KEYS_PER_S = 5_000       # measured ~130-150k/s
MIN_NATIVE_HASH_SPEEDUP = 1.5       # measured 3.1-3.9x with the C ext


@pytest.fixture(scope="module")
def report():
    return run_index_bench(n_instances=4, blocks_per_instance=5_000,
                           n_prompts=64, chain_len=16, threads=4, rounds=2)


def test_match_latency_budget(report):
    p50 = report["match_new"]["p50_ms"]
    assert p50 < MATCH_P50_CEILING_MS, (
        f"lock-free match p50 {p50:.3f} ms blew the CI budget "
        f"({MATCH_P50_CEILING_MS} ms) — did a lock or per-match hashing "
        f"sneak back onto the read path? Run "
        f"benchmarks/kvcache_routing_bench.py for the full table.")


def test_match_speedup_over_legacy(report):
    s1 = report["match_speedup_1t"]
    assert s1 >= MIN_MATCH_SPEEDUP, (
        f"match speedup over the pre-PR locked flat dict fell to {s1}x "
        f"(floor {MIN_MATCH_SPEEDUP}x)")


def test_ingest_throughput_budget(report):
    keys_s = report["ingest_new_keys_per_s"]
    assert keys_s >= MIN_INGEST_KEYS_PER_S, (
        f"heartbeat ingest throughput {keys_s}/s below floor "
        f"({MIN_INGEST_KEYS_PER_S}/s)")


def test_eviction_is_not_full_scan(report):
    # O(blocks owned): with 4 equal instances the new removal must not
    # cost more than a legacy full-index walk (it touches 1/4 the keys;
    # allow 1.5x for constant-factor noise on a loaded CI box).
    new_ms = report["remove_instance_new_ms"]
    legacy_ms = report["remove_instance_legacy_ms"]
    assert new_ms < legacy_ms * 1.5, (
        f"remove_instance {new_ms} ms vs legacy full-scan {legacy_ms} ms "
        f"— reverse index not engaged?")


def test_hashing_speedup():
    r = run_hashing_bench(iters=100, rounds=3)
    if native_available():
        assert r["speedup"] >= MIN_NATIVE_HASH_SPEEDUP, (
            f"native chained hashing speedup fell to {r['speedup']}x "
            f"(floor {MIN_NATIVE_HASH_SPEEDUP}x): {r}")
    else:
        # Pure-Python fallback: batched conversion must at least not
        # regress materially vs the old per-slice loop.
        assert r["speedup"] >= 0.7, r
