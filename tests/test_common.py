"""Unit tests for the L1 common layer (types, hashing, predictor, metrics,
ordered executor)."""

import time

import numpy as np
import pytest

from xllm_service_tpu.common.hashing import (
    DEFAULT_BLOCK_SIZE,
    hash_block,
    prefix_block_hashes,
    prefix_block_hash_hexes,
)
from xllm_service_tpu.common.metrics import MetricsRegistry
from xllm_service_tpu.common.ordered_executor import OrderedExecutor
from xllm_service_tpu.common.request import RequestOutput, SequenceOutput, Status, StatusCode, Usage, LogProb
from xllm_service_tpu.common.time_predictor import TimePredictor
from xllm_service_tpu.common.types import (
    CacheLocations,
    InstanceMetaInfo,
    InstanceType,
    KvCacheEvent,
    LoadMetrics,
    TpuTopology,
)


class TestHashing:
    def test_chained_and_deterministic(self):
        toks = list(range(DEFAULT_BLOCK_SIZE * 3 + 5))
        h1 = prefix_block_hashes(toks)
        h2 = prefix_block_hashes(toks)
        assert h1 == h2
        assert len(h1) == 3  # trailing partial block ignored
        assert all(len(h) == 16 for h in h1)
        assert len(set(h1)) == 3

    def test_prefix_property(self):
        """Shared prefixes share leading block hashes; divergence changes all
        subsequent hashes (chaining)."""
        a = list(range(256))
        b = list(range(256))
        b[200] = 9999  # diverge in block 2
        ha, hb = prefix_block_hashes(a), prefix_block_hashes(b)
        assert ha[0] == hb[0]
        assert ha[1] != hb[1]

    def test_block_size_variants(self):
        toks = list(range(64))
        assert prefix_block_hashes(toks, block_size=16) != prefix_block_hashes(toks, block_size=32)
        assert len(prefix_block_hashes(toks, block_size=16)) == 4
        with pytest.raises(ValueError):
            prefix_block_hashes(toks, block_size=0)

    def test_chain_seed(self):
        blk = list(range(DEFAULT_BLOCK_SIZE))
        assert hash_block(b"", blk) != hash_block(b"\x00" * 16, blk)
        assert prefix_block_hash_hexes(blk)[0] == hash_block(b"", blk).hex()

    def test_hashlib_construction_equivalence(self):
        """The batched path (native C or memoryview fast path) must be
        byte-identical to the definitional per-block construction — every
        party in the cluster keys the same prefix to the same 16 bytes."""
        import hashlib

        rng = np.random.default_rng(7)
        for n in (1, 127, 128, 129, 512, 4096, 5000):
            toks = rng.integers(0, 2**31 - 1, size=n).tolist()
            got = prefix_block_hashes(toks, 128)
            arr = np.asarray(toks, dtype=np.int32)
            prev, ref = b"", []
            for i in range(len(arr) // 128):
                key = prev if prev else b"xllm-service-tpu"
                prev = hashlib.blake2b(
                    arr[i * 128:(i + 1) * 128].tobytes(),
                    digest_size=16, key=key).digest()
                ref.append(prev)
            assert got == ref
            # ndarray input takes the buffer path; must agree too.
            assert prefix_block_hashes(arr, 128) == ref

    def test_native_matches_python_fallback(self, monkeypatch):
        from xllm_service_tpu.common import hashing as H

        if not H.native_available():
            pytest.skip("libblockhash.so not built")
        toks = list(range(1000))
        native = H.prefix_block_hashes(toks, 64)
        # Force the PURE fallback (the path every non-built deployment
        # runs): both native entry points disabled.
        monkeypatch.setattr(H, "_NATIVE", None)
        monkeypatch.setattr(H, "_NATIVE_LIST", None)
        assert H.prefix_block_hashes(toks, 64) == native
        assert H.prefix_block_hashes(np.asarray(toks, dtype=np.int32),
                                     64) == native

    def test_extend_prefix_block_hashes(self):
        from xllm_service_tpu.common.hashing import extend_prefix_block_hashes

        toks = list(range(DEFAULT_BLOCK_SIZE * 4 + 17))
        full = prefix_block_hashes(toks)
        for k in (0, 1, 2, 4):
            assert extend_prefix_block_hashes(full[:k], toks) == full
        # Longer memo than prompt covers (truncation): prefix returned.
        assert extend_prefix_block_hashes(full, toks[:DEFAULT_BLOCK_SIZE * 2]) \
            == full[:2]

    def test_as_key_normalization(self):
        from xllm_service_tpu.common.hashing import as_key

        raw = bytes(range(16))
        assert as_key(raw) == raw
        assert as_key(raw.hex()) == raw
        assert as_key("zz") is None
        assert as_key("aa") is None          # wrong length
        assert as_key(b"short") is None
        assert as_key(12) is None


class TestTypes:
    def test_instance_meta_roundtrip(self):
        info = InstanceMetaInfo(
            name="10.0.0.1:9000",
            rpc_address="10.0.0.1:9001",
            type=InstanceType.PREFILL,
            dp_size=2,
            topology=TpuTopology(slice_id="slice-a", mesh_shape=[2, 4],
                                 axis_names=["data", "model"],
                                 host_addrs=["10.0.0.1:9100"]),
            ttft_profiling_data=[[128, 30.0], [512, 90.0], [2048, 300.0]],
            incarnation_id="abc123",
        )
        back = InstanceMetaInfo.from_json(info.to_json())
        assert back == info
        assert back.topology.num_devices() == 8

    def test_kv_event_and_locations(self):
        ev = KvCacheEvent(stored=["aa" * 16], removed=[], offloaded=[])
        assert not ev.empty()
        assert KvCacheEvent.from_dict(ev.to_dict()) == ev
        loc = CacheLocations(hbm={"i1", "i2"}, dram={"i3"})
        back = CacheLocations.from_dict(loc.to_dict())
        assert back == loc
        back.remove_instance("i1")
        assert back.hbm == {"i2"}
        row = loc.to_row()
        assert CacheLocations.from_row(row) == loc

    def test_kv_event_wire_forms(self):
        """Hex (JSON wire) and raw-bytes (msgpack wire) forms carry the
        same keys; either form round-trips through from_dict."""
        raw = [bytes([i]) * 16 for i in range(3)]
        ev = KvCacheEvent(stored=raw[:2], removed=[raw[2]])
        jd = ev.to_dict()
        assert jd["stored"] == [k.hex() for k in raw[:2]]
        wd = ev.to_wire_dict()
        assert wd["stored"] == raw[:2] and wd["removed"] == [raw[2]]
        # Hex-built event produces identical wire bytes.
        hex_ev = KvCacheEvent.from_dict(jd)
        assert hex_ev.to_wire_dict() == wd
        assert hex_ev.to_dict() == jd

    def test_load_metrics_roundtrip(self):
        lm = LoadMetrics(waiting_requests_num=3, hbm_cache_usage_perc=0.5)
        assert LoadMetrics.from_dict(lm.to_dict()) == lm

    def test_request_output_roundtrip(self):
        out = RequestOutput(
            request_id="r1", service_request_id="s1",
            status=Status(StatusCode.OK),
            outputs=[SequenceOutput(index=0, text="hi", token_ids=[1, 2],
                                    finish_reason="stop",
                                    logprobs=[LogProb(token="hi", token_id=1, logprob=-0.5)])],
            usage=Usage(10, 2), finished=True)
        back = RequestOutput.from_dict(out.to_dict())
        assert back == out


class TestTimePredictor:
    def test_ttft_quadratic_fit(self):
        tp = TimePredictor()
        xs = np.array([64, 128, 256, 512, 1024, 2048], dtype=float)
        ys = 5.0 + 0.05 * xs + 1e-5 * xs * xs
        assert tp.fit_ttft(np.stack([xs, ys], axis=1).tolist())
        assert tp.predict_ttft(300) == pytest.approx(5.0 + 0.05 * 300 + 1e-5 * 300 * 300, rel=1e-3)

    def test_tpot_linear_fit(self):
        tp = TimePredictor()
        rows = [[b, t, 2.0 + 0.5 * b + 0.001 * t]
                for b in (1, 4, 16, 64) for t in (100, 1000, 10000)]
        assert tp.fit_tpot(rows)
        assert tp.predict_tpot(8, 5000) == pytest.approx(2.0 + 0.5 * 8 + 5.0, rel=1e-3)

    def test_insufficient_data(self):
        tp = TimePredictor()
        assert not tp.fit_ttft([[1, 2]])
        assert tp.predict_ttft(100) == 0.0
        assert not tp.has_ttft


class TestMetrics:
    def test_prometheus_render(self):
        reg = MetricsRegistry()
        c = reg.counter("reqs_total", "total requests")
        c.inc()
        c.inc(2)
        h = reg.histogram("lat_ms", buckets=(10, 100))
        h.observe(5)
        h.observe(50)
        h.observe(500)
        text = reg.render_prometheus()
        assert "reqs_total 3.0" in text
        assert '# TYPE lat_ms histogram' in text
        assert 'lat_ms_bucket{le="10"} 1' in text
        assert 'lat_ms_bucket{le="100"} 2' in text
        assert 'lat_ms_bucket{le="+Inf"} 3' in text
        assert reg.counter("reqs_total") is c
        with pytest.raises(TypeError):
            reg.gauge("reqs_total")


class TestOrderedExecutor:
    def test_per_key_ordering(self):
        ex = OrderedExecutor(num_lanes=4)
        results: dict[str, list[int]] = {"a": [], "b": []}
        for i in range(50):
            for key in ("a", "b"):
                ex.submit(key, lambda k=key, i=i: results[k].append(i))
        ex.drain()
        assert results["a"] == list(range(50))
        assert results["b"] == list(range(50))
        ex.shutdown()

    def test_lane_stability(self):
        ex = OrderedExecutor(num_lanes=8)
        assert ex.lane_for("req-1") == ex.lane_for("req-1")
        ex.shutdown()
