"""InstanceMgr tests: registration/linking, failure state machine,
incarnation replacement, RR selection, SLO selection + PD flips.

Covers the reference scenarios of SURVEY.md §3.4 hermetically (the
reference's own rpc_service_test.cpp left these as commented-out TODOs).
"""

import time

import pytest

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.request import Request
from xllm_service_tpu.common.types import (
    InstanceRuntimeState,
    InstanceType,
    LoadMetrics,
    RequestAction,
)
from xllm_service_tpu.coordination.memory import InMemoryCoordination
from xllm_service_tpu.scheduler.instance_mgr import InstanceMgr

from fakes import FakeChannel, make_meta, register_in_coord, wait_until


@pytest.fixture(autouse=True)
def _reset_channels():
    FakeChannel.reset()
    yield
    FakeChannel.reset()


@pytest.fixture()
def coord(store):
    c = InMemoryCoordination(store)
    yield c
    c.close()


def fast_opts(**kw) -> ServiceOptions:
    return ServiceOptions(
        health_probe_attempts=1, health_probe_timeout_s=0.05,
        heartbeat_silence_to_suspect_s=0.2,
        detect_disconnected_instance_interval_s=0.3,
        reconcile_interval_s=0.05, lease_ttl_s=0.2, **kw)


def make_mgr(coord, **kw) -> InstanceMgr:
    return InstanceMgr(coord, fast_opts(), channel_factory=FakeChannel.factory,
                       start_threads=kw.pop("start_threads", False), **kw)


class TestRegistration:
    def test_boot_time_link_fanout(self, coord):
        """A master that starts AFTER engines registered (or restarts under
        a live fleet) must link every pre-existing P<->D pair (reference
        `instance_mgr.cpp:150-182`)."""
        register_in_coord(coord, make_meta("p1", InstanceType.PREFILL))
        register_in_coord(coord, make_meta("p2", InstanceType.PREFILL))
        register_in_coord(coord, make_meta("d1", InstanceType.DECODE))
        mgr = make_mgr(coord)
        # Every P<->D pair linked in both directions.
        assert "d1" in FakeChannel.registry["p1"].links
        assert "d1" in FakeChannel.registry["p2"].links
        assert set(FakeChannel.registry["d1"].links) == {"p1", "p2"}
        mgr.stop()

    def test_watch_registration_and_pd_linking(self, coord):
        mgr = make_mgr(coord)
        register_in_coord(coord, make_meta("p1", InstanceType.PREFILL))
        assert wait_until(lambda: mgr.get_instance_meta("p1") is not None)
        register_in_coord(coord, make_meta("d1", InstanceType.DECODE))
        assert wait_until(lambda: mgr.get_instance_meta("d1") is not None)
        # New decode was linked to existing prefill, both directions.
        assert "d1" in FakeChannel.registry["p1"].links
        assert "p1" in FakeChannel.registry["d1"].links
        mgr.stop()

    def test_link_failure_rolls_back(self, coord):
        mgr = make_mgr(coord)
        register_in_coord(coord, make_meta("p1", InstanceType.PREFILL))
        assert wait_until(lambda: mgr.get_instance_meta("p1") is not None)
        FakeChannel.registry["p1"].link_ok = False  # peer refuses the link
        assert not mgr.register_instance(make_meta("d1", InstanceType.DECODE))
        assert mgr.get_instance_meta("d1") is None
        mgr.stop()

    def test_incarnation_replacement(self, coord):
        mgr = make_mgr(coord)
        m1 = make_meta("i1", InstanceType.MIX, incarnation_id="inc-old")
        register_in_coord(coord, m1)
        assert wait_until(lambda: mgr.get_instance_meta("i1") is not None)
        m2 = make_meta("i1", InstanceType.MIX, incarnation_id="inc-new")
        register_in_coord(coord, m2)
        assert wait_until(
            lambda: (mgr.get_instance_meta("i1") or m1).incarnation_id == "inc-new")
        mgr.stop()

    def test_same_incarnation_refreshes_to_active(self, coord):
        mgr = make_mgr(coord)
        m = make_meta("i1", InstanceType.MIX, incarnation_id="inc-1")
        register_in_coord(coord, m, ttl_s=0.25, keepalive=False)
        assert wait_until(lambda: mgr.get_instance_meta("i1") is not None)
        # Lease lapses; healthy probe => LEASE_LOST grace.
        assert wait_until(lambda: mgr.get_instance_state("i1")
                          == InstanceRuntimeState.LEASE_LOST)
        register_in_coord(coord, m)  # re-registration, same incarnation
        assert wait_until(lambda: mgr.get_instance_state("i1")
                          == InstanceRuntimeState.ACTIVE)
        mgr.stop()


class TestFailureDetection:
    def test_lease_lost_grace_when_probe_ok(self, coord):
        mgr = make_mgr(coord)
        register_in_coord(coord, make_meta("i1"), ttl_s=0.25, keepalive=False)
        assert wait_until(lambda: mgr.get_instance_meta("i1") is not None)
        assert wait_until(lambda: mgr.get_instance_state("i1")
                          == InstanceRuntimeState.LEASE_LOST)
        # LEASE_LOST instances remain schedulable.
        assert mgr.get_next_instance_pair().prefill_name == "i1"
        mgr.stop()

    def test_suspect_when_probe_fails(self, coord):
        mgr = make_mgr(coord)
        register_in_coord(coord, make_meta("i1"), ttl_s=0.25, keepalive=False)
        assert wait_until(lambda: mgr.get_instance_meta("i1") is not None)
        FakeChannel.registry["i1"].healthy = False
        assert wait_until(lambda: mgr.get_instance_state("i1")
                          == InstanceRuntimeState.SUSPECT)
        assert mgr.get_next_instance_pair().prefill_name == ""
        mgr.stop()

    def test_heartbeat_silence_promotes_to_suspect_then_evicts(self, coord):
        failures = []
        mgr = make_mgr(coord)
        mgr.on_instance_failure = lambda n, inc, t: failures.append((n, inc))
        register_in_coord(coord, make_meta("i1", incarnation_id="X"),
                          ttl_s=0.25, keepalive=False)
        assert wait_until(lambda: mgr.get_instance_meta("i1") is not None)
        assert wait_until(lambda: mgr.get_instance_state("i1")
                          == InstanceRuntimeState.LEASE_LOST)
        # No heartbeats: reconcile promotes to SUSPECT then evicts.
        deadline = time.time() + 3
        while time.time() < deadline and mgr.get_instance_meta("i1") is not None:
            mgr.reconcile_once()
            time.sleep(0.05)
        assert mgr.get_instance_meta("i1") is None
        assert failures == [("i1", "X")]
        mgr.stop()

    def test_heartbeat_recovers_suspect(self, coord):
        mgr = make_mgr(coord)
        register_in_coord(coord, make_meta("i1", incarnation_id="X"),
                          ttl_s=0.25, keepalive=False)
        assert wait_until(lambda: mgr.get_instance_meta("i1") is not None)
        FakeChannel.registry["i1"].healthy = False
        assert wait_until(lambda: mgr.get_instance_state("i1")
                          == InstanceRuntimeState.SUSPECT)
        assert mgr.record_instance_heartbeat("i1", "X", LoadMetrics())
        assert mgr.get_instance_state("i1") == InstanceRuntimeState.LEASE_LOST
        mgr.stop()

    def test_stale_incarnation_heartbeat_rejected(self, coord):
        mgr = make_mgr(coord)
        register_in_coord(coord, make_meta("i1", incarnation_id="new"))
        assert wait_until(lambda: mgr.get_instance_meta("i1") is not None)
        assert not mgr.record_instance_heartbeat("i1", "old")
        assert mgr.record_instance_heartbeat("i1", "new")
        mgr.stop()


class TestSelection:
    def test_round_robin_pairs(self, coord):
        mgr = make_mgr(coord)
        for n in ("p1", "p2"):
            mgr.register_instance(make_meta(n, InstanceType.PREFILL),
                                  link_peers=False)
        for n in ("d1", "d2"):
            mgr.register_instance(make_meta(n, InstanceType.DECODE),
                                  link_peers=False)
        pairs = {(mgr.get_next_instance_pair().prefill_name,
                  mgr.get_next_instance_pair().decode_name)
                 for _ in range(4)}
        prefills = {mgr.get_next_instance_pair().prefill_name for _ in range(4)}
        assert prefills == {"p1", "p2"}
        mgr.stop()

    def test_default_only_fleet(self, coord):
        mgr = make_mgr(coord)
        mgr.register_instance(make_meta("m1", InstanceType.DEFAULT),
                              link_peers=False)
        r = mgr.get_next_instance_pair()
        assert r.prefill_name == "m1" and r.decode_name == ""
        assert mgr.has_available_instances()
        mgr.stop()

    def test_mix_instance_serves_both_roles(self, coord):
        mgr = make_mgr(coord)
        mgr.register_instance(make_meta("mix1", InstanceType.MIX),
                              link_peers=False)
        r = mgr.get_next_instance_pair()
        assert r.prefill_name == "mix1" and r.decode_name == ""
        mgr.stop()

    def test_prefill_only_fleet_not_ready(self, coord):
        """Readiness (reference `instance_mgr.cpp:1430-1472`): a fleet with
        only PREFILL instances must report NOT ready — accepted traffic
        could never reach a decode peer. Adding one decode (or MIX) makes
        it ready; a SUSPECT decode revokes readiness again."""
        mgr = make_mgr(coord)
        mgr.register_instance(make_meta("p1", InstanceType.PREFILL),
                              link_peers=False)
        mgr.register_instance(make_meta("p2", InstanceType.PREFILL),
                              link_peers=False)
        assert not mgr.has_available_instances()
        mgr.register_instance(make_meta("d1", InstanceType.DECODE),
                              link_peers=False)
        assert mgr.has_available_instances()
        # Decode goes SUSPECT -> not ready again.
        FakeChannel.registry["d1"].healthy = False
        mgr._handle_instance_delete("d1")
        assert not mgr.has_available_instances()
        mgr.stop()

    def test_decode_only_fleet_not_ready(self, coord):
        mgr = make_mgr(coord)
        mgr.register_instance(make_meta("d1", InstanceType.DECODE),
                              link_peers=False)
        assert not mgr.has_available_instances()
        mgr.register_instance(make_meta("mix1", InstanceType.MIX),
                              link_peers=False)
        assert mgr.has_available_instances()
        mgr.stop()


class TestSlo:
    def _mgr_with_profiles(self, coord):
        mgr = make_mgr(coord)
        ttft = [[128, 20.0], [512, 60.0], [2048, 200.0], [4096, 420.0]]
        # p1 fast decode, p2 slower.
        tpot_fast = [[1, 100, 5.0], [4, 1000, 10.0], [16, 8000, 30.0]]
        tpot_slow = [[1, 100, 40.0], [4, 1000, 80.0], [16, 8000, 200.0]]
        mgr.register_instance(make_meta(
            "p1", InstanceType.PREFILL, ttft_profiling_data=ttft),
            link_peers=False)
        mgr.register_instance(make_meta(
            "d1", InstanceType.DECODE, tpot_profiling_data=tpot_fast),
            link_peers=False)
        mgr.register_instance(make_meta(
            "d2", InstanceType.DECODE, tpot_profiling_data=tpot_slow),
            link_peers=False)
        return mgr

    def test_slo_picks_decode_meeting_tpot(self, coord):
        mgr = self._mgr_with_profiles(coord)
        req = Request(service_request_id="s1", token_ids=list(range(256)))
        r = mgr.select_instance_pair_on_slo(req)
        assert r.prefill_name == "p1"
        assert r.decode_name == "d1"  # first decode meeting 50ms TPOT target
        assert req.metrics.estimated_ttft_ms > 0
        mgr.stop()

    def test_overloaded_decode_flips_idle_prefill(self, coord):
        mgr = make_mgr(coord)
        tpot_awful = [[1, 100, 500.0], [4, 1000, 900.0], [16, 8000, 2000.0]]
        mgr.register_instance(make_meta("p1", InstanceType.PREFILL),
                              link_peers=False)
        mgr.register_instance(make_meta("p2", InstanceType.PREFILL),
                              link_peers=False)
        mgr.register_instance(make_meta(
            "d1", InstanceType.DECODE, tpot_profiling_data=tpot_awful),
            link_peers=False)
        req = Request(service_request_id="s1", token_ids=list(range(128)))
        r = mgr.select_instance_pair_on_slo(req)
        # The request itself routes to the (overloaded) existing decode —
        # the flip must NOT run on the request path (no engine RPC inside
        # schedule); it is queued for the reconcile thread.
        assert r.decode_name == "d1"
        assert not any("DECODE" in ch.flips
                       for ch in FakeChannel.registry.values())
        mgr.reconcile_once()   # reconcile performs the queued flip
        flipped = [n for n, ch in FakeChannel.registry.items()
                   if "DECODE" in ch.flips]
        assert flipped
        assert mgr.get_instance_meta(flipped[0]).type == InstanceType.DECODE
        # Subsequent requests can now use the flipped decode capacity.
        r2 = mgr.select_instance_pair_on_slo(
            Request(service_request_id="s2", token_ids=list(range(128))))
        assert r2.decode_name == flipped[0]
        mgr.stop()

    def test_request_metrics_accounting(self, coord):
        mgr = make_mgr(coord)
        mgr.register_instance(make_meta("p1", InstanceType.PREFILL),
                              link_peers=False)
        mgr.register_instance(make_meta("d1", InstanceType.DECODE),
                              link_peers=False)
        req = Request(service_request_id="s1", token_ids=list(range(64)))
        req.routing.prefill_name = "p1"
        req.routing.decode_name = "d1"
        mgr.update_request_metrics(req, RequestAction.SCHEDULE)
        assert mgr._request_loads["p1"].num_prefill_requests == 1
        mgr.update_request_metrics(req, RequestAction.FINISH_PREFILL,
                                   n_new=2)
        assert mgr._request_loads["p1"].num_prefill_requests == 0
        assert mgr._request_loads["d1"].num_decode_requests == 1
        # 3 more deltas of 2, 5, 1 tokens: credits total ntok + 10.
        for n in (2, 5, 1):
            mgr.update_request_metrics(req, RequestAction.DECODE_STEP,
                                       n_new=n)
        req.num_generated_tokens = 10
        mgr.update_request_metrics(req, RequestAction.FINISH_DECODE)
        assert mgr._request_loads["d1"].num_decode_requests == 0
        # Exact balance, not max(0, ...)-clamped drift: under-crediting
        # here collapses decode load toward phantom-idle over time.
        assert mgr._request_loads["d1"].num_decode_tokens == 0
        assert mgr._request_loads["p1"].num_prefill_tokens == 0
        mgr.stop()

    def test_cancel_before_first_token_leaks_no_decode_load(self, coord):
        """A request that errors/disconnects before producing a token must
        reverse only its SCHEDULE increments; it must NOT credit the decode
        instance with load (the FINISH_PREFILL path would, and that load
        would never be reversed — skewing SLO/CAR routing forever)."""
        mgr = make_mgr(coord)
        mgr.register_instance(make_meta("p1", InstanceType.PREFILL),
                              link_peers=False)
        mgr.register_instance(make_meta("d1", InstanceType.DECODE),
                              link_peers=False)
        req = Request(service_request_id="s1", token_ids=list(range(64)))
        req.routing.prefill_name = "p1"
        req.routing.decode_name = "d1"
        mgr.update_request_metrics(req, RequestAction.SCHEDULE)
        mgr.update_request_metrics(req, RequestAction.CANCEL)
        assert mgr._request_loads["p1"].num_prefill_requests == 0
        assert mgr._request_loads["p1"].num_prefill_tokens == 0
        assert mgr._request_loads["d1"].num_decode_requests == 0
        assert mgr._request_loads["d1"].num_decode_tokens == 0
        mgr.stop()


class TestRoleFlip:
    def test_flip_updates_coordination(self, coord):
        mgr = make_mgr(coord)
        mgr.register_instance(make_meta("i1", InstanceType.PREFILL),
                              link_peers=False)
        # Seed the coordination record as the engine would have.
        register_in_coord(coord, mgr.get_instance_meta("i1"))
        assert mgr.flip_instance_role("i1", InstanceType.DECODE)
        assert mgr.get_instance_meta("i1").type == InstanceType.DECODE
        from xllm_service_tpu.rpc import instance_key
        assert coord.get(instance_key("DECODE", "i1")) is not None
        assert coord.get(instance_key("PREFILL", "i1")) is None
        assert FakeChannel.registry["i1"].flips == ["DECODE"]
        mgr.stop()

    def test_flip_relinks_for_new_role(self, coord):
        """A flipped instance must be linked to the peers of its NEW role
        (the handoff gate rejects unlinked senders, so an unlinked flipped
        decode would 403 every KV transfer routed to it)."""
        mgr = make_mgr(coord)
        mgr.register_instance(make_meta("p1", InstanceType.PREFILL),
                              link_peers=False)
        mgr.register_instance(make_meta("p2", InstanceType.PREFILL),
                              link_peers=False)
        assert mgr.flip_instance_role("p2", InstanceType.DECODE)
        # Both directions of every new P<->D pair.
        assert "p2" in FakeChannel.registry["p1"].links
        assert "p1" in FakeChannel.registry["p2"].links
        mgr.stop()

    def test_flip_rejected_by_engine(self, coord):
        mgr = make_mgr(coord)
        mgr.register_instance(make_meta("i1", InstanceType.PREFILL),
                              link_peers=False)
        FakeChannel.registry["i1"].flip_ok = False
        assert not mgr.flip_instance_role("i1", InstanceType.DECODE)
        assert mgr.get_instance_meta("i1").type == InstanceType.PREFILL
        mgr.stop()


class TestLoadMetricsSync:
    def test_master_upload_and_replica_mirror(self, coord, store):
        master = make_mgr(coord)
        register_in_coord(coord, make_meta("i1"))
        assert wait_until(lambda: master.get_instance_meta("i1") is not None)
        master.record_instance_heartbeat(
            "i1", "", LoadMetrics(waiting_requests_num=7))
        master.upload_load_metrics()

        replica_coord = InMemoryCoordination(store)
        replica = InstanceMgr(replica_coord, fast_opts(), is_master=False,
                              channel_factory=FakeChannel.factory,
                              start_threads=False)
        assert wait_until(
            lambda: replica.get_load_infos().get("i1") is not None
            and replica.get_load_infos()["i1"].load.waiting_requests_num == 7)
        master.stop(); replica.stop(); replica_coord.close()
