"""Real-checkpoint serving drill (VERDICT r1 #9, hermetic variant): an
HF-layout checkpoint directory (safetensors shards + tokenizer.json +
tokenizer_config.json with chat template and added tokens) is loaded
through models/loader.py and served end-to-end — client → master (HF
tokenizer + Jinja template) → engine agent → SSE — exercising the full
tokenizer-args path with a real (non-Simple) tokenizer."""

import json

import jax.numpy as jnp
import pytest
import requests

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.types import InstanceType
from xllm_service_tpu.coordination.memory import InMemoryCoordination, MemoryStore
from xllm_service_tpu.engine.agent import AgentConfig, EngineAgent
from xllm_service_tpu.engine.config import EngineConfig
from xllm_service_tpu.master import Master
from xllm_service_tpu.models.base import tiny_config
from xllm_service_tpu.models.loader import load_hf_llama_safetensors
from xllm_service_tpu.tokenizer import TokenizerFactory
from xllm_service_tpu.tokenizer.factory import HFTokenizer

from fakes import wait_until
from test_loader import make_hf_checkpoint

TEMPLATE = ("{% for message in messages %}{{ message['role'] }} : "
            "{{ message['content'] }} \n{% endfor %}"
            "{% if add_generation_prompt %}assistant :{% endif %}")


def make_model_dir(tmp_path, cfg):
    """Checkpoint + HF tokenizer + config, one directory like a real
    HF model snapshot."""
    make_hf_checkpoint(tmp_path, cfg)

    from tokenizers import Tokenizer as HFTok
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    words = ["user", "assistant", "system", ":", "hello", "world",
             "what", "is", "up", "\n", "[UNK]", "<|eot|>"]
    vocab = {w: i for i, w in enumerate(words)}
    t = HFTok(WordLevel(vocab, unk_token="[UNK]"))
    t.pre_tokenizer = Whitespace()
    t.save(str(tmp_path / "tokenizer.json"))

    (tmp_path / "tokenizer_config.json").write_text(json.dumps({
        "chat_template": TEMPLATE,
        "eos_token": {"content": "<|eot|>"},
        "add_bos_token": False,
        "tokenizer_class": "PreTrainedTokenizerFast",
        "added_tokens_decoder": {
            str(vocab["<|eot|>"]): {"content": "<|eot|>"}},
    }))
    return tmp_path


@pytest.fixture(scope="module")
def ckpt_cluster(tmp_path_factory):
    model_dir = make_model_dir(
        tmp_path_factory.mktemp("model"),
        tiny_config(dtype=jnp.float32, max_context_len=256))
    cfg = tiny_config(dtype=jnp.float32, max_context_len=256)
    store = MemoryStore(expiry_tick_s=0.05)
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=1.0, sync_interval_s=0.3,
                          reconcile_interval_s=0.1,
                          tokenizer_path=str(model_dir))
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()
    params = load_hf_llama_safetensors(model_dir, cfg)
    ecfg = EngineConfig(
        model_id="ckpt-llama", model=cfg,
        num_pages=64, page_size=16, hash_block_size=32,
        max_batch_size=4, max_seq_len=256, prefill_buckets=(32, 64, 256))
    agent = EngineAgent(
        ecfg,
        AgentConfig(host="127.0.0.1", model_id="ckpt-llama",
                    instance_type=InstanceType.MIX,
                    tokenizer_path=str(model_dir),
                    heartbeat_interval_s=0.3, lease_ttl_s=1.0),
        coord=InMemoryCoordination(store), params=params)
    agent.start()
    assert wait_until(
        lambda: master.scheduler.instance_mgr.get_instance_meta(agent.name)
        is not None, timeout=10)
    yield master, agent, model_dir
    agent.stop()
    master.stop()
    store.close()


class TestCheckpointServing:
    def test_real_tokenizer_selected(self, ckpt_cluster):
        master, agent, model_dir = ckpt_cluster
        assert isinstance(master.scheduler.tokenizer, HFTokenizer)
        assert isinstance(agent.engine.tokenizer, HFTokenizer)
        assert TokenizerFactory.load_chat_template(str(model_dir)) == \
            TEMPLATE

    def test_chat_completion_over_checkpoint(self, ckpt_cluster):
        master, agent, model_dir = ckpt_cluster
        base = f"http://127.0.0.1:{master.http_port}"
        r = requests.post(base + "/v1/chat/completions", json={
            "model": "ckpt-llama",
            "messages": [{"role": "user", "content": "hello world"}],
            "max_tokens": 8, "temperature": 0, "ignore_eos": True,
        }, timeout=120)
        assert r.status_code == 200, r.text
        body = r.json()
        choice = body["choices"][0]
        assert choice["finish_reason"] == "length"
        # Prompt tokenized by the HF tokenizer through the rendered
        # template: "user : hello world \n assistant :".
        tok = master.scheduler.tokenizer
        rendered = master.scheduler.chat_template.apply(
            [{"role": "user", "content": "hello world"}])
        assert "user" in rendered and "assistant" in rendered
        assert body["usage"]["prompt_tokens"] == len(tok.encode(rendered))
        # Output decodes through the same vocab (WordLevel ids -> words).
        assert isinstance(choice["message"]["content"], str)

    def test_served_output_matches_direct_forward(self, ckpt_cluster):
        """The served greedy continuation equals running the loaded
        checkpoint directly through the engine (weights really came from
        the safetensors, not random init)."""
        import threading

        from xllm_service_tpu.common.request import SamplingParams
        from xllm_service_tpu.engine.engine import (
            EngineRequest,
            InferenceEngine,
        )

        master, agent, model_dir = ckpt_cluster
        base = f"http://127.0.0.1:{master.http_port}"
        prompt = "what is up"
        r = requests.post(base + "/v1/completions", json={
            "model": "ckpt-llama", "prompt": prompt,
            "max_tokens": 6, "temperature": 0, "ignore_eos": True,
        }, timeout=120)
        assert r.status_code == 200, r.text
        served_text = r.json()["choices"][0]["text"]

        cfg = tiny_config(dtype=jnp.float32, max_context_len=256)
        params = load_hf_llama_safetensors(model_dir, cfg)
        engine = InferenceEngine(
            EngineConfig(model_id="direct", model=cfg, num_pages=64,
                         page_size=16, hash_block_size=32, max_batch_size=4,
                         max_seq_len=256, prefill_buckets=(32, 64, 256)),
            tokenizer=TokenizerFactory.create_tokenizer(str(model_dir)),
            params=params)
        done = threading.Event()
        texts = []

        def cb(out):
            texts.extend(s.text for s in out.outputs)
            if out.finished:
                done.set()

        engine.submit(EngineRequest(
            "direct", token_ids=engine.tokenizer.encode(prompt),
            sampling=SamplingParams(max_tokens=6, temperature=0.0,
                                    ignore_eos=True),
            on_output=cb))
        for _ in range(300):
            if done.is_set():
                break
            engine.step()
        assert done.is_set()
        assert "".join(texts) == served_text
