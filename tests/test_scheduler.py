"""Scheduler tests: schedule→generation flow, streaming/non-stream delivery,
disconnect cancellation, failure cancel-and-surface, master election."""

import pytest

from xllm_service_tpu.common.call_data import CollectingConnection
from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.request import (
    Request,
    RequestOutput,
    SequenceOutput,
    Status,
    StatusCode,
    Usage,
)
from xllm_service_tpu.common.types import InstanceType
from xllm_service_tpu.coordination.memory import InMemoryCoordination
from xllm_service_tpu.scheduler.scheduler import Scheduler

from fakes import FakeChannel, make_meta, wait_until


@pytest.fixture(autouse=True)
def _reset_channels():
    FakeChannel.reset()
    yield
    FakeChannel.reset()


def make_scheduler(store, **kw):
    coord = InMemoryCoordination(store)
    opts = ServiceOptions(reconcile_interval_s=0.05, sync_interval_s=0.1,
                          lease_ttl_s=0.2, **kw)
    sched = Scheduler(opts, coord=coord, start_threads=False)
    # Swap in fake channels.
    sched.instance_mgr._channel_factory = FakeChannel.factory
    return sched


def fleet(sched, *metas):
    for m in metas:
        sched.instance_mgr.register_instance(m, link_peers=False)


def _drain(sched):
    sched._output_executor.drain()


class TestScheduleFlow:
    def test_schedule_tokenizes_and_routes(self, store):
        sched = make_scheduler(store)
        fleet(sched, make_meta("m1", InstanceType.MIX))
        req = Request(service_request_id="s1", prompt="hello world")
        st = sched.schedule(req)
        assert st.ok()
        assert req.token_ids
        assert req.routing.prefill_name == "m1"
        assert req.prefill_incarnation
        sched.stop()

    def test_schedule_applies_chat_template(self, store):
        sched = make_scheduler(store)
        fleet(sched, make_meta("m1", InstanceType.MIX))
        req = Request(service_request_id="s1",
                      messages=[{"role": "user", "content": "hi"}])
        assert sched.schedule(req).ok()
        assert "<|im_start|>user" in req.prompt
        sched.stop()

    def test_schedule_no_instances(self, store):
        sched = make_scheduler(store)
        st = sched.schedule(Request(service_request_id="s1", prompt="x"))
        assert st.code == StatusCode.UNAVAILABLE
        sched.stop()

    def test_streaming_generation_delivery(self, store):
        sched = make_scheduler(store)
        fleet(sched, make_meta("m1", InstanceType.MIX))
        req = Request(service_request_id="s1", request_id="chatcmpl-1",
                      model="m", stream=True, prompt="hi")
        assert sched.schedule(req).ok()
        conn = CollectingConnection(stream=True)
        sched.record_new_request(req, conn, "chat")
        assert sched.handle_generation(RequestOutput(
            service_request_id="s1",
            outputs=[SequenceOutput(index=0, text="he", token_ids=[1])]))
        assert sched.handle_generation(RequestOutput(
            service_request_id="s1",
            outputs=[SequenceOutput(index=0, text="llo", token_ids=[2],
                                    finish_reason="stop")],
            usage=Usage(1, 2), finished=True))
        _drain(sched)
        assert conn.finished
        content = "".join(
            c["choices"][0]["delta"].get("content") or ""
            for c in conn.payloads if c.get("choices"))
        assert content == "hello"
        assert not sched.has_request("s1")
        # Unknown request now -> engine told to stop.
        assert not sched.handle_generation(RequestOutput(
            service_request_id="s1",
            outputs=[SequenceOutput(index=0, text="x")]))
        sched.stop()

    def test_non_stream_aggregation(self, store):
        sched = make_scheduler(store)
        fleet(sched, make_meta("m1", InstanceType.MIX))
        req = Request(service_request_id="s2", request_id="cmpl-1",
                      model="m", stream=False, prompt="hi")
        assert sched.schedule(req).ok()
        conn = CollectingConnection()
        sched.record_new_request(req, conn, "completion")
        for i, (txt, fin) in enumerate([("a", ""), ("b", ""), ("c", "stop")]):
            sched.handle_generation(RequestOutput(
                service_request_id="s2",
                outputs=[SequenceOutput(index=0, text=txt, token_ids=[i],
                                        finish_reason=fin)],
                finished=bool(fin)))
        _drain(sched)
        assert conn.finished
        assert conn.payloads[0]["choices"][0]["text"] == "abc"
        assert conn.payloads[0]["usage"]["completion_tokens"] == 3
        sched.stop()

    def test_disconnect_cancels_on_engine(self, store):
        sched = make_scheduler(store)
        fleet(sched, make_meta("m1", InstanceType.MIX))
        req = Request(service_request_id="s3", request_id="r", model="m",
                      stream=True, prompt="hi")
        assert sched.schedule(req).ok()
        conn = CollectingConnection(stream=True)
        sched.record_new_request(req, conn, "chat")
        conn.disconnected = True
        assert not sched.handle_generation(RequestOutput(
            service_request_id="s3",
            outputs=[SequenceOutput(index=0, text="x", token_ids=[1])]))
        assert "s3" in FakeChannel.registry["m1"].cancels
        assert not sched.has_request("s3")
        sched.stop()

    def test_duplicate_delta_seq_dropped(self, store):
        """A retried Generations POST (same delta_seq) must be acked but
        not re-delivered or re-counted."""
        sched = make_scheduler(store)
        fleet(sched, make_meta("m1", InstanceType.MIX))
        req = Request(service_request_id="d1", request_id="r", model="m",
                      stream=True, prompt="hi")
        assert sched.schedule(req).ok()
        conn = CollectingConnection(stream=True)
        sched.record_new_request(req, conn, "chat")
        out = RequestOutput(
            service_request_id="d1", request_id="r", delta_seq=1,
            outputs=[SequenceOutput(index=0, text="x", token_ids=[1])])
        assert sched.handle_generation(out)
        assert sched.handle_generation(out)   # duplicate: acked, dropped
        _drain(sched)
        assert req.num_generated_tokens == 1
        texts = [p for p in conn.payloads
                 if p["choices"][0]["delta"].get("content") == "x"]
        assert len(texts) == 1
        sched.stop()

    def test_pre_token_exit_paths_leak_no_load(self, store):
        """Disconnect, error, and GC-timeout before the first token must
        leave all load accounting at zero (ADVICE r1: FINISH_PREFILL on
        those paths leaked decode load; GC leaked prefill load)."""
        sched = make_scheduler(store, request_timeout_s=0.0)
        fleet(sched, make_meta("m1", InstanceType.MIX))

        def loads():
            rl = sched.instance_mgr._request_loads.get("m1")
            if rl is None:
                return (0, 0, 0, 0)
            return (rl.num_prefill_requests, rl.num_prefill_tokens,
                    rl.num_decode_requests, rl.num_decode_tokens)

        # Disconnect path.
        req = Request(service_request_id="g1", request_id="r", model="m",
                      stream=True, prompt="hi")
        assert sched.schedule(req).ok()
        conn = CollectingConnection(stream=True)
        sched.record_new_request(req, conn, "chat")
        conn.disconnected = True
        sched.handle_generation(RequestOutput(
            service_request_id="g1",
            outputs=[SequenceOutput(index=0, text="x", token_ids=[1])]))
        assert loads() == (0, 0, 0, 0)

        # Error-status path.
        req = Request(service_request_id="g2", request_id="r", model="m",
                      stream=False, prompt="hi")
        assert sched.schedule(req).ok()
        sched.record_new_request(req, CollectingConnection(), "chat")
        sched.handle_generation(RequestOutput(
            service_request_id="g2",
            status=Status(StatusCode.RESOURCE_EXHAUSTED, "full"),
            finished=True))
        _drain(sched)
        assert loads() == (0, 0, 0, 0)

        # GC-timeout path (request_timeout_s=0 → instantly stale).
        req = Request(service_request_id="g3", request_id="r", model="m",
                      stream=False, prompt="hi")
        assert sched.schedule(req).ok()
        sched.record_new_request(req, CollectingConnection(), "chat")
        req.latest_generate_time_ms -= 1
        sched._gc_stale_requests()
        _drain(sched)
        assert not sched.has_request("g3")
        assert loads() == (0, 0, 0, 0)
        sched.stop()

    def test_error_status_surfaces(self, store):
        sched = make_scheduler(store)
        fleet(sched, make_meta("m1", InstanceType.MIX))
        req = Request(service_request_id="s4", request_id="r", model="m",
                      stream=False, prompt="hi")
        sched.schedule(req)
        conn = CollectingConnection()
        sched.record_new_request(req, conn, "chat")
        sched.handle_generation(RequestOutput(
            service_request_id="s4",
            status=Status(StatusCode.RESOURCE_EXHAUSTED, "kv pool full"),
            finished=True))
        _drain(sched)
        assert conn.error is not None
        assert "kv pool full" in conn.error[1]
        sched.stop()


class TestFailurePath:
    def test_clear_requests_on_failed_instance(self, store):
        sched = make_scheduler(store)
        fleet(sched, make_meta("p1", InstanceType.PREFILL, incarnation_id="I1"),
              make_meta("d1", InstanceType.DECODE, incarnation_id="I2"))
        req = Request(service_request_id="s5", request_id="r", model="m",
                      stream=True, prompt="hi")
        assert sched.schedule(req).ok()
        conn = CollectingConnection(stream=True)
        sched.record_new_request(req, conn, "chat")
        sched.clear_requests_on_failed_instance(
            req.routing.decode_name, "I2", InstanceType.DECODE)
        _drain(sched)
        assert conn.error is not None and conn.error[0] == 503
        assert not sched.has_request("s5")
        sched.stop()

    def test_failure_of_unrelated_incarnation_spares_request(self, store):
        sched = make_scheduler(store)
        fleet(sched, make_meta("m1", InstanceType.MIX, incarnation_id="I1"))
        req = Request(service_request_id="s6", request_id="r", model="m",
                      stream=True, prompt="hi")
        assert sched.schedule(req).ok()
        conn = CollectingConnection(stream=True)
        sched.record_new_request(req, conn, "chat")
        sched.clear_requests_on_failed_instance("m1", "OTHER", InstanceType.MIX)
        _drain(sched)
        assert conn.error is None
        assert sched.has_request("s6")
        sched.stop()

    def test_heartbeat_feeds_kvcache_mgr(self, store):
        sched = make_scheduler(store)
        fleet(sched, make_meta("m1", InstanceType.MIX, incarnation_id="I1"))
        from xllm_service_tpu.common.hashing import prefix_block_hash_hexes

        toks = list(range(128))
        hashes = prefix_block_hash_hexes(toks, 128)
        assert sched.handle_instance_heartbeat({
            "name": "m1", "incarnation_id": "I1",
            "load_metrics": {"waiting_requests_num": 2},
            "kv_cache_event": {"stored": hashes, "removed": [], "offloaded": []},
        })
        assert sched.kvcache_mgr.match(toks).scores.get("m1") == 1.0
        # Unknown instance heartbeat rejected.
        assert not sched.handle_instance_heartbeat({"name": "ghost",
                                                    "incarnation_id": "x"})
        sched.stop()


class TestMasterElection:
    def test_first_is_master_second_replica_takeover(self, store):
        s1 = make_scheduler(store, rpc_port=9001)
        assert s1.is_master
        s2 = make_scheduler(store, rpc_port=9002)
        assert not s2.is_master
        s1.stop()   # releases master lease -> s2 takes over via watch
        assert wait_until(lambda: s2.is_master, timeout=3.0)
        s2.stop()
