"""Fault-plane wiring tests for the I/O layers: engine-channel retry
backoff (idempotent vs non-idempotent), and coordination-client reconnect
with list-then-watch resync after an injected connection blip."""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from xllm_service_tpu.common.faults import FAULTS
from xllm_service_tpu.common.metrics import RPC_RETRIES_TOTAL
from xllm_service_tpu.coordination.base import WatchEventType
from xllm_service_tpu.coordination.client import TcpCoordinationClient
from xllm_service_tpu.coordination.server import CoordinationServer
from xllm_service_tpu.rpc.channel import EngineChannel

from fakes import wait_until


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.clear()
    yield
    FAULTS.clear()


# --------------------------------------------------------------- channel
class _CountingHandler(BaseHTTPRequestHandler):
    posts: list[str] = []

    def do_POST(self):  # noqa: N802 — stdlib API
        _CountingHandler.posts.append(self.path)
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        body = json.dumps({"ok": True}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # silence
        pass


@pytest.fixture()
def http_target():
    _CountingHandler.posts = []
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _CountingHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


class TestChannelRetries:
    def test_post_retries_with_backoff_until_success(self, http_target):
        ch = EngineChannel(http_target, retries=3,
                           backoff_base_s=0.01, backoff_max_s=0.05)
        rule = FAULTS.add("rpc.post", action="drop", max_fires=2)
        before = RPC_RETRIES_TOTAL.value()
        start = time.monotonic()
        assert ch.cancel("sid-x")       # 3rd attempt lands
        elapsed = time.monotonic() - start
        assert rule.fires == 2
        assert _CountingHandler.posts == ["/rpc/cancel"]
        assert RPC_RETRIES_TOTAL.value() == before + 2
        assert elapsed >= 0.01          # backed off between attempts
        ch.close()

    def test_get_retries(self, http_target):
        ch = EngineChannel(http_target, retries=2,
                           backoff_base_s=0.01, backoff_max_s=0.02)
        rule = FAULTS.add("rpc.get", action="error", max_fires=1)
        ok, body = ch._get("/anything")   # server 501s GET → retried once,
        assert rule.fires == 1            # then real HTTP error surfaces
        assert not ok
        ch.close()

    def test_forward_is_single_shot(self, http_target):
        """Non-idempotent generation forwards must NOT be retried by the
        channel on ambiguous failures — replay belongs to the failover
        layer."""
        ch = EngineChannel(http_target, retries=3,
                           backoff_base_s=0.01, backoff_max_s=0.02)
        rule = FAULTS.add("rpc.post", action="error")
        ok, err = ch.forward("/v1/completions", {"prompt": "x"})
        assert not ok and "fault injected" in str(err)
        assert rule.fires == 1          # exactly one attempt
        assert _CountingHandler.posts == []
        ch.close()

    def test_health_single_probe(self, http_target):
        """InstanceMgr owns probe retries; the channel must not multiply
        them."""
        ch = EngineChannel(http_target, retries=3)
        rule = FAULTS.add("rpc.get", action="error")
        assert not ch.health()
        assert rule.fires == 1
        ch.close()


# ---------------------------------------------------------- coordination
class _Sink:
    def __init__(self):
        self.events = []
        self.cv = threading.Condition()

    def __call__(self, events, prefix):
        with self.cv:
            self.events.extend(events)
            self.cv.notify_all()

    def keys(self, type_=None):
        with self.cv:
            return [e.key for e in self.events
                    if type_ is None or e.type == type_]


@pytest.fixture()
def coord_server():
    srv = CoordinationServer(host="127.0.0.1", port=0)
    srv.start_background()
    yield srv
    srv.shutdown()


class TestWatchResyncAfterBlip:
    def test_blip_does_not_freeze_discovery(self, coord_server):
        """Sever the watcher's connection (fault plane), hold reconnect
        down for a few rounds, mutate the keyspace from another client in
        the meantime, then let reconnect succeed: the resync must deliver
        the missed PUT and DELETE."""
        addr = f"127.0.0.1:{coord_server.port}"
        watcher = TcpCoordinationClient(addr)
        writer = TcpCoordinationClient(addr)
        try:
            sink = _Sink()
            watcher.add_watch("INST:", sink)
            assert writer.set("INST:a", "1")
            assert wait_until(lambda: "INST:a" in sink.keys(), timeout=5)

            # Blip: next call severs the socket; the first 3 reconnect
            # attempts are refused — a deterministic outage window.
            FAULTS.configure([
                dict(point="coord.call", action="disconnect", max_fires=1),
                dict(point="coord.connect", action="error", max_fires=3),
            ])
            watcher.get("INST:a")   # trips the disconnect
            # Mutations the watcher cannot see while down:
            assert writer.set("INST:b", "2")
            assert writer.rm("INST:a")

            assert wait_until(
                lambda: "INST:b" in sink.keys(WatchEventType.PUT)
                and "INST:a" in sink.keys(WatchEventType.DELETE),
                timeout=10), sink.events
            # And the connection is live again end-to-end.
            assert watcher.get("INST:b") == "2"
        finally:
            watcher.close()
            writer.close()

    def test_plain_reconnect_resumes_watch_stream(self, coord_server):
        """After a blip with no missed events, later watch pushes still
        arrive (re-subscription works and resync is a no-op)."""
        addr = f"127.0.0.1:{coord_server.port}"
        watcher = TcpCoordinationClient(addr)
        writer = TcpCoordinationClient(addr)
        try:
            sink = _Sink()
            watcher.add_watch("K:", sink)
            FAULTS.configure([
                dict(point="coord.call", action="disconnect", max_fires=1)])
            watcher.get("K:x")      # blip
            FAULTS.clear()
            assert wait_until(lambda: watcher.get("K:x") is None, timeout=5)
            assert writer.set("K:x", "v")
            assert wait_until(lambda: "K:x" in sink.keys(), timeout=5)
        finally:
            watcher.close()
            writer.close()
