"""Gemma family: paged incremental decode == full prefill, the gemma
config switches actually alter the computation, engine serving, and
softcap behavior."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xllm_service_tpu.models.base import get_model_family, tiny_config
from xllm_service_tpu.models.gemma import gemma_tiny_config

PAGE = 16


def gemma_tiny(**kw):
    kw.setdefault("dtype", jnp.float32)
    return gemma_tiny_config(**kw)


def alloc_pages(cfg, num_pages):
    return jnp.zeros((cfg.num_layers, 2, num_pages, cfg.num_kv_heads,
                      PAGE, cfg.head_dim), cfg.dtype)


@pytest.fixture(scope="module")
def setup():
    cfg = gemma_tiny()
    fam = get_model_family("gemma")
    params = fam.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, fam, params


class TestGemmaPagedCorrectness:
    def test_decode_matches_full_prefill(self, setup):
        cfg, fam, params = setup
        T = 21
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, T), 0,
                                  cfg.vocab_size)
        pt = jnp.arange(8, dtype=jnp.int32)[None, :]
        pos = jnp.arange(T)[None, :]
        kv = alloc_pages(cfg, 8)
        logits_full, _ = fam.prefill_forward(
            params, cfg, toks, pos, kv, pt,
            jnp.zeros((1,), jnp.int32), jnp.array([T], jnp.int32))
        kv2 = alloc_pages(cfg, 8)
        _, kv2 = fam.prefill_forward(
            params, cfg, toks[:, :T - 1], pos[:, :T - 1], kv2, pt,
            jnp.zeros((1,), jnp.int32), jnp.array([T - 1], jnp.int32))
        logits_dec, _ = fam.decode_forward(
            params, cfg, toks[:, T - 1], jnp.array([T - 1], jnp.int32),
            kv2, pt, jnp.array([T], jnp.int32))
        np.testing.assert_allclose(np.asarray(logits_dec),
                                   np.asarray(logits_full),
                                   rtol=2e-4, atol=2e-4)

    def test_gemma_switches_change_the_math(self, setup):
        """Same weights under llama semantics must give different logits
        — guards against the config switches silently not applying."""
        cfg, fam, params = setup
        plain = tiny_config(dtype=jnp.float32, tie_embeddings=True)
        T = 8
        toks = jax.random.randint(jax.random.PRNGKey(2), (1, T), 0, 512)
        pt = jnp.arange(4, dtype=jnp.int32)[None, :]
        pos = jnp.arange(T)[None, :]

        def run(c):
            kv = alloc_pages(c, 4)
            logits, _ = fam.prefill_forward(
                params, c, toks, pos, kv, pt,
                jnp.zeros((1,), jnp.int32), jnp.array([T], jnp.int32))
            return np.asarray(logits)

        assert np.abs(run(cfg) - run(plain)).max() > 1e-3

    def test_softcap_bounds_logits(self, setup):
        cfg, fam, params = setup
        # Scale weights up so uncapped logits would exceed the cap.
        big = jax.tree.map(lambda a: a * 4.0, params)
        T = 6
        toks = jax.random.randint(jax.random.PRNGKey(3), (1, T), 0, 512)
        kv = alloc_pages(cfg, 4)
        logits, _ = fam.prefill_forward(
            big, cfg, toks, jnp.arange(T)[None, :], kv,
            jnp.arange(4, dtype=jnp.int32)[None, :],
            jnp.zeros((1,), jnp.int32), jnp.array([T], jnp.int32))
        assert float(jnp.max(jnp.abs(logits))) <= cfg.final_logit_softcap


class TestGemma2:
    """Gemma-2 extras: sliding-window/global alternation, attention-score
    softcap, query scale, sandwich norms — all on the shared llama body."""

    @pytest.fixture(scope="class")
    def setup2(self):
        from xllm_service_tpu.models.gemma import gemma2_tiny_config
        cfg = gemma2_tiny_config(dtype=jnp.float32)
        fam = get_model_family("gemma")
        params = fam.init_params(cfg, jax.random.PRNGKey(0))
        return cfg, fam, params

    def test_config_layer_pattern(self, setup2):
        cfg, _, _ = setup2
        assert [cfg.layer_is_local(l) for l in range(4)] == \
            [True, False, True, False]

    def test_sandwich_params_exist(self, setup2):
        cfg, _, params = setup2
        assert "pre_ffw_norm" in params["layers"]
        assert "post_ffw_norm" in params["layers"]

    def test_decode_matches_full_prefill(self, setup2):
        """Incremental decode == one-shot prefill, with T far past the
        window so local layers genuinely mask (window=8, T=21)."""
        cfg, fam, params = setup2
        T = 21
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, T), 0,
                                  cfg.vocab_size)
        pt = jnp.arange(8, dtype=jnp.int32)[None, :]
        pos = jnp.arange(T)[None, :]
        logits_full, _ = fam.prefill_forward(
            params, cfg, toks, pos, alloc_pages(cfg, 8), pt,
            jnp.zeros((1,), jnp.int32), jnp.array([T], jnp.int32))
        kv2 = alloc_pages(cfg, 8)
        _, kv2 = fam.prefill_forward(
            params, cfg, toks[:, :T - 1], pos[:, :T - 1], kv2, pt,
            jnp.zeros((1,), jnp.int32), jnp.array([T - 1], jnp.int32))
        logits_dec, _ = fam.decode_forward(
            params, cfg, toks[:, T - 1], jnp.array([T - 1], jnp.int32),
            kv2, pt, jnp.array([T], jnp.int32))
        np.testing.assert_allclose(np.asarray(logits_dec),
                                   np.asarray(logits_full),
                                   rtol=2e-4, atol=2e-4)

    def test_chunked_prefill_matches(self, setup2):
        """Prefix-cached continuation crosses the window boundary: the
        second chunk's queries must see only the trailing window of the
        cached prefix on local layers."""
        cfg, fam, params = setup2
        T, split = 20, 13
        toks = jax.random.randint(jax.random.PRNGKey(4), (1, T), 0,
                                  cfg.vocab_size)
        pt = jnp.arange(8, dtype=jnp.int32)[None, :]
        pos = jnp.arange(T)[None, :]
        logits_full, _ = fam.prefill_forward(
            params, cfg, toks, pos, alloc_pages(cfg, 8), pt,
            jnp.zeros((1,), jnp.int32), jnp.array([T], jnp.int32))
        kv = alloc_pages(cfg, 8)
        _, kv = fam.prefill_forward(
            params, cfg, toks[:, :split], pos[:, :split], kv, pt,
            jnp.zeros((1,), jnp.int32), jnp.array([split], jnp.int32))
        logits_chunk, _ = fam.prefill_forward(
            params, cfg, toks[:, split:], pos[:, split:], kv, pt,
            jnp.array([split], jnp.int32), jnp.array([T - split], jnp.int32))
        np.testing.assert_allclose(np.asarray(logits_chunk),
                                   np.asarray(logits_full),
                                   rtol=2e-4, atol=2e-4)

    def test_sliding_window_changes_long_context(self, setup2):
        """Windowing must alter logits once T > window but leave T <=
        window untouched (vs the same config with the window off)."""
        cfg, fam, params = setup2
        nowin = gemma2_nowindow(cfg)

        def run(c, T, key):
            toks = jax.random.randint(jax.random.PRNGKey(key), (1, T), 0,
                                      c.vocab_size)
            logits, _ = fam.prefill_forward(
                params, c, toks, jnp.arange(T)[None, :],
                alloc_pages(c, 8), jnp.arange(8, dtype=jnp.int32)[None, :],
                jnp.zeros((1,), jnp.int32), jnp.array([T], jnp.int32))
            return np.asarray(logits)

        # T=6 <= window=8: identical.
        np.testing.assert_allclose(run(cfg, 6, 7), run(nowin, 6, 7),
                                   rtol=1e-5, atol=1e-5)
        # T=20 > window: the local layers mask, logits diverge.
        assert np.abs(run(cfg, 20, 8) - run(nowin, 20, 8)).max() > 1e-4

    def test_seq_parallel_mesh_refused(self, setup2):
        from xllm_service_tpu.engine.config import EngineConfig
        from xllm_service_tpu.engine.engine import InferenceEngine
        from xllm_service_tpu.parallel.mesh import MeshConfig, build_mesh
        cfg, _, _ = setup2
        mesh = build_mesh(MeshConfig(seq=2), devices=jax.devices()[:2])
        with pytest.raises(ValueError, match="seq-axis"):
            InferenceEngine(EngineConfig(
                model_family="gemma", model=cfg, num_pages=32, page_size=16,
                hash_block_size=32, max_batch_size=2, max_seq_len=128,
                prefill_buckets=(128,), decode_horizon=2), mesh=mesh)

    def test_engine_serves_gemma2(self):
        from test_engine import Collector, run_requests
        from xllm_service_tpu.common.request import SamplingParams
        from xllm_service_tpu.engine.config import EngineConfig
        from xllm_service_tpu.engine.engine import (
            EngineRequest,
            InferenceEngine,
        )
        from xllm_service_tpu.models.gemma import gemma2_tiny_config

        cfg = EngineConfig(
            model_family="gemma",
            model=gemma2_tiny_config(max_context_len=128),
            num_pages=64, page_size=16, hash_block_size=32,
            max_batch_size=2, max_seq_len=128,
            prefill_buckets=(32, 64, 128), decode_horizon=4)
        engine = InferenceEngine(cfg)
        col = Collector()
        run_requests(engine, [EngineRequest(
            service_request_id="g2", token_ids=list(range(3, 40)),
            sampling=SamplingParams(max_tokens=8, temperature=0.0),
            on_output=col)])
        assert len(col.tokens) == 8
        assert col.finish_reason == "length"


def gemma2_nowindow(cfg):
    """Same gemma-2 config with the sliding window disabled."""
    import dataclasses
    return dataclasses.replace(cfg, sliding_window=0,
                               sliding_window_pattern=0)


class TestGemmaEngine:
    def test_engine_serves_gemma(self):
        from test_engine import Collector, run_requests
        from xllm_service_tpu.common.request import SamplingParams
        from xllm_service_tpu.engine.config import EngineConfig
        from xllm_service_tpu.engine.engine import (
            EngineRequest,
            InferenceEngine,
        )

        cfg = EngineConfig(
            model_family="gemma", model=gemma_tiny(max_context_len=128),
            num_pages=64, page_size=16, hash_block_size=32,
            max_batch_size=2, max_seq_len=128,
            prefill_buckets=(32, 64, 128), decode_horizon=4)
        engine = InferenceEngine(cfg)
        col = Collector()
        run_requests(engine, [EngineRequest(
            service_request_id="g0", token_ids=[5, 7, 9, 11, 13],
            sampling=SamplingParams(max_tokens=8, temperature=0.0),
            on_output=col)])
        assert len(col.tokens) == 8
        assert col.finish_reason == "length"
