"""Chunked prefill + multimodal composition: a long VL prompt written
chunk-by-chunk (each chunk consuming its own slice of the visual
embeddings) must produce exactly the same output as whole-suffix prefill,
including placeholder runs that straddle chunk boundaries."""

import threading

import jax
import jax.numpy as jnp
import numpy as np

from xllm_service_tpu.common.request import RequestOutput, SamplingParams
from xllm_service_tpu.engine.config import EngineConfig
from xllm_service_tpu.engine.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.models.qwen2_vl import tiny_vl_config

IMG_TOK = 100


def make_vl_engine(chunk=0) -> InferenceEngine:
    return InferenceEngine(EngineConfig(
        model_id="tiny-vl", model_family="qwen2_vl",
        model=tiny_vl_config(dtype=jnp.float32, max_context_len=256,
                             image_token_id=IMG_TOK),
        num_pages=64, page_size=16, hash_block_size=32,
        max_batch_size=2, max_seq_len=256, prefill_buckets=(16, 32, 64, 256),
        prefill_chunk_tokens=chunk))


class Collector:
    def __init__(self):
        self.outputs: list[RequestOutput] = []
        self.done = threading.Event()

    def __call__(self, out: RequestOutput) -> None:
        self.outputs.append(out)
        if out.finished:
            self.done.set()

    @property
    def tokens(self):
        return [t for o in self.outputs for s in o.outputs
                for t in s.token_ids]


def run_one(engine, prompt, mm, n=5):
    col = Collector()
    engine.submit(EngineRequest(
        "vl1", token_ids=list(prompt),
        sampling=SamplingParams(max_tokens=n, temperature=0.0,
                                ignore_eos=True),
        mm_embeds=mm, on_output=col))
    for _ in range(400):
        if col.done.is_set():
            break
        engine.step()
    assert col.done.is_set()
    return col.tokens


def make_prompt_and_mm(cfg):
    """~60-token prompt with two placeholder runs, one of which straddles
    the 16-token chunk boundary."""
    D = cfg.hidden_size
    n_mm = 6
    rng = np.random.default_rng(0)
    mm = rng.normal(size=(n_mm, D)).astype(np.float32)
    prompt = (list(range(10, 22)) + [IMG_TOK] * 3      # run crosses t=16
              + list(range(30, 55)) + [IMG_TOK] * 3
              + list(range(60, 77)))
    assert prompt.count(IMG_TOK) == n_mm
    return prompt, mm


class TestChunkedMultimodal:
    def test_chunked_matches_unchunked(self):
        base = make_vl_engine(0)
        prompt, mm = make_prompt_and_mm(base.cfg.model)
        want = run_one(base, prompt, mm)

        chunked = make_vl_engine(16)
        spy = {"chunks": 0}
        real = chunked._prefill_chunk

        def wrap(*a):
            spy["chunks"] += 1
            return real(*a)

        chunked._prefill_chunk = wrap
        got = run_one(chunked, prompt, mm)
        assert spy["chunks"] >= 2, "prompt was not actually chunked"
        assert got == want

    def test_warmup_covers_image_variant(self):
        """VL warmup must pre-compile the image-carrying program variant
        too (its mm operand is unit-padded, a different shape from the
        no-image dummy), and a post-warmup image request must match a
        cold engine's output (ADVICE r2: image variants stayed cold)."""
        cold = make_vl_engine(0)
        prompt, mm = make_prompt_and_mm(cold.cfg.model)
        want = run_one(cold, prompt, mm)

        import dataclasses
        warm = InferenceEngine(dataclasses.replace(
            make_vl_engine(0).cfg, warmup_programs=True))
        unit = max(1, warm.cfg.model.vision.out_tokens * 4)
        seen = set()
        real = warm._prefill_install

        def spy(params, dstate, packed, mm_arr):
            seen.add(mm_arr.shape[1])
            return real(params, dstate, packed, mm_arr)

        warm._prefill_install = spy
        warm._warmup_programs()
        assert {1, unit} <= seen, f"warmup mm widths: {seen}"
        assert run_one(warm, prompt, mm) == want

    def test_different_images_still_differ_when_chunked(self):
        engine = make_vl_engine(16)
        prompt, mm = make_prompt_and_mm(engine.cfg.model)
        out1 = run_one(engine, prompt, mm)
        mm2 = np.random.default_rng(9).normal(
            size=mm.shape).astype(np.float32)
        out2 = run_one(engine, [t + 1 if t < IMG_TOK else t
                                for t in prompt], mm2)
        # (different prompt+images -> overwhelmingly different tokens;
        # guards against the splice silently ignoring mm in chunks)
        assert out1 != out2
