"""Tier-1 master hot-path budget gate.

Runs the fake-engine multiproc hot-path bench (coordination server,
master, fake engine — real OS processes, zero model compute) with a small
workload and a DELIBERATELY generous ceiling: the point is to catch an
order-of-magnitude regression on the master+wire span (a blocking call
sneaking onto the schedule path, a lost executor, a per-delta connect)
without flaking on CI-box noise. Current p50 on a loaded 2-core container
is ~15-40 ms; the ceiling is 10x that.
"""

import pytest

from benchmarks.master_hotpath_bench import run_bench

# Generous CI ceilings (ms): order-of-magnitude guards, not perf targets.
TTFT_P50_CEILING_MS = 400.0
STAGE_P50_CEILING_MS = 250.0


@pytest.fixture(scope="module")
def report():
    return run_bench(requests_n=24, concurrency=2, prompt_chars=512,
                     max_tokens=8, reply_chars=32)


def test_master_hotpath_budget(report):
    assert report["errors"] == 0, report
    p50 = report["master_wire_ttft_ms"]["p50"]
    assert p50 < TTFT_P50_CEILING_MS, (
        f"master+wire TTFT p50 {p50:.1f} ms blew the CI budget "
        f"({TTFT_P50_CEILING_MS} ms) — a blocking call or lost executor "
        f"on the hot path? Run benchmarks/master_hotpath_bench.py and "
        f"read the per-stage table.")


def test_master_hotpath_stage_table(report):
    stages = report.get("master_stages_ms")
    assert stages, "master /admin/hotpath served no stage table"
    for stage in ("schedule", "enrich", "forward", "first_delta"):
        row = stages.get(stage)
        assert row and row["n"] > 0, f"stage {stage} recorded no samples"
        assert row["p50"] < STAGE_P50_CEILING_MS, (
            f"stage {stage} p50 {row['p50']:.1f} ms blew the CI budget")
