"""Mixtral family: no-shared-expert MoE with GQA attention — paged
decode consistency, expert-parallel parity, engine serving."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xllm_service_tpu.models.base import get_model_family
from xllm_service_tpu.models.mixtral import mixtral_tiny_config

PAGE = 16


def alloc_pages(cfg, num_pages):
    return jnp.zeros((cfg.num_layers, 2, num_pages, cfg.num_kv_heads,
                      PAGE, cfg.head_dim), cfg.dtype)


@pytest.fixture(scope="module")
def setup():
    cfg = mixtral_tiny_config(dtype=jnp.float32)
    fam = get_model_family("mixtral")
    params = fam.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, fam, params


class TestMixtral:
    def test_no_shared_expert_params(self, setup):
        cfg, fam, params = setup
        assert "shared" not in params["moe"]
        assert "dense_mlp" not in params
        assert params["moe"]["experts"]["gate_proj"]["kernel"].shape[1] \
            == cfg.num_experts

    def test_decode_matches_full_prefill(self, setup):
        cfg, fam, params = setup
        T = 19
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, T), 0,
                                  cfg.vocab_size)
        pt = jnp.arange(8, dtype=jnp.int32)[None, :]
        pos = jnp.arange(T)[None, :]
        kv = alloc_pages(cfg, 8)
        logits_full, _ = fam.prefill_forward(
            params, cfg, toks, pos, kv, pt,
            jnp.zeros((1,), jnp.int32), jnp.array([T], jnp.int32))
        kv2 = alloc_pages(cfg, 8)
        _, kv2 = fam.prefill_forward(
            params, cfg, toks[:, :T - 1], pos[:, :T - 1], kv2, pt,
            jnp.zeros((1,), jnp.int32), jnp.array([T - 1], jnp.int32))
        logits_dec, _ = fam.decode_forward(
            params, cfg, toks[:, T - 1], jnp.array([T - 1], jnp.int32),
            kv2, pt, jnp.array([T], jnp.int32))
        np.testing.assert_allclose(np.asarray(logits_dec),
                                   np.asarray(logits_full),
                                   rtol=2e-4, atol=2e-4)

    def test_expert_sharded_matches_single_device(self, setup):
        cfg, fam, params = setup
        from xllm_service_tpu.parallel.mesh import MeshConfig, build_mesh
        from xllm_service_tpu.parallel.sharding import shard_params

        T = 12
        toks = jax.random.randint(jax.random.PRNGKey(2), (1, T), 0,
                                  cfg.vocab_size)
        pt = jnp.arange(4, dtype=jnp.int32)[None, :]
        pos = jnp.arange(T)[None, :]

        kv = alloc_pages(cfg, 4)
        ref, _ = fam.prefill_forward(
            params, cfg, toks, pos, kv, pt,
            jnp.zeros((1,), jnp.int32), jnp.array([T], jnp.int32))

        mesh = build_mesh(MeshConfig(expert=4),
                          devices=jax.devices()[:4])
        sp = shard_params(params, mesh, fam.sharding_rules)
        got, _ = fam.prefill_forward(
            sp, cfg, toks, pos, alloc_pages(cfg, 4), pt,
            jnp.zeros((1,), jnp.int32), jnp.array([T], jnp.int32))
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_engine_serves_mixtral(self):
        from test_engine import Collector, run_requests
        from xllm_service_tpu.common.request import SamplingParams
        from xllm_service_tpu.engine.config import EngineConfig
        from xllm_service_tpu.engine.engine import (
            EngineRequest,
            InferenceEngine,
        )

        cfg = EngineConfig(
            model_family="mixtral",
            model=mixtral_tiny_config(dtype=jnp.float32,
                                      max_context_len=128),
            num_pages=64, page_size=16, hash_block_size=32,
            max_batch_size=2, max_seq_len=128,
            prefill_buckets=(32, 64, 128), decode_horizon=4)
        engine = InferenceEngine(cfg)
        col = Collector()
        run_requests(engine, [EngineRequest(
            service_request_id="m0", token_ids=[5, 7, 9, 11, 13],
            sampling=SamplingParams(max_tokens=8, temperature=0.0),
            on_output=col)])
        assert len(col.tokens) == 8
        assert col.finish_reason == "length"
