"""Coordination layer tests: lease expiry, watches, election, TCP server."""

import threading
import time

import pytest

from xllm_service_tpu.coordination.base import WatchEventType
from xllm_service_tpu.coordination.client import TcpCoordinationClient
from xllm_service_tpu.coordination.memory import InMemoryCoordination, MemoryStore
from xllm_service_tpu.coordination.server import CoordinationServer


class _WatchSink:
    def __init__(self):
        self.events = []
        self.cv = threading.Condition()

    def __call__(self, events, prefix):
        with self.cv:
            self.events.extend(events)
            self.cv.notify_all()

    def wait_for(self, pred, timeout=3.0):
        with self.cv:
            return self.cv.wait_for(lambda: pred(self.events), timeout)


class TestInMemory:
    def test_basic_kv(self, store):
        c = InMemoryCoordination(store)
        assert c.set("a/b", "1")
        assert c.get("a/b") == "1"
        c.bulk_set({"a/c": "2", "d": "3"})
        assert c.get_prefix("a/") == {"a/b": "1", "a/c": "2"}
        assert c.rm("a/b")
        assert c.get("a/b") is None
        assert c.bulk_rm(["a/c", "nope"]) == 1
        c.close()

    def test_namespace(self, store):
        c1 = InMemoryCoordination(store, namespace="tenant1")
        c2 = InMemoryCoordination(store, namespace="tenant2")
        c1.set("k", "v1")
        c2.set("k", "v2")
        assert c1.get("k") == "v1"
        assert c2.get("k") == "v2"
        assert store.get("tenant1/k") == "v1"
        c1.close(); c2.close()

    def test_lease_expiry_without_keepalive(self, store):
        c = InMemoryCoordination(store)
        sink = _WatchSink()
        c.add_watch("inst/", sink)
        c.set("inst/x", "v", ttl_s=0.1, keepalive=False)
        assert sink.wait_for(lambda ev: any(
            e.type == WatchEventType.DELETE and e.key == "inst/x" for e in ev))
        assert c.get("inst/x") is None
        c.close()

    def test_keepalive_then_client_death(self, store):
        owner = InMemoryCoordination(store)
        observer = InMemoryCoordination(store)
        sink = _WatchSink()
        observer.add_watch("svc/", sink)
        owner.set("svc/me", "alive", ttl_s=0.15)
        time.sleep(0.5)  # several ttl periods: keepalive must hold it
        assert observer.get("svc/me") == "alive"
        owner.close()    # "process death"
        assert sink.wait_for(lambda ev: any(
            e.type == WatchEventType.DELETE and e.key == "svc/me" for e in ev))
        observer.close()

    def test_create_if_absent_election(self, store):
        a = InMemoryCoordination(store)
        b = InMemoryCoordination(store)
        won_a = a.create_if_absent("MASTER", "a", ttl_s=0.15)
        won_b = b.create_if_absent("MASTER", "b", ttl_s=0.15)
        assert won_a and not won_b
        assert b.get("MASTER") == "a"
        # Master dies -> key lapses -> replica can win.
        a.close()
        deadline = time.time() + 2
        while time.time() < deadline:
            if b.create_if_absent("MASTER", "b", ttl_s=0.15):
                break
            time.sleep(0.02)
        else:
            pytest.fail("replica never won election after master death")
        b.close()

    def test_guarded_rm_prefix(self, store):
        c = InMemoryCoordination(store)
        c.set("CACHE/a", "1")
        c.set("CACHE/b", "2")
        assert c.rm_prefix("CACHE/", guard_key="MASTER") == 0  # guard absent
        c.set("MASTER", "me")
        assert c.rm_prefix("CACHE/", guard_key="MASTER") == 2
        c.close()

    def test_watch_put_events(self, store):
        c = InMemoryCoordination(store)
        sink = _WatchSink()
        wid = c.add_watch("p/", sink)
        c.set("p/x", "1")
        c.set("q/y", "2")  # outside prefix
        assert sink.wait_for(lambda ev: len(ev) >= 1)
        assert [e.key for e in sink.events] == ["p/x"]
        c.remove_watch(wid)
        c.set("p/z", "3")
        time.sleep(0.1)
        assert [e.key for e in sink.events] == ["p/x"]
        c.close()


class TestTcpServer:
    @pytest.fixture()
    def server(self):
        srv = CoordinationServer(host="127.0.0.1", port=0)
        srv.start_background()
        yield srv
        srv.stop()

    def test_kv_and_watch_over_tcp(self, server):
        c1 = TcpCoordinationClient(f"127.0.0.1:{server.port}")
        c2 = TcpCoordinationClient(f"127.0.0.1:{server.port}")
        sink = _WatchSink()
        c2.add_watch("inst/", sink)
        assert c1.set("inst/a", "hello")
        assert c2.get("inst/a") == "hello"
        assert sink.wait_for(lambda ev: any(e.key == "inst/a" for e in ev))
        assert c1.get_prefix("inst/") == {"inst/a": "hello"}
        c1.close(); c2.close()

    def test_lease_over_tcp_client_death(self, server):
        owner = TcpCoordinationClient(f"127.0.0.1:{server.port}")
        observer = TcpCoordinationClient(f"127.0.0.1:{server.port}")
        sink = _WatchSink()
        observer.add_watch("svc/", sink)
        owner.set("svc/me", "alive", ttl_s=0.2)
        time.sleep(0.6)
        assert observer.get("svc/me") == "alive"  # keepalive held it
        owner.close()  # refreshes stop -> lease lapses
        assert sink.wait_for(lambda ev: any(
            e.type == WatchEventType.DELETE and e.key == "svc/me" for e in ev),
            timeout=5.0)
        observer.close()

    def test_watch_callback_may_issue_calls(self, server):
        """Election takeover over TCP: the replica's MASTER-key watch
        callback itself calls `create_if_absent`. Regression — callbacks
        used to run ON the reader thread, so that call waited on a
        response only the (blocked) reader could deliver: the server
        applied the write, the client timed out believing the election
        failed, and the unrefreshed key lapsed into a promotion loop that
        never completed. Callbacks now run on a dedicated dispatcher."""
        owner = TcpCoordinationClient(f"127.0.0.1:{server.port}")
        observer = TcpCoordinationClient(f"127.0.0.1:{server.port}",
                                         timeout_s=2.0)
        won = threading.Event()

        def takeover(events, _prefix):
            for e in events:
                if e.type == WatchEventType.DELETE and e.key == "svc/MASTER":
                    if observer.create_if_absent("svc/MASTER", "observer",
                                                 ttl_s=0.3):
                        won.set()

        observer.add_watch("svc/MASTER", takeover)
        assert owner.create_if_absent("svc/MASTER", "owner", ttl_s=0.2)
        owner.close()  # lease lapses -> DELETE -> takeover runs inline
        assert won.wait(5.0), "election callback deadlocked on its own call"
        assert observer.get("svc/MASTER") == "observer"
        time.sleep(0.6)   # keepalive must hold the won key across ttl
        assert observer.get("svc/MASTER") == "observer"
        observer.close()

    def test_auth(self):
        srv = CoordinationServer(host="127.0.0.1", port=0, auth=("u", "p"))
        srv.start_background()
        try:
            ok = TcpCoordinationClient(f"127.0.0.1:{srv.port}",
                                       username="u", password="p")
            assert ok.set("k", "v")
            ok.close()
            from xllm_service_tpu.coordination.client import CoordinationError
            with pytest.raises(CoordinationError):
                TcpCoordinationClient(f"127.0.0.1:{srv.port}",
                                      username="u", password="wrong")
        finally:
            srv.stop()
