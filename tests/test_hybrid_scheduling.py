"""Online/offline hybrid scheduling: admission priority + preemption with
lossless continuation (BASELINE config 3's hybrid half)."""

import jax.numpy as jnp

from xllm_service_tpu.common.request import SamplingParams
from xllm_service_tpu.engine.config import EngineConfig
from xllm_service_tpu.engine.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.models.base import tiny_config

from test_engine import Collector, naive_greedy, run_requests


def tight_engine(num_pages=24, max_batch=2) -> InferenceEngine:
    """An engine with scarce KV pages so admission pressure is easy to hit."""
    return InferenceEngine(EngineConfig(
        model=tiny_config(dtype=jnp.float32, max_context_len=256),
        num_pages=num_pages, page_size=16, hash_block_size=32,
        max_batch_size=max_batch, max_seq_len=128,
        prefill_buckets=(32, 64, 128)))


class TestHybridScheduling:
    def test_online_admitted_before_offline(self):
        engine = tight_engine(num_pages=64, max_batch=1)  # one slot: serialize
        order = []

        def track(name, col):
            def cb(out):
                col(out)
                if out.finished:
                    order.append(name)
            return cb

        cols = {n: Collector() for n in ("off1", "off2", "on1")}
        sp = SamplingParams(max_tokens=2, temperature=0.0, ignore_eos=True)
        # Two offline queued first, then an online one.
        reqs = [
            EngineRequest("off1", token_ids=list(range(10)), sampling=sp,
                          offline=True, on_output=track("off1", cols["off1"])),
            EngineRequest("off2", token_ids=list(range(10, 20)), sampling=sp,
                          offline=True, on_output=track("off2", cols["off2"])),
            EngineRequest("on1", token_ids=list(range(20, 30)), sampling=sp,
                          on_output=track("on1", cols["on1"])),
        ]
        for r in reqs:
            engine.submit(r)
        while not all(c.done.is_set() for c in cols.values()):
            if not engine.step():
                break
        # off1 was already running (single slot); the online request must
        # jump ahead of off2 in the queue.
        assert order.index("on1") < order.index("off2")

    def test_preemption_resumes_losslessly(self):
        engine = tight_engine(num_pages=7, max_batch=2)
        # 6 usable pages. Offline reserves 3 (30 prompt + 12 new = 42 tok);
        # online needs 4 (60 prompt + 4 new) -> must preempt the offline.
        off_prompt = list(range(30, 60))
        on_prompt = list(range(100, 160))
        expected_off = naive_greedy(engine, off_prompt, 12)
        expected_on = naive_greedy(engine, on_prompt, 4)

        off_col, on_col = Collector(), Collector()
        engine.submit(EngineRequest(
            "off", token_ids=off_prompt,
            sampling=SamplingParams(max_tokens=12, temperature=0.0,
                                    ignore_eos=True),
            offline=True, on_output=off_col))
        # Let the offline request run a few tokens.
        for _ in range(4):
            engine.step()
        assert len(off_col.tokens) >= 2
        engine.submit(EngineRequest(
            "on", token_ids=on_prompt,
            sampling=SamplingParams(max_tokens=4, temperature=0.0,
                                    ignore_eos=True),
            on_output=on_col))
        while not (off_col.done.is_set() and on_col.done.is_set()):
            if not engine.step():
                break
        # Online served correctly.
        assert on_col.tokens == expected_on
        # Offline finished with the exact same stream an uninterrupted run
        # would have produced (continuation is lossless, no repeats).
        assert off_col.tokens == expected_off
        assert off_col.finish_reason == "length"
        # Engine drained cleanly, and the offline victim really was
        # preempted (not just co-scheduled).
        assert engine.preemption_count >= 1
        assert engine.stats()["running"] == 0
