"""Context-parallel paged decode attention: page pool sharded over the
seq axis, flash-stats psum merge — must equal single-device paged
attention exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xllm_service_tpu.ops.attention import paged_attention_xla
from xllm_service_tpu.ops.cp_paged_attention import cp_paged_attention
from xllm_service_tpu.parallel.mesh import MeshConfig, build_mesh


def make_case(B=4, pages=32, n_kv=2, ps=16, hd=32, H=4, seed=0):
    rng = np.random.default_rng(seed)
    k_pages = jnp.asarray(rng.normal(size=(pages, n_kv, ps, hd)),
                          jnp.float32)
    v_pages = jnp.asarray(rng.normal(size=(pages, n_kv, ps, hd)),
                          jnp.float32)
    q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.float32)
    # Page tables deliberately interleave pages from every shard range.
    pt = jnp.asarray(rng.permutation(pages)[:B * 4].reshape(B, 4)
                     .astype(np.int32))
    clens = jnp.asarray(rng.integers(5, 4 * ps, B).astype(np.int32))
    return q, k_pages, v_pages, pt, clens


class TestCpPagedAttention:
    @pytest.mark.parametrize("sp", [2, 4])
    def test_matches_single_device(self, sp):
        q, kp, vp, pt, clens = make_case()
        want = paged_attention_xla(q, kp, vp, pt, clens)
        mesh = build_mesh(MeshConfig(seq=sp), devices=jax.devices()[:sp])
        with mesh:
            got = jax.jit(lambda *a: cp_paged_attention(
                *a, mesh=mesh))(q, kp, vp, pt, clens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("H,n_kv", [(4, 4), (8, 2)])
    def test_kernel_path_matches_xla(self, monkeypatch, H, n_kv):
        """The Pallas partial-stats body (chunked page DMA over owned
        pages only) must match the dense XLA body exactly — interpret
        mode exercises the REAL kernel routing hermetically."""
        import xllm_service_tpu.ops.cp_paged_attention as cpmod

        monkeypatch.setenv("XLLM_PALLAS_INTERPRET", "1")
        calls = {"n": 0}
        real = cpmod._paged_partial_pallas

        def spy(*a, **k):
            calls["n"] += 1
            return real(*a, **k)

        monkeypatch.setattr(cpmod, "_paged_partial_pallas", spy)
        q, kp, vp, pt, clens = make_case(hd=128, H=H, n_kv=n_kv, seed=5)
        want = paged_attention_xla(q, kp, vp, pt, clens)
        mesh = build_mesh(MeshConfig(seq=4), devices=jax.devices()[:4])
        with mesh:
            got = cp_paged_attention(q, kp, vp, pt, clens, mesh=mesh)
        assert calls["n"] > 0, "Pallas partial body was not selected"
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)

    def test_gqa_and_garbage_pages(self):
        """GQA head grouping + rows whose page tables include the garbage
        page (id 0, present in every inactive slot's table)."""
        q, kp, vp, pt, clens = make_case(H=8, n_kv=2, seed=3)
        pt = pt.at[0].set(jnp.array([0, 0, 0, 0], jnp.int32))
        clens = clens.at[0].set(1)
        want = paged_attention_xla(q, kp, vp, pt, clens)
        mesh = build_mesh(MeshConfig(seq=4), devices=jax.devices()[:4])
        with mesh:
            got = cp_paged_attention(q, kp, vp, pt, clens, mesh=mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
