"""Randomized engine soak: concurrent arrivals, cancellations, mixed
budgets/priorities/stop-tokens, online+offline — against the pipelined
decode/spec/admission paths. Asserts terminal-output and resource-return
invariants rather than exact streams (exactness is covered by the
targeted suites)."""

from __future__ import annotations

import threading
import time

import jax.numpy as jnp
import numpy as np

from xllm_service_tpu.common.request import SamplingParams
from xllm_service_tpu.engine.config import EngineConfig
from xllm_service_tpu.engine.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.models.base import tiny_config


class Term:
    def __init__(self):
        self.tokens = 0
        self.finished = False
        self.status_ok = True
        self.finish_reason = ""
        self.done = threading.Event()

    def __call__(self, out):
        for s in out.outputs:
            self.tokens += len(s.token_ids)
            if s.finish_reason:
                self.finish_reason = s.finish_reason
        if out.status is not None and not out.status.ok():
            self.status_ok = False
        if out.finished:
            self.finished = True
            self.done.set()


def _soak(cfg: EngineConfig, seed: int, plen_hi: int = 60):
    rng = np.random.default_rng(seed)
    engine = InferenceEngine(cfg)
    engine.start()

    N = 36
    terms = [Term() for _ in range(N)]
    cancelled: set[int] = set()

    def feeder():
        for i in range(N):
            plen = int(rng.integers(4, plen_hi))
            max_tokens = int(rng.integers(1, 24))
            sp = SamplingParams(max_tokens=max_tokens,
                                temperature=0.0, ignore_eos=True)
            if rng.random() < 0.2:
                # Some requests may stop early on a token they generate.
                sp.stop_token_ids = [int(rng.integers(10, 200))]
            if rng.random() < 0.3:
                sp = SamplingParams(max_tokens=max_tokens,
                                    temperature=0.7,
                                    seed=int(rng.integers(0, 1 << 30)),
                                    ignore_eos=True)
            engine.submit(EngineRequest(
                f"soak-{i}",
                token_ids=[int(t) for t in rng.integers(5, 400, plen)],
                sampling=sp,
                offline=bool(rng.random() < 0.3),
                priority=int(rng.integers(0, 3)),
                on_output=terms[i]))
            if rng.random() < 0.15:
                victim = int(rng.integers(0, i + 1))
                cancelled.add(victim)
                engine.cancel(f"soak-{victim}")
            time.sleep(float(rng.random()) * 0.05)

    f = threading.Thread(target=feeder)
    f.start()
    f.join()

    deadline = time.monotonic() + 180
    for i, t in enumerate(terms):
        assert t.done.wait(max(1.0, deadline - time.monotonic())), \
            f"request {i} never reached a terminal output"
    engine.stop()

    for i, t in enumerate(terms):
        assert t.finished, i
        if i not in cancelled:
            assert t.status_ok, i
    # Every slot and page returned (prefix-cache pages are retained but
    # accounted as cached, not leaked).
    assert len(engine._running) == 0
    assert len(engine._prefillings) == 0
    assert sorted(engine._free_slots) == list(range(cfg.max_batch_size))
    assert engine._pending_decode is None
    assert engine._pending_spec is None
    st = engine.stats()
    assert st["waiting"] == 0
    return engine


def test_soak_random_workload():
    _soak(EngineConfig(
        model=tiny_config(dtype=jnp.float32, max_context_len=256),
        num_pages=48, page_size=16, hash_block_size=32,
        max_batch_size=4, max_seq_len=128,
        prefill_buckets=(32, 64, 128),
        decode_horizon=4, admission_horizon=2,
        speculate_k=3),                   # spec path on (llama family)
        seed=42)


def test_soak_with_sarathi_chunking():
    """Same randomized invariants with chunked prefill + mixed
    decode+chunk rides in the mix (spec stays on, so ride/spec path
    switching, cancels mid-ride, and preemption all interleave)."""
    engine = _soak(EngineConfig(
        model=tiny_config(dtype=jnp.float32, max_context_len=256),
        num_pages=48, page_size=16, hash_block_size=32,
        max_batch_size=4, max_seq_len=128,
        prefill_buckets=(32, 64, 128),
        decode_horizon=4, admission_horizon=2,
        speculate_k=3, prefill_chunk_tokens=32),
        seed=1234, plen_hi=100)
    assert engine.sarathi_rides > 0, \
        "soak never exercised the mixed decode+chunk path"
