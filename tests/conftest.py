"""Test harness config.

Engine/sharding tests run on a virtual 8-device CPU mesh (the standard JAX
multi-host test pattern; SURVEY.md §4) — env must be set before jax import.
"""

import os
import sys

# Force CPU. A TPU-attach sitecustomize (if present) registers the TPU
# plugin at interpreter start and pins the platform in-process, so the env
# var alone is not enough — override via jax.config too (wins over the
# hook). Tests run hermetic on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

from xllm_service_tpu.coordination.memory import MemoryStore  # noqa: E402


@pytest.fixture()
def store():
    """A fresh coordination 'cluster' per test."""
    st = MemoryStore(expiry_tick_s=0.02)
    yield st
    st.close()
