"""Test harness config.

Engine/sharding tests run on a virtual 8-device CPU mesh (the standard JAX
multi-host test pattern; SURVEY.md §4) — env must be set before jax import.
"""

import os
import sys

# Force CPU. A TPU-attach sitecustomize (if present) registers the TPU
# plugin at interpreter start and pins the platform in-process, so the env
# var alone is not enough — override via jax.config too (wins over the
# hook). Tests run hermetic on the virtual 8-device CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache: the suite is dominated by recompiles of
# the same tiny-model programs across test processes (VERDICT r2 weak #8
# — 1402s, mostly XLA). Cache survives across runs in the repo's
# .pytest_cache sibling dir; first run pays, every later run reuses.
_cache_dir = os.environ.get(
    "XLLM_TEST_COMPILE_CACHE",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 ".jax_compile_cache"))
if _cache_dir != "0":
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402

from xllm_service_tpu.coordination.memory import MemoryStore  # noqa: E402
from xllm_service_tpu.devtools import lifecycle as _xlifecycle  # noqa: E402
from xllm_service_tpu.devtools import locks as _xlocks  # noqa: E402
from xllm_service_tpu.devtools import ownership as _xownership  # noqa: E402
from xllm_service_tpu.devtools import rcu as _xrcu  # noqa: E402


@pytest.fixture()
def store():
    """A fresh coordination 'cluster' per test."""
    st = MemoryStore(expiry_tick_s=0.02)
    yield st
    st.close()


@pytest.fixture(autouse=True)
def _instrumented_lock_guard():
    """Under XLLM_LOCK_DEBUG=1 every test doubles as a race/deadlock
    detector: any lock-order inversion or lock-held-across-I/O recorded by
    the instrumented locks (devtools/locks.py) during the test fails it —
    so the existing chaos drills moonlight as a race detector."""
    if not _xlocks.debug_enabled():
        yield
        return
    _xlocks.reset_violations()
    yield
    vs = _xlocks.violations()
    assert not vs, ("instrumented-lock violations:\n"
                    + "\n".join(str(v) for v in vs))


@pytest.fixture(autouse=True)
def _state_ownership_guard():
    """Under XLLM_STATE_DEBUG=1 every test doubles as an attribute-race
    detector: registered classes (devtools/ownership.py
    STATE_DISCIPLINES) record (thread role, locks held) for every write
    and any discipline violation recorded during the test fails it — so
    the chaos, multimaster-kill and tier drills moonlight as a
    shared-state ownership verifier, mirroring the lock and RCU guards
    around this one."""
    if not _xownership.debug_enabled():
        yield
        return
    _xownership.reset_violations()
    yield
    vs = _xownership.violations()
    assert not vs, ("state-ownership violations:\n"
                    + "\n".join(str(v) for v in vs))


@pytest.fixture(autouse=True)
def _leak_guard():
    """Under XLLM_LEAK_DEBUG=1 every test doubles as a resource-leak
    detector: instrumented acquire/release pairs (devtools/lifecycle.py
    EFFECT_PAIRS) keep per-pair balance counters with acquisition
    stacks. A double-release or metric-series resurrection recorded
    during the test fails it, and so does a nonzero teardown balance on
    a `strict` pair (an admission slot or flight-recorder context
    provider that leaked) — the runtime mirror of xlint's pair-release/
    pair-once/pair-evict rules, following the lock/state/RCU guards
    around this one."""
    if not _xlifecycle.debug_enabled():
        yield
        return
    _xlifecycle.reset_violations()
    _xlifecycle.reset_balances()
    yield
    vs = _xlifecycle.violations() + _xlifecycle.strict_imbalances()
    assert not vs, ("lifecycle pair violations:\n"
                    + "\n".join(str(v) for v in vs))


@pytest.fixture(autouse=True)
def _rcu_freeze_guard():
    """Under XLLM_RCU_DEBUG=1 every test doubles as a snapshot-race
    detector: RCU publications are deep-frozen (devtools/rcu.py) and any
    in-place mutation recorded during the test fails it — even when the
    raising path was swallowed by a broad except. The chaos, multimaster
    kill, and tier-transition drills all moonlight as detectors this
    way, mirroring the instrumented-lock guard above."""
    if not _xrcu.debug_enabled():
        yield
        return
    _xrcu.reset_violations()
    yield
    vs = _xrcu.violations()
    assert not vs, ("rcu deep-freeze violations:\n"
                    + "\n".join(str(v) for v in vs))
