"""XLLM_RCU_DEBUG deep-freeze detector tests: frozen views, recursion,
the thaw escape hatch, passthrough-when-disabled, publication integration
for the registered managers, and the resurrected PR-6 in-place-apply bug
(caught at runtime by the freezer — the static half of that regression
pair lives in tests/test_xlint.py / rcu_regress.py)."""

import numpy as np
import pytest

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.hashing import prefix_block_hash_hexes
from xllm_service_tpu.common.types import KvCacheEvent
from xllm_service_tpu.coordination.base import KeyEvent, WatchEventType
from xllm_service_tpu.coordination.memory import InMemoryCoordination
from xllm_service_tpu.devtools import rcu
from xllm_service_tpu.engine.kv_tier import TieredKVStore
from xllm_service_tpu.multimaster.ownership import OwnershipRouter
from xllm_service_tpu.rpc import CACHE_FRAME_KEY_PREFIX, CACHE_KEY_PREFIX
from xllm_service_tpu.rpc.wire import encode_kv_frame
from xllm_service_tpu.scheduler.global_kvcache_mgr import (
    GlobalKVCacheMgr,
    PrefixIndex,
    _BlockLoc,
)
from xllm_service_tpu.scheduler.instance_mgr import InstanceMgr, RoutingSnapshot

from fakes import FakeChannel, make_meta, wait_until

BLOCK = 16


@pytest.fixture()
def coord(store):
    c = InMemoryCoordination(store)
    yield c
    c.close()


@pytest.fixture()
def rcu_debug():
    """Arm the freezer for the test body; restore the PRIOR state on
    teardown (hardcoding False here would silently disarm a suite-wide
    XLLM_RCU_DEBUG=1 run for every test collected after this file)."""
    was = rcu.debug_enabled()
    rcu.set_debug(True)
    rcu.reset_violations()
    yield
    rcu.reset_violations()
    rcu.set_debug(was)


@pytest.fixture(autouse=True)
def _reset_channels():
    FakeChannel.reset()
    yield
    FakeChannel.reset()


# --------------------------------------------------------------- frozen views
class TestFrozenViews:
    def test_frozen_dict_reads_work_writes_raise(self, rcu_debug):
        d = rcu.freeze({"a": 1, "b": 2})
        assert d["a"] == 1 and dict(d) == {"a": 1, "b": 2}
        assert isinstance(d, dict)
        rcu.reset_violations()
        with pytest.raises(rcu.RcuMutationError):
            d["c"] = 3
        with pytest.raises(rcu.RcuMutationError):
            d.pop("a")
        with pytest.raises(rcu.RcuMutationError):
            d.update({"x": 1})
        with pytest.raises(rcu.RcuMutationError):
            del d["a"]
        assert len(rcu.violations()) == 4
        rcu.reset_violations()

    def test_frozen_list_and_set(self, rcu_debug):
        lst = rcu.freeze([1, 2, 3])
        st = rcu.freeze({1, 2})
        assert list(lst) == [1, 2, 3] and 1 in st
        rcu.reset_violations()
        with pytest.raises(rcu.RcuMutationError):
            lst.append(4)
        with pytest.raises(rcu.RcuMutationError):
            lst[0] = 9
        with pytest.raises(rcu.RcuMutationError):
            st.add(3)
        with pytest.raises(rcu.RcuMutationError):
            st.discard(1)
        rcu.reset_violations()

    def test_nested_freeze_recursion(self, rcu_debug):
        v = rcu.freeze({"outer": {"inner": [1, {2, 3}]}})
        inner = v["outer"]["inner"]
        rcu.reset_violations()
        with pytest.raises(rcu.RcuMutationError):
            v["outer"]["x"] = 1
        with pytest.raises(rcu.RcuMutationError):
            inner.append(4)
        with pytest.raises(rcu.RcuMutationError):
            inner[1].add(9)
        rcu.reset_violations()

    def test_tuple_children_frozen(self, rcu_debug):
        t = rcu.freeze(("a", [1], {"k": 2}))
        assert t[0] == "a"
        rcu.reset_violations()
        with pytest.raises(rcu.RcuMutationError):
            t[1].append(2)
        with pytest.raises(rcu.RcuMutationError):
            t[2]["k"] = 3
        rcu.reset_violations()
        # All-immutable tuples keep their identity (no rebuild).
        plain = ("a", 1)
        assert rcu.freeze(plain) is plain

    def test_freeze_idempotent(self, rcu_debug):
        d = rcu.freeze({"a": [1]})
        assert rcu.freeze(d) is d

    def test_registered_type_attribute_writes_raise(self, rcu_debug):
        idx = rcu.publish(PrefixIndex({b"k": _BlockLoc(hbm=("i1",))}))
        assert isinstance(idx, PrefixIndex)       # shadow subclass
        assert idx.blocks[b"k"].hbm == frozenset({"i1"})
        rcu.reset_violations()
        with pytest.raises(rcu.RcuMutationError):
            idx.blocks = {}
        with pytest.raises(rcu.RcuMutationError):
            idx.blocks[b"x"] = _BlockLoc(hbm=("i2",))
        loc = idx.blocks[b"k"]
        with pytest.raises(rcu.RcuMutationError):
            loc.scored = ()
        rcu.reset_violations()

    def test_unregistered_leaves_stay_mutable(self, rcu_debug):
        class Plain:
            pass

        p = Plain()
        snap = rcu.freeze({"entry": p})
        assert snap["entry"] is p
        p.x = 1   # shared-mutable leaf by design (e.g. _Entry)
        assert p.x == 1


# -------------------------------------------------------------- passthrough
class TestPassthrough:
    def test_publish_is_identity_when_disabled(self):
        assert not rcu.debug_enabled()
        obj = {"a": [1]}
        assert rcu.publish(obj) is obj
        snap = RoutingSnapshot({})
        assert rcu.publish(snap) is snap

    def test_thaw_is_identity_on_plain_containers(self):
        d = {"a": 1}
        assert rcu.thaw(d, "reason") is d

    def test_thaw_requires_reason_even_when_disabled(self):
        with pytest.raises(ValueError):
            rcu.thaw({}, "")


# -------------------------------------------------------------- escape hatch
class TestThaw:
    def test_thaw_mutates_underlying_frozen_dict(self, rcu_debug):
        d = rcu.freeze({"a": 1})
        store = rcu.thaw(d, "declared entry-level writer")
        store["b"] = 2
        assert d["b"] == 2 and store.get("a") == 1
        assert store.pop("a") == 1 and "a" not in d
        store.update({"c": 3})
        del store["c"]
        assert set(store) == {"b"} and len(store) == 1
        assert not rcu.violations()


# -------------------------------------------------- manager integration
class TestManagerIntegration:
    def test_instance_mgr_publishes_frozen_snapshot(self, coord, rcu_debug):
        mgr = InstanceMgr(coord, ServiceOptions(block_size=BLOCK),
                          channel_factory=FakeChannel.factory,
                          start_threads=False)
        try:
            assert mgr.register_instance(make_meta("i1"))
            snap = mgr.routing_snapshot()
            assert "i1" in snap.schedulable
            rcu.reset_violations()
            with pytest.raises(rcu.RcuMutationError):
                snap.entries["ghost"] = None
            with pytest.raises(rcu.RcuMutationError):
                snap.prefill = ()
            rcu.reset_violations()
            infos = mgr.get_load_infos()
            with pytest.raises(rcu.RcuMutationError):
                infos["ghost"] = None
            info = infos["i1"]
            with pytest.raises(rcu.RcuMutationError):
                info.schedulable = False
            rcu.reset_violations()
        finally:
            mgr.stop()

    def test_kvcache_ingest_and_match_run_frozen(self, coord, rcu_debug):
        """The declared entry-level writers (thaw) still work with the
        freezer armed, and the lock-free reader sees their writes."""
        mgr = GlobalKVCacheMgr(coord, block_size=BLOCK)
        toks = list(range(BLOCK * 2))
        hashes = prefix_block_hash_hexes(toks, BLOCK)
        mgr.record_updated_kvcaches("i1", KvCacheEvent(stored=hashes))
        assert mgr.match(toks).scores["i1"] == pytest.approx(2.0)
        mgr.record_updated_kvcaches("i1", KvCacheEvent(offloaded=hashes[:1]))
        mgr.remove_instance("i1")
        assert mgr.match(toks).scores == {}
        assert not rcu.violations()
        # Direct mutation of the published index still raises.
        with pytest.raises(rcu.RcuMutationError):
            mgr._snapshot.blocks[b"x" * 16] = _BlockLoc(hbm=("i9",))
        rcu.reset_violations()

    def test_ownership_members_published(self, coord, rcu_debug):
        router = OwnershipRouter(coord, "a:1", start_watch=False)
        router.update_self_addr("a:2")
        assert router.members() == ("a:2",)
        assert not rcu.violations()

    def test_tier_drained_events_are_frozen(self, rcu_debug):
        store = TieredKVStore(block_shape=(2, 2), dtype="float32",
                              dram_bytes=64, threads=1, max_inflight=2)
        try:
            assert store.offload("ab" * 16, np.ones((2, 2), np.float32))
            wait_until(lambda: store.ready("ab" * 16))
            off, rem = store.drain_events()
            assert off == ["ab" * 16]
            rcu.reset_violations()
            with pytest.raises(rcu.RcuMutationError):
                off.append("late-delta")   # the PR-7 bug class
            rcu.reset_violations()
        finally:
            store.close()


# ------------------------------------------------- resurrected PR-6 bug
class TestResurrectedInPlaceApply:
    """PR-6 regression pair, runtime half: full-frame watch batches
    applied IN PLACE on the live index (the pre-COW-fix code). The
    mutation reaches the dict through a parameter alias the static rule
    cannot track — XLLM_RCU_DEBUG is what catches it."""

    def _compaction_events(self, hashes):
        legacy_key = CACHE_KEY_PREFIX + hashes[0]
        frame = encode_kv_frame(
            {bytes.fromhex(h): [["i1"], [], []] for h in hashes}, [],
            full=True)
        return [
            KeyEvent(WatchEventType.DELETE, legacy_key, ""),
            KeyEvent(WatchEventType.PUT, f"{CACHE_FRAME_KEY_PREFIX}"
                                         f"{0:020d}", frame),
        ]

    def test_bug_flipped_on_is_caught_by_freezer(self, coord, rcu_debug):
        replica = GlobalKVCacheMgr(coord, block_size=BLOCK, is_master=False)
        try:
            toks = list(range(BLOCK * 2))
            hashes = prefix_block_hash_hexes(toks, BLOCK)
            replica.record_updated_kvcaches("i1", KvCacheEvent(stored=hashes))
            replica._inplace_full_apply = True   # resurrect the bug
            rcu.reset_violations()
            with pytest.raises(rcu.RcuMutationError):
                replica._on_cache_event(self._compaction_events(hashes), "")
            assert rcu.violations(), "freezer must record the mutation"
            rcu.reset_violations()
        finally:
            replica.stop()

    def test_fixed_path_applies_clean(self, coord, rcu_debug):
        replica = GlobalKVCacheMgr(coord, block_size=BLOCK, is_master=False)
        try:
            toks = list(range(BLOCK * 2))
            hashes = prefix_block_hash_hexes(toks, BLOCK)
            replica.record_updated_kvcaches("i1", KvCacheEvent(stored=hashes))
            rcu.reset_violations()
            replica._on_cache_event(self._compaction_events(hashes), "")
            assert not rcu.violations()
            # COW apply: the post-compaction index is complete.
            assert replica.match(toks).scores["i1"] == pytest.approx(2.0)
        finally:
            replica.stop()
