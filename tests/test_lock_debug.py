"""Instrumented-lock race detector (`XLLM_LOCK_DEBUG=1` mode of
devtools/locks.py): deliberate lock-order inversions and
blocking-calls-under-lock must be detected, and a real chaos-failover
drill must run clean with every orchestration lock instrumented."""

import threading

import pytest
import requests

from xllm_service_tpu.common.faults import FAULTS
from xllm_service_tpu.common.types import InstanceType
from xllm_service_tpu.coordination.memory import InMemoryCoordination
from xllm_service_tpu.devtools import locks
from xllm_service_tpu.devtools.locks import InstrumentedLock, make_lock

pytestmark = pytest.mark.chaos


@pytest.fixture()
def debug_locks():
    """Instrumentation on for locks created inside the test; violation
    list drained at exit so the conftest guard (armed when the whole
    suite runs under XLLM_LOCK_DEBUG=1) doesn't see our deliberate
    inversions."""
    prev = locks.debug_enabled()
    locks.set_debug(True)
    locks.reset_violations()
    yield
    locks.reset_violations()
    locks.set_debug(prev)


class TestFactoryModes:
    def test_passthrough_by_default(self):
        prev = locks.debug_enabled()
        locks.set_debug(False)
        try:
            lk = make_lock("t.passthrough", order=1)
            rl = make_lock("t.passthrough_r", order=2, reentrant=True)
            assert not isinstance(lk, InstrumentedLock)
            assert not isinstance(rl, InstrumentedLock)
            assert isinstance(lk, type(threading.Lock()))
        finally:
            locks.set_debug(prev)

    def test_instrumented_under_debug(self, debug_locks):
        lk = make_lock("t.instr", order=1)
        assert isinstance(lk, InstrumentedLock)
        with lk:
            assert "t.instr" in locks.held_locks()
        assert "t.instr" not in locks.held_locks()


class TestOrderInversion:
    def test_inversion_detected(self, debug_locks):
        a = make_lock("t.a", order=1)
        b = make_lock("t.b", order=2)
        with b:
            with a:
                pass
        vs = [v for v in locks.violations() if v.kind == "lock-order"]
        assert vs, "inversion b(2) -> a(1) not detected"
        assert "t.a" in vs[0].message and "t.b" in vs[0].message
        assert vs[0].stack   # acquisition stack recorded

    def test_correct_order_clean(self, debug_locks):
        a = make_lock("t.a2", order=1)
        b = make_lock("t.b2", order=2)
        with a:
            with b:
                pass
        assert not locks.violations()

    def test_reentrant_reacquisition_clean(self, debug_locks):
        r = make_lock("t.r", order=3, reentrant=True)
        with r:
            with r:
                pass
        assert not locks.violations()

    def test_equal_order_different_locks_flagged(self, debug_locks):
        x = make_lock("t.x", order=7)
        y = make_lock("t.y", order=7)
        with x:
            with y:
                pass
        assert any(v.kind == "lock-order" for v in locks.violations())


class TestHeldAcrossYield:
    def test_blocking_call_under_lock_detected(self, debug_locks):
        """A fault point (= modeled blocking I/O) crossed while holding an
        instrumented lock is the runtime blocking-under-lock signal."""
        lk = make_lock("t.io", order=1)
        with lk:
            FAULTS.check("rpc.post", instance="t", path="/x")
        vs = [v for v in locks.violations() if v.kind == "held-across-yield"]
        assert vs
        assert "t.io" in vs[0].message and "rpc.post" in vs[0].message

    def test_reentrant_hold_reported_once(self, debug_locks):
        """An RLock held at depth 2 across a yield point is ONE violation
        (and one held_locks entry), not one per acquisition."""
        r = make_lock("t.rdepth", order=1, reentrant=True)
        with r:
            with r:
                assert locks.held_locks().count("t.rdepth") == 1
                FAULTS.check("rpc.post", instance="t", path="/x")
            # Inner release must not drop the entry while still held.
            assert "t.rdepth" in locks.held_locks()
        assert "t.rdepth" not in locks.held_locks()
        vs = [v for v in locks.violations() if v.kind == "held-across-yield"]
        assert len(vs) == 1

    def test_fault_point_outside_lock_clean(self, debug_locks):
        lk = make_lock("t.io2", order=1)
        with lk:
            pass
        FAULTS.check("rpc.post", instance="t", path="/x")
        assert not locks.violations()


class TestChaosDrillInstrumented:
    def test_failover_drill_clean_under_instrumented_locks(self, store,
                                                           debug_locks):
        """The PR-1 chaos drill (kill the serving instance mid-stream,
        stream fails over byte-identically) with every orchestration lock
        instrumented: the drill must pass AND record zero lock
        violations — the suite doubling as a race detector."""
        from xllm_service_tpu.common.config import ServiceOptions
        from xllm_service_tpu.master import Master
        from xllm_service_tpu.testing.fake_engine import (
            FakeEngine,
            FakeEngineConfig,
        )
        from fakes import wait_until

        FAULTS.configure((), seed=0)
        opts = ServiceOptions(
            host="127.0.0.1", http_port=0, rpc_port=0,
            lease_ttl_s=0.5, reconcile_interval_s=0.05,
            heartbeat_silence_to_suspect_s=0.3,
            detect_disconnected_instance_interval_s=0.3,
            health_probe_attempts=1, health_probe_timeout_s=0.2,
            sync_interval_s=0.2,
            failover_backoff_base_s=0.05, failover_backoff_max_s=0.3,
            rpc_backoff_base_s=0.02, rpc_backoff_max_s=0.1)
        reply = "Instrumented locks must not change failover behavior."
        master = Master(opts, coord=InMemoryCoordination(store))
        master.start()
        cfg = FakeEngineConfig(reply_text=reply, chunk_size=4, delay_s=0.05,
                               heartbeat_interval_s=0.1, lease_ttl_s=0.5,
                               instance_type=InstanceType.MIX)
        engines = [FakeEngine(InMemoryCoordination(store), cfg).start()
                   for _ in range(2)]
        try:
            assert wait_until(
                lambda: all(master.scheduler.instance_mgr.get_instance_meta(
                    e.name) is not None for e in engines), timeout=5)
            # Crash the serving instance right before its 3rd delta.
            FAULTS.configure([dict(point="engine.token", action="crash",
                                   after=2, max_fires=1)], seed=0)
            r = requests.post(
                f"http://127.0.0.1:{master.http_port}/v1/completions",
                json={"model": "fake-model", "prompt": "chaos",
                      "max_tokens": 1000}, timeout=60)
            assert r.status_code == 200, r.text
            assert r.json()["choices"][0]["text"] == reply
            assert sum(1 for e in engines if not e._alive) == 1
        finally:
            FAULTS.clear()
            for e in engines:
                e.stop()
            master.stop()
        vs = locks.violations()
        assert not vs, ("chaos drill produced lock violations:\n"
                        + "\n".join(str(v) for v in vs))
