"""Tier-1 tests for the fleet-scope observability plane (ISSUE 9):

- trace federation: `/admin/trace?scope=fleet` assembles ONE tree across
  frontends + engine agents (relayed request drill), degrades partially
  when an agent dies, and merges spans a peer holds that this frontend
  never recorded (standalone span-peer server with its own Tracer),
- metrics federation: `/metrics/fleet` merges + re-labels engine and
  peer-frontend series, keeps serving with a dead agent (partial,
  non-erroring),
- SLO burn-rate monitor: window math units + the fault-plane latency
  drill moving `/admin/slo` burn rates,
- anomaly flight recorder: ring/JSONL capture units + the owner-kill
  chaos drill asserting a `handoff_recovery` bundle was captured,
- tail-based trace sampling: sampled-out traces drop on clean exit and
  ALWAYS record on failover/error/SLO breach,
- engine-agent labeled-series eviction (PD unlink, master change),
- the bench-trend regression tripwire (scripts/bench_trend.py).
"""

import asyncio
import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path
from types import SimpleNamespace

import pytest
import requests
from aiohttp import web

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.faults import FAULTS
from xllm_service_tpu.common.flightrecorder import RECORDER, FlightRecorder
from xllm_service_tpu.common.metrics import (
    ENGINE_HEARTBEATS_TOTAL,
    ENGINE_PEER_LINKED,
    relabel_prometheus_text,
)
from xllm_service_tpu.common.slo import SloMonitor
from xllm_service_tpu.common.tracing import (
    TRACER,
    Tracer,
    make_trace_handlers,
    merge_fleet_spans,
)
from xllm_service_tpu.coordination.memory import InMemoryCoordination
from xllm_service_tpu.engine.agent import EngineAgent
from xllm_service_tpu.master import Master
from xllm_service_tpu.rpc import SERVICE_KEY_PREFIX
from xllm_service_tpu.testing.fake_engine import FakeEngine, FakeEngineConfig
from xllm_service_tpu.utils import pick_free_port

from fakes import wait_until

SEED = int(os.environ.get("XLLM_CHAOS_SEED", "0"))
REPO = Path(__file__).resolve().parent.parent
REPLY = "One fleet, one trace tree, one merged scrape."


@pytest.fixture(autouse=True)
def _clean_plane():
    FAULTS.configure((), seed=SEED)
    TRACER.configure(enabled=True, mirror=None, sample_rate=1.0)
    TRACER.store.clear()
    RECORDER.clear()
    RECORDER.configure(capacity=64, directory="")
    yield
    FAULTS.clear()
    TRACER.configure(enabled=True, mirror=None, sample_rate=1.0)
    RECORDER.configure(capacity=64, directory="")


# ----------------------------------------------------------------- helpers
def _opts(**kw) -> ServiceOptions:
    base = dict(
        host="127.0.0.1", http_port=0, rpc_port=0,
        lease_ttl_s=0.5, sync_interval_s=0.2,
        reconcile_interval_s=0.05,
        heartbeat_silence_to_suspect_s=0.3,
        detect_disconnected_instance_interval_s=0.3,
        health_probe_attempts=1, health_probe_timeout_s=0.2,
        failover_backoff_base_s=0.05, failover_backoff_max_s=0.3,
        rpc_backoff_base_s=0.02, rpc_backoff_max_s=0.1,
        handoff_stall_timeout_s=1.5,
        metrics_fleet_cache_ttl_s=0.0,
        fleet_peer_timeout_s=2.0)
    base.update(kw)
    return ServiceOptions(**base)


def _master(store, **kw) -> Master:
    m = Master(_opts(**kw), coord=InMemoryCoordination(store))
    m.start()
    return m


def _engine(store, **cfg_kw) -> FakeEngine:
    cfg_kw.setdefault("delay_s", 0.02)
    cfg = FakeEngineConfig(reply_text=REPLY, chunk_size=4,
                           heartbeat_interval_s=0.1, lease_ttl_s=0.5,
                           **cfg_kw)
    return FakeEngine(InMemoryCoordination(store), cfg).start()


def _base(m: Master) -> str:
    return f"http://127.0.0.1:{m.http_port}"


def _await_fleet(masters, engines) -> None:
    addrs = {m.scheduler.self_addr for m in masters}
    assert wait_until(
        lambda: all(
            all(m.scheduler.instance_mgr.get_instance_meta(e.name)
                is not None for e in engines)
            and set(m.scheduler.ownership.members()) == addrs
            for m in masters), timeout=20)


def _stream(m: Master, okey=None, after_frames=0, hook=None, timeout=90,
            want_sid=False):
    """Returns the streamed text; with ``want_sid`` a ``(text, sid)``
    pair, where sid is the X-Request-Id header — the internal service
    id the tracer records — so tests can scope trace assertions to THIS
    request instead of the shared global store (straggler spans from a
    prior test's killed masters make globally-empty checks flaky)."""
    body = {"model": "fake-model", "prompt": "fleet", "stream": True,
            "max_tokens": 1000}
    if okey is not None:
        body["ownership_key"] = okey
    r = requests.post(_base(m) + "/v1/completions", json=body,
                      stream=True, timeout=timeout)
    assert r.status_code == 200, r.text
    sid = r.headers.get("X-Request-Id", "")
    text, n, fired = "", 0, False
    for line in r.iter_lines():
        if not line.startswith(b"data: "):
            continue
        data = line[len(b"data: "):]
        if data == b"[DONE]":
            break
        obj = json.loads(data)
        if "error" in obj:
            raise RuntimeError(f"stream error: {obj['error']}")
        for c in obj.get("choices", ()):
            text += c.get("text", "")
        n += 1
        if hook is not None and not fired and n >= after_frames:
            fired = True
            hook()
    return (text, sid) if want_sid else text


def _completion(m: Master, max_tokens=50, want_sid=False):
    r = requests.post(_base(m) + "/v1/completions", json={
        "model": "fake-model", "prompt": "fleet",
        "max_tokens": max_tokens}, timeout=30)
    assert r.status_code == 200, r.text
    text = r.json()["choices"][0]["text"]
    if want_sid:
        return text, r.headers.get("X-Request-Id", "")
    return text


def _fleet_trace(m: Master, **params):
    params["scope"] = "fleet"
    return requests.get(_base(m) + "/admin/trace", params=params,
                        timeout=15)


def _key_owned_by(router, addr: str) -> str:
    for i in range(10000):
        k = f"obs-affinity-{i}"
        if router.owner_of(k) == addr:
            return k
    raise AssertionError(f"no key owned by {addr}")


class _SpanPeer:
    """Standalone span-server: serves /admin/trace(+recent) + /metrics
    from its OWN Tracer instance — a fleet peer whose spans this process's
    global TRACER never saw, so the merge is provably doing network
    federation, not reading shared memory."""

    def __init__(self):
        self.tracer = Tracer(capacity=128)
        self.port = pick_free_port("127.0.0.1")
        self.addr = f"127.0.0.1:{self.port}"
        self._loop = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        assert self._started.wait(10)

    def _run(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        trace_h, recent_h = make_trace_handlers(self.tracer)
        app = web.Application()
        app.router.add_get("/admin/trace", trace_h)
        app.router.add_get("/admin/trace/recent", recent_h)

        async def metrics(_req):
            return web.Response(text="peer_requests_total 7\n",
                                content_type="text/plain")

        app.router.add_get("/metrics", metrics)

        async def start():
            self._runner = web.AppRunner(app)
            await self._runner.setup()
            await web.TCPSite(self._runner, "127.0.0.1", self.port).start()

        self._loop.run_until_complete(start())
        self._started.set()
        self._loop.run_forever()

    def stop(self):
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5)


# ------------------------------------------------------------ SLO monitor
class TestSloMonitor:
    def test_burn_rate_math(self):
        mon = SloMonitor()
        mon.configure(ttft_ms=100.0, tpot_ms=10.0, budget=0.1,
                      fast_s=60.0, slow_s=600.0, alert=2.0)
        now = 1000.0
        for i in range(8):
            mon.record_ttft(50.0, now=now + i)       # good
        for i in range(2):
            mon.record_ttft(500.0, now=now + 8 + i)  # bad
        rep = mon.report(now=now + 10)
        ttft = rep["objectives"]["ttft"]
        assert ttft["fast"]["n"] == 10 and ttft["fast"]["bad"] == 2
        # bad_fraction 0.2 / budget 0.1 = burn 2.0 in both windows.
        assert ttft["fast"]["burn_rate"] == pytest.approx(2.0)
        assert ttft["slow"]["burn_rate"] == pytest.approx(2.0)
        assert ttft["breaching"] is True
        assert "ttft" in rep["breaching"]

    def test_multiwindow_requires_both_hot(self):
        """A burst that already ended burns the fast window cold again —
        only a sustained burn (both windows hot) breaches."""
        mon = SloMonitor()
        mon.configure(ttft_ms=100.0, tpot_ms=10.0, budget=0.01,
                      fast_s=10.0, slow_s=600.0, alert=5.0)
        now = 2000.0
        for i in range(50):
            mon.record_ttft(500.0, now=now + i * 0.1)   # hot burst
        for i in range(100):
            mon.record_ttft(5.0, now=now + 20 + i * 0.1)  # recovered
        rep = mon.report(now=now + 31)
        ttft = rep["objectives"]["ttft"]
        assert ttft["fast"]["bad"] == 0          # burst aged out of fast
        assert ttft["slow"]["bad"] == 50         # still burning slow
        assert ttft["breaching"] is False

    def test_error_rate_objective_and_windows_age_out(self):
        mon = SloMonitor()
        mon.configure(ttft_ms=100.0, tpot_ms=10.0, budget=0.5,
                      fast_s=5.0, slow_s=50.0, alert=1.5)
        now = 3000.0
        mon.record_request(ok=False, now=now)
        mon.record_request(ok=True, now=now + 1)
        rep = mon.report(now=now + 2)
        err = rep["objectives"]["error_rate"]
        assert err["fast"]["bad_fraction"] == pytest.approx(0.5)
        # Past the fast window both samples are gone.
        rep = mon.report(now=now + 30)
        assert rep["objectives"]["error_rate"]["fast"]["n"] == 0
        assert rep["objectives"]["error_rate"]["slow"]["n"] == 2

    @pytest.mark.chaos
    def test_slo_endpoint_moves_under_injected_latency(self, store):
        """Acceptance drill: the fault plane injects per-token latency,
        TTFT blows through a tight objective, /admin/slo burn rates move
        from 0 to hot."""
        master = _master(store, slo_ttft_ms=10000.0)
        engine = _engine(store, delay_s=0.0)
        try:
            _await_fleet([master], [engine])
            assert _completion(master) == REPLY
            rep = requests.get(_base(master) + "/admin/slo",
                               timeout=5).json()
            assert rep["objectives"]["ttft"]["fast"]["bad"] == 0
            # Tighten the target live, then inject latency ahead of the
            # first token.
            master.options.slo_ttft_ms = 1.0
            from xllm_service_tpu.common.slo import SLO_MONITOR
            SLO_MONITOR.ttft_target_ms = 1.0
            FAULTS.configure([dict(point="engine.token", action="delay",
                                   delay_s=0.2, max_fires=2)], seed=SEED)
            assert _completion(master) == REPLY
            rep = requests.get(_base(master) + "/admin/slo",
                               timeout=5).json()
            ttft = rep["objectives"]["ttft"]
            assert ttft["fast"]["bad"] >= 1
            assert ttft["fast"]["burn_rate"] > 1.0
            # ... and the gauges rode along to /metrics.
            text = requests.get(_base(master) + "/metrics", timeout=5).text
            assert 'slo_burn_rate{objective="ttft",window="fast"}' in text
        finally:
            engine.stop()
            master.stop()


# ------------------------------------------------------- flight recorder
class TestFlightRecorder:
    def test_ring_capture_and_jsonl(self, tmp_path):
        rec = FlightRecorder(capacity=4)
        rec.configure(directory=str(tmp_path))
        rec.add_context_provider("ctx", lambda: {"x": 1})
        rec.add_context_provider("broken", lambda: 1 / 0)
        with TRACER.span("scheduler.schedule", request_id="fr-1") as sp:
            pass
        rec.record("error", request_id="fr-1", trace_id=sp.trace_id,
                   detail={"code": 503})
        got = rec.recent()
        assert len(got) == 1
        b = got[0]
        assert b["kind"] == "error" and b["detail"]["code"] == 503
        assert b["ctx"] == {"x": 1}
        assert "error" in b["broken"]            # provider failure inline
        assert b["num_spans"] == 1
        assert b["trace"][0]["point"] == "scheduler.schedule"
        assert "hotpath" in b
        lines = (tmp_path / "flightrecorder.jsonl").read_text().splitlines()
        assert json.loads(lines[0])["kind"] == "error"
        for i in range(10):
            rec.record("failover", request_id=f"r{i}")
        assert len(rec.recent(limit=50)) == 4    # bounded ring
        rec.remove_context_provider("ctx")
        rec.remove_context_provider("broken")
        rec.close()

    def test_recent_filters_by_kind(self):
        rec = FlightRecorder(capacity=8)
        rec.record("error", request_id="a")
        rec.record("failover", request_id="b")
        assert [r["request_id"] for r in rec.recent(kind="failover")] == ["b"]

    def test_failover_drill_captures_bundle(self, store):
        """Engine dies mid-stream -> transparent failover -> the recorder
        holds a 'failover' bundle with the dead instance, and the
        /admin/flightrecorder/recent endpoint serves it."""
        master = _master(store)
        engines = [_engine(store), _engine(store)]
        try:
            _await_fleet([master], engines)
            FAULTS.configure([dict(point="engine.token", action="crash",
                                   after=4, max_fires=1)], seed=SEED)
            assert _stream(master) == REPLY
            assert wait_until(
                lambda: RECORDER.recent(kind="failover"), timeout=10)
            flr = requests.get(
                _base(master) + "/admin/flightrecorder/recent",
                params={"kind": "failover"}, timeout=5).json()
            assert flr["num_records"] >= 1
            b = flr["records"][0]
            dead = next(e for e in engines if not e._alive)
            assert b["detail"]["dead_instance"] == dead.name
            assert b["service"]["is_master"] is True
            # The bundle froze the trace at anomaly time: the dead
            # incarnation's spans are in it.
            points = set()

            def walk(nodes):
                for n in nodes:
                    points.add(n["point"])
                    walk(n["children"])
            walk(b.get("trace", []))
            assert "frontend.request" in points or b["num_spans"] >= 1
        finally:
            for e in engines:
                e.stop()
            master.stop()


# ------------------------------------------------------- tail sampling
class TestTailSampling:
    def test_sampled_out_clean_trace_drops(self):
        tr = Tracer(capacity=64)
        tr.configure(sample_rate=0.0)
        sp = tr.start_span("frontend.request", request_id="clean-1")
        sp.end()
        # Pending (queryable by id) but not in the ring.
        assert tr.query_trace(request_id="clean-1")[0] == 200
        assert tr.query_recent()["traces"] == []
        tr.drop_trace(sp.trace_id)
        assert tr.query_trace(request_id="clean-1")[0] == 404

    def test_anomalous_trace_promotes(self):
        tr = Tracer(capacity=64)
        tr.configure(sample_rate=0.0)
        sp = tr.start_span("frontend.request", request_id="anom-1")
        child = tr.start_span("scheduler.schedule", ctx=sp.context(),
                              request_id="anom-1")
        child.end()
        sp.end()
        tr.keep_trace(sp.trace_id)
        recent = tr.query_recent()["traces"]
        assert [r["request_id"] for r in recent] == ["anom-1"]
        assert tr.query_trace(request_id="anom-1")[1]["num_spans"] == 2
        # Late span of a kept trace goes straight to the ring.
        late = tr.start_span("engine.decode", ctx=sp.context(),
                             request_id="anom-1")
        late.end()
        assert tr.query_trace(request_id="anom-1")[1]["num_spans"] == 3

    def test_rate_restored_to_one_still_settles_parked_traces(self):
        """Raising trace_sample_rate back to 1.0 live must not strand
        traces already parked in the pending buffer: their tail verdict
        (keep OR drop) still lands."""
        tr = Tracer(capacity=64)
        tr.configure(sample_rate=0.0)
        kept = tr.start_span("frontend.request", request_id="parked-keep")
        kept.end()
        dropped = tr.start_span("frontend.request", request_id="parked-drop")
        dropped.end()
        tr.configure(sample_rate=1.0)
        tr.keep_trace(kept.trace_id)      # anomaly verdict -> ring
        tr.drop_trace(dropped.trace_id)   # clean verdict -> gone
        assert [r["request_id"] for r in tr.query_recent()["traces"]] \
            == ["parked-keep"]
        assert tr.query_trace(request_id="parked-drop")[0] == 404

    def test_sampling_decision_is_deterministic_across_tracers(self):
        a, b = Tracer(), Tracer()
        a.configure(sample_rate=0.5)
        b.configure(sample_rate=0.5)
        ids = [f"trace-{i:04d}" for i in range(400)]
        va = [a.is_sampled(t) for t in ids]
        assert va == [b.is_sampled(t) for t in ids]
        # Rate lands in the right ballpark.
        assert 100 < sum(va) < 300

    def test_e2e_sampled_out_kept_only_on_anomaly(self, store):
        """sample_rate=0: a clean request leaves no queryable trace; a
        crash-failover request ALWAYS records, engine spans included."""
        master = _master(store, trace_sample_rate=0.0)
        engines = [_engine(store), _engine(store)]
        try:
            _await_fleet([master], engines)
            # Per-request scoping (not a globally-empty store check —
            # straggler spans from earlier tests' killed masters can
            # land in the shared ring at any point): the clean request's
            # OWN id must never be recorded at sample_rate=0.
            clean, clean_sid = _stream(master, want_sid=True)
            assert clean == REPLY and clean_sid
            time.sleep(0.3)
            recent = requests.get(_base(master) + "/admin/trace/recent",
                                  timeout=5).json()["traces"]
            assert clean_sid not in {r["request_id"] for r in recent}
            FAULTS.configure([dict(point="engine.token", action="crash",
                                   after=4, max_fires=1)], seed=SEED)
            text, sid = _stream(master, want_sid=True)
            assert text == REPLY and sid

            def kept():
                rows = requests.get(
                    _base(master) + "/admin/trace/recent",
                    timeout=5).json()["traces"]
                return any(r["request_id"] == sid for r in rows)
            assert wait_until(kept, timeout=10)
            got = requests.get(_base(master) + "/admin/trace",
                               params={"request_id": sid}, timeout=5).json()
            points = {s["point"] for s in got["spans"]}
            assert {"frontend.request", "scheduler.failover",
                    "engine.prefill"} <= points
        finally:
            for e in engines:
                e.stop()
            master.stop()


# -------------------------------------------------- fleet trace federation
class TestFleetTraceFederation:
    @pytest.mark.chaos
    def test_relayed_failed_over_request_one_tree(self, store):
        """Acceptance drill: master + 2 engines + a request relayed
        across 2 frontends that ALSO fails over mid-stream (engine crash)
        -> `/admin/trace?scope=fleet` assembles ONE tree whose root is
        the accepting frontend's relay span, containing the owner's
        frontend.request, the failover attempt, and BOTH engines' spans;
        every peer reports ok."""
        m1 = _master(store)
        m2 = _master(store)
        engines = [_engine(store), _engine(store)]
        try:
            _await_fleet([m1, m2], engines)
            okey = _key_owned_by(m1.scheduler.ownership,
                                 m2.scheduler.self_addr)
            FAULTS.configure([dict(point="engine.token", action="crash",
                                   after=4, max_fires=1)], seed=SEED)
            text, sid = _stream(m1, okey=okey, want_sid=True)
            assert text == REPLY and sid
            # Wait for THIS request's trace (not "any trace": a prior
            # test's straggler span would satisfy that immediately).
            assert wait_until(
                lambda: any(
                    r["request_id"] == sid
                    for r in requests.get(
                        _base(m1) + "/admin/trace/recent",
                        timeout=5).json()["traces"]), timeout=10)

            def fleet_has_failover():
                doc = _fleet_trace(m1, request_id=sid).json()
                pts = {s["point"] for s in doc.get("spans", ())}
                return "scheduler.failover" in pts
            assert wait_until(fleet_has_failover, timeout=10)
            got = _fleet_trace(m1, request_id=sid)
            assert got.status_code == 200, got.text
            doc = got.json()
            assert doc["scope"] == "fleet"
            # Every engine + the peer frontend was consulted. The crashed
            # engine's port is dead, so its marker may be non-ok — but
            # the peer frontend and the surviving engine answered.
            roles = {a: p["role"] for a, p in doc["peers"].items()}
            assert roles[m2.scheduler.self_addr] == "frontend"
            assert sum(1 for r in roles.values() if r == "engine") >= 1
            assert doc["peers"][m2.scheduler.self_addr]["status"] in (
                "ok", "no_spans")
            # ONE tree: the relay's root; owner + engines inside it.
            assert len(doc["tree"]) == 1
            root = doc["tree"][0]
            assert root["point"] == "frontend.request"
            assert root["attrs"].get("relay") is True

            points = set()

            def walk(nodes):
                for n in nodes:
                    points.add(n["point"])
                    walk(n["children"])
            walk(doc["tree"])
            assert {"frontend.request", "scheduler.schedule",
                    "scheduler.failover", "engine.prefill",
                    "engine.decode"} <= points
            # Both incarnations: prefill ran on both engines.
            prefills = [s for s in doc["spans"]
                        if s["point"] == "engine.prefill"]
            assert len({s["instance"] for s in prefills}) == 2
            # Dedup: merged spans are unique by span_id.
            ids = [s["span_id"] for s in doc["spans"]]
            assert len(ids) == len(set(ids)) == doc["num_spans"]
        finally:
            for e in engines:
                e.stop()
            m1.stop()
            m2.stop()

    def test_foreign_peer_spans_are_merged(self, store):
        """A peer's spans that THIS process never recorded appear in the
        fleet view (true network federation, not shared memory)."""
        master = _master(store)
        engine = _engine(store)
        peer = _SpanPeer()
        coord = InMemoryCoordination(store)
        try:
            _await_fleet([master], [engine])
            text, sid = _completion(master, want_sid=True)
            assert text == REPLY and sid
            local = requests.get(_base(master) + "/admin/trace",
                                 params={"request_id": sid},
                                 timeout=5).json()
            tid = local["trace_id"]
            root = next(s for s in local["spans"]
                        if s["point"] == "frontend.request")
            # The foreign peer holds an extra span of the same trace.
            from xllm_service_tpu.common.tracing import TraceContext
            fsp = peer.tracer.start_span(
                "kv_transfer.pull",
                ctx=TraceContext(trace_id=tid, span_id=root["span_id"]),
                request_id=sid, instance="foreign-peer")
            fsp.end()
            # Register the peer as a service member -> fleet target.
            coord.set(SERVICE_KEY_PREFIX + peer.addr,
                      json.dumps({"rpc_address": peer.addr}))
            assert wait_until(
                lambda: peer.addr in master.scheduler.ownership.members(),
                timeout=5)
            doc = _fleet_trace(master, trace_id=tid).json()
            assert doc["peers"][peer.addr]["status"] == "ok"
            foreign = [s for s in doc["spans"]
                       if s["instance"] == "foreign-peer"]
            assert len(foreign) == 1
            assert foreign[0]["parent_span_id"] == root["span_id"]
            # ... and it hangs under the local root in the merged tree.
            assert len(doc["tree"]) == 1
        finally:
            coord.rm(SERVICE_KEY_PREFIX + peer.addr)
            peer.stop()
            engine.stop()
            master.stop()

    @pytest.mark.chaos
    def test_dead_agent_partial_marker(self, store):
        """Kill one agent: the fleet query still answers 200 with the
        survivors' spans and a non-ok marker for the dead peer."""
        # Slow eviction so the dead agent stays a fan-out target.
        master = _master(store,
                         heartbeat_silence_to_suspect_s=3.0,
                         detect_disconnected_instance_interval_s=30.0,
                         fleet_peer_timeout_s=1.0)
        engines = [_engine(store), _engine(store)]
        try:
            _await_fleet([master], engines)
            text, sid = _completion(master, want_sid=True)
            assert text == REPLY and sid
            victim = next(e for e in engines
                          if any(s["instance"] == e.name for s in
                                 requests.get(
                                     _base(master) + "/admin/trace",
                                     params={"request_id": sid},
                                     timeout=5).json()["spans"]
                                 if s["point"].startswith("engine.")))
            victim.kill()
            time.sleep(0.2)
            doc = _fleet_trace(master, request_id=sid)
            assert doc.status_code == 200, doc.text
            doc = doc.json()
            status = doc["peers"][victim.name]["status"]
            assert status not in ("ok", "no_spans"), doc["peers"]
            # The view degraded (the dead agent's engine spans came from
            # the shared in-process store here, but the endpoint itself
            # stayed partial-not-erroring) and still has ONE root.
            assert len(doc["tree"]) == 1
        finally:
            for e in engines:
                e.stop()
            master.stop()


# ------------------------------------------------- fleet metrics federation
class TestFleetMetrics:
    def test_relabel_prometheus_text(self):
        text = ("# TYPE x_total counter\n"
                "x_total 3.0\n"
                'y_ms{instance="e1",phase="p"} 1.5\n'
                "garbage line\n")
        out = relabel_prometheus_text(text, "10.0.0.1:99", "frontend")
        assert ('x_total{instance="10.0.0.1:99",role="frontend"} 3.0'
                in out)
        # Pre-existing instance label survives as exported_instance.
        assert ('y_ms{exported_instance="e1",phase="p",'
                'instance="10.0.0.1:99",role="frontend"} 1.5') in out
        assert "garbage" not in out
        assert "# TYPE x_total counter" in out

    def test_fleet_scrape_merges_and_survives_dead_agent(self, store):
        master = _master(store,
                         heartbeat_silence_to_suspect_s=3.0,
                         detect_disconnected_instance_interval_s=30.0,
                         fleet_peer_timeout_s=1.0)
        m2 = _master(store,
                     heartbeat_silence_to_suspect_s=3.0,
                     detect_disconnected_instance_interval_s=30.0)
        engines = [_engine(store), _engine(store)]
        try:
            _await_fleet([master, m2], engines)
            assert _completion(master) == REPLY
            text = requests.get(_base(master) + "/metrics/fleet",
                                timeout=15).text
            # Engine series re-labeled by instance/role.
            for e in engines:
                assert (f'engine_running_requests{{instance="{e.name}",'
                        f'role="engine"}}') in text
            # Peer frontend series present, labeled frontend.
            peer_addr = m2.scheduler.self_addr
            assert f'instance="{peer_addr}",role="frontend"' in text
            # Master's own per-engine series keep their original label as
            # exported_instance (no duplicate 'instance' key).
            assert "exported_instance=" in text
            # Kill an agent: scrape stays 200, dead target marked down.
            engines[0].kill()
            time.sleep(0.2)
            r = requests.get(_base(master) + "/metrics/fleet", timeout=15)
            assert r.status_code == 200
            assert (f'fleet_scrape_up{{instance="{engines[0].name}",'
                    f'role="engine"}} 0') in r.text
            assert (f'fleet_scrape_up{{instance="{engines[1].name}",'
                    f'role="engine"}} 1') in r.text
        finally:
            for e in engines:
                e.stop()
            master.stop()
            m2.stop()

    def test_fleet_scrape_ttl_cache(self, store):
        master = _master(store, metrics_fleet_cache_ttl_s=30.0)
        engine = _engine(store)
        try:
            _await_fleet([master], [engine])
            t1 = requests.get(_base(master) + "/metrics/fleet",
                              timeout=15).text
            engine.kill()   # within the TTL the cached merge still serves
            t2 = requests.get(_base(master) + "/metrics/fleet",
                              timeout=15).text
            assert t1 == t2
        finally:
            engine.stop()
            master.stop()


# ------------------------------------------- owner-kill flight-record drill
class TestOwnerKillDrill:
    pytestmark = pytest.mark.chaos

    def test_owner_kill_captures_handoff_recovery(self, store):
        """The multimaster owner-kill drill is self-documenting now: the
        relay's re-ownership lands a handoff_recovery bundle in the
        flight recorder (chaos_soak.sh --obs asserts this leg)."""
        m1 = _master(store)
        m2 = _master(store)
        engine = _engine(store, delay_s=0.05)
        reaper = None
        try:
            _await_fleet([m1, m2], [engine])
            okey = _key_owned_by(m1.scheduler.ownership,
                                 m2.scheduler.self_addr)
            holder = {}

            def kill_owner():
                holder["t"] = m2.kill()

            text = _stream(m1, okey=okey, after_frames=3, hook=kill_owner)
            reaper = holder.get("t")
            assert text == REPLY     # stream completed on the survivor
            recs = RECORDER.recent(kind="handoff_recovery")
            assert recs, "owner-kill drill captured no recovery bundle"
            b = recs[0]
            assert b["detail"]["dead_owner"] == m2.scheduler.self_addr
            assert b["detail"]["successor"] == m1.scheduler.self_addr
        finally:
            if reaper is not None:
                reaper.join(timeout=10)
            engine.stop()
            m1.stop()
            m2.stop()


# ------------------------------------------------ agent series eviction
class TestAgentSeriesEviction:
    class _Req:
        def __init__(self, body):
            self._body = body

        async def json(self):
            return self._body

    def test_unlink_evicts_peer_series(self):
        ENGINE_PEER_LINKED.labels(peer="p1:1").set(1)
        ENGINE_PEER_LINKED.labels(peer="p2:2").set(1)
        agent = SimpleNamespace(linked_peers={"p1:1": object(),
                                              "p2:2": object()})
        resp = asyncio.run(EngineAgent._h_unlink(
            agent, self._Req({"peer_name": "p1:1"})))
        assert resp.status == 200
        text = ENGINE_PEER_LINKED.render()
        assert 'peer="p1:1"' not in text
        assert 'peer="p2:2"' in text
        # Unknown peer: no-op, nothing re-created.
        asyncio.run(EngineAgent._h_unlink(
            agent, self._Req({"peer_name": "nope"})))
        assert 'peer="nope"' not in ENGINE_PEER_LINKED.render()
        ENGINE_PEER_LINKED.remove(peer="p2:2")

    def test_master_change_evicts_heartbeat_series(self):
        from xllm_service_tpu.rpc import wire
        ENGINE_HEARTBEATS_TOTAL.labels(master="old:1").inc(5)
        agent = SimpleNamespace(_hb_master="old:1",
                                _hb_wire=wire.WIRE_JSON)
        EngineAgent._note_master(agent, "new:2")
        assert agent._hb_master == "new:2"
        # Wire re-probes msgpack against the new master...
        assert agent._hb_wire == wire.WIRE_MSGPACK
        # ...and the dead master's labeled series is gone.
        assert 'master="old:1"' not in ENGINE_HEARTBEATS_TOTAL.render()
        # Same master again: no churn.
        EngineAgent._note_master(agent, "new:2")
        ENGINE_HEARTBEATS_TOTAL.remove(master="new:2")


# --------------------------------------------------------- bench trend
class TestBenchTrend:
    def _run(self, root: Path, *args):
        return subprocess.run(
            [sys.executable, str(REPO / "scripts" / "bench_trend.py"),
             "--root", str(root), *args],
            capture_output=True, text=True)

    def test_regression_fails(self, tmp_path):
        (tmp_path / "BENCH_hotpath_r06.json").write_text(json.dumps(
            {"headline": {"sustained_req_per_s_conc8": {"after": 17.3}}}))
        (tmp_path / "BENCH_hotpath_r10.json").write_text(json.dumps(
            {"headline": {"sustained_req_per_s_conc8": {"after": 12.0}}}))
        r = self._run(tmp_path)
        assert r.returncode == 1, r.stdout
        assert "FAIL" in r.stdout
        assert "sustained_req_per_s_conc8" in r.stdout

    def test_improvement_and_small_drift_pass(self, tmp_path):
        (tmp_path / "BENCH_kvtier_r09.json").write_text(json.dumps(
            {"tier_ttft": {"warm_vs_cold_speedup": 3.56},
             "capacity": {"capacity_multiplier": 3.73},
             "step_latency": {"delta_p50_perc": 0.58}}))
        (tmp_path / "BENCH_kvtier_r11.json").write_text(json.dumps(
            {"tier_ttft": {"warm_vs_cold_speedup": 3.40},   # -4.5%: ok
             "capacity": {"capacity_multiplier": 4.1},      # better
             "step_latency": {"delta_p50_perc": 0.60}}))
        r = self._run(tmp_path)
        assert r.returncode == 0, r.stdout

    def test_pct_headline_judged_in_absolute_points(self, tmp_path):
        # A noise-floor baseline (even negative) must not disarm the
        # tripwire: +15 points of tracing overhead fails ...
        (tmp_path / "BENCH_tracing_r10.json").write_text(json.dumps(
            {"headline": {"ring_overhead_p50_pct": -7.2}}))
        (tmp_path / "BENCH_tracing_r12.json").write_text(json.dumps(
            {"headline": {"ring_overhead_p50_pct": 8.0}}))
        r = self._run(tmp_path)
        assert r.returncode == 1
        assert "ring_overhead_p50_pct" in r.stdout
        # ... while drift inside the threshold (points, not relative —
        # -7.2 -> -0.5 is +1300% relative but only +6.7 points) passes.
        (tmp_path / "BENCH_tracing_r12.json").write_text(json.dumps(
            {"headline": {"ring_overhead_p50_pct": -0.5}}))
        r = self._run(tmp_path)
        assert r.returncode == 0, r.stdout

    def test_single_round_and_missing_paths_are_not_errors(self, tmp_path):
        (tmp_path / "BENCH_kvcache_r07.json").write_text(json.dumps(
            {"index": {"match_new": {"throughput_1t_per_s": 57444.5}}}))
        (tmp_path / "BENCH_solo_r01.json").write_text(json.dumps({}))
        r = self._run(tmp_path)
        assert r.returncode == 0, r.stdout
        assert "nothing to diff" in r.stdout

    def test_real_repo_artifacts_pass(self):
        r = self._run(REPO)
        assert r.returncode == 0, r.stdout
