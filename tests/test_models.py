"""Model correctness: paged incremental decode == dense full prefill, prefix
cache reuse == recompute, sharded == single-device."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xllm_service_tpu.models.base import tiny_config, get_model_family


def alloc_pages(cfg, num_pages, page_size):
    return jnp.zeros((cfg.num_layers, 2, num_pages, cfg.num_kv_heads,
                      page_size, cfg.head_dim), cfg.dtype)


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_config(dtype=jnp.float32)  # f32 on CPU for tight comparison
    fam = get_model_family("llama")
    params = fam.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, fam, params


PAGE = 16


class TestLlamaPagedCorrectness:
    def test_decode_matches_full_prefill(self, setup):
        cfg, fam, params = setup
        T = 33
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, T), 0,
                                  cfg.vocab_size)
        pt = jnp.arange(8, dtype=jnp.int32)[None, :]   # pages 0..7
        pos = jnp.arange(T)[None, :]

        # Full prefill over all T tokens.
        kv = alloc_pages(cfg, 8, PAGE)
        logits_full, _ = fam.prefill_forward(
            params, cfg, toks, pos, kv, pt,
            jnp.zeros((1,), jnp.int32), jnp.array([T], jnp.int32))

        # Prefill T-1 then decode token T-1.
        kv2 = alloc_pages(cfg, 8, PAGE)
        _, kv2 = fam.prefill_forward(
            params, cfg, toks[:, :T - 1], pos[:, :T - 1], kv2, pt,
            jnp.zeros((1,), jnp.int32), jnp.array([T - 1], jnp.int32))
        logits_dec, _ = fam.decode_forward(
            params, cfg, toks[:, T - 1], jnp.array([T - 1], jnp.int32),
            kv2, pt, jnp.array([T], jnp.int32))

        np.testing.assert_allclose(np.asarray(logits_full),
                                   np.asarray(logits_dec), rtol=2e-4, atol=2e-4)

    def test_prefix_cached_prefill_matches_recompute(self, setup):
        cfg, fam, params = setup
        T, K = 48, 32   # K must be page-aligned (2 pages of 16)
        toks = jax.random.randint(jax.random.PRNGKey(2), (1, T), 0,
                                  cfg.vocab_size)
        pt = jnp.arange(8, dtype=jnp.int32)[None, :]
        pos = jnp.arange(T)[None, :]

        kv_a = alloc_pages(cfg, 8, PAGE)
        logits_a, _ = fam.prefill_forward(
            params, cfg, toks, pos, kv_a, pt,
            jnp.zeros((1,), jnp.int32), jnp.array([T], jnp.int32))

        # Prefill prefix, then prefill only the suffix with prefix_lens=K.
        kv_b = alloc_pages(cfg, 8, PAGE)
        _, kv_b = fam.prefill_forward(
            params, cfg, toks[:, :K], pos[:, :K], kv_b, pt,
            jnp.zeros((1,), jnp.int32), jnp.array([K], jnp.int32))
        logits_b, _ = fam.prefill_forward(
            params, cfg, toks[:, K:], pos[:, K:], kv_b, pt,
            jnp.array([K], jnp.int32), jnp.array([T - K], jnp.int32))

        np.testing.assert_allclose(np.asarray(logits_a), np.asarray(logits_b),
                                   rtol=2e-4, atol=2e-4)

    def test_padding_rows_ignored(self, setup):
        """Batch rows with different lengths: padded positions must not leak."""
        cfg, fam, params = setup
        toks = jax.random.randint(jax.random.PRNGKey(3), (2, 24), 0,
                                  cfg.vocab_size)
        pt = jnp.stack([jnp.arange(4), jnp.arange(4, 8)]).astype(jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(24)[None, :], (2, 24))
        kv = alloc_pages(cfg, 8, PAGE)
        seq_lens = jnp.array([24, 10], jnp.int32)
        logits_batch, _ = fam.prefill_forward(
            params, cfg, toks, pos, kv, pt, jnp.zeros((2,), jnp.int32),
            seq_lens)

        # Row 1 alone, unpadded.
        kv1 = alloc_pages(cfg, 8, PAGE)
        logits_single, _ = fam.prefill_forward(
            params, cfg, toks[1:2, :10], pos[1:2, :10], kv1,
            pt[1:2] - 4, jnp.zeros((1,), jnp.int32),
            jnp.array([10], jnp.int32))
        np.testing.assert_allclose(np.asarray(logits_batch[1]),
                                   np.asarray(logits_single[0]),
                                   rtol=2e-4, atol=2e-4)

    def test_sharded_matches_single_device(self, setup):
        cfg, fam, params = setup
        from xllm_service_tpu.parallel.mesh import MeshConfig, build_mesh
        from xllm_service_tpu.parallel.sharding import shard_params
        from xllm_service_tpu.models.llama import LLAMA_STACKED_RULES

        mesh = build_mesh(MeshConfig(data=1, model=2),
                          devices=jax.devices()[:2])
        sharded = shard_params(params, mesh, LLAMA_STACKED_RULES)
        toks = jax.random.randint(jax.random.PRNGKey(4), (1, 16), 0,
                                  cfg.vocab_size)
        pt = jnp.arange(4, dtype=jnp.int32)[None, :]
        pos = jnp.arange(16)[None, :]
        args = (toks, pos, alloc_pages(cfg, 4, PAGE), pt,
                jnp.zeros((1,), jnp.int32), jnp.array([16], jnp.int32))
        ref, _ = fam.prefill_forward(params, cfg, *args)
        with mesh:
            got, _ = jax.jit(
                lambda p, *a: fam.prefill_forward(p, cfg, *a))(sharded, *args)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                                   rtol=2e-3, atol=2e-3)
