"""ICI-topology placement plane (ISSUE 20).

Five layers, cheapest first:

1. the pure link-cost kernel (`common/topology.py`) as input->output
   tables — placement semantics, link classes, budget fallbacks, the
   KV-layout payload estimate, the armed bit;
2. routing consumers in-process: RR's same-slice decode pool, CAR's
   `topology_tradeoff` boundary, the SLO policy's cheapest-link-first
   scan + modeled transfer time, the scheduled pair-link census —
   each with a FLAT control proving dormancy (zero routing change);
3. the autoscaler controller's lost-slice census: a replacement
   scale-out targets the slice the failure emptied, and a flat fleet's
   spawn commands carry no slice id;
4. the slice-death chaos drill: a whole slice dies hard and the fleet
   re-converges onto survivor same-slice pairs with ZERO survivor
   SUSPECT transitions (no detector storm) and streams still serving.

`scripts/check.sh` re-runs this file under combined LOCK+RCU+STATE
instrumentation — the census/counter paths must hold their declared
lock disciplines (devtools/ownership.py).
"""

import pytest
import requests

from xllm_service_tpu.common import topology as topo
from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.request import Request
from xllm_service_tpu.common.types import (
    InstanceRuntimeState,
    InstanceType,
    LatencyMetrics,
    LoadMetrics,
    RequestAction,
    Routing,
    TpuTopology,
)
from xllm_service_tpu.autoscaler.actuator import FleetActuator
from xllm_service_tpu.coordination.memory import InMemoryCoordination
from xllm_service_tpu.master import Master
from xllm_service_tpu.scheduler.global_kvcache_mgr import GlobalKVCacheMgr
from xllm_service_tpu.scheduler.instance_mgr import InstanceMgr
from xllm_service_tpu.scheduler.policies import create_policy
from xllm_service_tpu.scheduler.policies.slo_aware import select_pair_on_slo
from xllm_service_tpu.testing.fake_engine import FakeEngine, FakeEngineConfig

from fakes import FakeChannel, make_meta, wait_until

BLOCK = 16


@pytest.fixture()
def coord(store):
    c = InMemoryCoordination(store)
    yield c
    c.close()


@pytest.fixture(autouse=True)
def _reset_channels():
    FakeChannel.reset()
    yield
    FakeChannel.reset()


def _opts(**kw) -> ServiceOptions:
    base = dict(block_size=BLOCK, reconcile_interval_s=0.05)
    base.update(kw)
    return ServiceOptions(**base)


def _coord_of(slice_id, host, chip=-1):
    return topo.effective_coord(
        TpuTopology(slice_id=slice_id, host=host, chip=chip), "n:1")


# ---------------------------------------------------------------------------
# 1) The pure kernel, as tables.
# ---------------------------------------------------------------------------
class TestKernel:
    @pytest.mark.parametrize("a,b,expect", [
        # Same host: the handoff never leaves the machine.
        (("s0", "h0"), ("s0", "h0"), topo.LINK_LOCAL),
        # Same host wins even across declared slices (host is physical).
        (("s0", "h0"), ("s1", "h0"), topo.LINK_LOCAL),
        # Same slice, different host: ICI.
        (("s0", "h0"), ("s0", "h1"), topo.LINK_ICI),
        # Different slices: DCN, the slow path.
        (("s0", "h0"), ("s1", "h1"), topo.LINK_DCN),
    ])
    def test_link_class_table(self, a, b, expect):
        assert topo.link_class(_coord_of(*a), _coord_of(*b)) == expect

    def test_link_class_empty_slices_are_dcn(self):
        # Degenerate coords (no slice, no host) must not accidentally
        # classify as matching: "" == "" is not a locality claim.
        assert topo.link_class(topo.Coord("", ""),
                               topo.Coord("", "")) == topo.LINK_DCN

    @pytest.mark.parametrize("name,slice_id,host,want", [
        # Operator-placed: host set => placed, declared slice kept.
        ("10.0.0.1:9000", "slice-a", "host-a0",
         topo.Coord("slice-a", "host-a0", -1, True)),
        # Host set, slice empty => per-host slice, still PLACED.
        ("10.0.0.1:9000", "", "host-a0",
         topo.Coord("host:host-a0", "host-a0", -1, True)),
        # Unplaced (no host): synthetic per-host coordinate from the
        # registry name; slice_id alone never places (agents have always
        # defaulted slice_id, so keying off it would re-route every
        # existing deployment).
        ("10.0.0.1:9000", "slice-a", "",
         topo.Coord("host:10.0.0.1", "10.0.0.1", -1, False)),
    ])
    def test_effective_coord_table(self, name, slice_id, host, want):
        got = topo.effective_coord(
            TpuTopology(slice_id=slice_id, host=host), name)
        assert got == want

    def test_effective_coord_none_topology(self):
        got = topo.effective_coord(None, "box:8000")
        assert got == topo.Coord("host:box", "box", -1, False)

    def test_transfer_cost_zero_budget_uses_class_defaults(self):
        # Budget 0 = account-only on the engine side; the kernel falls
        # back to class defaults so the ordering local < ici < dcn
        # survives on unthrottled fleets.
        n = 10 ** 9
        local = topo.transfer_cost(n, topo.LINK_LOCAL)
        ici = topo.transfer_cost(n, topo.LINK_ICI)
        dcn = topo.transfer_cost(n, topo.LINK_DCN)
        assert 0 < local < ici < dcn
        assert ici == pytest.approx(n / topo.DEFAULT_BYTES_PER_S["ici"])

    def test_transfer_cost_budget_overrides(self):
        assert topo.transfer_cost(1000, topo.LINK_ICI,
                                  ici_bytes_per_s=500.0) \
            == pytest.approx(2.0)
        assert topo.transfer_cost(1000, topo.LINK_DCN,
                                  dcn_bytes_per_s=250.0) \
            == pytest.approx(4.0)
        # local ignores both budgets: the accountant has no intra-host
        # budget to borrow.
        assert topo.transfer_cost(1000, topo.LINK_LOCAL,
                                  ici_bytes_per_s=1.0,
                                  dcn_bytes_per_s=1.0) \
            == pytest.approx(1000 / topo.DEFAULT_BYTES_PER_S["local"])

    @pytest.mark.parametrize("nbytes", [0, -5])
    def test_transfer_cost_nonpositive_is_free(self, nbytes):
        assert topo.transfer_cost(nbytes, topo.LINK_DCN) == 0.0

    @pytest.mark.parametrize("dtype,itemsize", [
        ("bfloat16", 2), ("float16", 2), ("float32", 4),
        ("int8", 1), ("fp8_e4m3", 1), ("", 2),
    ])
    def test_kv_handoff_bytes_dtype_table(self, dtype, itemsize):
        meta = make_meta("e1", num_layers=4, num_kv_heads=8, head_dim=128,
                         kv_dtype=dtype)
        # 2 (K+V) * layers * heads * head_dim * itemsize * tokens
        assert topo.kv_handoff_bytes(meta, 10) \
            == 2 * 4 * 8 * 128 * itemsize * 10

    def test_kv_handoff_bytes_unadvertised_layout_is_zero(self):
        # Fake engines advertise no KV layout: callers substitute their
        # own modeled payload.
        assert topo.kv_handoff_bytes(make_meta("e1"), 10) == 0
        assert topo.kv_handoff_bytes(None, 10) == 0
        assert topo.kv_handoff_bytes(
            make_meta("e1", num_layers=4, num_kv_heads=8, head_dim=128), 0) \
            == 0

    def test_fleet_topo_active(self):
        a0 = topo.Coord("slice-a", "h0", placed=True)
        a1 = topo.Coord("slice-a", "h1", placed=True)
        b0 = topo.Coord("slice-b", "h2", placed=True)
        assert not topo.fleet_topo_active([])
        assert not topo.fleet_topo_active([a0, a1])
        assert topo.fleet_topo_active([a0, a1, b0])

    def test_link_penalty_ordering(self):
        assert topo.link_penalty(topo.LINK_LOCAL) == 0.0
        assert topo.link_penalty(topo.LINK_LOCAL) \
            < topo.link_penalty(topo.LINK_ICI) \
            < topo.link_penalty(topo.LINK_DCN)
        # Unknown classes cost like the slow path, never like a freebie.
        assert topo.link_penalty("unknown") \
            == topo.link_penalty(topo.LINK_DCN)


# ---------------------------------------------------------------------------
# 2) Routing consumers over a live InstanceMgr (fake channels).
# ---------------------------------------------------------------------------
def _placed_fleet(coord, opts=None):
    """One prefill on slice-a, one same-slice decode, two cross-slice
    decodes — the DCN decodes register FIRST so the legacy scan order
    (registration order) would pick a cross-slice partner."""
    mgr = InstanceMgr(coord, opts or _opts(), start_threads=False,
                      channel_factory=FakeChannel.factory)
    mgr.register_instance(
        make_meta("pa", InstanceType.PREFILL,
                  slice_id="slice-a", topo_host="host-a0"),
        link_peers=False)
    mgr.register_instance(
        make_meta("dfar", InstanceType.DECODE,
                  slice_id="slice-b", topo_host="host-b0"),
        link_peers=False)
    mgr.register_instance(
        make_meta("dfar2", InstanceType.DECODE,
                  slice_id="slice-b", topo_host="host-b1"),
        link_peers=False)
    mgr.register_instance(
        make_meta("dnear", InstanceType.DECODE,
                  slice_id="slice-a", topo_host="host-a1"),
        link_peers=False)
    return mgr


def _flat_fleet(coord, opts=None):
    """Same shape, no placement: every meta keeps the default empty
    topo_host, so all coordinates are synthetic."""
    mgr = InstanceMgr(coord, opts or _opts(), start_threads=False,
                      channel_factory=FakeChannel.factory)
    mgr.register_instance(make_meta("pa", InstanceType.PREFILL),
                          link_peers=False)
    for n in ("dfar", "dfar2", "dnear"):
        mgr.register_instance(make_meta(n, InstanceType.DECODE),
                              link_peers=False)
    return mgr


def _heartbeat_all(mgr, **per_name_loads):
    for meta in mgr.list_instances():
        mgr.record_instance_heartbeat(
            meta.name, meta.incarnation_id,
            per_name_loads.get(meta.name, LoadMetrics()), LatencyMetrics())


class TestRoutingConsumers:
    def test_rr_pairs_within_prefill_slice(self, coord):
        mgr = _placed_fleet(coord)
        decodes = {mgr.get_next_instance_pair().decode_name
                   for _ in range(6)}
        # RR carries no load signal, so locality simply wins: every pair
        # stays on the prefill's slice.
        assert decodes == {"dnear"}
        mgr.stop()

    def test_rr_falls_back_fleetwide_when_slice_has_no_decode(self, coord):
        mgr = InstanceMgr(coord, _opts(), start_threads=False,
                          channel_factory=FakeChannel.factory)
        mgr.register_instance(
            make_meta("pa", InstanceType.PREFILL,
                      slice_id="slice-a", topo_host="host-a0"),
            link_peers=False)
        for i, n in enumerate(("d1", "d2")):
            mgr.register_instance(
                make_meta(n, InstanceType.DECODE,
                          slice_id="slice-b", topo_host=f"host-b{i}"),
                link_peers=False)
        decodes = {mgr.get_next_instance_pair().decode_name
                   for _ in range(4)}
        assert decodes == {"d1", "d2"}   # no local decode: full RR pool
        mgr.stop()

    def test_rr_flat_fleet_unchanged(self, coord):
        # Dormancy: an unplaced fleet keeps the legacy fleet-wide RR even
        # with the tradeoff knob at its non-zero default.
        mgr = _flat_fleet(coord)
        decodes = [mgr.get_next_instance_pair().decode_name
                   for _ in range(6)]
        assert set(decodes) == {"dfar", "dfar2", "dnear"}
        mgr.stop()

    def test_rr_knob_zero_disarms_placed_fleet(self, coord):
        mgr = _placed_fleet(coord, _opts(topology_tradeoff=0.0))
        decodes = {mgr.get_next_instance_pair().decode_name
                   for _ in range(6)}
        assert decodes == {"dfar", "dfar2", "dnear"}
        mgr.stop()

    # -- CAR: the tradeoff knob is a score-unit boundary -------------------
    def _car(self, coord, tradeoff, waiting_near=2):
        opts = _opts(max_waiting_requests=10, topology_tradeoff=tradeoff)
        mgr = _placed_fleet(coord, opts)
        kv = GlobalKVCacheMgr(coord, block_size=BLOCK)
        # Fresh telemetry everywhere (the stale-discount set must stay
        # empty); the same-slice decode carries the queue.
        _heartbeat_all(mgr, dnear=LoadMetrics(
            waiting_requests_num=waiting_near))
        policy = create_policy("CAR", mgr, kv, opts)
        r = policy.select_instances_pair(
            Request(token_ids=list(range(BLOCK * 2))))
        mgr.stop()
        return r

    def test_car_same_slice_wins_within_knob(self, coord):
        # dnear is docked waiting/max_waiting = 0.2 score units; the DCN
        # candidates are docked tradeoff * (penalty_dcn - penalty_ici)
        # ~= 0.97 * t relative to it. t = 0.25 => 0.2425 > 0.2: locality
        # absorbs the load skew.
        r = self._car(coord, tradeoff=0.25)
        assert r.prefill_name == "pa"
        assert r.decode_name == "dnear"

    def test_car_load_skew_beyond_knob_pays_dcn(self, coord):
        # t = 0.15 => 0.1455 < 0.2: the load advantage exceeds the knob
        # and the cross-slice candidate wins — the knob is a boundary,
        # not a veto.
        r = self._car(coord, tradeoff=0.15)
        assert r.decode_name in ("dfar", "dfar2")

    def test_car_knob_zero_is_legacy_scoring(self, coord):
        r = self._car(coord, tradeoff=0.0)
        assert r.decode_name in ("dfar", "dfar2")

    def test_car_flat_fleet_ignores_knob(self, coord):
        # Unplaced fleet: every candidate pays the same synthetic-DCN
        # penalty, so the knob cannot change the argmax.
        opts = _opts(max_waiting_requests=10, topology_tradeoff=0.25)
        mgr = _flat_fleet(coord, opts)
        kv = GlobalKVCacheMgr(coord, block_size=BLOCK)
        _heartbeat_all(mgr, dnear=LoadMetrics(waiting_requests_num=2))
        policy = create_policy("CAR", mgr, kv, opts)
        r = policy.select_instances_pair(
            Request(token_ids=list(range(BLOCK * 2))))
        assert r.decode_name in ("dfar", "dfar2")   # least loaded, as ever
        mgr.stop()

    # -- SLO: cheapest-link-first scan + modeled transfer ------------------
    def test_slo_scans_cheapest_link_first(self, coord):
        # Registration order puts the DCN decodes first; without the
        # topology sort the first candidate meeting the TPOT target is
        # "dfar". With it, the ICI partner is scanned first.
        opts = _opts(topology_kv_bytes_per_token=1024,
                     topology_ici_bytes_per_s=1e6,
                     topology_dcn_bytes_per_s=1e5)
        mgr = _placed_fleet(coord, opts)
        req = Request(token_ids=list(range(32)))
        r = select_pair_on_slo(mgr, opts, req, flip_sink=lambda *a: None)
        assert (r.prefill_name, r.decode_name) == ("pa", "dnear")
        # Predicted TTFT carries the modeled wire time for the chosen
        # pair: 32 tok * 1024 B / 1e6 B/s = 32.77 ms.
        assert req.metrics.estimated_ttft_ms \
            == pytest.approx(32.768, rel=0.01)
        mgr.stop()

    def test_slo_knob_zero_keeps_legacy_scan_order(self, coord):
        opts = _opts(topology_tradeoff=0.0,
                     topology_kv_bytes_per_token=1024)
        mgr = _placed_fleet(coord, opts)
        req = Request(token_ids=list(range(32)))
        r = select_pair_on_slo(mgr, opts, req, flip_sink=lambda *a: None)
        assert r.decode_name == "dfar"   # first registered, legacy order
        # No transfer model joins the estimate when the knob is off (and
        # the unfitted predictor contributes 0).
        assert req.metrics.estimated_ttft_ms == 0.0
        mgr.stop()

    # -- pair-link census --------------------------------------------------
    def test_scheduled_pair_link_census(self, coord):
        mgr = _placed_fleet(coord)

        def sched(p, d):
            req = Request(token_ids=list(range(8)))
            req.routing = Routing(prefill_name=p, decode_name=d)
            mgr.update_request_metrics(req, RequestAction.SCHEDULE)

        sched("pa", "dnear")    # same slice, different host -> ici
        sched("pa", "dfar")     # cross slice -> dcn
        sched("pa", "dfar")
        sched("pa", "pa")       # collapsed pair -> mix
        assert mgr.pair_link_counts() == {"ici": 1, "dcn": 2, "mix": 1}
        assert mgr.stats()["topology"]["pair_links"] \
            == {"ici": 1, "dcn": 2, "mix": 1}
        mgr.stop()

    def test_snapshot_exports_topology_view(self, coord):
        mgr = _placed_fleet(coord)
        snap = mgr.routing_snapshot()
        assert snap.topo_active
        assert snap.coords["pa"] \
            == topo.Coord("slice-a", "host-a0", -1, True)
        assert set(snap.decode_by_slice["slice-a"]) == {"dnear"}
        assert set(snap.decode_by_slice["slice-b"]) == {"dfar", "dfar2"}
        stats = mgr.stats()["topology"]
        assert stats["active"]
        assert stats["coords"]["dnear"]["slice_id"] == "slice-a"
        mgr.stop()

    def test_flat_snapshot_stays_dormant(self, coord):
        mgr = InstanceMgr(coord, _opts(), start_threads=False,
                          channel_factory=FakeChannel.factory)
        # One-box flat fleet: names share the host part, so all synthetic
        # coordinates collapse into one slice.
        for i, t in enumerate((InstanceType.PREFILL, InstanceType.DECODE,
                               InstanceType.DECODE)):
            mgr.register_instance(
                make_meta(f"127.0.0.1:{9000 + i}", t), link_peers=False)
        snap = mgr.routing_snapshot()
        assert not snap.topo_active
        assert all(not c.placed for c in snap.coords.values())
        mgr.stop()


# ---------------------------------------------------------------------------
# 3) Controller: replacement spawns target the slice that lost capacity.
# ---------------------------------------------------------------------------
class _SliceRecordingActuator(FleetActuator):
    name = "slice-recording"

    def __init__(self):
        self.calls: list[tuple[int, str]] = []   # (count, slice_id)

    def scale_out(self, count, reason, slice_id=""):
        self.calls.append((count, slice_id))
        return count

    def scale_in(self, instance, reason):
        return True


def _controller_opts(**kw) -> ServiceOptions:
    base = dict(autoscaler_enabled=True, autoscaler_breach_ticks=2,
                autoscaler_min_instances=1, autoscaler_max_instances=8,
                autoscaler_stale_hold_s=30.0)
    base.update(kw)
    return ServiceOptions(**base)


class TestReplacementTargetsLostSlice:
    def _tick_fleet(self, coord, metas):
        from xllm_service_tpu.autoscaler import AutoscalerController
        from xllm_service_tpu.common.slo import SloMonitor

        opts = _controller_opts()
        mgr = InstanceMgr(coord, opts, start_threads=False,
                          channel_factory=FakeChannel.factory)
        for m in metas:
            mgr.register_instance(m, link_peers=False)
        act = _SliceRecordingActuator()
        ctl = AutoscalerController(opts, mgr, act,
                                   is_master_fn=lambda: True,
                                   slo_monitor=SloMonitor())
        return mgr, act, ctl

    def test_replacement_lands_on_lost_slice(self, coord):
        mgr, act, ctl = self._tick_fleet(coord, [
            make_meta("pa", InstanceType.MIX,
                      slice_id="slice-a", topo_host="host-a0"),
            make_meta("da", InstanceType.MIX,
                      slice_id="slice-a", topo_host="host-a1"),
            make_meta("pb", InstanceType.MIX,
                      slice_id="slice-b", topo_host="host-b0"),
            make_meta("db", InstanceType.MIX,
                      slice_id="slice-b", topo_host="host-b1"),
        ])
        _heartbeat_all(mgr)
        rec = ctl.tick()    # census {a: 2, b: 2}; desired raised to 4
        assert rec["actions"] == []
        assert ctl.report()["slice_census"] == {"slice-a": 2, "slice-b": 2}

        # slice-b dies between ticks (hard loss: both instances gone).
        mgr.deregister_instance("pb")
        mgr.deregister_instance("db")
        _heartbeat_all(mgr)
        rec = ctl.tick()    # live 2 < desired 4: hysteresis-free replace
        kinds = [a["kind"] for a in rec["actions"]]
        assert kinds == ["scale_out"]
        assert rec["enacted"][0]["target_slice"] == "slice-b"
        assert act.calls == [(2, "slice-b")]
        assert "slice-b" not in ctl.report()["lost_slices"]  # consumed
        mgr.stop()

    def test_flat_fleet_spawns_carry_no_slice(self, coord):
        # Control: the identical drill on an UNPLACED fleet must keep the
        # spawn call byte-identical to the legacy path (slice_id "").
        mgr, act, ctl = self._tick_fleet(coord, [
            make_meta("e1"), make_meta("e2"),
            make_meta("e3"), make_meta("e4"),
        ])
        _heartbeat_all(mgr)
        ctl.tick()
        assert ctl.report()["slice_census"] == {}   # never armed
        mgr.deregister_instance("e3")
        mgr.deregister_instance("e4")
        _heartbeat_all(mgr)
        rec = ctl.tick()
        assert [a["kind"] for a in rec["actions"]] == ["scale_out"]
        assert "target_slice" not in rec["enacted"][0]
        assert act.calls == [(2, "")]
        mgr.stop()


# ---------------------------------------------------------------------------
# 4) Slice-death chaos drill: converge without a SUSPECT storm.
# ---------------------------------------------------------------------------
def _drill_opts(**kw) -> ServiceOptions:
    base = dict(
        host="127.0.0.1", http_port=0, rpc_port=0,
        lease_ttl_s=0.5, reconcile_interval_s=0.05,
        heartbeat_silence_to_suspect_s=0.3,
        detect_disconnected_instance_interval_s=0.3,
        health_probe_attempts=1, health_probe_timeout_s=0.2,
        sync_interval_s=0.2,
        failover_backoff_base_s=0.05, failover_backoff_max_s=0.3,
        rpc_backoff_base_s=0.02, rpc_backoff_max_s=0.1)
    base.update(kw)
    return ServiceOptions(**base)


def _placed_engine(store, itype, slice_id, host) -> FakeEngine:
    cfg = FakeEngineConfig(
        instance_type=itype, reply_text="topology keeps the bytes close.",
        chunk_size=4, delay_s=0.02, heartbeat_interval_s=0.1,
        lease_ttl_s=0.5, slice_id=slice_id, topo_host=host)
    return FakeEngine(InMemoryCoordination(store), cfg).start()


def _stream(master) -> str:
    import json

    r = requests.post(
        f"http://127.0.0.1:{master.http_port}/v1/completions",
        json={"model": "fake-model", "prompt": "topo", "stream": True,
              "max_tokens": 64}, stream=True, timeout=30)
    assert r.status_code == 200, r.text
    text = ""
    for line in r.iter_lines():
        if not line.startswith(b"data: "):
            continue
        data = line[len(b"data: "):]
        if data == b"[DONE]":
            break
        obj = json.loads(data)
        assert "error" not in obj, obj
        for c in obj.get("choices", ()):
            text += c.get("text", "")
    return text


@pytest.mark.chaos
class TestSliceDeathDrill:
    def test_whole_slice_dies_without_suspect_storm(self, store):
        master = Master(_drill_opts(), coord=InMemoryCoordination(store))
        master.start()
        engines = {
            "pa": _placed_engine(store, InstanceType.PREFILL,
                                 "slice-a", "host-a0"),
            "da": _placed_engine(store, InstanceType.DECODE,
                                 "slice-a", "host-a1"),
            "pb": _placed_engine(store, InstanceType.PREFILL,
                                 "slice-b", "host-b0"),
            "db": _placed_engine(store, InstanceType.DECODE,
                                 "slice-b", "host-b1"),
        }
        mgr = master.scheduler.instance_mgr
        try:
            assert wait_until(
                lambda: all(mgr.get_instance_meta(e.name) is not None
                            for e in engines.values()), timeout=5)
            assert mgr.routing_snapshot().topo_active
            expected = _stream(master)
            assert expected

            survivors = (engines["pa"].name, engines["da"].name)
            snap = mgr.routing_snapshot()
            since_before = {n: snap.entries[n].state_since_ms
                            for n in survivors}

            # Hard death of ALL of slice-b: leases lapse, probes fail,
            # no deregister.
            engines["pb"].kill()
            engines["db"].kill()
            dead = (engines["pb"].name, engines["db"].name)
            assert wait_until(
                lambda: all(n not in mgr.routing_snapshot().entries
                            for n in dead), timeout=10)

            # Re-converged placement: every new pair rides the survivor
            # slice's ICI (or collapses onto one instance), never DCN.
            before = mgr.pair_link_counts()
            for _ in range(3):
                assert _stream(master) == expected
            after = mgr.pair_link_counts()
            assert after.get("dcn", 0) == before.get("dcn", 0)
            assert sum(after.values()) >= sum(before.values()) + 3

            # Zero survivor SUSPECT transitions: any state round-trip
            # bumps state_since_ms.
            snap = mgr.routing_snapshot()
            for n in survivors:
                assert snap.entries[n].state == InstanceRuntimeState.ACTIVE
                assert snap.entries[n].state_since_ms == since_before[n]
        finally:
            for e in engines.values():
                if e._alive:
                    e.stop()
            master.stop()
