"""Routing-policy e2e: cache-aware affinity and SLO-aware placement through
the full master + fake-engine stack."""

import pytest
import requests

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.coordination.memory import InMemoryCoordination, MemoryStore
from xllm_service_tpu.master import Master
from xllm_service_tpu.testing.fake_engine import FakeEngine, FakeEngineConfig

from fakes import wait_until


def _cluster(store, policy: str, n_engines: int = 2):
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          load_balance_policy=policy,
                          lease_ttl_s=1.0, sync_interval_s=0.2,
                          reconcile_interval_s=0.1, block_size=128)
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()
    engines = [FakeEngine(InMemoryCoordination(store),
                          FakeEngineConfig(heartbeat_interval_s=0.2,
                                           lease_ttl_s=1.0)).start()
               for _ in range(n_engines)]
    for e in engines:
        assert wait_until(
            lambda e=e: master.scheduler.instance_mgr.get_instance_meta(e.name)
            is not None, timeout=5)
    return master, engines


class TestCacheAwareRouting:
    def test_repeat_prompt_routes_to_cache_holder(self, store):
        master, engines = _cluster(store, "CAR")
        try:
            base = f"http://127.0.0.1:{master.http_port}"
            prompt = "cache affinity " * 40   # > 1 hash block of 128 tokens
            r1 = requests.post(base + "/v1/completions", json={
                "model": "fake-model", "prompt": prompt, "max_tokens": 16,
            }, timeout=10)
            assert r1.status_code == 200
            first_engine = next(e for e in engines if e.accepted_requests)
            # Wait for the heartbeat KV event to reach the global index.
            assert wait_until(
                lambda: master.scheduler.kvcache_mgr.num_blocks() > 0,
                timeout=5)
            # The same prefix must now route to the holder every time.
            for _ in range(3):
                r = requests.post(base + "/v1/completions", json={
                    "model": "fake-model", "prompt": prompt,
                    "max_tokens": 16}, timeout=10)
                assert r.status_code == 200
            assert len(first_engine.accepted_requests) == 4
            other = next(e for e in engines if e is not first_engine)
            assert len(other.accepted_requests) == 0
        finally:
            for e in engines:
                e.stop()
            master.stop()

    def test_untokenizable_requests_still_balance(self, store):
        master, engines = _cluster(store, "CAR")
        try:
            base = f"http://127.0.0.1:{master.http_port}"
            # Distinct prompts, no shared prefix: load should spread.
            for i in range(6):
                r = requests.post(base + "/v1/completions", json={
                    "model": "fake-model", "prompt": f"unique {i} " * 30,
                    "max_tokens": 8}, timeout=10)
                assert r.status_code == 200
            counts = sorted(len(e.accepted_requests) for e in engines)
            assert sum(counts) == 6
        finally:
            for e in engines:
                e.stop()
            master.stop()


class TestSloAwareRouting:
    def test_routes_prefill_to_fastest_predictor(self, store):
        opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                              load_balance_policy="SLO_AWARE",
                              lease_ttl_s=1.0, sync_interval_s=0.2,
                              reconcile_interval_s=0.1)
        master = Master(opts, coord=InMemoryCoordination(store))
        master.start()
        from xllm_service_tpu.common.types import InstanceType

        fast = FakeEngine(InMemoryCoordination(store), FakeEngineConfig(
            instance_type=InstanceType.PREFILL,
            heartbeat_interval_s=0.2, lease_ttl_s=1.0))
        slow = FakeEngine(InMemoryCoordination(store), FakeEngineConfig(
            instance_type=InstanceType.PREFILL,
            heartbeat_interval_s=0.2, lease_ttl_s=1.0))
        decode = FakeEngine(InMemoryCoordination(store), FakeEngineConfig(
            instance_type=InstanceType.DECODE,
            heartbeat_interval_s=0.2, lease_ttl_s=1.0))
        # Override profiling tables BEFORE registration.
        fast.meta_override = {"ttft": [[128, 5.0], [512, 12.0], [2048, 40.0]]}
        slow.meta_override = {"ttft": [[128, 500.0], [512, 1200.0],
                                       [2048, 4000.0]]}
        orig_meta = FakeEngine.meta

        def meta_with_override(self):
            m = orig_meta(self)
            ov = getattr(self, "meta_override", None)
            if ov and "ttft" in ov:
                m.ttft_profiling_data = ov["ttft"]
            return m

        FakeEngine.meta = meta_with_override
        try:
            for e in (fast, slow, decode):
                e.start()
                assert wait_until(
                    lambda e=e: master.scheduler.instance_mgr
                    .get_instance_meta(e.name) is not None, timeout=5)
            base = f"http://127.0.0.1:{master.http_port}"
            for i in range(4):
                r = requests.post(base + "/v1/completions", json={
                    "model": "fake-model", "prompt": "route me " * 50,
                    "max_tokens": 8}, timeout=10)
                assert r.status_code == 200, r.text
            # All prefills should land on the fast instance.
            assert len(fast.accepted_requests) == 4
            assert len(slow.accepted_requests) == 0
        finally:
            FakeEngine.meta = orig_meta
            for e in (fast, slow, decode):
                e.stop()
            master.stop()
