"""xlint rule tests: every rule must fire on its violation fixture, stay
quiet on the clean/hatched variants, and the real tree must lint clean
(the tier-1 CI gate)."""

import os
from pathlib import Path

import pytest

from xllm_service_tpu.devtools import xlint

FIXTURES = Path(__file__).parent / "data" / "xlint_fixtures"
PACKAGE = Path(__file__).parent.parent / "xllm_service_tpu"


@pytest.fixture(scope="module")
def fixture_violations():
    return xlint.run([str(FIXTURES)])


def hits(violations, rule, path_part="", msg_part=""):
    return [v for v in violations
            if v.rule == rule and path_part in v.path and msg_part in v.message]


# ------------------------------------------------------ no-blocking-under-lock
class TestBlockingUnderLock:
    def test_sleep_under_lock_flagged(self, fixture_violations):
        assert hits(fixture_violations, "no-blocking-under-lock",
                    "blocking.py", "sleep")

    def test_http_under_lock_flagged(self, fixture_violations):
        assert hits(fixture_violations, "no-blocking-under-lock",
                    "blocking.py", "HTTP I/O")

    def test_coordination_call_under_lock_flagged(self, fixture_violations):
        assert hits(fixture_violations, "no-blocking-under-lock",
                    "blocking.py", "coordination call")

    def test_channel_rpc_under_lock_flagged(self, fixture_violations):
        assert hits(fixture_violations, "no-blocking-under-lock",
                    "blocking.py", "engine-channel RPC")

    def test_exact_violation_count(self, fixture_violations):
        # fine_outside / closure_defined_under_lock / excused must NOT
        # fire: exactly the four deliberate violations above.
        assert len(hits(fixture_violations,
                        "no-blocking-under-lock", "blocking.py")) == 4


# ------------------------------------------------------------- lock-discipline
class TestLockDiscipline:
    def test_missing_annotation_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-discipline", "discipline.py",
                    "unannotated_lock")

    def test_declaration_outside_init_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-discipline", "discipline.py",
                    "late_lock")

    def test_bare_acquire_and_release_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-discipline", "discipline.py",
                    "acquire")
        assert hits(fixture_violations, "lock-discipline", "discipline.py",
                    "release")

    def test_function_local_lock_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-discipline", "discipline.py",
                    "local 'tmp_lock'")

    def test_hatched_local_lock_not_flagged(self, fixture_violations):
        assert not hits(fixture_violations, "lock-discipline",
                        "discipline.py", "scratch")

    def test_conflicting_redeclaration_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-discipline", "discipline.py",
                    "re-declared with order 21")

    def test_hatched_acquire_not_flagged(self, fixture_violations):
        # excused_acquire carries allow-bare-acquire hatches: exactly one
        # acquire + one release violation remain (manual_acquire's).
        bare = [v for v in hits(fixture_violations, "lock-discipline",
                                "discipline.py") if "bare" in v.message]
        assert len(bare) == 2


# ------------------------------------------------------------------ lock-order
class TestLockOrder:
    def test_nested_with_inversion_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-order", "ordering.py",
                    "Orderly.lock_b (order 2) -> Orderly.lock_a (order 1)")

    def test_interprocedural_inversion_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-order", "ordering.py",
                    "via call to Interproc.grab_inner_interproc()")

    def test_cycle_reported(self, fixture_violations):
        assert hits(fixture_violations, "lock-order", "ordering.py",
                    "cycle")

    def test_respecting_order_not_flagged(self, fixture_violations):
        assert not hits(fixture_violations, "lock-order", "ordering.py",
                        "Orderly.lock_a (order 1) -> Orderly.lock_b")


# ----------------------------------------------------------------- fault-point
class TestFaultPoints:
    def test_unregistered_point_flagged(self, fixture_violations):
        assert hits(fixture_violations, "fault-point", "fault_sites.py",
                    "demo.unregistered")

    def test_non_literal_point_flagged(self, fixture_violations):
        assert hits(fixture_violations, "fault-point", "fault_sites.py",
                    "string literal")

    def test_dead_point_flagged(self, fixture_violations):
        assert hits(fixture_violations, "fault-point", "faults.py",
                    "demo.dead")

    def test_registered_used_point_not_flagged(self, fixture_violations):
        assert not hits(fixture_violations, "fault-point", "", "demo.used")


# ----------------------------------------------------------------- span-point
class TestSpanPoints:
    def test_unregistered_point_flagged(self, fixture_violations):
        assert hits(fixture_violations, "span-point", "span_sites.py",
                    "demo.span_unregistered")

    def test_non_literal_point_flagged(self, fixture_violations):
        assert hits(fixture_violations, "span-point", "span_sites.py",
                    "string literal")

    def test_dead_point_flagged(self, fixture_violations):
        assert hits(fixture_violations, "span-point", "tracing.py",
                    "demo.span_dead")

    def test_registered_used_point_not_flagged(self, fixture_violations):
        assert not hits(fixture_violations, "span-point", "",
                        "demo.span_used")

    def test_hatched_forwarder_not_flagged(self, fixture_violations):
        # Exactly two span-site violations: the hatched forwarder and the
        # non-TRACER receiver stay quiet.
        assert len(hits(fixture_violations, "span-point",
                        "span_sites.py")) == 2


# ------------------------------------------------------------- metrics-registry
class TestMetricsRegistry:
    def test_ad_hoc_instrument_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry",
                    "metrics_sites.py", "ad-hoc")

    def test_undeclared_import_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry",
                    "metrics_sites.py", "NOT_DECLARED")

    def test_dead_instrument_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry", "metrics.py",
                    "DEAD_TOTAL")

    def test_import_alone_is_not_a_use(self, fixture_violations):
        # IMPORT_ONLY_TOTAL is imported by metrics_sites.py but never
        # referenced — still dead.
        assert hits(fixture_violations, "metrics-registry", "metrics.py",
                    "IMPORT_ONLY_TOTAL")

    def test_duplicate_name_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry", "metrics.py",
                    "duplicated_name")

    def test_used_instrument_not_flagged(self, fixture_violations):
        assert not [v for v in hits(fixture_violations, "metrics-registry",
                                    "", "dead metric")
                    if "USED_TOTAL" in v.message
                    or "LABELED_TOTAL" in v.message]

    def test_wrong_label_names_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry",
                    "metrics_sites.py", "declares labelnames")

    def test_write_without_labels_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry",
                    "metrics_sites.py", "write through .labels")

    def test_labels_on_unlabeled_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry",
                    "metrics_sites.py", "declares no labelnames")

    def test_module_qualified_write_flagged(self, fixture_violations):
        # m.LABELED_TOTAL.inc(): the attribute-receiver form is checked
        # just like the bare-name form.
        assert [v for v in hits(fixture_violations, "metrics-registry",
                                "metrics_sites.py", "write through")
                ] and len(hits(fixture_violations, "metrics-registry",
                               "metrics_sites.py", "write through")) == 2

    def test_correct_labeled_write_not_flagged(self, fixture_violations):
        # The clean .labels(instance=..., phase=...).inc() site: exactly
        # the six deliberate metrics_sites violations fire.
        assert len(hits(fixture_violations, "metrics-registry",
                        "metrics_sites.py")) == 6


# -------------------------------------------------------------------- hot-json
class TestHotJson:
    def test_dumps_reference_flagged(self, fixture_violations):
        assert hits(fixture_violations, "hot-json", "hot_sites.py",
                    "json.dumps")

    def test_json_kwarg_flagged(self, fixture_violations):
        assert hits(fixture_violations, "hot-json", "hot_sites.py",
                    "json= kwarg")

    def test_alias_laundering_flagged(self, fixture_violations):
        assert hits(fixture_violations, "hot-json", "hot_sites.py",
                    "push_hot")

    def test_stale_registry_entry_flagged(self, fixture_violations):
        assert hits(fixture_violations, "hot-json", "wire.py",
                    "Ghost.never_defined")

    def test_hatched_and_unregistered_quiet(self, fixture_violations):
        # forward_hatched (hatch) + unregistered_sibling + bystander stay
        # quiet: exactly the three deliberate site violations fire.
        assert len(hits(fixture_violations, "hot-json",
                        "hot_sites.py")) == 3


# ---------------------------------------------------------------- broad-except
class TestBroadExcept:
    def test_silent_swallow_flagged(self, fixture_violations):
        assert hits(fixture_violations, "broad-except", "broad_except.py",
                    "neither logs nor re-raises")

    def test_bare_except_flagged(self, fixture_violations):
        assert hits(fixture_violations, "broad-except", "broad_except.py",
                    "bare")

    def test_logging_reraising_and_hatched_not_flagged(self,
                                                       fixture_violations):
        # logs_it / reraises / excused are clean: exactly the two
        # deliberate violations above fire in the fixture.
        assert len(hits(fixture_violations, "broad-except",
                        "broad_except.py")) == 2

    def test_single_file_invocation_keeps_dir_scope(self):
        # Linting just the file must still apply the scheduler-path scope
        # (scope keys on the absolute path, not the display-relative one).
        vs = xlint.run([str(FIXTURES / "scheduler" / "broad_except.py")])
        assert [v for v in vs if v.rule == "broad-except"
                and "neither logs nor re-raises" in v.message]


# ---------------------------------------------------------------- rcu-frozen
class TestRcuFrozen:
    def test_in_class_mutation_flagged(self, fixture_violations):
        assert hits(fixture_violations, "rcu-frozen", "rcu_sites.py",
                    "self.items")
        assert hits(fixture_violations, "rcu-frozen", "rcu_sites.py",
                    "attribute write to published value 'self.n'")

    def test_mutation_via_tracked_local_flagged(self, fixture_violations):
        assert hits(fixture_violations, "rcu-frozen", "rcu_sites.py",
                    "snap.items")

    def test_mutation_of_fresh_ctor_local_flagged(self, fixture_violations):
        assert hits(fixture_violations, "rcu-frozen", "rcu_sites.py",
                    "fresh.n")

    def test_publication_field_write_flagged(self, fixture_violations):
        assert hits(fixture_violations, "rcu-frozen", "rcu_sites.py",
                    "item write on published value 'self._infos'")
        assert hits(fixture_violations, "rcu-frozen", "rcu_sites.py",
                    ".update() on published value 'self._snap.items'")

    def test_thaw_without_reason_flagged(self, fixture_violations):
        assert hits(fixture_violations, "rcu-frozen", "rcu_sites.py",
                    "thaw() without a reason")

    def test_annassign_bound_alias_tracked(self, fixture_violations):
        # An annotated alias must not escape tracking (the PR-4 lesson:
        # AnnAssign parse gaps silently make registry rules vacuous).
        assert hits(fixture_violations, "rcu-frozen", "rcu_sites.py",
                    "snap.items'")

    def test_thaw_and_hatch_quiet(self, fixture_violations):
        # thaw_ok + mutate_hatched stay quiet: exactly the eight
        # deliberate rcu-frozen violations fire in rcu_sites.py.
        assert len(hits(fixture_violations, "rcu-frozen",
                        "rcu_sites.py")) == 8

    def test_stale_frozen_type_flagged(self, fixture_violations):
        assert hits(fixture_violations, "rcu-frozen", "rcu.py",
                    "GhostType")

    def test_pr5_prune_after_install_resurrection_caught(
            self, fixture_violations):
        """The resurrected PR-5 compaction bug (prune DELETEs applied in
        place on the live published index) is caught statically."""
        assert hits(fixture_violations, "rcu-frozen", "rcu_regress.py",
                    ".pop() on published value 'self._snapshot.blocks'")


# --------------------------------------------------------------- rcu-publish
class TestRcuPublish:
    def test_swap_outside_lock_flagged(self, fixture_violations):
        assert hits(fixture_violations, "rcu-publish", "rcu_sites.py",
                    "Publisher._snap swapped outside")

    def test_swap_under_wrong_lock_flagged(self, fixture_violations):
        flagged = hits(fixture_violations, "rcu-publish", "rcu_sites.py",
                       "Publisher._infos swapped outside")
        assert flagged

    def test_aliased_swap_flagged(self, fixture_violations):
        assert hits(fixture_violations, "rcu-publish", "rcu_sites.py",
                    "freshly built FrozSnap")

    def test_augmented_assign_flagged(self, fixture_violations):
        assert hits(fixture_violations, "rcu-publish", "rcu_sites.py",
                    "augmented assignment")

    def test_annassign_swap_checked(self, fixture_violations):
        # `self._snap: FrozSnap = alias` is a swap site like any other:
        # both the plain and the annotated aliased swap fire.
        assert len(hits(fixture_violations, "rcu-publish", "rcu_sites.py",
                        "freshly built FrozSnap")) == 2

    def test_clean_and_hatched_publishes_quiet(self, fixture_violations):
        # publish_ok / publish_fresh_local_ok / publish_via_helper (call-
        # site summary) / publish_hatched: exactly the five deliberate
        # site violations fire.
        assert len(hits(fixture_violations, "rcu-publish",
                        "rcu_sites.py")) == 5

    def test_registry_staleness_flagged(self, fixture_violations):
        assert hits(fixture_violations, "rcu-publish", "rcu.py", "Phantom")
        assert hits(fixture_violations, "rcu-publish", "rcu.py",
                    "never assigned")
        assert hits(fixture_violations, "rcu-publish", "rcu.py", "_nolock")
        assert hits(fixture_violations, "rcu-publish", "rcu.py",
                    "_badspec")
        assert hits(fixture_violations, "rcu-publish", "rcu.py", "Widget")


# ------------------------------------------------------------------ rcu-read
class TestRcuRead:
    def test_double_direct_load_flagged(self, fixture_violations):
        assert hits(fixture_violations, "rcu-read", "rcu_sites.py",
                    "hot_double_read")

    def test_double_accessor_load_flagged(self, fixture_violations):
        assert hits(fixture_violations, "rcu-read", "rcu_sites.py",
                    "hot_accessor_double")

    def test_single_and_hatched_loads_quiet(self, fixture_violations):
        assert len(hits(fixture_violations, "rcu-read",
                        "rcu_sites.py")) == 2


# ------------------------------------------------------------ async-blocking
class TestAsyncBlocking:
    def test_sleep_in_coroutine_flagged(self, fixture_violations):
        assert hits(fixture_violations, "async-blocking", "async_sites.py",
                    "sleeps")

    def test_requests_in_coroutine_flagged(self, fixture_violations):
        assert hits(fixture_violations, "async-blocking", "async_sites.py",
                    "HTTP I/O")

    def test_raw_channel_in_coroutine_flagged(self, fixture_violations):
        assert hits(fixture_violations, "async-blocking", "async_sites.py",
                    "_post")

    def test_awaited_nested_and_hatched_quiet(self, fixture_violations):
        # awaited_ok / async_cm_ok / nested_sync_ok / hatched / the
        # module-level sync function: exactly three violations fire.
        assert len(hits(fixture_violations, "async-blocking",
                        "async_sites.py")) == 3


# ------------------------------------------------------- async lock ordering
class TestAsyncLockDiscipline:
    def test_async_with_inversion_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-order", "async_sites.py",
                    "AsyncOrderly.alock_inner (order 51) -> "
                    "AsyncOrderly.alock_outer (order 50)")

    def test_asyncio_lock_requires_annotation(self, fixture_violations):
        assert hits(fixture_violations, "lock-discipline", "async_sites.py",
                    "alock_raw")

    def test_ordered_async_with_not_flagged(self, fixture_violations):
        assert not hits(fixture_violations, "lock-order", "async_sites.py",
                        "alock_outer (order 50) -> AsyncOrderly.alock_inner")


# ------------------------------------------------------------ state rules
class TestStateDecl:
    def test_stale_class_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-decl", "ownership.py",
                    "Ghost._attr")

    def test_never_assigned_attr_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-decl", "ownership.py",
                    "StateHolder._never")

    def test_unknown_lock_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-decl", "ownership.py",
                    "_missing_lock")

    def test_unknown_role_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-decl", "ownership.py",
                    "ghost-role")

    def test_malformed_spec_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-decl", "ownership.py",
                    "franchised")

    def test_rcu_without_publication_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-decl", "ownership.py",
                    "_unpub")

    def test_dead_role_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-decl", "ownership.py",
                    "dead-role")

    def test_stale_strict_class_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-decl", "ownership.py",
                    "GhostStrict")

    def test_undeclared_post_init_attr_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-decl", "state_sites.py",
                    "_surprise")

    def test_hatched_and_lifecycle_attrs_quiet(self, fixture_violations):
        # _scratch carries allow-state-decl; _teardown_flag is assigned in
        # close() (lifecycle scope): only _surprise fires in the file.
        assert len(hits(fixture_violations, "state-decl",
                        "state_sites.py")) == 1


class TestStateWrite:
    def test_unlocked_item_write_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-write", "state_sites.py",
                    "write_unlocked")

    def test_wrong_lock_mutator_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-write", "state_sites.py",
                    "write_wrong_lock")

    def test_unlocked_rebind_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-write", "state_sites.py",
                    "rebind_unlocked")

    def test_escape_without_reason_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-write", "state_sites.py",
                    "without a reason")

    def test_confined_rebind_off_role_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-write", "state_sites.py",
                    "rogue_rebind")

    def test_init_only_rebind_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-write", "state_sites.py",
                    "reconfigure()")

    def test_immutable_rebind_and_mutation_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-write", "state_sites.py",
                    "tweak_weights")
        assert hits(fixture_violations, "state-write", "state_sites.py",
                    "mutated in place in poke_weights")

    def test_clean_locked_and_summary_writes_quiet(self, fixture_violations):
        # write_ok (lexical) / _rebuild_locked (transitive call summary) /
        # write_escaped (hatch CM) / write_hatched (comment) / tick +
        # _advance (role entry + caller fixpoint) / stop (lifecycle) /
        # publish_snap (rcu-owned): exactly the nine deliberate
        # violations fire in the file.
        assert len(hits(fixture_violations, "state-write",
                        "state_sites.py")) == 9

    def test_pure_call_cycle_is_not_a_lock_summary(self,
                                                   fixture_violations):
        # Mutually recursive helpers with no locked external call site
        # must flag — a cycle edge contributes no independent entry.
        assert hits(fixture_violations, "state-write", "state_sites.py",
                    "_cycle_a")

    def test_pre_pr5_heartbeat_rebuild_resurrection_caught(
            self, fixture_violations):
        """The resurrected pre-PR-5 bug (per-heartbeat O(fleet) load-info
        rebuild under the WRONG lock) is caught statically."""
        assert hits(fixture_violations, "state-write", "state_regress.py",
                    "record_heartbeat_buggy")

    def test_fixed_heartbeat_rebuild_control_quiet(self, fixture_violations):
        assert not hits(fixture_violations, "state-write",
                        "state_regress.py", "record_heartbeat_fixed")


class TestStateRead:
    def test_unlocked_hot_read_flagged(self, fixture_violations):
        assert hits(fixture_violations, "state-read", "state_sites.py",
                    "hot_read")

    def test_locked_and_cold_reads_quiet(self, fixture_violations):
        # hot_read_locked takes the lock; cold_read is unregistered:
        # exactly one state-read violation in the file.
        assert len(hits(fixture_violations, "state-read",
                        "state_sites.py")) == 1


# ------------------------------------------------------------------- CLI + CI
class TestDriver:
    def test_cli_reports_and_exits_nonzero_on_fixtures(self, capsys):
        rc = xlint.main([str(FIXTURES)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "no-blocking-under-lock" in out

    def test_unparseable_file_reported(self, tmp_path):
        bad = tmp_path / "broken.py"
        bad.write_text("def oops(:\n")
        vs = xlint.run([str(bad)])
        assert vs and vs[0].rule == "parse"


def test_xlint_tree_clean():
    """Tier-1 gate: the analyzer over the real package must be clean
    (the RCU pass included — publication discipline holds tree-wide)."""
    violations = xlint.run([str(PACKAGE)])
    assert not violations, (
        "xlint violations in the tree:\n"
        + "\n".join(str(v) for v in violations)
        + "\n\nrun: python -m xllm_service_tpu.devtools.xlint "
          "xllm_service_tpu")


def test_xlint_rcu_registry_is_live():
    """The RCU pass must actually be armed on the real tree: the
    registries parse non-empty and the rule is not silently inert (the
    PR-4 lesson — an AnnAssign parse gap made two registry rules vacuous
    for two rounds). Probe: injecting a known-bad snippet next to the
    real registry file must produce rcu violations."""
    import xllm_service_tpu.devtools.rcu as rcu_mod

    assert rcu_mod.RCU_FROZEN_TYPES and rcu_mod.RCU_PUBLICATIONS
    reg = Path(rcu_mod.__file__)
    probe = (
        "class PrefixIndex:\n"
        "    def __init__(self):\n"
        "        self.blocks = {}\n"
        "class Mgr:\n"
        "    def bad(self, snap):\n"
        "        snap = PrefixIndex()\n"
        "        snap.blocks = {}\n")
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        bad = Path(td) / "probe.py"
        bad.write_text(probe)
        vs = xlint.run([str(reg), str(bad)])
        assert any(v.rule == "rcu-frozen" and "probe.py" in v.path
                   for v in vs), vs


def test_xlint_state_registry_is_live():
    """The state-ownership pass must actually be armed on the real tree:
    the registries parse non-empty and each of the three rules fires when
    a known-bad snippet is linted next to the REAL registry file (the
    PR-4 vacuous-rule lesson, applied to the new rules on day one)."""
    import tempfile

    import xllm_service_tpu.devtools.ownership as own_mod
    import xllm_service_tpu.rpc.wire as wire_mod

    assert own_mod.STATE_DISCIPLINES and own_mod.THREAD_ROLES \
        and own_mod.STATE_CLASSES
    assert own_mod.STATE_DISCIPLINES["GlobalKVCacheMgr._frame_seq"] \
        == "lock:_lock"
    reg = Path(own_mod.__file__)
    wire = Path(wire_mod.__file__)
    # The probe impersonates a registered class: an unlocked write to a
    # lock-guarded attr, an undeclared post-init attr, and an unlocked
    # hot-path read (GlobalKVCacheMgr.match is in HOT_PATH_FUNCTIONS).
    probe = (
        "import threading\n"
        "class GlobalKVCacheMgr:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()  # lock-order: 26\n"
        "        self._frame_seq = 0\n"
        "        self._dirty = set()\n"
        "    def bad_write(self):\n"
        "        self._frame_seq = 7\n"
        "    def bad_decl(self):\n"
        "        self._made_up_attr = 1\n"
        "    def match(self):\n"
        "        return self._frame_seq\n")
    with tempfile.TemporaryDirectory() as td:
        bad = Path(td) / "probe.py"
        bad.write_text(probe)
        vs = xlint.run([str(reg), str(wire), str(bad)])
        by_rule = {r: [v for v in vs if v.rule == r and "probe.py" in v.path]
                   for r in ("state-decl", "state-write", "state-read")}
        assert by_rule["state-write"], vs
        assert any("_made_up_attr" in v.message
                   for v in by_rule["state-decl"]), vs
        assert by_rule["state-read"], vs


def test_xlint_owner_discipline_fires():
    """The `owner:<guard>` state discipline (ISSUE 15): writes to the
    sharded heartbeat fields must be dominated by a POSITIVE
    owns_telemetry() guard — an unguarded write, and a write under a
    NEGATED guard, both fail the build; the guarded write passes."""
    import tempfile

    import xllm_service_tpu.devtools.ownership as own_mod

    assert own_mod.STATE_DISCIPLINES["InstanceMgr._shard_dirty"] \
        == "owner:owns_telemetry"
    reg = Path(own_mod.__file__)
    probe = (
        "import threading\n"
        "class InstanceMgr:\n"
        "    def __init__(self):\n"
        "        self._metrics_lock = threading.Lock()  # lock-order: 24\n"
        "        self._cluster_lock = threading.Lock()  # lock-order: 20\n"
        "        self._shard_dirty = set()\n"
        "        self._shard_gone = {}\n"
        "    def owns_telemetry(self, name):\n"
        "        return True\n"
        "    def good(self, name):\n"
        "        if self.owns_telemetry(name):\n"
        "            self._shard_dirty.add(name)\n"
        "    def bad_unguarded(self, name):\n"
        "        self._shard_dirty.add(name)\n"
        "    def bad_negated(self, name):\n"
        "        if not self.owns_telemetry(name):\n"
        "            self._shard_gone[name] = ('x', 0)\n")
    with tempfile.TemporaryDirectory() as td:
        bad = Path(td) / "probe.py"
        bad.write_text(probe)
        vs = xlint.run([str(reg), str(bad)])
        owner_vs = [v for v in vs if v.rule == "state-write"
                    and "probe.py" in v.path and "owner:" in v.message]
        lines = {v.line for v in owner_vs}
        src = probe.splitlines()
        flagged = {src[ln - 1].strip() for ln in lines}
        assert any("bad_unguarded" in src[ln - 2] or
                   "_shard_dirty.add" in src[ln - 1] for ln in lines), vs
        # The negated guard earns no credit.
        assert any("_shard_gone[name]" in f for f in flagged), owner_vs
        # The positively-guarded write is clean.
        good_line = probe.splitlines().index(
            "            self._shard_dirty.add(name)") + 1
        assert good_line not in lines, owner_vs


def test_owner_guard_runtime_verifier():
    """Runtime half of `owner:`: with XLLM_STATE_DEBUG armed, a write to
    an owner-gated container after a FAILING guard check records a
    state-owner violation; a write after a passing check does not."""
    import xllm_service_tpu.devtools.ownership as own_mod

    class Probe:
        pass

    own_mod.note_owner_guard("owns_telemetry", True)
    assert own_mod._owner_guard_ok("owns_telemetry")
    own_mod.note_owner_guard("owns_telemetry", False)
    assert not own_mod._owner_guard_ok("owns_telemetry")
    own_mod.reset_violations()
    own_mod._check_write(Probe(), "InstanceMgr", "_shard_dirty",
                         "owner:owns_telemetry", first=False,
                         meth="record_instance_heartbeat")
    vs = own_mod.violations()
    assert any(v.kind == "state-owner" for v in vs), vs
    own_mod.reset_violations()
    own_mod.note_owner_guard("owns_telemetry", True)
    own_mod._check_write(Probe(), "InstanceMgr", "_shard_dirty",
                         "owner:owns_telemetry", first=False,
                         meth="record_instance_heartbeat")
    assert not own_mod.violations()


def test_xlint_state_registry_disciplines_parse():
    """Every live registry entry parses into a known discipline and the
    cross-referenced objects exist at runtime (the registry the static
    rule reads is the same dict the runtime verifier reads)."""
    import xllm_service_tpu.devtools.ownership as own_mod

    kinds = set()
    for key, spec in own_mod.STATE_DISCIPLINES.items():
        assert "." in key, key
        kind, _, arg = spec.partition(":")
        kinds.add(kind)
        assert kind in ("lock", "rcu", "confined", "init-only",
                        "immutable", "owner"), (key, spec)
        if kind == "confined":
            assert arg in own_mod.THREAD_ROLES, (key, spec)
        if kind == "owner":
            # The guard must be a live method on the class (the static
            # rule cross-checks the same; here we pin the runtime side).
            assert arg, (key, spec)
        if kind == "rcu":
            from xllm_service_tpu.devtools.rcu import RCU_PUBLICATIONS

            assert key in RCU_PUBLICATIONS, key
    # Every discipline kind is exercised by the live registry (a kind
    # nothing uses would mean untested rule surface).
    assert kinds == {"lock", "rcu", "confined", "init-only", "immutable",
                     "owner"}


def test_cli_json_format(tmp_path, capsys):
    """--format json: machine-readable output with the stable exit
    codes scripts/check.sh consumes (0 clean, 1 violations, 2 usage)."""
    import json

    bad = tmp_path / "bad.py"
    bad.write_text("import threading, time\n"
                   "class C:\n"
                   "    def __init__(self):\n"
                   "        self.lk = threading.Lock()  # lock-order: 1\n"
                   "    def f(self):\n"
                   "        with self.lk:\n"
                   "            time.sleep(1)\n")
    rc = xlint.main(["--format", "json", str(bad)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["count"] == len(doc["violations"]) >= 1
    assert doc["files"] == 1
    assert {"rule", "path", "line", "message"} <= set(
        doc["violations"][0])

    good = tmp_path / "good.py"
    good.write_text("X = 1\n")
    rc = xlint.main(["--format", "json", str(good)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["count"] == 0


def test_cli_usage_errors_exit_2(capsys):
    assert xlint.main(["--format"]) == 2
    assert xlint.main(["--format", "yaml", "x"]) == 2
    assert xlint.main(["--no-such-flag"]) == 2
    capsys.readouterr()


def test_xlint_support_tree_clean():
    """Tier-1 gate: tests/ + benchmarks/ under the relaxed profile
    (behavioral rules only; the fixture dir is excluded by design)."""
    root = Path(__file__).parent.parent
    violations = xlint.run([str(root / "tests"), str(root / "benchmarks")],
                           profile="support")
    assert not violations, (
        "xlint violations in support code:\n"
        + "\n".join(str(v) for v in violations)
        + "\n\nrun: python -m xllm_service_tpu.devtools.xlint --support "
          "tests benchmarks")


def test_support_profile_keeps_behavioral_rules(tmp_path):
    bad = tmp_path / "bench_helper.py"
    bad.write_text(
        "import threading, time\n"
        "lock = threading.Lock()\n"
        "def drive():\n"
        "    with lock:\n"
        "        time.sleep(1.0)\n"
        "async def handler():\n"
        "    time.sleep(0.1)\n")
    vs = xlint.run([str(bad)], profile="support")
    rules = {v.rule for v in vs}
    assert "no-blocking-under-lock" in rules
    assert "async-blocking" in rules
    # ...but not the declaration discipline (module-level lock without an
    # annotation is fine in support code).
    assert "lock-discipline" not in rules


# ------------------------------------------------------------- pair-release
class TestPairRelease:
    def test_leaky_acquire_flagged(self, fixture_violations):
        assert hits(fixture_violations, "pair-release", "pair_sites.py",
                    "not discharged")

    def test_pr12_leak_shape_resurrected(self, fixture_violations):
        # The exact pre-PR-12 admission shape: helper acquires, caller
        # never releases on the reject path.
        assert hits(fixture_violations, "pair-release", "pair_regress.py",
                    "PR-12 slot-leak shape")

    def test_stale_endpoints_flagged(self, fixture_violations):
        assert len(hits(fixture_violations, "pair-release", "lifecycle.py",
                        "stale pair 'ghost'")) == 2

    def test_hatched_stale_entry_quiet(self, fixture_violations):
        assert not hits(fixture_violations, "pair-release", "lifecycle.py",
                        "ghost2")

    def test_malformed_spec_flagged(self, fixture_violations):
        assert hits(fixture_violations, "pair-release", "lifecycle.py",
                    "missing '@ scope'")

    def test_dead_pair_flagged(self, fixture_violations):
        assert hits(fixture_violations, "pair-release", "lifecycle.py",
                    "dead pair 'dead'")

    def test_clean_hatched_and_fixed_shapes_quiet(self, fixture_violations):
        # clean_finally, the Frontend helper discharged by its caller's
        # finally, hatched_claim and the FixedFrontend control must stay
        # quiet: exactly one site violation per fixture file, plus the
        # four registry-side ones asserted above.
        assert len(hits(fixture_violations, "pair-release",
                        "pair_sites.py")) == 1
        assert len(hits(fixture_violations, "pair-release",
                        "pair_regress.py")) == 1
        assert len(hits(fixture_violations, "pair-release")) == 6


# ---------------------------------------------------------------- pair-once
class TestPairOnce:
    def test_double_release_flagged(self, fixture_violations):
        assert hits(fixture_violations, "pair-once", "pair_sites.py",
                    "released twice")

    def test_release_after_transfer_flagged(self, fixture_violations):
        assert hits(fixture_violations, "pair-once", "pair_sites.py",
                    "released after ownership transfer")

    def test_guarded_and_hatched_releases_quiet(self, fixture_violations):
        # finish_guarded (flag-guarded second release) and finish_hatched
        # must not fire: exactly the two deliberate violations.
        assert len(hits(fixture_violations, "pair-once")) == 2


# --------------------------------------------------------------- pair-evict
class TestPairEvict:
    def test_direct_remove_flagged(self, fixture_violations):
        assert hits(fixture_violations, "pair-evict", "pair_sites.py",
                    "direct LABELED_TOTAL.remove()")

    def test_write_after_evict_flagged(self, fixture_violations):
        # The PR-12 gauge-resurrection shape, caught statically.
        assert hits(fixture_violations, "pair-evict", "pair_sites.py",
                    "gauge-resurrection")

    def test_helperless_evict_pair_flagged(self, fixture_violations):
        assert hits(fixture_violations, "pair-evict", "lifecycle.py",
                    "declares no helper=")

    def test_blessed_and_hatched_evictions_quiet(self, fixture_violations):
        # evict_blessed and evict_hatched stay quiet: exactly the two
        # site violations plus the registry one.
        assert len(hits(fixture_violations, "pair-evict")) == 3


def test_xlint_pair_registry_is_live():
    """The pair rules must actually be armed on the real tree: every
    EFFECT_PAIRS entry parses, and a known-bad snippet linted next to
    the REAL registry file fires all three rules (the PR-4 vacuous-rule
    lesson, applied to the new rules on day one)."""
    import tempfile

    import xllm_service_tpu.devtools.lifecycle as lc_mod

    assert lc_mod.EFFECT_PAIRS
    assert set(lc_mod.pair_specs()) == set(lc_mod.EFFECT_PAIRS), \
        "some EFFECT_PAIRS entries failed to parse"
    reg = Path(lc_mod.__file__)
    metrics = PACKAGE / "common" / "metrics.py"
    # The probe impersonates the admission controller: an undischarged
    # try_admit, a double release, and a direct labeled-series remove
    # (INSTANCE_QUEUE_DEPTH is a real labeled instrument).
    probe = (
        "class AdmissionController:\n"
        "    def try_admit(self):\n"
        "        return True\n"
        "    def release(self):\n"
        "        pass\n"
        "ADMISSION = AdmissionController()\n"
        "def leaky():\n"
        "    if ADMISSION.try_admit():\n"
        "        pass\n"
        "def twice():\n"
        "    ADMISSION.release()\n"
        "    ADMISSION.release()\n"
        "def zap(name):\n"
        "    INSTANCE_QUEUE_DEPTH.remove(instance=name)\n")
    with tempfile.TemporaryDirectory() as td:
        bad = Path(td) / "probe.py"
        bad.write_text(probe)
        vs = xlint.run([str(reg), str(metrics), str(bad)])
        by_rule = {r: [v for v in vs if v.rule == r and "probe.py" in v.path]
                   for r in ("pair-release", "pair-once", "pair-evict")}
        assert any("not discharged" in v.message
                   for v in by_rule["pair-release"]), vs
        assert any("released twice" in v.message
                   for v in by_rule["pair-once"]), vs
        assert any("remove" in v.message
                   for v in by_rule["pair-evict"]), vs


# -------------------------------------------------------------- hatch audit
def test_tree_hatches_all_carry_reasons():
    """Every escape hatch in the real tree — comment suppressions and
    runtime ownership.escape()/lifecycle.escape()/rcu.thaw() calls —
    must carry a non-empty reason, and the audit itself must be live
    (the tree does use both kinds)."""
    stats: dict = {}
    xlint.run([str(PACKAGE)], stats=stats)
    hatches = stats["hatches"]
    assert hatches
    for h in hatches:
        assert h["reason"], f"hatch without a reason: {h}"
    kinds = {h["kind"].split(":")[0] for h in hatches}
    assert kinds == {"comment", "runtime"}


def test_cli_json_includes_hatches(tmp_path, capsys):
    """Hatch reasons surface in --format json (the auditable inventory
    scripts consume), for both runtime and comment hatches."""
    import json

    f = tmp_path / "h.py"
    f.write_text(
        "from xllm_service_tpu.devtools import lifecycle, ownership\n"
        "def drill(obj):\n"
        "    with lifecycle.escape('soak harness owns the slot'):\n"
        "        pass\n"
        "    with ownership.escape('test-only reset'):\n"
        "        obj.x = 1\n")
    rc = xlint.main(["--format", "json", str(f)])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert {"path", "line", "kind", "reason"} <= set(doc["hatches"][0])
    assert any(h["kind"] == "runtime:escape"
               and h["reason"] == "soak harness owns the slot"
               for h in doc["hatches"])

    rc = xlint.main(["--format", "json",
                     str(FIXTURES / "pair_sites.py")])
    doc = json.loads(capsys.readouterr().out)
    comment = [h for h in doc["hatches"]
               if h["kind"] == "comment:pair-release"]
    assert comment and comment[0]["reason"].startswith("drill hook")


# ---------------------------------------------------------------- --changed
def test_cli_changed_usage_and_bad_ref_exit_2(tmp_path, capsys, monkeypatch):
    f = tmp_path / "x.py"
    f.write_text("X = 1\n")
    assert xlint.main(["--changed"]) == 2
    monkeypatch.chdir(tmp_path)      # not a git checkout
    assert xlint.main(["--changed", "HEAD", str(f)]) == 2
    capsys.readouterr()


def test_cli_changed_filters_to_diff(tmp_path, capsys, monkeypatch):
    """--changed <ref> lints the full tree but reports only violations
    in files the diff touches — except registry files, which are never
    filtered (a stale registry entry is everyone's failure)."""
    import json
    import subprocess

    monkeypatch.chdir(tmp_path)
    git = ["git", "-c", "user.email=t@t", "-c", "user.name=t"]
    subprocess.run(["git", "init", "-q"], check=True)

    def bad_module(cls, order):
        return ("import threading, time\n"
                f"class {cls}:\n"
                "    def __init__(self):\n"
                f"        self.lk = threading.Lock()  # lock-order: {order}\n"
                "    def f(self):\n"
                "        with self.lk:\n"
                "            time.sleep(1)\n")

    (tmp_path / "bad_old.py").write_text(bad_module("C1", 1))
    # Registry files are exempt from the filter: a committed,
    # unmodified lifecycle.py with a malformed entry must still report.
    (tmp_path / "lifecycle.py").write_text(
        "EFFECT_PAIRS = {\n"
        "    \"x\": \"A.b -> C.d\",\n"
        "}\n")
    subprocess.run(git + ["add", "."], check=True)
    subprocess.run(git + ["commit", "-q", "-m", "seed"], check=True)
    (tmp_path / "bad_new.py").write_text(bad_module("C2", 2))

    rc = xlint.main(["--format", "json", "--changed", "HEAD", "."])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    paths = {v["path"] for v in doc["violations"]}
    assert doc["changed"] == "HEAD"
    assert any("bad_new.py" in p for p in paths)
    assert not any("bad_old.py" in p for p in paths)
    assert any("lifecycle.py" in p for p in paths)


def test_cli_clean_on_tree():
    assert xlint.main([str(PACKAGE), "-q"]) == 0


def test_fixture_files_never_imported():
    """The fixtures must stay import-dead (they contain deliberate
    anti-patterns): no __init__.py anywhere under the fixture root."""
    assert not list(FIXTURES.rglob("__init__.py"))
    assert os.path.isdir(FIXTURES)
