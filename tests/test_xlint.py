"""xlint rule tests: every rule must fire on its violation fixture, stay
quiet on the clean/hatched variants, and the real tree must lint clean
(the tier-1 CI gate)."""

import os
from pathlib import Path

import pytest

from xllm_service_tpu.devtools import xlint

FIXTURES = Path(__file__).parent / "data" / "xlint_fixtures"
PACKAGE = Path(__file__).parent.parent / "xllm_service_tpu"


@pytest.fixture(scope="module")
def fixture_violations():
    return xlint.run([str(FIXTURES)])


def hits(violations, rule, path_part="", msg_part=""):
    return [v for v in violations
            if v.rule == rule and path_part in v.path and msg_part in v.message]


# ------------------------------------------------------ no-blocking-under-lock
class TestBlockingUnderLock:
    def test_sleep_under_lock_flagged(self, fixture_violations):
        assert hits(fixture_violations, "no-blocking-under-lock",
                    "blocking.py", "sleep")

    def test_http_under_lock_flagged(self, fixture_violations):
        assert hits(fixture_violations, "no-blocking-under-lock",
                    "blocking.py", "HTTP I/O")

    def test_coordination_call_under_lock_flagged(self, fixture_violations):
        assert hits(fixture_violations, "no-blocking-under-lock",
                    "blocking.py", "coordination call")

    def test_channel_rpc_under_lock_flagged(self, fixture_violations):
        assert hits(fixture_violations, "no-blocking-under-lock",
                    "blocking.py", "engine-channel RPC")

    def test_exact_violation_count(self, fixture_violations):
        # fine_outside / closure_defined_under_lock / excused must NOT
        # fire: exactly the four deliberate violations above.
        assert len(hits(fixture_violations,
                        "no-blocking-under-lock", "blocking.py")) == 4


# ------------------------------------------------------------- lock-discipline
class TestLockDiscipline:
    def test_missing_annotation_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-discipline", "discipline.py",
                    "unannotated_lock")

    def test_declaration_outside_init_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-discipline", "discipline.py",
                    "late_lock")

    def test_bare_acquire_and_release_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-discipline", "discipline.py",
                    "acquire")
        assert hits(fixture_violations, "lock-discipline", "discipline.py",
                    "release")

    def test_function_local_lock_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-discipline", "discipline.py",
                    "local 'tmp_lock'")

    def test_hatched_local_lock_not_flagged(self, fixture_violations):
        assert not hits(fixture_violations, "lock-discipline",
                        "discipline.py", "scratch")

    def test_conflicting_redeclaration_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-discipline", "discipline.py",
                    "re-declared with order 21")

    def test_hatched_acquire_not_flagged(self, fixture_violations):
        # excused_acquire carries allow-bare-acquire hatches: exactly one
        # acquire + one release violation remain (manual_acquire's).
        bare = [v for v in hits(fixture_violations, "lock-discipline",
                                "discipline.py") if "bare" in v.message]
        assert len(bare) == 2


# ------------------------------------------------------------------ lock-order
class TestLockOrder:
    def test_nested_with_inversion_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-order", "ordering.py",
                    "Orderly.lock_b (order 2) -> Orderly.lock_a (order 1)")

    def test_interprocedural_inversion_flagged(self, fixture_violations):
        assert hits(fixture_violations, "lock-order", "ordering.py",
                    "via call to Interproc.grab_inner_interproc()")

    def test_cycle_reported(self, fixture_violations):
        assert hits(fixture_violations, "lock-order", "ordering.py",
                    "cycle")

    def test_respecting_order_not_flagged(self, fixture_violations):
        assert not hits(fixture_violations, "lock-order", "ordering.py",
                        "Orderly.lock_a (order 1) -> Orderly.lock_b")


# ----------------------------------------------------------------- fault-point
class TestFaultPoints:
    def test_unregistered_point_flagged(self, fixture_violations):
        assert hits(fixture_violations, "fault-point", "fault_sites.py",
                    "demo.unregistered")

    def test_non_literal_point_flagged(self, fixture_violations):
        assert hits(fixture_violations, "fault-point", "fault_sites.py",
                    "string literal")

    def test_dead_point_flagged(self, fixture_violations):
        assert hits(fixture_violations, "fault-point", "faults.py",
                    "demo.dead")

    def test_registered_used_point_not_flagged(self, fixture_violations):
        assert not hits(fixture_violations, "fault-point", "", "demo.used")


# ----------------------------------------------------------------- span-point
class TestSpanPoints:
    def test_unregistered_point_flagged(self, fixture_violations):
        assert hits(fixture_violations, "span-point", "span_sites.py",
                    "demo.span_unregistered")

    def test_non_literal_point_flagged(self, fixture_violations):
        assert hits(fixture_violations, "span-point", "span_sites.py",
                    "string literal")

    def test_dead_point_flagged(self, fixture_violations):
        assert hits(fixture_violations, "span-point", "tracing.py",
                    "demo.span_dead")

    def test_registered_used_point_not_flagged(self, fixture_violations):
        assert not hits(fixture_violations, "span-point", "",
                        "demo.span_used")

    def test_hatched_forwarder_not_flagged(self, fixture_violations):
        # Exactly two span-site violations: the hatched forwarder and the
        # non-TRACER receiver stay quiet.
        assert len(hits(fixture_violations, "span-point",
                        "span_sites.py")) == 2


# ------------------------------------------------------------- metrics-registry
class TestMetricsRegistry:
    def test_ad_hoc_instrument_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry",
                    "metrics_sites.py", "ad-hoc")

    def test_undeclared_import_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry",
                    "metrics_sites.py", "NOT_DECLARED")

    def test_dead_instrument_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry", "metrics.py",
                    "DEAD_TOTAL")

    def test_import_alone_is_not_a_use(self, fixture_violations):
        # IMPORT_ONLY_TOTAL is imported by metrics_sites.py but never
        # referenced — still dead.
        assert hits(fixture_violations, "metrics-registry", "metrics.py",
                    "IMPORT_ONLY_TOTAL")

    def test_duplicate_name_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry", "metrics.py",
                    "duplicated_name")

    def test_used_instrument_not_flagged(self, fixture_violations):
        assert not [v for v in hits(fixture_violations, "metrics-registry",
                                    "", "dead metric")
                    if "USED_TOTAL" in v.message
                    or "LABELED_TOTAL" in v.message]

    def test_wrong_label_names_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry",
                    "metrics_sites.py", "declares labelnames")

    def test_write_without_labels_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry",
                    "metrics_sites.py", "write through .labels")

    def test_labels_on_unlabeled_flagged(self, fixture_violations):
        assert hits(fixture_violations, "metrics-registry",
                    "metrics_sites.py", "declares no labelnames")

    def test_module_qualified_write_flagged(self, fixture_violations):
        # m.LABELED_TOTAL.inc(): the attribute-receiver form is checked
        # just like the bare-name form.
        assert [v for v in hits(fixture_violations, "metrics-registry",
                                "metrics_sites.py", "write through")
                ] and len(hits(fixture_violations, "metrics-registry",
                               "metrics_sites.py", "write through")) == 2

    def test_correct_labeled_write_not_flagged(self, fixture_violations):
        # The clean .labels(instance=..., phase=...).inc() site: exactly
        # the six deliberate metrics_sites violations fire.
        assert len(hits(fixture_violations, "metrics-registry",
                        "metrics_sites.py")) == 6


# -------------------------------------------------------------------- hot-json
class TestHotJson:
    def test_dumps_reference_flagged(self, fixture_violations):
        assert hits(fixture_violations, "hot-json", "hot_sites.py",
                    "json.dumps")

    def test_json_kwarg_flagged(self, fixture_violations):
        assert hits(fixture_violations, "hot-json", "hot_sites.py",
                    "json= kwarg")

    def test_alias_laundering_flagged(self, fixture_violations):
        assert hits(fixture_violations, "hot-json", "hot_sites.py",
                    "push_hot")

    def test_stale_registry_entry_flagged(self, fixture_violations):
        assert hits(fixture_violations, "hot-json", "wire.py",
                    "Ghost.never_defined")

    def test_hatched_and_unregistered_quiet(self, fixture_violations):
        # forward_hatched (hatch) + unregistered_sibling + bystander stay
        # quiet: exactly the three deliberate site violations fire.
        assert len(hits(fixture_violations, "hot-json",
                        "hot_sites.py")) == 3


# ---------------------------------------------------------------- broad-except
class TestBroadExcept:
    def test_silent_swallow_flagged(self, fixture_violations):
        assert hits(fixture_violations, "broad-except", "broad_except.py",
                    "neither logs nor re-raises")

    def test_bare_except_flagged(self, fixture_violations):
        assert hits(fixture_violations, "broad-except", "broad_except.py",
                    "bare")

    def test_logging_reraising_and_hatched_not_flagged(self,
                                                       fixture_violations):
        # logs_it / reraises / excused are clean: exactly the two
        # deliberate violations above fire in the fixture.
        assert len(hits(fixture_violations, "broad-except",
                        "broad_except.py")) == 2

    def test_single_file_invocation_keeps_dir_scope(self):
        # Linting just the file must still apply the scheduler-path scope
        # (scope keys on the absolute path, not the display-relative one).
        vs = xlint.run([str(FIXTURES / "scheduler" / "broad_except.py")])
        assert [v for v in vs if v.rule == "broad-except"
                and "neither logs nor re-raises" in v.message]


# ------------------------------------------------------------------- CLI + CI
class TestDriver:
    def test_cli_reports_and_exits_nonzero_on_fixtures(self, capsys):
        rc = xlint.main([str(FIXTURES)])
        out = capsys.readouterr().out
        assert rc == 1
        assert "no-blocking-under-lock" in out

    def test_unparseable_file_reported(self, tmp_path):
        bad = tmp_path / "broken.py"
        bad.write_text("def oops(:\n")
        vs = xlint.run([str(bad)])
        assert vs and vs[0].rule == "parse"


def test_xlint_tree_clean():
    """Tier-1 gate: the analyzer over the real package must be clean."""
    violations = xlint.run([str(PACKAGE)])
    assert not violations, (
        "xlint violations in the tree:\n"
        + "\n".join(str(v) for v in violations)
        + "\n\nrun: python -m xllm_service_tpu.devtools.xlint "
          "xllm_service_tpu")


def test_cli_clean_on_tree():
    assert xlint.main([str(PACKAGE), "-q"]) == 0


def test_fixture_files_never_imported():
    """The fixtures must stay import-dead (they contain deliberate
    anti-patterns): no __init__.py anywhere under the fixture root."""
    assert not list(FIXTURES.rglob("__init__.py"))
    assert os.path.isdir(FIXTURES)
