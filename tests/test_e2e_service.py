"""End-to-end slice (SURVEY.md §7.2 checkpoint A): HTTP client → master →
fake engine → streamed tokens. Plus failure drills over the full stack."""

import json
import time

import pytest
import requests

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.coordination.memory import InMemoryCoordination, MemoryStore
from xllm_service_tpu.master import Master
from xllm_service_tpu.testing.fake_engine import FakeEngine, FakeEngineConfig

from fakes import wait_until


@pytest.fixture()
def cluster(store):
    """One master + one MIX fake engine sharing an in-memory coordination
    'cluster'."""
    opts = ServiceOptions(
        host="127.0.0.1", http_port=0, rpc_port=0,
        lease_ttl_s=1.0, reconcile_interval_s=0.1,
        heartbeat_silence_to_suspect_s=0.5,
        detect_disconnected_instance_interval_s=0.5,
        health_probe_attempts=1, health_probe_timeout_s=0.3,
        sync_interval_s=0.2)
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()
    engine = FakeEngine(InMemoryCoordination(store),
                        FakeEngineConfig()).start()
    assert wait_until(
        lambda: master.scheduler.instance_mgr.get_instance_meta(engine.name)
        is not None, timeout=5)
    yield master, engine
    engine.stop()
    master.stop()


def _base(master) -> str:
    return f"http://127.0.0.1:{master.http_port}"


class TestE2E:
    def test_hello_and_models(self, cluster):
        master, engine = cluster
        r = requests.get(_base(master) + "/hello", timeout=5)
        assert r.status_code == 200 and r.json()["status"] == "ok"
        models = requests.get(_base(master) + "/v1/models", timeout=5).json()
        assert [m["id"] for m in models["data"]] == ["fake-model"]

    def test_non_stream_completion(self, cluster):
        master, engine = cluster
        r = requests.post(_base(master) + "/v1/completions", json={
            "model": "fake-model", "prompt": "Say hi", "max_tokens": 64,
        }, timeout=10)
        assert r.status_code == 200, r.text
        body = r.json()
        assert body["object"] == "text_completion"
        assert body["choices"][0]["text"] == "Hello from the fake engine!"
        assert body["choices"][0]["finish_reason"] == "stop"
        assert body["usage"]["prompt_tokens"] > 0
        # The engine saw the enriched payload.
        fwd = engine.accepted_requests[-1]
        assert fwd["service_request_id"].startswith("completion-")
        assert fwd["token_ids"]
        assert fwd["routing"]["prefill_name"] == engine.name

    def test_streaming_chat(self, cluster):
        master, engine = cluster
        r = requests.post(_base(master) + "/v1/chat/completions", json={
            "model": "fake-model",
            "messages": [{"role": "user", "content": "hi"}],
            "stream": True, "max_tokens": 64,
            "stream_options": {"include_usage": True},
        }, stream=True, timeout=10)
        assert r.status_code == 200
        assert r.headers["Content-Type"].startswith("text/event-stream")
        events = []
        for line in r.iter_lines():
            if line.startswith(b"data: "):
                events.append(line[len(b"data: "):])
        assert events[-1] == b"[DONE]"
        chunks = [json.loads(e) for e in events[:-1]]
        content = "".join(
            (c["choices"][0]["delta"].get("content") or "")
            for c in chunks if c.get("choices"))
        assert content == "Hello from the fake engine!"
        finish = [c["choices"][0].get("finish_reason")
                  for c in chunks if c.get("choices")]
        assert "stop" in finish
        usage = [c for c in chunks if c.get("usage")]
        assert usage and usage[-1]["usage"]["completion_tokens"] > 0

    def test_metrics_endpoint(self, cluster):
        master, engine = cluster
        requests.post(_base(master) + "/v1/completions", json={
            "model": "fake-model", "prompt": "x", "max_tokens": 8}, timeout=10)
        text = requests.get(_base(master) + "/metrics", timeout=5).text
        assert "server_request_in_total" in text
        assert "time_to_first_token_latency_milliseconds" in text

    def test_embeddings_proxied_to_engine(self, cluster):
        """/v1/embeddings proxies to the routed engine with its status
        passed through (real engines serve it — test_e2e_real_engine; the
        fake engine has no such endpoint, so its 404 surfaces as-is rather
        than the old hard 501 or an opaque 502)."""
        master, _ = cluster
        r = requests.post(_base(master) + "/v1/embeddings",
                          json={"input": "x"}, timeout=10)
        assert r.status_code == 404

    def test_heartbeat_feeds_global_kvcache(self, cluster):
        master, engine = cluster
        requests.post(_base(master) + "/v1/completions", json={
            "model": "fake-model",
            "prompt": "tok " * 400,   # > 1 block of 128 tokens
            "max_tokens": 8}, timeout=10)
        assert wait_until(
            lambda: master.scheduler.kvcache_mgr.num_blocks() > 0, timeout=5)


class TestE2EFailure:
    def test_engine_death_evicts_and_gates(self, cluster, store):
        master, engine = cluster
        engine.kill()
        # Suspect eviction: instance disappears from the fleet.
        assert wait_until(
            lambda: master.scheduler.instance_mgr.get_instance_meta(engine.name)
            is None, timeout=10)
        # Readiness gate: API traffic rejected with 503.
        r = requests.post(_base(master) + "/v1/completions", json={
            "model": "fake-model", "prompt": "x"}, timeout=5)
        assert r.status_code == 503

    def test_engine_replacement_same_name(self, cluster, store):
        master, engine = cluster
        old_incarnation = engine.incarnation_id
        engine.pause()
        import uuid as _uuid

        engine.incarnation_id = _uuid.uuid4().hex[:12]  # "restart"
        engine.resume()
        assert wait_until(
            lambda: (master.scheduler.instance_mgr.get_instance_meta(engine.name)
                     or engine.meta()).incarnation_id == engine.incarnation_id
            and master.scheduler.instance_mgr.get_instance_meta(engine.name)
            is not None, timeout=5)
        assert master.scheduler.instance_mgr.get_instance_meta(
            engine.name).incarnation_id != old_incarnation

    def test_request_cancelled_when_engine_dies_midstream(self, store):
        opts = ServiceOptions(
            host="127.0.0.1", http_port=0, rpc_port=0,
            lease_ttl_s=0.5, reconcile_interval_s=0.1,
            heartbeat_silence_to_suspect_s=0.3,
            detect_disconnected_instance_interval_s=0.3,
            health_probe_attempts=1, health_probe_timeout_s=0.2,
            sync_interval_s=0.2)
        master = Master(opts, coord=InMemoryCoordination(store))
        master.start()
        engine = FakeEngine(
            InMemoryCoordination(store),
            FakeEngineConfig(reply_text="slow " * 200, chunk_size=5,
                             delay_s=0.1)).start()
        try:
            assert wait_until(
                lambda: master.scheduler.instance_mgr.get_instance_meta(
                    engine.name) is not None, timeout=5)
            r = requests.post(
                f"http://127.0.0.1:{master.http_port}/v1/completions",
                json={"model": "fake-model", "prompt": "x", "stream": True,
                      "max_tokens": 1000},
                stream=True, timeout=10)
            it = r.iter_lines()
            assert next(it)  # first chunk arrived
            engine.kill()
            # Cancel-and-surface: stream ends with an error payload.
            saw_error = False
            deadline = time.time() + 15
            for line in it:
                if time.time() > deadline:
                    break
                if line.startswith(b"data: ") and b"error" in line:
                    saw_error = True
                    break
            assert saw_error
        finally:
            engine.stop()
            master.stop()


class TestAdminAndTracing:
    def test_live_config_reload(self, cluster):
        """Reference parity: target_ttft/target_tpot are live-reloadable
        with validation (global_gflags.cpp:122-132)."""
        master, _ = cluster
        base = _base(master)
        cfg = requests.get(base + "/admin/config", timeout=5).json()
        assert cfg["target_tpot_ms"] == 50.0
        r = requests.post(base + "/admin/config",
                          json={"target_tpot_ms": 25.0,
                                "target_ttft_ms": 500.0}, timeout=5)
        assert r.status_code == 200
        assert master.scheduler._opts.target_tpot_ms == 25.0
        # Validation: non-positive targets and unknown keys rejected.
        assert requests.post(base + "/admin/config",
                             json={"target_tpot_ms": -1},
                             timeout=5).status_code == 400
        assert requests.post(base + "/admin/config",
                             json={"http_port": 1},
                             timeout=5).status_code == 400

    def test_request_tracing(self, store, tmp_path):
        """Opt-in JSONL request tracing (reference RequestTracer)."""
        opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                              lease_ttl_s=1.0, sync_interval_s=0.3,
                              enable_request_trace=True,
                              trace_dir=str(tmp_path))
        master = Master(opts, coord=InMemoryCoordination(store))
        master.start()
        engine = FakeEngine(InMemoryCoordination(store),
                            FakeEngineConfig()).start()
        try:
            assert wait_until(
                lambda: master.scheduler.instance_mgr.get_instance_meta(
                    engine.name) is not None, timeout=5)
            r = requests.post(
                f"http://127.0.0.1:{master.http_port}/v1/completions",
                json={"model": "fake-model", "prompt": "trace me",
                      "max_tokens": 16}, timeout=10)
            assert r.status_code == 200
            trace = (tmp_path / "trace.jsonl").read_text().splitlines()
            assert len(trace) >= 2   # request record + output deltas
            first = json.loads(trace[0])
            assert first["service_request_id"].startswith("completion-")
            assert first["data"]["request"]["prompt"] == "trace me"

            # Span breakdown is emitted at request exit on the output
            # lane — it may land just after the HTTP response returns.
            def _spans():
                lines = (tmp_path / "trace.jsonl").read_text().splitlines()
                return [json.loads(ln)["data"] for ln in lines
                        if json.loads(ln)["data"].get("type") == "spans"]

            assert wait_until(lambda: bool(_spans()), timeout=5), \
                "no span record in trace"
            spans = _spans()
            sp = spans[0]
            assert sp["total_ms"] >= (sp["ttft_ms"] or 0) >= 0
            assert sp["prompt_tokens"] > 0
            assert sp["generated_tokens"] > 0
            assert sp["prefill_instance"] == engine.name
        finally:
            engine.stop()
            master.stop()
