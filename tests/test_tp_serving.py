"""Tensor-parallel engine serving e2e: an engine whose mesh shards the
model over the `model` axis (GSPMD rules) must serve through the full
stack with output identical to a single-device engine, including PD
disaggregation over the host KV-transfer path."""

import jax.numpy as jnp
import pytest
import requests

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.types import InstanceType
from xllm_service_tpu.coordination.memory import InMemoryCoordination, MemoryStore
from xllm_service_tpu.engine.agent import AgentConfig, EngineAgent
from xllm_service_tpu.engine.config import EngineConfig
from xllm_service_tpu.engine.kv_transfer import device_transfer_available
from xllm_service_tpu.master import Master
from xllm_service_tpu.models.base import tiny_config
from xllm_service_tpu.parallel.mesh import MeshConfig

from fakes import wait_until

BODY = {"model": "tiny-llama", "prompt": "shard me across the mesh",
        "max_tokens": 6, "temperature": 0, "ignore_eos": True}


def _cfg(tp=1) -> EngineConfig:
    return EngineConfig(
        model_id="tiny-llama",
        # kv heads divisible by tp for head sharding.
        model=tiny_config(dtype=jnp.float32, max_context_len=256,
                          num_heads=4, num_kv_heads=2),
        mesh=MeshConfig(model=tp) if tp > 1 else None,
        num_pages=64, page_size=16, hash_block_size=32,
        max_batch_size=4, max_seq_len=256, prefill_buckets=(32, 64, 256))


def _cluster(tp, itypes=(InstanceType.MIX,)):
    store = MemoryStore(expiry_tick_s=0.05)
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=1.0, sync_interval_s=0.3,
                          reconcile_interval_s=0.1)
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()
    agents = []
    for itype in itypes:
        a = EngineAgent(
            _cfg(tp),
            AgentConfig(host="127.0.0.1", model_id="tiny-llama",
                        instance_type=itype,
                        heartbeat_interval_s=0.3, lease_ttl_s=1.0),
            coord=InMemoryCoordination(store)).start()
        agents.append(a)
    assert wait_until(
        lambda: all(master.scheduler.instance_mgr.get_instance_meta(a.name)
                    is not None for a in agents), timeout=10)
    return master, agents, store


def _run(master):
    r = requests.post(f"http://127.0.0.1:{master.http_port}/v1/completions",
                      json=BODY, timeout=180)
    assert r.status_code == 200, r.text
    return r.json()["choices"][0]["text"]


class TestTensorParallelServing:
    def test_tp2_matches_single_device(self):
        m1, a1, s1 = _cluster(tp=1)
        try:
            want = _run(m1)
        finally:
            for a in a1:
                a.stop()
            m1.stop()
            s1.close()

        m2, a2, s2 = _cluster(tp=2)
        try:
            assert a2[0].engine.mesh is not None
            assert a2[0].engine.mesh.shape["model"] == 2
            meta = m2.scheduler.instance_mgr.get_instance_meta(a2[0].name)
            assert meta.topology.num_devices() == 2
            got = _run(m2)
        finally:
            for a in a2:
                a.stop()
            m2.stop()
            s2.close()
        assert got == want

    @pytest.mark.skipif(
        not device_transfer_available(),
        reason="jax.experimental.transfer absent in this jax build: the "
               "device-path KV handoff has no transport (the host-msgpack "
               "fallback is covered by test_e2e_pd_disagg)")
    def test_tp2_pd_disaggregation_device_path(self):
        """PD pair of TP-sharded engines with identical mesh topologies:
        the handoff rides the device path shard-for-shard (the pull
        reconstructs the sender's partition spec on the receiver's mesh)
        and output matches MIX."""
        m1, a1, s1 = _cluster(tp=2)
        try:
            want = _run(m1)
        finally:
            for a in a1:
                a.stop()
            m1.stop()
            s1.close()

        m2, a2, s2 = _cluster(tp=2, itypes=(InstanceType.PREFILL,
                                            InstanceType.DECODE))
        try:
            prefill, decode = a2
            assert prefill.kv_transfer is not None
            got = _run(m2)
            assert prefill.kv_device_sent == 1
            assert decode.kv_device_received == 1
        finally:
            for a in a2:
                a.stop()
            m2.stop()
            s2.close()
        assert got == want
