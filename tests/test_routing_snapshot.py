"""Routing-snapshot (RCU) consistency drills.

PR 4 made the scheduling hot path lock-free: `select_instances_pair`,
`bind_request_instance_incarnations`, `has_available_instances` and
`get_channel` read an immutable snapshot published by membership writers.
These drills race heartbeats, evictions, replacements and PD-role flips
against concurrent scheduling and pin the consistency contract:

- a schedule that returns OK is bound to a (name, incarnation) pair that
  was live at some instant during the call — NEVER to an instance evicted
  (or an incarnation replaced) before the call began;
- a drained/SUSPECT/evicted instance disappears from routing as soon as
  its state change publishes;
- readiness and wire negotiation follow the snapshot.

The chaos-marked drill runs the same race through the full HTTP stack
with live streams and the fault plane (and doubles as a race detector
under XLLM_LOCK_DEBUG=1 via the conftest instrumented-lock guard).
"""

import json
import threading
import time
import uuid

import pytest
import requests

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.faults import FAULTS
from xllm_service_tpu.common.request import Request
from xllm_service_tpu.common.types import InstanceRuntimeState, InstanceType
from xllm_service_tpu.coordination.memory import InMemoryCoordination
from xllm_service_tpu.master import Master
from xllm_service_tpu.rpc.wire import WIRE_JSON, WIRE_MSGPACK
from xllm_service_tpu.scheduler.instance_mgr import InstanceMgr
from xllm_service_tpu.scheduler.scheduler import Scheduler
from xllm_service_tpu.testing.fake_engine import FakeEngine, FakeEngineConfig

from fakes import FakeChannel, make_meta, wait_until


def _mgr(store, **opt_kw) -> InstanceMgr:
    opts = ServiceOptions(reconcile_interval_s=3600,
                          sync_interval_s=3600, **opt_kw)
    return InstanceMgr(InMemoryCoordination(store), opts,
                       channel_factory=FakeChannel.factory,
                       start_threads=False)


class TestSnapshotSemantics:
    def test_suspect_and_draining_leave_routing(self, store):
        FakeChannel.reset()
        mgr = _mgr(store)
        mgr.register_instance(make_meta("a", InstanceType.MIX),
                              link_peers=False)
        mgr.register_instance(make_meta("b", InstanceType.MIX),
                              link_peers=False)
        assert mgr.has_available_instances()
        picked = {mgr.get_next_instance_pair().prefill_name
                  for _ in range(8)}
        assert picked == {"a", "b"}

        with mgr._cluster_lock:
            mgr._set_state(mgr._instances["a"],
                           InstanceRuntimeState.SUSPECT)
        picked = {mgr.get_next_instance_pair().prefill_name
                  for _ in range(8)}
        assert picked == {"b"}

        # Draining flag arrives via a meta refresh: also leaves routing.
        meta_b = mgr.get_instance_meta("b")
        meta_b.draining = True
        mgr._handle_instance_put(meta_b)
        assert not mgr.has_available_instances()
        assert not mgr.get_next_instance_pair().valid()

    def test_bind_fails_for_instance_evicted_after_select(self, store):
        FakeChannel.reset()
        mgr = _mgr(store)
        mgr.register_instance(make_meta("a", InstanceType.MIX),
                              link_peers=False)
        routing = mgr.get_next_instance_pair()
        assert routing.prefill_name == "a"
        mgr.deregister_instance("a", reason="drill")
        req = Request(service_request_id="s", request_id="r", model="m")
        req.routing = routing
        # RCU validation: the CURRENT snapshot no longer holds "a".
        assert not mgr.bind_request_instance_incarnations(req)

    def test_wire_negotiation_and_demotion(self, store):
        FakeChannel.reset()
        mgr = _mgr(store)
        mgr.register_instance(
            make_meta("m", InstanceType.MIX,
                      wire_formats=[WIRE_MSGPACK, WIRE_JSON]),
            link_peers=False)
        mgr.register_instance(make_meta("legacy", InstanceType.MIX),
                              link_peers=False)
        assert mgr.dispatch_wire("m") == WIRE_MSGPACK
        assert mgr.dispatch_wire("legacy") == WIRE_JSON   # default meta
        assert mgr.get_channel("m").wire_format == WIRE_MSGPACK
        mgr.demote_wire("m")
        assert mgr.dispatch_wire("m") == WIRE_JSON
        mgr.demote_wire("m")   # idempotent
        assert mgr.dispatch_wire("m") == WIRE_JSON

    def test_channel_read_is_snapshot_backed(self, store):
        FakeChannel.reset()
        mgr = _mgr(store)
        mgr.register_instance(make_meta("a", InstanceType.MIX),
                              link_peers=False)
        assert mgr.get_channel("a") is FakeChannel.registry["a"]
        mgr.deregister_instance("a", reason="drill")
        assert mgr.get_channel("a") is None


class TestSchedulingRaces:
    """Writers churn the fleet while readers schedule: no OK schedule may
    bind to a pair that was already dead before the call began."""

    def _scheduler(self, store) -> Scheduler:
        sched = Scheduler(ServiceOptions(reconcile_interval_s=3600,
                                         sync_interval_s=3600,
                                         lease_ttl_s=3600),
                          coord=InMemoryCoordination(store),
                          start_threads=False)
        sched.instance_mgr._channel_factory = FakeChannel.factory
        return sched

    def test_evictions_and_replacements_race_schedule(self, store):
        FakeChannel.reset()
        sched = self._scheduler(store)
        mgr = sched.instance_mgr
        names = [f"i{k}" for k in range(4)]
        for n in names:
            mgr.register_instance(make_meta(n, InstanceType.MIX),
                                  link_peers=False)

        dead_lock = threading.Lock()
        dead: set = set()          # (name, incarnation) no longer live
        stop = threading.Event()
        errors: list = []

        def churner(my_names):
            while not stop.is_set():
                for n in my_names:
                    meta = mgr.get_instance_meta(n)
                    if meta is None:
                        continue
                    with dead_lock:
                        dead.add((n, meta.incarnation_id))
                    # Replacement: same name, new incarnation (the
                    # deregister+register path the watch plane takes).
                    mgr.deregister_instance(n, reason="replaced")
                    mgr.register_instance(
                        make_meta(n, InstanceType.MIX,
                                  incarnation_id=uuid.uuid4().hex[:8]),
                        link_peers=False)

        def reader():
            while not stop.is_set():
                with dead_lock:
                    dead_before = set(dead)
                req = Request(service_request_id=uuid.uuid4().hex[:8],
                              request_id="r", model="m", prompt="hi")
                status = sched.schedule(req)
                if not status.ok():
                    continue   # churn window: UNAVAILABLE is legal
                pair = (req.routing.prefill_name, req.prefill_incarnation)
                if not req.prefill_incarnation:
                    errors.append(f"unbound OK schedule: {pair}")
                elif pair in dead_before:
                    errors.append(f"routed to stale incarnation: {pair}")

        threads = [threading.Thread(target=churner, args=(names[:2],)),
                   threading.Thread(target=churner, args=(names[2:],))] + \
                  [threading.Thread(target=reader) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        try:
            assert not errors, errors[:5]
        finally:
            sched.stop()

    def test_role_flips_race_schedule(self, store):
        FakeChannel.reset()
        sched = self._scheduler(store)
        mgr = sched.instance_mgr
        for k in range(2):
            mgr.register_instance(make_meta(f"p{k}", InstanceType.PREFILL),
                                  link_peers=False)
            mgr.register_instance(make_meta(f"d{k}", InstanceType.DECODE),
                                  link_peers=False)
        stop = threading.Event()
        errors: list = []

        def flipper():
            flip = True
            while not stop.is_set():
                # p1/d1 swap roles continuously; p0/d0 anchor the fleet.
                mgr.flip_instance_role(
                    "p1", InstanceType.DECODE if flip
                    else InstanceType.PREFILL)
                mgr.flip_instance_role(
                    "d1", InstanceType.PREFILL if flip
                    else InstanceType.DECODE)
                flip = not flip

        def reader():
            while not stop.is_set():
                req = Request(service_request_id=uuid.uuid4().hex[:8],
                              request_id="r", model="m", prompt="hi")
                status = sched.schedule(req)
                if not status.ok():
                    errors.append(status.message)   # anchors always exist
                elif not req.prefill_incarnation:
                    errors.append("unbound OK schedule")

        threads = [threading.Thread(target=flipper)] + \
                  [threading.Thread(target=reader) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(1.2)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        try:
            assert not errors, errors[:5]
        finally:
            sched.stop()


@pytest.mark.chaos
class TestSnapshotChaosDrill:
    """Full-stack: fleet churn (pause/resume + role flips) under live
    streams with the fault plane armed. Every stream must complete with
    the full reply (transparent failover covers any mid-churn binding)."""

    REPLY = "Snapshots never route to the dead."

    def test_streams_survive_fleet_churn(self, store):
        FAULTS.configure((), seed=7)
        opts = ServiceOptions(
            host="127.0.0.1", http_port=0, rpc_port=0,
            lease_ttl_s=0.5, reconcile_interval_s=0.05,
            heartbeat_silence_to_suspect_s=0.3,
            detect_disconnected_instance_interval_s=0.5,
            health_probe_attempts=1, health_probe_timeout_s=0.2,
            sync_interval_s=0.2, failover_backoff_base_s=0.05,
            failover_backoff_max_s=0.3)
        master = Master(opts, coord=InMemoryCoordination(store))
        master.start()
        engines = [
            FakeEngine(InMemoryCoordination(store), FakeEngineConfig(
                reply_text=self.REPLY, chunk_size=4, delay_s=0.03,
                heartbeat_interval_s=0.1, lease_ttl_s=0.5)).start()
            for _ in range(3)]
        base = f"http://127.0.0.1:{master.http_port}"
        try:
            assert wait_until(
                lambda: all(master.scheduler.instance_mgr
                            .get_instance_meta(e.name) is not None
                            for e in engines), timeout=5)
            stop = threading.Event()

            def churner():
                flip = True
                while not stop.is_set():
                    # Role flips + a heartbeat pause/resume cycle on one
                    # engine: SUSPECT → recovery churns the snapshot.
                    master.scheduler.instance_mgr.flip_instance_role(
                        engines[0].name,
                        InstanceType.PREFILL if flip else InstanceType.MIX)
                    engines[1].pause()
                    time.sleep(0.15)
                    engines[1].resume()
                    flip = not flip
                    time.sleep(0.1)

            results, errors = [], []

            def run_stream():
                try:
                    r = requests.post(base + "/v1/completions", json={
                        "model": "fake-model", "prompt": "chaos",
                        "stream": True, "max_tokens": 1000},
                        stream=True, timeout=60)
                    assert r.status_code == 200, r.text
                    text = ""
                    for line in r.iter_lines():
                        if not line.startswith(b"data: ") \
                                or line == b"data: [DONE]":
                            continue
                        obj = json.loads(line[len(b"data: "):])
                        if "error" in obj:
                            raise RuntimeError(str(obj["error"]))
                        for c in obj.get("choices", ()):
                            text += c.get("text", "")
                    results.append(text)
                except Exception as e:  # noqa: BLE001 — collected
                    errors.append(e)

            churn = threading.Thread(target=churner)
            churn.start()
            streams = [threading.Thread(target=run_stream)
                       for _ in range(6)]
            for t in streams:
                t.start()
                time.sleep(0.05)
            for t in streams:
                t.join(timeout=60)
            stop.set()
            churn.join(timeout=10)
            assert not errors, errors
            assert len(results) == 6
            assert all(t == self.REPLY for t in results), results
        finally:
            FAULTS.clear()
            for e in engines:
                e.stop()
            master.stop()
