"""Checkpoint C (SURVEY.md §7.2): PD-disaggregated serving — prefill and
decode on separate engine instances with KV handoff; output must equal the
single-instance (MIX) result."""

import json

import jax.numpy as jnp
import pytest
import requests

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.types import InstanceType
from xllm_service_tpu.coordination.memory import InMemoryCoordination, MemoryStore
from xllm_service_tpu.engine.agent import AgentConfig, EngineAgent
from xllm_service_tpu.engine.config import EngineConfig
from xllm_service_tpu.engine.kv_transfer import device_transfer_available
from xllm_service_tpu.master import Master
from xllm_service_tpu.models.base import tiny_config

from fakes import wait_until


def _engine_cfg() -> EngineConfig:
    return EngineConfig(
        model_id="tiny-llama",
        model=tiny_config(dtype=jnp.float32, max_context_len=256),
        num_pages=64, page_size=16, hash_block_size=32,
        max_batch_size=4, max_seq_len=256, prefill_buckets=(32, 64, 256))


def _agent(store, itype: InstanceType, device_kv: bool = True) -> EngineAgent:
    return EngineAgent(
        _engine_cfg(),
        AgentConfig(host="127.0.0.1", model_id="tiny-llama",
                    instance_type=itype,
                    heartbeat_interval_s=0.3, lease_ttl_s=1.0,
                    enable_device_kv_transfer=device_kv),
        coord=InMemoryCoordination(store)).start()


@pytest.fixture(scope="module")
def pd_cluster():
    store = MemoryStore(expiry_tick_s=0.05)
    opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                          lease_ttl_s=1.0, sync_interval_s=0.3,
                          reconcile_interval_s=0.1)
    master = Master(opts, coord=InMemoryCoordination(store))
    master.start()
    prefill = _agent(store, InstanceType.PREFILL)
    decode = _agent(store, InstanceType.DECODE)
    assert wait_until(
        lambda: master.scheduler.instance_mgr.get_instance_meta(prefill.name)
        is not None
        and master.scheduler.instance_mgr.get_instance_meta(decode.name)
        is not None, timeout=10)
    yield master, prefill, decode
    prefill.stop()
    decode.stop()
    master.stop()
    store.close()


def _base(master):
    return f"http://127.0.0.1:{master.http_port}"


BODY = {
    "model": "tiny-llama", "prompt": "disaggregate me please",
    "max_tokens": 6, "temperature": 0, "ignore_eos": True,
}


class TestPDDisaggregation:
    def test_pair_routing_and_linking(self, pd_cluster):
        master, prefill, decode = pd_cluster
        # The two instances were introduced to each other at registration.
        assert wait_until(lambda: decode.name in prefill.linked_peers
                          or prefill.name in decode.linked_peers, timeout=5)

    def test_pd_completion_matches_mix(self, pd_cluster):
        master, prefill, decode = pd_cluster
        r = requests.post(_base(master) + "/v1/completions", json=BODY,
                          timeout=120)
        assert r.status_code == 200, r.text
        pd_body = r.json()
        assert pd_body["choices"][0]["finish_reason"] == "length"
        assert pd_body["usage"]["completion_tokens"] == 6
        pd_text = pd_body["choices"][0]["text"]

        # Decode emitted the whole stream (prefill-only sequences emit
        # nothing locally); prefill holds no residual running sequences.
        assert decode.engine.stats()["total_generated"] >= 6
        assert prefill.engine.stats()["running"] == 0
        # Prefill cached the prompt's full blocks (hash block = 32 tokens is
        # longer than this prompt — so only the decode prefix-cache check in
        # the dedicated test below applies; here just assert no leak).
        assert prefill.engine.page_mgr.usage_perc() < 0.5

        # Same request on a MIX-only cluster must produce the same text
        # (same seed => same weights; greedy decoding).
        store2 = MemoryStore(expiry_tick_s=0.05)
        opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                              lease_ttl_s=1.0, sync_interval_s=0.3)
        m2 = Master(opts, coord=InMemoryCoordination(store2))
        m2.start()
        mix = _agent(store2, InstanceType.MIX)
        try:
            assert wait_until(
                lambda: m2.scheduler.instance_mgr.get_instance_meta(mix.name)
                is not None, timeout=10)
            r2 = requests.post(f"http://127.0.0.1:{m2.http_port}"
                               "/v1/completions", json=BODY, timeout=120)
            assert r2.status_code == 200, r2.text
            assert r2.json()["choices"][0]["text"] == pd_text
        finally:
            mix.stop()
            m2.stop()
            store2.close()

    def test_pd_streaming(self, pd_cluster):
        master, prefill, decode = pd_cluster
        r = requests.post(_base(master) + "/v1/completions",
                          json={**BODY, "stream": True}, stream=True,
                          timeout=120)
        assert r.status_code == 200
        events = [line for line in r.iter_lines()
                  if line.startswith(b"data: ")]
        assert events[-1] == b"data: [DONE]"
        texts = [json.loads(e[6:])["choices"][0]["text"]
                 for e in events[:-1] if b'"choices"' in e]
        assert len("".join(texts)) > 0

    @pytest.mark.skipif(not device_transfer_available(),
                        reason="jax.experimental.transfer not available "
                               "in this runtime (host-msgpack fallback "
                               "covered by the other PD tests)")
    def test_device_transfer_path_used(self, pd_cluster):
        """With transfer servers available on both sides, the handoff must
        ride the device path (KV pulled device-to-device), not the host
        msgpack bounce."""
        master, prefill, decode = pd_cluster
        assert prefill.kv_transfer is not None
        assert decode.kv_transfer is not None
        before = prefill.kv_device_sent
        r = requests.post(_base(master) + "/v1/completions", json=BODY,
                          timeout=120)
        assert r.status_code == 200, r.text
        assert prefill.kv_device_sent == before + 1
        assert prefill.kv_host_sent == 0
        assert decode.kv_device_received >= 1
        assert decode.kv_host_received == 0

    def test_unlinked_peer_handoff_rejected(self, pd_cluster):
        """The link-time KV-layout gate only protects if the transfer
        itself enforces the link: a handoff from an unlinked sender must
        be refused."""
        import msgpack as _mp

        _, _, decode = pd_cluster
        msg = _mp.packb({
            "service_request_id": "rogue-1", "request_id": "rogue-1",
            "source_service_addr": "127.0.0.1:1", "token_ids": [1, 2, 3],
            "first_token": 1, "sampling": {},
            "source_instance": "127.0.0.1:59999",   # never linked
            "kv": {"bytes": b"", "shape": [0], "dtype": "float32"},
        }, use_bin_type=True)
        r = requests.post(f"http://{decode.name}/rpc/kv_transfer",
                          data=msg,
                          headers={"Content-Type": "application/msgpack"},
                          timeout=30)
        assert r.status_code == 403

    def test_decode_kv_transfer_populates_prefix_cache(self, pd_cluster):
        master, prefill, decode = pd_cluster
        requests.post(_base(master) + "/v1/completions",
                      json={**BODY, "prompt": "cache this prefix " * 8},
                      timeout=120)
        # Both sides should now hold prefix blocks (prompt >= 1 hash block).
        assert wait_until(
            lambda: prefill.engine.stats()["cached_blocks"] > 0, timeout=5)
        assert wait_until(
            lambda: decode.engine.stats()["cached_blocks"] > 0, timeout=5)


class TestHostFallbackPath:
    def test_host_path_matches_device_path(self, pd_cluster):
        """The DCN host-msgpack fallback (device transfer disabled) must
        produce the same output as the device path — same PrefillHandoff
        contract, different transport."""
        master, _, _ = pd_cluster
        device_text = requests.post(
            _base(master) + "/v1/completions", json=BODY,
            timeout=120).json()["choices"][0]["text"]

        store2 = MemoryStore(expiry_tick_s=0.05)
        opts = ServiceOptions(host="127.0.0.1", http_port=0, rpc_port=0,
                              lease_ttl_s=1.0, sync_interval_s=0.3,
                              reconcile_interval_s=0.1)
        m2 = Master(opts, coord=InMemoryCoordination(store2))
        m2.start()
        p2 = _agent(store2, InstanceType.PREFILL, device_kv=False)
        d2 = _agent(store2, InstanceType.DECODE, device_kv=False)
        try:
            assert p2.kv_transfer is None and d2.kv_transfer is None
            assert wait_until(
                lambda: m2.scheduler.instance_mgr.get_instance_meta(p2.name)
                is not None
                and m2.scheduler.instance_mgr.get_instance_meta(d2.name)
                is not None, timeout=10)
            r = requests.post(f"http://127.0.0.1:{m2.http_port}"
                              "/v1/completions", json=BODY, timeout=120)
            assert r.status_code == 200, r.text
            assert r.json()["choices"][0]["text"] == device_text
            assert p2.kv_host_sent == 1 and p2.kv_device_sent == 0
            assert d2.kv_host_received == 1
        finally:
            p2.stop()
            d2.stop()
            m2.stop()
            store2.close()
