"""Engine tests: greedy correctness vs a naive reference loop, continuous
batching, prefix-cache reuse, cancellation, page accounting."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xllm_service_tpu.common.request import RequestOutput, SamplingParams
from xllm_service_tpu.engine.config import EngineConfig
from xllm_service_tpu.engine.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.engine.kv_cache import KVPageManager
from xllm_service_tpu.models.base import tiny_config


def make_engine(**kw) -> InferenceEngine:
    cfg = EngineConfig(
        model=tiny_config(dtype=jnp.float32, max_context_len=256),
        num_pages=kw.pop("num_pages", 64), page_size=16,
        hash_block_size=32,
        max_batch_size=kw.pop("max_batch_size", 4),
        max_seq_len=256, prefill_buckets=(32, 64, 256), **kw)
    return InferenceEngine(cfg)


class Collector:
    def __init__(self):
        self.outputs: list[RequestOutput] = []
        self.done = threading.Event()

    def __call__(self, out: RequestOutput) -> None:
        self.outputs.append(out)
        if out.finished:
            self.done.set()

    @property
    def tokens(self):
        return [t for o in self.outputs for s in o.outputs for t in s.token_ids]

    @property
    def text(self):
        return "".join(s.text for o in self.outputs for s in o.outputs)

    @property
    def finish_reason(self):
        for o in self.outputs:
            for s in o.outputs:
                if s.finish_reason:
                    return s.finish_reason
        return ""


def run_requests(engine, reqs, timeout=60):
    for r in reqs:
        engine.submit(r)
    while any(not r.on_output.done.is_set() for r in reqs):
        if not engine.step():
            time.sleep(0.001)


def naive_greedy(engine: InferenceEngine, prompt: list[int], n: int) -> list[int]:
    """Reference loop: full dense prefill each step, argmax.

    Tokens are padded to ONE fixed bucket (seq_lens masks the tail) so
    every step of every caller shares a single compiled program — the
    growing-S version compiled a fresh XLA program per generated token
    and dominated the suite's wall-clock (VERDICT r3 weak #5)."""
    cfg = engine.cfg
    fam, mcfg = engine.family, cfg.model
    S_max = min(cfg.max_seq_len, 256)
    out = []
    toks = list(prompt)
    for _ in range(n):
        S = len(toks)
        assert S <= S_max
        kv = jnp.zeros_like(engine.kv_pages)
        pt = jnp.arange(1, cfg.pages_per_seq + 1, dtype=jnp.int32)[None, :]
        padded = toks + [0] * (S_max - S)
        logits, _ = fam.prefill_forward(
            engine.params, mcfg, jnp.asarray([padded], jnp.int32),
            jnp.arange(S_max)[None, :], kv, pt,
            jnp.zeros((1,), jnp.int32), jnp.asarray([S], jnp.int32))
        nxt = int(jnp.argmax(logits[0]))
        out.append(nxt)
        toks.append(nxt)
    return out


class TestEngineCorrectness:
    def test_greedy_matches_naive_loop(self):
        engine = make_engine()
        prompt = list(range(10, 30))
        want = naive_greedy(engine, prompt, 8)
        col = Collector()
        req = EngineRequest("s1", "r1", token_ids=prompt,
                            sampling=SamplingParams(max_tokens=8,
                                                    temperature=0.0,
                                                    ignore_eos=True),
                            on_output=col)
        run_requests(engine, [req])
        assert col.tokens == want
        assert col.finish_reason == "length"
        usage = [o.usage for o in col.outputs if o.usage]
        assert usage[0].num_prompt_tokens == 20
        assert usage[0].num_generated_tokens == 8

    def test_batched_equals_solo(self):
        """Concurrent greedy sequences must not perturb each other."""
        engine = make_engine()
        prompts = [list(range(5, 20)), list(range(40, 70)),
                   list(range(100, 140))]
        want = [naive_greedy(engine, p, 6) for p in prompts]
        cols = [Collector() for _ in prompts]
        reqs = [EngineRequest(f"s{i}", f"r{i}", token_ids=p,
                              sampling=SamplingParams(max_tokens=6,
                                                      temperature=0.0,
                                                      ignore_eos=True),
                              on_output=c)
                for i, (p, c) in enumerate(zip(prompts, cols))]
        run_requests(engine, reqs)
        for c, w in zip(cols, want):
            assert c.tokens == w

    def test_queueing_beyond_batch_size(self):
        engine = make_engine(max_batch_size=2)
        cols = [Collector() for _ in range(5)]
        reqs = [EngineRequest(f"s{i}", token_ids=list(range(3 + i, 20 + i)),
                              sampling=SamplingParams(max_tokens=4,
                                                      temperature=0.0,
                                                      ignore_eos=True),
                              on_output=c)
                for i, c in enumerate(cols)]
        run_requests(engine, reqs)
        for c in cols:
            assert c.finish_reason == "length"
            assert len(c.tokens) == 4
        # All slots and pages returned.
        assert len(engine._running) == 0
        assert engine.page_mgr.usage_perc() <= \
            engine.page_mgr.pages_per_block * 6 / (engine.cfg.num_pages - 1)

    def test_prefix_cache_reuse_same_output(self):
        engine = make_engine()
        prompt = list(range(1, 65))   # 64 tokens = 2 hash blocks of 32
        col1 = Collector()
        run_requests(engine, [EngineRequest(
            "a", token_ids=prompt,
            sampling=SamplingParams(max_tokens=5, temperature=0.0,
                                    ignore_eos=True), on_output=col1)])
        assert engine.page_mgr.cached_block_count() >= 1
        ev = engine.drain_kv_events()
        assert ev.stored   # blocks advertised for global cache index
        col2 = Collector()
        run_requests(engine, [EngineRequest(
            "b", token_ids=prompt,
            sampling=SamplingParams(max_tokens=5, temperature=0.0,
                                    ignore_eos=True), on_output=col2)])
        assert col2.tokens == col1.tokens

    def test_seeded_sampling_deterministic(self):
        engine = make_engine()
        prompt = list(range(50, 80))
        sp = SamplingParams(max_tokens=6, temperature=0.8, top_k=20,
                            seed=42, ignore_eos=True)
        cols = [Collector(), Collector()]
        for c in cols:
            run_requests(engine, [EngineRequest(
                f"s-{id(c)}", token_ids=prompt, sampling=sp, on_output=c)])
        assert cols[0].tokens == cols[1].tokens

    def test_logprobs_emitted(self):
        engine = make_engine()
        col = Collector()
        run_requests(engine, [EngineRequest(
            "lp", token_ids=list(range(12)),
            sampling=SamplingParams(max_tokens=3, temperature=0.0,
                                    logprobs=True, top_logprobs=3,
                                    ignore_eos=True),
            on_output=col)])
        lps = [lp for o in col.outputs for s in o.outputs for lp in s.logprobs]
        assert len(lps) == 3
        assert all(len(lp.top_logprobs) == 3 for lp in lps)
        assert all(lp.logprob <= 0 for lp in lps)
        # Greedy chosen token must be the argmax == first top logprob.
        assert lps[0].token_id == lps[0].top_logprobs[0].token_id

    def test_cancellation(self):
        engine = make_engine()
        col = Collector()
        engine.submit(EngineRequest(
            "c1", token_ids=list(range(20)),
            sampling=SamplingParams(max_tokens=200, temperature=0.0,
                                    ignore_eos=True),
            on_output=col))
        for _ in range(3):
            engine.step()
        engine.cancel("c1")
        for _ in range(5):
            engine.step()
        assert col.done.is_set()
        assert len(engine._running) == 0

    def test_stop_token_ids(self):
        engine = make_engine()
        prompt = list(range(10, 26))
        first = naive_greedy(engine, prompt, 1)[0]
        col = Collector()
        run_requests(engine, [EngineRequest(
            "st", token_ids=prompt,
            sampling=SamplingParams(max_tokens=10, temperature=0.0,
                                    stop_token_ids=[first], ignore_eos=True),
            on_output=col)])
        assert col.finish_reason == "stop"
        assert len(col.tokens) == 1
        # OpenAI/vLLM semantics: the matched stop token's text must not
        # leak into visible content. (The sampled token may fall in the
        # SimpleTokenizer's silent special range and decode to "" — the
        # leak check is only meaningful when it has text at all.)
        stop_text = engine.tokenizer.decode([first])
        assert not stop_text or stop_text not in col.text

    def test_horizon_bounded_by_remaining_budget(self):
        """The decode horizon is bounded by the LONGEST remaining token
        budget across the batch (pow2 ceiling): when every running
        sequence is nearly done, whole-batch dead steps are avoided —
        while per-sequence budgets are enforced on device (see
        TestDeviceBudgetFreeze), so one short sequence alone never
        shrinks the horizon."""
        engine = make_engine(decode_horizon=8)
        horizons = []
        real = engine._decode_multi

        def spy(params, d, horizon):
            horizons.append(horizon)
            return real(params, d, horizon)

        engine._decode_multi = spy
        prompt = list(range(10, 30))
        want = naive_greedy(engine, prompt, 5)
        col = Collector()
        run_requests(engine, [EngineRequest(
            "hb", token_ids=prompt,
            sampling=SamplingParams(max_tokens=5, temperature=0.0,
                                    ignore_eos=True),
            on_output=col)])
        # 1 token from prefill + 4 remaining: max-remaining = 4 -> the
        # first decode call shrinks to horizon 4 (pow2 ceil), not 8.
        assert col.tokens == want
        assert col.finish_reason == "length"
        assert horizons and all(h <= 4 for h in horizons)

    def test_horizon_follows_longest_budget_in_mixed_batch(self):
        """A 2-token request next to a 20-token request must NOT clamp
        the batch horizon: with max-remaining bounding, calls stay at the
        long sequence's (pow2-ceiled) remaining, and the short sequence
        is frozen on device at its own budget."""
        engine = make_engine(decode_horizon=8)
        horizons = []
        real = engine._decode_multi

        def spy(params, d, horizon):
            horizons.append(horizon)
            return real(params, d, horizon)

        engine._decode_multi = spy
        cols = [Collector(), Collector()]
        reqs = [EngineRequest(
            f"m{i}", token_ids=list(range(10 + 40 * i, 30 + 40 * i)),
            sampling=SamplingParams(max_tokens=n, temperature=0.0,
                                    ignore_eos=True), on_output=c)
            for i, (n, c) in enumerate(zip((2, 20), cols))]
        run_requests(engine, reqs)
        assert len(cols[0].tokens) == 2 and len(cols[1].tokens) == 20
        # The old min-remaining rule would have clamped the first call to
        # horizon 1 (short request has 1 remaining after prefill).
        assert horizons[0] == 8, horizons

    def test_device_stop_freezes_slot_mid_horizon(self):
        """A stop-token hit mid-horizon deactivates the slot on device; the
        other sequence in the batch must be unaffected and the stopped one
        must emit exactly one token."""
        engine = make_engine(decode_horizon=8)
        p1, p2 = list(range(10, 26)), list(range(40, 60))
        stop_tok = naive_greedy(engine, p1, 2)[1]   # second greedy token
        want2 = naive_greedy(engine, p2, 8)
        c1, c2 = Collector(), Collector()
        run_requests(engine, [
            EngineRequest("a", token_ids=p1,
                          sampling=SamplingParams(max_tokens=8,
                                                  temperature=0.0,
                                                  stop_token_ids=[stop_tok],
                                                  ignore_eos=True),
                          on_output=c1),
            EngineRequest("b", token_ids=p2,
                          sampling=SamplingParams(max_tokens=8,
                                                  temperature=0.0,
                                                  ignore_eos=True),
                          on_output=c2),
        ])
        assert c1.finish_reason == "stop"
        assert len(c1.tokens) == 2 and c1.tokens[1] == stop_tok
        assert c2.tokens == want2

    def test_incremental_detokenization_multibyte(self):
        """The per-token decode is incremental (no O(n^2) full re-decode);
        a UTF-8 char split across byte-level tokens must be held back
        until complete and then emitted exactly once."""
        import base64

        from xllm_service_tpu.tokenizer.tiktoken import TiktokenTokenizer

        # Byte-level vocab: "é" = 0xC3 0xA9 split across two tokens.
        vocab = {b"a": 0, b"\xc3": 1, b"\xa9": 2, b"b": 3}
        lines = "\n".join(f"{base64.b64encode(k).decode()} {v}"
                          for k, v in vocab.items())
        import tempfile
        with tempfile.NamedTemporaryFile("w", suffix=".tiktoken",
                                         delete=False) as f:
            f.write(lines)
            path = f.name
        tok = TiktokenTokenizer(path)

        engine = make_engine()
        engine.tokenizer = tok
        from xllm_service_tpu.engine.engine import _Sequence
        from xllm_service_tpu.engine.kv_cache import SequencePages

        seq = _Sequence(req=EngineRequest("x", token_ids=[0]),
                        pages=SequencePages(), prompt_len=1,
                        max_total_len=32)
        calls = {"n": 0}
        real = tok.decode

        def spy(ids, **kw):
            calls["n"] += 1
            calls["last"] = list(ids)
            return real(ids, **kw)

        tok.decode = spy
        seq.output_ids = [0]
        assert engine._incremental_text(seq) == "a"
        seq.output_ids = [0, 1]           # partial UTF-8: held back
        assert engine._incremental_text(seq) == "a�"
        assert seq.decoded_ok == 1        # partial byte NOT finalized
        seq.output_ids = [0, 1, 2]        # completes "é"
        assert engine._incremental_text(seq) == "aé"
        seq.output_ids = [0, 1, 2, 3]
        assert engine._incremental_text(seq) == "aéb"
        # Incremental: per-token decode calls see a BOUNDED window
        # (context + tail), never the whole history.
        for _ in range(30):
            seq.output_ids.append(0)
            engine._incremental_text(seq)
            assert len(calls["last"]) <= 2 * engine.DETOK_WINDOW + 1
        assert engine._incremental_text(seq).endswith("a" * 30)

    def test_incremental_detok_preserves_word_boundaries(self):
        """decode(A)+decode(B) != decode(A+B) for SentencePiece-style
        tokenizers (the run's leading word marker is stripped) — the
        incremental path must diff WITH context so streamed text keeps its
        inter-word spaces."""

        class SpLikeTokenizer:
            """Minimal SentencePiece-decode semantics: pieces carry a
            leading ▁ word marker; decode joins pieces, ▁ -> space, and
            strips the overall leading space."""

            PIECES = {0: "▁Hello", 1: "▁world", 2: "▁again", 3: "!"}

            def decode(self, ids, skip_special_tokens=True):
                s = "".join(self.PIECES[int(i)] for i in ids)
                return s.replace("▁", " ").lstrip(" ")

        engine = make_engine()
        engine.tokenizer = SpLikeTokenizer()
        from xllm_service_tpu.engine.engine import _Sequence
        from xllm_service_tpu.engine.kv_cache import SequencePages

        seq = _Sequence(req=EngineRequest("x", token_ids=[0]),
                        pages=SequencePages(), prompt_len=1,
                        max_total_len=32)
        for i, want in [(0, "Hello"), (1, "Hello world"),
                        (3, "Hello world!"), (2, "Hello world! again")]:
            seq.output_ids.append(i)
            assert engine._incremental_text(seq) == want

    def test_prompt_too_long_rejected(self):
        engine = make_engine()
        col = Collector()
        engine.submit(EngineRequest(
            "big", token_ids=list(range(300)),
            sampling=SamplingParams(max_tokens=5), on_output=col))
        assert col.done.is_set()
        assert not col.outputs[0].status.ok()


class TestKVPageManager:
    def test_alloc_free(self):
        mgr = KVPageManager(num_pages=9, page_size=16, hash_block_size=32)
        a = mgr.allocate(4)
        assert len(a) == 4 and 0 not in a   # garbage page never allocated
        assert mgr.allocate(5) is None      # only 4 left
        b = mgr.allocate(4)
        assert len(b) == 4 and not (set(a) & set(b))
        mgr.free(a)
        assert mgr.num_free == 4

    def test_prefix_cache_lifecycle(self):
        mgr = KVPageManager(num_pages=17, page_size=16, hash_block_size=32)
        toks = list(range(64))          # 2 blocks
        pages = mgr.allocate(4)
        stored, donated = mgr.store_prefix(toks, pages)
        assert len(stored) == 2 and donated == set(pages)
        ev = mgr.drain_events()
        assert len(ev.stored) == 2
        # Match takes references.
        n, mpages, hashes = mgr.match_prefix(toks + [999])
        assert n == 64 and mpages == pages
        # Referenced blocks cannot be evicted.
        assert mgr.allocate(14) is None
        mgr.release_prefix(hashes)
        mgr.release_prefix(stored)
        # Now eviction can reclaim cached pages — lazily, oldest first:
        # 12 free + one evicted block (2 pages) covers the request.
        assert mgr.allocate(14) is not None
        ev = mgr.drain_events()
        assert len(ev.removed) == 1
        assert mgr.cached_block_count() == 1

    def test_tail_page_never_donated(self):
        """The fused decode kernel's whole-page RMW append is safe only
        because a partially-filled tail page stays PRIVATE to its
        sequence (ops/pallas_fused_decode_attention.py). Donation must
        stay full-hash-block granular: a prompt whose tail doesn't fill a
        block leaves the tail page out of the donated set, and
        page-misaligned block sizes are rejected at construction."""
        mgr = KVPageManager(num_pages=17, page_size=16, hash_block_size=32)
        toks = list(range(72))          # 2 full blocks + 8-token tail
        pages = mgr.allocate(5)         # 4 full pages + 1 tail page
        stored, donated = mgr.store_prefix(toks, pages)
        assert len(stored) == 2
        assert pages[4] not in donated          # the tail page is private
        assert donated == set(pages[:4])
        with pytest.raises(ValueError, match="whole number of pages"):
            KVPageManager(num_pages=17, page_size=16, hash_block_size=40)

    def test_partial_match_after_divergence(self):
        mgr = KVPageManager(num_pages=17, page_size=16, hash_block_size=32)
        toks = list(range(64))
        pages = mgr.allocate(4)
        stored, _ = mgr.store_prefix(toks, pages)
        other = toks[:32] + [7777] * 32
        n, mpages, hashes = mgr.match_prefix(other)
        assert n == 32 and mpages == pages[:2]
        mgr.release_prefix(hashes)
        mgr.release_prefix(stored)


class TestPenalties:
    def test_strong_frequency_penalty_never_repeats(self):
        """With a huge frequency penalty every emitted (and prompt) token
        gets a massive logit cut, so greedy decode must never repeat a
        token — exercises the with-counts install variant + the device
        count updates end-to-end."""
        engine = make_engine()
        prompt = [7, 8, 9, 7, 8, 9, 7, 8, 9]
        col = Collector()
        run_requests(engine, [EngineRequest(
            "fp", token_ids=list(prompt),
            sampling=SamplingParams(max_tokens=12, temperature=0.0,
                                    frequency_penalty=100.0,
                                    ignore_eos=True),
            on_output=col)])
        assert len(col.tokens) == 12
        assert len(set(col.tokens)) == 12, col.tokens       # no repeats
        assert not (set(col.tokens) & set(prompt))          # no prompt toks

    def test_counts_variant_routing(self):
        """Penalty-free requests use the no-counts install program (no
        dense [V] histogram upload); penalty requests use the with-counts
        one."""
        engine = make_engine()
        used = {"counts": 0, "nc": 0}
        real_c, real_nc = engine._prefill_install, engine._prefill_install_nc

        def spy_c(*a, **k):
            used["counts"] += 1
            return real_c(*a, **k)

        def spy_nc(*a, **k):
            used["nc"] += 1
            return real_nc(*a, **k)

        engine._prefill_install = spy_c
        engine._prefill_install_nc = spy_nc
        cols = [Collector(), Collector()]
        run_requests(engine, [
            EngineRequest("plain", token_ids=list(range(10, 20)),
                          sampling=SamplingParams(max_tokens=2,
                                                  temperature=0.0,
                                                  ignore_eos=True),
                          on_output=cols[0]),
            EngineRequest("pen", token_ids=list(range(30, 40)),
                          sampling=SamplingParams(max_tokens=2,
                                                  temperature=0.0,
                                                  presence_penalty=0.5,
                                                  ignore_eos=True),
                          on_output=cols[1]),
        ])
        assert used == {"counts": 1, "nc": 1}
        assert all(len(c.tokens) == 2 for c in cols)


class TestAdaptiveHorizon:
    def test_short_calls_while_waiting_full_when_idle(self):
        """With admission_horizon set, decode calls shrink while requests
        queue (so admission isn't blocked behind a long lax.scan) and
        recover to the full horizon once the queue drains."""
        engine = make_engine(decode_horizon=8, admission_horizon=2,
                             max_batch_size=1)   # one slot: forces a queue
        horizons = []
        real = engine._decode_multi

        def spy(params, d, horizon):
            horizons.append((horizon, len(engine._waiting)))
            return real(params, d, horizon)

        engine._decode_multi = spy
        cols = [Collector(), Collector()]
        reqs = [EngineRequest(
            f"ah{i}", token_ids=list(range(10 + 30 * i, 26 + 30 * i)),
            sampling=SamplingParams(max_tokens=24, temperature=0.0,
                                    ignore_eos=True), on_output=c)
            for i, c in enumerate(cols)]
        run_requests(engine, reqs)
        assert all(len(c.tokens) == 24 for c in cols)
        # Calls made while the second request queued must be short; calls
        # with an empty queue run the full horizon.
        waiting_calls = [h for h, w in horizons if w > 0]
        idle_calls = [h for h, w in horizons if w == 0]
        assert waiting_calls and all(h <= 2 for h in waiting_calls)
        assert any(h == 8 for h in idle_calls)


class TestDeviceBudgetFreeze:
    def test_mixed_budgets_exact_outputs(self):
        """Per-slot budgets are enforced ON DEVICE (slot freezes at
        max_total_len like a stop hit) so a nearly-done sequence no
        longer clamps the batch horizon. Both streams must be exact: the
        short one stops at its budget, the long one is unperturbed by
        decoding alongside a frozen slot."""
        engine = make_engine(decode_horizon=8)
        prompts = [list(range(5, 25)), list(range(50, 80))]
        budgets = [2, 24]
        want = [naive_greedy(engine, p, n)
                for p, n in zip(prompts, budgets)]
        cols = [Collector() for _ in prompts]
        reqs = [EngineRequest(f"bud{i}", token_ids=p,
                              sampling=SamplingParams(max_tokens=n,
                                                      temperature=0.0,
                                                      ignore_eos=True),
                              on_output=c)
                for i, (p, n, c) in enumerate(zip(prompts, budgets, cols))]
        run_requests(engine, reqs)
        for c, w, n in zip(cols, want, budgets):
            assert len(c.tokens) == n
            assert c.tokens == w
            assert c.finish_reason == "length"


class TestBurstAdmission:
    def test_same_burst_identical_prompts_share_prefix_cache(self):
        """Admission dispatches a burst of installs before completing any
        (async pipeline) — but two identical prompts in ONE burst must
        still dedupe through the prefix cache (the n>1 choice fan-out
        relies on it), which requires completing the first before
        matching the second."""
        engine = make_engine()
        prompt = list(range(10, 10 + 64))      # 2 hash blocks of 32
        cols = [Collector(), Collector()]
        for i, col in enumerate(cols):
            engine.submit(EngineRequest(
                f"burst-{i}", token_ids=list(prompt),
                sampling=SamplingParams(max_tokens=4, temperature=0.0,
                                        ignore_eos=True), on_output=col))
        free_before = engine.page_mgr.num_free
        engine.start()                          # both pop in one admit pass
        for col in cols:
            assert col.done.wait(30)
        engine.stop()
        # Same greedy continuation for both.
        assert cols[0].tokens == cols[1].tokens
        # The second sequence matched the first's donated prompt blocks:
        # together they consumed fewer pages than two unshared prefills
        # (prompt is 4 pages; +1 page of decode growth each).
        used = free_before - engine.page_mgr.num_free
        assert used <= 4 + 2 * 1 + 1, used


class TestEngineResilience:
    def test_step_failure_fails_inflight_requests(self):
        """A step-level failure (e.g. kernel compile error on real hardware)
        must surface to clients instead of hanging them (found in live
        verification: the loop thread died and requests hung)."""
        engine = make_engine()
        col = Collector()
        engine.submit(EngineRequest(
            "boom", token_ids=list(range(16)),
            sampling=SamplingParams(max_tokens=50, temperature=0.0,
                                    ignore_eos=True), on_output=col))
        engine.step()          # admit + first token

        def explode(*a, **k):
            raise RuntimeError("Mosaic failed to compile TPU kernel")

        engine._decode_multi = explode
        engine.start()         # loop thread hits the failure
        assert col.done.is_set() or col.done.wait(10)
        engine.stop()
        final = col.outputs[-1]
        assert not final.status.ok()
        assert "engine failure" in final.status.message
        assert engine.stats()["running"] == 0
        # The engine still accepts new work afterwards (fresh program path).
        engine2 = make_engine()
        col2 = Collector()
        run_requests(engine2, [EngineRequest(
            "ok", token_ids=list(range(16)),
            sampling=SamplingParams(max_tokens=2, temperature=0.0,
                                    ignore_eos=True), on_output=col2)])
        assert col2.finish_reason == "length"

    def test_prefill_failure_fails_that_request(self):
        """Prefill-program failure mid-admission must error the triggering
        request (it is in neither _waiting nor _running at that point) and
        leak no slot/pages (code-review finding)."""
        engine = make_engine()

        def explode(*a, **k):
            raise RuntimeError("prefill compile failure")

        engine._dispatch_prefill_install = explode
        col = Collector()
        engine.submit(EngineRequest(
            "pboom", token_ids=list(range(16)),
            sampling=SamplingParams(max_tokens=4, temperature=0.0,
                                    ignore_eos=True), on_output=col))
        engine.start()
        assert col.done.is_set() or col.done.wait(10)
        engine.stop()
        assert not col.outputs[-1].status.ok()
        assert "prefill failure" in col.outputs[-1].status.message
        assert len(col.outputs) == 1            # exactly one error callback
        assert len(engine._free_slots) == engine.cfg.max_batch_size
        assert engine.page_mgr.num_free == engine.cfg.num_pages - 1


class TestChunkedPrefill:
    def _engine(self, chunk):
        cfg = EngineConfig(
            model=tiny_config(dtype=jnp.float32, max_context_len=256),
            num_pages=64, page_size=16, hash_block_size=32,
            max_batch_size=4, max_seq_len=256, prefill_buckets=(32, 64, 256),
            prefill_chunk_tokens=chunk)
        return InferenceEngine(cfg)

    def test_chunked_matches_unchunked(self):
        chunked = self._engine(32)
        plain = self._engine(0)
        prompt = list(range(3, 120))    # 117 tokens -> 3 chunks + final
        want = naive_greedy(plain, prompt, 5)
        col = Collector()
        run_requests(chunked, [EngineRequest(
            "c", token_ids=prompt,
            sampling=SamplingParams(max_tokens=5, temperature=0.0,
                                    ignore_eos=True), on_output=col)])
        assert col.tokens == want

    def test_decode_interleaves_with_chunked_prefill(self):
        engine = self._engine(32)
        short_col = Collector()
        engine.submit(EngineRequest(
            "short", token_ids=list(range(10)),
            sampling=SamplingParams(max_tokens=30, temperature=0.0,
                                    ignore_eos=True), on_output=short_col))
        engine.step()           # short admitted + first token
        tokens_before = len(short_col.tokens)
        long_col = Collector()
        engine.submit(EngineRequest(
            "long", token_ids=list(range(5, 200)),   # 195 tokens, 6 chunks
            sampling=SamplingParams(max_tokens=3, temperature=0.0,
                                    ignore_eos=True), on_output=long_col))
        # During the chunked admission of 'long', 'short' keeps decoding.
        interleaved = 0
        while engine._prefillings or not long_col.done.is_set():
            before = len(short_col.tokens)
            engine.step()
            if engine._prefillings and len(short_col.tokens) > before:
                interleaved += 1
            if short_col.done.is_set() and long_col.done.is_set():
                break
        assert interleaved >= 2   # decode progressed during prefill chunks
        while not (short_col.done.is_set() and long_col.done.is_set()):
            engine.step()
        assert len(long_col.tokens) == 3
        assert len(short_col.tokens) == 30

    def test_chunked_prefill_cancellation(self):
        engine = self._engine(32)
        col = Collector()
        engine.submit(EngineRequest(
            "cx", token_ids=list(range(200)),
            sampling=SamplingParams(max_tokens=5, temperature=0.0,
                                    ignore_eos=True), on_output=col))
        engine.step()            # starts chunked admission
        assert engine._prefillings
        engine.cancel("cx")
        engine.step()
        assert not engine._prefillings
        assert col.done.is_set()
        assert not col.outputs[-1].status.ok()
        assert len(engine._free_slots) == engine.cfg.max_batch_size
        assert engine.page_mgr.num_free == engine.cfg.num_pages - 1


class TestConcurrentChunkedPrefills:
    def _engine(self, chunk):
        return make_engine(prefill_chunk_tokens=chunk)

    def test_two_long_prompts_progress_together(self):
        """Both long prompts are in flight at once (round-robin chunks) and
        a short prompt admits past them instead of queuing behind."""
        engine = self._engine(32)
        plain = self._engine(0)
        p1 = list(range(3, 150))
        p2 = list(range(7, 160))
        want1 = naive_greedy(plain, p1, 3)
        want2 = naive_greedy(plain, p2, 3)
        c1, c2, c3 = Collector(), Collector(), Collector()
        engine.submit(EngineRequest(
            "l1", token_ids=p1,
            sampling=SamplingParams(max_tokens=3, temperature=0.0,
                                    ignore_eos=True), on_output=c1))
        engine.submit(EngineRequest(
            "l2", token_ids=p2,
            sampling=SamplingParams(max_tokens=3, temperature=0.0,
                                    ignore_eos=True), on_output=c2))
        engine.step()
        engine.step()
        assert len(engine._prefillings) == 2   # both in flight together
        # A short prompt admits immediately despite two chunked prefills.
        engine.submit(EngineRequest(
            "short", token_ids=list(range(8)),
            sampling=SamplingParams(max_tokens=2, temperature=0.0,
                                    ignore_eos=True), on_output=c3))
        engine.step()
        assert c3.tokens, "short prompt stalled behind chunked prefills"
        for _ in range(200):
            if c1.done.is_set() and c2.done.is_set() and c3.done.is_set():
                break
            engine.step()
        assert c1.tokens == want1
        assert c2.tokens == want2
        assert len(c3.tokens) == 2

    def test_third_long_prompt_waits_for_capacity(self):
        engine = self._engine(32)   # max_concurrent_prefills = 2
        cols = [Collector() for _ in range(3)]
        for i, c in enumerate(cols):
            engine.submit(EngineRequest(
                f"l{i}", token_ids=list(range(5 + i, 150 + i)),
                sampling=SamplingParams(max_tokens=2, temperature=0.0,
                                        ignore_eos=True), on_output=c))
        engine.step()
        assert len(engine._prefillings) == 2
        assert len(engine._waiting) == 1       # third deferred
        for _ in range(300):
            if all(c.done.is_set() for c in cols):
                break
            engine.step()
        assert all(len(c.tokens) == 2 for c in cols)


class TestLogitBias:
    def test_bias_forces_token(self):
        """A +100 bias on a chosen token makes greedy pick it every step;
        an unbiased request is unaffected."""
        engine = make_engine()
        prompt = list(range(10, 30))
        forced = 123
        biased, plain = Collector(), Collector()
        run_requests(engine, [
            EngineRequest("b", token_ids=prompt,
                          sampling=SamplingParams(
                              max_tokens=4, temperature=0.0,
                              ignore_eos=True,
                              logit_bias={forced: 100.0}),
                          on_output=biased),
            EngineRequest("p", token_ids=prompt,
                          sampling=SamplingParams(max_tokens=4,
                                                  temperature=0.0,
                                                  ignore_eos=True),
                          on_output=plain),
        ])
        assert biased.tokens == [forced] * 4
        assert plain.tokens == naive_greedy(engine, prompt, 4)

    def test_negative_bias_suppresses_token(self):
        engine = make_engine()
        prompt = list(range(40, 60))
        first = naive_greedy(engine, prompt, 1)[0]
        col = Collector()
        run_requests(engine, [EngineRequest(
            "nb", token_ids=prompt,
            sampling=SamplingParams(max_tokens=3, temperature=0.0,
                                    ignore_eos=True,
                                    logit_bias={first: -100.0}),
            on_output=col)])
        assert first not in col.tokens
