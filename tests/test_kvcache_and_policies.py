"""GlobalKVCacheMgr + LB policy tests."""

import pytest

from xllm_service_tpu.common.config import ServiceOptions
from xllm_service_tpu.common.hashing import prefix_block_hash_hexes
from xllm_service_tpu.common.request import Request
from xllm_service_tpu.common.types import InstanceType, KvCacheEvent, LoadMetrics
from xllm_service_tpu.coordination.memory import InMemoryCoordination
from xllm_service_tpu.scheduler.global_kvcache_mgr import GlobalKVCacheMgr
from xllm_service_tpu.scheduler.instance_mgr import InstanceMgr
from xllm_service_tpu.scheduler.policies import create_policy

from fakes import FakeChannel, make_meta, wait_until

BLOCK = 16  # small block size for tests


@pytest.fixture()
def coord(store):
    c = InMemoryCoordination(store)
    yield c
    c.close()


@pytest.fixture(autouse=True)
def _reset_channels():
    FakeChannel.reset()
    yield
    FakeChannel.reset()


def _opts(**kw):
    return ServiceOptions(block_size=BLOCK, reconcile_interval_s=0.05, **kw)


class TestGlobalKVCache:
    def test_match_walks_until_first_miss(self, coord):
        mgr = GlobalKVCacheMgr(coord, block_size=BLOCK)
        toks = list(range(BLOCK * 4))
        hashes = prefix_block_hash_hexes(toks, BLOCK)
        # i1 holds blocks 0,1; i2 holds block 0 only. Block 2 missing.
        mgr.record_updated_kvcaches("i1", KvCacheEvent(stored=hashes[:2]))
        mgr.record_updated_kvcaches("i2", KvCacheEvent(stored=hashes[:1]))
        ov = mgr.match(toks)
        assert ov.max_block_num == 4
        assert ov.scores["i1"] == pytest.approx(2.0)
        assert ov.scores["i2"] == pytest.approx(1.0)
        # Block 3 stored but 2 missing: walk stops at 2, so 3 never counts.
        mgr.record_updated_kvcaches("i1", KvCacheEvent(stored=[hashes[3]]))
        assert mgr.match(toks).scores["i1"] == pytest.approx(2.0)

    def test_offload_demotion_chain(self, coord):
        mgr = GlobalKVCacheMgr(coord, block_size=BLOCK)
        h = prefix_block_hash_hexes(list(range(BLOCK)), BLOCK)
        mgr.record_updated_kvcaches("i1", KvCacheEvent(stored=h))
        mgr.record_updated_kvcaches("i1", KvCacheEvent(offloaded=h))  # HBM->DRAM
        ov = mgr.match(list(range(BLOCK)))
        assert ov.scores["i1"] == pytest.approx(0.6)   # DRAM weight
        mgr.record_updated_kvcaches("i1", KvCacheEvent(offloaded=h))  # DRAM->SSD
        assert mgr.match(list(range(BLOCK))).scores["i1"] == pytest.approx(0.3)
        mgr.record_updated_kvcaches("i1", KvCacheEvent(removed=h))
        assert mgr.match(list(range(BLOCK))).scores == {}

    def test_master_upload_replica_mirror(self, coord, store):
        master = GlobalKVCacheMgr(coord, block_size=BLOCK, is_master=True)
        toks = list(range(BLOCK * 2))
        hashes = prefix_block_hash_hexes(toks, BLOCK)
        master.record_updated_kvcaches("i1", KvCacheEvent(stored=hashes))
        master.upload_kvcache()
        rc = InMemoryCoordination(store)
        replica = GlobalKVCacheMgr(rc, block_size=BLOCK, is_master=False)
        assert replica.match(toks).scores.get("i1") == pytest.approx(2.0)
        # Delta replication: removal propagates.
        master.record_updated_kvcaches("i1", KvCacheEvent(removed=hashes))
        master.upload_kvcache()
        assert wait_until(lambda: replica.match(toks).scores == {})
        master.stop(); replica.stop(); rc.close()

    def test_remove_instance(self, coord):
        mgr = GlobalKVCacheMgr(coord, block_size=BLOCK)
        h = prefix_block_hash_hexes(list(range(BLOCK)), BLOCK)
        mgr.record_updated_kvcaches("i1", KvCacheEvent(stored=h))
        mgr.record_updated_kvcaches("i2", KvCacheEvent(stored=h))
        mgr.remove_instance("i1")
        assert set(mgr.match(list(range(BLOCK))).scores) == {"i2"}


class TestPolicies:
    def _fleet(self, coord):
        mgr = InstanceMgr(coord, _opts(), channel_factory=FakeChannel.factory,
                          start_threads=False)
        for n in ("p1", "p2"):
            mgr.register_instance(make_meta(n, InstanceType.PREFILL),
                                  link_peers=False)
        for n in ("d1", "d2"):
            mgr.register_instance(make_meta(n, InstanceType.DECODE),
                                  link_peers=False)
        return mgr

    def test_rr_policy(self, coord):
        mgr = self._fleet(coord)
        policy = create_policy("RR", mgr, None, _opts())
        seen = {policy.select_instances_pair(Request()).prefill_name
                for _ in range(4)}
        assert seen == {"p1", "p2"}
        mgr.stop()

    def test_car_prefers_cache_hits(self, coord):
        mgr = self._fleet(coord)
        kv = GlobalKVCacheMgr(coord, block_size=BLOCK)
        opts = _opts()
        policy = create_policy("CAR", mgr, kv, opts)
        toks = list(range(BLOCK * 3))
        hashes = prefix_block_hash_hexes(toks, BLOCK)
        kv.record_updated_kvcaches("p2", KvCacheEvent(stored=hashes))
        kv.record_updated_kvcaches("d1", KvCacheEvent(stored=hashes[:1]))
        r = policy.select_instances_pair(Request(token_ids=toks))
        assert r.prefill_name == "p2"
        assert r.decode_name == "d1"
        mgr.stop()

    def test_car_penalizes_load(self, coord):
        mgr = self._fleet(coord)
        kv = GlobalKVCacheMgr(coord, block_size=BLOCK)
        opts = _opts(max_waiting_requests=10)
        policy = create_policy("CAR", mgr, kv, opts)
        toks = list(range(BLOCK * 2))
        hashes = prefix_block_hash_hexes(toks, BLOCK)
        # p1 has all blocks cached but is heavily loaded.
        kv.record_updated_kvcaches("p1", KvCacheEvent(stored=hashes))
        mgr.record_instance_heartbeat("p1", "", LoadMetrics(
            waiting_requests_num=10, hbm_cache_usage_perc=0.99))
        r = policy.select_instances_pair(Request(token_ids=toks))
        assert r.prefill_name == "p2"   # cache hit outweighed by load
        mgr.stop()

    def test_car_untokenized_falls_back_rr(self, coord):
        mgr = self._fleet(coord)
        kv = GlobalKVCacheMgr(coord, block_size=BLOCK)
        policy = create_policy("CAR", mgr, kv, _opts())
        r = policy.select_instances_pair(Request())
        assert r.prefill_name in ("p1", "p2")
        mgr.stop()

    def test_slo_policy_untokenized_falls_back(self, coord):
        mgr = self._fleet(coord)
        policy = create_policy("SLO_AWARE", mgr, None, _opts())
        assert policy.select_instances_pair(Request()).prefill_name in ("p1", "p2")
        mgr.stop()

    def test_unknown_policy_raises(self, coord):
        with pytest.raises(ValueError):
            create_policy("NOPE", None, None, _opts())
